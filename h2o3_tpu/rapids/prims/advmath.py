"""Rapids advanced math prims (18) + misc repeaters/time-series.

Reference: ``water/rapids/ast/prims/advmath/`` — Correlation Distance Hist
Impute KFold Kurtosis Mode ModuloKFold Qtile Runif Skewness
SpearmanCorrelation StratifiedKFold StratifiedSplit Table TfIdf Unique
Variance; plus ``repeaters/`` (RepLen Seq SeqLen), ``timeseries/``
(DiffLag1 Isax), ``misc/`` (Ls Comma SetProperty).
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.frame.frame import Column, ColType, Frame, NA_CAT
from h2o3_tpu.models.framework import fold_assignment
from h2o3_tpu.rapids.prims import prim
from h2o3_tpu.rapids.prims.util import numeric_data
from h2o3_tpu.rapids.runtime import RapidsError, Val


def _matrix(fr: Frame) -> np.ndarray:
    return np.stack([numeric_data(c) for c in fr.columns], axis=1)


@prim("cor")
def cor(env, args):
    """(cor frx fry use method) — Pearson correlation matrix (AstCorrelation);
    use: everything | complete.obs | all.obs."""
    fx, fy = args[0].as_frame(), args[1].as_frame()
    use = args[2].as_str() if len(args) > 2 else "everything"
    x, y = _matrix(fx), _matrix(fy)
    if use == "complete.obs":
        ok = ~(np.isnan(x).any(axis=1) | np.isnan(y).any(axis=1))
        x, y = x[ok], y[ok]
    elif use == "all.obs" and (np.isnan(x).any() or np.isnan(y).any()):
        raise RapidsError("cor: missing observations with use=all.obs")
    xm = x - x.mean(axis=0)
    ym = y - y.mean(axis=0)
    cov = xm.T @ ym / (len(x) - 1)
    sx = x.std(axis=0, ddof=1)
    sy = y.std(axis=0, ddof=1)
    out = cov / np.outer(sx, sy)
    if out.size == 1:
        return Val.num(float(out[0, 0]))
    return Val.frame(
        Frame([Column(c.name, out[:, j], ColType.NUM) for j, c in enumerate(fy.columns)])
    )


@prim("spearman")
def spearman(env, args):
    """(spearman fr colx coly) — Spearman rank correlation."""
    fr = args[0].as_frame()
    def _c(v):
        return fr.names.index(v.as_str()) if v.is_str() else int(v.as_num())
    x = numeric_data(fr.col(_c(args[1])))
    y = numeric_data(fr.col(_c(args[2])))
    ok = ~(np.isnan(x) | np.isnan(y))
    from scipy import stats

    rho = stats.spearmanr(x[ok], y[ok]).statistic
    return Val.num(float(rho))


@prim("var")
def var(env, args):
    """(var frx fry use symmetric) — covariance matrix (AstVariance)."""
    fx = args[0].as_frame()
    fy = args[1].as_frame() if len(args) > 1 and args[1].is_frame() else fx
    use = args[2].as_str() if len(args) > 2 else "everything"
    x, y = _matrix(fx), _matrix(fy)
    if use == "complete.obs":
        ok = ~(np.isnan(x).any(axis=1) | np.isnan(y).any(axis=1))
        x, y = x[ok], y[ok]
    xm = x - x.mean(axis=0)
    ym = y - y.mean(axis=0)
    cov = xm.T @ ym / (len(x) - 1)
    if cov.size == 1:
        return Val.num(float(cov[0, 0]))
    return Val.frame(
        Frame([Column(c.name, cov[:, j], ColType.NUM) for j, c in enumerate(fy.columns)])
    )


def _moment_stat(env, args, fn):
    fr = args[0].as_frame()
    na_rm = bool(args[1].as_num()) if len(args) > 1 else False
    vals = []
    for c in fr.columns:
        d = numeric_data(c)
        if na_rm:
            d = d[~np.isnan(d)]
        vals.append(fn(d))
    return Val.num(vals[0]) if len(vals) == 1 else Val.nums(vals)


@prim("skewness")
def skewness(env, args):
    """Sample skewness g1 (AstSkewness)."""
    return _moment_stat(
        env, args, lambda d: float(np.mean((d - d.mean()) ** 3) / d.std(ddof=0) ** 3) if len(d) else float("nan")
    )


@prim("kurtosis")
def kurtosis(env, args):
    """Sample kurtosis (not excess) (AstKurtosis)."""
    return _moment_stat(
        env, args, lambda d: float(np.mean((d - d.mean()) ** 4) / d.std(ddof=0) ** 4) if len(d) else float("nan")
    )


@prim("mode")
def mode(env, args):
    fr = args[0].as_frame()
    c = fr.col(0)
    if c.type is ColType.CAT:
        counts = np.bincount(c.data[c.data >= 0], minlength=len(c.domain))
        return Val.num(float(np.argmax(counts)))
    d = numeric_data(c)
    v, n = np.unique(d[~np.isnan(d)], return_counts=True)
    return Val.num(float(v[np.argmax(n)]) if len(v) else float("nan"))


@prim("hist")
def hist(env, args):
    """(hist fr breaks) — histogram frame [breaks counts mids density]
    (AstHist; breaks: count, 'sturges', 'rice', 'sqrt', 'doane', 'fd', 'scott'
    or an explicit break list)."""
    fr = args[0].as_frame()
    c = fr.col(0)
    d = numeric_data(c)
    d = d[~np.isnan(d)]
    spec = args[1] if len(args) > 1 else Val.str_("sturges")
    n = len(d)
    if spec.kind == Val.NUMS and len(spec.value) > 1:
        edges = spec.value
    else:
        if spec.is_str():
            method = spec.as_str().lower()
            k = {
                "sturges": int(np.ceil(np.log2(n) + 1)),
                "rice": int(np.ceil(2 * n ** (1 / 3))),
                "sqrt": int(np.ceil(np.sqrt(n))),
            }.get(method)
            if k is None:
                edges = np.histogram_bin_edges(d, bins=method)
                k = len(edges) - 1
            else:
                edges = np.linspace(d.min(), d.max(), k + 1)
        else:
            k = int(spec.as_num())
            edges = np.linspace(d.min(), d.max(), k + 1)
    counts, edges = np.histogram(d, bins=edges)
    mids = (edges[:-1] + edges[1:]) / 2
    width = np.diff(edges)
    dens = counts / (counts.sum() * width)
    pad = lambda a: np.concatenate([[np.nan], a]) if len(a) < len(edges) else a
    return Val.frame(
        Frame(
            [
                Column("breaks", edges, ColType.NUM),
                Column("counts", pad(counts.astype(np.float64)), ColType.NUM),
                Column("mids_true", pad(mids), ColType.NUM),
                Column("mids", pad(mids), ColType.NUM),
                Column("density", pad(dens), ColType.NUM),
            ]
        )
    )


@prim("impute", "h2o.impute")
def impute(env, args):
    """(impute fr col method combine_method [by] [groupByFrame] [values])
    (AstImpute): method mean|median|mode; col -1 = all."""
    fr = args[0].as_frame()
    col = int(args[1].as_num()) if len(args) > 1 else -1
    method = args[2].as_str().lower() if len(args) > 2 else "mean"
    by = [int(i) for i in args[4].as_nums()] if len(args) > 4 and args[4].kind == Val.NUMS and len(args[4].value) else None
    targets = range(fr.ncols) if col == -1 else [col]
    out = [c.copy() for c in fr.columns]
    filled_means = []
    for j in targets:
        c = out[j]
        if c.type in (ColType.STR, ColType.UUID):
            continue
        if c.type is ColType.CAT and method != "mode":
            if col != -1:
                raise RapidsError("impute: categorical columns need method=mode")
            continue
        if by:
            from h2o3_tpu.rapids import groupby as G

            order, starts, _ = G.group_keys(fr, by)
            bounds = np.append(starts, fr.nrows)
            d = numeric_data(c).copy()
            for g in range(len(starts)):
                rows = order[bounds[g] : bounds[g + 1]]
                seg = d[rows]
                fill = _impute_value(seg, method)
                seg[np.isnan(seg)] = fill
                d[rows] = seg
            new = d
        else:
            d = numeric_data(c).copy()
            fill = _impute_value(d, method)
            filled_means.append(fill)
            d[np.isnan(d)] = fill
            new = d
        if c.type is ColType.CAT:
            out[j] = Column(c.name, new.astype(np.int32), ColType.CAT, c.domain)
        else:
            out[j] = Column(c.name, new, c.type)
    return Val.frame(Frame(out))


def _impute_value(d: np.ndarray, method: str) -> float:
    ok = d[~np.isnan(d)]
    if not len(ok):
        return float("nan")
    if method == "mean":
        return float(ok.mean())
    if method == "median":
        return float(np.median(ok))
    if method == "mode":
        v, n = np.unique(ok, return_counts=True)
        return float(v[np.argmax(n)])
    raise RapidsError(f"impute: unknown method {method!r}")


@prim("h2o.runif")
def runif(env, args):
    """(h2o.runif fr seed) — uniform [0,1) column, length nrows (AstRunif)."""
    fr = args[0].as_frame()
    seed = int(args[1].as_num()) if len(args) > 1 else -1
    rng = np.random.default_rng(None if seed == -1 else seed)
    return Val.frame(Frame([Column("rnd", rng.random(fr.nrows), ColType.NUM)]))


@prim("kfold_column")
def kfold_column(env, args):
    fr = args[0].as_frame()
    nfolds = int(args[1].as_num())
    seed = int(args[2].as_num()) if len(args) > 2 else -1
    f = fold_assignment(fr.nrows, nfolds, "random", seed if seed != -1 else 42)
    return Val.frame(Frame([Column("fold", f.astype(np.float64), ColType.NUM)]))


@prim("modulo_kfold_column")
def modulo_kfold(env, args):
    fr = args[0].as_frame()
    nfolds = int(args[1].as_num())
    f = fold_assignment(fr.nrows, nfolds, "modulo")
    return Val.frame(Frame([Column("fold", f.astype(np.float64), ColType.NUM)]))


@prim("stratified_kfold_column")
def stratified_kfold(env, args):
    fr = args[0].as_frame()
    nfolds = int(args[1].as_num())
    seed = int(args[2].as_num()) if len(args) > 2 else -1
    y = fr.col(0).numeric_view()
    f = fold_assignment(fr.nrows, nfolds, "stratified", seed if seed != -1 else 42, y=y)
    return Val.frame(Frame([Column("fold", f.astype(np.float64), ColType.NUM)]))


@prim("h2o.random_stratified_split")
def stratified_split(env, args):
    """(h2o.random_stratified_split y test_frac seed) -> 0/1 train/test column
    stratified by the response (AstStratifiedSplit)."""
    fr = args[0].as_frame()
    frac = args[1].as_num()
    seed = int(args[2].as_num()) if len(args) > 2 else -1
    rng = np.random.default_rng(None if seed == -1 else seed)
    y = fr.col(0)
    codes = y.data if y.type is ColType.CAT else y.numeric_view()
    out = np.zeros(fr.nrows, dtype=np.float64)
    cf = np.asarray(codes, dtype=np.float64)
    # exclude NAs from stratification: NaN for numeric, code -1 for CAT
    vals = np.unique(cf[~np.isnan(cf) & (cf >= 0 if y.type is ColType.CAT else True)])
    for v in vals:
        idx = np.nonzero(codes == v)[0]
        k = int(round(len(idx) * frac))
        pick = rng.choice(idx, size=k, replace=False)
        out[pick] = 1.0
    return Val.frame(Frame([Column("test_train_split", out, ColType.CAT, ["train", "test"])]))


@prim("quantile")
def quantile(env, args):
    """(quantile fr [probs] interpolation weights) (AstQtile) — per numeric
    column; returns probs column + per-column quantile columns."""
    fr = args[0].as_frame()
    probs = args[1].as_nums()
    method = args[2].as_str() if len(args) > 2 and args[2].is_str() else "interpolated"
    cols = [Column("Probs", probs.copy(), ColType.NUM)]
    for c in fr.columns:
        if c.type in (ColType.STR, ColType.UUID):
            continue
        d = numeric_data(c)
        d = d[~np.isnan(d)]
        # R type-7 linear interpolation — matches hex/quantile default
        q = np.quantile(d, probs, method="linear" if method.startswith("inter") else "lower")
        cols.append(Column(c.name + "Quantiles", np.asarray(q, dtype=np.float64), ColType.NUM))
    return Val.frame(Frame(cols))


@prim("table")
def table(env, args):
    """(table fr1 [fr2] dense) — frequency table (AstTable)."""
    f1 = args[0].as_frame()
    f2 = args[1].as_frame() if len(args) > 1 and args[1].is_frame() else None
    if f1.ncols == 2 and f2 is None:
        f2 = Frame([f1.col(1)])
        f1 = Frame([f1.col(0)])
    c1 = f1.col(0)

    def codes_domain(c):
        """-> (codes, labels, is_cat, raw_values) — raw numeric uniques kept
        exact (a %g label round-trip would collapse values past 6 sig digits)."""
        if c.type is ColType.CAT:
            return c.data.astype(np.int64), list(c.domain), True, None
        d = numeric_data(c)
        u = np.unique(d[~np.isnan(d)])
        codes = np.full(len(d), -1, dtype=np.int64)
        ok = ~np.isnan(d)
        codes[ok] = np.searchsorted(u, d[ok])
        return codes, [repr(float(v)) for v in u], False, u

    def key_column(c, dom, cat, raw):
        if cat:
            return Column(c.name, np.arange(len(dom), dtype=np.int32), ColType.CAT, dom)
        return Column(c.name, raw.astype(np.float64), ColType.NUM)

    k1, dom1, cat1, raw1 = codes_domain(c1)
    if f2 is None:
        counts = np.bincount(k1[k1 >= 0], minlength=len(dom1)).astype(np.float64)
        return Val.frame(
            Frame([key_column(c1, dom1, cat1, raw1), Column("Count", counts, ColType.NUM)])
        )
    c2 = f2.col(0)
    k2, dom2, cat2, raw2 = codes_domain(c2)
    ok = (k1 >= 0) & (k2 >= 0)
    flat = k1[ok] * len(dom2) + k2[ok]
    counts = np.bincount(flat, minlength=len(dom1) * len(dom2)).reshape(len(dom1), len(dom2))
    cols = [key_column(c1, dom1, cat1, raw1)]
    for j, lv in enumerate(dom2):
        cols.append(Column(str(lv), counts[:, j].astype(np.float64), ColType.NUM))
    return Val.frame(Frame(cols))


@prim("unique")
def unique(env, args):
    """(unique fr include_nas) (AstUnique)."""
    fr = args[0].as_frame()
    include_nas = bool(args[1].as_num()) if len(args) > 1 else False
    c = fr.col(0)
    if c.type is ColType.CAT:
        present = np.unique(c.data[c.data >= 0])
        codes = present.astype(np.int32)
        if include_nas and (c.data < 0).any():
            codes = np.concatenate([codes, [NA_CAT]]).astype(np.int32)
        return Val.frame(Frame([Column(c.name, codes, ColType.CAT, c.domain)]))
    d = numeric_data(c)
    u = np.unique(d[~np.isnan(d)])
    if include_nas and np.isnan(d).any():
        u = np.concatenate([u, [np.nan]])
    return Val.frame(Frame([Column(c.name, u, ColType.NUM)]))


@prim("tf-idf")
def tfidf(env, args):
    """(tf-idf fr doc_id_idx text_idx preprocess case_sensitive) (AstTfIdf).
    Output: [doc_id word tf idf tf_idf] (hex/tfidf MRTasks)."""
    fr = args[0].as_frame()
    doc_idx = int(args[1].as_num())
    text_idx = int(args[2].as_num())
    preprocess = bool(args[3].as_num()) if len(args) > 3 else True
    case_sensitive = bool(args[4].as_num()) if len(args) > 4 else True
    from h2o3_tpu.rapids.prims.strings import _str_values

    docs = fr.col(doc_idx).numeric_view()
    texts = _str_values(fr.col(text_idx))
    pairs = {}
    doc_words = {}
    if preprocess:
        tokens_per_row = [
            (d, (t if case_sensitive else t.lower()).split()) if t is not None else (d, [])
            for d, t in zip(docs, texts)
        ]
    else:
        tokens_per_row = [
            (d, [t if case_sensitive else t.lower()]) if t is not None else (d, [])
            for d, t in zip(docs, texts)
        ]
    from collections import Counter, defaultdict

    tf = defaultdict(Counter)
    for d, toks in tokens_per_row:
        tf[d].update(toks)
    n_docs = len(tf)
    df = Counter()
    for d, counter in tf.items():
        df.update(counter.keys())
    rows = []
    for d in sorted(tf):
        for w, c in sorted(tf[d].items()):
            idf = np.log((1.0 + n_docs) / (1.0 + df[w]))
            rows.append((d, w, float(c), idf, float(c) * idf))
    words = sorted({w for _, w, *_ in rows})
    widx = {w: i for i, w in enumerate(words)}
    return Val.frame(
        Frame(
            [
                Column(fr.names[doc_idx], np.array([r[0] for r in rows]), ColType.NUM),
                Column(fr.names[text_idx], np.array([widx[r[1]] for r in rows], dtype=np.int32), ColType.CAT, words),
                Column("TF", np.array([r[2] for r in rows]), ColType.NUM),
                Column("IDF", np.array([r[3] for r in rows]), ColType.NUM),
                Column("TF_IDF", np.array([r[4] for r in rows]), ColType.NUM),
            ]
        )
    )


# -- repeaters / sequences ---------------------------------------------------
@prim("rep_len")
def rep_len(env, args):
    v = args[0]
    n = int(args[1].as_num())
    if v.is_frame():
        c = v.value.col(0)
        data = np.resize(c.data, n)
        return Val.frame(Frame([Column(c.name, data, c.type, c.domain)]))
    return Val.frame(Frame([Column("C1", np.full(n, v.as_num()), ColType.NUM)]))


@prim("seq")
def seq(env, args):
    frm, to, by = args[0].as_num(), args[1].as_num(), args[2].as_num() if len(args) > 2 else 1.0
    vals = np.arange(frm, to + by * 0.5 * np.sign(by), by)
    return Val.frame(Frame([Column("C1", vals, ColType.NUM)]))


@prim("seq_len")
def seq_len(env, args):
    n = int(args[0].as_num())
    return Val.frame(Frame([Column("C1", np.arange(1, n + 1, dtype=np.float64), ColType.NUM)]))


# -- time series -------------------------------------------------------------
@prim("difflag1")
def difflag1(env, args):
    """(difflag1 fr) — first difference x[i]-x[i-1], first row NA (AstDiffLag1)."""
    fr = args[0].as_frame()
    c = fr.col(0)
    d = numeric_data(c)
    out = np.concatenate([[np.nan], np.diff(d)])
    return Val.frame(Frame([Column(c.name, out, ColType.NUM)]))


@prim("isax")
def isax(env, args):
    """(isax fr num_words max_cardinality optimize_card) — iSAX2 symbolic
    aggregate approximation of each row's time series (AstIsax)."""
    fr = args[0].as_frame()
    num_words = int(args[1].as_num())
    max_card = int(args[2].as_num())
    mat = _matrix(fr)
    n, t = mat.shape
    mu = np.nanmean(mat, axis=1, keepdims=True)
    sd = np.nanstd(mat, axis=1, keepdims=True)
    sd[sd == 0] = 1.0
    z = (mat - mu) / sd
    # PAA: mean per word segment
    seg = np.array_split(np.arange(t), num_words)
    paa = np.stack([np.nanmean(z[:, s], axis=1) for s in seg], axis=1)
    # gaussian breakpoints for max_card symbols
    from scipy import stats as _st

    bp = _st.norm.ppf(np.linspace(0, 1, max_card + 1)[1:-1])
    codes = np.stack([np.searchsorted(bp, paa[:, j]) for j in range(num_words)], axis=1)
    strings = np.array(["^".join(str(int(v)) for v in row) for row in codes], dtype=object)
    cols = [Column("iSax_index", strings, ColType.STR)]
    for j in range(num_words):
        cols.append(Column(f"iSax_word_{j}", codes[:, j].astype(np.float64), ColType.NUM))
    return Val.frame(Frame(cols))


# -- misc --------------------------------------------------------------------
@prim("ls")
def ls(env, args):
    from h2o3_tpu.keyed import DKV

    keys = sorted(DKV.keys())
    return Val.frame(Frame([Column("key", np.array(keys, dtype=object), ColType.STR)]))


@prim("setproperty")
def setproperty(env, args):
    import os

    os.environ[args[0].as_str()] = args[1].as_str()
    return Val.num(0)


@prim(",")
def comma(env, args):
    """(, expr expr ...) — sequence; value of the last (AstComma)."""
    return args[-1] if args else Val.num(0)


@prim("distance")
def distance(env, args):
    """(distance references queries measure) — pairwise measure between
    all rows: [R rows x Q cols] (AstDistance). Measures: 'l1', 'l2',
    'cosine' (similarity, dot/(|r||q|)), 'cosine_sq' (dot²/(|r|²|q|²))."""
    refs = _matrix(args[0].as_frame())
    qs = _matrix(args[1].as_frame())
    measure = args[2].as_str().lower()
    if measure not in ("cosine", "cosine_sq", "l1", "l2"):
        raise ValueError(
            f"Invalid distance measure provided: {measure}. Must be one "
            "of ['cosine', 'cosine_sq', 'l1', 'l2']")
    if refs.shape[1] != qs.shape[1]:
        raise ValueError(
            f"Frames must have the same number of cols, found "
            f"{refs.shape[1]} and {qs.shape[1]}")
    if np.isnan(refs).any() or np.isnan(qs).any():
        raise ValueError("distance frames must not contain missing values")
    if measure in ("cosine", "cosine_sq"):
        dot = refs @ qs.T  # [R, Q] — the MXU-shaped path
        dr = (refs * refs).sum(axis=1)[:, None]
        dq = (qs * qs).sum(axis=1)[None, :]
        if measure == "cosine_sq":
            out = (dot * dot) / (dr * dq)
        else:
            out = dot / np.sqrt(dr * dq)
    elif measure == "l2":
        d2 = ((refs * refs).sum(axis=1)[:, None]
              + (qs * qs).sum(axis=1)[None, :]
              - 2.0 * (refs @ qs.T))
        out = np.sqrt(np.maximum(d2, 0.0))
    else:  # l1 — accumulate per feature: a [R, Q, p] broadcast temp
        # would be p times the (already R*Q) output size
        out = np.zeros((refs.shape[0], qs.shape[0]))
        for j in range(refs.shape[1]):
            out += np.abs(refs[:, j][:, None] - qs[:, j][None, :])
    return Val.frame(Frame([
        Column(f"C{j + 1}", out[:, j].astype(np.float64), ColType.NUM)
        for j in range(out.shape[1])
    ]))
