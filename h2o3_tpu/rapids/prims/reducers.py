"""Rapids reducers (26): frame-wide and cumulative reductions.

Reference: ``water/rapids/ast/prims/reducers/`` — All Any AnyNa CumMax CumMin
CumProd CumSum Mad Max MaxNa Mean Median Min MinNa NaCnt Prod ProdNa Sdev Sum
SumAxis SumNa TopN.  Simple reducers ride cached RollupStats in the reference
(RollupOp); here rollups are the same lazily-cached per-column stats
(h2o3_tpu/frame/rollups.py).
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.frame.frame import Column, ColType, Frame
from h2o3_tpu.rapids.prims import prim
from h2o3_tpu.rapids.prims.util import map_columns, numeric_data
from h2o3_tpu.rapids.runtime import RapidsError, Val


def _numeric_cols(fr: Frame):
    return [c for c in fr.columns if c.type not in (ColType.STR, ColType.UUID)]


def _reduce(name, col_fn, all_fn=None, fusible=False):
    """Reducer over every numeric column. With na_rm=0 (default), NAs poison
    the result (reference Max vs MaxNa pairs); the *Na variants skip NAs.

    Fusible reducers may root a fused region: the elementwise chain below
    them compiles into one dispatch and the reducer runs as a host epilogue
    through this very prim, keeping the combine bit-identical (numpy pairwise
    summation does not match an XLA reduction's rounding). Only the
    single-arg form fuses — an explicit na_rm argument falls back."""

    @prim(name, fusible=fusible, kind="reduce",
          fuse_args=(lambda ast_args: len(ast_args) == 1) if fusible else None)
    def op(env, args, col_fn=col_fn, name=name):
        v = args[0]
        na_rm = (
            bool(args[1].as_num())
            if len(args) > 1 and not np.isnan(args[1].as_num())
            else name.lower().endswith("na") or name in ("mean", "median", "sd", "mad")
        )
        if not v.is_frame():
            return Val.num(v.as_num())
        vals = []
        for c in _numeric_cols(v.value):
            d = numeric_data(c)
            if na_rm:
                d = d[~np.isnan(d)]
            with np.errstate(all="ignore"):
                vals.append(float(col_fn(d)) if len(d) else float("nan"))
        if not vals:
            raise RapidsError(f"{name}: no numeric columns")
        return Val.num(vals[0]) if len(vals) == 1 else Val.nums(vals)

    return op


_reduce("max", np.max, fusible=True)
_reduce("maxNA", np.max, fusible=True)
_reduce("min", np.min, fusible=True)
_reduce("minNA", np.min, fusible=True)
_reduce("sum", np.sum, fusible=True)
_reduce("sumNA", np.sum, fusible=True)
_reduce("prod", np.prod, fusible=True)
_reduce("prodNA", np.prod, fusible=True)
_reduce("mean", np.mean, fusible=True)
_reduce("median", np.median)
_reduce("sd", lambda d: np.std(d, ddof=1))
_reduce("mad", lambda d: 1.4826 * np.median(np.abs(d - np.median(d))))
# NaN != 0 is True in numpy, so all/any must NA-poison explicitly under
# na_rm=0 (matching the Max/MaxNa NA-poisoning convention above)
_reduce("all", lambda d: float("nan") if np.isnan(d).any() else float(np.all(d != 0)))
_reduce("any", lambda d: float("nan") if np.isnan(d).any() else float(np.any(d != 0)))


@prim("naCnt")
def na_cnt(env, args):
    fr = args[0].as_frame()
    counts = [float(c.na_count()) for c in fr.columns]
    return Val.num(counts[0]) if len(counts) == 1 else Val.nums(counts)


@prim("anyNA", "any.na")
def any_na(env, args):
    fr = args[0].as_frame()
    return Val.num(float(any(c.na_count() > 0 for c in fr.columns)))


def _cumop(name, fn):
    """Cumulative ops along rows (axis=0) or columns (axis=1)."""

    @prim(name)
    def op(env, args, fn=fn):
        fr = args[0].as_frame()
        axis = int(args[1].as_num()) if len(args) > 1 else 0
        mat = np.stack([numeric_data(c) for c in _numeric_cols(fr)], axis=1)
        out = fn(mat, axis=axis)
        cols = [
            Column(c.name, out[:, j], ColType.NUM)
            for j, c in enumerate(_numeric_cols(fr))
        ]
        return Val.frame(Frame(cols))

    return op


_cumop("cumsum", np.cumsum)
_cumop("cumprod", np.cumprod)
_cumop("cummax", np.maximum.accumulate)
_cumop("cummin", np.minimum.accumulate)


@prim("sumaxis")
def sumaxis(env, args):
    """(sumaxis fr na_rm axis) — axis=0 per-column sums as a 1-row frame,
    axis=1 per-row sums as a 1-col frame (AstSumAxis)."""
    fr = args[0].as_frame()
    na_rm = bool(args[1].as_num()) if len(args) > 1 else False
    axis = int(args[2].as_num()) if len(args) > 2 else 0
    cols = _numeric_cols(fr)
    mat = np.stack([numeric_data(c) for c in cols], axis=1)
    red = np.nansum if na_rm else np.sum
    with np.errstate(all="ignore"):
        if axis == 1:
            return Val.frame(Frame([Column("sum", red(mat, axis=1), ColType.NUM)]))
        sums = red(mat, axis=0)
    return Val.frame(
        Frame([Column(c.name, np.array([s]), ColType.NUM) for c, s in zip(cols, sums)])
    )


@prim("topn")
def topn(env, args):
    """(topn fr col_idx percent grab_top) -> 2-col frame [row_idx value]
    of the top/bottom nrows*percent% values (AstTopN)."""
    fr = args[0].as_frame()
    col = fr.col(int(args[1].as_num()))
    percent = args[2].as_num()
    grab_top = int(args[3].as_num()) if len(args) > 3 else 1
    d = numeric_data(col)
    valid = np.nonzero(~np.isnan(d))[0]
    k = max(1, int(len(d) * percent / 100.0))
    order = np.argsort(d[valid], kind="stable")
    picked = valid[order[::-1][:k]] if grab_top else valid[order[:k]]
    return Val.frame(
        Frame(
            [
                Column("Row Indices", picked.astype(np.float64), ColType.NUM),
                Column(col.name, d[picked], ColType.NUM),
            ]
        )
    )
