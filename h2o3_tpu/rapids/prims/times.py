"""Rapids time prims (16).

Reference: ``water/rapids/ast/prims/time/`` — AsDate Day DayOfWeek GetTimeZone
Hour ListTimeZones Millis Minute Mktime Moment Month Second SetTimeZone Time
Week Year.  TIME columns hold float64 milliseconds since epoch (UTC);
timezone is a process-wide setting like the reference's ParseTime zone.
"""

from __future__ import annotations

import datetime as _dt
from typing import Callable

import numpy as np

from h2o3_tpu.frame.frame import Column, ColType, Frame
from h2o3_tpu.rapids.prims import prim
from h2o3_tpu.rapids.prims.util import map_columns, numeric_data
from h2o3_tpu.rapids.runtime import RapidsError, Val

_TIME_ZONE = "UTC"


def _tz():
    import zoneinfo

    return zoneinfo.ZoneInfo(_TIME_ZONE)


def _field_map(ms: np.ndarray, field: str) -> np.ndarray:
    """Extract a datetime field from ms-since-epoch via numpy datetime64
    (fast path, UTC) or per-element zoneinfo when a zone is set."""
    out = np.full(ms.shape, np.nan)
    ok = ~np.isnan(ms)
    if _TIME_ZONE == "UTC":
        dt = ms[ok].astype("int64").astype("datetime64[ms]")
        Y = dt.astype("datetime64[Y]").astype(np.int64) + 1970
        M = (dt.astype("datetime64[M]").astype(np.int64) % 12) + 1
        D = (dt.astype("datetime64[D]") - dt.astype("datetime64[M]")).astype(np.int64) + 1
        if field == "year":
            out[ok] = Y
        elif field == "month":
            out[ok] = M
        elif field == "day":
            out[ok] = D
        elif field == "dayofweek":
            # 1970-01-01 was Thursday; reference DayOfWeek: 0=Mon..6=Sun
            out[ok] = ((dt.astype("datetime64[D]").astype(np.int64) + 3) % 7)
        elif field == "hour":
            out[ok] = (dt - dt.astype("datetime64[D]")).astype("timedelta64[h]").astype(np.int64)
        elif field == "minute":
            out[ok] = (dt - dt.astype("datetime64[h]")).astype("timedelta64[m]").astype(np.int64)
        elif field == "second":
            out[ok] = (dt - dt.astype("datetime64[m]")).astype("timedelta64[s]").astype(np.int64)
        elif field == "millis":
            out[ok] = (dt - dt.astype("datetime64[s]")).astype("timedelta64[ms]").astype(np.int64)
        elif field == "week":
            iso = [
                _dt.datetime.fromtimestamp(v / 1000.0, _dt.timezone.utc).isocalendar()[1]
                for v in ms[ok]
            ]
            out[ok] = iso
        else:
            raise RapidsError(f"unknown time field {field!r}")
        return out
    tz = _tz()
    for i in np.nonzero(ok)[0]:
        d = _dt.datetime.fromtimestamp(ms[i] / 1000.0, tz)
        out[i] = {
            "year": d.year,
            "month": d.month,
            "day": d.day,
            "dayofweek": d.weekday(),
            "hour": d.hour,
            "minute": d.minute,
            "second": d.second,
            "millis": d.microsecond // 1000,
            "week": d.isocalendar()[1],
        }[field]
    return out


def _timeop(name: str, field: str):
    @prim(name)
    def op(env, args, field=field):
        v = args[0]
        if v.is_frame():
            return Val.frame(map_columns(v.value, lambda a: _field_map(a, field)))
        return Val.num(float(_field_map(np.array([v.as_num()]), field)[0]))

    return op


_timeop("year", "year")
_timeop("month", "month")
_timeop("day", "day")
_timeop("dayOfWeek", "dayofweek")
_timeop("hour", "hour")
_timeop("minute", "minute")
_timeop("second", "second")
_timeop("millis", "millis")
_timeop("week", "week")


@prim("mktime")
def mktime(env, args):
    """(mktime year month day hour minute second msec) — frames or scalars;
    month/day are ZERO-based in rapids (AstMktime)."""
    parts = []
    n = 1
    for v in args:
        if v.is_frame():
            parts.append(numeric_data(v.value.col(0)))
            n = max(n, v.value.nrows)
        else:
            parts.append(np.array([v.as_num()]))
    while len(parts) < 7:
        parts.append(np.zeros(1))
    parts = [np.resize(p, n) for p in parts]
    out = np.empty(n)
    tz = _tz()
    for i in range(n):
        y, mo, d, h, mi, s, ms_ = (parts[j][i] for j in range(7))
        if any(np.isnan(x) for x in (y, mo, d, h, mi, s, ms_)):
            out[i] = np.nan
            continue
        dt = _dt.datetime(
            int(y), int(mo) + 1, int(d) + 1, int(h), int(mi), int(s), int(ms_) * 1000, tzinfo=tz
        )
        out[i] = dt.timestamp() * 1000.0
    if n == 1 and not any(v.is_frame() for v in args):
        return Val.num(float(out[0]))
    return Val.frame(Frame([Column("mktime", out, ColType.TIME)]))


@prim("moment")
def moment(env, args):
    return mktime(env, args)


@prim("as.Date")
def as_date(env, args):
    """(as.Date fr format) — parse STR/CAT to TIME (AstAsDate)."""
    fr = args[0].as_frame()
    fmt = args[1].as_str()
    # translate Joda-ish patterns to strptime
    py_fmt = (
        fmt.replace("yyyy", "%Y").replace("yy", "%y").replace("MM", "%m")
        .replace("dd", "%d").replace("HH", "%H").replace("mm", "%M").replace("ss", "%S")
    )
    from h2o3_tpu.rapids.prims.strings import _str_values

    tz = _tz()
    cols = []
    for c in fr.columns:
        vals = _str_values(c)
        out = np.empty(len(vals))
        for i, v in enumerate(vals):
            if v is None:
                out[i] = np.nan
            else:
                dt = _dt.datetime.strptime(v, py_fmt).replace(tzinfo=tz)
                out[i] = dt.timestamp() * 1000.0
        cols.append(Column(c.name, out, ColType.TIME))
    return Val.frame(Frame(cols))


@prim("time")
def time_(env, args):
    """ms-of-day component."""
    v = args[0]
    fn = lambda a: np.where(np.isnan(a), np.nan, np.mod(a, 86400000.0))
    if v.is_frame():
        return Val.frame(map_columns(v.value, fn))
    return Val.num(float(fn(np.array([v.as_num()]))[0]))


@prim("getTimeZone")
def get_time_zone(env, args):
    return Val.str_(_TIME_ZONE)


@prim("setTimeZone")
def set_time_zone(env, args):
    global _TIME_ZONE
    import zoneinfo

    name = args[0].as_str()
    zoneinfo.ZoneInfo(name)  # validate
    _TIME_ZONE = name
    return Val.str_(name)


@prim("listTimeZones")
def list_time_zones(env, args):
    import zoneinfo

    zones = sorted(zoneinfo.available_timezones())
    return Val.frame(Frame([Column("timezones", np.array(zones, dtype=object), ColType.STR)]))
