"""Rapids operators (21): arithmetic, comparison, logical, ifelse.

Reference: ``water/rapids/ast/prims/operators/`` — And BinOp Div Eq Ge Gt
IfElse IntDiv IntDivR LAnd LOr Le Lt Mod ModR Mul Ne Or Plus Pow Sub.
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.frame.frame import Column, ColType, Frame
from h2o3_tpu.rapids.prims import prim
from h2o3_tpu.rapids.prims.util import binop_frame, numeric_data
from h2o3_tpu.rapids.runtime import RapidsError, Val


def _binop(name: str, fn, emit=None):
    @prim(name, fusible=emit is not None, kind="binop", emit=emit)
    def op(env, args, fn=fn, name=name):
        if len(args) != 2:
            raise RapidsError(f"{name} expects 2 args")
        return _maybe_string_eq(name, args) or binop_frame(args[0], args[1], fn, name)

    return op


# ---------------------------------------------------------------------------
# emit(jnp) tracers — the XLA forms of the fusible operators. Each MUST be
# bit-identical (up to NaN payload) to the host-numpy fn it mirrors for every
# float64 input; ``^`` (power) stays unfused because XLA's pow differs from
# numpy in the last ulp for negative exponents.


def _e_mod(jnp, a, b):
    # XLA's mod gives a +0.0 remainder where numpy's carries the divisor's
    # sign; re-sign exact-zero results to match npy_divmod
    out = jnp.mod(a, b)
    return jnp.where(out == 0.0, jnp.copysign(0.0, b), out)


def _e_intdiv(jnp, a, b):
    # replica of numpy's npy_divmod quotient (fmod -> sign adjust -> snap to
    # integer): plain floor(a/b) diverges on signed zeros, b==0 (numpy
    # returns a/b there) and inf dividends (numpy's fmod poisons them to NaN)
    mod = jnp.fmod(a, b)
    div = (a - mod) / b
    adj = (mod != 0) & ((b < 0) != (mod < 0))
    div = jnp.where(adj, div - 1.0, div)
    fd = jnp.floor(div)
    fd = jnp.where((div - fd) > 0.5, fd + 1.0, fd)
    fd = jnp.where(div == 0, jnp.copysign(0.0, a / b), fd)
    return jnp.where(b == 0, a / b, fd)


def _e_cmp(op):
    def e(jnp, a, b, op=op):
        out = op(a, b).astype(jnp.float64)
        na = jnp.isnan(a) | jnp.isnan(b)
        return jnp.where(na, jnp.nan, out)

    return e


def _e_and(jnp, a, b):
    out = ((a != 0) & (b != 0)).astype(jnp.float64)
    na = jnp.isnan(a) | jnp.isnan(b)
    zero = (a == 0) | (b == 0)
    return jnp.where(na & ~zero, jnp.nan, out)


def _e_or(jnp, a, b):
    out = ((a != 0) | (b != 0)).astype(jnp.float64)
    na = jnp.isnan(a) | jnp.isnan(b)
    one = (~jnp.isnan(a) & (a != 0)) | (~jnp.isnan(b) & (b != 0))
    return jnp.where(na & ~one, jnp.nan, out)


def _maybe_string_eq(name, args):
    """== / != against a string literal compares CAT levels / STR values
    (reference AstEq handles categorical string comparison)."""
    if name not in ("==", "!="):
        return None
    fr_v, s_v = None, None
    if args[0].is_frame() and args[1].is_str():
        fr_v, s_v = args[0], args[1]
    elif args[1].is_frame() and args[0].is_str():
        fr_v, s_v = args[1], args[0]
    else:
        return None
    s = s_v.as_str()
    cols = []
    for c in fr_v.value.columns:
        if c.type is ColType.CAT:
            try:
                code = c.domain.index(s)
                eq = (c.data == code).astype(np.float64)
            except ValueError:
                eq = np.zeros(len(c), dtype=np.float64)
        elif c.type in (ColType.STR, ColType.UUID):
            # vectorized object-array compare: elementwise __eq__ against the
            # scalar, NA (None) cells compare unequal. Some object payloads
            # defeat numpy's elementwise broadcast (it may return a single
            # bool) — fall back to the per-row loop for those.
            arr = np.asarray(c.data, dtype=object)
            raw = arr == s
            if not (isinstance(raw, np.ndarray) and raw.shape == arr.shape):
                raw = np.fromiter((v == s for v in arr), dtype=bool,
                                  count=len(arr))
            eq = raw.astype(np.float64)
        else:
            eq = np.zeros(len(c), dtype=np.float64)
        if name == "!=":
            eq = 1.0 - eq
        cols.append(Column(c.name, eq, ColType.NUM))
    return Val.frame(Frame(cols))


# NaN-propagating comparisons return NaN for NA inputs (reference cmp semantics)
def _cmp(fn):
    def g(a, b):
        out = fn(a, b).astype(np.float64)
        na = np.isnan(a) | np.isnan(b)
        return np.where(na, np.nan, out) if np.ndim(out) else (np.nan if na else out)

    return g


_binop("+", lambda a, b: a + b, emit=lambda jnp, a, b: a + b)
_binop("-", lambda a, b: a - b, emit=lambda jnp, a, b: a - b)
_binop("*", lambda a, b: a * b, emit=lambda jnp, a, b: a * b)
_binop("/", lambda a, b: a / b, emit=lambda jnp, a, b: a / b)
_binop("^", lambda a, b: np.power(a, b))  # unfused: XLA pow is off by ulps
_binop("%", lambda a, b: np.mod(a, b), emit=_e_mod)  # R-style modulo (AstMod)
_binop("%%", lambda a, b: np.mod(a, b), emit=_e_mod)
_binop("intDiv", lambda a, b: np.floor_divide(a, b), emit=_e_intdiv)
_binop("%/%", lambda a, b: np.floor_divide(a, b), emit=_e_intdiv)
_binop("==", _cmp(lambda a, b: a == b), emit=_e_cmp(lambda a, b: a == b))
_binop("!=", _cmp(lambda a, b: a != b), emit=_e_cmp(lambda a, b: a != b))
_binop("<", _cmp(lambda a, b: a < b), emit=_e_cmp(lambda a, b: a < b))
_binop("<=", _cmp(lambda a, b: a <= b), emit=_e_cmp(lambda a, b: a <= b))
_binop(">", _cmp(lambda a, b: a > b), emit=_e_cmp(lambda a, b: a > b))
_binop(">=", _cmp(lambda a, b: a >= b), emit=_e_cmp(lambda a, b: a >= b))
# logical: NA-aware and/or (AstAnd/AstOr: 0 && NA == 0, 1 || NA == 1)


def _and(a, b):
    out = ((a != 0) & (b != 0)).astype(np.float64)
    na = np.isnan(a) | np.isnan(b)
    zero = (a == 0) | (b == 0)
    return np.where(na & ~zero, np.nan, out)


def _or(a, b):
    out = ((a != 0) | (b != 0)).astype(np.float64)
    na = np.isnan(a) | np.isnan(b)
    one = (~np.isnan(a) & (a != 0)) | (~np.isnan(b) & (b != 0))
    return np.where(na & ~one, np.nan, out)


_binop("&", _and, emit=_e_and)
_binop("&&", _and, emit=_e_and)
_binop("|", _or, emit=_e_or)
_binop("||", _or, emit=_e_or)


@prim(
    "ifelse",
    fusible=True,
    kind="ifelse",
    emit=lambda jnp, t, y, n: jnp.where(
        jnp.isnan(t), jnp.nan, jnp.where(t != 0, y, n)
    ),
)
def ifelse(env, args):
    """(ifelse test yes no) — vectorized conditional (AstIfElse)."""
    if len(args) != 3:
        raise RapidsError("ifelse expects 3 args")
    test, yes, no = args
    if not test.is_frame():
        return yes if test.as_num() != 0 else no
    tf = test.value
    n = tf.nrows
    cols = []
    for tc in tf.columns:
        t = numeric_data(tc)

        def _branch(v):
            if v.is_frame():
                c = v.value.col(0)
                d = numeric_data(c)
                return (np.full(n, d[0]) if len(d) == 1 and n > 1 else d), c
            return np.full(n, v.as_num()), None

        yv, yc = _branch(yes)
        nv, nc = _branch(no)
        out = np.where(np.isnan(t), np.nan, np.where(t != 0, yv, nv))
        # preserve a shared categorical domain when both branches agree
        if (
            yc is not None
            and nc is not None
            and yc.type is ColType.CAT
            and nc.type is ColType.CAT
            and yc.domain == nc.domain
        ):
            codes = np.where(np.isnan(out), -1, out).astype(np.int32)
            cols.append(Column(tc.name, codes, ColType.CAT, yc.domain))
        else:
            cols.append(Column(tc.name, out, ColType.NUM))
    return Val.frame(Frame(cols))


@prim(
    "not",
    fusible=True,
    kind="uniop",
    emit=lambda jnp, x: jnp.where(
        jnp.isnan(x), jnp.nan, (x == 0).astype(jnp.float64)
    ),
)
def not_(env, args):
    """(not fr) — logical negation, NA-propagating (math/AstNot)."""
    from h2o3_tpu.rapids.prims.util import map_columns

    v = args[0]
    if not v.is_frame():
        x = v.as_num()
        return Val.num(float("nan") if np.isnan(x) else float(x == 0))
    return Val.frame(
        map_columns(v.value, lambda a: np.where(np.isnan(a), np.nan, (a == 0).astype(np.float64)))
    )
