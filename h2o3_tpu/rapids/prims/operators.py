"""Rapids operators (21): arithmetic, comparison, logical, ifelse.

Reference: ``water/rapids/ast/prims/operators/`` — And BinOp Div Eq Ge Gt
IfElse IntDiv IntDivR LAnd LOr Le Lt Mod ModR Mul Ne Or Plus Pow Sub.
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.frame.frame import Column, ColType, Frame
from h2o3_tpu.rapids.prims import prim
from h2o3_tpu.rapids.prims.util import binop_frame, numeric_data
from h2o3_tpu.rapids.runtime import RapidsError, Val


def _binop(name: str, fn):
    @prim(name)
    def op(env, args, fn=fn, name=name):
        if len(args) != 2:
            raise RapidsError(f"{name} expects 2 args")
        return _maybe_string_eq(name, args) or binop_frame(args[0], args[1], fn, name)

    return op


def _maybe_string_eq(name, args):
    """== / != against a string literal compares CAT levels / STR values
    (reference AstEq handles categorical string comparison)."""
    if name not in ("==", "!="):
        return None
    fr_v, s_v = None, None
    if args[0].is_frame() and args[1].is_str():
        fr_v, s_v = args[0], args[1]
    elif args[1].is_frame() and args[0].is_str():
        fr_v, s_v = args[1], args[0]
    else:
        return None
    s = s_v.as_str()
    cols = []
    for c in fr_v.value.columns:
        if c.type is ColType.CAT:
            try:
                code = c.domain.index(s)
                eq = (c.data == code).astype(np.float64)
            except ValueError:
                eq = np.zeros(len(c), dtype=np.float64)
        elif c.type in (ColType.STR, ColType.UUID):
            eq = np.array([v == s for v in c.data], dtype=np.float64)
        else:
            eq = np.zeros(len(c), dtype=np.float64)
        if name == "!=":
            eq = 1.0 - eq
        cols.append(Column(c.name, eq, ColType.NUM))
    return Val.frame(Frame(cols))


# NaN-propagating comparisons return NaN for NA inputs (reference cmp semantics)
def _cmp(fn):
    def g(a, b):
        out = fn(a, b).astype(np.float64)
        na = np.isnan(a) | np.isnan(b)
        return np.where(na, np.nan, out) if np.ndim(out) else (np.nan if na else out)

    return g


_binop("+", lambda a, b: a + b)
_binop("-", lambda a, b: a - b)
_binop("*", lambda a, b: a * b)
_binop("/", lambda a, b: a / b)
_binop("^", lambda a, b: np.power(a, b))
_binop("%", lambda a, b: np.mod(a, b))  # R-style modulo (AstMod)
_binop("%%", lambda a, b: np.mod(a, b))
_binop("intDiv", lambda a, b: np.floor_divide(a, b))
_binop("%/%", lambda a, b: np.floor_divide(a, b))
_binop("==", _cmp(lambda a, b: a == b))
_binop("!=", _cmp(lambda a, b: a != b))
_binop("<", _cmp(lambda a, b: a < b))
_binop("<=", _cmp(lambda a, b: a <= b))
_binop(">", _cmp(lambda a, b: a > b))
_binop(">=", _cmp(lambda a, b: a >= b))
# logical: NA-aware and/or (AstAnd/AstOr: 0 && NA == 0, 1 || NA == 1)


def _and(a, b):
    out = ((a != 0) & (b != 0)).astype(np.float64)
    na = np.isnan(a) | np.isnan(b)
    zero = (a == 0) | (b == 0)
    return np.where(na & ~zero, np.nan, out)


def _or(a, b):
    out = ((a != 0) | (b != 0)).astype(np.float64)
    na = np.isnan(a) | np.isnan(b)
    one = (~np.isnan(a) & (a != 0)) | (~np.isnan(b) & (b != 0))
    return np.where(na & ~one, np.nan, out)


_binop("&", _and)
_binop("&&", _and)
_binop("|", _or)
_binop("||", _or)


@prim("ifelse")
def ifelse(env, args):
    """(ifelse test yes no) — vectorized conditional (AstIfElse)."""
    if len(args) != 3:
        raise RapidsError("ifelse expects 3 args")
    test, yes, no = args
    if not test.is_frame():
        return yes if test.as_num() != 0 else no
    tf = test.value
    n = tf.nrows
    cols = []
    for tc in tf.columns:
        t = numeric_data(tc)

        def _branch(v):
            if v.is_frame():
                c = v.value.col(0)
                d = numeric_data(c)
                return (np.full(n, d[0]) if len(d) == 1 and n > 1 else d), c
            return np.full(n, v.as_num()), None

        yv, yc = _branch(yes)
        nv, nc = _branch(no)
        out = np.where(np.isnan(t), np.nan, np.where(t != 0, yv, nv))
        # preserve a shared categorical domain when both branches agree
        if (
            yc is not None
            and nc is not None
            and yc.type is ColType.CAT
            and nc.type is ColType.CAT
            and yc.domain == nc.domain
        ):
            codes = np.where(np.isnan(out), -1, out).astype(np.int32)
            cols.append(Column(tc.name, codes, ColType.CAT, yc.domain))
        else:
            cols.append(Column(tc.name, out, ColType.NUM))
    return Val.frame(Frame(cols))


@prim("not")
def not_(env, args):
    """(not fr) — logical negation, NA-propagating (math/AstNot)."""
    from h2o3_tpu.rapids.prims.util import map_columns

    v = args[0]
    if not v.is_frame():
        x = v.as_num()
        return Val.num(float("nan") if np.isnan(x) else float(x == 0))
    return Val.frame(
        map_columns(v.value, lambda a: np.where(np.isnan(a), np.nan, (a == 0).astype(np.float64)))
    )
