"""Rapids matrix prims: distributed matmul + transpose.

Reference: ``water/rapids/ast/prims/matrix/`` — AstMMult (chunk-blocked
distributed matmul), AstTranspose.

TPU-native: THIS op goes to the device — matmul is MXU work.  The left
operand is row-sharded over the mesh; each shard computes its block-row of
the product (no collective needed: the result keeps the row sharding).
Small frames short-circuit to host numpy to skip transfer latency.
"""

from __future__ import annotations

import numpy as np

from h2o3_tpu.frame.frame import Column, ColType, Frame
from h2o3_tpu.rapids.prims import prim
from h2o3_tpu.rapids.runtime import RapidsError, Val

_DEVICE_MIN_ELEMS = 1 << 20  # below this, host matmul wins on transfer cost


@prim("x")
def mmult(env, args):
    """(x fr1 fr2) — matrix multiply (AstMMult)."""
    a_fr = args[0].as_frame()
    b = args[1].as_frame().to_numpy()
    a_shape = (a_fr.nrows, a_fr.ncols)  # metadata only: no materialization
    if a_shape[1] != b.shape[0]:
        raise RapidsError(f"x: shape mismatch {a_shape} @ {b.shape}")
    if a_shape[0] * a_shape[1] + b.size >= _DEVICE_MIN_ELEMS:
        import jax.numpy as jnp

        from h2o3_tpu.frame import devcache
        from h2o3_tpu.parallel.mesh import default_mesh, shard_rows

        mesh = default_mesh()
        # the big left operand's placement is memoized on column versions;
        # to_numpy stays inside the builder so a warm repeat of
        # (x fr other) skips the O(N*P) host materialization too
        a_dev, n = devcache.cached(
            "mmult_lhs", devcache.frame_token(a_fr), None, mesh,
            lambda: shard_rows(
                a_fr.to_numpy().astype(np.float32), mesh, fill=0.0
            ),
            frame_key=getattr(a_fr, "key", None),
        )
        out = np.asarray(jnp.matmul(a_dev, jnp.asarray(b.astype(np.float32))))[:n]
        out = out.astype(np.float64)
    else:
        out = a_fr.to_numpy() @ b
    return Val.frame(
        Frame([Column(f"C{j+1}", out[:, j], ColType.NUM) for j in range(out.shape[1])])
    )


@prim("t")
def transpose(env, args):
    fr = args[0].as_frame()
    m = fr.to_numpy().T
    return Val.frame(
        Frame([Column(f"C{j+1}", m[:, j], ColType.NUM) for j in range(m.shape[1])])
    )
