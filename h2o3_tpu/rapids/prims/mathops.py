"""Rapids math prims (36): elementwise transcendental/rounding functions.

Reference: ``water/rapids/ast/prims/math/`` — Abs..Trunc (SURVEY.md App. A).
All are columnwise NaN-propagating maps over numeric columns.
"""

from __future__ import annotations

import math

import numpy as np
from scipy import special as _sp_special  # scipy ships with jax stack

from h2o3_tpu.rapids.prims import prim
from h2o3_tpu.rapids.prims.util import map_columns
from h2o3_tpu.rapids.runtime import RapidsError, Val


def _uniop(name: str, fn, emit=None):
    @prim(name, fusible=emit is not None, kind="uniop", emit=emit)
    def op(env, args, fn=fn, name=name):
        if len(args) != 1:
            raise RapidsError(f"{name} expects 1 arg")
        v = args[0]
        if v.is_frame():
            return Val.frame(map_columns(v.value, fn))
        with np.errstate(all="ignore"):
            return Val.num(float(fn(np.float64(v.as_num()))))

    return op


def _e_sign(jnp, x):
    # numpy's sign(-0.0) is +0.0; XLA's keeps the zero's sign
    return jnp.where(x == 0.0, 0.0, jnp.sign(x))


# Fusible unaries are exactly the ops whose XLA float64 output is
# bit-identical to numpy's for every input (exact arithmetic / rounding /
# selection, plus sin/cos whose libm tables agree on this backend — all
# verified by the tests/test_rapids_fusion.py parity suite). The
# transcendental family (exp/log/tan/hyperbolics/inverse-trig) and the scipy
# specials differ from numpy in the last ulp under XLA and stay interpreted.
_uniop("abs", np.abs, emit=lambda jnp, x: jnp.abs(x))
_uniop("acos", np.arccos)
_uniop("acosh", np.arccosh)
_uniop("asin", np.arcsin)
_uniop("asinh", np.arcsinh)
_uniop("atan", np.arctan)
_uniop("atanh", np.arctanh)
_uniop("ceiling", np.ceil, emit=lambda jnp, x: jnp.ceil(x))
_uniop("cos", np.cos, emit=lambda jnp, x: jnp.cos(x))
_uniop("cospi", lambda x: np.cos(np.pi * x),
       emit=lambda jnp, x: jnp.cos(jnp.pi * x))
_uniop("cosh", np.cosh)
_uniop("digamma", _sp_special.digamma)
_uniop("exp", np.exp)
_uniop("expm1", np.expm1)
_uniop("floor", np.floor, emit=lambda jnp, x: jnp.floor(x))
_uniop("gamma", _sp_special.gamma)
_uniop("lgamma", _sp_special.gammaln)
_uniop("log", np.log)
_uniop("log10", np.log10)
_uniop("log1p", np.log1p)
_uniop("log2", np.log2)
_uniop("sgn", np.sign, emit=_e_sign)
_uniop("sign", np.sign, emit=_e_sign)
_uniop("sin", np.sin, emit=lambda jnp, x: jnp.sin(x))
_uniop("sinpi", lambda x: np.sin(np.pi * x),
       emit=lambda jnp, x: jnp.sin(jnp.pi * x))
_uniop("sinh", np.sinh)
_uniop("sqrt", np.sqrt, emit=lambda jnp, x: jnp.sqrt(x))
_uniop("tan", np.tan)
_uniop("tanpi", lambda x: np.tan(np.pi * x))
_uniop("tanh", np.tanh)
_uniop("trigamma", lambda x: _sp_special.polygamma(1, x))
_uniop("trunc", np.trunc, emit=lambda jnp, x: jnp.trunc(x))
_uniop("none", lambda x: x, emit=lambda jnp, x: x)  # AstNoOp


def _round_half_even(x, digits):
    # R/H2O round: IEC 60559 round-half-to-even (AstRound)
    return np.round(x, int(digits))


def _round_fuse_args(ast_args):
    # only the digits=0 form fuses: XLA round matches numpy's half-to-even
    # exactly there, while the scaled digits!=0 path multiplies by 10^d and
    # diverges in the last ulp
    from h2o3_tpu.rapids.parser import AstNum

    if len(ast_args) == 1:
        return True
    return (len(ast_args) == 2 and isinstance(ast_args[1], AstNum)
            and ast_args[1].value == 0)


@prim("round", fusible=True, kind="uniop",
      emit=lambda jnp, x: jnp.round(x), fuse_args=_round_fuse_args)
def round_(env, args):
    digits = args[1].as_num() if len(args) > 1 else 0
    v = args[0]
    if v.is_frame():
        return Val.frame(map_columns(v.value, lambda a: _round_half_even(a, digits)))
    return Val.num(float(_round_half_even(np.float64(v.as_num()), digits)))


@prim("signif")
def signif(env, args):
    """(signif fr digits) — round to significant digits (AstSignif)."""
    digits = int(args[1].as_num()) if len(args) > 1 else 6
    digits = max(digits, 1)

    def fn(a):
        with np.errstate(all="ignore"):
            mag = np.where(a == 0, 1.0, np.power(10.0, digits - 1 - np.floor(np.log10(np.abs(a)))))
            return np.round(a * mag) / mag

    v = args[0]
    if v.is_frame():
        return Val.frame(map_columns(v.value, fn))
    return Val.num(float(fn(np.array([v.as_num()]))[0]))
