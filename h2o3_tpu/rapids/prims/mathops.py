"""Rapids math prims (36): elementwise transcendental/rounding functions.

Reference: ``water/rapids/ast/prims/math/`` — Abs..Trunc (SURVEY.md App. A).
All are columnwise NaN-propagating maps over numeric columns.
"""

from __future__ import annotations

import math

import numpy as np
from scipy import special as _sp_special  # scipy ships with jax stack

from h2o3_tpu.rapids.prims import prim
from h2o3_tpu.rapids.prims.util import map_columns
from h2o3_tpu.rapids.runtime import RapidsError, Val


def _uniop(name: str, fn):
    @prim(name)
    def op(env, args, fn=fn, name=name):
        if len(args) != 1:
            raise RapidsError(f"{name} expects 1 arg")
        v = args[0]
        if v.is_frame():
            return Val.frame(map_columns(v.value, fn))
        with np.errstate(all="ignore"):
            return Val.num(float(fn(np.float64(v.as_num()))))

    return op


_uniop("abs", np.abs)
_uniop("acos", np.arccos)
_uniop("acosh", np.arccosh)
_uniop("asin", np.arcsin)
_uniop("asinh", np.arcsinh)
_uniop("atan", np.arctan)
_uniop("atanh", np.arctanh)
_uniop("ceiling", np.ceil)
_uniop("cos", np.cos)
_uniop("cospi", lambda x: np.cos(np.pi * x))
_uniop("cosh", np.cosh)
_uniop("digamma", _sp_special.digamma)
_uniop("exp", np.exp)
_uniop("expm1", np.expm1)
_uniop("floor", np.floor)
_uniop("gamma", _sp_special.gamma)
_uniop("lgamma", _sp_special.gammaln)
_uniop("log", np.log)
_uniop("log10", np.log10)
_uniop("log1p", np.log1p)
_uniop("log2", np.log2)
_uniop("sgn", np.sign)
_uniop("sign", np.sign)
_uniop("sin", np.sin)
_uniop("sinpi", lambda x: np.sin(np.pi * x))
_uniop("sinh", np.sinh)
_uniop("sqrt", np.sqrt)
_uniop("tan", np.tan)
_uniop("tanpi", lambda x: np.tan(np.pi * x))
_uniop("tanh", np.tanh)
_uniop("trigamma", lambda x: _sp_special.polygamma(1, x))
_uniop("trunc", np.trunc)
_uniop("none", lambda x: x)  # AstNoOp


def _round_half_even(x, digits):
    # R/H2O round: IEC 60559 round-half-to-even (AstRound)
    return np.round(x, int(digits))


@prim("round")
def round_(env, args):
    digits = args[1].as_num() if len(args) > 1 else 0
    v = args[0]
    if v.is_frame():
        return Val.frame(map_columns(v.value, lambda a: _round_half_even(a, digits)))
    return Val.num(float(_round_half_even(np.float64(v.as_num()), digits)))


@prim("signif")
def signif(env, args):
    """(signif fr digits) — round to significant digits (AstSignif)."""
    digits = int(args[1].as_num()) if len(args) > 1 else 6
    digits = max(digits, 1)

    def fn(a):
        with np.errstate(all="ignore"):
            mag = np.where(a == 0, 1.0, np.power(10.0, digits - 1 - np.floor(np.log10(np.abs(a)))))
            return np.round(a * mag) / mag

    v = args[0]
    if v.is_frame():
        return Val.frame(map_columns(v.value, fn))
    return Val.num(float(fn(np.array([v.as_num()]))[0]))
