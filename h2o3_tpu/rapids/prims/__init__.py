"""Rapids primitive registry.

Reference: ``water/rapids/ast/prims/{mungers,math,reducers,operators,advmath,
string,time,matrix,assign,search,...}`` — each ``Ast*`` class registers a
name; clients emit exactly these ops (SURVEY.md Appendix A inventory).

Here each primitive is a function ``prim(env, args: List[Val]) -> Val``
registered under one or more rapids names.
"""

from __future__ import annotations

from typing import Callable, Dict, List

PRIMS: Dict[str, Callable] = {}


def prim(*names: str):
    """Register a primitive under the given rapids op names."""

    def deco(fn):
        for n in names:
            if n in PRIMS:
                raise RuntimeError(f"duplicate rapids prim {n!r}")
            PRIMS[n] = fn
        return fn

    return deco


# importing the groups populates PRIMS
from h2o3_tpu.rapids.prims import (  # noqa: E402,F401
    advmath,
    assign,
    mathops,
    matrix,
    models,
    mungers,
    operators,
    reducers,
    search,
    strings,
    times,
)
