"""Rapids primitive registry.

Reference: ``water/rapids/ast/prims/{mungers,math,reducers,operators,advmath,
string,time,matrix,assign,search,...}`` — each ``Ast*`` class registers a
name; clients emit exactly these ops (SURVEY.md Appendix A inventory).

Here each primitive is a function ``prim(env, args: List[Val]) -> Val``
registered under one or more rapids names.

Fusibility: a prim may additionally declare itself *fusible* — eligible for
the rapids fusion pass (h2o3_tpu/rapids/fusion.py), which compiles maximal
subtrees of fusible ops into ONE jitted column-program instead of
interpreting them op-at-a-time. A fusible prim carries an ``emit(jnp, *args)``
tracer that reproduces its host-numpy elementwise semantics **bit-exactly**
under XLA (float64): only prims whose emitters pass the bit-parity suite in
tests/test_rapids_fusion.py may claim the flag, and
scripts/check_telemetry.py lints that every flagged prim has both an emitter
and a parity case. Prims whose XLA counterpart differs from numpy in even
the last ulp (pow, the transcendental family, scipy specials) deliberately
stay unfused and run through the interpreter at region boundaries.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

PRIMS: Dict[str, Callable] = {}


class FuseSpec:
    """Fusibility declaration for one prim.

    kind:
      * ``binop``  — 2-arg elementwise with H2O broadcasting (emit required)
      * ``uniop``  — 1-arg columnwise map (emit required)
      * ``ifelse`` — 3-arg vectorized conditional (emit required)
      * ``select`` — static column re-indexing (cols/cols_py; structural,
                     no emit — the fusion pass rewires column references)
      * ``reduce`` — trailing reducer: the fused program materializes its
                     child chain in one dispatch and the reducer itself runs
                     as a host epilogue THROUGH the registered prim, so the
                     combine is bit-identical to the interpreter by
                     construction (numpy pairwise summation vs an XLA
                     reduction would differ in rounding)

    ``fuse_args(ast_args)`` — optional static predicate over the *unevaluated*
    AST argument list; a node whose args fail it is treated as a region leaf
    (e.g. ``round`` only fuses the digits=0 form, ``cols`` only literal
    selectors, reducers only the single-arg form).
    """

    __slots__ = ("name", "kind", "emit", "fuse_args")

    _EMIT_KINDS = ("binop", "uniop", "ifelse")

    def __init__(self, name: str, kind: str, emit: Optional[Callable],
                 fuse_args: Optional[Callable]) -> None:
        if kind not in ("binop", "uniop", "ifelse", "select", "reduce"):
            raise RuntimeError(f"prim {name!r}: unknown fuse kind {kind!r}")
        if kind in self._EMIT_KINDS and emit is None:
            raise RuntimeError(
                f"prim {name!r} is flagged fusible ({kind}) but has no "
                f"emit(jnp) tracer")
        self.name = name
        self.kind = kind
        self.emit = emit
        self.fuse_args = fuse_args


#: rapids name -> FuseSpec for every prim the fusion pass may fold
FUSIBLE: Dict[str, FuseSpec] = {}


def prim(*names: str, fusible: bool = False, kind: Optional[str] = None,
         emit: Optional[Callable] = None,
         fuse_args: Optional[Callable] = None):
    """Register a primitive under the given rapids op names.

    ``fusible=True`` additionally registers a :class:`FuseSpec` so the
    fusion pass may fold the op into a compiled column-program; ``kind``,
    ``emit`` and ``fuse_args`` describe how (see FuseSpec).
    """

    def deco(fn):
        for n in names:
            if n in PRIMS:
                raise RuntimeError(f"duplicate rapids prim {n!r}")
            PRIMS[n] = fn
            if fusible:
                FUSIBLE[n] = FuseSpec(n, kind, emit, fuse_args)
        return fn

    return deco


# importing the groups populates PRIMS
from h2o3_tpu.rapids.prims import (  # noqa: E402,F401
    advmath,
    assign,
    mathops,
    matrix,
    models,
    mungers,
    operators,
    reducers,
    search,
    strings,
    times,
)
