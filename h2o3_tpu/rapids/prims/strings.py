"""Rapids string prims (17).

Reference: ``water/rapids/ast/prims/string/`` — CountMatches
CountSubstringsWords Entropy Grep LStrip RStrip ReplaceAll ReplaceFirst
StrDistance StrLength StrSplit Substring ToLower ToUpper Tokenize Trim.
String columns stay host-side (device holds dictionary codes only — mirrors
the reference's CStrChunk + domain design, SURVEY.md §7 hard part (c)).
"""

from __future__ import annotations

import math
import re
from collections import Counter
from typing import Callable, List, Optional

import numpy as np

from h2o3_tpu.frame.frame import Column, ColType, Frame, NA_CAT
from h2o3_tpu.rapids.prims import prim
from h2o3_tpu.rapids.runtime import RapidsError, Val


def _str_values(c: Column) -> List[Optional[str]]:
    if c.type is ColType.CAT:
        return [c.domain[i] if i >= 0 else None for i in c.data]
    if c.type in (ColType.STR, ColType.UUID):
        return list(c.data)
    raise RapidsError(f"column {c.name!r} is not a string/categorical column")


def _map_str(fr: Frame, fn: Callable[[str], Optional[str]]) -> Frame:
    """Apply a str->str fn to every string/cat column. CAT columns map their
    domains (the reference mutates domains, not rows — cheap and exact)."""
    cols = []
    for c in fr.columns:
        if c.type is ColType.CAT:
            new_dom = [fn(d) for d in c.domain]
            # domains must stay unique; re-code if the map collapses levels
            if len(set(new_dom)) == len(new_dom):
                cols.append(Column(c.name, c.data.copy(), ColType.CAT, new_dom))
            else:
                uniq = sorted(set(new_dom))
                remap = np.array([uniq.index(d) for d in new_dom], dtype=np.int32)
                codes = np.where(c.data >= 0, remap[np.clip(c.data, 0, None)], NA_CAT).astype(np.int32)
                cols.append(Column(c.name, codes, ColType.CAT, uniq))
        elif c.type in (ColType.STR, ColType.UUID):
            data = np.array([None if v is None else fn(v) for v in c.data], dtype=object)
            cols.append(Column(c.name, data, ColType.STR))
        else:
            cols.append(c.copy())
    return Frame(cols)


def _map_str_num(fr: Frame, fn: Callable[[Optional[str]], float]) -> Frame:
    cols = []
    for c in fr.columns:
        vals = _str_values(c)
        cols.append(Column(c.name, np.array([fn(v) for v in vals], dtype=np.float64), ColType.NUM))
    return Frame(cols)


@prim("tolower")
def tolower(env, args):
    return Val.frame(_map_str(args[0].as_frame(), str.lower))


@prim("toupper")
def toupper(env, args):
    return Val.frame(_map_str(args[0].as_frame(), str.upper))


@prim("trim")
def trim(env, args):
    return Val.frame(_map_str(args[0].as_frame(), str.strip))


@prim("lstrip")
def lstrip(env, args):
    chars = args[1].as_str() if len(args) > 1 else None
    return Val.frame(_map_str(args[0].as_frame(), lambda s: s.lstrip(chars)))


@prim("rstrip")
def rstrip(env, args):
    chars = args[1].as_str() if len(args) > 1 else None
    return Val.frame(_map_str(args[0].as_frame(), lambda s: s.rstrip(chars)))


@prim("replaceall")
def replaceall(env, args):
    pattern, replacement = args[1].as_str(), args[2].as_str()
    ignore_case = bool(args[3].as_num()) if len(args) > 3 else False
    rx = re.compile(pattern, re.IGNORECASE if ignore_case else 0)
    return Val.frame(_map_str(args[0].as_frame(), lambda s: rx.sub(replacement, s)))


@prim("replacefirst")
def replacefirst(env, args):
    pattern, replacement = args[1].as_str(), args[2].as_str()
    ignore_case = bool(args[3].as_num()) if len(args) > 3 else False
    rx = re.compile(pattern, re.IGNORECASE if ignore_case else 0)
    return Val.frame(_map_str(args[0].as_frame(), lambda s: rx.sub(replacement, s, count=1)))


@prim("strsplit")
def strsplit(env, args):
    """(strsplit fr pattern) -> multi-column frame of split parts."""
    fr = args[0].as_frame()
    pattern = args[1].as_str()
    rx = re.compile(pattern)
    out_cols = []
    for c in fr.columns:
        vals = _str_values(c)
        parts = [rx.split(v) if v is not None else [] for v in vals]
        width = max((len(p) for p in parts), default=0)
        for j in range(width):
            data = np.array([p[j] if j < len(p) else None for p in parts], dtype=object)
            out_cols.append(Column(f"{c.name}{j+1}", data, ColType.STR))
    return Val.frame(Frame(out_cols))


@prim("substring")
def substring(env, args):
    fr = args[0].as_frame()
    # AstSubstring clamps indices into [0, len] — raw python slicing would
    # give negative-start from-the-end semantics instead
    start = max(int(args[1].as_num()), 0)
    end = int(args[2].as_num()) if len(args) > 2 and not math.isnan(args[2].as_num()) else None
    if end is not None:
        end = max(end, start)
    return Val.frame(_map_str(fr, lambda s: s[start:end]))


@prim("length", "strlen")
def strlen(env, args):
    return Val.frame(_map_str_num(args[0].as_frame(), lambda v: float(len(v)) if v is not None else float("nan")))


@prim("entropy")
def entropy(env, args):
    """Shannon entropy of the character distribution (AstEntropy)."""

    def ent(v):
        if v is None or not v:
            return float("nan") if v is None else 0.0
        counts = Counter(v)
        n = len(v)
        return -sum((c / n) * math.log2(c / n) for c in counts.values())

    return Val.frame(_map_str_num(args[0].as_frame(), ent))


@prim("countmatches")
def countmatches(env, args):
    pats = args[1].as_strs()
    return Val.frame(
        _map_str_num(
            args[0].as_frame(),
            lambda v: float("nan") if v is None else float(sum(v.count(p) for p in pats)),
        )
    )


@prim("num_valid_substrings")
def count_substrings_words(env, args):
    """(num_valid_substrings fr words_path) — count substrings that are valid
    words (AstCountSubstringsWords; the reference reads a words file)."""
    fr = args[0].as_frame()
    path = args[1].as_str()
    with open(path) as f:
        words = {w.strip() for w in f if w.strip()}

    def count(v):
        if v is None:
            return float("nan")
        n = 0
        for i in range(len(v)):
            for j in range(i + 2, len(v) + 1):  # reference: substrings len>=2
                if v[i:j] in words:
                    n += 1
        return float(n)

    return Val.frame(_map_str_num(fr, count))


@prim("grep")
def grep(env, args):
    """(grep fr regex ignore_case invert output_logical) (AstGrep)."""
    fr = args[0].as_frame()
    rx = re.compile(args[1].as_str(), re.IGNORECASE if len(args) > 2 and args[2].as_num() else 0)
    invert = bool(args[3].as_num()) if len(args) > 3 else False
    output_logical = bool(args[4].as_num()) if len(args) > 4 else False
    vals = _str_values(fr.col(0))
    hit = np.array([bool(rx.search(v)) if v is not None else False for v in vals])
    if invert:
        hit = ~hit
    if output_logical:
        return Val.frame(Frame([Column("grep", hit.astype(np.float64), ColType.NUM)]))
    return Val.frame(
        Frame([Column("grep", np.nonzero(hit)[0].astype(np.float64), ColType.NUM)])
    )


def _levenshtein(a: str, b: str) -> float:
    if a == b:
        return 0.0
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i]
        for j, cb in enumerate(b, 1):
            cur.append(min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + (ca != cb)))
        prev = cur
    return float(prev[-1])


def _jaccard(a: str, b: str) -> float:
    sa, sb = set(a), set(b)
    return len(sa & sb) / len(sa | sb) if sa | sb else 1.0


def _jaro(a: str, b: str) -> float:
    if a == b:
        return 1.0
    la, lb = len(a), len(b)
    if not la or not lb:
        return 0.0
    window = max(la, lb) // 2 - 1
    ma = [False] * la
    mb = [False] * lb
    matches = 0
    for i in range(la):
        lo, hi = max(0, i - window), min(lb, i + window + 1)
        for j in range(lo, hi):
            if not mb[j] and a[i] == b[j]:
                ma[i] = mb[j] = True
                matches += 1
                break
    if not matches:
        return 0.0
    t = 0.0
    k = 0
    for i in range(la):
        if ma[i]:
            while not mb[k]:
                k += 1
            if a[i] != b[k]:
                t += 0.5
            k += 1
    return (matches / la + matches / lb + (matches - t) / matches) / 3.0


def _jaro_winkler(a: str, b: str) -> float:
    j = _jaro(a, b)
    prefix = 0
    for ca, cb in zip(a, b):
        if ca != cb or prefix == 4:
            break
        prefix += 1
    return j + prefix * 0.1 * (1 - j)


_STR_MEASURES = {
    "lv": _levenshtein,
    "levenshtein": _levenshtein,
    "jaccard": _jaccard,
    "jw": _jaro_winkler,
    "jaro_winkler": _jaro_winkler,
}


@prim("strDistance")
def str_distance(env, args):
    """(strDistance fr1 fr2 measure compare_empty) (AstStrDistance)."""
    f1, f2 = args[0].as_frame(), args[1].as_frame()
    measure = args[2].as_str().lower()
    compare_empty = bool(args[3].as_num()) if len(args) > 3 else True
    fn = _STR_MEASURES.get(measure)
    if fn is None:
        raise RapidsError(f"strDistance: unknown measure {measure!r}")
    v1, v2 = _str_values(f1.col(0)), _str_values(f2.col(0))
    out = np.empty(len(v1))
    for i, (a, b) in enumerate(zip(v1, v2)):
        if a is None or b is None or (not compare_empty and (a == "" or b == "")):
            out[i] = np.nan
        else:
            out[i] = fn(a, b)
    return Val.frame(Frame([Column("distance", out, ColType.NUM)]))


@prim("tokenize")
def tokenize(env, args):
    """(tokenize fr regex) -> single string column of tokens with NA row
    separating each input row (AstTokenize output contract)."""
    fr = args[0].as_frame()
    rx = re.compile(args[1].as_str())
    col_vals = [_str_values(c) for c in fr.columns]
    out: List[Optional[str]] = []
    for i in range(fr.nrows):
        for vals in col_vals:
            v = vals[i]
            if v is None:
                continue
            out.extend(t for t in rx.split(v) if t)
        out.append(None)
    return Val.frame(Frame([Column("token", np.array(out, dtype=object), ColType.STR)]))
