"""Shared helpers for rapids primitives: columnwise application + broadcasting.

Mirrors the reference's ``AstBinOp.prim_apply`` family (frame-frame,
frame-scalar, scalar-frame, row broadcasting) and ``AstUniOp`` columnwise
numeric application.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Union

import numpy as np

from h2o3_tpu.frame.frame import Column, ColType, Frame
from h2o3_tpu.rapids.runtime import RapidsError, Val


def numeric_data(col: Column) -> np.ndarray:
    """float64 data with NaN NAs; CAT columns expose their codes
    (matches reference semantics: arithmetic on categoricals uses codes,
    e.g. == comparisons against level indices)."""
    if col.type is ColType.CAT:
        out = col.data.astype(np.float64)
        out[col.data < 0] = np.nan
        return out
    if col.type in (ColType.STR, ColType.UUID):
        raise RapidsError(f"column {col.name!r} is a string column; op needs numeric")
    return col.data


def map_columns(fr: Frame, fn: Callable[[np.ndarray], np.ndarray]) -> Frame:
    """Apply a numeric elementwise fn to every column (AstUniOp over frame)."""
    cols = []
    for c in fr.columns:
        with np.errstate(all="ignore"):
            cols.append(Column(c.name, fn(numeric_data(c)), ColType.NUM))
    return Frame(cols)


def binop_frame(
    lhs: Val, rhs: Val, fn: Callable[[np.ndarray, np.ndarray], np.ndarray], name: str
) -> Val:
    """Generic binary op with H2O's broadcasting rules (AstBinOp):
    frame⊕frame columnwise (or single-column broadcast), frame⊕scalar,
    scalar⊕frame; scalar⊕scalar folds to a number."""
    with np.errstate(all="ignore"):
        if lhs.is_frame() and rhs.is_frame():
            lf, rf = lhs.value, rhs.value
            if lf.nrows != rf.nrows and 1 not in (lf.nrows, rf.nrows):
                raise RapidsError(
                    f"{name}: row mismatch {lf.nrows} vs {rf.nrows}"
                )
            if lf.ncols == rf.ncols:
                pairs = zip(lf.columns, rf.columns)
            elif rf.ncols == 1:
                pairs = ((a, rf.col(0)) for a in lf.columns)
            elif lf.ncols == 1:
                pairs = ((lf.col(0), b) for b in rf.columns)
            else:
                raise RapidsError(f"{name}: column mismatch {lf.ncols} vs {rf.ncols}")
            out = [
                Column(a.name, fn(numeric_data(a), numeric_data(b)), ColType.NUM)
                for a, b in pairs
            ]
            return Val.frame(Frame(out))
        if lhs.is_frame():
            r = rhs.as_num()
            return Val.frame(
                Frame(
                    [
                        Column(c.name, fn(numeric_data(c), r), ColType.NUM)
                        for c in lhs.value.columns
                    ]
                )
            )
        if rhs.is_frame():
            l = lhs.as_num()
            return Val.frame(
                Frame(
                    [
                        Column(c.name, fn(l, numeric_data(c)), ColType.NUM)
                        for c in rhs.value.columns
                    ]
                )
            )
        return Val.num(float(fn(np.float64(lhs.as_num()), np.float64(rhs.as_num()))))


def col_indices(fr: Frame, sel: Val) -> List[int]:
    """Resolve a column selector Val (num, nums, str, strs) to indices
    (AstColSlice / AstColPySlice semantics; negative = from-end python style)."""
    if sel.kind == Val.STR:
        return [fr.names.index(sel.value)]
    if sel.kind == Val.STRS:
        return [fr.names.index(s) for s in sel.value]
    idx = sel.as_nums().astype(np.int64)
    out = []
    for i in idx:
        j = int(i)
        if j < 0:
            j += fr.ncols
        if not 0 <= j < fr.ncols:
            raise RapidsError(f"column index {int(i)} out of range for {fr.ncols} cols")
        out.append(j)
    return out


def row_indices(fr: Frame, sel: Val) -> np.ndarray:
    """Resolve a row selector: nums = indices; single-col frame = bool mask
    or index list (AstRowSlice)."""
    if sel.is_frame():
        c = sel.value.col(0)
        vals = numeric_data(c)
        if sel.value.nrows == fr.nrows and np.all(np.isin(vals[~np.isnan(vals)], (0.0, 1.0))):
            return np.nonzero(vals == 1.0)[0]
        return vals[~np.isnan(vals)].astype(np.int64)
    idx = sel.as_nums().astype(np.int64)
    idx = np.where(idx < 0, idx + fr.nrows, idx)
    return idx


def single_column(v: Val, op: str) -> Column:
    fr = v.as_frame()
    if fr.ncols != 1:
        raise RapidsError(f"{op}: expected a single-column frame, got {fr.ncols} cols")
    return fr.col(0)


def const_frame(name: str, value: float, nrows: int) -> Frame:
    return Frame([Column(name, np.full(nrows, value), ColType.NUM)])
