"""Device-side distributed sort / searchsorted / groupby aggregation.

Reference: ``water/rapids/RadixOrder.java:20,74-85`` (MSB radix partition
of keys across the cluster, per-partition local order) and
``BinaryMerge.java`` (batched merges of sorted key ranges between nodes);
``AstGroup``'s distributed reduction. The reference moves ragged key
ranges between JVMs over its RPC; that shape is hostile to XLA, so the
TPU-native design is a **sample sort** with static shapes:

  1. each shard sorts its rows locally (``lax.sort``),
  2. evenly-spaced key samples are ``all_gather``-ed and D-1 splitters
     chosen (the MSB-partition analogue — data-driven, so skew that
     would starve fixed MSB buckets balances automatically),
  3. every shard scatters its rows into D capacity-S send buffers
     (S = rows/shard, so a destination can NEVER overflow: each of the
     D sources contributes at most S rows) and one ``all_to_all``
     exchanges them over ICI,
  4. each shard merges what it received with one more local sort.

Keys are order-preserving uint64 encodings split into (hi, lo) uint32
lanes (x64 stays off); ties break on the original row id, which both
makes the sort stable and lets multi-column sorts compose LSD-style
exactly like the host ``lexsort``.

Group-by aggregation needs no sort at all: it is a segment reduction,
so each shard computes ``segment_sum`` partials over the group codes
and one ``psum`` combines them (MRTask shape, ``compute/mapreduce.py``).

The host paths in ``merge.py``/``groupby.py`` remain the small-N fast
path and the parity oracle (tests assert device == host).
"""

from __future__ import annotations

import os
from functools import partial
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from h2o3_tpu.parallel.mesh import DATA_AXIS, default_mesh, pad_rows

#: below this many rows the host numpy paths win on latency; overridable
#: for tests and for TPU slices where the crossover sits lower
DIST_SORT_MIN = int(os.environ.get("H2O3_TPU_DIST_SORT_MIN", 262_144))

_SENT_HI = np.uint32(0xFFFFFFFF)
_SENT_LO = np.uint32(0xFFFFFFFF)


# ---------------------------------------------------------------------------
# key encoding: float64 / int codes -> order-preserving uint64 -> (hi, lo)


def encode_f64(x: np.ndarray, ascending: bool = True,
               na_first: bool = True) -> np.ndarray:
    """Order-preserving uint64 image of float64 (the radix key transform,
    RadixOrder's byte-order trick): flip sign bit for positives, all bits
    for negatives; NaN pinned to the low (or high) end."""
    x = np.ascontiguousarray(x, dtype=np.float64)
    x = x + 0.0  # canonicalize -0.0 == +0.0, matching the host oracle
    u = x.view(np.uint64).copy()
    neg = (u >> np.uint64(63)) != 0
    u[neg] = ~u[neg]
    u[~neg] |= np.uint64(1) << np.uint64(63)
    if not ascending:
        u = ~u
    nan = np.isnan(x)
    # reserve the extreme values for NA so it sorts first (Merge.sort
    # semantics: NA = -Inf) regardless of direction
    u[nan] = np.uint64(0) if na_first else np.uint64(0xFFFFFFFFFFFFFFFE)
    return u


def split_u64(u: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    return ((u >> np.uint64(32)).astype(np.uint32),
            (u & np.uint64(0xFFFFFFFF)).astype(np.uint32))


# ---------------------------------------------------------------------------
# distributed argsort (sample sort over the mesh)


@partial(jax.jit, static_argnames=("mesh_size", "n_samples"))
def _sample_sort_program(hi, lo, idx, *, mesh_size: int, n_samples: int):
    """The SPMD program: hi/lo/idx are [Npad] row-sharded; returns
    [Npad * mesh_size]-per-shard (stacked: [D, D*S]) sorted (idx, hi, lo)."""
    mesh = default_mesh(mesh_size)
    D = mesh_size

    def shard_fn(hi_s, lo_s, idx_s):
        S = hi_s.shape[0]
        # 1. local sort (idx as final key => deterministic + stable)
        hi_l, lo_l, idx_l = jax.lax.sort(
            (hi_s, lo_s, idx_s), num_keys=3)
        # 2. splitters from gathered evenly-spaced samples
        pos = (jnp.arange(n_samples) * S) // n_samples
        samp_hi = jax.lax.all_gather(hi_l[pos], DATA_AXIS).reshape(-1)
        samp_lo = jax.lax.all_gather(lo_l[pos], DATA_AXIS).reshape(-1)
        samp_hi, samp_lo = jax.lax.sort((samp_hi, samp_lo), num_keys=2)
        cut = (jnp.arange(1, D) * (D * n_samples)) // D
        sp_hi, sp_lo = samp_hi[cut], samp_lo[cut]  # [D-1]
        # 3. destination shard per row: count of splitters < key
        gt = (hi_l[:, None] > sp_hi[None, :]) | (
            (hi_l[:, None] == sp_hi[None, :]) & (lo_l[:, None] > sp_lo[None, :]))
        dest = jnp.sum(gt, axis=1).astype(jnp.int32)  # [S] in [0, D)
        # position within destination group (dest is sorted ascending
        # because the rows are key-sorted): pos = i - first_i_with_my_dest
        counts = jnp.bincount(dest, length=D)
        starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                                  jnp.cumsum(counts)[:-1].astype(jnp.int32)])
        within = jnp.arange(S, dtype=jnp.int32) - starts[dest]
        # scatter into [D, S] send buffers, sentinel-padded
        buf_hi = jnp.full((D, S), _SENT_HI, jnp.uint32).at[dest, within].set(hi_l)
        buf_lo = jnp.full((D, S), _SENT_LO, jnp.uint32).at[dest, within].set(lo_l)
        buf_ix = jnp.full((D, S), -1, jnp.int32).at[dest, within].set(idx_l)
        # 4. one all_to_all moves bucket d of every shard onto shard d
        r_hi = jax.lax.all_to_all(buf_hi, DATA_AXIS, 0, 0, tiled=False)
        r_lo = jax.lax.all_to_all(buf_lo, DATA_AXIS, 0, 0, tiled=False)
        r_ix = jax.lax.all_to_all(buf_ix, DATA_AXIS, 0, 0, tiled=False)
        # 5. merge the D received runs; sentinels sort last
        m_hi, m_lo, m_ix = jax.lax.sort(
            (r_hi.reshape(-1), r_lo.reshape(-1), r_ix.reshape(-1)),
            num_keys=3)
        return (m_ix[None, :], m_hi[None, :], m_lo[None, :])

    return shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(DATA_AXIS, None),) * 3,
        check_rep=False,
    )(hi, lo, idx)


def device_argsort_u64(keys: np.ndarray,
                       mesh_size: Optional[int] = None) -> np.ndarray:
    """Global stable argsort of uint64 keys on the device mesh."""
    mesh = default_mesh(mesh_size)
    D = mesh.devices.size
    n = len(keys)
    padded, _ = pad_rows(keys, D, fill=np.uint64(0xFFFFFFFFFFFFFFFF))
    hi, lo = split_u64(padded)
    idx = np.arange(len(padded), dtype=np.int32)
    idx[n:] = -1
    sh = NamedSharding(mesh, P(DATA_AXIS))
    m_ix, m_hi, m_lo = _sample_sort_program(
        jax.device_put(hi, sh), jax.device_put(lo, sh),
        jax.device_put(idx, sh),
        mesh_size=D, n_samples=max(1, min(64, len(padded) // D)))
    out = np.asarray(m_ix).reshape(-1)
    return out[out >= 0].astype(np.int64)


def device_lexsort(keys: Sequence[np.ndarray],
                   mesh_size: Optional[int] = None) -> np.ndarray:
    """np.lexsort-compatible (last key primary) via LSD passes of the
    stable device sort: each pass sorts one column with the previous
    pass's rank as the tiebreak id."""
    order = device_argsort_u64(np.asarray(keys[0], dtype=np.uint64),
                               mesh_size)
    for k in keys[1:]:
        k = np.asarray(k, dtype=np.uint64)
        # stable: tiebreak on current rank, then map ranks back to rows
        sub = device_argsort_u64(k[order], mesh_size)
        order = order[sub]
    return order


# ---------------------------------------------------------------------------
# distributed searchsorted (the probe side of the sort-merge join)


def _pair_less(th, tl, qh, ql, or_equal: bool):
    lt = (th < qh) | ((th == qh) & (tl < ql))
    if or_equal:
        lt = lt | ((th == qh) & (tl == ql))
    return lt


def _pair_bisect(thi, tlo, qh, ql, or_equal: bool):
    """Binary search for one (qh, ql) pair in the sorted pair table —
    the single probe body both searchsorted programs share."""
    N = thi.shape[0]

    def cond(state):
        lft, rgt = state
        return lft < rgt

    def body(state):
        lft, rgt = state
        mid = (lft + rgt) // 2
        go_right = _pair_less(thi[mid], tlo[mid], qh, ql, or_equal)
        return jnp.where(go_right, mid + 1, lft), \
            jnp.where(go_right, rgt, mid)

    lft, _ = jax.lax.while_loop(cond, body, (jnp.int32(0), jnp.int32(N)))
    return lft


@partial(jax.jit, static_argnames=("mesh_size", "side"))
def _searchsorted_program(thi, tlo, qhi, qlo, *, mesh_size: int,
                          side: str):
    """uint64 keys live as (hi, lo) uint32 pairs (x64 off), so the probe
    is an explicit vmapped binary search on pairs; the table is
    replicated, the queries row-sharded (every node probes its rows —
    BinaryMerge's binary-search leg)."""
    mesh = default_mesh(mesh_size)
    or_equal = side == "right"

    def shard_fn(qh, ql):
        return jax.vmap(
            lambda a, b: _pair_bisect(thi, tlo, a, b, or_equal))(qh, ql)

    return shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=P(DATA_AXIS),
        check_rep=False,
    )(qhi, qlo)


@partial(jax.jit, static_argnames=("mesh_size",))
def _searchsorted_both_program(thi, tlo, qhi, qlo, *, mesh_size: int):
    """Both probe sides in ONE program: a large join would otherwise
    ship the table + queries to the mesh twice."""
    mesh = default_mesh(mesh_size)

    def shard_fn(qh, ql):
        lo = jax.vmap(
            lambda a, b: _pair_bisect(thi, tlo, a, b, False))(qh, ql)
        hi = jax.vmap(
            lambda a, b: _pair_bisect(thi, tlo, a, b, True))(qh, ql)
        return lo, hi

    return shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(DATA_AXIS), P(DATA_AXIS)),
        check_rep=False,
    )(qhi, qlo)


def _prep_probe(sorted_keys, queries, mesh):
    D = mesh.devices.size
    qpad, _ = pad_rows(np.asarray(queries, np.uint64), D)
    thi, tlo = split_u64(np.asarray(sorted_keys, np.uint64))
    qhi, qlo = split_u64(qpad)
    sh = NamedSharding(mesh, P(DATA_AXIS))
    return (jnp.asarray(thi), jnp.asarray(tlo),
            jax.device_put(qhi, sh), jax.device_put(qlo, sh))


def device_searchsorted(sorted_keys: np.ndarray, queries: np.ndarray,
                        side: str = "left",
                        mesh_size: Optional[int] = None) -> np.ndarray:
    """Probe a replicated sorted uint64 key vector with mesh-sharded
    uint64 queries; matches np.searchsorted(sorted_keys, queries, side)."""
    mesh = default_mesh(mesh_size)
    n = len(queries)
    thi, tlo, qhi, qlo = _prep_probe(sorted_keys, queries, mesh)
    out = _searchsorted_program(
        thi, tlo, qhi, qlo, mesh_size=mesh.devices.size, side=side)
    return np.asarray(out)[:n].astype(np.int64)


def device_searchsorted_both(
    sorted_keys: np.ndarray, queries: np.ndarray,
    mesh_size: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """(left, right) insertion points in one device round trip."""
    mesh = default_mesh(mesh_size)
    n = len(queries)
    thi, tlo, qhi, qlo = _prep_probe(sorted_keys, queries, mesh)
    lo, hi = _searchsorted_both_program(
        thi, tlo, qhi, qlo, mesh_size=mesh.devices.size)
    return (np.asarray(lo)[:n].astype(np.int64),
            np.asarray(hi)[:n].astype(np.int64))


# ---------------------------------------------------------------------------
# distributed group-by aggregation (segment reduction + psum)


@partial(jax.jit, static_argnames=("mesh_size", "num_groups"))
def _segment_agg_program(codes, vals, valid, *, mesh_size: int,
                         num_groups: int):
    """codes/vals/valid row-sharded; vals pre-cleaned (no NaN); valid
    already excludes padding AND NA rows."""
    mesh = default_mesh(mesh_size)

    def shard_fn(c, v, m):
        w = m.astype(jnp.float32)
        vw = v * w
        ones = jax.ops.segment_sum(w, c, num_segments=num_groups)
        s = jax.ops.segment_sum(vw, c, num_segments=num_groups)
        s2 = jax.ops.segment_sum(v * vw, c, num_segments=num_groups)
        big = jnp.where(m, v, jnp.inf)
        small = jnp.where(m, v, -jnp.inf)
        mn = jax.ops.segment_min(big, c, num_segments=num_groups)
        mx = jax.ops.segment_max(small, c, num_segments=num_groups)
        ones = jax.lax.psum(ones, DATA_AXIS)
        s = jax.lax.psum(s, DATA_AXIS)
        s2 = jax.lax.psum(s2, DATA_AXIS)
        mn = jax.lax.pmin(mn, DATA_AXIS)
        mx = jax.lax.pmax(mx, DATA_AXIS)
        return ones, s, s2, mn, mx

    return shard_map(
        shard_fn, mesh=mesh,
        in_specs=(P(DATA_AXIS),) * 3,
        out_specs=(P(),) * 5,
        check_rep=False,
    )(codes, vals, valid)


def device_group_aggregate(
    codes: np.ndarray, values: np.ndarray, num_groups: int,
    mesh_size: Optional[int] = None,
) -> Dict[str, np.ndarray]:
    """Per-group {count, sum, sumsq, min, max, nacnt} of one value column
    over mesh-sharded rows. NaN values count into nacnt and are excluded
    from the moments (AstGroup ignore-NA aggregation). float32 on device
    (TPU-native accumulate; callers needing exact f64 moments use the
    host path — the parity tests bound the difference)."""
    mesh = default_mesh(mesh_size)
    D = mesh.devices.size
    n = len(codes)
    codes = np.asarray(codes, np.int32)
    values = np.asarray(values, np.float64)
    cpad, _ = pad_rows(codes, D)
    vpad, _ = pad_rows(values, D)
    nan_in = np.isnan(vpad)
    valid = np.zeros(len(cpad), dtype=bool)
    valid[:n] = True
    sh = NamedSharding(mesh, P(DATA_AXIS))
    ones, s, s2, mn, mx = _segment_agg_program(
        jax.device_put(cpad, sh),
        jax.device_put(np.nan_to_num(vpad).astype(np.float32), sh),
        jax.device_put(valid & ~nan_in, sh),
        mesh_size=D, num_groups=num_groups)
    na_counts = np.bincount(
        codes[np.isnan(values)], minlength=num_groups
    ).astype(np.float64)
    return {
        "count": np.asarray(ones, dtype=np.float64),
        "sum": np.asarray(s, dtype=np.float64),
        "sumsq": np.asarray(s2, dtype=np.float64),
        "min": np.asarray(mn, dtype=np.float64),
        "max": np.asarray(mx, dtype=np.float64),
        "nacnt": na_counts,
    }
