"""Rapids query fusion — compile munging pipelines into one jitted dispatch.

The evaluator in runtime.py executes op-at-a-time on host numpy: every prim
materializes a full intermediate Frame and never touches XLA, so a 10-op
pipeline pays 10 allocations plus 10 interpreter round-trips. This pass makes
the move XLA itself makes for elementwise chains (and DrJAX makes for placed
building blocks): before interpreting a prim application, greedily cover the
maximal subtree of *fusible* ops rooted there (h2o3_tpu/rapids/prims.FUSIBLE:
arithmetic/comparison/logical operators, bit-exact per-row math, per-row
mungers, trailing reducers), lower it to a single column-program, and dispatch
it as ONE jitted ``map_batches`` call.

Pipeline per candidate region:

1. **Scan** (static, no evaluation): walk the AST from the fusible root;
   non-fusible children become region *leaves* in depth-first argument order —
   exactly the order the interpreter would evaluate them.
2. **Leaf evaluation**: each leaf AST evaluates once through the normal
   evaluator (nested fusible regions inside a leaf fuse recursively).
3. **Plan lookup**: the compiled plan is memoized in the dispatch plan cache
   (:func:`h2o3_tpu.compute.mapreduce.plan_memo`) keyed on the subtree's
   canonical S-expression + the leaf schema, so a repeated pipeline compiles
   nothing.
4. **Lowering** (on miss): replicate ``binop_frame``'s broadcasting and
   naming rules symbolically, producing one expression per output column over
   column references and scalar slots. Literal-only scalar subexpressions fold
   on the host THROUGH the registered prims (exact by construction).
5. **Dispatch**: referenced columns resolve through the PR 3 devcache as
   float64 ``FrameTable``s keyed on per-Column version stamps (an unmutated
   frame re-uploads nothing), merge into one table, and run under
   ``jax.experimental.enable_x64`` so device arithmetic is true float64.
   Trailing reducers run as a host epilogue through their registered prim.

Anything the lowering cannot prove bit-identical — string/categorical
semantics, 1-row broadcasts, computed selectors, runtime type surprises —
raises :class:`_Unfusible` and the region *replays* through the same prim
functions on the already-evaluated leaf values: no double evaluation, and
results (including raised errors) match the interpreter exactly.

Env knobs: ``H2O3_TPU_RAPIDS_FUSION=0`` kills the pass entirely (the
evaluator is then byte-for-byte today's interpreter);
``H2O3_TPU_RAPIDS_FUSION_MIN_OPS`` (default 2) is the minimum fused-op count
worth a device round-trip.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp
from jax.experimental import enable_x64

from h2o3_tpu.compute.mapreduce import (
    FrameTable,
    gather_rows,
    map_batches,
    plan_memo,
)
from h2o3_tpu.frame.devcache import region_token
from h2o3_tpu.frame.frame import Column, ColType, Frame
from h2o3_tpu.parallel.mesh import default_mesh
from h2o3_tpu.rapids.parser import (
    AstExec,
    AstId,
    AstNum,
    AstNumList,
    AstStr,
    AstStrList,
    canonical_sexpr,
)
from h2o3_tpu.rapids.prims import FUSIBLE, PRIMS
from h2o3_tpu.rapids.runtime import Val, eval_ast
from h2o3_tpu.util import telemetry

_FUSION = telemetry.counter(
    "rapids_fusion_total",
    "fusion pass outcome per candidate region (fused = one compiled "
    "dispatch, fallback = replayed through the interpreter prims)",
    labels=("result",),
)
_FUSED_OPS = telemetry.histogram(
    "rapids_fused_ops",
    "prims folded into one fused column-program",
    buckets=(1, 2, 3, 4, 6, 8, 12, 16, 24, 32),
)
_EVAL = telemetry.histogram(
    "rapids_eval_seconds",
    "end-to-end rapids expression evaluation wall time",
    labels=("path",),
)


def enabled() -> bool:
    """Fusion kill switch: H2O3_TPU_RAPIDS_FUSION=0 reproduces the
    pre-fusion interpreter exactly (the pass is a pre-dispatch hook)."""
    return os.environ.get("H2O3_TPU_RAPIDS_FUSION", "1").lower() not in (
        "0", "false", "off")


def min_ops() -> int:
    """Minimum fusible ops a region must cover to be worth one dispatch."""
    try:
        return max(1, int(os.environ.get("H2O3_TPU_RAPIDS_FUSION_MIN_OPS", 2)))
    except ValueError:
        return 2


# ---------------------------------------------------------------------------
# per-eval path accounting (exec_rapids brackets each expression)

_tls = threading.local()


def begin_eval() -> None:
    _tls.fused = False


def observe_eval(seconds: float) -> None:
    path = "fused" if getattr(_tls, "fused", False) else "interpreted"
    _EVAL.observe(seconds, path=path)


class _Unfusible(Exception):
    """Region cannot be compiled bit-identically — replay it instead."""


#: negative plan-cache sentinel: this (sexpr, schema) can never fuse
_UNFUSIBLE_PLAN = "unfusible"

#: AST children the scanner descends into, per fuse kind (remaining args —
#: round digits, cols selectors — are static and handled by the lowering)
_SCAN_ARITY = {"binop": 2, "uniop": 1, "ifelse": 3, "select": 1, "reduce": 1}
_DEFAULT_ARITY = {"binop": 2, "uniop": 1, "ifelse": 3}


# ---------------------------------------------------------------------------
# phase 1: static region scan


def _node_spec(node, root: bool):
    """FuseSpec if ``node`` is a fusible application, else None (leaf)."""
    if not (isinstance(node, AstExec) and isinstance(node.op, AstId)):
        return None
    spec = FUSIBLE.get(node.op.name)
    if spec is None:
        return None
    if spec.kind == "reduce" and not root:
        # interior reducers produce scalars; they stay interpreter leaves
        # (their own argument chain still fuses when the leaf evaluates)
        return None
    if spec.fuse_args is not None:
        if not spec.fuse_args(node.args):
            return None
    elif len(node.args) != _DEFAULT_ARITY.get(spec.kind, -1):
        return None
    return spec


def _scan(node, leaves: List, seen: set) -> int:
    """Fused-op count under ``node``; leaves collect in DFS arg order."""
    if isinstance(node, AstNum):
        return 0
    spec = _node_spec(node, root=False)
    if spec is None:
        if id(node) not in seen:
            seen.add(id(node))
            leaves.append(node)
        return 0
    n = 1
    for child in node.args[: _SCAN_ARITY[spec.kind]]:
        n += _scan(child, leaves, seen)
    return n


# ---------------------------------------------------------------------------
# phase 2: lowering — symbolic column sets replicating binop_frame exactly
#
# Column expressions (plain tuples, safe to close over and hash-print):
#   ("lit", v)            — float literal, baked into the plan key
#   ("sval", k)           — k-th runtime scalar leaf, passed as a traced arg
#   ("colref", li, name)  — column ``name`` of frame leaf ``li``
#   ("emit", prim, *xs)   — FUSIBLE[prim].emit(jnp, *xs)


class _C:
    """One symbolic column: name + expression + leaf-type flags."""

    __slots__ = ("name", "expr", "is_cat", "is_str")

    def __init__(self, name, expr, is_cat=False, is_str=False):
        self.name = name
        self.expr = expr
        self.is_cat = is_cat
        self.is_str = is_str

    def numeric(self):
        # the analogue of util.numeric_data: string columns cannot enter
        # numeric compute (the interpreter raises; we fall back and let it)
        if self.is_str:
            raise _Unfusible
        return self.expr


class _Cols:
    __slots__ = ("cols",)

    def __init__(self, cols):
        self.cols = cols


class _Scalar:
    __slots__ = ("expr",)

    def __init__(self, expr):
        self.expr = expr


def _fold(name: str, scalars: List[float]) -> float:
    """Host-fold a literal-only application through the registered prim —
    identical to the interpreter's scalar path by construction."""
    out = PRIMS[name](None, [Val.num(s) for s in scalars])
    return float(out.as_num())


def _leaf_schema(v: Val) -> Tuple:
    if v.kind == Val.FRAME:
        fr = v.value
        lay = getattr(fr, "chunk_layout", None)
        if lay is not None and getattr(fr, "_materialized", None) is None:
            # chunk-homed and unmaterialized: the layout already knows the
            # schema — inspecting it must not trigger a gather
            cols = tuple(
                (n,
                 1 if t in (ColType.STR, ColType.UUID) else
                 2 if t is ColType.CAT else 0)
                for n, t in zip(lay["column_names"], lay["column_types"]))
            return ("frame",) + cols
        cols = tuple(
            (c.name,
             1 if c.type in (ColType.STR, ColType.UUID) else
             2 if c.type is ColType.CAT else 0)
            for c in fr.columns)
        return ("frame",) + cols
    if v.kind == Val.NUM:
        return ("num",)
    if v.kind == Val.NUMS:
        return ("num",) if len(v.value) == 1 else ("nums", len(v.value))
    return ("other", v.kind)


class _Plan:
    __slots__ = ("static", "out_names", "outputs", "dev_exprs", "refs",
                 "sval_leaves", "lit_vals", "reduce_name", "fn",
                 "validated_token")

    def __init__(self):
        self.static = None          # folded scalar result, or None
        self.out_names = ()         # output column names
        self.outputs = ()           # ("host", li, name) | ("dev", k)
        self.dev_exprs = ()         # computed column expressions
        self.refs = ()              # ordered unique (li, name) device inputs
        self.sval_leaves = ()       # leaf indices feeding scalar slots
        self.lit_vals = ()          # literal constants fed as runtime scalars
        self.reduce_name = None     # host-epilogue reducer prim, if any
        self.fn = None              # the traceable program (stable identity)
        self.validated_token = None  # region_token of last validated inputs


def _build_plan(node, leaf_idx_by_id: Dict[int, int],
                schemas: Tuple) -> "_Plan":
    sval_slots: Dict[int, int] = {}
    for i, sch in enumerate(schemas):
        if sch == ("num",):
            sval_slots[i] = len(sval_slots)

    def leaf_cols(idx: int) -> "_Cols":
        sch = schemas[idx]
        names = [name for name, _tc in sch[1:]]
        if len(set(names)) != len(names):
            raise _Unfusible  # by-name column refs need unique names
        return _Cols([
            _C(name, ("colref", idx, name), is_cat=tc == 2, is_str=tc == 1)
            for name, tc in sch[1:]
        ])

    def branch01(v):
        """ifelse branch: scalar expr, or col(0) of a frame (the prim
        always takes column 0 regardless of width)."""
        if isinstance(v, _Scalar):
            return v.expr, False
        c = v.cols[0]
        return c.numeric(), c.is_cat

    def low(n, root=False):
        if isinstance(n, AstNum):
            return _Scalar(("lit", float(n.value)))
        idx = leaf_idx_by_id.get(id(n))
        if idx is not None:
            sch = schemas[idx]
            if sch[0] == "frame":
                return leaf_cols(idx)
            if sch == ("num",):
                return _Scalar(("sval", sval_slots[idx]))
            raise _Unfusible
        spec = _node_spec(n, root=root)
        if spec is None:  # scanner invariant: every non-leaf is fusible
            raise _Unfusible
        name = n.op.name
        if spec.kind == "reduce":
            child = low(n.args[0])
            return ("reduce", name, child)
        if spec.kind == "select":
            a = low(n.args[0])
            if isinstance(a, _Scalar):
                raise _Unfusible  # as_frame coercion of scalars: fall back
            return _Cols([a.cols[j] for j in _sel_indices(a, n.args[1])])
        if spec.kind == "uniop":
            a = low(n.args[0])
            if isinstance(a, _Scalar):
                if a.expr[0] == "lit":
                    return _Scalar(("lit", _fold(name, [a.expr[1]])))
                return _Scalar(("emit", name, a.expr))
            return _Cols([
                _C(c.name, ("emit", name, c.numeric())) for c in a.cols
            ])
        if spec.kind == "ifelse":
            t = low(n.args[0])
            y = low(n.args[1])
            z = low(n.args[2])
            for b in (y, z):
                if not isinstance(b, (_Scalar, _Cols)):
                    raise _Unfusible
            if isinstance(t, _Scalar):
                if t.expr[0] != "lit":
                    raise _Unfusible
                # (ifelse scalar y n): branch VALUE selection; NaN tests
                # true (nan != 0) exactly like the interpreter's as_num path
                return y if t.expr[1] != 0 else z
            ye, ycat = branch01(y)
            ze, zcat = branch01(z)
            if ycat and zcat:
                # both branches categorical: the interpreter may preserve a
                # shared domain — a non-NUM output shape we never fuse
                raise _Unfusible
            return _Cols([
                _C(tc.name, ("emit", name, tc.numeric(), ye, ze))
                for tc in t.cols
            ])
        # binop — replicate binop_frame's pairing and naming byte-for-byte
        a = low(n.args[0])
        b = low(n.args[1])
        if isinstance(a, _Scalar) and isinstance(b, _Scalar):
            if a.expr[0] == "lit" and b.expr[0] == "lit":
                return _Scalar(
                    ("lit", _fold(name, [a.expr[1], b.expr[1]])))
            return _Scalar(("emit", name, a.expr, b.expr))
        if isinstance(a, _Cols) and isinstance(b, _Scalar):
            return _Cols([
                _C(c.name, ("emit", name, c.numeric(), b.expr))
                for c in a.cols
            ])
        if isinstance(a, _Scalar) and isinstance(b, _Cols):
            return _Cols([
                _C(c.name, ("emit", name, a.expr, c.numeric()))
                for c in b.cols
            ])
        na, nb = len(a.cols), len(b.cols)
        if na == nb:
            pairs = zip(a.cols, b.cols)
        elif nb == 1:
            pairs = ((x, b.cols[0]) for x in a.cols)
        elif na == 1:
            pairs = ((a.cols[0], y) for y in b.cols)
        else:
            raise _Unfusible  # interpreter raises; the fallback will too
        return _Cols([
            _C(x.name, ("emit", name, x.numeric(), y.numeric()))
            for x, y in pairs
        ])

    plan = _Plan()
    res = low(node, root=True)
    if isinstance(res, tuple) and res[0] == "reduce":
        plan.reduce_name = res[1]
        res = res[2]
        if isinstance(res, _Scalar):
            # (reduce scalar) is the scalar itself (interpreter: as_num)
            if res.expr[0] == "lit":
                plan.static = res.expr[1]
                return plan
            raise _Unfusible
    if isinstance(res, _Scalar):
        if res.expr[0] == "lit":
            plan.static = res.expr[1]
            return plan
        raise _Unfusible  # pure-scalar chains: host interpreter is exact
    outputs: List[Tuple] = []
    dev_exprs: List[Tuple] = []
    for c in res.cols:
        if c.expr[0] == "colref":
            # bare pass-through: reuse the host Column object (type, domain
            # and aliasing identical to the interpreter's cols path)
            outputs.append(("host", c.expr[1], c.expr[2]))
        else:
            outputs.append(("dev", len(dev_exprs)))
            dev_exprs.append(c.expr)
    plan.out_names = tuple(c.name for c in res.cols)
    plan.outputs = tuple(outputs)
    # literals become runtime scalar slots, NEVER traced constants: XLA's
    # algebraic simplifier folds constant patterns like x + 0.0 -> x, which
    # flips the sign of zero (-0.0 + 0.0 is +0.0 in IEEE) — with the value
    # unknown at trace time no such folding can fire. The plan key already
    # pins the literal values via the canonical S-expression.
    dev_exprs, lit_vals = _externalize_lits(dev_exprs, len(sval_slots))
    plan.lit_vals = tuple(lit_vals)
    plan.dev_exprs = tuple(dev_exprs)
    refs: Dict[Tuple[int, str], None] = {}

    def walk(e):
        if e[0] == "colref":
            refs.setdefault((e[1], e[2]))
        elif e[0] == "emit":
            for x in e[2:]:
                walk(x)

    for e in dev_exprs:
        walk(e)
    plan.refs = tuple(refs)
    plan.sval_leaves = tuple(sorted(sval_slots, key=sval_slots.get))
    if dev_exprs:
        plan.fn = _make_fn(plan.dev_exprs)
    return plan


def _externalize_lits(exprs: List[Tuple], base_slot: int):
    """Rewrite every ("lit", v) into a fresh ("sval", slot) past the leaf
    slots, returning the rewritten exprs and the literal values in slot
    order."""
    lits: List[float] = []

    def sub(e):
        if e[0] == "lit":
            slot = base_slot + len(lits)
            lits.append(e[1])
            return ("sval", slot)
        if e[0] == "emit":
            return ("emit", e[1]) + tuple(sub(x) for x in e[2:])
        return e

    return [sub(e) for e in exprs], lits


def _sel_indices(a: "_Cols", sel) -> List[int]:
    """Static column selection, replicating util.col_indices; any
    out-of-range/unknown selector falls back so the interpreter raises."""
    names = [c.name for c in a.cols]
    if isinstance(sel, AstStr):
        picks = [sel.value]
    elif isinstance(sel, AstStrList):
        picks = list(sel.values)
    else:
        vals = [sel.value] if isinstance(sel, AstNum) else list(sel.values)
        out = []
        for v in vals:
            j = int(np.int64(v))
            if j < 0:
                j += len(names)
            if not 0 <= j < len(names):
                raise _Unfusible
            out.append(j)
        return out
    try:
        return [names.index(s) for s in picks]
    except ValueError:
        raise _Unfusible


def _akey(li: int, name: str) -> str:
    return f"{li}:{name}"


def _make_fn(dev_exprs: Tuple, decode: Tuple = ()):
    """The jitted column-program. ONE closure per cached plan: map_batches
    keys its shard_map plan on this function's identity, so a warm repeat
    re-traces and re-compiles nothing.

    ``decode`` maps column-ref akeys to chunk-codec decode specs
    (frame/codecs.py group reps) so ENCODED columns feed the program as
    packed codes with the decode arithmetic emitted INTO the trace — XLA
    fuses decompress-into-compute and no dense host copy ever exists:

    - ``("affine", off_slot, scale_slot, sentinel)`` — the input array
      holds u16 codes; decode is ``off + codes.astype(f64) * scale``
      (offset/scale as TRACED scalar slots, never baked constants — the
      _externalize_lits signed-zero rule applies to decode params too)
      with sentinel codes mapping to NaN;
    - ``("dict", table_slot)`` — u16 codes gather into a replicated
      unique-value table riding as a trailing map_batches arg (bit-exact
      by construction);
    - ``("const", val_slot)`` — the column never ships: its value
      broadcasts from a scalar slot;
    - ``("f32",)`` — f32 storage widens in-trace (exact by selection);
    - absent / ``("dense",)`` — the array is plain f64."""
    dec = dict(decode)

    def fused_program(arrays, mask, *svals):
        def col(li, name):
            akey = _akey(li, name)
            spec = dec.get(akey)
            if spec is None or spec[0] == "dense":
                return arrays[akey]
            kind = spec[0]
            if kind == "f32":
                return arrays[akey].astype(jnp.float64)
            if kind == "const":
                return jnp.full(mask.shape, svals[spec[1]],
                                dtype=jnp.float64)
            if kind == "affine":
                c = arrays[akey]
                x = svals[spec[1]] + c.astype(jnp.float64) * svals[spec[2]]
                return jnp.where(c == spec[3], jnp.nan, x)
            if kind == "dict":
                return jnp.take(svals[spec[1]], arrays[akey])
            raise ValueError(f"unknown decode spec {kind!r}")

        def ev(e):
            tag = e[0]
            if tag == "lit":
                return e[1]
            if tag == "sval":
                return svals[e[1]]
            if tag == "colref":
                return col(e[1], e[2])
            spec = FUSIBLE[e[1]]
            return spec.emit(jnp, *[ev(x) for x in e[2:]])

        return tuple(ev(e) for e in dev_exprs)

    return fused_program


# ---------------------------------------------------------------------------
# phase 3: dispatch


def _execute(plan: "_Plan", leaf_vals: List[Val], env) -> Val:
    if plan.static is not None:
        return Val.num(plan.static)
    used: Dict[int, None] = {}
    for kind, *rest in plan.outputs:
        if kind == "host":
            used.setdefault(rest[0])
    for li, _name in plan.refs:
        used.setdefault(li)
    frames = {li: leaf_vals[li].value for li in used}
    nrows = {fr.nrows for fr in frames.values()}
    if len(nrows) != 1 or 0 in nrows:
        raise _Unfusible  # mixed row counts = 1-row broadcasts: interpreter
    n_valid = next(iter(nrows))
    ref_lis = list(dict.fromkeys(li for li, _ in plan.refs))
    by_leaf = {li: [n for j, n in plan.refs if j == li] for li in ref_lis}
    rtok = region_token([(frames[li], by_leaf[li]) for li in ref_lis])
    if rtok is None or rtok != plan.validated_token:
        for li, name in plan.refs:
            if frames[li].col(name).type in (ColType.STR, ColType.UUID):
                raise _Unfusible
        plan.validated_token = rtok
    dev_host: List[np.ndarray] = []
    if plan.dev_exprs:
        svals = [float(leaf_vals[li].as_num()) for li in plan.sval_leaves]
        svals += list(plan.lit_vals)
        mesh = default_mesh()
        # float64 end-to-end: the interpreter computes in host float64, so
        # the device program must too — scoped here, not process-global,
        # so float32 model paths keep their dtype
        with enable_x64():
            merged: Dict[str, Any] = {}
            mask = None
            for li in ref_lis:
                t = FrameTable.from_frame(
                    frames[li], columns=by_leaf[li], mesh=mesh,
                    dtype=jnp.float64, cache=True)
                for name in by_leaf[li]:
                    merged[_akey(li, name)] = t.arrays[name]
                mask = t.mask
            table = FrameTable(merged, mask, n_valid, mesh)
            outs = map_batches(plan.fn, table, *svals)
        dev_host = [gather_rows(o, n_valid).copy() for o in outs]
    cols = []
    for name, out in zip(plan.out_names, plan.outputs):
        if out[0] == "host":
            cols.append(frames[out[1]].col(out[2]))
        else:
            cols.append(Column(name, dev_host[out[1]], ColType.NUM))
    result = Frame(cols)
    if plan.reduce_name is not None:
        return PRIMS[plan.reduce_name](env, [Val.frame(result)])
    return Val.frame(result)


# ---------------------------------------------------------------------------
# fallback: replay the region through the interpreter prims


def _replay(node, env, leaf_val_by_id: Dict[int, Val]) -> Val:
    v = leaf_val_by_id.get(id(node))
    if v is not None:
        return v
    if isinstance(node, AstExec):
        args = [_replay(a, env, leaf_val_by_id) for a in node.args]
        return PRIMS[node.op.name](env, args)
    return eval_ast(node, env)  # literals / static selector args


# ---------------------------------------------------------------------------
# entry point


def try_fuse(node: AstExec, env) -> Optional[Val]:
    """Attempt to execute ``node`` as one fused dispatch.

    Returns the result Val, or None when the node is not a worthwhile
    region root (the caller then interprets it normally). Leaf subtrees are
    evaluated exactly once in interpreter order; any lowering or dispatch
    failure replays the region over those values through the same prim
    functions, so results — and raised errors — match the interpreter."""
    if not enabled():
        return None
    spec = _node_spec(node, root=True)
    if spec is None:
        return None
    leaves: List = []
    seen: set = set()
    n_ops = 1
    for child in node.args[: _SCAN_ARITY[spec.kind]]:
        n_ops += _scan(child, leaves, seen)
    small = n_ops < min_ops()
    from h2o3_tpu.rapids import dist_exec as _dist

    if small and not _dist.peek_dist(leaves, env):
        # below the device-dispatch threshold and nothing chunk-homed in
        # sight: not worth a round-trip, interpret normally
        return None
    leaf_vals = [eval_ast(leaf, env) for leaf in leaves]
    dist = _dist.try_dist(node, leaves, leaf_vals, env)
    if dist is not None:
        _FUSION.inc(result="fused")
        _FUSED_OPS.observe(n_ops)
        _tls.fused = True
        return dist
    if small:
        # the DistFrame declined to ship (or the region is unfusible):
        # replay over the once-evaluated leaves — the interpreter path,
        # minus a second leaf evaluation
        return _replay(node, env, {id(l): v for l, v in zip(leaves,
                                                            leaf_vals)})
    try:
        schemas = tuple(_leaf_schema(v) for v in leaf_vals)
        key = (canonical_sexpr(node), schemas)
        leaf_idx_by_id = {id(leaf): i for i, leaf in enumerate(leaves)}

        def build():
            try:
                return _build_plan(node, leaf_idx_by_id, schemas)
            except _Unfusible:
                return _UNFUSIBLE_PLAN

        plan = plan_memo("rapids_fusion", key, build)
        if plan == _UNFUSIBLE_PLAN:
            raise _Unfusible
        result = _execute(plan, leaf_vals, env)
    except Exception:
        # correctness over cleverness: ANY fused-path failure replays the
        # region through the interpreter prims on the already-evaluated
        # leaves (genuine user errors re-raise from there, identically)
        _FUSION.inc(result="fallback")
        return _replay(node, env, {id(l): v for l, v in zip(leaves, leaf_vals)})
    _FUSION.inc(result="fused")
    _FUSED_OPS.observe(n_ops)
    _tls.fused = True
    return result
