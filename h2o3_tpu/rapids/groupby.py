"""Group-by aggregation engine.

Reference: ``water/rapids/ast/prims/mungers/AstGroup.java`` — distributed
group-by computing aggregates {nrow, mean, sum, min, max, sd, var, mode,
median, first, last} per group with per-agg NA handling (all/rm/ignore).

TPU-native: groups are materialized with a single lexicographic sort of the
group-key codes (np.lexsort ≡ the reference's radix-order pass), then each
aggregate is one segmented reduction over the sorted runs — the same
sort-then-segment shape a device implementation uses (jax.ops.segment_*);
host numpy keeps it allocation-light for the munging path.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from h2o3_tpu.frame.frame import Column, ColType, Frame
from h2o3_tpu.rapids.merge import lexsort

AGGS = ("nrow", "mean", "sum", "min", "max", "sd", "var", "mode", "median", "first", "last")


def group_keys(fr: Frame, by: Sequence[int]) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Return (sorted_order, group_starts, group_ids_sorted): rows lexsorted
    by the key columns, run boundaries marking each distinct key."""
    keys = []
    for j in by:
        c = fr.col(j)
        if c.type is ColType.CAT:
            keys.append(c.data.astype(np.int64))
        elif c.type in (ColType.STR, ColType.UUID):
            _, codes = np.unique(np.asarray([("" if v is None else str(v)) for v in c.data]), return_inverse=True)
            keys.append(codes.astype(np.int64))
        else:
            # factorize numeric values (NaN -> own group at the end)
            d = c.data
            uniq, codes = np.unique(d[~np.isnan(d)], return_inverse=True)
            full = np.full(len(d), len(uniq), dtype=np.int64)
            full[~np.isnan(d)] = codes
            keys.append(full)
    order = lexsort(list(reversed(keys)))
    stacked = np.stack([k[order] for k in keys], axis=1)
    change = np.any(stacked[1:] != stacked[:-1], axis=1)
    starts = np.concatenate([[0], np.nonzero(change)[0] + 1])
    return order, starts, stacked


def _segment_apply(vals: np.ndarray, starts: np.ndarray, fn, na: str) -> np.ndarray:
    out = np.empty(len(starts), dtype=np.float64)
    bounds = np.append(starts, len(vals))
    for g in range(len(starts)):
        seg = vals[bounds[g] : bounds[g + 1]]
        if na == "rm":
            seg = seg[~np.isnan(seg)]
        out[g] = fn(seg) if len(seg) else np.nan
    return out


def _agg_fn(name: str):
    if name == "nrow":
        return len
    if name == "mean":
        return np.mean
    if name == "sum":
        return np.sum
    if name == "min":
        return np.min
    if name == "max":
        return np.max
    if name == "sd":
        return lambda s: np.std(s, ddof=1) if len(s) > 1 else np.nan
    if name == "var":
        return lambda s: np.var(s, ddof=1) if len(s) > 1 else np.nan
    if name == "median":
        return np.median
    if name == "first":
        return lambda s: s[0]
    if name == "last":
        return lambda s: s[-1]
    if name == "mode":
        def mode(s):
            if not len(s):
                return np.nan
            v, c = np.unique(s[~np.isnan(s)], return_counts=True)
            return v[np.argmax(c)] if len(v) else np.nan
        return mode
    raise ValueError(f"unknown aggregate {name!r}")


#: aggregates the device segment-reduction path covers (order statistics
#: like mode/median stay host-side)
_DEVICE_AGGS = {"nrow", "mean", "sum", "min", "max", "sd", "var"}


def _group_by_device(
    fr: Frame, by: Sequence[int], aggs: Sequence[Tuple[str, int, str]]
) -> Optional[Frame]:
    """Mesh path: factorize the key tuple host-side (one pass), then every
    aggregate is a per-shard segment reduction + psum on the device mesh
    (``dist.device_group_aggregate`` — AstGroup's distributed reduction,
    TPU-native). Covers {nrow, mean, sum, min, max, sd, var} with NA
    removal; anything else falls back to the host engine (None)."""
    from h2o3_tpu.rapids import dist

    if fr.nrows < dist.DIST_SORT_MIN:
        return None
    if not all(
        a in _DEVICE_AGGS and (na == "rm" or a == "nrow")
        for a, _j, na in aggs
    ):
        return None
    # composite key code, first column most significant — so sorted
    # composites enumerate groups in the host engine's exact order
    keys = []
    for j in by:
        c = fr.col(j)
        if c.type is ColType.CAT:
            keys.append((c.data.astype(np.int64), len(c.domain) + 1))
        elif c.type in (ColType.STR, ColType.UUID):
            _, codes = np.unique(np.asarray(
                [("" if v is None else str(v)) for v in c.data]),
                return_inverse=True)
            keys.append((codes.astype(np.int64), int(codes.max()) + 2))
        else:
            d = c.data
            uniq, codes = np.unique(d[~np.isnan(d)], return_inverse=True)
            full = np.full(len(d), len(uniq), dtype=np.int64)
            full[~np.isnan(d)] = codes
            keys.append((full, len(uniq) + 2))
    comp = np.zeros(fr.nrows, dtype=np.int64)
    for k, card in keys:
        if int(comp.max(initial=0)) > (2**62) // card:
            return None  # composite would overflow: host path
        comp = comp * card + (k + 1)
    uniq_codes, first_rows, inv = np.unique(
        comp, return_index=True, return_inverse=True)
    G = len(uniq_codes)
    inv = inv.astype(np.int32)

    out_cols: List[Column] = []
    for j in by:
        c = fr.col(j)
        out_cols.append(Column(c.name, c.data[first_rows], c.type, c.domain))
    cache: dict = {}
    for agg_name, j, na in aggs:
        if agg_name == "nrow" and (na != "rm" or j < 0):
            cnt = np.bincount(inv, minlength=G).astype(np.float64)
            out_cols.append(Column("nrow", cnt, ColType.NUM))
            continue
        col = fr.col(j)
        if j not in cache:
            vals = col.numeric_view()
            # center before the f32 device accumulate: shifts cancel in
            # var and are added back to sum/mean exactly once, and the
            # conditioning of sumsq improves by orders of magnitude
            with np.errstate(all="ignore"):
                shift = float(np.nanmean(vals)) if len(vals) else 0.0
            if np.isnan(shift):
                shift = 0.0
            agg = dist.device_group_aggregate(inv, vals - shift, G)
            cache[j] = (agg, shift)
        agg, shift = cache[j]
        n, s = agg["count"], agg["sum"]
        if agg_name == "nrow":
            res = n
        elif agg_name == "sum":
            # empty post-rm segment is NA, matching the host oracle
            res = np.where(n > 0, s + n * shift, np.nan)
        elif agg_name == "mean":
            res = np.where(n > 0, s / np.maximum(n, 1) + shift, np.nan)
        elif agg_name == "min":
            res = np.where(n > 0, agg["min"] + shift, np.nan)
        elif agg_name == "max":
            res = np.where(n > 0, agg["max"] + shift, np.nan)
        else:  # sd / var on centered moments
            var = np.where(
                n > 1,
                (agg["sumsq"] - s * s / np.maximum(n, 1)) / np.maximum(n - 1, 1),
                np.nan,
            )
            var = np.maximum(var, 0.0)
            res = np.sqrt(var) if agg_name == "sd" else var
        # the host engine names every nrow aggregate plain "nrow"
        name = "nrow" if agg_name == "nrow" else f"{agg_name}_{col.name}"
        base, k2 = name, 1
        while any(c.name == name for c in out_cols):
            name = f"{base}_{k2}"
            k2 += 1
        out_cols.append(Column(name, np.asarray(res, np.float64), ColType.NUM))
    return Frame(out_cols)


def group_by(
    fr: Frame,
    by: Sequence[int],
    aggs: Sequence[Tuple[str, int, str]],
) -> Frame:
    """aggs: list of (agg_name, col_idx, na_handling) with na in all|rm|ignore.
    Output: one row per group — key columns then one column per aggregate,
    named ``{agg}_{col}`` (matches reference output naming).

    Large frames aggregate on the device mesh (segment reduction + psum,
    ``rapids/dist.py``); the host engine below is the small-N path, the
    order-statistics (mode/median) path, and the parity oracle."""
    try:
        dev = _group_by_device(fr, by, aggs)
    except Exception:
        dev = None
    if dev is not None:
        return dev
    order, starts, stacked = group_keys(fr, by)
    bounds = np.append(starts, fr.nrows)
    out_cols: List[Column] = []
    for i, j in enumerate(by):
        c = fr.col(j)
        first_rows = order[starts]
        out_cols.append(Column(c.name, c.data[first_rows], c.type, c.domain))
    for agg_name, j, na in aggs:
        if agg_name == "nrow":
            if na == "rm" and j >= 0:
                vals = fr.col(j).numeric_view()[order]
                cnt = _segment_apply(vals, starts, len, "rm")
                cnt = np.nan_to_num(cnt, nan=0.0)  # a count is 0, never NA
            else:
                cnt = (bounds[1:] - bounds[:-1]).astype(np.float64)
            out_cols.append(Column("nrow", cnt, ColType.NUM))
            continue
        col = fr.col(j)
        vals = col.numeric_view()[order]
        res = _segment_apply(vals, starts, _agg_fn(agg_name), na)
        name = f"{agg_name}_{col.name}"
        base, k = name, 1
        while any(c.name == name for c in out_cols):
            name = f"{base}_{k}"
            k += 1
        if agg_name in ("mode", "first", "last") and col.type is ColType.CAT:
            codes = np.where(np.isnan(res), -1, res).astype(np.int32)
            out_cols.append(Column(name, codes, ColType.CAT, col.domain))
        else:
            out_cols.append(Column(name, res, ColType.NUM))
    return Frame(out_cols)


def rank_within_group_by(
    fr: Frame, by: Sequence[int], sort_cols: Sequence[int], ascending: Sequence[bool],
    new_col: str,
) -> Frame:
    """AstRankWithinGroupBy: dense rank of rows within each group under the
    given sort order; NAs get NaN rank."""
    order, starts, _ = group_keys(fr, by)
    bounds = np.append(starts, fr.nrows)
    rank = np.full(fr.nrows, np.nan)
    sort_vals = [fr.col(j).numeric_view() for j in sort_cols]
    for g in range(len(starts)):
        rows = order[bounds[g] : bounds[g + 1]]
        keys = []
        valid = np.ones(len(rows), dtype=bool)
        for v, asc in zip(reversed(sort_vals), reversed(list(ascending))):
            vv = v[rows]
            valid &= ~np.isnan(vv)
            keys.append(vv if asc else -vv)
        rows_v = rows[valid]
        if not len(rows_v):
            continue
        sub = lexsort([k[valid] for k in keys])
        rank[rows_v[sub]] = np.arange(1, len(rows_v) + 1, dtype=np.float64)
    out = fr.add_column(Column(new_col, rank, ColType.NUM))
    return out
