"""Distributed Rapids — ship fused column programs to chunk homes.

The fusion pass (rapids/fusion.py) compiles a munging pipeline into one
column program; this module moves that program to the data instead of the
data to the program.  When every frame leaf of a fused region is an
unmaterialized chunk-homed :class:`~h2o3_tpu.cluster.frames.DistFrame` on
ONE layout, the region's canonical S-expression + leaf schemas (tiny,
like PR 15's ``__dist__`` frame reference) ship to each chunk home as a
``rapids_exec`` ctx-DTask.  Each home rebuilds the plan out of its own
mapreduce plan cache (:func:`plan_memo` — a warm op compiles nothing
home-side), assembles its group's columns through the devcache-resident
chunk path, runs the same jitted ``map_batches`` program the local pass
would, and either

* returns a tiny **reducer partial** (trailing-reducer regions — the
  caller merges partials in canonical home order, the ``mr_chunks``
  shape), or
* writes the derived columns straight back to the ring as **new
  chunk-homed vectors on the same layout** (same ESPC bounds, same
  homes, replicated ×``H2O3_TPU_CHUNK_REPLICAS``) and returns only the
  new layout arithmetic — ``:=`` assignment, filters, and column
  pipelines never move row data.

Recovery rides the chunk-home ladder exactly like ``mr_chunks``:
home → ring-successor replica → any survivor → caller-local execution
from replica chunks (``cluster_fanout_recovered_total{path=...}``).
Results stay bit-identical to the local interpreter (uint64 views,
both-NaN exempt): home arithmetic is the identical float64 program over
the identical chunk bytes, and partial merging is restricted to the
reduction shapes whose regrouping is IEEE-exact for the values involved
(min/max always; sum/mean/prod partials are combined with the same numpy
reduction the interpreter applies).  Anything else — unfusible regions,
mixed layouts, string outputs, row-subset assigns — declines and falls
back to the exact gather path: correctness never depends on fusibility.

Env knobs: ``H2O3_TPU_RAPIDS_DIST=0`` kills the pass (every DistFrame
eval gathers, today's behavior); ``H2O3_TPU_RAPIDS_DIST_TIMEOUT``
(seconds, default 120) bounds each per-group RPC before the ladder
moves to the next rung.
"""

from __future__ import annotations

import os
import pickle
import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from jax.experimental import enable_x64

from h2o3_tpu.cluster import frames as _frames
from h2o3_tpu.cluster import rpc as _rpc
from h2o3_tpu.cluster.dkv import MAX_REPLICAS
from h2o3_tpu.compute.mapreduce import FrameTable, gather_rows, map_batches, \
    plan_memo
from h2o3_tpu.frame import codecs as _codecs
from h2o3_tpu.frame import devcache as _devcache
from h2o3_tpu.frame.frame import ColType, NA_CAT
from h2o3_tpu.parallel.mesh import default_mesh, row_mask, shard_rows
from h2o3_tpu.rapids import fusion as _fusion
from h2o3_tpu.rapids.parser import AstId, canonical_sexpr
from h2o3_tpu.rapids.runtime import Val
from h2o3_tpu.util import flight as _flight
from h2o3_tpu.util import ledger as _ledger
from h2o3_tpu.util import telemetry

_DIST = telemetry.counter(
    "rapids_dist_total",
    "distributed-Rapids dispatch outcome per eligible region: dist = "
    "executed on the chunk homes (only sexpr out, partials/layout back), "
    "fallback = a distributed attempt failed mid-flight and the region "
    "re-ran on the exact gather path, gather = a DistFrame was present "
    "but the region could not ship (unfusible, mixed layouts, string "
    "outputs, no ring)",
    labels=("result",),
)
_PARTIAL_BYTES = telemetry.counter(
    "rapids_dist_partial_bytes_total",
    "bytes of reducer partials and layout arithmetic returned by chunk "
    "homes to the Rapids caller — the entire data-plane response of a "
    "distributed eval (compare against the frame bytes a gather would "
    "have moved)",
)


def enabled() -> bool:
    """Kill switch: H2O3_TPU_RAPIDS_DIST=0 makes every DistFrame eval
    gather through the store exactly as before this pass existed."""
    return os.environ.get("H2O3_TPU_RAPIDS_DIST", "1").lower() not in (
        "0", "false", "off")


def dist_timeout() -> float:
    """Per-group RPC deadline (H2O3_TPU_RAPIDS_DIST_TIMEOUT seconds)
    before the recovery ladder tries the next rung."""
    try:
        return float(os.environ.get("H2O3_TPU_RAPIDS_DIST_TIMEOUT", "120"))
    except ValueError:
        return 120.0


class _NotDistributable(Exception):
    """Region cannot ship — fall straight back to the gather path."""


class _NonBinary(Exception):
    """A filter selector turned out not to be a 0/1 mask home-side."""


#: trailing reducers whose partial/merge regrouping this module implements
#: (the full fusible set); anything else declines to the gather path
_RFNS = {"max": np.max, "maxNA": np.max, "min": np.min, "minNA": np.min,
         "sum": np.sum, "sumNA": np.sum, "prod": np.prod, "prodNA": np.prod}
_DIST_REDUCERS = frozenset(_RFNS) | {"mean"}


def _na_rm(reduce_name: str) -> bool:
    # mirror of prims.reducers._reduce's default na_rm resolution for the
    # fusible reducers (mean strips NAs; the NA-suffixed variants do too)
    return reduce_name.lower().endswith("na") or reduce_name == "mean"


def _is_dist(fr) -> bool:
    """An unmaterialized chunk-homed frame — the only shape worth
    shipping to (a materialized one already paid the gather)."""
    return (fr is not None
            and getattr(fr, "chunk_layout", None) is not None
            and getattr(fr, "_materialized", None) is None)


def _aligned(a: Dict[str, Any], b: Dict[str, Any]) -> bool:
    """Same row partitioning AND same homes: derived columns land beside
    their sources and per-group execution sees aligned row ranges."""
    if a is b:
        return True
    if [int(e) for e in a["espc"]] != [int(e) for e in b["espc"]]:
        return False
    ga, gb = a["groups"], b["groups"]
    if len(ga) != len(gb):
        return False
    return all(x["lo"] == y["lo"] and x["hi"] == y["hi"]
               and x["home"] == y["home"] for x, y in zip(ga, gb))


def peek_dist(leaves, env) -> bool:
    """Cheap pre-evaluation probe: does any identifier leaf resolve to an
    unmaterialized DistFrame?  Lets try_fuse ship single-op regions that
    would otherwise fall under MIN_OPS and trigger a gather."""
    if not enabled():
        return False
    for leaf in leaves:
        if not isinstance(leaf, AstId):
            continue
        try:
            v = env.lookup(leaf.name)
            fr = v.value if (v is not None and v.kind == Val.FRAME) \
                else env.session.lookup(leaf.name)
        except Exception:
            continue
        if _is_dist(fr):
            return True
    return False


def _context(base_frame):
    """(cloud, store, router, workers) when a ≥2-worker ring is up."""
    try:
        from h2o3_tpu.cluster import active_cloud
        from h2o3_tpu.cluster import tasks as _tasks
        cloud = active_cloud()
    except Exception:
        return None
    if cloud is None:
        return None
    store = getattr(base_frame, "_store", None)
    if store is None:
        try:
            store = _frames._resolve_store(cloud)
        except Exception:
            return None
    router = getattr(store, "router", None)
    workers = _tasks._healthy_workers(cloud)
    if router is None or not router.active() or len(workers) < 2:
        return None
    return cloud, store, router, workers


# ---------------------------------------------------------------------------
# home-side executor (the rapids_exec ctx-DTask body)


def _rep_inputs(refs, layouts: Dict[int, Dict[str, Any]], g: int,
                base_svals: List[Any], store):
    """Codec-aware device inputs for one group's referenced columns.

    Each referenced column homogenizes to one chunk-codec group rep
    (cluster/frames.group_column_rep) and the rep — not a dense f64
    column — becomes the program input: packed u16 codes (affine/dict),
    f32 storage, or nothing at all (const columns ride a scalar slot).
    Returns ``(decode, run_svals, uploads)`` where ``decode`` maps akeys
    to the specs _make_fn emits arithmetic for, ``run_svals`` extends the
    plan's scalar slots with decode params (offset/scale/const values as
    TRACED runtime args, dict tables as replicated trailing arrays), and
    ``uploads`` lists ``(li, name, akey, host_data, pad_fill)``."""
    reps = {}
    for li, x in refs:
        reps[(int(li), x)] = _frames.group_column_rep(
            store, layouts[int(li)], g, x)
    if reps and all(r[0] == "const" for r in reps.values()):
        # the shard shapes need at least one row-sharded array: demote
        # one all-const rep to its (tiny) dense broadcast
        k0 = next(iter(reps))
        rep0 = reps[k0]
        reps[k0] = ("dense", np.repeat(
            np.asarray(rep0[1], dtype=np.float64), int(rep0[2])))
    decode: Dict[str, Tuple] = {}
    run_svals = list(base_svals)
    uploads: List[Tuple] = []
    for (li, x), rep in reps.items():
        akey = _fusion._akey(li, x)
        kind = rep[0]
        if kind == "const":
            decode[akey] = ("const", len(run_svals))
            run_svals.append(float(rep[1][0]))
        elif kind == "affine":
            decode[akey] = ("affine", len(run_svals), len(run_svals) + 1,
                            int(rep[4]))
            run_svals.extend([float(rep[2]), float(rep[3])])
            uploads.append((li, x, akey, rep[1], int(rep[4])))
        elif kind == "dict":
            decode[akey] = ("dict", len(run_svals))
            run_svals.append(np.ascontiguousarray(rep[2]))
            uploads.append((li, x, akey, rep[1], 0))
        elif kind == "f32":
            decode[akey] = ("f32",)
            uploads.append((li, x, akey, rep[1], np.nan))
        else:
            uploads.append((li, x, akey, rep[1], np.nan))
    return decode, run_svals, uploads


def _partial(reduce_name: str, d: np.ndarray) -> Dict[str, Any]:
    """One column's reducer partial over one group's rows."""
    d = np.asarray(d, dtype=np.float64)
    dd = d[~np.isnan(d)] if _na_rm(reduce_name) else d
    n_valid = int(dd.size)
    with np.errstate(all="ignore"):
        if reduce_name == "mean":
            return {"s": float(np.sum(dd)) if n_valid else 0.0, "n": n_valid}
        v = float(_RFNS[reduce_name](dd)) if n_valid else float("nan")
    return {"v": v, "n": n_valid}


def _merge_partials(reduce_name: str, parts: List[Dict[str, Any]]) -> float:
    """Caller-side merge in canonical group order — the same numpy
    reduction the interpreter applies, over the per-group partials."""
    with np.errstate(all="ignore"):
        if reduce_name == "mean":
            ntot = sum(int(p["n"]) for p in parts)
            if ntot == 0:
                return float("nan")
            s = np.sum(np.array([p["s"] for p in parts if p["n"]],
                                dtype=np.float64))
            return float(s / ntot)
        vals = [p["v"] for p in parts if p["n"]]
        if not vals:
            return float("nan")
        return float(_RFNS[reduce_name](np.array(vals, dtype=np.float64)))


def rapids_exec(payload: Dict[str, Any], cloud, store) -> Dict[str, Any]:
    """Execute one group's slice of a shipped column program ON a chunk
    holder: assemble the group's columns (devcache-warm after the first
    touch), run the memoized jitted program, then either return reducer
    partials or write derived chunks back to the ring and return only
    their layout arithmetic."""
    if store is None:
        raise _rpc.RpcFault("no DKV store installed on this node", code=503)
    g = int(payload["g"])
    layouts: Dict[int, Dict[str, Any]] = {}
    for li, ref in payload["leaves"].items():
        layouts[int(li)] = _frames._layout_for(store, ref[0], ref[1])
    base = layouts[int(payload["base"])]
    grp = base["groups"][g]
    espc = base["espc"]
    lo, hi = int(grp["lo"]), int(grp["hi"])
    n = int(espc[hi]) - int(espc[lo])

    # dense host columns only where a dense copy is genuinely needed:
    # pass-through outputs and filter masks.  Program INPUTS go through
    # the codec rep path below instead — no dense working set for them.
    host_names: Dict[int, List[str]] = {}

    def _need_host(li: int, nm: str) -> None:
        cols = host_names.setdefault(int(li), [])
        if nm not in cols:
            cols.append(nm)

    for out in payload["outputs"]:
        if out[0] == "host":
            _need_host(int(out[1]), out[2])
    _flt = payload.get("filter")
    if _flt is not None:
        _need_host(int(_flt["li"]), _flt["name"])
    host: Dict[int, Dict[str, np.ndarray]] = {}
    for li, nms in host_names.items():
        host[li] = _frames.columns_from_group(store, layouts[li], g, nms)

    dev_host: List[np.ndarray] = []
    dev_exprs = tuple(payload.get("dev_exprs") or ())
    if dev_exprs:
        from h2o3_tpu.cluster import tasks as _tasks

        refs = [tuple(r) for r in payload["refs"]]
        svals = [float(s) for s in payload["svals"]]
        if n > 0:
            mesh = default_mesh()
            decode, run_svals, uploads = _rep_inputs(
                refs, layouts, g, svals, store)
            # the program is memoized per decode signature too: the same
            # region over differently-encoded frames (or the dense
            # H2O3_TPU_CODECS=0 plane) must not share a compiled decode
            dsig = tuple(sorted(
                (ak,) + tuple(s for s in sp) for ak, sp in decode.items()))
            fn = plan_memo(
                "rapids_dist",
                ("fn",) + tuple(payload["key"]) + (dsig,),
                lambda: _fusion._make_fn(dev_exprs,
                                         tuple(decode.items())))
            # one multi-device program at a time in this process — XLA:CPU
            # wedges on concurrent launches from several server threads
            with _tasks._SHARD_EXEC_LOCK:
                with enable_x64():
                    merged: Dict[str, Any] = {}
                    mask = None
                    for li, x, akey, data, fill in uploads:
                        lay = layouts[li]
                        token = (lay["frame_key"], lay["stamp"], int(g),
                                 x, decode.get(akey, ("dense",))[0])

                        def build(d=data, f=fill):
                            return shard_rows(np.asarray(d), mesh,
                                              fill=f)[0]

                        arr = _devcache.cached(
                            "rapids_rep_arr", token, (), mesh, build,
                            frame_key=lay["frame_key"])
                        merged[akey] = arr
                        mask = row_mask(n, int(arr.shape[0]), mesh)
                    table = FrameTable(merged, mask, n, mesh)
                    # _SHARD_EXEC_LOCK exists to serialize shard
                    # execution: XLA:CPU multi-device collectives
                    # deadlock when dispatched from concurrent threads
                    # h2o3: noqa[LOCK001]
                    outs = map_batches(fn, table, *run_svals)
                dev_host = [np.asarray(gather_rows(o, n)).copy()
                            for o in outs]
        else:
            dev_host = [np.empty(0, dtype=np.float64) for _ in dev_exprs]

    fills = payload.get("fills") or ()
    arrs: List[np.ndarray] = []
    for out in payload["outputs"]:
        if out[0] == "host":
            arrs.append(np.asarray(host[int(out[1])][out[2]],
                                   dtype=np.float64))
        elif out[0] == "dev":
            arrs.append(dev_host[int(out[1])])
        else:  # ("fill", j) — scalar := over the group's whole row range
            arrs.append(np.full(n, float(fills[int(out[1])]),
                                dtype=np.float64))

    reduce_name = payload.get("reduce")
    if reduce_name:
        return {"mode": "reduce", "rows": n,
                "cols": [_partial(reduce_name, a) for a in arrs]}

    keep = None
    flt = payload.get("filter")
    if flt is not None:
        mv = host[int(flt["li"])][flt["name"]]
        valid = mv[~np.isnan(mv)]
        if valid.size and not np.all(np.isin(valid, (0.0, 1.0))):
            # not a mask: row-INDEX selection semantics — decline before
            # writing anything so the caller can take the gather path
            return {"mode": "nonbinary"}
        keep = mv == 1.0

    w = payload["write"]
    out_names = payload["out_names"]
    types = w["types"]
    domains = w.get("domains") or {}
    replicas = int(w["replicas"])
    nrows_out: List[int] = []
    nbytes = 0
    off = int(espc[lo])
    for i in range(lo, hi):
        sl = slice(int(espc[i]) - off, int(espc[i + 1]) - off)
        k = keep[sl] if keep is not None else None
        pls: List[Any] = []
        ni = 0
        for nm2, a, t in zip(out_names, arrs, types):
            seg = a[sl]
            if k is not None:
                seg = seg[k]
            ni = int(seg.size)
            if t is ColType.CAT:
                codes = np.full(seg.shape, NA_CAT, dtype=np.int32)
                m = ~np.isnan(seg)
                codes[m] = seg[m].astype(np.int32)
                pls.append((codes, list(domains.get(nm2) or [])))
            else:
                pls.append(np.ascontiguousarray(seg, dtype=np.float64))
        # derived chunks land ENCODED exactly like parsed ones: the wire
        # guard, replica fan-out and layout nbytes all see codec bytes
        value = _codecs.encode_chunk([ni, pls, False])
        ck = _frames.chunk_key(w["anchor"], i)
        nbytes += _frames.guard_chunk_payload(ck, value)
        store.put(ck, value, replicas=replicas)
        nrows_out.append(ni)
    return {"mode": "frame", "nrows": nrows_out, "nbytes": int(nbytes)}


# ---------------------------------------------------------------------------
# caller-side fan-out (the mr_chunk_homed recovery ladder, rapids flavor)


def _run_groups(base_lay: Dict[str, Any], payloads: List[Dict[str, Any]],
                cloud, store, router, workers,
                kind: str) -> List[Dict[str, Any]]:
    """Fan the per-group programs to their CURRENT ring homes and collect
    responses in canonical group order.  Ladder on failure: home →
    replica successors → any survivor → caller-local execution from
    replica chunks (never a gather)."""
    from h2o3_tpu.cluster import tasks as _tasks

    groups = base_lay["groups"]
    timeout = dist_timeout()
    my_name = cloud.info.name
    _tasks._FANOUT.set(len(groups))
    results: List[Optional[Dict[str, Any]]] = [None] * len(groups)
    errors: List[Optional[BaseException]] = [None] * len(groups)

    with telemetry.Span("rapids_dist", groups=len(groups),
                        rows=int(base_lay["espc"][-1]), op=kind):
        ctx = telemetry.current_trace_context()
        fo = _flight.FANOUTS.begin("rapids_exec", len(groups),
                                   rows=int(base_lay["espc"][-1]))
        _flight.record(_flight.FANOUT, "info", "schedule",
                       kind="rapids_exec", groups=len(groups), op=kind)

        def _run(gi: int) -> None:
            try:
                _run_group(gi)
            finally:
                fo.progress()

        def _run_group(gi: int) -> None:
            grp = groups[gi]
            payload = payloads[gi]
            cands = router.home_members(grp["anchor"], MAX_REPLICAS)
            with telemetry.Span(
                    "rapids_group", trace_id=ctx["trace_id"],
                    parent_id=ctx["span_id"], group=gi,
                    anchor=grp["anchor"]):
                # rung 0: the group's CURRENT ring home (chunk-local)
                try:
                    if cands and cands[0].info.name == my_name:
                        results[gi] = rapids_exec(payload, cloud, store)
                        return
                    if cands:
                        results[gi] = _tasks.submit(
                            cloud, cands[0], "rapids_exec", payload,
                            timeout=timeout)
                        return
                except (_rpc.RPCError, _rpc.RpcFault):
                    pass
                # rung 1: ring successors hold replica CHUNKS
                for m in cands[1:]:
                    try:
                        if m.info.name == my_name:
                            out = rapids_exec(payload, cloud, store)
                        else:
                            out = _tasks.submit(cloud, m, "rapids_exec",
                                                payload, timeout=timeout)
                        _tasks._RECOVERED.inc(path="replica")
                        _flight.record(_flight.RECOVERY, "warn",
                                       "rapids_group", path="replica",
                                       group=gi, member=m.info.name)
                        results[gi] = out
                        return
                    except (_rpc.RPCError, _rpc.RpcFault):
                        continue
                # rung 2: any other healthy member (ring-walks the chunks)
                cand_names = {m.info.name for m in cands}
                for m in workers:
                    if (m.info.name in cand_names
                            or m.info.name == my_name or not m.healthy):
                        continue
                    try:
                        out = _tasks.submit(cloud, m, "rapids_exec",
                                            payload, timeout=timeout)
                        _tasks._RECOVERED.inc(path="survivor")
                        _flight.record(_flight.RECOVERY, "warn",
                                       "rapids_group", path="survivor",
                                       group=gi, member=m.info.name)
                        results[gi] = out
                        return
                    except (_rpc.RPCError, _rpc.RpcFault):
                        continue
                # rung 3: the caller itself, from replica chunks via the
                # store's ring walk — still never a gather
                try:
                    results[gi] = rapids_exec(payload, cloud, store)
                    _tasks._RECOVERED.inc(path="local")
                    _flight.record(_flight.RECOVERY, "warn", "rapids_group",
                                   path="local", group=gi)
                except BaseException as e:  # noqa: BLE001 — surfaced below
                    errors[gi] = e

        threads = [threading.Thread(target=_run, args=(gi,), daemon=True)
                   for gi in range(len(groups))]
        try:
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=timeout)
        finally:
            fo.end()

        for gi in range(len(groups)):
            if results[gi] is None and errors[gi] is None:
                results[gi] = rapids_exec(payloads[gi], cloud, store)
                _tasks._RECOVERED.inc(path="local")
                _flight.record(_flight.RECOVERY, "warn", "rapids_group",
                               path="local", group=gi, deadline=True)
        for e in errors:
            if e is not None:
                raise e

        # the fan-out choke point: everything the homes sent back —
        # partials or layout arithmetic, never row data
        nb = sum(len(pickle.dumps(r, protocol=pickle.HIGHEST_PROTOCOL))
                 for r in results)
        _PARTIAL_BYTES.inc(nb)
        _ledger.charge(_ledger.RAPIDS_PARTIAL_BYTES, nb)
        _flight.record(_flight.FANOUT, "info", "partials",
                       kind="rapids_exec", groups=len(groups), bytes=nb)
    return results  # type: ignore[return-value]


def _cleanup_chunks(store, anchors: List[str],
                    groups: List[Dict[str, Any]]) -> None:
    """Best-effort removal of derived chunks after an aborted write."""
    for j, grp in enumerate(groups):
        for i in range(int(grp["lo"]), int(grp["hi"])):
            try:
                store.remove(_frames.chunk_key(anchors[j], i))
            except Exception:
                pass


def _derived_frame(store, router, base_fr, out_names: List[str],
                   out_types: List[ColType], domains: Dict[str, list],
                   new_key: str, anchors: List[str],
                   results: List[Dict[str, Any]],
                   filtered: bool):
    """Assemble the new chunk-homed frame's layout from the per-group
    write receipts and publish layout+setup to the ring."""
    from h2o3_tpu.frame.parse import ParseSetup

    base_lay = base_fr.chunk_layout
    groups_in = base_lay["groups"]
    if filtered:
        espc = [0]
        for gi, grp in enumerate(groups_in):
            for nr in results[gi]["nrows"]:
                espc.append(espc[-1] + int(nr))
    else:
        espc = [int(e) for e in base_lay["espc"]]
    groups = [{"g": gi, "anchor": anchors[gi],
               "lo": int(grp["lo"]), "hi": int(grp["hi"]),
               "home": grp["home"], "home_name": grp["home_name"]}
              for gi, grp in enumerate(groups_in)]
    layout = {
        "frame_key": new_key,
        "espc": espc,
        "replicas": _frames.chunk_replicas(),
        "groups": groups,
        "column_names": list(out_names),
        "column_types": list(out_types),
        "domains": {n: list(domains[n]) for n in domains},
        "nbytes": int(sum(int(r["nbytes"]) for r in results)),
        "stamp": _frames._layout_stamp(espc, anchors),
    }
    setup = ParseSetup(
        separator=",", header=True, column_names=list(out_names),
        column_types=list(out_types), na_strings=(),
        skip_blank_lines=True, quote_char='"')
    store.put(_frames.setup_key(new_key), _frames.setup_payload(setup),
              replicas=MAX_REPLICAS)
    store.put(_frames.layout_key(new_key), layout, replicas=MAX_REPLICAS)
    return _frames.DistFrame(layout, setup, store)


def _new_anchors(router, new_key: str,
                 groups: List[Dict[str, Any]]) -> List[str]:
    """Probe derived-frame anchors CALLER-side so the new layout homes on
    the same members as its source regardless of which ladder rung ends
    up executing each group."""
    return [_frames._probe_anchor(router, new_key, gi, grp["home"])
            for gi, grp in enumerate(groups)]


def _new_frame_key() -> str:
    return f"rapids_{uuid.uuid4().hex[:10]}"


# ---------------------------------------------------------------------------
# entry point 1: fused regions (hooked from fusion.try_fuse)


def try_dist(node, leaves, leaf_vals, env) -> Optional[Val]:
    """Attempt to run a fused region on the chunk homes.  Returns the
    result Val, or None — the caller then proceeds with the local
    (gather-based) execute/replay, which is always correct."""
    if not enabled():
        return None
    if not any(v.kind == Val.FRAME and _is_dist(v.value) for v in leaf_vals):
        return None
    try:
        return _dispatch_region(node, leaves, leaf_vals, env)
    except _NotDistributable:
        _DIST.inc(result="gather")
        return None
    except Exception:
        # a distributed attempt died mid-flight (beneath the ladder):
        # divert to the exact gather path — correctness over locality
        _DIST.inc(result="fallback")
        return None


def _dispatch_region(node, leaves, leaf_vals, env) -> Val:
    base_fr = next(v.value for v in leaf_vals
                   if v.kind == Val.FRAME and _is_dist(v.value))
    ctx = _context(base_fr)
    if ctx is None:
        raise _NotDistributable
    cloud, store, router, workers = ctx
    base_lay = base_fr.chunk_layout
    frame_leaves: Dict[int, Any] = {}
    for i, v in enumerate(leaf_vals):
        if v.kind == Val.FRAME:
            if not _is_dist(v.value) or \
                    not _aligned(base_lay, v.value.chunk_layout):
                raise _NotDistributable
            frame_leaves[i] = v.value
        elif v.kind != Val.NUM:
            raise _NotDistributable

    schemas = tuple(_fusion._leaf_schema(v) for v in leaf_vals)
    key = (canonical_sexpr(node), schemas)
    leaf_idx_by_id = {id(leaf): i for i, leaf in enumerate(leaves)}

    def build():
        try:
            return _fusion._build_plan(node, leaf_idx_by_id, schemas)
        except _fusion._Unfusible:
            return _fusion._UNFUSIBLE_PLAN

    plan = plan_memo("rapids_fusion", key, build)
    if plan == _fusion._UNFUSIBLE_PLAN:
        raise _NotDistributable
    if plan.static is not None:
        _DIST.inc(result="dist")
        return Val.num(plan.static)
    if plan.reduce_name is not None and \
            plan.reduce_name not in _DIST_REDUCERS:
        raise _NotDistributable
    if len(set(plan.out_names)) != len(plan.out_names):
        raise _NotDistributable  # derived layouts need unique column names

    def leaf_col_type(li: int, name: str) -> ColType:
        lay = frame_leaves[li].chunk_layout
        return lay["column_types"][lay["column_names"].index(name)]

    names: Dict[int, List[str]] = {}

    def need(li: int, nm: str) -> None:
        cols = names.setdefault(li, [])
        if nm not in cols:
            cols.append(nm)

    out_types: List[ColType] = []
    domains: Dict[str, list] = {}
    for nm, out in zip(plan.out_names, plan.outputs):
        if out[0] == "host":
            li, src = int(out[1]), out[2]
            t = leaf_col_type(li, src)
            if t in (ColType.STR, ColType.UUID):
                raise _NotDistributable
            out_types.append(t)
            if t is ColType.CAT:
                lay = frame_leaves[li].chunk_layout
                domains[nm] = list(lay["domains"].get(src) or [])
            need(li, src)
        else:
            out_types.append(ColType.NUM)
    for li, nm in plan.refs:
        need(int(li), nm)

    svals = [float(leaf_vals[li].as_num()) for li in plan.sval_leaves]
    svals += list(plan.lit_vals)
    base_li = min(frame_leaves)
    common = {
        "base": base_li,
        "leaves": {li: (fr.chunk_layout["frame_key"],
                        fr.chunk_layout["stamp"])
                   for li, fr in frame_leaves.items()},
        "names": names,
        "key": key,
        "dev_exprs": plan.dev_exprs,
        "refs": plan.refs,
        "svals": svals,
        "outputs": plan.outputs,
        "out_names": plan.out_names,
        "fills": (),
        "reduce": plan.reduce_name,
    }

    if plan.reduce_name is not None:
        payloads = [dict(common, g=gi, write=None)
                    for gi in range(len(base_lay["groups"]))]
        results = _run_groups(base_lay, payloads, cloud, store, router,
                              workers, kind="reduce")
        per_col = list(zip(*[r["cols"] for r in results]))
        vals = [_merge_partials(plan.reduce_name, list(parts))
                for parts in per_col]
        _DIST.inc(result="dist")
        return Val.num(vals[0]) if len(vals) == 1 else Val.nums(vals)

    new_key = _new_frame_key()
    anchors = _new_anchors(router, new_key, base_lay["groups"])
    payloads = [dict(common, g=gi,
                     write={"anchor": anchors[gi],
                            "replicas": _frames.chunk_replicas(),
                            "types": list(out_types),
                            "domains": domains})
                for gi in range(len(base_lay["groups"]))]
    results = _run_groups(base_lay, payloads, cloud, store, router,
                          workers, kind="frame")
    out = _derived_frame(store, router, base_fr, list(plan.out_names),
                         out_types, domains, new_key, anchors, results,
                         filtered=False)
    _DIST.inc(result="dist")
    return Val.frame(out)


# ---------------------------------------------------------------------------
# entry point 2: whole-frame := assignment (hooked from prims/assign.py)


def try_assign_dist(env, args) -> Optional[Val]:
    """``(:= dst src cols _)`` over a DistFrame: write the assigned
    columns home-side (scalar fill or an aligned dist source column) and
    pass the rest through as chunk references — no row data moves.
    Returns None for any shape outside that contract (row-subset
    assigns, string sources, misaligned layouts): the interpreter's
    gather-based path then runs, bit-identical as ever."""
    if not enabled():
        return None
    dstv = args[0]
    if not (dstv.is_frame() and _is_dist(dstv.value)):
        return None
    try:
        out = _assign_dist(env, args)
    except _NotDistributable:
        _DIST.inc(result="gather")
        return None
    except Exception:
        _DIST.inc(result="fallback")
        return None
    if out is None:
        _DIST.inc(result="gather")
        return None
    _DIST.inc(result="dist")
    return Val.frame(out)


def _assign_dist(env, args):
    from h2o3_tpu.rapids.prims.util import col_indices

    dst = args[0].value
    srcv, cselv, rselv = args[1], args[2], args[3]
    if not (rselv.is_num() and np.isnan(rselv.as_num())):
        raise _NotDistributable  # row-subset assign: interpreter path
    ctx = _context(dst)
    if ctx is None:
        raise _NotDistributable
    cloud, store, router, workers = ctx
    lay = dst.chunk_layout
    dst_names = list(lay["column_names"])
    dst_types = list(lay["column_types"])
    cidx = col_indices(dst, cselv)
    if len(set(cidx)) != len(cidx):
        raise _NotDistributable

    scalar = None
    src = None
    src_names: List[str] = []
    if srcv.is_frame():
        src = srcv.value
        if not (_is_dist(src) and _aligned(lay, src.chunk_layout)):
            raise _NotDistributable
        slay = src.chunk_layout
        src_names = list(slay["column_names"])
        stypes = list(slay["column_types"])
        for k in range(len(cidx)):
            j = k if len(src_names) > 1 else 0
            if j >= len(src_names) or \
                    stypes[j] not in (ColType.NUM, ColType.TIME):
                raise _NotDistributable
    elif srcv.kind == Val.NUM:
        scalar = float(srcv.as_num())
    else:
        raise _NotDistributable

    cset = {int(j): k for k, j in enumerate(cidx)}
    outputs: List[Tuple] = []
    out_types: List[ColType] = []
    fills: List[float] = []
    domains: Dict[str, list] = {}
    names: Dict[int, List[str]] = {}

    def need(li: int, nm: str) -> None:
        cols = names.setdefault(li, [])
        if nm not in cols:
            cols.append(nm)

    for j, nm in enumerate(dst_names):
        if j in cset:
            if dst_types[j] not in (ColType.NUM, ColType.TIME):
                raise _NotDistributable  # CAT/STR dst: interpreter path
            if scalar is not None:
                outputs.append(("fill", len(fills)))
                fills.append(scalar)
            else:
                sn = src_names[cset[j] if len(src_names) > 1 else 0]
                outputs.append(("host", 1, sn))
                need(1, sn)
            out_types.append(ColType.NUM)
        else:
            t = dst_types[j]
            if t in (ColType.STR, ColType.UUID):
                raise _NotDistributable
            outputs.append(("host", 0, nm))
            out_types.append(t)
            if t is ColType.CAT:
                domains[nm] = list(lay["domains"].get(nm) or [])
            need(0, nm)

    leaves = {0: (lay["frame_key"], lay["stamp"])}
    if src is not None:
        leaves[1] = (src.chunk_layout["frame_key"],
                     src.chunk_layout["stamp"])
    new_key = _new_frame_key()
    anchors = _new_anchors(router, new_key, lay["groups"])
    payloads = [
        {"base": 0, "g": gi, "leaves": leaves, "names": names,
         "key": None, "dev_exprs": (), "refs": (), "svals": (),
         "outputs": tuple(outputs), "out_names": tuple(dst_names),
         "fills": tuple(fills), "reduce": None,
         "write": {"anchor": anchors[gi],
                   "replicas": _frames.chunk_replicas(),
                   "types": list(out_types), "domains": domains}}
        for gi in range(len(lay["groups"]))]
    results = _run_groups(lay, payloads, cloud, store, router, workers,
                          kind="assign")
    return _derived_frame(store, router, dst, dst_names, out_types,
                          domains, new_key, anchors, results,
                          filtered=False)


# ---------------------------------------------------------------------------
# entry point 3: mask filters (hooked from prims/mungers.py rows)


def try_rows_dist(env, args) -> Optional[Val]:
    """``(rows fr sel)`` with an aligned one-column dist selector: each
    home validates its slice of the mask and writes the surviving rows of
    its own chunks as the new frame's chunks — ESPC recomputed from the
    per-chunk survivor counts, zero row movement.  A selector that turns
    out not to be 0/1 (row-index semantics) aborts cleanly, removes any
    chunks already written, and returns None for the exact gather path."""
    if not enabled():
        return None
    frv, selv = args[0], args[1]
    if not (frv.is_frame() and _is_dist(frv.value)):
        return None
    try:
        out = _filter_dist(env, frv.value, selv)
    except _NotDistributable:
        _DIST.inc(result="gather")
        return None
    except Exception:
        _DIST.inc(result="fallback")
        return None
    if out is None:
        _DIST.inc(result="gather")
        return None
    _DIST.inc(result="dist")
    return Val.frame(out)


def _filter_dist(env, fr, selv):
    if not selv.is_frame():
        raise _NotDistributable  # numeric row indices: interpreter path
    sel = selv.value
    if not (_is_dist(sel) and _aligned(fr.chunk_layout, sel.chunk_layout)):
        raise _NotDistributable
    slay = sel.chunk_layout
    if len(slay["column_names"]) != 1:
        raise _NotDistributable
    ctx = _context(fr)
    if ctx is None:
        raise _NotDistributable
    cloud, store, router, workers = ctx
    lay = fr.chunk_layout
    out_names = list(lay["column_names"])
    out_types = list(lay["column_types"])
    if any(t in (ColType.STR, ColType.UUID) for t in out_types):
        raise _NotDistributable
    domains = {n: list(lay["domains"].get(n) or [])
               for n, t in zip(out_names, out_types) if t is ColType.CAT}
    sel_name = slay["column_names"][0]
    if slay["column_types"][0] in (ColType.STR, ColType.UUID):
        raise _NotDistributable
    names: Dict[int, List[str]] = {0: list(out_names), 1: [sel_name]}
    outputs = tuple(("host", 0, nm) for nm in out_names)
    leaves = {0: (lay["frame_key"], lay["stamp"]),
              1: (slay["frame_key"], slay["stamp"])}
    new_key = _new_frame_key()
    anchors = _new_anchors(router, new_key, lay["groups"])
    payloads = [
        {"base": 0, "g": gi, "leaves": leaves, "names": names,
         "key": None, "dev_exprs": (), "refs": (), "svals": (),
         "outputs": outputs, "out_names": tuple(out_names),
         "fills": (), "reduce": None,
         "filter": {"li": 1, "name": sel_name},
         "write": {"anchor": anchors[gi],
                   "replicas": _frames.chunk_replicas(),
                   "types": list(out_types), "domains": domains}}
        for gi in range(len(lay["groups"]))]
    results = _run_groups(lay, payloads, cloud, store, router, workers,
                          kind="filter")
    if any(r.get("mode") == "nonbinary" for r in results):
        # the selector is an index list, not a mask: undo partial writes
        # and let the interpreter's exact row_indices path decide
        _cleanup_chunks(store, anchors, lay["groups"])
        return None
    return _derived_frame(store, router, fr, out_names, out_types,
                          domains, new_key, anchors, results,
                          filtered=True)
