"""Distributed sort and merge (join).

Reference: ``water/rapids/RadixOrder.java`` + ``BinaryMerge.java`` +
``Merge.java`` — MSB radix partition, per-MSB single-threaded order, batched
binary merge of sorted key ranges; powers the ``sort`` and ``merge`` prims.

TPU-native: the MSB-partition/merge machinery existed to move key ranges
between JVMs; with host-canonical dense columns a single vectorized
``np.lexsort`` (radix-family, stable) is the same algorithm without the
shuffle.  Joins: factorize both sides' key tuples into one int64 code space,
sort the right side once, then ``searchsorted`` + run-length expansion —
a sort-merge join, exactly the reference's strategy.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from h2o3_tpu.frame.frame import Column, ColType, Frame, _merge_domains

#: below this the ctypes/key-transform overhead beats numpy's introsort
_RADIX_MIN_N = 4096


def stable_argsort(keys: np.ndarray) -> np.ndarray:
    """Stable argsort, using the native LSD radix sort (native/codecs.cpp —
    the RadixOrder.java analogue) for large int64/uint64/float64 arrays,
    numpy otherwise. Parity pinned by tests/test_native.py."""
    keys = np.asarray(keys)
    if len(keys) >= _RADIX_MIN_N and keys.dtype in (
        np.dtype(np.int64), np.dtype(np.uint64), np.dtype(np.float64)
    ):
        try:
            from h2o3_tpu import native

            order = native.radix_argsort(keys)
            if order is not None:
                return order
        except Exception:
            pass
    return np.argsort(keys, kind="stable")


def lexsort(keys: Sequence[np.ndarray]) -> np.ndarray:
    """np.lexsort-compatible multi-key stable sort (last key primary),
    as successive stable radix passes — LSD over whole keys, exactly the
    composition RadixOrder.java applies byte-wise."""
    keys = [np.asarray(k) for k in keys]
    order = stable_argsort(keys[0])
    for k in keys[1:]:
        order = order[stable_argsort(k[order])]
    return order


def sort_frame(fr: Frame, by: Sequence[int], ascending: Optional[Sequence[bool]] = None) -> Frame:
    """(sort fr [cols] [asc]) — stable multi-key sort; NAs sort first
    (reference Merge.sort: NA = -Inf in radix order).

    Large frames sort on the device mesh (sample sort over all chips,
    ``rapids/dist.py`` — the RadixOrder.java:20 cluster partition,
    TPU-native); the host radix path below is the small-N fast path and
    the parity oracle."""
    if ascending is None:
        ascending = [True] * len(by)
    keys = []
    for j, asc in zip(reversed(list(by)), reversed(list(ascending))):
        c = fr.col(j)
        if c.type in (ColType.STR, ColType.UUID):
            svals = np.asarray([("" if v is None else str(v)) for v in c.data])
            _, codes = np.unique(svals, return_inverse=True)
            k = codes.astype(np.float64)
        else:
            k = c.numeric_view().copy()
            k[np.isnan(k)] = -np.inf  # NAs first
        keys.append(k if asc else -k)
    order = _order_of(keys, fr.nrows)
    return fr.rows(order)


def _order_of(keys: Sequence[np.ndarray], nrows: int) -> np.ndarray:
    """lexsort, on the device mesh above the size threshold."""
    from h2o3_tpu.rapids import dist

    if nrows >= dist.DIST_SORT_MIN:
        try:
            return dist.device_lexsort(
                [dist.encode_f64(np.asarray(k, np.float64)) for k in keys])
        except Exception:  # no mesh / backend trouble: host path still works
            pass
    return lexsort(keys)


def _encode_keys(
    left: Frame, right: Frame, by_left: Sequence[int], by_right: Sequence[int]
) -> Tuple[np.ndarray, np.ndarray]:
    """Factorize each key-column pair over the union of both sides, then mix
    the per-column codes into one int64 key per row."""
    lcodes, rcodes = np.zeros(left.nrows, dtype=np.int64), np.zeros(right.nrows, dtype=np.int64)
    for jl, jr in zip(by_left, by_right):
        cl, cr = left.col(jl), right.col(jr)
        if cl.type is ColType.CAT and cr.type is ColType.CAT:
            # align domains so equal levels get equal codes
            dom, rmap = _merge_domains(cl.domain, cr.domain)
            lv = cl.data.astype(np.int64)
            rv = np.where(cr.data >= 0, rmap[np.clip(cr.data, 0, None)], -1).astype(np.int64)
            card = len(dom) + 1
        else:
            lvals, rvals = cl.numeric_view(), cr.numeric_view()
            both = np.concatenate([lvals, rvals])
            finite = both[~np.isnan(both)]
            uniq = np.unique(finite)
            lv = np.where(np.isnan(lvals), -1, np.searchsorted(uniq, np.nan_to_num(lvals))).astype(np.int64)
            rv = np.where(np.isnan(rvals), -1, np.searchsorted(uniq, np.nan_to_num(rvals))).astype(np.int64)
            card = len(uniq) + 1
        # overflow guard: the mixed-radix encoding must stay within int64 or
        # unrelated key tuples would silently collide
        max_prior = max(int(lcodes.max(initial=0)), int(rcodes.max(initial=0)))
        if max_prior > (2**62) // card:
            raise ValueError(
                "merge: combined key cardinality exceeds int64 encoding range; "
                "reduce the number/cardinality of join columns"
            )
        lcodes = lcodes * card + (lv + 1)
        rcodes = rcodes * card + (rv + 1)
    return lcodes, rcodes


def merge_frames(
    left: Frame,
    right: Frame,
    by_left: Sequence[int],
    by_right: Sequence[int],
    all_left: bool = False,
    all_right: bool = False,
) -> Frame:
    """Sort-merge join (rapids ``merge``; Merge.java semantics):
    inner by default; all_left/all_right add unmatched rows with NAs.
    Output columns: join keys (left naming), then left non-key, right non-key."""
    lk, rk = _encode_keys(left, right, by_left, by_right)
    from h2o3_tpu.rapids import dist

    def _host_probe():
        order = stable_argsort(rk)
        srt = rk[order]
        return (order, srt, np.searchsorted(srt, lk, side="left"),
                np.searchsorted(srt, lk, side="right"))

    if max(left.nrows, right.nrows) >= dist.DIST_SORT_MIN:
        # device mesh: distributed sort of the build side + sharded
        # binary-search probe (RadixOrder + BinaryMerge, TPU-native);
        # the codes are non-negative int64 so the uint64 cast is
        # order-preserving
        try:
            r_order = dist.device_argsort_u64(rk.astype(np.uint64))
            rk_sorted = rk[r_order]
            lo, hi = dist.device_searchsorted_both(
                rk_sorted.astype(np.uint64), lk.astype(np.uint64))
        except Exception:
            r_order, rk_sorted, lo, hi = _host_probe()
    else:
        r_order, rk_sorted, lo, hi = _host_probe()
    counts = hi - lo
    matched = counts > 0

    # inner part: expand each left row by its match count
    l_idx = np.repeat(np.arange(left.nrows), np.where(matched, counts, 0))
    offs = np.concatenate([[0], np.cumsum(np.where(matched, counts, 0))])[:-1]
    within = np.arange(len(l_idx)) - np.repeat(offs, np.where(matched, counts, 0))
    r_idx = r_order[np.repeat(lo, np.where(matched, counts, 0)) + within]

    if all_left:
        un_l = np.nonzero(~matched)[0]
        l_idx = np.concatenate([l_idx, un_l])
        r_idx = np.concatenate([r_idx, np.full(len(un_l), -1, dtype=np.int64)])
    if all_right:
        r_matched = np.zeros(right.nrows, dtype=bool)
        r_matched[np.unique(r_idx[r_idx >= 0])] = True
        un_r = np.nonzero(~r_matched)[0]
        l_idx = np.concatenate([l_idx, np.full(len(un_r), -1, dtype=np.int64)])
        r_idx = np.concatenate([r_idx, un_r])

    def take(col: Column, idx: np.ndarray) -> Column:
        miss = idx < 0
        safe = np.clip(idx, 0, None)
        if col.type is ColType.CAT:
            data = np.where(miss, -1, col.data[safe]).astype(np.int32)
            return Column(col.name, data, ColType.CAT, col.domain)
        if col.type in (ColType.STR, ColType.UUID):
            data = col.data[safe].copy()
            data[miss] = None
            return Column(col.name, data, col.type)
        data = np.where(miss, np.nan, col.data[safe])
        return Column(col.name, data, col.type)

    out_cols: List[Column] = []
    taken = set()
    for pos, (jl, jr) in enumerate(zip(by_left, by_right)):
        # key column: prefer left values, fill from right for all_right rows
        lc, rc = take(left.col(jl), l_idx), take(right.col(jr), r_idx)
        if left.col(jl).type is ColType.CAT and right.col(jr).type is ColType.CAT:
            dom, rmap = _merge_domains(left.col(jl).domain, right.col(jr).domain)
            lcd = lc.data
            rcd = np.where(rc.data >= 0, rmap[np.clip(rc.data, 0, None)], -1).astype(np.int32)
            data = np.where(l_idx >= 0, lcd, rcd).astype(np.int32)
            out_cols.append(Column(lc.name, data, ColType.CAT, dom))
        elif lc.type in (ColType.STR, ColType.UUID):
            data = np.where(l_idx >= 0, lc.data, rc.data)
            out_cols.append(Column(lc.name, data.astype(object), lc.type))
        else:
            data = np.where(l_idx >= 0, lc.data, rc.data)
            out_cols.append(Column(lc.name, data, lc.type))
        taken.add(lc.name)
    for j, c in enumerate(left.columns):
        if j in list(by_left):
            continue
        cc = take(c, l_idx)
        out_cols.append(cc)
        taken.add(cc.name)
    for j, c in enumerate(right.columns):
        if j in list(by_right):
            continue
        cc = take(c, r_idx)
        name, k = cc.name, 0
        while name in taken:
            name = f"{cc.name}_{k}"
            k += 1
        cc.name = name
        taken.add(name)
        out_cols.append(cc)
    return Frame(out_cols)
