"""Explanation plots — ``h2o-py/h2o/explanation/_explain.py`` analogue.

Matplotlib renderings over the same REST surfaces the plain client uses:
variable importance (``GET /3/Models/{id}/varimp``) and partial
dependence (``POST /3/PartialDependence``-style makePDP handler). Each
function returns the matplotlib Figure so callers can save or show it.
"""

from __future__ import annotations

from typing import Any, List, Optional


def _model_id(model) -> str:
    import urllib.parse

    return urllib.parse.quote(getattr(model, "model_id", model), safe="")


def _varimp_rows(model) -> list:
    """One GET of /3/Models/{id}/varimp, normalized to row dicts."""
    import h2o3_tpu.client as h2o

    out = h2o.connection().request(
        f"GET /3/Models/{_model_id(model)}/varimp")
    rows = out.get("varimp", out.get("variable_importances", []))
    if isinstance(rows, dict):
        rows = [
            {"variable": v, "scaled_importance": s}
            for v, s in zip(rows.get("variable", []),
                            rows.get("scaled_importance", []))
        ]
    return rows


def varimp_plot(model, num_of_features: int = 10):
    """Horizontal bar chart of scaled variable importances
    (h2o-py varimp_plot)."""
    import matplotlib.pyplot as plt  # auto-selects Agg when headless

    rows = _varimp_rows(model)[:num_of_features]
    names = [r["variable"] for r in rows][::-1]
    vals = [float(r.get("scaled_importance", r.get("relative_importance", 0)))
            for r in rows][::-1]
    fig, ax = plt.subplots(figsize=(8, max(2, 0.4 * len(names))))
    ax.barh(names, vals)
    ax.set_xlabel("scaled importance")
    ax.set_title(f"Variable importance: {_model_id(model)}")
    fig.tight_layout()
    return fig


def permutation_importance_plot(model, frame, metric: str = "AUTO",
                                n_samples: int = 10_000, n_repeats: int = 1,
                                features=None, seed: int = -1,
                                num_of_features: int = 10):
    """Bar chart of permutation variable importance
    (h2o-py permutation_importance_plot; AstPermutationVarImp)."""
    import matplotlib.pyplot as plt

    pvi = model.permutation_importance(
        frame, metric=metric, n_samples=n_samples, n_repeats=n_repeats,
        features=features, seed=seed)
    data = pvi.get_frame_data()
    names = list(data["Variable"])[:num_of_features][::-1]
    col = "Scaled Importance" if "Scaled Importance" in data else "Run 1"
    vals = [float(v) for v in data[col][:num_of_features]][::-1]
    fig, ax = plt.subplots(figsize=(8, max(2, 0.4 * len(names))))
    ax.barh(names, vals)
    ax.set_xlabel(f"permutation importance ({col.lower()})")
    ax.set_title(f"Permutation variable importance: {_model_id(model)}")
    fig.tight_layout()
    return fig


def pd_plot(model, frame, column: str, nbins: int = 20):
    """Partial-dependence curve for one column (h2o-py pd_plot)."""
    import matplotlib.pyplot as plt

    import h2o3_tpu.client as h2o

    out = h2o.connection().request(
        "POST /3/PartialDependence", {
            "model_id": _model_id(model),
            "frame_id": frame.frame_id,
            "cols": column,
            "nbins": nbins,
        })
    pd = out["partial_dependence_data"][0]
    xs = pd["values"]
    ys = [float(v) for v in pd["mean_response"]]
    fig, ax = plt.subplots(figsize=(8, 5))
    try:  # the server formats numeric sweep points as strings
        xnum = [float(x) for x in xs]
    except (TypeError, ValueError):
        xnum = None
    if xnum is not None:
        ax.plot(xnum, ys, marker="o")
    else:
        ax.bar([str(x) for x in xs], ys)
        ax.tick_params(axis="x", rotation=45)
    ax.set_xlabel(column)
    ax.set_ylabel("mean response")
    ax.set_title(f"Partial dependence: {column} ({_model_id(model)})")
    fig.tight_layout()
    return fig


def explain(model, frame, columns: Optional[List[str]] = None) -> List[Any]:
    """h2o.explain-style convenience: varimp plot + a PD plot per (top)
    column. Returns the list of Figures."""
    if columns is None:
        columns = [r["variable"] for r in _varimp_rows(model)[:3]]
    figs = [varimp_plot(model)]
    try:
        figs.append(permutation_importance_plot(model, frame))
    except Exception:
        pass  # e.g. unsupervised model with no scoreable metric
    for c in columns:
        figs.append(pd_plot(model, frame, c))
    return figs
