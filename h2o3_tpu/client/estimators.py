"""Client-side estimator wrappers.

Reference: ``h2o-py/h2o/estimators/`` (22.6k LoC, 21 estimator classes
code-generated from the server's parameter schemas by
``h2o-bindings/bin/gen_python.py:140``).  Here the estimators are one
parametric base + thin per-algo subclasses generated from the same server
registry, keeping the h2o-py surface: ``est.train(x, y, training_frame)``,
``est.predict(frame)``, ``est.model_performance()``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from h2o3_tpu.client.connection import H2OConnection, H2OResponseError
from h2o3_tpu.client.frame import H2OFrame


class H2OModel:
    """Client handle to a server-side model (h2o-py ModelBase)."""

    def __init__(self, conn: H2OConnection, model_id: str) -> None:
        self._conn = conn
        self.model_id = model_id
        self._schema: Optional[Dict[str, Any]] = None

    def _fetch(self) -> Dict[str, Any]:
        if self._schema is None:
            self._schema = self._conn.request(f"GET /3/Models/{self.model_id}")[
                "models"
            ][0]
        return self._schema

    @property
    def algo(self) -> str:
        return self._fetch()["algo"]

    @property
    def params(self) -> Dict[str, Any]:
        return self._fetch()["parameters"]

    def _metrics(self, which: str) -> Optional[Dict[str, Any]]:
        return self._fetch()["output"].get(which)

    def auc(self, valid: bool = False, xval: bool = False) -> Optional[float]:
        which = (
            "cross_validation_metrics" if xval
            else "validation_metrics" if valid else "training_metrics"
        )
        mm = self._metrics(which)
        return mm.get("auc") if mm else None

    def rmse(self, valid: bool = False) -> Optional[float]:
        mm = self._metrics("validation_metrics" if valid else "training_metrics")
        return mm.get("rmse") if mm else None

    def logloss(self, valid: bool = False) -> Optional[float]:
        mm = self._metrics("validation_metrics" if valid else "training_metrics")
        return mm.get("logloss") if mm else None

    def coef(self) -> Optional[Dict[str, float]]:
        return self._fetch()["output"].get("coefficients")

    def varimp(self) -> Optional[Dict[str, float]]:
        return self._fetch()["output"].get("variable_importances")

    def predict(self, frame: H2OFrame) -> H2OFrame:
        frame.refresh()
        out = self._conn.request(
            f"POST /3/Predictions/models/{self.model_id}/frames/{frame.frame_id}"
        )
        key = out["model_metrics"][0]["predictions_frame"]["name"]
        return H2OFrame.from_key(self._conn, key)

    def model_performance(self, frame: H2OFrame) -> Dict[str, Any]:
        frame.refresh()
        out = self._conn.request(
            f"POST /3/Predictions/models/{self.model_id}/frames/{frame.frame_id}"
        )
        return out["model_metrics"][0]

    def permutation_importance(self, frame: H2OFrame, metric: str = "AUTO",
                               n_samples: int = 10_000, n_repeats: int = 1,
                               features=None, seed: int = -1) -> H2OFrame:
        """Permutation variable importance over ``frame``
        (h2o-py ModelBase.permutation_importance — emits the
        ``PermutationVarImp`` rapids op, AstPermutationVarImp)."""
        from h2o3_tpu.client.expr import ExprNode

        frame.refresh()  # materialize once; nrows below reuses the key
        if n_samples == -1 or n_samples > frame.nrows:
            n_samples = -1
        ex = ExprNode("PermutationVarImp", ExprNode.raw(self.model_id),
                      frame, metric, n_samples, n_repeats,
                      features, seed)
        return H2OFrame(self._conn, ex)

    def reset_threshold(self, threshold: float) -> float:
        """Set the classification threshold used by predict; returns the
        old one (h2o-py reset_model_threshold —
        the ``model.reset.threshold`` rapids op)."""
        from h2o3_tpu.client.expr import ExprNode

        ex = ExprNode("model.reset.threshold",
                      ExprNode.raw(self.model_id), threshold)
        fr = H2OFrame(self._conn, ex)
        return float(fr._scalar(ExprNode("flatten", fr)))

    def download_mojo(self, path: str, format: str = "native") -> str:
        """format='reference' emits the actual H2O-3 MOJO zip layout."""
        import os
        import urllib.parse

        raw = self._conn.request(
            f"GET /3/Models/{urllib.parse.quote(self.model_id, safe='')}"
            f"/mojo?format={urllib.parse.quote(format, safe='')}",
            raw=True)
        if os.path.isdir(path):  # h2o-py accepts a target directory
            path = os.path.join(path, f"{self.model_id}.mojo")
        with open(path, "wb") as f:
            f.write(raw)
        return path

    def __repr__(self) -> str:
        return f"<H2OModel {self.model_id}>"


class H2OEstimator:
    """Base estimator (h2o-py estimator_base.H2OEstimator).

    Every builder parameter is exposed: the accepted kwargs are exactly the
    server-side Parameters dataclass fields (the h2o-py estimators are
    code-generated from the same schemas, h2o-bindings/bin/gen_python.py:140)
    — an unknown kwarg raises immediately instead of being silently dropped
    at train time."""

    algo: str = "?"
    _param_cache: Optional[frozenset] = None

    @classmethod
    def param_names(cls) -> frozenset:
        """The server-side Parameters dataclass field names for this algo."""
        if cls._param_cache is None:
            import dataclasses

            from h2o3_tpu.api.registry import algo_map

            _, pcls = algo_map()[cls.algo]
            cls._param_cache = frozenset(
                f.name for f in dataclasses.fields(pcls)
            )
        return cls._param_cache

    def __init__(self, **params: Any) -> None:
        if self.algo != "?":
            unknown = set(params) - self.param_names() - {"model_id"}
            if unknown:
                raise TypeError(
                    f"{type(self).__name__} got unknown parameters "
                    f"{sorted(unknown)}; accepted: {sorted(self.param_names())}"
                )
        self._params = params
        self.model: Optional[H2OModel] = None

    def train(
        self,
        x: Optional[List[str]] = None,
        y: Optional[str] = None,
        training_frame: Optional[H2OFrame] = None,
        validation_frame: Optional[H2OFrame] = None,
    ) -> H2OModel:
        if training_frame is None:
            if self.algo == "generic":  # artifact import needs no frame
                from h2o3_tpu.client import connection

                conn = connection()
                out = conn.request(
                    f"POST /3/ModelBuilders/{self.algo}", dict(self._params)
                )
                self.model = H2OModel(conn, out["model_id"]["name"])
                return self.model
            raise ValueError("training_frame required")
        training_frame.refresh()
        payload: Dict[str, Any] = dict(self._params)
        payload["training_frame"] = training_frame.frame_id
        if validation_frame is not None:
            validation_frame.refresh()
            payload["validation_frame"] = validation_frame.frame_id
        if y is not None:
            payload["response_column"] = y
        if x is not None:
            ignored = [
                c for c in training_frame.names if c not in x and c != y
            ]
            payload["ignored_columns"] = ignored
        conn = training_frame._conn
        out = conn.request(f"POST /3/ModelBuilders/{self.algo}", payload)
        self.model = H2OModel(conn, out["model_id"]["name"])
        return self.model

    def predict(self, frame: H2OFrame) -> H2OFrame:
        if self.model is None:
            raise ValueError("train first")
        return self.model.predict(frame)

    def __getattr__(self, name):  # delegate metrics to the trained model
        if name.startswith("_"):
            raise AttributeError(name)
        model = self.__dict__.get("model")
        if model is not None:
            return getattr(model, name)
        raise AttributeError(name)


#: algo name -> generated estimator class (deterministic lookup for
#: adapters; dir()-scanning would pick an arbitrary class on collisions)
_BY_ALGO: dict = {}


def _make(algo: str, cls_name: str):
    cls = type(cls_name, (H2OEstimator,), {"algo": algo})
    cls.__doc__ = f"h2o-py style estimator for the {algo!r} REST algo."
    _BY_ALGO[algo] = cls
    return cls


def for_algo(algo: str):
    """The generated estimator class for a REST algo name (None if absent)."""
    return _BY_ALGO.get(algo)


# the h2o-py estimator surface (h2o-py/h2o/estimators/, SURVEY.md Appendix C)
H2OGradientBoostingEstimator = _make("gbm", "H2OGradientBoostingEstimator")
H2ORandomForestEstimator = _make("drf", "H2ORandomForestEstimator")
H2OXGBoostEstimator = _make("xgboost", "H2OXGBoostEstimator")
H2OGeneralizedLinearEstimator = _make("glm", "H2OGeneralizedLinearEstimator")
H2OGeneralizedAdditiveEstimator = _make("gam", "H2OGeneralizedAdditiveEstimator")
H2ODeepLearningEstimator = _make("deeplearning", "H2ODeepLearningEstimator")
H2OKMeansEstimator = _make("kmeans", "H2OKMeansEstimator")
H2ONaiveBayesEstimator = _make("naivebayes", "H2ONaiveBayesEstimator")
H2OPrincipalComponentAnalysisEstimator = _make("pca", "H2OPrincipalComponentAnalysisEstimator")
H2OSingularValueDecompositionEstimator = _make("svd", "H2OSingularValueDecompositionEstimator")
H2OIsolationForestEstimator = _make("isolationforest", "H2OIsolationForestEstimator")
H2OExtendedIsolationForestEstimator = _make(
    "extendedisolationforest", "H2OExtendedIsolationForestEstimator"
)
H2OCoxProportionalHazardsEstimator = _make("coxph", "H2OCoxProportionalHazardsEstimator")
H2OGeneralizedLowRankEstimator = _make("glrm", "H2OGeneralizedLowRankEstimator")
H2OPSVMEstimator = _make("psvm", "H2OPSVMEstimator")
H2ORuleFitEstimator = _make("rulefit", "H2ORuleFitEstimator")
H2OStackedEnsembleEstimator = _make("stackedensemble", "H2OStackedEnsembleEstimator")
H2OWord2vecEstimator = _make("word2vec", "H2OWord2vecEstimator")
H2OAggregatorEstimator = _make("aggregator", "H2OAggregatorEstimator")
H2OTargetEncoderEstimator = _make("targetencoder", "H2OTargetEncoderEstimator")
H2OGenericEstimator = _make("generic", "H2OGenericEstimator")
