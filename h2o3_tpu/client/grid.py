"""Grid-search client — ``h2o-py/h2o/grid/grid_search.py`` analogue.

H2OGridSearch wraps ``POST /99/Grid/{algo}``: base params + a hyper-param
dict; the server walks the space (cartesian or random with stopping
criteria) and returns the grid id + per-combo model ids. ``get_grid``
re-sorts server-side like ``GET /99/Grids/{grid_id}?sort_by=``.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional


class H2OGridSearch:
    def __init__(self, model: Any, hyper_params: Dict[str, List[Any]],
                 grid_id: Optional[str] = None,
                 search_criteria: Optional[Dict[str, Any]] = None,
                 **base_params: Any) -> None:
        # `model` accepts an estimator CLASS, an estimator INSTANCE
        # (its params become base params), or a bare algo name string
        if isinstance(model, str):
            algo = model
        else:
            algo = getattr(model, "algo", None)
            if algo in (None, "?"):
                raise ValueError(f"cannot derive algo from {model!r}")
            inst_params = getattr(model, "_params", None)
            if isinstance(inst_params, dict):
                base_params = {**inst_params, **base_params}
        self.algo = algo
        self.hyper_params = dict(hyper_params)
        self.search_criteria = dict(search_criteria or {})
        self.base_params = dict(base_params)
        self.grid_id = grid_id
        self._summary: Optional[Dict[str, Any]] = None

    def train(self, x: Optional[List[str]] = None,
              y: Optional[str] = None, training_frame=None,
              **extra: Any) -> "H2OGridSearch":
        import h2o3_tpu.client as h2o

        conn = h2o.connection()
        if training_frame is None:
            raise ValueError("training_frame is required")
        payload: Dict[str, Any] = dict(self.base_params)
        payload.update(extra)
        if y is not None:
            payload["response_column"] = y
        if x is not None:
            # h2o-py semantics: x lists the predictors; everything else
            # (except the response) is ignored — same translation as
            # H2OEstimator.train
            payload["ignored_columns"] = [
                c for c in training_frame.names if c not in x and c != y
            ]
        payload["training_frame"] = training_frame.frame_id
        payload["hyper_parameters"] = json.dumps(self.hyper_params)
        if self.search_criteria:
            payload["search_criteria"] = json.dumps(self.search_criteria)
        if self.grid_id:
            payload["grid_id"] = self.grid_id
        out = conn.request(f"POST /99/Grid/{self.algo}", payload)
        self.grid_id = out["grid_id"]["name"]
        self._summary = None
        return self

    # -- results -------------------------------------------------------------

    def _fetch(self, sort_by: str = "auto") -> Dict[str, Any]:
        import h2o3_tpu.client as h2o

        if self.grid_id is None:
            raise ValueError("train first")
        return h2o.connection().request(
            f"GET /99/Grids/{self.grid_id}", {"sort_by": sort_by})

    @property
    def model_ids(self) -> List[str]:
        if self._summary is None:
            self._summary = self._fetch()
        return [m["name"] for m in self._summary["model_ids"]]

    @property
    def models(self):
        from h2o3_tpu.client.estimators import H2OModel

        import h2o3_tpu.client as h2o

        conn = h2o.connection()
        return [H2OModel(conn, mid) for mid in self.model_ids]

    def get_grid(self, sort_by: str = "auto") -> "H2OGridSearch":
        """Re-sort server-side (grid_get sort_by); model_ids / models
        then reflect the new order."""
        self._summary = self._fetch(sort_by)
        return self

    @property
    def hyper_params_used(self) -> List[Dict[str, Any]]:
        if self._summary is None:
            self._summary = self._fetch()
        return self._summary.get("hyper_params", [])

    @property
    def failure_details(self) -> List[str]:
        if self._summary is None:
            self._summary = self._fetch()
        return self._summary.get("failure_details", [])
