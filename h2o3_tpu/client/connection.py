"""REST connection core.

Reference: ``h2o-py/h2o/backend/connection.py:229,409-433`` —
``H2OConnection.request(method endpoint, data=...)``, JSON responses,
error objects raised as exceptions, cloud-up polling.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, Optional


class H2OResponseError(Exception):
    def __init__(self, status: int, payload: Dict[str, Any]) -> None:
        super().__init__(payload.get("msg", f"HTTP {status}"))
        self.status = status
        self.payload = payload


class H2OConnection:
    """A live connection to one h2o3-tpu server."""

    def __init__(self, url: str) -> None:
        self.base_url = url.rstrip("/")
        self.session_id: Optional[str] = None

    def request(
        self,
        endpoint: str,
        data: Optional[Dict[str, Any]] = None,
        raw: bool = False,
    ) -> Any:
        """endpoint: "METHOD /path" like h2o-py (connection.py:229)."""
        method, path = endpoint.split(" ", 1)
        body = None
        headers = {}
        if data is not None:
            body = json.dumps(data).encode()
            headers["Content-Type"] = "application/json"
        req = urllib.request.Request(
            self.base_url + path, data=body, headers=headers, method=method
        )
        try:
            with urllib.request.urlopen(req) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as e:
            body = e.read()
            try:
                err = json.loads(body)
            except (ValueError, UnicodeDecodeError):
                # non-JSON error (proxy / wrong server): keep the status +
                # a body excerpt instead of masking it with JSONDecodeError
                err = {
                    "http_status": e.code,
                    "msg": body.decode(errors="replace")[:200] or str(e),
                }
            raise H2OResponseError(e.code, err)
        return payload if raw else json.loads(payload)

    # -- session (h2o-py lazily opens one for rapids) ------------------------
    def ensure_session(self) -> str:
        if self.session_id is None:
            self.session_id = self.request("POST /4/sessions")["session_key"]
        return self.session_id

    def close(self) -> None:
        if self.session_id is not None:
            try:
                self.request(f"DELETE /4/sessions/{self.session_id}")
            except H2OResponseError:
                pass
            self.session_id = None

    def cloud_info(self) -> Dict[str, Any]:
        return self.request("GET /3/Cloud")
