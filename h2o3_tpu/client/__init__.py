"""h2o3_tpu.client — the h2o-py-equivalent Python client.

Reference: ``h2o-py/h2o/h2o.py`` module functions (init/connect/
import_file/upload_file/get_frame/ls/remove, h2o.py:127,383), the lazy
``H2OFrame``/ExprNode surface (``h2o-py/h2o/expr.py``), and the estimator
classes (``h2o-py/h2o/estimators/``).

Usage::

    from h2o3_tpu import client as h2o
    h2o.init()                       # starts an in-process server
    fr = h2o.upload_csv("a,b\\n1,2\\n")
    train = h2o.import_file("data.csv")
    m = h2o.H2OGradientBoostingEstimator(ntrees=50)
    m.train(y="label", training_frame=train)
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

from h2o3_tpu.client.connection import H2OConnection, H2OResponseError
from h2o3_tpu.client.expr import ExprNode
from h2o3_tpu.client.frame import H2OFrame
from h2o3_tpu.client.estimators import (  # noqa: F401
    H2OAggregatorEstimator,
    H2OCoxProportionalHazardsEstimator,
    H2ODeepLearningEstimator,
    H2OEstimator,
    H2OExtendedIsolationForestEstimator,
    H2OGeneralizedAdditiveEstimator,
    H2OGeneralizedLinearEstimator,
    H2OGeneralizedLowRankEstimator,
    H2OGradientBoostingEstimator,
    H2OIsolationForestEstimator,
    H2OKMeansEstimator,
    H2OModel,
    H2ONaiveBayesEstimator,
    H2OPSVMEstimator,
    H2OPrincipalComponentAnalysisEstimator,
    H2ORandomForestEstimator,
    H2ORuleFitEstimator,
    H2OSingularValueDecompositionEstimator,
    H2OStackedEnsembleEstimator,
    H2OTargetEncoderEstimator,
    H2OWord2vecEstimator,
    H2OXGBoostEstimator,
)

_conn: Optional[H2OConnection] = None
_server = None  # in-process server when init() started one


def connection() -> H2OConnection:
    if _conn is None:
        raise RuntimeError("call h2o.init() or h2o.connect(url) first")
    return _conn


def init(url: Optional[str] = None) -> H2OConnection:
    """Start (or connect to) a server — h2o.init (h2o-py/h2o/h2o.py:127).
    Without a url, starts an in-process server (the reference spawns a local
    JVM, backend/server.py:33; here the 'cluster' is this process + its
    device mesh)."""
    global _conn, _server
    if url is None:
        from h2o3_tpu.api import start_server

        _server = start_server(port=0)
        url = _server.url
    _conn = H2OConnection(url)
    # evaluated NOW, while any in-process server at this URL is alive:
    # later consumers (adapter dead-server recovery) must know whether
    # this connection targeted one of our own servers — a stopped
    # server's port can be reused by an unrelated external service
    from h2o3_tpu.api.server import served_from_this_process

    _conn.in_process = served_from_this_process(url)
    _conn.cloud_info()  # fail fast if unreachable
    return _conn


def connect(url: str) -> H2OConnection:
    return init(url)


def shutdown() -> None:
    global _conn, _server
    if _conn is not None:
        try:
            _conn.close()
            _conn.request("POST /3/Shutdown")
        except Exception:  # unreachable server must not leave stale state
            pass
        _conn = None
    if _server is not None:
        _server.stop()
        _server = None


def import_file(path: str, destination_frame: Optional[str] = None) -> H2OFrame:
    """h2o.import_file (h2o.py:383): ImportFiles -> ParseSetup -> Parse.
    path may be a file, glob, directory, or URI — ALL matched sources parse
    into one frame (the reference's multi-file ParseDataset)."""
    c = connection()
    imp = c.request("POST /3/ImportFiles", {"path": path})
    srcs = imp["destination_frames"]
    setup = c.request("POST /3/ParseSetup", {"source_frames": srcs})
    dest = destination_frame or setup["destination_frame"]
    payload = {"source_frames": srcs, "destination_frame": dest}
    # separator/check_header exist only for CSV sources (non-CSV formats
    # carry their own structure)
    if "separator" in setup:
        payload["separator"] = setup["separator"]
        payload["check_header"] = setup["check_header"]
    out = c.request("POST /3/Parse", payload)
    key = out["destination_frame"]["name"]
    fr = c.request(f"GET /3/Frames/{key}")["frames"][0]
    return H2OFrame.from_key(c, key, nrows=fr["rows"], ncols=fr["num_columns"])


def upload_csv(text: str, destination_frame: Optional[str] = None) -> H2OFrame:
    """h2o.upload_file for in-memory CSV text."""
    c = connection()
    up = c.request("POST /3/PostFile", {"data": text})
    out = c.request(
        "POST /3/Parse",
        {
            "source_frames": [up["destination_frame"]],
            "destination_frame": destination_frame or "",
        },
    )
    key = out["destination_frame"]["name"]
    fr = c.request(f"GET /3/Frames/{key}")["frames"][0]
    return H2OFrame.from_key(c, key, nrows=fr["rows"], ncols=fr["num_columns"])


upload_file = import_file  # path-based alias


def get_frame(frame_id: str) -> H2OFrame:
    c = connection()
    fr = c.request(f"GET /3/Frames/{frame_id}")["frames"][0]
    return H2OFrame.from_key(c, frame_id, nrows=fr["rows"], ncols=fr["num_columns"])


def ls() -> List[str]:
    c = connection()
    return [f["frame_id"]["name"] for f in c.request("GET /3/Frames")["frames"]]


def remove(key: str) -> None:
    c = connection()
    try:
        c.request(f"DELETE /3/Frames/{key}")
    except H2OResponseError:
        c.request(f"DELETE /3/Models/{key}")


def remove_all() -> None:
    c = connection()
    c.request("DELETE /3/Frames")
    c.request("DELETE /3/Models")


def save_model(model_or_id, dir: str, force: bool = False) -> str:
    """h2o.save_model: binary model export server-side; returns the path.
    force=False (the h2o-py default) refuses to overwrite an existing file."""
    model_id = getattr(model_or_id, "model_id", model_or_id)
    out = connection().request(
        f"POST /3/Models/{model_id}/save", {"dir": dir, "force": str(force).lower()}
    )
    return out["dir"]


def load_model(path: str):
    """h2o.load_model: load a binary model file server-side."""
    from h2o3_tpu.client.estimators import H2OModel

    out = connection().request("POST /99/Models.bin", {"dir": path})
    return H2OModel(connection(), out["models"][0]["model_id"]["name"])


def import_mojo(path: str, model_id: Optional[str] = None):
    """h2o.import_mojo: import a MOJO archive as a servable Generic model."""
    from h2o3_tpu.client.estimators import H2OModel

    params = {"dir": path}
    if model_id:
        params["model_id"] = model_id
    out = connection().request("POST /99/Models.mojo", params)
    return H2OModel(connection(), out["models"][0]["model_id"]["name"])


def save_frame(frame_or_id, dir: str) -> str:
    """h2o.save_frame analogue (water/fvec/persist/FramePersist)."""
    frame_id = getattr(frame_or_id, "frame_id", frame_or_id)
    out = connection().request(f"POST /3/Frames/{frame_id}/save", {"dir": dir})
    return out["dir"]


def load_frame(path: str, frame_id: Optional[str] = None) -> "H2OFrame":
    """h2o.load_frame analogue: load a saved frame file server-side."""
    params = {"dir": path}
    if frame_id:
        params["frame_id"] = frame_id
    out = connection().request("POST /3/Frames/load", params)
    return get_frame(out["frames"][0]["frame_id"]["name"])


def rapids(ast: str) -> Dict[str, Any]:
    c = connection()
    return c.request(
        "POST /99/Rapids", {"ast": ast, "session_id": c.ensure_session()}
    )


def make_mojo_pipeline(models: Dict[str, Any], input_mapping: Dict[str, str],
                       main_model: str, path: str) -> str:
    """Compose trained models into ONE reference-layout pipeline MOJO on
    the server and save the zip locally (h2o.make_mojo_pipeline's role;
    hex/genmodel/MojoPipelineWriter). ``models`` maps alias -> model (or
    model id); ``input_mapping`` maps a generated column consumed by the
    main model to ``"alias:prediction_index"``."""
    import os

    spec = {alias: _key_of(m) if not isinstance(m, str) else m
            for alias, m in models.items()}
    raw = connection().request(
        "POST /99/MojoPipeline",
        {"models": spec, "input_mapping": input_mapping,
         "main_model": main_model}, raw=True)
    if os.path.isdir(path):
        path = os.path.join(path, "pipeline.mojo")
    with open(path, "wb") as f:
        f.write(raw)
    return path


def cluster_status() -> Dict[str, Any]:
    return connection().cloud_info()


def _key_of(obj) -> str:
    for attr in ("key", "model_id", "frame_id"):
        v = getattr(obj, attr, None)
        if isinstance(v, str) and v:
            return v
    if not isinstance(obj, str):
        raise TypeError(f"expected an id string or a keyed object, "
                        f"got {type(obj).__name__}")
    return obj


def make_metrics(predicted, actuals, domain: Optional[List[str]] = None,
                 distribution: str = "gaussian") -> Dict[str, Any]:
    """Metrics from raw predictions + actuals frames with no model
    (h2o.make_metrics -> POST /3/ModelMetrics/predictions_frame/...)."""
    params: Dict[str, Any] = {"distribution": distribution}
    if domain is not None:
        params["domain"] = list(domain)
    out = connection().request(
        f"POST /3/ModelMetrics/predictions_frame/{_key_of(predicted)}"
        f"/actuals_frame/{_key_of(actuals)}", params)
    return out["model_metrics"][0]


def feature_interaction(model_or_id, top_n: int = 100) -> Dict[str, Any]:
    """Pairwise split interactions of a tree model (/3/FeatureInteraction)."""
    return connection().request(
        "POST /3/FeatureInteraction",
        {"model_id": _key_of(model_or_id), "top_n": top_n})


def h_statistic(model_or_id, frame_or_id, variables: List[str],
                n_sample: int = 50) -> float:
    """Friedman-Popescu H for a variable pair (/3/FriedmansPopescusH)."""
    out = connection().request(
        "POST /3/FriedmansPopescusH",
        {"model_id": _key_of(model_or_id), "frame": _key_of(frame_or_id),
         "variables": list(variables), "n_sample": n_sample})
    return out["h"]


def tabulate(frame_or_id, predictor: str, response: str,
             weight: Optional[str] = None, nbins_predictor: int = 20,
             nbins_response: int = 10) -> Dict[str, Any]:
    """Co-occurrence + mean-response tables (h2o.tabulate -> /99/Tabulate)."""
    params: Dict[str, Any] = {
        "dataset": _key_of(frame_or_id), "predictor": predictor,
        "response": response, "nbins_predictor": nbins_predictor,
        "nbins_response": nbins_response,
    }
    if weight:
        params["weight"] = weight
    return connection().request("POST /99/Tabulate", params)


def interaction(frame_or_id, factor_columns: List[str],
                pairwise: bool = False, max_factors: int = 100,
                min_occurrence: int = 1,
                destination_frame: Optional[str] = None) -> "H2OFrame":
    """Categorical interaction columns (h2o.interaction -> /3/Interaction)."""
    params: Dict[str, Any] = {
        "source_frame": _key_of(frame_or_id),
        "factor_columns": list(factor_columns), "pairwise": pairwise,
        "max_factors": max_factors, "min_occurrence": min_occurrence,
    }
    if destination_frame:
        params["dest"] = destination_frame
    out = connection().request("POST /3/Interaction", params)
    return get_frame(out["destination_frame"]["name"])


def export_file(frame_or_id, path: str, force: bool = False) -> str:
    """Write a frame as CSV on the server (h2o.export_file)."""
    out = connection().request(
        f"POST /3/Frames/{_key_of(frame_or_id)}/export",
        {"path": path, "force": force})
    return out["path"]


def download_mojo(model_or_id, path: str, format: str = "native") -> str:
    """Save a model's MOJO archive locally (h2o.download_mojo).
    format='reference' emits the actual H2O-3 MOJO zip layout."""
    from h2o3_tpu.client.estimators import H2OModel

    # one implementation: the model method owns the endpoint + directory
    # handling; the module function just resolves the id
    m = H2OModel(connection(), _key_of(model_or_id))
    return m.download_mojo(path, format=format)


def download_pojo(model_or_id, lang: str = "java") -> str:
    """Standalone scoring source (h2o.download_pojo -> /3/Models.java)."""
    out = connection().request(
        f"GET /3/Models.java/{_key_of(model_or_id)}?lang={lang}", raw=True)
    return out.decode() if isinstance(out, bytes) else out


class H2OAutoML:
    """h2o-py/h2o/automl/H2OAutoML surface over /99/AutoMLBuilder."""

    def __init__(self, **params: Any) -> None:
        self._params = params
        self.project_key: Optional[str] = None
        self.leader: Optional[H2OModel] = None
        self._leaderboard: List[Dict[str, Any]] = []

    def train(
        self,
        y: str,
        training_frame: H2OFrame,
        x: Optional[List[str]] = None,
    ) -> "H2OAutoML":
        training_frame.refresh()
        c = training_frame._conn
        payload = dict(self._params)
        payload["training_frame"] = training_frame.frame_id
        payload["response_column"] = y
        if x is not None:
            payload["x"] = x
        out = c.request("POST /99/AutoMLBuilder", payload)
        self.project_key = out["automl_id"]["name"]
        self.leader = H2OModel(c, out["leader"]["name"])
        self._leaderboard = out["leaderboard"]
        return self

    @property
    def leaderboard(self) -> List[Dict[str, Any]]:
        return self._leaderboard


# -- scoring pipelines (mojo-pipeline extension analogue) --------------------


def build_pipeline(model_or_id=None, assembly_id: Optional[str] = None) -> str:
    """Build a server-side ScoringPipeline from a trained model and/or a
    fitted assembly; returns the pipeline key (hex/mojopipeline analogue)."""
    body: Dict[str, Any] = {}
    if model_or_id is not None:
        body["model"] = _key_of(model_or_id)
    if assembly_id:
        body["assembly"] = assembly_id
    out = connection().request("POST /99/PipelineMojo", body)
    return out["pipeline"]["name"]


def download_pipeline(pipeline_id: str, path: str) -> str:
    """Save a pipeline artifact zip locally."""
    data = connection().request(
        f"GET /99/PipelineMojo.fetch/{pipeline_id}", raw=True)
    if os.path.isdir(path):
        path = os.path.join(path, f"{pipeline_id}.zip")
    with open(path, "wb") as f:
        f.write(data if isinstance(data, bytes) else data.encode())
    return path


def import_pipeline(path: Optional[str] = None,
                    data: Optional[bytes] = None,
                    pipeline_id: Optional[str] = None) -> str:
    """Import a pipeline artifact from a LOCAL file (uploaded as base64)
    or raw bytes; returns the new pipeline key."""
    import base64

    if data is None:
        if path is None:
            raise ValueError("path or data required")
        with open(path, "rb") as f:
            data = f.read()
    body = {"data": base64.b64encode(data).decode()}
    if pipeline_id:
        body["destination_key"] = pipeline_id
    out = connection().request("POST /99/PipelineMojo.import", body)
    return out["pipeline"]["name"]


def pipeline_transform(pipeline_id: str, frame_or_id,
                       destination_frame: Optional[str] = None) -> "H2OFrame":
    """Run a frame through a pipeline; returns the result frame
    (MojoPipeline.transform)."""
    body = {"pipeline": pipeline_id, "frame": _key_of(frame_or_id)}
    if destination_frame:
        body["destination_frame"] = destination_frame
    out = connection().request("POST /99/PipelineMojo.transform", body)
    return get_frame(out["result"]["name"])
