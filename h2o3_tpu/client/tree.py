"""Tree inspection client — ``h2o-py/h2o/tree/tree.py`` analogue.

H2OTree fetches ``GET /3/Trees/{model_id}/{tree_number}`` (TreeV3-style
node arrays in heap layout: children of node i are 2i+1 / 2i+2) and
exposes the per-node arrays plus simple navigation.
"""

from __future__ import annotations

from typing import Any, List, Optional


class H2OTree:
    def __init__(self, model, tree_number: int, tree_class: int = 0) -> None:
        import urllib.parse

        import h2o3_tpu.client as h2o

        model_id = getattr(model, "model_id", model)
        quoted = urllib.parse.quote(model_id, safe="")
        out = h2o.connection().request(
            f"GET /3/Trees/{quoted}/{tree_number}",
            {"tree_class": tree_class})
        self.model_id: str = out["model_id"]["name"]
        self.tree_number: int = out["tree_number"]
        self.tree_class: int = out["tree_class"]
        self.features: List[Optional[str]] = out["features"]
        self.thresholds: List[Optional[float]] = out["thresholds"]
        self.is_split: List[bool] = out["is_split"]
        self.default_left: List[bool] = out["default_left"]
        self.predictions: List[float] = out["predictions"]

    def __len__(self) -> int:
        return len(self.features)

    @property
    def nodes(self) -> int:
        return len(self.features)

    def left_child(self, i: int) -> Optional[int]:
        c = 2 * i + 1
        return c if self.is_split[i] and c < len(self.features) else None

    def right_child(self, i: int) -> Optional[int]:
        c = 2 * i + 2
        return c if self.is_split[i] and c < len(self.features) else None

    def describe_node(self, i: int) -> str:
        if self.is_split[i]:
            na = "left" if self.default_left[i] else "right"
            return (f"node {i}: split on {self.features[i]} at "
                    f"{self.thresholds[i]} (NA -> {na})")
        return f"node {i}: leaf = {self.predictions[i]:.6g}"

    def __repr__(self) -> str:  # pragma: no cover - cosmetic
        n_leaves = sum(1 for s in self.is_split if not s)
        return (f"<H2OTree {self.model_id} tree={self.tree_number} "
                f"class={self.tree_class} nodes={self.nodes} "
                f"leaves~{n_leaves}>")
