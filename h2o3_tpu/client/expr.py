"""Lazy expression DAG compiled to Rapids ASTs.

Reference: ``h2o-py/h2o/expr.py:27-104`` — ``ExprNode``: an op + children,
stringified to the Lisp wire form, evaluated server-side on first use of
shape/data, with the result cached under a session temp key.
"""

from __future__ import annotations

import itertools
from typing import Any, List, Optional, Tuple, Union


_tmp_counter = itertools.count()


def _quote(s: str) -> str:
    return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'


def _to_ast(x: Any) -> str:
    """Render one argument to the Rapids wire syntax (expr.py _arg_to_expr)."""
    from h2o3_tpu.client.frame import H2OFrame

    if isinstance(x, ExprNode):
        return x.to_rapids()
    if isinstance(x, H2OFrame):
        return x._ex.to_rapids()
    if isinstance(x, bool):
        return "1" if x else "0"
    if isinstance(x, (int, float)):
        return repr(x)
    if isinstance(x, str):
        return _quote(x)
    if x is None:
        return '""'
    if isinstance(x, slice):  # [lo:hi) row/col ranges render as [lo:count]
        if x.step not in (None, 1):
            raise TypeError("stepped slices are not supported by rapids ranges")
        if x.stop is None:
            raise TypeError(
                "open-ended slice reached the wire layer; H2OFrame.__getitem__"
                " should have bounded it"
            )
        lo = x.start or 0
        return f"[{lo}:{x.stop - lo}]"
    if isinstance(x, (list, tuple)):
        return "[" + " ".join(_to_ast(v) for v in x) + "]"
    raise TypeError(f"cannot render {type(x)} into a rapids ast")


class ExprNode:
    """One node: op + args; leaves are frame keys / literals."""

    def __init__(self, op: str, *args: Any) -> None:
        self._op = op
        self._args = args

    def to_rapids(self) -> str:
        if self._op == "__key__":  # leaf: a server-side frame key
            return str(self._args[0])
        return "(" + self._op + "".join(" " + _to_ast(a) for a in self._args) + ")"

    @staticmethod
    def key(frame_key: str) -> "ExprNode":
        return ExprNode("__key__", frame_key)

    @staticmethod
    def raw(text: str) -> "ExprNode":
        """A pre-rendered rapids fragment (e.g. a ``{ x . ... }`` lambda)
        spliced into the wire string verbatim."""
        return ExprNode("__key__", text)

    @staticmethod
    def tmp_key() -> str:
        return f"py_tmp_{next(_tmp_counter)}"

    def __repr__(self) -> str:
        return f"<Expr {self.to_rapids()}>"
