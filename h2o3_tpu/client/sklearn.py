"""sklearn-compliant wrappers over the client estimators.

Reference: ``h2o-py/h2o/sklearn/`` (wrapper.py + the generated
``___Classifier`` / ``___Regressor`` / ``___Estimator`` families): fit /
predict / predict_proba / transform / score over numpy or pandas inputs,
full get_params/set_params so the wrappers clone inside sklearn pipelines
and searches, and automatic backend connection handling.

TPU-native build keeps the same surface but generates the wrappers from
this framework's own estimator registry; data travels as CSV through the
same REST the plain client uses.

Usage::

    from h2o3_tpu.client.sklearn import H2OGradientBoostingClassifier
    clf = H2OGradientBoostingClassifier(ntrees=50)
    clf.fit(X, y).predict(X)            # numpy in -> numpy out
    cross_val_score(clf, X, y, cv=3)    # clones via get_params
"""

from __future__ import annotations

import io
from typing import Any, Dict, Optional

import numpy as np

from sklearn.base import (
    BaseEstimator,
    ClassifierMixin,
    ClusterMixin,
    RegressorMixin,
    TransformerMixin,
)


def _connection():
    """Reuse the module-level client connection, starting an in-process
    server on first use (H2OConnectionMonitorMixin's auto-connect role)."""
    import h2o3_tpu.client as h2o

    try:
        return h2o.connection()
    except RuntimeError:  # never connected
        return h2o.init()


def _remove_quietly(key: str) -> None:
    import h2o3_tpu.client as h2o

    try:
        h2o.remove(key)
    except Exception:
        pass  # cleanup only — never turn a successful predict into an error


def _to_2d(X) -> np.ndarray:
    arr = np.asarray(
        X.values if hasattr(X, "values") else X, dtype=np.float64)
    if arr.ndim == 1:
        arr = arr[:, None]
    return arr


def _upload(X, y=None, y_categorical: bool = False):
    """numpy/pandas/list -> uploaded H2OFrame (CSV over REST).

    Classification responses upload as level strings (``c<label>``) so the
    server parses the column categorical — sklearn's numeric class labels
    would otherwise train a regressor.

    If the cached IN-PROCESS server has gone away (another component
    stopped it — test suites do), the first request fails at the
    connection level; one re-init + retry recovers instead of failing
    every adapter call. HTTP-level errors pass through untouched
    (H2OConnection converts them to H2OResponseError, which is not
    caught here), and a dead REMOTE connection also propagates: silently
    swapping a user's remote cluster for a fresh local server would send
    their data somewhere they never asked for.
    """
    import urllib.error

    import h2o3_tpu.client as h2o

    try:
        return _upload_once(X, y, y_categorical)
    except (urllib.error.URLError, ConnectionError, OSError):
        conn = getattr(h2o, "_conn", None)
        if conn is not None and not getattr(conn, "in_process", False):
            # a dead EXTERNAL connection is not ours to replace — even a
            # loopback address can be a port-forwarded remote cluster
            # (or a reused port of a long-gone local server); the user's
            # backend being down must surface, not silently reroute
            # their data to a fresh local server. `in_process` was
            # stamped at connect time, while the target was alive.
            raise
        # the dead server ran inside THIS process (ours, or a test
        # harness's) and is gone for good: start fresh, retry once
        h2o.init()
        return _upload_once(X, y, y_categorical)


def _upload_once(X, y=None, y_categorical: bool = False):
    import h2o3_tpu.client as h2o

    _connection()
    arr = _to_2d(X)
    names = [f"x{i}" for i in range(arr.shape[1])]
    cols = [arr[:, i].astype(str) for i in range(arr.shape[1])]
    if y is not None:
        yv = np.asarray(y.values if hasattr(y, "values") else y).ravel()
        names.append("y")
        cols.append(
            np.char.add("c", yv.astype(str)) if y_categorical
            else yv.astype(np.float64).astype(str)
        )
    import csv

    buf = io.StringIO()
    w = csv.writer(buf)  # proper quoting: labels may contain , or newlines
    w.writerow(names)
    w.writerows(zip(*cols))
    return h2o.upload_csv(buf.getvalue())


class _H2OSklearnBase(BaseEstimator):
    """get_params/set_params over the open **params dict (the reference
    generates explicit signatures; a dict keeps clone()/pipelines working
    without codegen)."""

    _algo: str = "?"

    def __init__(self, **params: Any) -> None:
        self._params: Dict[str, Any] = dict(params)
        self._model = None

    def get_params(self, deep: bool = True) -> Dict[str, Any]:
        return dict(self._params)

    def set_params(self, **params: Any) -> "_H2OSklearnBase":
        self._params.update(params)
        return self

    # -- shared plumbing -----------------------------------------------------

    def _estimator(self):
        from h2o3_tpu.client import estimators as E

        cls = E.for_algo(self._algo)
        if cls is None:
            raise ValueError(f"no client estimator for algo {self._algo!r}")
        return cls(**self._params)

    def _fit(self, X, y=None, categorical: bool = False,
             keep_train_frame: bool = False):
        arr = _to_2d(X)
        fr = _upload(arr, y, y_categorical=categorical)
        est = self._estimator()
        est.train(y="y" if y is not None else None, training_frame=fr)
        if self._model is not None:
            # refit: drop the superseded server-side model (CV/search loops
            # refit the same wrapper; models must not pile up in the DKV)
            _remove_quietly(self._model.model_id)
        self._model = est.model
        if keep_train_frame:
            self._train_frame = fr  # clusterer reads in-sample labels_
        else:
            _remove_quietly(fr.frame_id)
        self.n_features_in_ = arr.shape[1]
        return self

    def _predictions(self, X):
        """Score X and return the columns; server-side temp frames are
        deleted immediately — sklearn CV/search loops call predict many
        times and must not accumulate frames in the server's DKV."""
        if self._model is None:
            raise ValueError("fit first")
        fr = _upload(X)
        pred = self._model.predict(fr)
        data = pred.get_frame_data()
        _remove_quietly(pred.frame_id)
        _remove_quietly(fr.frame_id)
        return data


class _H2OClassifier(ClassifierMixin, _H2OSklearnBase):
    def fit(self, X, y):
        yv = np.asarray(y.values if hasattr(y, "values") else y).ravel()
        # upload CLASS INDICES as the level strings: np.unique and str()
        # can disagree on which values are "the same" (int 1 vs float 1.0
        # under object dtype), so uploading str(y) could mint more server
        # classes than classes_ holds; indices share one label space
        self.classes_, yidx = np.unique(yv, return_inverse=True)
        return self._fit(X, yidx, categorical=True)

    def predict(self, X):
        data = self._predictions(X)
        # map level strings back through classes_ — a dtype cast would
        # corrupt e.g. bool targets (np.asarray(['False'], bool) is True)
        by_name = {f"c{i}": c for i, c in enumerate(self.classes_)}
        return np.asarray([by_name[s] for s in data["predict"]],
                          dtype=self.classes_.dtype)

    def predict_proba(self, X):
        data = self._predictions(X)
        cols = []
        for i, c in enumerate(self.classes_):
            col = data.get(f"pc{i}")
            if col is None:
                raise ValueError(f"no probability column for class {c!r}")
            cols.append(np.asarray(col, dtype=np.float64))
        return np.stack(cols, axis=1)

    def predict_log_proba(self, X):
        return np.log(self.predict_proba(X))


class _H2ORegressor(RegressorMixin, _H2OSklearnBase):
    def fit(self, X, y):
        return self._fit(X, y, categorical=False)

    def predict(self, X):
        data = self._predictions(X)
        return np.asarray(data["predict"], dtype=np.float64)


class _H2OClusterer(ClusterMixin, _H2OSklearnBase):
    def fit(self, X, y=None):
        self._fit(X, keep_train_frame=True)
        # score the already-uploaded training frame — no second upload
        pred = self._model.predict(self._train_frame)
        data = pred.get_frame_data()
        _remove_quietly(pred.frame_id)
        _remove_quietly(self._train_frame.frame_id)
        del self._train_frame
        self.labels_ = np.asarray(data["predict"], dtype=np.int64)
        return self

    def predict(self, X):
        data = self._predictions(X)
        return np.asarray(data["predict"], dtype=np.int64)


class _H2OTransformer(TransformerMixin, _H2OSklearnBase):
    def fit(self, X, y=None):
        return self._fit(X)

    def transform(self, X):
        data = self._predictions(X)  # dict preserves server column order
        return np.stack(
            [np.asarray(c, dtype=np.float64) for c in data.values()], axis=1)


def _gen(name: str, algo: str, base: type) -> type:
    cls = type(name, (base,), {"_algo": algo})
    cls.__doc__ = (
        f"sklearn-compliant wrapper over the {algo!r} estimator "
        f"(h2o-py h2o.sklearn.{name} analogue)."
    )
    return cls


H2OGradientBoostingClassifier = _gen(
    "H2OGradientBoostingClassifier", "gbm", _H2OClassifier)
H2OGradientBoostingRegressor = _gen(
    "H2OGradientBoostingRegressor", "gbm", _H2ORegressor)
H2ORandomForestClassifier = _gen(
    "H2ORandomForestClassifier", "drf", _H2OClassifier)
H2ORandomForestRegressor = _gen(
    "H2ORandomForestRegressor", "drf", _H2ORegressor)
H2OXGBoostClassifier = _gen("H2OXGBoostClassifier", "xgboost", _H2OClassifier)
H2OXGBoostRegressor = _gen("H2OXGBoostRegressor", "xgboost", _H2ORegressor)
H2OGeneralizedLinearClassifier = _gen(
    "H2OGeneralizedLinearClassifier", "glm", _H2OClassifier)
H2OGeneralizedLinearRegressor = _gen(
    "H2OGeneralizedLinearRegressor", "glm", _H2ORegressor)
H2ODeepLearningClassifier = _gen(
    "H2ODeepLearningClassifier", "deeplearning", _H2OClassifier)
H2ODeepLearningRegressor = _gen(
    "H2ODeepLearningRegressor", "deeplearning", _H2ORegressor)
H2OKMeansEstimator = _gen("H2OKMeansEstimator", "kmeans", _H2OClusterer)
H2OPrincipalComponentAnalysisEstimator = _gen(
    "H2OPrincipalComponentAnalysisEstimator", "pca", _H2OTransformer)
