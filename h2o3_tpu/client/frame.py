"""H2OFrame — the client-side lazy dataframe.

Reference: ``h2o-py/h2o/frame.py:41`` (5.2k LoC H2OFrame) +
``h2o-py/h2o/expr.py`` cache semantics: operations build an ExprNode DAG;
the first use of shape/summary/data triggers one Rapids round trip that
materializes the result under a session temp key and caches nrows/ncols/
names/types client-side.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

from h2o3_tpu.client.connection import H2OConnection
from h2o3_tpu.client.expr import ExprNode, _to_ast


class H2OFrame:
    def __init__(self, conn: H2OConnection, ex: ExprNode) -> None:
        self._conn = conn
        self._ex = ex
        self._key: Optional[str] = None  # set once materialized
        self._nrows: Optional[int] = None
        self._ncols: Optional[int] = None
        self._names: Optional[List[str]] = None

    # -- construction --------------------------------------------------------
    @staticmethod
    def from_key(conn: H2OConnection, key: str, nrows=None, ncols=None) -> "H2OFrame":
        fr = H2OFrame(conn, ExprNode.key(key))
        fr._key = key
        fr._nrows, fr._ncols = nrows, ncols
        return fr

    # -- evaluation (expr.py _eager_frame / _eager_scalar) -------------------
    def refresh(self) -> "H2OFrame":
        """Materialize under a session temp key; cache the shape."""
        if self._key is None:
            sid = self._conn.ensure_session()
            # session id in the key: two clients of one server must not
            # clobber each other's temps (h2o-py scopes temp keys the same way)
            tmp = f"{sid}_{ExprNode.tmp_key()}"
            out = self._conn.request(
                "POST /99/Rapids",
                {"ast": f"(tmp= {tmp} {self._ex.to_rapids()})", "session_id": sid},
            )
            self._key = out["key"]["name"]
            self._nrows = out["num_rows"]
            self._ncols = out["num_cols"]
            self._ex = ExprNode.key(self._key)
        return self

    def _scalar(self, ex: ExprNode) -> Any:
        sid = self._conn.ensure_session()
        out = self._conn.request(
            "POST /99/Rapids", {"ast": ex.to_rapids(), "session_id": sid}
        )
        if "scalar" in out:
            v = out["scalar"]
            return v[0] if isinstance(v, list) and len(v) == 1 else v
        if "string" in out:
            return out["string"]
        return H2OFrame(self._conn, ExprNode.key(out["key"]["name"]))

    @property
    def frame_id(self) -> str:
        self.refresh()
        return self._key

    # -- shape ---------------------------------------------------------------
    @property
    def nrows(self) -> int:
        if self._nrows is None:
            self._nrows = int(self._scalar(ExprNode("nrow", self)))
        return self._nrows

    @property
    def ncols(self) -> int:
        if self._ncols is None:
            self._ncols = int(self._scalar(ExprNode("ncol", self)))
        return self._ncols

    @property
    def dim(self) -> List[int]:
        return [self.nrows, self.ncols]

    @property
    def names(self) -> List[str]:
        if self._names is None:
            self.refresh()
            out = self._conn.request(f"GET /3/Frames/{self._key}")
            self._names = out["frames"][0]["column_names"]
        return self._names

    @property
    def columns(self) -> List[str]:
        return self.names

    @property
    def types(self) -> Dict[str, str]:
        self.refresh()
        out = self._conn.request(f"GET /3/Frames/{self._key}")
        return {c["label"]: c["type"] for c in out["frames"][0]["columns"]}

    # -- derived frames ------------------------------------------------------
    def _unary(self, op: str, *extra) -> "H2OFrame":
        return H2OFrame(self._conn, ExprNode(op, self, *extra))

    def _binop(self, op: str, rhs: Any, reverse: bool = False) -> "H2OFrame":
        a, b = (rhs, self) if reverse else (self, rhs)
        return H2OFrame(self._conn, ExprNode(op, a, b))

    def __add__(self, o): return self._binop("+", o)
    def __radd__(self, o): return self._binop("+", o, True)
    def __sub__(self, o): return self._binop("-", o)
    def __rsub__(self, o): return self._binop("-", o, True)
    def __mul__(self, o): return self._binop("*", o)
    def __rmul__(self, o): return self._binop("*", o, True)
    def __truediv__(self, o): return self._binop("/", o)
    def __rtruediv__(self, o): return self._binop("/", o, True)
    def __pow__(self, o): return self._binop("^", o)
    def __mod__(self, o): return self._binop("%", o)
    def __eq__(self, o): return self._binop("==", o)  # type: ignore[override]
    def __ne__(self, o): return self._binop("!=", o)  # type: ignore[override]
    def __lt__(self, o): return self._binop("<", o)
    def __le__(self, o): return self._binop("<=", o)
    def __gt__(self, o): return self._binop(">", o)
    def __ge__(self, o): return self._binop(">=", o)
    def __and__(self, o): return self._binop("&", o)
    def __or__(self, o): return self._binop("|", o)
    def __invert__(self): return self._unary("not")
    def __neg__(self): return self._binop("-", 0, True)

    def __hash__(self):  # __eq__ is element-wise, keep hashability
        return id(self)

    def _bound_rows_slice(self, s: slice) -> slice:
        """Normalize a row slice: reject steps, bound open ends (stepped and
        negative ranges are outside the rapids [lo:count] wire form)."""
        if s.step not in (None, 1):
            raise TypeError("H2OFrame slicing does not support a step")
        start = s.start or 0
        stop = self.nrows if s.stop is None else min(s.stop, self.nrows)
        if start < 0 or stop < 0:
            raise TypeError("H2OFrame slicing does not support negative indices")
        return slice(start, max(stop, start))

    def __getitem__(self, item) -> "H2OFrame":
        """fr["col"], fr[["a","b"]], fr[rows_expr, :], fr[1:5, "a"] — the
        slicing surface of h2o-py frame.py __getitem__."""
        if isinstance(item, str):
            return self._unary("cols_py", item)
        if isinstance(item, (list, tuple)) and all(isinstance(i, str) for i in item):
            return self._unary("cols_py", list(item))
        if isinstance(item, int):
            return self._unary("cols_py", item)
        if isinstance(item, slice):
            return H2OFrame(
                self._conn, ExprNode("rows", self, self._bound_rows_slice(item))
            )
        if isinstance(item, H2OFrame):  # boolean row mask
            return H2OFrame(self._conn, ExprNode("rows", self, item))
        if isinstance(item, tuple) and len(item) == 2:
            rows, cols = item
            base = self
            if not (isinstance(cols, slice) and cols == slice(None)):
                base = base[cols]
            if isinstance(rows, slice):
                if rows == slice(None):
                    return base
                rows = self._bound_rows_slice(rows)
            return H2OFrame(self._conn, ExprNode("rows", base, rows))
        raise TypeError(f"cannot index H2OFrame with {item!r}")

    # -- reducers (eager scalars) -------------------------------------------
    def mean(self, na_rm: bool = True):
        return self._scalar(ExprNode("mean", self, na_rm, 0))

    def sum(self, na_rm: bool = True):
        return self._scalar(ExprNode("sum", self, na_rm))

    def min(self):
        return self._scalar(ExprNode("min", self, True))

    def max(self):
        return self._scalar(ExprNode("max", self, True))

    def sd(self):
        return self._scalar(ExprNode("sd", self, True))

    def median(self, na_rm: bool = True):
        return self._scalar(ExprNode("median", self, na_rm))

    def nacnt(self):
        v = self._scalar(ExprNode("naCnt", self))
        return v if isinstance(v, list) else [v]

    def unique(self) -> "H2OFrame":
        return self._unary("unique")

    def table(self) -> "H2OFrame":
        return self._unary("table", False)

    def quantile(self, prob=None,
                 combine_method: str = "interpolate") -> "H2OFrame":
        """Per-column quantiles (h2o-py H2OFrame.quantile; AstQtile)."""
        probs = list(prob) if prob is not None else \
            [0.001, 0.01, 0.1, 0.25, 0.333, 0.5, 0.667, 0.75, 0.9,
             0.99, 0.999]
        return H2OFrame(self._conn,
                        ExprNode("quantile", self, probs, combine_method))

    def impute(self, column: int = -1, method: str = "mean",
               combine_method: str = "interpolate",
               by=None) -> "H2OFrame":
        """NA imputation in place server-side (h2o-py H2OFrame.impute;
        AstImpute). column -1 = every numeric column."""
        return H2OFrame(self._conn, ExprNode(
            "h2o.impute", self, column, method, combine_method,
            list(by) if by else []))

    def cor(self, other: Optional["H2OFrame"] = None,
            use: str = "everything",
            method: str = "Pearson") -> "H2OFrame":
        """Correlation matrix (h2o-py H2OFrame.cor; AstCorrelation)."""
        return H2OFrame(self._conn, ExprNode(
            "cor", self, other if other is not None else self, use,
            method))

    def scale(self, center=True, scale=True) -> "H2OFrame":
        """Center/scale numeric columns (h2o-py H2OFrame.scale;
        AstScale)."""
        return H2OFrame(self._conn,
                        ExprNode("scale", self, center, scale))

    def cumsum(self, axis: int = 0) -> "H2OFrame":
        return self._unary("cumsum", axis)

    def cumprod(self, axis: int = 0) -> "H2OFrame":
        return self._unary("cumprod", axis)

    def tolower(self) -> "H2OFrame":
        return self._unary("tolower")

    def toupper(self) -> "H2OFrame":
        return self._unary("toupper")

    def trim(self) -> "H2OFrame":
        return self._unary("trim")

    def gsub(self, pattern: str, replacement: str,
             ignore_case: bool = False) -> "H2OFrame":
        """Replace all regex matches (h2o-py H2OFrame.gsub ->
        replaceall)."""
        return H2OFrame(self._conn, ExprNode(
            "replaceall", self, pattern, replacement, ignore_case))

    def strsplit(self, pattern: str) -> "H2OFrame":
        return self._unary("strsplit", pattern)

    def substring(self, start_index: int,
                  end_index: Optional[int] = None) -> "H2OFrame":
        return H2OFrame(self._conn, ExprNode(
            "substring", self, start_index,
            end_index if end_index is not None else -1))

    def nchar(self) -> "H2OFrame":
        return self._unary("length")

    def year(self) -> "H2OFrame":
        return self._unary("year")

    def month(self) -> "H2OFrame":
        return self._unary("month")

    def day(self) -> "H2OFrame":
        return self._unary("day")

    def hour(self) -> "H2OFrame":
        return self._unary("hour")

    # -- munging -------------------------------------------------------------
    def asfactor(self) -> "H2OFrame":
        return self._unary("as.factor")

    def asnumeric(self) -> "H2OFrame":
        return self._unary("as.numeric")

    def ascharacter(self) -> "H2OFrame":
        return self._unary("as.character")

    def cbind(self, other: "H2OFrame") -> "H2OFrame":
        return H2OFrame(self._conn, ExprNode("cbind", self, other))

    def rbind(self, other: "H2OFrame") -> "H2OFrame":
        return H2OFrame(self._conn, ExprNode("rbind", self, other))

    def set_names(self, names: List[str]) -> "H2OFrame":
        fr = H2OFrame(
            self._conn,
            ExprNode("colnames=", self, list(range(len(names))), names),
        )
        return fr

    def sort(self, by: Union[str, List[str]], ascending: bool = True) -> "H2OFrame":
        cols = [by] if isinstance(by, str) else list(by)
        idxs = [self.names.index(c) for c in cols]
        flags = [1 if ascending else 0] * len(idxs)
        return H2OFrame(self._conn, ExprNode("sort", self, idxs, flags))

    def merge(self, other: "H2OFrame", all_x: bool = False, all_y: bool = False) -> "H2OFrame":
        return H2OFrame(
            self._conn,
            ExprNode("merge", self, other, all_x, all_y, [], [], "auto"),
        )

    def group_by_sum(self, by: str, col: str) -> "H2OFrame":
        """Minimal groupby surface: (GB fr [by] "sum" col "all")."""
        return self.group_by(by).sum(col).get_frame()

    def group_by(self, by) -> "H2OGroupBy":
        """Fluent multi-aggregation group-by (h2o-py H2OFrame.group_by)."""
        return H2OGroupBy(self, by)

    def apply(self, fun, axis: int = 0) -> "H2OFrame":
        """h2o-py H2OFrame.apply: run an expression-shaped lambda per
        column (axis=0) or per row (axis=1). The lambda is traced with a
        symbolic proxy into a rapids ``{ x . expr }`` function (the
        reference compiles bytecode via astfun.py; tracing covers the
        same expression lambdas)."""
        if axis not in (0, 1):
            raise ValueError(f"axis must be 0 (columns) or 1 (rows), "
                             f"got {axis!r}")
        proxy = _LambdaProxy("x")
        out = fun(proxy)
        if not isinstance(out, _LambdaProxy):
            raise ValueError("lambda must return an expression built "
                             "from its argument")
        margin = 2 if axis == 0 else 1
        lam = "{ x . " + out._ast + " }"
        return H2OFrame(
            self._conn, ExprNode("apply", self, margin, ExprNode.raw(lam))
        )

    # -- materialization -----------------------------------------------------
    def get_frame_data(self) -> Dict[str, list]:
        """Full data download via /3/DownloadDataset (frame.py
        get_frame_data)."""
        self.refresh()
        raw = self._conn.request(
            f"GET /3/DownloadDataset?frame_id={self._key}", raw=True
        )
        import csv
        import io

        rows = list(csv.reader(io.StringIO(raw.decode())))
        head, body = rows[0], rows[1:]
        return {
            name: [r[i] if i < len(r) else None for r in body]
            for i, name in enumerate(head)
        }

    def as_data_frame(self):
        import pandas as pd

        data = self.get_frame_data()
        df = pd.DataFrame(data)
        return df.apply(pd.to_numeric, errors="ignore") if hasattr(df, "apply") else df

    def head(self, rows: int = 10) -> "H2OFrame":
        return self[0:rows]  # __getitem__ clamps to nrows

    def __repr__(self) -> str:
        if self._key:
            return f"<H2OFrame {self._key} {self._nrows}x{self._ncols}>"
        return f"<H2OFrame lazy {self._ex.to_rapids()[:60]}>"


class H2OGroupBy:
    """Fluent group-by builder — ``h2o-py/h2o/group_by.py`` analogue.

    Chain aggregations, then read ``.frame``/``get_frame()``: one
    ``(GB fr [by] agg col na ...)`` rapids op with all requested
    aggregations (AstGroup's multi-agg form).
    """

    def __init__(self, fr: "H2OFrame", by) -> None:
        self._fr = fr
        self._by = [by] if isinstance(by, str) else list(by)
        self._aggs: list = []

    #: server-accepted aggregate names (rapids/groupby.py AGGS)
    _AGGS = ("nrow", "sum", "mean", "min", "max", "sd", "var", "median",
             "mode")

    def _add(self, agg: str, col, na: str) -> "H2OGroupBy":
        if agg not in self._AGGS:
            raise ValueError(f"unknown aggregate {agg!r}; one of {self._AGGS}")
        cols = ([col] if isinstance(col, str)
                else list(col) if col is not None
                else [n for n in self._fr.names if n not in self._by])
        for c in cols:
            self._aggs.append((agg, self._fr.names.index(c), na))
        return self

    def count(self, na: str = "all") -> "H2OGroupBy":
        # nrow counts per group regardless of a value column; anchor on
        # the first by-column like the reference client does
        self._aggs.append(("nrow", self._fr.names.index(self._by[0]), na))
        return self

    def sum(self, col=None, na: str = "all") -> "H2OGroupBy":
        return self._add("sum", col, na)

    def mean(self, col=None, na: str = "all") -> "H2OGroupBy":
        return self._add("mean", col, na)

    def min(self, col=None, na: str = "all") -> "H2OGroupBy":
        return self._add("min", col, na)

    def max(self, col=None, na: str = "all") -> "H2OGroupBy":
        return self._add("max", col, na)

    def sd(self, col=None, na: str = "all") -> "H2OGroupBy":
        return self._add("sd", col, na)

    def var(self, col=None, na: str = "all") -> "H2OGroupBy":
        return self._add("var", col, na)

    def median(self, col=None, na: str = "all") -> "H2OGroupBy":
        return self._add("median", col, na)

    def mode(self, col=None, na: str = "all") -> "H2OGroupBy":
        return self._add("mode", col, na)

    def get_frame(self) -> "H2OFrame":
        if not self._aggs:
            raise ValueError("add at least one aggregation first")
        args: list = [self._fr, [self._fr.names.index(b) for b in self._by]]
        for agg, ci, na in self._aggs:
            args += [agg, ci, na]
        return H2OFrame(self._fr._conn, ExprNode("GB", *args))

    @property
    def frame(self) -> "H2OFrame":
        return self.get_frame()


class _LambdaProxy:
    """Symbolic stand-in passed to a user lambda: records arithmetic and
    method calls and prints as a rapids expression. Covers the
    expression-shaped lambdas H2OFrame.apply takes (the reference's
    astfun.py decompiles bytecode; tracing needs no bytecode and covers
    the same straight-line expressions, but not Python control flow)."""

    def __init__(self, ast: str) -> None:
        self._ast = ast

    # arithmetic ------------------------------------------------------------
    def _bin(self, op: str, other, flip: bool = False) -> "_LambdaProxy":
        if isinstance(other, _LambdaProxy):
            o = other._ast
        else:
            o = _to_ast(other)  # shared literal rendering; raises clearly
        a, b = (o, self._ast) if flip else (self._ast, o)
        return _LambdaProxy(f"({op} {a} {b})")

    def __add__(self, o):
        return self._bin("+", o)

    def __radd__(self, o):
        return self._bin("+", o, flip=True)

    def __sub__(self, o):
        return self._bin("-", o)

    def __rsub__(self, o):
        return self._bin("-", o, flip=True)

    def __mul__(self, o):
        return self._bin("*", o)

    def __rmul__(self, o):
        return self._bin("*", o, flip=True)

    def __truediv__(self, o):
        return self._bin("/", o)

    def __rtruediv__(self, o):
        return self._bin("/", o, flip=True)

    def __pow__(self, o):
        return self._bin("^", o)

    def __neg__(self):
        return _LambdaProxy(f"(- 0 {self._ast})")

    def __gt__(self, o):
        return self._bin(">", o)

    def __ge__(self, o):
        return self._bin(">=", o)

    def __lt__(self, o):
        return self._bin("<", o)

    def __le__(self, o):
        return self._bin("<=", o)

    def __eq__(self, o):  # element-wise, like the H2OFrame surface
        return self._bin("==", o)

    def __ne__(self, o):
        return self._bin("!=", o)

    __hash__ = None  # symbolic: never hash/deduplicate by identity

    def __bool__(self):
        raise TypeError(
            "a traced lambda expression has no truth value: chained "
            "comparisons (0 < x < 5) and and/or would silently drop "
            "terms — write (x > 0) * (x < 5) instead")

    # reducers / math methods ----------------------------------------------
    #: op -> extra rendered args; reducers carry na_rm=True so a lambda's
    #: x.sum() agrees with the direct H2OFrame sum() (whose client also
    #: sends na_rm) instead of NA-poisoning
    _METHODS = {
        "sum": ("sum", " 1"), "mean": ("mean", " 1 0"),
        "min": ("min", " 1"), "max": ("max", " 1"),
        "sd": ("sd", " 1"), "var": ("var", " 1"),
        "median": ("median", " 1"), "abs": ("abs", ""),
        "log": ("log", ""), "exp": ("exp", ""), "sqrt": ("sqrt", ""),
        "floor": ("floor", ""), "ceil": ("ceiling", ""),
        "nacnt": ("naCnt", ""),
    }

    def __getattr__(self, name: str):
        entry = self._METHODS.get(name)
        if entry is None:
            raise AttributeError(
                f"H2OFrame.apply lambda supports "
                f"{sorted(self._METHODS)} and arithmetic; got .{name}")
        op, extra = entry
        ast = self._ast

        def call() -> "_LambdaProxy":
            return _LambdaProxy(f"({op} {ast}{extra})")

        return call
