"""h2o3_tpu — a TPU-native, in-memory, distributed machine-learning platform.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of H2O-3
(reference: /root/reference, Java). The reference's four load-bearing ideas map to:

  * Frame/Vec/Chunk (water/fvec/Frame.java)        -> sharded columnar arrays
    (host-canonical numpy columns, device shards over a ``jax.sharding.Mesh``)
  * MRTask map + tree-reduce (water/MRTask.java)   -> ``shard_map`` + ``psum``
  * DKV distributed K/V store (water/DKV.java)     -> host-side keyed catalog
    (JAX owns device placement; no coherence protocol needed)
  * Rapids DSL + REST API (water/rapids/)          -> same logical op surface

This is NOT a port: the *data* plane has no Java cluster runtime — XLA
collectives over ICI/DCN and the JAX distributed runtime own sharded compute
(SURVEY.md §5 "Distributed communication backend").  The *control* plane the
runtime must own itself (membership, failure detection, key homes, remote
task dispatch) lives in ``h2o3_tpu/cluster/``: heartbeat-gossip clouds with
quorum hashing, stdlib-socket node RPC with the reference's retry ladder,
consistent-hash DKV homes, and multi-node map_reduce/parse fan-out.
"""

__version__ = "0.1.0"

__all__ = [
    "Frame",
    "Column",
    "ColType",
    "parse_csv",
    "parse_setup",
    "import_parse",
    "parse_svmlight",
    "parse_arff",
    "KeyedStore",
    "DKV",
]

_LAZY = {
    "Frame": ("h2o3_tpu.frame.frame", "Frame"),
    "Column": ("h2o3_tpu.frame.frame", "Column"),
    "ColType": ("h2o3_tpu.frame.frame", "ColType"),
    "parse_csv": ("h2o3_tpu.frame.parse", "parse_csv"),
    "parse_setup": ("h2o3_tpu.frame.parse", "parse_setup"),
    "import_parse": ("h2o3_tpu.frame.ingest", "import_parse"),
    "parse_svmlight": ("h2o3_tpu.frame.ingest", "parse_svmlight"),
    "parse_arff": ("h2o3_tpu.frame.ingest", "parse_arff"),
    "KeyedStore": ("h2o3_tpu.keyed", "KeyedStore"),
    "DKV": ("h2o3_tpu.keyed", "DKV"),
}


def __getattr__(name):
    """Lazy top-level exports (PEP 562) so the numpy-only
    ``h2o3_tpu.genmodel`` scoring package can be imported without pulling
    in jax (the reference ships genmodel as a dependency-light jar for the
    same reason, SURVEY.md §2.6)."""
    try:
        mod_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    val = getattr(importlib.import_module(mod_name), attr)
    globals()[name] = val
    return val
