"""h2o3_tpu — a TPU-native, in-memory, distributed machine-learning platform.

A from-scratch JAX/XLA/Pallas re-design of the capabilities of H2O-3
(reference: /root/reference, Java). The reference's four load-bearing ideas map to:

  * Frame/Vec/Chunk (water/fvec/Frame.java)        -> sharded columnar arrays
    (host-canonical numpy columns, device shards over a ``jax.sharding.Mesh``)
  * MRTask map + tree-reduce (water/MRTask.java)   -> ``shard_map`` + ``psum``
  * DKV distributed K/V store (water/DKV.java)     -> host-side keyed catalog
    (JAX owns device placement; no coherence protocol needed)
  * Rapids DSL + REST API (water/rapids/)          -> same logical op surface

This is NOT a port: no Java cluster runtime, no custom UDP/TCP transport, no
Paxos — XLA collectives over ICI/DCN and the JAX distributed runtime replace
all of it (SURVEY.md §5 "Distributed communication backend").
"""

__version__ = "0.1.0"

from h2o3_tpu.frame.frame import Frame, Column, ColType
from h2o3_tpu.frame.parse import parse_csv, parse_setup
from h2o3_tpu.keyed import KeyedStore, DKV

__all__ = [
    "Frame",
    "Column",
    "ColType",
    "parse_csv",
    "parse_setup",
    "KeyedStore",
    "DKV",
]
