"""Pass framework for the h2o3_tpu static analyzer.

The analyzer walks the repo's own sources (``ast`` only — importing this
module must never pull jax or any runtime module, so ``--changed-only``
runs stay fast) and reports :class:`Finding`\\ s keyed by a stable rule id.

Three suppression layers, in order of preference:

1. fix the code;
2. an inline ``# h2o3: noqa[RULE]`` comment on the flagged line (or the
   line directly above it) for sites that are *intentionally* in
   violation — the comment documents the exception next to the code;
3. an entry in the checked-in JSON baseline (``analysis_baseline.json``)
   with a one-line justification, for accepted pre-existing findings
   that should not block the build but also should not be silently
   blessed in-source.

Baseline entries match on a content fingerprint (rule + file + enclosing
symbol + stripped source line), not on line numbers, so unrelated edits
above a baselined site do not invalidate it.
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set

#: directories/files under the repo root the analyzer scans by default
DEFAULT_ROOTS = ("h2o3_tpu", "scripts", "bench.py")

#: path fragments never analyzed (generated/vendored/fixture code)
EXCLUDE_PARTS = ("tests/", "h2o3r/", "deploy/", "/.", "__pycache__")

_NOQA_RE = re.compile(r"#\s*h2o3:\s*noqa(?:\[([A-Za-z0-9_,\s]+)\])?")


@dataclass
class Finding:
    """One rule violation at a specific site."""

    rule: str
    file: str          #: repo-relative path
    line: int          #: 1-based
    symbol: str        #: enclosing ``Class.method`` / function qualname, or ""
    message: str
    snippet: str = ""  #: stripped source of the flagged line

    @property
    def fingerprint(self) -> str:
        """Line-number-independent identity used by the baseline."""
        raw = "|".join((self.rule, self.file, self.symbol, self.snippet))
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def to_dict(self) -> dict:
        return {
            "rule": self.rule,
            "file": self.file,
            "line": self.line,
            "symbol": self.symbol,
            "message": self.message,
            "snippet": self.snippet,
            "fingerprint": self.fingerprint,
        }

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        return f"{self.file}:{self.line}: {self.rule}{sym} {self.message}"


@dataclass
class Module:
    """A parsed source file plus the suppression map derived from it."""

    path: str                      #: absolute path
    rel: str                       #: repo-relative path
    source: str
    tree: ast.Module
    lines: List[str] = field(default_factory=list)
    #: line -> set of rule ids suppressed there ({"*"} = all rules)
    noqa: Dict[int, Set[str]] = field(default_factory=dict)

    @classmethod
    def parse(cls, path: str, rel: str, source: Optional[str] = None
              ) -> "Module":
        if source is None:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        tree = ast.parse(source, filename=rel)
        lines = source.splitlines()
        noqa: Dict[int, Set[str]] = {}
        for i, text in enumerate(lines, start=1):
            m = _NOQA_RE.search(text)
            if not m:
                continue
            rules = ({"*"} if m.group(1) is None else
                     {r.strip() for r in m.group(1).split(",") if r.strip()})
            noqa.setdefault(i, set()).update(rules)
        return cls(path=path, rel=rel, source=source, tree=tree,
                   lines=lines, noqa=noqa)

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    def suppressed(self, lineno: int, rule: str) -> bool:
        """True if ``rule`` is noqa'd on the line or the line above it."""
        for ln in (lineno, lineno - 1):
            rules = self.noqa.get(ln)
            if rules and ("*" in rules or rule in rules):
                return True
        return False


def iter_source_files(root: str,
                      roots: Sequence[str] = DEFAULT_ROOTS) -> List[str]:
    """Repo-relative paths of every analyzable ``.py`` file."""
    out: List[str] = []
    for entry in roots:
        full = os.path.join(root, entry)
        if os.path.isfile(full):
            out.append(entry)
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = [d for d in dirnames
                           if d != "__pycache__" and not d.startswith(".")]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                rel = rel.replace(os.sep, "/")
                if any(part in rel for part in EXCLUDE_PARTS):
                    continue
                out.append(rel)
    return sorted(set(out))


def load_modules(root: str,
                 files: Optional[Iterable[str]] = None) -> List[Module]:
    """Parse ``files`` (repo-relative; default: the whole scan surface)."""
    rels = list(files) if files is not None else iter_source_files(root)
    mods: List[Module] = []
    for rel in rels:
        path = os.path.join(root, rel)
        if not os.path.isfile(path):
            continue
        try:
            mods.append(Module.parse(path, rel))
        except SyntaxError as e:
            mods.append(Module.parse(
                path, rel, source=""))  # keep slot; surface as a finding
            mods[-1].noqa = {}
            mods[-1].lines = []
            mods[-1].tree = ast.Module(body=[], type_ignores=[])
            mods[-1].source = ""
            _SYNTAX_ERRORS.append(Finding(
                rule="PARSE001", file=rel, line=e.lineno or 0, symbol="",
                message=f"file does not parse: {e.msg}", snippet=""))
    return mods


_SYNTAX_ERRORS: List[Finding] = []


# ---------------------------------------------------------------------------
# baseline

BASELINE_VERSION = 1


def load_baseline(path: str) -> Dict[str, dict]:
    """fingerprint -> entry dict. Missing file = empty baseline."""
    if not os.path.isfile(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(
            f"baseline {path} has unsupported version {data.get('version')!r}")
    return {e["fingerprint"]: e for e in data.get("entries", [])}


def save_baseline(path: str, findings: Sequence[Finding],
                  justifications: Optional[Dict[str, str]] = None) -> None:
    """Write a baseline accepting ``findings``; keeps prior justifications
    for fingerprints already present when ``justifications`` maps them."""
    justifications = justifications or {}
    entries = []
    for f in sorted(findings, key=lambda f: (f.file, f.line, f.rule)):
        entries.append({
            "fingerprint": f.fingerprint,
            "rule": f.rule,
            "file": f.file,
            "symbol": f.symbol,
            "snippet": f.snippet,
            "justification": justifications.get(
                f.fingerprint, "accepted pre-existing finding"),
        })
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": BASELINE_VERSION, "entries": entries}, f,
                  indent=2, sort_keys=False)
        f.write("\n")


def split_baselined(findings: Sequence[Finding], baseline: Dict[str, dict]
                    ) -> tuple:
    """(new, accepted) partition of ``findings`` against the baseline."""
    new, accepted = [], []
    for f in findings:
        (accepted if f.fingerprint in baseline else new).append(f)
    return new, accepted


# ---------------------------------------------------------------------------
# driver


@dataclass
class Context:
    """Shared inputs handed to every pass."""

    root: str
    readme_path: str
    modules: List[Module] = field(default_factory=list)
    #: full-surface module list for cross-module passes (lock ordering,
    #: knob registry) even when only a subset is being re-analyzed
    all_modules: List[Module] = field(default_factory=list)


def default_passes() -> Dict[str, object]:
    """name -> run(ctx) callable for every registered pass (lazy imports
    so a subset run does not pay for the others)."""
    from .passes import (knob_registry, lock_discipline, rpc_payload,
                         seeded_determinism, tracer_purity)

    passes = {
        "lock-discipline": lock_discipline.run,
        "tracer-purity": tracer_purity.run,
        "seeded-determinism": seeded_determinism.run,
        "knob-registry": knob_registry.run,
        "rpc-payload": rpc_payload.run,
    }
    from .passes import telemetry_drift
    passes["telemetry-drift"] = telemetry_drift.run
    return passes


def run_passes(ctx: Context,
               pass_names: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the requested passes, apply noqa suppressions, sort findings."""
    registry = default_passes()
    names = list(pass_names) if pass_names else list(registry)
    unknown = [n for n in names if n not in registry]
    if unknown:
        raise KeyError(f"unknown pass(es): {', '.join(unknown)}")

    by_rel = {m.rel: m for m in ctx.all_modules or ctx.modules}
    findings: List[Finding] = list(_SYNTAX_ERRORS)
    _SYNTAX_ERRORS.clear()
    for name in names:
        findings.extend(registry[name](ctx))

    kept = []
    for f in findings:
        mod = by_rel.get(f.file)
        if mod is not None and mod.suppressed(f.line, f.rule):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.file, f.line, f.rule, f.message))
    return kept


def analyze(root: str, files: Optional[Iterable[str]] = None,
            pass_names: Optional[Sequence[str]] = None,
            readme_path: Optional[str] = None) -> List[Finding]:
    """One-call entry point: parse, run passes, suppress, sort."""
    all_modules = load_modules(root)
    if files is None:
        modules = all_modules
    else:
        wanted = set(files)
        by_rel = {m.rel: m for m in all_modules}
        modules = [by_rel[rel] for rel in sorted(wanted) if rel in by_rel]
        # subset files outside the default scan surface still analyze —
        # and must join all_modules so cross-module passes see them
        extra = load_modules(root, sorted(
            rel for rel in wanted if rel not in by_rel))
        modules.extend(extra)
        all_modules = all_modules + extra
    ctx = Context(root=root,
                  readme_path=readme_path or os.path.join(root, "README.md"),
                  modules=modules, all_modules=all_modules)
    return run_passes(ctx, pass_names)


def analyze_source(source: str, rel: str = "snippet.py",
                   pass_names: Optional[Sequence[str]] = None,
                   readme_text: str = "") -> List[Finding]:
    """Analyze an in-memory snippet — the unit-test entry point.

    ``readme_text`` stands in for README.md for the knob-registry pass.
    """
    mod = Module.parse(rel, rel, source=source)
    ctx = Context(root="", readme_path="", modules=[mod], all_modules=[mod])
    ctx.readme_text = readme_text  # type: ignore[attr-defined]
    names = list(pass_names) if pass_names else [
        "lock-discipline", "tracer-purity", "seeded-determinism",
        "knob-registry", "rpc-payload",
    ]
    return run_passes(ctx, names)
