"""seeded-determinism: chaos/retry decisions must draw from seeded PRNGs.

PR 8's chaos plane promises byte-identical verdicts for the same
``--seed``: every jitter, drop, and delay decision must come from a
plan-derived ``random.Random(seed)`` (``FaultPlan`` per-rule streams,
``backoff_rng()``), never from the process-global ``random`` module or
the wall clock. These rules only apply to the decision-making scope
files — the rest of the codebase may use ``random`` freely.

SEED001 — bare module-level ``random.<fn>()`` call in a scope file.
SEED002 — unseeded ``random.Random()`` constructed in a scope file.
SEED003 — ``time.time()`` / ``time.time_ns()`` used in a chaos decision
file (``faults.py``, ``scripts/chaos.py``) where it would leak
wall-clock nondeterminism into verdicts.
"""

from __future__ import annotations

import ast
from typing import List

from ..astutil import call_name
from ..core import Context, Finding
from ..astutil import enclosing_symbol

RULES = {
    "SEED001": "bare random.* call in a determinism-scoped file",
    "SEED002": "unseeded random.Random() in a determinism-scoped file",
    "SEED003": "wall-clock read in a chaos decision file",
}

#: files whose control decisions must be plan-seeded
SEED_SCOPE = (
    "h2o3_tpu/cluster/faults.py",
    "h2o3_tpu/cluster/rpc.py",
    "scripts/chaos.py",
)

#: files where wall-clock reads leak into chaos verdicts
TIME_SCOPE = (
    "h2o3_tpu/cluster/faults.py",
    "scripts/chaos.py",
)

_RANDOM_MODULE_FNS = {
    "random", "uniform", "randint", "randrange", "choice", "choices",
    "shuffle", "sample", "expovariate", "gauss", "normalvariate",
    "betavariate", "triangular", "seed", "getrandbits",
}


def run(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        seed_scoped = mod.rel in SEED_SCOPE
        time_scoped = mod.rel in TIME_SCOPE
        if not (seed_scoped or time_scoped):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            line = node.lineno
            if seed_scoped:
                parts = name.split(".")
                if (len(parts) == 2 and parts[0] in ("random", "np.random")
                        and parts[1] in _RANDOM_MODULE_FNS):
                    findings.append(Finding(
                        rule="SEED001", file=mod.rel, line=line,
                        symbol=enclosing_symbol(mod.tree, line),
                        message=f"{name}() draws from the process-global "
                                f"RNG; chaos/retry decisions must use a "
                                f"plan-derived random.Random(seed)",
                        snippet=mod.line_text(line)))
                if name in ("random.Random", "Random") and not node.args \
                        and not node.keywords:
                    findings.append(Finding(
                        rule="SEED002", file=mod.rel, line=line,
                        symbol=enclosing_symbol(mod.tree, line),
                        message="random.Random() without a seed is "
                                "nondeterministic; derive the seed from "
                                "the fault plan",
                        snippet=mod.line_text(line)))
            if time_scoped and name in ("time.time", "time.time_ns"):
                findings.append(Finding(
                    rule="SEED003", file=mod.rel, line=line,
                    symbol=enclosing_symbol(mod.tree, line),
                    message=f"{name}() leaks wall-clock nondeterminism "
                            f"into chaos decisions; thread a logical "
                            f"clock or plan-derived value instead",
                    snippet=mod.line_text(line)))
    return findings
