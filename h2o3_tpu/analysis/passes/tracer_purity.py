"""tracer-purity: traced functions must be pure.

TRACE001 — a function handed to ``jax.jit`` / ``shard_map`` / ``pmap`` /
``map_batches`` / ``map_reduce`` (or installed as a fusion ``emit=``
tracer) calls ``time.*``, ``random.*``, telemetry, acquires a lock, or
does I/O. Side effects inside a tracer run once at trace time and then
silently never again — a wall-clock read or a meter increment there is
a bug every time, and a lock acquire can deadlock the compile path.

``arr.at[i].set(v)`` is functional jax, not telemetry — ``.set`` is
deliberately NOT in the impurity list.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..astutil import (FuncNode, call_name, dotted_name, index_functions,
                       module_level_defs)
from ..core import Context, Finding

RULES = {
    "TRACE001": "impure operation inside a traced/jitted function",
}

#: call names whose first positional argument is traced
TRACING_CALLS = {
    "jax.jit", "jit", "jax.pmap", "pmap", "shard_map",
    "jax.experimental.shard_map.shard_map",
}

#: attribute/bare suffixes whose first argument is traced (methods too)
TRACING_SUFFIXES = {"map_batches", "map_reduce", "distributed_map_reduce"}

_RANDOM_PREFIXES = ("random.", "np.random.", "numpy.random.")


def _impure_reason(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Call):
        name = call_name(node)
        if not name:
            return None
        if name.startswith("time."):
            return f"wall-clock/sleep call {name}()"
        if name.startswith(_RANDOM_PREFIXES) and not name.endswith(".Random"):
            return f"unseeded RNG call {name}()"
        if name.startswith("telemetry.") or name.endswith(
                (".inc", ".observe", ".labels")):
            return f"telemetry call {name}()"
        if name == "Span" or name.endswith(".Span"):
            return f"telemetry span {name}()"
        if name.endswith(".acquire"):
            return f"lock acquire {name}()"
        if name in ("open", "print"):
            return f"I/O call {name}()"
        if name.endswith((".sendall", ".recv", ".connect")):
            return f"socket I/O {name}()"
    elif isinstance(node, ast.With):
        for item in node.items:
            nm = dotted_name(item.context_expr) or ""
            if "lock" in nm.lower():
                return f"holds lock {nm}"
    return None


def _is_tracing_decorator(dec: ast.expr) -> Optional[str]:
    name = dotted_name(dec)
    if name in TRACING_CALLS:
        return name
    if isinstance(dec, ast.Call):
        cname = call_name(dec) or ""
        if cname in TRACING_CALLS:
            return cname
        if cname in ("partial", "functools.partial") and dec.args:
            inner = dotted_name(dec.args[0])
            if inner in TRACING_CALLS:
                return inner
    return None


def run(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    markers = ("jit", "shard_map", "pmap", "map_batches", "map_reduce",
               "emit")
    for mod in ctx.modules:
        # fast gate: no tracing entry point named anywhere → nothing
        # can be traced in this module
        if not any(m in mod.source for m in markers):
            continue
        funcs = index_functions(mod.tree)
        top = module_level_defs(mod.tree)
        by_simple: Dict[str, List[ast.AST]] = {}
        for qual, info in funcs.items():
            by_simple.setdefault(qual.split(".")[-1], []).append(info.node)

        traced: List[Tuple[ast.AST, str, str]] = []  # node, symbol, how

        def resolve(arg: ast.expr, how: str) -> None:
            if isinstance(arg, ast.Lambda):
                traced.append((arg, "<lambda>", how))
            elif isinstance(arg, ast.Name):
                node = top.get(arg.id)
                if node is None:
                    cands = by_simple.get(arg.id, [])
                    node = cands[0] if len(cands) == 1 else None
                if node is not None:
                    traced.append((node, arg.id, how))

        for qual, info in funcs.items():
            for dec in info.node.decorator_list:
                how = _is_tracing_decorator(dec)
                if how:
                    traced.append(
                        (info.node, qual, f"decorated with @{how}"))
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            last = name.split(".")[-1]
            if (name in TRACING_CALLS or last in TRACING_SUFFIXES) \
                    and node.args:
                resolve(node.args[0], f"passed to {name}()")
            for kw in node.keywords:
                if kw.arg == "emit" and kw.value is not None:
                    resolve(kw.value, "installed as fusion emit= tracer")

        seen = set()
        for fn_node, symbol, how in traced:
            key = id(fn_node)
            if key in seen:
                continue
            seen.add(key)
            body = fn_node.body if isinstance(fn_node, FuncNode) \
                else [fn_node.body]
            for stmt in body:
                for sub in ast.walk(stmt) if isinstance(stmt, ast.AST) \
                        else ():
                    reason = _impure_reason(sub)
                    if reason:
                        findings.append(Finding(
                            rule="TRACE001", file=mod.rel,
                            line=getattr(sub, "lineno", fn_node.lineno),
                            symbol=symbol,
                            message=f"{reason} inside traced function "
                                    f"({how})",
                            snippet=mod.line_text(
                                getattr(sub, "lineno", fn_node.lineno))))
    return findings
