"""knob-registry: every ``H2O3_TPU_*`` env knob must be documented.

KNOB001 — an ``H2O3_TPU_*`` env var is referenced in code (a direct
``os.environ.get`` read, a subscript, or a config-table constant) but
README.md never mentions it. Undocumented knobs are how two nodes end
up booted with silently different behavior.

KNOB002 — README.md names an ``H2O3_TPU_*`` knob that no code reads:
either stale docs or a typo'd knob name that operators will set to no
effect.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Tuple

from ..astutil import enclosing_symbol
from ..core import Context, Finding

RULES = {
    "KNOB001": "env knob read in code but undocumented in README",
    "KNOB002": "env knob documented in README but never read in code",
}

_KNOB_RE = re.compile(r"H2O3_TPU_[A-Z0-9_]+")


def _env_reads(tree: ast.Module) -> List[Tuple[str, int]]:
    """(knob, line) for every code reference to a knob.

    Any string constant that is *exactly* a knob name counts: direct
    ``os.environ.get("H2O3_TPU_X")`` reads, subscripts, and table-driven
    configs like server.py's ``{"max_conns": ("H2O3_TPU_HTTP_MAX_CONNS",
    ...)}``. Docstrings and error messages mention knobs inside prose so
    they never full-match.
    """
    out: List[Tuple[str, int]] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and _KNOB_RE.fullmatch(node.value):
            out.append((node.value, node.lineno))
    return out


def _readme(ctx: Context) -> Tuple[str, str]:
    """(text, display-path) of the README the docs side is checked
    against; ``analyze_source`` injects ``ctx.readme_text`` instead."""
    text = getattr(ctx, "readme_text", None)
    if text is not None:
        return text, "README.md"
    try:
        with open(ctx.readme_path, encoding="utf-8") as f:
            return f.read(), "README.md"
    except OSError:
        return "", "README.md"


def run(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    readme_text, readme_rel = _readme(ctx)
    documented: Dict[str, int] = {}
    for i, line in enumerate(readme_text.splitlines(), start=1):
        for knob in _KNOB_RE.findall(line):
            documented.setdefault(knob, i)

    # the KNOB002 direction only needs the set of knob names referenced
    # anywhere — a source regex for exact quoted literals is ~10x
    # cheaper than walking every module's AST
    read_anywhere = set()
    quoted = re.compile(r"""["'](H2O3_TPU_[A-Z0-9_]+)["']""")
    for mod in ctx.all_modules:
        read_anywhere.update(quoted.findall(mod.source))

    for mod in ctx.modules:
        for knob, line in _env_reads(mod.tree):
            if knob not in documented:
                findings.append(Finding(
                    rule="KNOB001", file=mod.rel, line=line,
                    symbol=enclosing_symbol(mod.tree, line),
                    message=f"env knob {knob} is read here but README.md "
                            f"never documents it",
                    snippet=mod.line_text(line)))

    for knob, line in sorted(documented.items()):
        if knob not in read_anywhere:
            findings.append(Finding(
                rule="KNOB002", file=readme_rel, line=line, symbol=knob,
                message=f"README.md documents env knob {knob} but no code "
                        f"reads it",
                snippet=knob))
    return findings
