"""rpc-payload: values crossing the wire must be routable.

``cluster/dkv.py`` defines ``ROUTABLE_VALUE_TYPES`` — plain data only.
Functions, lambdas, and closures pickle by *module reference*: they
appear to serialize locally and then fail (or silently resolve to
different code) on the receiving node, which is why
``distributed_map_reduce`` rejects them at runtime. These rules catch
the statically-obvious cases at the call site instead of at unpickle
time on a remote host.

ROUTE001 — a lambda or a reference to a locally-defined function is
handed to a DKV ``put``/``remote_put``/``replicate`` value slot.
ROUTE002 — a lambda appears anywhere inside an RPC ``call``/``submit``
payload expression.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from ..astutil import (call_name, dotted_name, enclosing_symbol,
                       module_level_defs)
from ..core import Context, Finding

RULES = {
    "ROUTE001": "non-routable value handed to DKV put/replicate",
    "ROUTE002": "lambda inside an RPC call/submit payload",
}

#: receiver-name fragments that mark a ``.put()`` as a DKV store put
#: (bare ``q.put(item)`` on local queues is not a wire crossing)
_STORE_HINTS = ("store", "dkv", "router", "kv", "catalog")


def _lambda_in(expr: ast.expr) -> Optional[ast.AST]:
    for node in ast.walk(expr):
        if isinstance(node, ast.Lambda):
            return node
    return None


def run(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for mod in ctx.modules:
        top = module_level_defs(mod.tree)

        def flag(rule: str, node: ast.AST, msg: str) -> None:
            line = getattr(node, "lineno", 0)
            findings.append(Finding(
                rule=rule, file=mod.rel, line=line,
                symbol=enclosing_symbol(mod.tree, line), message=msg,
                snippet=mod.line_text(line)))

        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node) or ""
            parts = name.split(".")
            last = parts[-1]

            value = None
            if last in ("remote_put", "replicate") and len(node.args) >= 2:
                value = node.args[1]
            elif last == "put" and len(node.args) >= 2:
                recv = ".".join(parts[:-1]).lower()
                if any(h in recv for h in _STORE_HINTS):
                    value = node.args[1]
            if value is not None:
                lam = _lambda_in(value)
                if lam is not None:
                    flag("ROUTE001", lam,
                         f"lambda in the value handed to {name}(); "
                         f"functions pickle by module reference and are "
                         f"not ROUTABLE_VALUE_TYPES-compatible")
                elif isinstance(value, ast.Name) and value.id in top:
                    flag("ROUTE001", value,
                         f"locally-defined function {value.id!r} handed to "
                         f"{name}(); not ROUTABLE_VALUE_TYPES-compatible")

            if last in ("call", "submit") and len(parts) > 1:
                for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                        if kw.value is not None]:
                    lam = _lambda_in(arg)
                    if lam is not None:
                        flag("ROUTE002", lam,
                             f"lambda inside the payload of {name}(); "
                             f"lambdas cannot cross the wire (pickled by "
                             f"module reference)")
                        break
    return findings
