"""telemetry-drift: observability docs must match the live registries.

The sixth pass absorbs ``scripts/check_telemetry.py`` (which remains as
a thin shim over :func:`collect`). Unlike the AST passes this one
imports the runtime — server routes, the telemetry registry, and the
fusion prim table are *live* objects — so it is skipped by
``--changed-only`` runs unless a telemetry-relevant file changed.

TDRIFT001 — observability route registered but undocumented in
README.md's Observability table, or documented but not registered.
TDRIFT002 — README documents a metric name the telemetry registry
never declares.
TDRIFT003 — a fusible prim has no emitter (silent fallback on every
query).
TDRIFT004 — a fusible prim has no fused-vs-interpreted parity case.
TDRIFT005 — the algo registry and the ``/3/ModelBuilders/{algo}`` train
route have drifted apart.
"""

from __future__ import annotations

import os
import re
from typing import List, Tuple

from ..core import Context, Finding

RULES = {
    "TDRIFT001": "observability route table drift (README vs server)",
    "TDRIFT002": "documented metric missing from the telemetry registry",
    "TDRIFT003": "fusible prim without an emitter",
    "TDRIFT004": "fusible prim without a parity test case",
    "TDRIFT005": "algo registry vs train route drift",
}

#: route prefixes that constitute the observability surface
OBS_PREFIXES = (
    "/3/Logs",
    "/3/Timeline",
    "/3/Traces",
    "/3/SlowOps",
    "/3/Diagnostics",
    "/3/Metrics",
    "/3/Profiler",
    "/3/JStack",
    "/3/WaterMeterCpuTicks",
    "/3/Ping",
)

#: backticked tokens with one of these suffixes (optionally carrying a
#: ``{label,...}`` hint) are treated as metric references the registry
#: must actually contain
_METRIC_SUFFIXES = ("_total", "_seconds", "_bytes", "_entries", "_workers",
                    "_inflight", "_depth", "_batch_size", "_connections",
                    "_homes", "_state")

#: README sections whose backticked metric references the registry must
#: actually contain — ``##`` sections or ``###`` subsections (the cost
#: ledger and cluster profiler live under ``## Observability``)
_METRIC_SECTIONS = ("Observability", "Clustering", "Distributed Frames",
                    "Distributed Rapids", "Distributed model search",
                    "Distributed training", "Failure model", "Serving plane",
                    "Cost ledger & slow-op log", "Cluster profiler",
                    "Health plane", "Device cache", "Chunk codecs")


def readme_documented_routes(readme_path: str) -> set:
    """Route strings out of the Observability section's markdown table."""
    with open(readme_path) as f:
        text = f.read()
    m = re.search(r"^## Observability$(.*?)(?=^## |\Z)", text,
                  re.MULTILINE | re.DOTALL)
    if not m:
        return set()
    routes = set()
    for line in m.group(1).splitlines():
        if not line.startswith("|"):
            continue
        cell = line.split("|")[1].strip().strip("`")
        parts = cell.split()
        if len(parts) == 2 and parts[0] in ("GET", "POST", "DELETE"):
            # table escapes | inside parameter hints; the route is parts[1]
            routes.add((parts[0], parts[1]))
    return routes


def readme_documented_metrics(readme_path: str) -> set:
    """Metric names referenced in the metric-documenting sections' prose."""
    with open(readme_path) as f:
        text = f.read()
    names = set()
    for section in _METRIC_SECTIONS:
        # ## sections end at the next ##; ### subsections end at the next
        # heading of EITHER depth ("### X" never matches "^## " — the
        # required trailing space — so ## behavior is unchanged)
        m = re.search(
            rf"^(##|###) {re.escape(section)}$(.*?)(?=^\1 |^## |\Z)",
            text, re.MULTILINE | re.DOTALL)
        if not m:
            continue
        for tok in re.findall(r"`([a-z][a-z0-9_]*)(?:\{[a-z0-9_,]+\})?`",
                              m.group(2)):
            if tok.endswith(_METRIC_SUFFIXES):
                names.add(tok)
    return names


def live_metrics() -> set:
    """Registry names after importing every metric-declaring module the
    server pulls in (parse/ingest/devcache/mapreduce come via the server
    import below; list the frame layer explicitly so the lint cannot go
    vacuous if a route stops importing it)."""
    import h2o3_tpu.frame.ingest     # noqa: F401  parse_* / ingest_* meters
    import h2o3_tpu.frame.codecs     # noqa: F401  chunk_codec_* meters
    import h2o3_tpu.frame.devcache   # noqa: F401  devcache_* meters
    import h2o3_tpu.compute.mapreduce  # noqa: F401  mapreduce_* meters
    import h2o3_tpu.models.framework  # noqa: F401  model_fit_seconds
    import h2o3_tpu.cluster.rpc      # noqa: F401  rpc_* meters
    import h2o3_tpu.cluster.membership  # noqa: F401  cluster_* meters
    import h2o3_tpu.cluster.dkv      # noqa: F401  cluster_dkv_* meters
    import h2o3_tpu.cluster.tasks    # noqa: F401  cluster_tasks_* meters
    import h2o3_tpu.cluster.faults   # noqa: F401  cluster_faults_* meters
    import h2o3_tpu.cluster.frames   # noqa: F401  cluster_chunk_* meters
    import h2o3_tpu.cluster.search   # noqa: F401  cluster_search_* meters
    import h2o3_tpu.models.tree.dist_hist  # noqa: F401  dist_hist_* meters
    import h2o3_tpu.ops.histogram    # noqa: F401  hist_plan_cache meter
    import h2o3_tpu.api.coalesce     # noqa: F401  predict_batch_size
    import h2o3_tpu.cluster.serving  # noqa: F401  serve_* meters
    import h2o3_tpu.rapids.fusion    # noqa: F401  rapids_fusion_* meters
    import h2o3_tpu.rapids.dist_exec  # noqa: F401  rapids_dist_* meters
    import h2o3_tpu.util.ledger      # noqa: F401  ledger_* / slowop_* meters
    import h2o3_tpu.util.flight     # noqa: F401  flight_events_total
    import h2o3_tpu.cluster.health  # noqa: F401  cluster_health_state
    from h2o3_tpu.util import telemetry

    return set(telemetry.REGISTRY.names())


def live_routes():
    """(method, template) pairs off a constructed (not started) server."""
    from h2o3_tpu.api.server import H2OServer

    return H2OServer(port=0).registry.templates()


def collect(root: str, readme_path: str
            ) -> Tuple[List[Tuple[str, str, str, str]], str]:
    """((rule, file, symbol, message) failures, OK-summary string)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    failures: List[Tuple[str, str, str, str]] = []

    routes = live_routes()
    documented = readme_documented_routes(readme_path)
    if not documented:
        failures.append((
            "TDRIFT001", "README.md", "observability-table",
            "README.md has no '## Observability' route table at all"))
    obs = [
        (m, t) for m, t in routes
        if any(t.startswith(p) for p in OBS_PREFIXES)
    ]
    for m, t in sorted(obs):
        if (m, t) not in documented:
            failures.append((
                "TDRIFT001", "README.md", f"{m} {t}",
                f"observability route {m} {t} is registered but missing "
                f"from README.md's Observability table"))
    stale = {
        (m, t) for m, t in documented
        if any(t.startswith(p) for p in OBS_PREFIXES)
        and (m, t) not in set(routes)
    }
    for m, t in sorted(stale):
        failures.append((
            "TDRIFT001", "README.md", f"{m} {t}",
            f"README.md documents {m} {t} but no such route is registered"))

    registered = live_metrics()
    ghost = readme_documented_metrics(readme_path) - registered
    for name in sorted(ghost):
        failures.append((
            "TDRIFT002", "README.md", name,
            f"README.md's {'/'.join(_METRIC_SECTIONS)} sections document "
            f"metric {name!r} but the telemetry registry never declares it"))

    # fusion registry lint: a prim flagged fusible without an emitter would
    # silently fall back on every query (binop/uniop/ifelse kinds), and a
    # fusible prim with no parity test case is an unverified bit-identity
    # claim — both fail the build
    from h2o3_tpu.rapids.prims import FUSIBLE

    emit_kinds = ("binop", "uniop", "ifelse")
    for name, spec in sorted(FUSIBLE.items()):
        if spec.kind in emit_kinds and spec.emit is None:
            failures.append((
                "TDRIFT003", "h2o3_tpu/rapids/prims.py", name,
                f"fusible prim {name!r} (kind={spec.kind}) has no emitter"))
    parity_path = os.path.join(root, "tests", "test_rapids_fusion.py")
    try:
        with open(parity_path) as f:
            parity_src = f.read()
    except OSError:
        parity_src = ""
        failures.append((
            "TDRIFT004", "tests/test_rapids_fusion.py", "missing-file",
            "tests/test_rapids_fusion.py is missing — every fusible prim "
            "needs a fused-vs-interpreted parity case"))
    untested = [
        name for name in sorted(FUSIBLE)
        if f'"{name}"' not in parity_src and f"'{name}'" not in parity_src
    ]
    for name in untested:
        failures.append((
            "TDRIFT004", "tests/test_rapids_fusion.py", name,
            f"fusible prim {name!r} has no parity case in "
            f"tests/test_rapids_fusion.py"))

    from h2o3_tpu.api.registry import algo_map

    train_routes = {t for m, t in routes if m == "POST"}
    if "/3/ModelBuilders/{algo}" not in train_routes:
        failures.append((
            "TDRIFT005", "h2o3_tpu/api/registry.py", "train-route",
            "train route /3/ModelBuilders/{algo} not registered"))
    else:
        # every registry algo name must be a clean single path segment,
        # so the train route's {algo} placeholder can actually match it
        for algo in algo_map():
            if not re.match(r"^[a-z0-9_]+$", algo):
                failures.append((
                    "TDRIFT005", "h2o3_tpu/api/registry.py", algo,
                    f"algo {algo!r} in api/registry.py cannot be a "
                    f"URL path segment of /3/ModelBuilders/{{algo}}"))

    n_doc_metrics = len(readme_documented_metrics(readme_path))
    summary = (
        f"check_telemetry: OK — {len(obs)} observability routes documented, "
        f"{n_doc_metrics} documented metrics registered, "
        f"{len(algo_map())} algos registered, "
        f"{len(FUSIBLE)} fusible prims emitter+parity checked"
    )
    return failures, summary


def run(ctx: Context) -> List[Finding]:
    failures, _summary = collect(ctx.root, ctx.readme_path)
    return [
        Finding(rule=rule, file=file, line=1, symbol=symbol,
                message=message, snippet=symbol)
        for rule, file, symbol, message in failures
    ]
