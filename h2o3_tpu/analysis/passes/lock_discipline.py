"""lock-discipline: no blocking work while a ``threading`` lock is held.

LOCK001 — a blocking operation (RPC ``call``, socket send/recv, jitted
device dispatch, ``map_batches``/shard execution, ``subprocess``,
``time.sleep``, disk I/O) executes while a ``threading.Lock``/``RLock``
is held, either directly inside the ``with`` body or via a local
function call (one module-local call graph, fixpoint-propagated). This
is the PR 11 deadlock class: a lock shared with RPC server threads plus
a dispatch that can block on another node's progress.

LOCK002 — lock-order inversion: two locks are acquired in opposite
orders somewhere in the codebase (global acquisition graph, cycle
detection across modules).

Locks are recognized from ``NAME = threading.Lock()`` module globals,
``self.x = threading.Lock()`` attributes, and — as a heuristic — any
``with`` expression whose name contains "lock". Condition-variable
``cv.wait()`` inside ``with cv:`` is not flagged (it releases the lock).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..astutil import (FuncNode, call_name, dotted_name, index_functions,
                       walk_no_nested_funcs)
from ..core import Context, Finding

RULES = {
    "LOCK001": "blocking operation reachable while a threading lock is held",
    "LOCK002": "lock-order inversion across modules",
}

#: attribute-call suffixes that block on another thread/process/host
BLOCKING_ATTRS = {
    "call", "submit", "request", "sendall", "send", "recv", "recv_into",
    "accept", "connect", "wait", "makedirs", "urlopen", "getaddrinfo",
    "create_connection", "block_until_ready",
}

#: bare/any-position call names that execute shards or touch disk
BLOCKING_NAMES = {
    "map_reduce", "map_batches", "distributed_map_reduce", "_mr_shard_local",
    "save_frame", "load_frame", "urlopen", "open",
}

_SUBPROCESS_FNS = {"run", "check_output", "check_call", "Popen"}


def classify_blocking(call: ast.Call) -> Optional[str]:
    """Human-readable reason if this call is blocking, else None."""
    name = call_name(call)
    if name is None:
        return None
    parts = name.split(".")
    last = parts[-1]
    if name == "time.sleep":
        return "time.sleep"
    if (name.startswith("subprocess.") and last in _SUBPROCESS_FNS) \
            or name == "os.system":
        return f"subprocess ({name})"
    if name.startswith(("jnp.", "jax.numpy.")):
        return f"device dispatch ({name})"
    if name in BLOCKING_NAMES or last in BLOCKING_NAMES:
        return f"blocking call ({name})"
    if len(parts) > 1 and last in BLOCKING_ATTRS:
        return f"blocking call ({name})"
    return None


class _ModuleLocks:
    """Lock inventory + per-function blocking/acquisition facts."""

    def __init__(self, mod) -> None:
        self.mod = mod
        self.funcs = index_functions(mod.tree)
        #: local name ("X" or "Class.X" or "self.X" form) -> global lock id
        self.global_locks: Dict[str, str] = {}
        self.attr_locks: Set[str] = set()   # attribute names, e.g. "_lock"
        self._collect_lock_defs()
        #: simple func name -> blocking reason (after fixpoint)
        self.blocking: Dict[str, str] = {}
        #: simple func name -> lock ids its body acquires (after fixpoint)
        self.acquires: Dict[str, Set[str]] = {}
        self._analyze_functions()

    def _collect_lock_defs(self) -> None:
        for node in ast.walk(self.mod.tree):
            if not isinstance(node, ast.Assign):
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            vname = call_name(value) or ""
            if vname not in ("threading.Lock", "threading.RLock",
                             "Lock", "RLock"):
                continue
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    self.global_locks[tgt.id] = f"{self.mod.rel}:{tgt.id}"
                elif (isinstance(tgt, ast.Attribute)
                      and isinstance(tgt.value, ast.Name)
                      and tgt.value.id == "self"):
                    self.attr_locks.add(tgt.attr)

    def lock_id(self, expr: ast.expr) -> Optional[str]:
        """Global lock id if ``expr`` names a lock, else None."""
        name = dotted_name(expr)
        if not name:
            return None
        if name in self.global_locks:
            return self.global_locks[name]
        if name.startswith("self.") and name[5:] in self.attr_locks:
            return f"{self.mod.rel}:self.{name[5:]}"
        if "lock" in name.lower():
            return f"{self.mod.rel}:{name}"
        return None

    def _analyze_functions(self) -> None:
        simple: Dict[str, List] = {}
        for qual, info in self.funcs.items():
            simple.setdefault(qual.split(".")[-1], []).append(info)
        direct_block: Dict[str, str] = {}
        direct_acq: Dict[str, Set[str]] = {}
        for qual, info in self.funcs.items():
            name = qual.split(".")[-1]
            for node in walk_no_nested_funcs(info.node):
                if isinstance(node, ast.Call):
                    why = classify_blocking(node)
                    if why and name not in direct_block:
                        direct_block[name] = why
                elif isinstance(node, ast.With):
                    for item in node.items:
                        lid = self.lock_id(item.context_expr)
                        if lid:
                            direct_acq.setdefault(name, set()).add(lid)
        # fixpoint over the module-local call graph
        self.blocking = dict(direct_block)
        self.acquires = {k: set(v) for k, v in direct_acq.items()}
        changed = True
        while changed:
            changed = False
            for qual, info in self.funcs.items():
                name = qual.split(".")[-1]
                for callee in info.local_calls:
                    if callee in self.blocking and name not in self.blocking:
                        self.blocking[name] = (
                            f"{self.blocking[callee]} via {callee}()")
                        changed = True
                    for lid in self.acquires.get(callee, ()):
                        acq = self.acquires.setdefault(name, set())
                        if lid not in acq:
                            acq.add(lid)
                            changed = True


def _finding(mod, node: ast.AST, rule: str, symbol: str, msg: str) -> Finding:
    return Finding(rule=rule, file=mod.rel, line=node.lineno, symbol=symbol,
                   message=msg, snippet=mod.line_text(node.lineno))


def run(ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    #: (held lock id, acquired lock id) -> first site (mod, node, symbol)
    edges: Dict[Tuple[str, str], Tuple[object, ast.AST, str]] = {}

    # fast gate: a module whose source never says "lock" has no lock
    # regions, no edges, and nothing to report — skip the AST work
    lockful = [m for m in ctx.all_modules if "lock" in m.source.lower()]
    infos = {m.rel: _ModuleLocks(m) for m in lockful}

    analyzed = {m.rel for m in ctx.modules}
    for mod in lockful:
        ml = infos[mod.rel]
        for qual, func in ml.funcs.items():
            for node in walk_no_nested_funcs(func.node):
                if not isinstance(node, ast.With):
                    continue
                held = [(ml.lock_id(i.context_expr),
                         dotted_name(i.context_expr) or "")
                        for i in node.items]
                held = [(lid, nm) for lid, nm in held if lid]
                if not held:
                    continue
                _scan_region(mod, ml, node, held, qual, findings,
                             edges, report=mod.rel in analyzed)

    # LOCK002: cycles in the global lock-acquisition graph
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    for (a, b), (mod, node, symbol) in sorted(
            edges.items(), key=lambda kv: (kv[1][0].rel, kv[1][1].lineno)):
        if _reachable(graph, b, a) and mod.rel in analyzed:
            findings.append(_finding(
                mod, node, "LOCK002", symbol,
                f"lock-order inversion: acquires {b.split(':')[-1]!r} while "
                f"holding {a.split(':')[-1]!r}, but the opposite order also "
                f"exists in the codebase"))
    return findings


def _scan_region(mod, ml: _ModuleLocks, with_node: ast.With,
                 held: List[Tuple[str, str]], symbol: str,
                 findings: List[Finding], edges: Dict, report: bool) -> None:
    held_ids = [lid for lid, _ in held]
    held_names = {nm for _, nm in held}
    stack = []
    for stmt in with_node.body:
        stack.append(stmt)
    while stack:
        node = stack.pop()
        if isinstance(node, FuncNode) or isinstance(node, ast.Lambda):
            continue
        if isinstance(node, ast.With):
            for item in node.items:
                lid = ml.lock_id(item.context_expr)
                if lid:
                    for h in held_ids:
                        if h != lid:
                            edges.setdefault((h, lid), (mod, node, symbol))
        if isinstance(node, ast.Call):
            name = call_name(node) or ""
            # cv.wait() inside `with cv:` releases the lock — not blocking
            owner = name.rsplit(".", 1)[0] if "." in name else ""
            if name.endswith(".wait") and owner in held_names:
                stack.extend(ast.iter_child_nodes(node))
                continue
            why = classify_blocking(node)
            if why is None:
                base = name.split(".")
                callee = base[-1] if (len(base) == 2 and base[0] == "self") \
                    else (name if "." not in name else None)
                if callee and callee in ml.blocking:
                    why = f"{ml.blocking[callee]} (via local call "\
                          f"{callee}())"
            if why and report:
                lock_desc = ", ".join(
                    lid.split(":")[-1] for lid in held_ids)
                findings.append(_finding(
                    mod, node, "LOCK001", symbol,
                    f"{why} while holding {lock_desc}"))
        stack.extend(ast.iter_child_nodes(node))


def _reachable(graph: Dict[str, Set[str]], src: str, dst: str) -> bool:
    seen = set()
    stack = [src]
    while stack:
        n = stack.pop()
        if n == dst:
            return True
        if n in seen:
            continue
        seen.add(n)
        stack.extend(graph.get(n, ()))
    return False
