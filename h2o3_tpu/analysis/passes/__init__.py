"""Analyzer passes. Each module exposes ``RULES`` (id -> summary) and
``run(ctx) -> List[Finding]``; registration lives in
``h2o3_tpu.analysis.core.default_passes``."""
