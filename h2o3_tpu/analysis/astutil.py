"""Shared AST helpers for the analyzer passes.

Everything here is intentionally syntactic: no imports are executed, no
types are inferred. Passes trade a little precision for a framework that
runs in milliseconds over the whole repo.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)
ScopeNode = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)


def dotted_name(node: ast.expr) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    """Dotted name of a call's target (``self.f`` -> ``self.f``)."""
    return dotted_name(call.func)


@dataclass
class FuncInfo:
    """One function/method definition with its local call fan-out."""

    qualname: str                 #: ``Class.method`` or ``func``
    node: ast.AST
    class_name: Optional[str] = None
    #: simple names this function calls (bare ``f()`` and ``self.m()``)
    local_calls: Set[str] = field(default_factory=set)


def walk_no_nested_funcs(node: ast.AST) -> Iterator[ast.AST]:
    """Yield descendants, NOT descending into nested function/lambda
    definitions (their bodies run later, not at the yield site)."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if isinstance(child, FuncNode) or isinstance(child, ast.Lambda):
            continue
        stack.extend(ast.iter_child_nodes(child))


def index_functions(tree: ast.Module) -> Dict[str, FuncInfo]:
    """qualname -> FuncInfo for every def in the module (methods use
    ``Class.method``; nested defs use ``outer.<locals>.inner``)."""
    out: Dict[str, FuncInfo] = {}

    def visit(node: ast.AST, prefix: str, class_name: Optional[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.", None)
            elif isinstance(child, FuncNode):
                qual = f"{prefix}{child.name}"
                info = FuncInfo(qualname=qual, node=child,
                                class_name=_enclosing_class(prefix))
                for sub in walk_no_nested_funcs(child):
                    if isinstance(sub, ast.Call):
                        name = call_name(sub)
                        if name is None:
                            continue
                        if name.startswith("self."):
                            info.local_calls.add(name[len("self."):]
                                                 .split(".")[0])
                        elif "." not in name:
                            info.local_calls.add(name)
                out[qual] = info
                visit(child, f"{qual}.<locals>.", None)
            else:
                visit(child, prefix, class_name)

    def _enclosing_class(prefix: str) -> Optional[str]:
        parts = [p for p in prefix.split(".") if p and p != "<locals>"]
        return parts[-1] if parts else None

    visit(tree, "", None)
    return out


def enclosing_symbol(tree: ast.Module, lineno: int) -> str:
    """Qualname of the innermost def/class containing ``lineno``."""
    best = ""
    best_span = None

    def visit(node: ast.AST, prefix: str) -> None:
        nonlocal best, best_span
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ScopeNode):
                start = child.lineno
                end = getattr(child, "end_lineno", start) or start
                qual = f"{prefix}{child.name}"
                if start <= lineno <= end:
                    span = end - start
                    if best_span is None or span <= best_span:
                        best, best_span = qual, span
                    visit(child, f"{qual}.")
            else:
                visit(child, prefix)

    visit(tree, "")
    return best


def resolve_local_callable(call: ast.Call, module_tree: ast.Module
                           ) -> Optional[ast.AST]:
    """If an argument position holds a Name bound to a module-level def
    or lambda, return that def's node. Used to chase ``jit(fn)`` to fn."""
    return None  # resolution is done per-pass with the name tables below


def module_level_defs(tree: ast.Module) -> Dict[str, ast.AST]:
    """name -> def/lambda node for module-level functions and
    ``name = lambda ...`` bindings."""
    out: Dict[str, ast.AST] = {}
    for node in tree.body:
        if isinstance(node, FuncNode):
            out[node.name] = node
        elif isinstance(node, ast.Assign) and isinstance(node.value,
                                                         ast.Lambda):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out[tgt.id] = node.value
    return out


def with_items_of(node: ast.With) -> List[Tuple[ast.expr, str]]:
    """(context-expr, source-ish dotted name or '') per with-item."""
    out = []
    for item in node.items:
        name = dotted_name(item.context_expr)
        if name is None and isinstance(item.context_expr, ast.Call):
            name = call_name(item.context_expr) or ""
        out.append((item.context_expr, name or ""))
    return out
