"""Project-specific static analyzer for h2o3_tpu invariants.

Six passes over the repo's own sources, driven by ``scripts/analyze.py``
and run in tier-1 (see ``tests/test_analysis.py``):

- **lock-discipline** (LOCK001/LOCK002) — no blocking work under a
  ``threading`` lock; no lock-order inversions. The PR 11 deadlock class.
- **tracer-purity** (TRACE001) — jitted/shard-mapped/fusion-emit
  functions stay side-effect free.
- **seeded-determinism** (SEED001–SEED003) — chaos/retry decisions draw
  only from plan-derived PRNGs, never the global RNG or the wall clock.
- **knob-registry** (KNOB001/KNOB002) — ``H2O3_TPU_*`` env knobs and
  README.md stay in sync, both directions.
- **rpc-payload** (ROUTE001/ROUTE002) — nothing unroutable is handed to
  DKV puts or RPC payloads at the call site.
- **telemetry-drift** (TDRIFT001–TDRIFT005) — observability docs match
  the live route/metric/prim registries (absorbed
  ``scripts/check_telemetry.py``).

Importing this package (and every AST pass) pulls no runtime modules —
no jax, no server — so incremental ``--changed-only`` runs stay fast.
Only the telemetry-drift pass imports the runtime, lazily.
"""

from .core import (Context, Finding, Module, analyze, analyze_source,
                   default_passes, load_baseline, save_baseline,
                   split_baselined)

__all__ = [
    "Context",
    "Finding",
    "Module",
    "analyze",
    "analyze_source",
    "default_passes",
    "load_baseline",
    "save_baseline",
    "split_baselined",
]
