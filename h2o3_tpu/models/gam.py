"""GAM — generalized additive models: spline basis expansion + GLM core.

Reference: ``hex/gam/GAM.java:47`` — each ``gam_column`` is expanded into a
cubic-regression-spline basis block ("gamified" columns, knots at quantiles,
``hex/gam/GamSplines/``), the blocks are Z-transformed for identifiability
(centered against the intercept), and the penalized IRLSM solves
``(X'WX + Σ λⱼ Sⱼ) β = X'Wz`` with the smoothing penalty Sⱼ = DᵀB⁻¹D from the
natural-cubic-spline second-derivative quadratic form.

TPU-native: the basis expansion is a host-side construction (tiny, once); the
per-iteration Gram X'WX remains the one sharded matmul from the GLM core
(h2o3_tpu/models/glm.py), with the penalty added to the host-side solve.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.data_info import build_data_info, expand_matrix, response_vector
from h2o3_tpu.models.framework import Model, ModelBuilder
from h2o3_tpu.models.glm import (
    GLMParameters,
    _aic,
    _gram,
    _link_deriv,
    _link_of_mean,
    _linkinv,
    _solve_admm,
    _solve_ridge,
    _variance,
    deviance,
)
from h2o3_tpu.parallel.mesh import default_mesh, pad_rows, shard_rows


@dataclass
class GAMParameters(GLMParameters):
    gam_columns: List[str] = field(default_factory=list)
    num_knots: int = 10
    scale: float = 1.0  # smoothing λ (per gam column; reference: scale array)
    bs: int = 0  # 0 = cubic regression spline (the reference default)


# ---------------------------------------------------------------------------
# cubic regression spline machinery (hex/gam/GamSplines/CubicRegressionSplines)


def cr_matrices(knots: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Natural-cubic-spline D ((K-2)×K) and B ((K-2)×(K-2)) matrices.
    γ = B⁻¹D β maps knot values to interior second derivatives; the curvature
    penalty is S = DᵀB⁻¹D."""
    h = np.diff(knots)
    K = len(knots)
    D = np.zeros((K - 2, K))
    B = np.zeros((K - 2, K - 2))
    for i in range(K - 2):
        D[i, i] = 1.0 / h[i]
        D[i, i + 1] = -1.0 / h[i] - 1.0 / h[i + 1]
        D[i, i + 2] = 1.0 / h[i + 1]
        B[i, i] = (h[i] + h[i + 1]) / 3.0
        if i + 1 < K - 2:
            B[i, i + 1] = B[i + 1, i] = h[i + 1] / 6.0
    return D, B


def cr_penalty(knots: np.ndarray) -> np.ndarray:
    D, B = cr_matrices(knots)
    return D.T @ np.linalg.solve(B, D)


def cr_basis(x: np.ndarray, knots: np.ndarray) -> np.ndarray:
    """[N, K] cardinal natural-cubic-spline basis: row · β evaluates the
    spline with values β at the knots (linear extrapolation outside)."""
    D, B = cr_matrices(knots)
    F = np.vstack([np.zeros(len(knots)), np.linalg.solve(B, D), np.zeros(len(knots))])
    h = np.diff(knots)
    K = len(knots)
    xc = np.clip(x, knots[0], knots[-1])
    j = np.clip(np.searchsorted(knots, xc, side="right") - 1, 0, K - 2)
    hj = h[j]
    kl, kr = knots[j], knots[j + 1]
    am = (kr - xc) / hj
    ap = (xc - kl) / hj
    cm = ((kr - xc) ** 3 / hj - hj * (kr - xc)) / 6.0
    cp = ((xc - kl) ** 3 / hj - hj * (xc - kl)) / 6.0
    n = len(x)
    basis = np.zeros((n, K))
    rows = np.arange(n)
    basis[rows, j] += am
    basis[rows, j + 1] += ap
    basis += cm[:, None] * F[j] + cp[:, None] * F[j + 1]
    # linear extrapolation beyond the boundary knots (natural spline slope)
    lo, hi = x < knots[0], x > knots[-1]
    if lo.any():
        slope = (cr_basis(np.array([knots[0] + 1e-6]), knots) - cr_basis(np.array([knots[0]]), knots)) / 1e-6
        basis[lo] = cr_basis(np.array([knots[0]]), knots) + (x[lo] - knots[0])[:, None] * slope
    if hi.any():
        slope = (cr_basis(np.array([knots[-1]]), knots) - cr_basis(np.array([knots[-1] - 1e-6]), knots)) / 1e-6
        basis[hi] = cr_basis(np.array([knots[-1]]), knots) + (x[hi] - knots[-1])[:, None] * slope
    return basis


@dataclass
class GamSpec:
    column: str
    knots: np.ndarray
    Z: np.ndarray  # [K, K-1] identifiability transform (⊥ training column means)
    penalty: np.ndarray  # [K-1, K-1] Zᵀ S Z
    na_fill: float

    def expand(self, x: np.ndarray) -> np.ndarray:
        x = np.where(np.isnan(x), self.na_fill, x)
        return cr_basis(x, self.knots) @ self.Z


def _make_spec(name: str, x: np.ndarray, num_knots: int) -> GamSpec:
    ok = ~np.isnan(x)
    xs = x[ok]
    qs = np.quantile(xs, np.linspace(0, 1, num_knots))
    knots = np.unique(qs)
    if len(knots) < 3:
        raise ValueError(f"gam column {name!r} has too few distinct values for splines")
    basis = cr_basis(xs, knots)
    m = basis.mean(axis=0)
    # Z: orthonormal basis of the null space of mᵀ (H2O's centering transform
    # — gamified columns stay orthogonal to the intercept)
    _, _, Vt = np.linalg.svd(m[None, :], full_matrices=True)
    Z = Vt[1:].T  # [K, K-1]
    S = cr_penalty(knots)
    return GamSpec(name, knots, Z, Z.T @ S @ Z, float(np.median(xs)))


class GAMModel(Model):
    algo_name = "gam"

    def __init__(self, params: GAMParameters, data_info) -> None:
        super().__init__(params, data_info)
        self.specs: List[GamSpec] = []
        self.beta: Optional[np.ndarray] = None  # [P_lin + Σ(Kⱼ-1) + 1]
        self.coefficients: Dict[str, float] = {}
        self.null_deviance: float = np.nan
        self.residual_deviance: float = np.nan
        self.aic: float = np.nan
        self.iterations: int = 0

    def _design(self, frame: Frame) -> np.ndarray:
        Xl, _ = expand_matrix(self.data_info, frame, dtype=np.float64)
        blocks = [Xl]
        for s in self.specs:
            blocks.append(s.expand(frame.col(s.column).numeric_view().astype(np.float64)))
        return np.concatenate(blocks, axis=1)

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        p: GAMParameters = self.params
        X = self._design(frame)
        eta = X @ self.beta[:-1] + self.beta[-1]
        mu = _linkinv(p.actual_link(), eta, p)
        if p.family in ("binomial", "quasibinomial"):
            return np.stack([1 - mu, mu], axis=1)
        return mu


class GAM(ModelBuilder):

    SUPPORTED_COMMON = frozenset({"weights_column"})
    algo_name = "gam"

    def __init__(self, params: Optional[GAMParameters] = None, **kw) -> None:
        super().__init__(params or GAMParameters(**kw))

    def _validate(self, frame: Frame) -> None:
        super()._validate(frame)
        if not self.params.gam_columns:
            raise ValueError("GAM requires gam_columns")

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> GAMModel:
        p: GAMParameters = self.params
        link = p.actual_link()
        if p.family in ("binomial", "quasibinomial"):
            ycol = frame.col(p.response_column)
            if not ycol.is_categorical():
                frame = frame.add_column(ycol.as_factor())
        # gam columns are modeled through their basis only (GAM.java removes
        # them from the linear predictors)
        info = build_data_info(
            frame,
            y=p.response_column,
            ignored=list(p.ignored_columns) + list(p.gam_columns),
            standardize=p.standardize,
            missing_values_handling=p.missing_values_handling,
        )
        model = GAMModel(p, info)
        model.specs = [
            _make_spec(c, frame.col(c).numeric_view().astype(np.float64), p.num_knots)
            for c in p.gam_columns
        ]

        X = model._design(frame)
        y = response_vector(info, frame)
        obs_w = (
            frame.col(p.weights_column).numeric_view().astype(np.float64)
            if p.weights_column else np.ones(frame.nrows)
        )
        keep = ~(np.isnan(y) | np.isnan(X).any(axis=1))
        X, y, obs_w = X[keep], y[keep], obs_w[keep]
        n, pc = X.shape
        n_lin = info.n_coefs

        # block-diagonal smoothing penalty, zero on linear coefs + intercept
        Lam = np.zeros((pc + 1, pc + 1))
        off = n_lin
        for s in model.specs:
            kz = s.penalty.shape[0]
            Lam[off : off + kz, off : off + kz] = p.scale * s.penalty
            off += kz

        mesh = default_mesh()
        Xi = np.concatenate([X, np.ones((n, 1))], axis=1).astype(np.float32)
        Xd, _ = shard_rows(Xi, mesh)
        pad = lambda a: pad_rows(a, mesh.devices.size)[0]

        wsum = float(obs_w.sum())
        ybar = float((obs_w * y).sum() / wsum)
        beta = np.zeros(pc + 1)
        beta[-1] = _link_of_mean(link, ybar, p)
        # elastic net like GLM: l1 via ADMM soft-threshold, l2 via ridge
        l1 = p.lambda_ * p.alpha
        l2 = p.lambda_ * (1 - p.alpha)

        prev_obj = np.inf
        for it in range(p.max_iterations):
            eta = X @ beta[:-1] + beta[-1]
            mu = _linkinv(link, eta, p)
            d = _link_deriv(link, mu, p)
            v = _variance(p.family, mu, p)
            w = obs_w / np.maximum(v * d * d, 1e-12)
            wz = eta + (y - mu) * d

            G, q = _gram(Xd, pad(wz), pad(w))
            Gp = G / wsum + Lam / wsum  # smoothing penalty folded into Gram
            if l1 > 0:
                beta_new = _solve_admm(Gp, q / wsum, l1, l2, free=1)
            else:
                beta_new = _solve_ridge(Gp, q / wsum, l2, free=1)

            mu_new = _linkinv(link, X @ beta_new[:-1] + beta_new[-1], p)
            dev = float((obs_w * deviance(p.family, y, mu_new, p)).sum())
            bp = beta_new[:-1]  # intercept unpenalized
            obj = (
                dev / (2 * wsum)
                + float(beta_new @ Lam @ beta_new) / (2 * wsum)
                + l1 * float(np.abs(bp).sum())
                + 0.5 * l2 * float(bp @ bp)
            )
            delta = np.max(np.abs(beta_new - beta))
            beta = beta_new
            model.iterations = it + 1
            if delta < p.beta_epsilon or abs(prev_obj - obj) < p.objective_epsilon * max(abs(prev_obj), 1.0):
                break
            prev_obj = obj

        model.beta = beta
        names = list(info.coef_names)
        for s in model.specs:
            names += [f"{s.column}_cr_{i}" for i in range(s.penalty.shape[0])]
        model.coefficients = dict(zip(names, beta[:-1].tolist()))
        model.coefficients["Intercept"] = float(beta[-1])

        mu = _linkinv(link, X @ beta[:-1] + beta[-1], p)
        model.residual_deviance = float((obs_w * deviance(p.family, y, mu, p)).sum())
        model.null_deviance = float(
            (obs_w * deviance(p.family, y, np.full_like(y, ybar), p)).sum()
        )
        rank = pc + 1
        model.aic = _aic(p.family, y, mu, obs_w, model.residual_deviance, rank)
        model.training_metrics = model.model_performance(frame)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model
