"""GAM — generalized additive models: spline basis expansion + GLM core.

Reference: ``hex/gam/GAM.java:47`` — each ``gam_column`` is expanded into a
cubic-regression-spline basis block ("gamified" columns, knots at quantiles,
``hex/gam/GamSplines/``), the blocks are Z-transformed for identifiability
(centered against the intercept), and the penalized IRLSM solves
``(X'WX + Σ λⱼ Sⱼ) β = X'Wz`` with the smoothing penalty Sⱼ = DᵀB⁻¹D from the
natural-cubic-spline second-derivative quadratic form.

TPU-native: the basis expansion is a host-side construction (tiny, once); the
per-iteration Gram X'WX remains the one sharded matmul from the GLM core
(h2o3_tpu/models/glm.py), with the penalty added to the host-side solve.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.data_info import build_data_info, expand_matrix, response_vector
from h2o3_tpu.models.framework import Model, ModelBuilder
from h2o3_tpu.models.glm import (
    GLMParameters,
    _aic,
    _gram,
    _link_deriv,
    _link_of_mean,
    _linkinv,
    _solve_admm,
    _solve_ridge,
    _variance,
    deviance,
)
from h2o3_tpu.parallel.mesh import default_mesh, pad_rows, shard_rows


@dataclass
class GAMParameters(GLMParameters):
    gam_columns: List[str] = field(default_factory=list)
    #: knots per gam column — int (shared) or list aligned with gam_columns
    num_knots: object = 10
    #: smoothing λ per gam column — float (shared) or aligned list
    scale: object = 1.0
    #: spline family per column (GAMParametersV3 bs codes): 0 = cubic
    #: regression spline, 1 = thin-plate, 2 = monotone I-splines,
    #: 3 = M-splines; int (shared) or aligned list
    bs: object = 0
    #: explicit knot locations per gam column (reference knot_ids frames);
    #: None = quantile placement
    knots: Optional[List[Optional[List[float]]]] = None
    #: I-spline coefficients constrained >= 0 (monotone non-decreasing)
    splines_non_negative: bool = True


# ---------------------------------------------------------------------------
# cubic regression spline machinery (hex/gam/GamSplines/CubicRegressionSplines)


def cr_matrices(knots: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Natural-cubic-spline D ((K-2)×K) and B ((K-2)×(K-2)) matrices.
    γ = B⁻¹D β maps knot values to interior second derivatives; the curvature
    penalty is S = DᵀB⁻¹D."""
    h = np.diff(knots)
    K = len(knots)
    D = np.zeros((K - 2, K))
    B = np.zeros((K - 2, K - 2))
    for i in range(K - 2):
        D[i, i] = 1.0 / h[i]
        D[i, i + 1] = -1.0 / h[i] - 1.0 / h[i + 1]
        D[i, i + 2] = 1.0 / h[i + 1]
        B[i, i] = (h[i] + h[i + 1]) / 3.0
        if i + 1 < K - 2:
            B[i, i + 1] = B[i + 1, i] = h[i + 1] / 6.0
    return D, B


def cr_penalty(knots: np.ndarray) -> np.ndarray:
    D, B = cr_matrices(knots)
    return D.T @ np.linalg.solve(B, D)


def cr_basis(x: np.ndarray, knots: np.ndarray) -> np.ndarray:
    """[N, K] cardinal natural-cubic-spline basis: row · β evaluates the
    spline with values β at the knots (linear extrapolation outside)."""
    D, B = cr_matrices(knots)
    F = np.vstack([np.zeros(len(knots)), np.linalg.solve(B, D), np.zeros(len(knots))])
    h = np.diff(knots)
    K = len(knots)
    xc = np.clip(x, knots[0], knots[-1])
    j = np.clip(np.searchsorted(knots, xc, side="right") - 1, 0, K - 2)
    hj = h[j]
    kl, kr = knots[j], knots[j + 1]
    am = (kr - xc) / hj
    ap = (xc - kl) / hj
    cm = ((kr - xc) ** 3 / hj - hj * (kr - xc)) / 6.0
    cp = ((xc - kl) ** 3 / hj - hj * (xc - kl)) / 6.0
    n = len(x)
    basis = np.zeros((n, K))
    rows = np.arange(n)
    basis[rows, j] += am
    basis[rows, j + 1] += ap
    basis += cm[:, None] * F[j] + cp[:, None] * F[j + 1]
    # linear extrapolation beyond the boundary knots (natural spline slope)
    lo, hi = x < knots[0], x > knots[-1]
    if lo.any():
        slope = (cr_basis(np.array([knots[0] + 1e-6]), knots) - cr_basis(np.array([knots[0]]), knots)) / 1e-6
        basis[lo] = cr_basis(np.array([knots[0]]), knots) + (x[lo] - knots[0])[:, None] * slope
    if hi.any():
        slope = (cr_basis(np.array([knots[-1]]), knots) - cr_basis(np.array([knots[-1] - 1e-6]), knots)) / 1e-6
        basis[hi] = cr_basis(np.array([knots[-1]]), knots) + (x[hi] - knots[-1])[:, None] * slope
    return basis


# ---------------------------------------------------------------------------
# other spline families (hex/gam/GamSplines: ThinPlate*, NBSplinesTypeI/II)


def tp_basis(x: np.ndarray, knots: np.ndarray) -> np.ndarray:
    """1-D thin-plate basis: {x, |x-k|³ per knot} (the polynomial-plus-
    radial construction of ThinPlateRegressionUtils, d=1 → η(r)=r³)."""
    r = np.abs(x[:, None] - knots[None, :]) ** 3
    return np.concatenate([x[:, None], r], axis=1)


def tp_penalty(knots: np.ndarray) -> np.ndarray:
    """Bending-energy quadratic form on the radial coefficients; the
    linear term is unpenalized (thin-plate null space)."""
    K = len(knots)
    E = np.abs(knots[:, None] - knots[None, :]) ** 3
    S = np.zeros((K + 1, K + 1))
    S[1:, 1:] = E + 1e-8 * np.eye(K)  # PSD guard
    return S


def tp_m(d: int) -> int:
    """(m-1) = max polynomial degree of the TP null space:
    m = floor((d+1)/2)+1 (ThinPlateRegressionUtils.calculatem)."""
    return int(np.floor((d + 1) * 0.5)) + 1


def tp_poly_exponents(d: int, m: int) -> List[Tuple[int, ...]]:
    """All monomial exponent tuples with total degree < m, the all-zeros
    (constant) term first — M = C(d+m-1, d) of them
    (ThinPlateRegressionUtils.findPolyBasis)."""
    from itertools import product

    combos = [t for t in product(range(m), repeat=d) if sum(t) < m]
    combos.sort(key=lambda t: (sum(t), t))
    return combos


def tp_const(m: int, d: int) -> float:
    """Radial-basis scale (GamUtilsThinPlateRegression.calTPConstantTerm)."""
    from math import factorial, pi

    if d % 2 == 0:
        return ((-1.0) ** (m + 1 + d / 2.0)
                / (2.0 ** (2 * m - 1) * pi ** (d / 2.0)
                   * factorial(m - 1) * factorial(m - d // 2)))
    return ((-1.0) ** m * m
            / (factorial(2 * m) * pi ** ((d - 1) / 2.0)))


def tp_distance(X: np.ndarray, knots: np.ndarray, m: int) -> np.ndarray:
    """[N, K] radial terms φ(|x−kᵢ|) exactly as the reference scores them
    (GamUtilsThinPlateRegression.calculateDistance): const·r^(2m−d),
    and for even d an extra ·log(r^(2m−d)) where the power is nonzero."""
    d = knots.shape[1]
    # Gram identity keeps temporaries at [N, K] (an [N, K, d] broadcast
    # diff would dominate peak memory when scoring large frames)
    r2 = ((X * X).sum(axis=1)[:, None] + (knots * knots).sum(axis=1)[None]
          - 2.0 * X @ knots.T)
    r = np.sqrt(np.maximum(r2, 0.0))
    dist = r ** (2 * m - d)
    out = tp_const(m, d) * dist
    if d % 2 == 0:
        with np.errstate(divide="ignore"):
            lg = np.where(dist != 0, np.log(np.maximum(dist, 1e-300)), 0.0)
        out = out * lg
    return out


def tp_polynomials(X: np.ndarray,
                   expo: List[Tuple[int, ...]]) -> np.ndarray:
    """[N, M] monomial basis (calculatePolynomialBasis)."""
    out = np.ones((X.shape[0], len(expo)))
    for j, t in enumerate(expo):
        for p, e in enumerate(t):
            if e:
                out[:, j] *= X[:, p] ** e
    return out


def _bspline_knots(knots: np.ndarray, degree: int) -> np.ndarray:
    return np.concatenate([
        np.repeat(knots[0], degree), knots, np.repeat(knots[-1], degree)
    ])


def m_basis(x: np.ndarray, knots: np.ndarray, degree: int = 3) -> np.ndarray:
    """M-spline (normalized B-spline) basis via scipy (NBSplinesTypeII)."""
    from scipy.interpolate import BSpline

    t = _bspline_knots(knots, degree)
    xc = np.clip(x, knots[0], knots[-1])
    dm = BSpline.design_matrix(xc, t, degree, extrapolate=False).toarray()
    return dm


def m_penalty(n_basis: int) -> np.ndarray:
    """Second-difference P-spline penalty D₂ᵀD₂ (Eilers/Marx — the
    curvature surrogate the reference's NBSpline penalty plays)."""
    D = np.diff(np.eye(n_basis), n=2, axis=0)
    return D.T @ D


def i_basis(x: np.ndarray, knots: np.ndarray, degree: int = 3) -> np.ndarray:
    """I-spline basis (NBSplinesTypeI): running integrals of M-splines —
    each basis function is monotone non-decreasing 0→1, so non-negative
    coefficients give a monotone smooth."""
    from scipy.interpolate import BSpline

    t = _bspline_knots(knots, degree + 1)
    xc = np.clip(x, knots[0], knots[-1])
    dm = BSpline.design_matrix(xc, t, degree + 1, extrapolate=False).toarray()
    # I_j(x) = sum of higher-order B-splines from j+1 on (de Boor)
    return np.cumsum(dm[:, ::-1], axis=1)[:, ::-1][:, 1:]


@dataclass
class TpSpec:
    """Multi-predictor thin-plate smoother (ThinPlateDistanceWithKnots +
    ThinPlatePolynomialWithKnots): d-dim radial distances to K knot
    points, projected through zCS (the null space of the knot-polynomial
    matrix, the T'δ=0 constraint), concatenated with the M monomials of
    total degree < m, then centered through Z like every other smoother.
    Scoring math matches GamUtilsThinPlateRegression exactly."""

    columns: List[str]
    knots: np.ndarray          # [K, d] knot points (data rows)
    zcs: np.ndarray            # [K, K-M]
    Z: np.ndarray              # [K, K-1] centering transform
    penalty: np.ndarray        # [K-1, K-1] (bending energy through Z)
    na_fill: np.ndarray        # [d] per-predictor training medians
    m: int
    kind: int = 1
    nonneg: bool = False

    @property
    def column(self) -> str:  # display/coefficient-name anchor
        return "_".join(self.columns)

    @property
    def expo(self) -> List[Tuple[int, ...]]:
        return tp_poly_exponents(self.knots.shape[1], self.m)

    def raw_basis(self, X: np.ndarray) -> np.ndarray:
        dist = tp_distance(X, self.knots, self.m) @ self.zcs
        poly = tp_polynomials(X, self.expo)
        return np.concatenate([dist, poly], axis=1)

    def stack(self, frame: Frame) -> np.ndarray:
        """[N, d] raw predictor matrix — the ONE extraction both
        training and scoring use (train/predict skew guard)."""
        return _tp_stack(frame, self.columns)

    def expand(self, X: np.ndarray) -> np.ndarray:
        X = np.where(np.isnan(X), self.na_fill[None, :], X)
        return self.raw_basis(X) @ self.Z


def _tp_stack(frame: Frame, columns) -> np.ndarray:
    return np.column_stack([
        frame.col(c).numeric_view().astype(np.float64) for c in columns])


def _make_tp_spec(columns: List[str], X: np.ndarray,
                  num_knots: int) -> TpSpec:
    """Joint thin-plate smoother over ≥2 predictors. Knots are actual
    data rows, evenly spaced along the first predictor's sort order (the
    reference also takes knot points from the data)."""
    d = X.shape[1]
    ok = ~np.isnan(X).any(axis=1)
    Xs = X[ok]
    m = tp_m(d)
    expo = tp_poly_exponents(d, m)
    M = len(expo)
    if num_knots <= M + 1:
        raise ValueError(
            f"thin-plate smoother over {d} predictors needs num_knots > "
            f"{M + 1} (polynomial null space has {M} terms)")
    if len(Xs) < num_knots:
        raise ValueError("not enough complete rows for the requested "
                         "number of thin-plate knots")
    order = np.argsort(Xs[:, 0], kind="stable")
    pick = order[np.linspace(0, len(order) - 1, num_knots).astype(int)]
    knots = np.unique(Xs[pick], axis=0)
    K = len(knots)
    if K <= M + 1:
        raise ValueError("duplicate rows collapsed the thin-plate knots; "
                         "reduce num_knots or dedupe the predictors")
    # zCS: null space of T' where T[i,j] = poly_j(knot_i)
    T = tp_polynomials(knots, expo)
    Q, _ = np.linalg.qr(T, mode="complete")
    zcs = Q[:, M:]
    # bending energy on the constrained distance coefficients
    E = tp_distance(knots, knots, m)
    S_dist = zcs.T @ E @ zcs
    S_dist = (S_dist + S_dist.T) / 2.0
    # PSD guard: the projected radial form can have tiny negative
    # eigenvalues from float error
    w = np.linalg.eigvalsh(S_dist)
    if w.min() < 0:
        S_dist = S_dist - (w.min() - 1e-10) * np.eye(len(S_dist))
    S_raw = np.zeros((K, K))
    S_raw[:K - M, :K - M] = S_dist
    na_fill = np.median(Xs, axis=0)
    # centering against the intercept, same construction as _make_spec
    spec = TpSpec(columns=list(columns), knots=knots, zcs=zcs,
                  Z=np.empty(0), penalty=np.empty(0), na_fill=na_fill,
                  m=m)
    basis = spec.raw_basis(Xs)
    mean = basis.mean(axis=0)
    _, _, Vt = np.linalg.svd(mean[None, :], full_matrices=True)
    Z = Vt[1:].T
    spec.Z = Z
    spec.penalty = Z.T @ S_raw @ Z
    return spec


@dataclass
class GamSpec:
    column: str
    knots: np.ndarray
    Z: Optional[np.ndarray]  # identifiability transform (None: raw basis)
    penalty: np.ndarray
    na_fill: float
    kind: int = 0  # bs code
    nonneg: bool = False  # coefficients constrained >= 0 (monotone)

    def raw_basis(self, x: np.ndarray) -> np.ndarray:
        if self.kind == 1:
            return tp_basis(x, self.knots)
        if self.kind == 2:
            return i_basis(x, self.knots)
        if self.kind == 3:
            return m_basis(x, self.knots)
        return cr_basis(x, self.knots)

    def expand(self, x: np.ndarray) -> np.ndarray:
        x = np.where(np.isnan(x), self.na_fill, x)
        b = self.raw_basis(x)
        return b @ self.Z if self.Z is not None else b


def _make_spec(name: str, x: np.ndarray, num_knots: int, bs: int = 0,
               user_knots: Optional[List[float]] = None,
               nonneg: bool = True) -> GamSpec:
    ok = ~np.isnan(x)
    xs = x[ok]
    if user_knots is not None:
        knots = np.unique(np.asarray(user_knots, np.float64))
    else:
        qs = np.quantile(xs, np.linspace(0, 1, num_knots))
        knots = np.unique(qs)
    if len(knots) < 3:
        raise ValueError(f"gam column {name!r} has too few distinct values for splines")
    na_fill = float(np.median(xs))
    if bs == 1:
        S = tp_penalty(knots)
        basis = tp_basis(xs, knots)
    elif bs == 2:
        # monotone I-splines: NO centering transform — non-negativity
        # must hold on the actual coefficients (the monotone cone does
        # not survive a rotation); identifiability comes from the basis
        # having no constant function in its span
        basis = i_basis(xs, knots)
        return GamSpec(name, knots, None, m_penalty(basis.shape[1]),
                       na_fill, kind=2, nonneg=nonneg)
    elif bs == 3:
        basis = m_basis(xs, knots)
        S = m_penalty(basis.shape[1])
    else:
        S = cr_penalty(knots)
        basis = cr_basis(xs, knots)
    m = basis.mean(axis=0)
    # Z: orthonormal basis of the null space of mᵀ (H2O's centering transform
    # — gamified columns stay orthogonal to the intercept)
    _, _, Vt = np.linalg.svd(m[None, :], full_matrices=True)
    Z = Vt[1:].T
    return GamSpec(name, knots, Z, Z.T @ S @ Z, na_fill, kind=bs)


def _per_column(value, n: int, name: str) -> list:
    if isinstance(value, (list, tuple)):
        if len(value) != n:
            raise ValueError(
                f"{name} list must align with gam_columns "
                f"({len(value)} != {n})")
        return list(value)
    return [value] * n


def _project_nonneg(Gp, q, l2, nonneg_idx, solver):
    """Active-set projection: solve, clamp negative monotone-block coefs
    to zero (drop them from the system), repeat until none violate —
    the NNLS shape the reference's I-spline constraint solve takes."""
    n = len(q)
    clamped = np.zeros(n, dtype=bool)
    nonneg = np.zeros(n, dtype=bool)
    nonneg[nonneg_idx] = True
    beta = np.zeros(n)
    for _ in range(len(nonneg_idx) + 1):
        idxs = np.nonzero(~clamped)[0]
        sub = solver(Gp[np.ix_(idxs, idxs)], q[idxs])
        beta = np.zeros(n)
        beta[idxs] = sub
        bad = nonneg & (beta < -1e-12) & ~clamped
        if not bad.any():
            break
        clamped |= bad
    beta[nonneg] = np.maximum(beta[nonneg], 0.0)
    return beta


class GAMModel(Model):
    algo_name = "gam"

    def __init__(self, params: GAMParameters, data_info) -> None:
        super().__init__(params, data_info)
        self.specs: List[GamSpec] = []
        self.beta: Optional[np.ndarray] = None  # [P_lin + Σ(Kⱼ-1) + 1]
        self.coefficients: Dict[str, float] = {}
        self.null_deviance: float = np.nan
        self.residual_deviance: float = np.nan
        self.aic: float = np.nan
        self.iterations: int = 0

    def _design(self, frame: Frame) -> np.ndarray:
        Xl, _ = expand_matrix(self.data_info, frame, dtype=np.float64)
        blocks = [Xl]
        for s in self.specs:
            if isinstance(s, TpSpec):
                blocks.append(s.expand(s.stack(frame)))
            else:
                blocks.append(s.expand(
                    frame.col(s.column).numeric_view().astype(np.float64)))
        return np.concatenate(blocks, axis=1)

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        p: GAMParameters = self.params
        X = self._design(frame)
        eta = X @ self.beta[:-1] + self.beta[-1]
        mu = _linkinv(p.actual_link(), eta, p)
        if p.family in ("binomial", "quasibinomial"):
            return np.stack([1 - mu, mu], axis=1)
        return mu


class GAM(ModelBuilder):

    SUPPORTED_COMMON = frozenset({"weights_column"})
    algo_name = "gam"

    def __init__(self, params: Optional[GAMParameters] = None, **kw) -> None:
        super().__init__(params or GAMParameters(**kw))

    def _validate(self, frame: Frame) -> None:
        super()._validate(frame)
        if not self.params.gam_columns:
            raise ValueError("GAM requires gam_columns")

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> GAMModel:
        p: GAMParameters = self.params
        link = p.actual_link()
        # design-cache identity, captured BEFORE the response conversion
        # rebinds `frame`. The key holds exactly the params that shape the
        # design matrix (basis spec + layout), NOT solver knobs like
        # lambda/alpha/scale — so refits that only retune smoothing or
        # regularization reuse the resident device design.
        from h2o3_tpu.frame import devcache as _devcache

        def _hashable(v):
            if isinstance(v, (list, tuple)):
                return tuple(_hashable(x) for x in v)
            if isinstance(v, np.ndarray):
                return (v.shape, v.tobytes())
            return v

        self._design_token = _devcache.frame_token(frame)
        self._design_sig = (
            p.standardize, p.missing_values_handling,
            tuple(p.ignored_columns), p.response_column,
            _hashable(p.gam_columns), _hashable(p.num_knots),
            _hashable(p.bs), _hashable(p.knots), p.splines_non_negative,
        )
        self._train_frame_key = getattr(frame, "key", None)
        if p.family in ("binomial", "quasibinomial"):
            ycol = frame.col(p.response_column)
            if not ycol.is_categorical():
                frame = frame.add_column(ycol.as_factor())
        # gam columns are modeled through their basis only (GAM.java removes
        # them from the linear predictors)
        # gam_columns entries may be a column name or a LIST of names (a
        # joint multi-predictor thin-plate smoother, GAM.java's
        # gam_columns[][] shape)
        flat_gam_cols: List[str] = []
        for entry in p.gam_columns:
            if isinstance(entry, (list, tuple)):
                flat_gam_cols.extend(entry)
            else:
                flat_gam_cols.append(entry)
        info = build_data_info(
            frame,
            y=p.response_column,
            ignored=list(p.ignored_columns) + flat_gam_cols,
            standardize=p.standardize,
            missing_values_handling=p.missing_values_handling,
        )
        model = GAMModel(p, info)
        ncols = len(p.gam_columns)
        nk_list = _per_column(p.num_knots, ncols, "num_knots")
        bs_list = _per_column(p.bs, ncols, "bs")
        scale_list = _per_column(p.scale, ncols, "scale")
        knots_list = (list(p.knots) if p.knots is not None
                      else [None] * ncols)
        if len(knots_list) != ncols:
            raise ValueError("knots list must align with gam_columns")
        specs = []
        for i, c in enumerate(p.gam_columns):
            if isinstance(c, (list, tuple)) and len(c) > 1:
                if int(bs_list[i]) != 1:
                    # GAM.java: multi-column smoothers are thin-plate
                    # ONLY — a silently coerced bs=0 would hand the user
                    # a different basis than the documented code
                    raise ValueError(
                        "multi-predictor gam_columns entries are "
                        "thin-plate smoothers: pass bs=1 for "
                        f"{list(c)}")
                if knots_list[i] is not None:
                    raise ValueError("explicit knots are not supported "
                                     "for multi-predictor smoothers")
                specs.append(_make_tp_spec(
                    list(c), _tp_stack(frame, c), int(nk_list[i])))
            else:
                cc = c[0] if isinstance(c, (list, tuple)) else c
                specs.append(_make_spec(
                    cc, frame.col(cc).numeric_view().astype(np.float64),
                    int(nk_list[i]), bs=int(bs_list[i]),
                    user_knots=knots_list[i],
                    nonneg=p.splines_non_negative,
                ))
        model.specs = specs

        X = model._design(frame)
        y = response_vector(info, frame)
        obs_w = (
            frame.col(p.weights_column).numeric_view().astype(np.float64)
            if p.weights_column else np.ones(frame.nrows)
        )
        keep = ~(np.isnan(y) | np.isnan(X).any(axis=1))
        X, y, obs_w = X[keep], y[keep], obs_w[keep]
        n, pc = X.shape
        n_lin = info.n_coefs

        # block-diagonal smoothing penalty, zero on linear coefs +
        # intercept; per-column scale (GAMParametersV3 scale array)
        Lam = np.zeros((pc + 1, pc + 1))
        nonneg_idx: List[int] = []
        off = n_lin
        for i, s in enumerate(model.specs):
            kz = s.penalty.shape[0]
            Lam[off : off + kz, off : off + kz] = \
                float(scale_list[i]) * s.penalty
            if s.nonneg:
                nonneg_idx.extend(range(off, off + kz))
            off += kz

        mesh = default_mesh()

        def _build_design():
            Xi = np.concatenate([X, np.ones((n, 1))], axis=1).astype(np.float32)
            return shard_rows(Xi, mesh)[0]

        Xd = _devcache.cached(
            "gam_design", self._design_token, self._design_sig, mesh,
            _build_design, frame_key=self._train_frame_key,
        )
        pad = lambda a: pad_rows(a, mesh.devices.size)[0]

        wsum = float(obs_w.sum())
        ybar = float((obs_w * y).sum() / wsum)
        beta = np.zeros(pc + 1)
        beta[-1] = _link_of_mean(link, ybar, p)
        # elastic net like GLM: l1 via ADMM soft-threshold, l2 via ridge
        l1 = p.lambda_ * p.alpha
        l2 = p.lambda_ * (1 - p.alpha)

        prev_obj = np.inf
        for it in range(p.max_iterations):
            eta = X @ beta[:-1] + beta[-1]
            mu = _linkinv(link, eta, p)
            d = _link_deriv(link, mu, p)
            v = _variance(p.family, mu, p)
            w = obs_w / np.maximum(v * d * d, 1e-12)
            wz = eta + (y - mu) * d

            G, q = _gram(Xd, pad(wz), pad(w))
            Gp = G / wsum + Lam / wsum  # smoothing penalty folded into Gram
            if l1 > 0 and nonneg_idx:
                beta_new = _project_nonneg(
                    Gp, q / wsum, l2, nonneg_idx,
                    lambda Gs, qs: _solve_admm(Gs, qs, l1, l2, free=1))
            elif l1 > 0:
                beta_new = _solve_admm(Gp, q / wsum, l1, l2, free=1)
            elif nonneg_idx:
                beta_new = _project_nonneg(
                    Gp, q / wsum, l2, nonneg_idx,
                    lambda Gs, qs: _solve_ridge(Gs, qs, l2, free=1))
            else:
                beta_new = _solve_ridge(Gp, q / wsum, l2, free=1)

            mu_new = _linkinv(link, X @ beta_new[:-1] + beta_new[-1], p)
            dev = float((obs_w * deviance(p.family, y, mu_new, p)).sum())
            bp = beta_new[:-1]  # intercept unpenalized
            obj = (
                dev / (2 * wsum)
                + float(beta_new @ Lam @ beta_new) / (2 * wsum)
                + l1 * float(np.abs(bp).sum())
                + 0.5 * l2 * float(bp @ bp)
            )
            delta = np.max(np.abs(beta_new - beta))
            beta = beta_new
            model.iterations = it + 1
            if delta < p.beta_epsilon or abs(prev_obj - obj) < p.objective_epsilon * max(abs(prev_obj), 1.0):
                break
            prev_obj = obj

        model.beta = beta
        names = list(info.coef_names)
        fam_tag = {0: "cr", 1: "tp", 2: "is", 3: "ms"}
        for s in model.specs:
            tag = fam_tag.get(s.kind, "cr")
            names += [f"{s.column}_{tag}_{i}"
                      for i in range(s.penalty.shape[0])]
        model.coefficients = dict(zip(names, beta[:-1].tolist()))
        model.coefficients["Intercept"] = float(beta[-1])

        mu = _linkinv(link, X @ beta[:-1] + beta[-1], p)
        model.residual_deviance = float((obs_w * deviance(p.family, y, mu, p)).sum())
        model.null_deviance = float(
            (obs_w * deviance(p.family, y, np.full_like(y, ybar), p)).sum()
        )
        rank = pc + 1
        model.aic = _aic(p.family, y, mu, obs_w, model.residual_deviance, rank)
        model.training_metrics = model.model_performance(frame)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model
