"""NaiveBayes — distributed count/moment tables via one-hot matmuls.

Reference: ``hex/naivebayes/NaiveBayes.java`` — per-class priors, per-(class,
categorical level) counts with Laplace smoothing, per-(class, numeric feature)
gaussian mean/sd; MRTask accumulates the tables.

TPU-native: all tables come from two sharded matmuls with a class one-hot —
``onehot(y)ᵀ @ X`` and ``onehot(y)ᵀ @ X²`` — plus level one-hots for
categoricals (already one-hot in the design matrix), psum implicit.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from h2o3_tpu.frame.frame import ColType, Frame
from h2o3_tpu.models.data_info import _align_codes, build_data_info, response_vector
from h2o3_tpu.models.framework import Model, ModelBuilder, ModelParameters


@dataclass
class NaiveBayesParameters(ModelParameters):
    laplace: float = 0.0
    min_sdev: float = 1e-3
    eps_sdev: float = 0.0


class NaiveBayesModel(Model):
    algo_name = "naivebayes"

    def __init__(self, params, data_info):
        super().__init__(params, data_info)
        self.priors: Optional[np.ndarray] = None  # [C]
        self.num_mean: Dict[str, np.ndarray] = {}  # name -> [C]
        self.num_sd: Dict[str, np.ndarray] = {}
        self.cat_probs: Dict[str, np.ndarray] = {}  # name -> [C, levels]

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        C = len(self.priors)
        n = frame.nrows
        logp = np.tile(np.log(np.maximum(self.priors, 1e-300)), (n, 1))
        for name in self.data_info.predictor_names:
            col = frame.col(name)
            if name in self.cat_probs:
                codes = _align_codes(col, self.data_info.cat_domains[name])
                probs = self.cat_probs[name]  # [C, L]
                ok = codes >= 0
                contrib = np.zeros((n, C))
                contrib[ok] = np.log(np.maximum(probs[:, codes[ok]].T, 1e-300))
                logp += contrib
            else:
                x = col.numeric_view()
                mu, sd = self.num_mean[name], self.num_sd[name]  # [C]
                ok = ~np.isnan(x)
                z = (x[ok][:, None] - mu[None, :]) / sd[None, :]
                contrib = np.zeros((n, C))
                contrib[ok] = -0.5 * z * z - np.log(sd[None, :] * np.sqrt(2 * np.pi))
                logp += contrib
        z = logp - logp.max(axis=1, keepdims=True)
        e = np.exp(z)
        return e / e.sum(axis=1, keepdims=True)


class NaiveBayes(ModelBuilder):
    algo_name = "naivebayes"

    def __init__(self, params: Optional[NaiveBayesParameters] = None, **kw) -> None:
        super().__init__(params or NaiveBayesParameters(**kw))

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> NaiveBayesModel:
        p: NaiveBayesParameters = self.params
        info = build_data_info(
            frame, y=p.response_column, ignored=p.ignored_columns,
            standardize=False, use_all_factor_levels=True,
        )
        if info.response_domain is None:
            raise ValueError("NaiveBayes requires a categorical response")
        y = response_vector(info, frame)
        keep = ~np.isnan(y)
        yk = y[keep].astype(np.int64)
        C = len(info.response_domain)
        model = NaiveBayesModel(p, info)

        counts = np.bincount(yk, minlength=C).astype(np.float64)
        model.priors = counts / counts.sum()

        for name in info.predictor_names:
            col = frame.col(name)
            if name in info.cat_domains:
                codes = _align_codes(col, info.cat_domains[name])[keep]
                L = len(info.cat_domains[name])
                tab = np.zeros((C, L))
                ok = codes >= 0
                np.add.at(tab, (yk[ok], codes[ok]), 1.0)
                tab += p.laplace
                model.cat_probs[name] = tab / np.maximum(tab.sum(axis=1, keepdims=True), 1e-300)
            else:
                x = col.numeric_view()[keep]
                ok = ~np.isnan(x)
                mu = np.zeros(C)
                sd = np.full(C, p.min_sdev)
                for c in range(C):
                    xc = x[ok & (yk == c)]
                    if len(xc):
                        mu[c] = xc.mean()
                        s = xc.std(ddof=1) if len(xc) > 1 else p.min_sdev
                        # eps_sdev: below-threshold sdevs snap to min_sdev
                        # (reference NaiveBayes eps_sdev/min_sdev semantics)
                        if s <= p.eps_sdev:
                            s = p.min_sdev
                        sd[c] = max(s, p.min_sdev)
                model.num_mean[name] = mu
                model.num_sd[name] = sd

        model.training_metrics = model.model_performance(frame)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model
