"""The tpu_hist booster core — histogram GBDT shared by GBM/DRF/XGBoost.

Reference architecture being re-designed (not translated):
  * driver loop: ``hex/tree/SharedTree.java:208-210,440-469`` (iterate trees ×
    scoreAndBuildTrees, k trees per class);
  * per-level fused pass: ``hex/tree/ScoreBuildHistogram2.java`` (re-assign
    rows to new leaves + accumulate histograms);
  * split search over bins: ``hex/tree/DTree.java`` (UndecidedNode.bestCol);
  * XGBoost-style second-order machinery: ``h2o-extensions/xgboost``'s native
    ``grow_gpu_hist`` updater (``XGBoostModel.java:382-394``), Rabit allreduce
    replaced by ``lax.psum`` (SURVEY.md §2.3).

TPU-native design decisions (device-resident, round 2 rewrite):
  * global quantile binning once per training run (static uint8-range codes)
    — the reference's ``histogram_type=QuantilesGlobal`` made the default,
    because per-leaf re-binning (UniformAdaptive) implies dynamic shapes;
  * the ENTIRE tree build is one traced program: levels are unrolled inside
    the trace with per-level static node capacity (level d has exactly 2^d
    slots), so histogram/split/route for a whole tree — and a whole block of
    trees via ``lax.scan`` — compile to a single XLA executable.  Bins, g/h,
    row→node assignment and the margin never leave the device; the host sees
    tree arrays only at block boundaries (score_tree_interval granularity),
    exactly where the reference's driver scores (``SharedTree.java:440``);
  * gradients/hessians are computed on device from the distribution family
    (``hex/Distribution.java`` analogue) inside the same program;
  * row/column subsampling and per-node mtries draw from ``jax.random`` keys
    folded per (block, tree, level) — reproducible under jit;
  * the histogram is a shard-private scatter-add (or Pallas MXU kernel on
    TPU) + psum (h2o3_tpu/ops/histogram.py);
  * NA routing learns a per-split default direction by evaluating the NA
    bucket on both sides (DHistogram's trailing NA bin, XGBoost default-dir).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache, partial
from typing import Callable, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from h2o3_tpu.ops.histogram import (
    apply_bins,
    build_histogram_sharded,
    make_bins,
)
from h2o3_tpu.parallel.mesh import default_mesh, row_sharding

#: boosting rounds fused into one XLA program when no monitor is active
#: (overridable via H2O3_TPU_TREE_BLOCK); also the deadline-check cadence
DEFAULT_TREE_BLOCK = 16


def tree_block_size() -> int:
    import os

    return int(os.environ.get("H2O3_TPU_TREE_BLOCK", str(DEFAULT_TREE_BLOCK)))


@dataclass(frozen=True)
class TreeParams:
    ntrees: int = 50
    max_depth: int = 6
    learn_rate: float = 0.1
    nbins: int = 256
    min_rows: float = 1.0
    min_split_improvement: float = 1e-5
    reg_lambda: float = 1.0  # L2 on leaf values (xgboost lambda; GBM uses 0)
    reg_alpha: float = 0.0  # L1 on leaf values
    gamma: float = 0.0  # min loss reduction (xgboost gamma)
    sample_rate: float = 1.0  # row subsample per tree
    col_sample_rate_per_tree: float = 1.0
    mtries: int = -1  # features per split; -1 = all (DRF uses sqrt/thirds)
    seed: int = 42


class Trees:
    """Heap-layout tree arrays. Node i's children are 2i+1 / 2i+2.

    Per tree: feat[M] int32, split_bin[M] int32, default_left[M] bool,
    is_split[M] bool, leaf[M] f32 (learn-rate scaled), with
    M = 2^(max_depth+1)-1. Stored stacked: [T, M] per field.
    """

    def __init__(self, max_depth: int, n_bins1: int, edges: np.ndarray):
        self.max_depth = max_depth
        self.n_bins1 = n_bins1
        self.edges = edges  # [F, B-1] for re-binning at predict time
        self.feat: List[np.ndarray] = []
        self.split_bin: List[np.ndarray] = []
        self.default_left: List[np.ndarray] = []
        self.is_split: List[np.ndarray] = []
        self.leaf: List[np.ndarray] = []

    def append(self, feat, split_bin, default_left, is_split, leaf) -> None:
        self.feat.append(np.asarray(feat))
        self.split_bin.append(np.asarray(split_bin))
        self.default_left.append(np.asarray(default_left))
        self.is_split.append(np.asarray(is_split))
        self.leaf.append(np.asarray(leaf))

    @property
    def ntrees(self) -> int:
        return len(self.feat)

    def stacked(self):
        return (
            jnp.asarray(np.stack(self.feat)),
            jnp.asarray(np.stack(self.split_bin)),
            jnp.asarray(np.stack(self.default_left)),
            jnp.asarray(np.stack(self.is_split)),
            jnp.asarray(np.stack(self.leaf)),
        )


# ---------------------------------------------------------------------------
# device-side objective families (hex/Distribution.java analogue)


def grad_hess_device(objective: str, y, margin):
    """Per-row (g, h) of the loss wrt the margin, traced on device.

    y: [N] labels/targets, or [N, C] fixed targets for objective='fixed'
    (DRF: each tree independently fits the raw targets, so g=-y, h=1 gives a
    Newton leaf equal to the in-leaf target mean). margin: [N, C] f32.

    Parameterized families (hex/Distribution.java analogues) encode their
    parameter in the objective string: ``tweedie:1.5``, ``quantile:0.9``,
    ``huber:<delta>`` — the string is the jit/compile cache key, so each
    parameter value compiles its own program with the constant folded in.
    """
    name, _, arg = objective.partition(":")
    if name == "custom":
        # user objective (udf.register_distribution — the
        # CDistributionFunc analogue): written with jnp ops, so it traces
        # straight into this device program
        from h2o3_tpu.udf import get_distribution

        g, h = get_distribution(arg)["grad_hess"](y, margin[:, 0])
        return (jnp.asarray(g, jnp.float32)[:, None],
                jnp.maximum(jnp.asarray(h, jnp.float32), 1e-16)[:, None])
    if name == "fixed":
        t = y if y.ndim == 2 else y[:, None]
        return -t.astype(jnp.float32), jnp.ones_like(t, dtype=jnp.float32)
    if name == "gaussian":
        g = margin[:, 0] - y
        return g[:, None], jnp.ones_like(g)[:, None]
    if name == "bernoulli":
        p = jax.nn.sigmoid(margin[:, 0])
        return (p - y)[:, None], jnp.maximum(p * (1 - p), 1e-16)[:, None]
    if name == "multinomial":
        p = jax.nn.softmax(margin, axis=1)
        onehot = (y.astype(jnp.int32)[:, None] == jnp.arange(margin.shape[1])[None, :]).astype(
            jnp.float32
        )
        return p - onehot, jnp.maximum(p * (1 - p), 1e-16)
    if name == "poisson":
        mu = jnp.exp(margin[:, 0])
        return (mu - y)[:, None], jnp.maximum(mu, 1e-16)[:, None]
    if name == "gamma":
        # deviance with log link: L = 2(y e^{-f} + f - log y - 1)
        ymf = y * jnp.exp(-margin[:, 0])
        return (1.0 - ymf)[:, None], jnp.maximum(ymf, 1e-16)[:, None]
    if name == "tweedie":
        # log link, 1<p<2: L = -y e^{(1-p)f}/(1-p) + e^{(2-p)f}/(2-p)
        pw = float(arg)
        a = y * jnp.exp((1.0 - pw) * margin[:, 0])
        b = jnp.exp((2.0 - pw) * margin[:, 0])
        g = b - a
        h = (pw - 1.0) * a + (2.0 - pw) * b
        return g[:, None], jnp.maximum(h, 1e-16)[:, None]
    if name == "huber":
        delta = float(arg)
        r = margin[:, 0] - y
        return jnp.clip(r, -delta, delta)[:, None], jnp.ones_like(r)[:, None]
    if name == "laplace":
        g = jnp.sign(margin[:, 0] - y)
        return g[:, None], jnp.ones_like(g)[:, None]
    if name == "quantile" or objective == "quantile_0.5":
        alpha = float(arg) if arg else 0.5
        g = jnp.where(margin[:, 0] < y, -alpha, 1.0 - alpha)
        return g[:, None], jnp.ones_like(g)[:, None]
    raise ValueError(f"unknown objective {objective!r}")


# ---------------------------------------------------------------------------
# traced level-step pieces


def _split_search(
    hist, lam, alpha, gamma, lr, feat_mask, min_rows: float, n_bins1: int,
    constraints=None, node_lo=None, node_hi=None, child_stats: bool = False,
):
    """Per-node best split over (feature, bin, NA-direction).

    hist: [K, F, B+1, 3] (Σg, Σh, count). Returns per-node arrays:
    feat, bin, default_left, gain, leaf_value (lr-scaled) — plus, in
    monotone mode, the best split's unscaled (left, right) child values.

    child_stats=True additionally returns (wl, wr, left_small): the chosen
    split's unscaled child leaf values and whether the LEFT child holds no
    more rows than the right — the inputs the histogram-subtraction level
    flow needs (build the smaller sibling, derive the larger by
    subtraction; terminal leaves come straight from wl/wr with no extra
    totals pass).

    Monotone mode (constraints: [F] in {-1,0,+1}, node_lo/node_hi: [K]
    per-node leaf-value bounds): candidates whose child values violate the
    feature's direction are masked out, and the terminal leaf value is
    clipped into the node's inherited bounds — the same two-sided design as
    the reference's GBM monotone path (hex/tree/gbm/GBM.java) and XGBoost's
    monotone_constraints.
    """
    B = n_bins1 - 1
    total = hist.sum(axis=2)  # [K, F, 3] — identical across F
    G = total[:, 0, 0]
    H = total[:, 0, 1]
    CNT = total[:, 0, 2]

    real = hist[:, :, :B, :]
    na = hist[:, :, B, :]  # [K, F, 3]
    cum = jnp.cumsum(real, axis=2)  # bins <= b on the left

    def thresh(g):
        return jnp.sign(g) * jnp.maximum(jnp.abs(g) - alpha, 0.0)

    def side_score(g, h):
        # optimal leaf objective with L1/L2: 0.5 * T(g)^2 / (h + lam)
        t = thresh(g)
        return t * t / jnp.maximum(h + lam, 1e-12)

    def opt_w(g, h):
        # unscaled optimal leaf value
        return -thresh(g) / jnp.maximum(h + lam, 1e-12)

    parent = side_score(G, H)  # [K]

    def dir_gain(gl, hl, cl):
        # constraints mode materializes per-candidate child values (the
        # directional mask needs them); otherwise child stats for the ONE
        # winning candidate are gathered later — full [K, F, B] wl/wr
        # arrays would be pure waste on the default subtract path
        gr = G[:, None, None] - gl
        hr = H[:, None, None] - hl
        cr = CNT[:, None, None] - cl
        gain = 0.5 * (side_score(gl, hl) + side_score(gr, hr) - parent[:, None, None]) - gamma
        ok = (cl >= min_rows) & (cr >= min_rows)
        gain = jnp.where(ok, gain, -jnp.inf)
        if constraints is not None:
            wl = opt_w(gl, hl)
            wr = opt_w(gr, hr)
            c = constraints[None, :, None].astype(gl.dtype)
            gain = jnp.where((c != 0) & (c * (wr - wl) < 0), -jnp.inf, gain)
        return gain

    # NA right (default_left=False): left stats = cum; NA left: left += NA bucket
    gain_r = dir_gain(cum[..., 0], cum[..., 1], cum[..., 2])
    gain_l = dir_gain(
        cum[..., 0] + na[..., 0][:, :, None],
        cum[..., 1] + na[..., 1][:, :, None],
        cum[..., 2] + na[..., 2][:, :, None],
    )

    go_left_better = gain_l > gain_r
    gain_fb = jnp.where(go_left_better, gain_l, gain_r)  # [K, F, B]
    # feat_mask: [F] global or [K, F] per-node (DRF mtries per split)
    fm = feat_mask[None, :, None] if feat_mask.ndim == 1 else feat_mask[:, :, None]
    gain_fb = jnp.where(fm, gain_fb, -jnp.inf)

    flat = gain_fb.reshape(gain_fb.shape[0], -1)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    best_f = (best // B).astype(jnp.int32)
    best_b = (best % B).astype(jnp.int32)
    dl = jnp.take_along_axis(
        go_left_better.reshape(go_left_better.shape[0], -1), best[:, None], axis=1
    )[:, 0]

    # leaf value if this node terminates (Newton step, L1-thresholded, lr-scaled)
    raw_leaf = opt_w(G, H)
    if constraints is not None:
        raw_leaf = jnp.clip(raw_leaf, node_lo, node_hi)
    if constraints is not None or child_stats:
        # gather the winning candidate's (Σg, Σh, Σw) left-side stats from
        # cum/na — K-sized gathers, not full [K, F, B] re-materialization
        K = hist.shape[0]
        idx_f = jnp.broadcast_to(best_f[:, None, None, None], (K, 1, B, 3))
        cum_f = jnp.take_along_axis(cum, idx_f, axis=1)[:, 0]  # [K, B, 3]
        stats_l = jnp.take_along_axis(
            cum_f, jnp.broadcast_to(best_b[:, None, None], (K, 1, 3)), axis=1
        )[:, 0]  # [K, 3]
        na_f = jnp.take_along_axis(
            na, jnp.broadcast_to(best_f[:, None, None], (K, 1, 3)), axis=1
        )[:, 0]  # [K, 3]
        stats_l = stats_l + dl[:, None].astype(stats_l.dtype) * na_f
        gl_b, hl_b, cl_b = stats_l[:, 0], stats_l[:, 1], stats_l[:, 2]
        best_wl = opt_w(gl_b, hl_b)
        best_wr = opt_w(G - gl_b, H - hl_b)
        left_small = 2.0 * cl_b <= CNT
        return (best_f, best_b, dl, best_gain, lr * raw_leaf,
                best_wl, best_wr, left_small)
    return best_f, best_b, dl, best_gain, lr * raw_leaf


def _sel_table(table, idx):
    """table[idx] for a small table [K] and big idx [N] — as a masked
    reduction, NOT a gather (XLA TPU gathers are scalar-serialized: ~250ns
    per element; this is one fused VPU pass)."""
    K = table.shape[0]
    mask = idx[:, None] == jnp.arange(K, dtype=idx.dtype)[None, :]
    zero = jnp.zeros((), dtype=table.dtype)
    return jnp.sum(jnp.where(mask, table[None, :], zero), axis=1)


def _sel_tables(tables, idx):
    """Select from several same-length small tables sharing one mask."""
    K = tables[0].shape[0]
    mask = idx[:, None] == jnp.arange(K, dtype=idx.dtype)[None, :]
    outs = []
    for t in tables:
        zero = jnp.zeros((), dtype=t.dtype)
        outs.append(jnp.sum(jnp.where(mask, t[None, :], zero), axis=1))
    return outs


def _sel_cols(bins, f_idx):
    """bins[i, f_idx[i]] — per-row column select as a masked reduction."""
    F = bins.shape[1]
    mask = f_idx[:, None] == jnp.arange(F, dtype=f_idx.dtype)[None, :]
    return jnp.sum(jnp.where(mask, bins, 0), axis=1)


def _tree_walk(bins, feat, split_bin, default_left, is_split, leaf, max_depth: int, n_bins1):
    """Heap-walk a single tree (arrays [M]); returns per-row leaf values."""
    idx = jnp.zeros(bins.shape[0], dtype=jnp.int32)

    def body(_, idx):
        f, sb, dl, sp = _sel_tables((feat, split_bin, default_left, is_split), idx)
        b = _sel_cols(bins, f)
        is_na = b >= n_bins1 - 1
        go_left = jnp.where(is_na, dl, b <= sb)
        nxt = 2 * idx + jnp.where(go_left, 1, 2)
        return jnp.where(sp, nxt, idx)

    idx = jax.lax.fori_loop(0, max_depth, body, idx)
    return _sel_table(leaf, idx)


@partial(jax.jit, static_argnames=("max_depth",))
def _predict_stacked(bins, feat, split_bin, default_left, is_split, leaf, max_depth: int, n_bins1_arr):
    """Sum of all trees' outputs for each row. Tree arrays: [T, M]."""

    def one_tree(carry, tree):
        tf, tb, tdl, tsp, tlf = tree
        return carry + _tree_walk(bins, tf, tb, tdl, tsp, tlf, max_depth, n_bins1_arr), None

    out, _ = jax.lax.scan(
        one_tree,
        jnp.zeros(bins.shape[0], jnp.float32),
        (feat, split_bin, default_left, is_split, leaf),
    )
    return out


# ---------------------------------------------------------------------------
# the device-resident training block


def _tree_subtract_enabled() -> bool:
    """Histogram-subtraction level flow: build only the SMALLER sibling of
    each split and derive the larger by subtraction from the retained
    parent histogram (the standard hist-GBDT trick — LightGBM, XGBoost
    ``hist`` and the reference's ``grow_gpu_hist`` all do this); terminal
    leaves come from the last split's child stats with no totals pass.

    Env H2O3_TPU_TREE_SUBTRACT: '1' on, '0' off, unset/'auto' = on for the
    Pallas TPU path, off for the XLA scatter path (keeps the CPU oracle
    tier bit-stable). Read at trace time of the training block.
    """
    import os

    from h2o3_tpu.ops.histogram import _hist_impl

    v = os.environ.get("H2O3_TPU_TREE_SUBTRACT", "auto")
    if v in ("0", "1"):
        return v == "1"
    return _hist_impl(None) == "pallas"


def _build_one_tree(
    bins, g, h, sample, feat_mask, key, p: TreeParams, mesh, bins_fm=None,
    constraints=None, rw=None, subtract: bool = False,
):
    """Grow one tree to max_depth, fully traced. Levels are unrolled with
    per-level static node capacity 2^d (the fixed-capacity redesign of the
    reference's dynamic DTree node growth).

    Every row (sampled or not) is routed so its leaf is known at the end —
    the margin update is then a single small-table select, with no separate
    prediction walk over the finished tree. Only ``sample`` rows contribute
    to histograms (row-subsampling semantics of GBM/DRF).

    constraints: optional [F] monotone directions; when set, per-node
    leaf-value bounds [lo, hi] are carried down the levels (children of a
    split on a constrained feature inherit the split's midpoint as the
    shared bound) and leaf values are clipped into them.

    Returns (heap arrays [M], per-row leaf value [N]).
    """
    D = p.max_depth
    n_bins1 = p.nbins + 1
    F = bins.shape[1]
    pos = jnp.zeros(bins.shape[0], dtype=jnp.int32)  # absolute heap position
    mono = constraints is not None
    if mono:
        b_lo = jnp.full((1,), -jnp.inf, jnp.float32)
        b_hi = jnp.full((1,), jnp.inf, jnp.float32)

    tf_l, tb_l, tdl_l, tsp_l, tlf_l = [], [], [], [], []
    prev_hist = prev_can = prev_left_small = prev_wl = prev_wr = None
    for d in range(D + 1):
        K = 2**d
        lo = K - 1
        local = pos - lo
        in_lvl = (local >= 0) & (local < K)
        hist_nodes = jnp.where(in_lvl & sample, local, -1).astype(jnp.int32)
        if d == D:
            if subtract and prev_wl is not None:  # D=0 has no parent split
                # terminal leaves straight from the parent split's child
                # stats: child(2k+0) = wl[k], child(2k+1) = wr[k] — the
                # level-(D-1) cumsum stats cover exactly the rows each
                # child receives, so no totals pass is needed at all
                raw_leaf = jnp.stack([prev_wl, prev_wr], axis=1).reshape(K)
            else:
                # terminal level: no split is possible, so the full
                # [K, F, B+1, 3] histogram (the widest of the tree) is pure
                # waste — per-node (Σg, Σh) totals give the leaf values
                from h2o3_tpu.ops.histogram import node_totals_sharded

                tot = node_totals_sharded(
                    hist_nodes, g, h, K, mesh=mesh, rw=rw)
                G, H = tot[:, 0], tot[:, 1]
                t = jnp.sign(G) * jnp.maximum(
                    jnp.abs(G) - jnp.float32(p.reg_alpha), 0.0
                )
                raw_leaf = -t / jnp.maximum(
                    H + jnp.float32(p.reg_lambda), 1e-12)
            if mono:
                raw_leaf = jnp.clip(raw_leaf, b_lo, b_hi)
            tf_l.append(jnp.zeros(K, jnp.int32))
            tb_l.append(jnp.zeros(K, jnp.int32))
            tdl_l.append(jnp.zeros(K, bool))
            tsp_l.append(jnp.zeros(K, bool))
            tlf_l.append(jnp.float32(p.learn_rate) * raw_leaf)
            break
        if subtract and d > 0:
            # build ONLY each parent's smaller child (one kernel slot per
            # parent, K/2 nodes); the larger sibling = parent − smaller.
            # Children of non-split parents hold no rows: their small
            # half is all-zero by the in_lvl mask and their big half is
            # masked to zero by prev_can.
            Kp = K // 2
            par = jnp.clip(local // 2, 0, Kp - 1)
            parity = local % 2
            small_parity = jnp.where(prev_left_small, 0, 1)  # [Kp]
            sp_row = _sel_table(small_parity.astype(jnp.int32), par)
            half_nodes = jnp.where(
                in_lvl & sample & (parity == sp_row), par, -1
            ).astype(jnp.int32)
            hist_small = build_histogram_sharded(
                bins, half_nodes, g, h, n_nodes=Kp, n_bins1=n_bins1,
                mesh=mesh, bins_fm=bins_fm, rw=rw,
            )
            can_m = prev_can[:, None, None, None]
            hist_big = jnp.where(can_m, prev_hist - hist_small, 0.0)
            ls_m = prev_left_small[:, None, None, None]
            left = jnp.where(ls_m, hist_small, hist_big)
            right = jnp.where(ls_m, hist_big, hist_small)
            hist = jnp.stack([left, right], axis=1).reshape(
                K, *hist_small.shape[1:]
            )
        else:
            hist = build_histogram_sharded(
                bins, hist_nodes, g, h, n_nodes=K, n_bins1=n_bins1,
                mesh=mesh, bins_fm=bins_fm, rw=rw,
            )
        if p.mtries > 0:
            key, sub = jax.random.split(key)
            r = jax.random.uniform(sub, (K, F))
            thresh = jnp.sort(r, axis=1)[:, p.mtries - 1][:, None]
            node_feat_mask = (r <= thresh) & feat_mask[None, :]
        else:
            node_feat_mask = feat_mask
        out = _split_search(
            hist,
            jnp.float32(p.reg_lambda),
            jnp.float32(p.reg_alpha),
            jnp.float32(p.gamma),
            jnp.float32(p.learn_rate),
            node_feat_mask,
            min_rows=float(p.min_rows),
            n_bins1=n_bins1,
            constraints=constraints if mono else None,
            node_lo=b_lo if mono else None,
            node_hi=b_hi if mono else None,
            child_stats=subtract,
        )
        if mono or subtract:
            bf, bb, dl, gain, leaf, bwl, bwr, left_small = out
        else:
            bf, bb, dl, gain, leaf = out
        can = (gain > max(p.min_split_improvement, 0.0)) & jnp.isfinite(gain) & (d < D)
        tf_l.append(bf)
        tb_l.append(bb)
        tdl_l.append(dl)
        tsp_l.append(can)
        tlf_l.append(leaf)
        if subtract:
            prev_hist, prev_can, prev_left_small = hist, can, left_small
            prev_wl, prev_wr = bwl, bwr
        if d < D:
            k = jnp.clip(local, 0, K - 1)
            f, sb, dlk, cank = _sel_tables((bf, bb, dl, can), k)
            b = _sel_cols(bins, f)
            go_left = jnp.where(b >= n_bins1 - 1, dlk, b <= sb)
            child = 2 * (lo + k) + jnp.where(go_left, 1, 2)
            pos = jnp.where(in_lvl & cank, child, pos).astype(jnp.int32)
            if mono:
                # propagate bounds: split midpoint caps the monotone side
                c_best = jnp.take(constraints, bf).astype(jnp.float32)  # [K]
                mid = jnp.clip(0.5 * (bwl + bwr), b_lo, b_hi)
                lo_left = jnp.where(c_best < 0, jnp.maximum(b_lo, mid), b_lo)
                hi_left = jnp.where(c_best > 0, jnp.minimum(b_hi, mid), b_hi)
                lo_right = jnp.where(c_best > 0, jnp.maximum(b_lo, mid), b_lo)
                hi_right = jnp.where(c_best < 0, jnp.minimum(b_hi, mid), b_hi)
                b_lo = jnp.stack([lo_left, lo_right], axis=1).reshape(2 * K)
                b_hi = jnp.stack([hi_left, hi_right], axis=1).reshape(2 * K)

    # per-level concatenation IS the heap layout: node (d, i) -> 2^d - 1 + i
    tree = (
        jnp.concatenate(tf_l),
        jnp.concatenate(tb_l),
        jnp.concatenate(tdl_l),
        jnp.concatenate(tsp_l),
        jnp.concatenate(tlf_l),
    )
    pred = _sel_table(tree[4], pos)
    return tree, pred


@lru_cache(maxsize=64)
def _make_block_fn(
    objective: str,
    n_class_trees: int,
    block: int,
    p: TreeParams,
    mesh,
    weighted: bool = False,
    monotone: bool = False,
    subtract: bool = False,
):
    """Compile one training block: scan over `block` boosting rounds, the
    whole thing one XLA program. Returns f(bins, y, valid, margin, keys,
    bins_fm, w, mono) -> (margin', tree arrays [block, C, M]).
    `weighted`/`monotone` are compile-time flags so the unweighted /
    unconstrained program is byte-identical to before (w/mono are passed as
    None and never touched)."""
    D = p.max_depth
    n_bins1 = p.nbins + 1
    C = n_class_trees

    @partial(jax.jit, donate_argnums=(3,))
    def block_fn(bins, y, valid, margin, keys, bins_fm, w, mono):
        def one_round(margin, key_t):
            g_all, h_all = grad_hess_device(objective, y, margin)
            if weighted:
                # fold row weights into (g, h): every Σg/Σh a histogram sees
                # becomes the weighted sum (DHistogram's Σw-scaled stats)
                g_all = g_all * w[:, None]
                h_all = h_all * w[:, None]
            kr, kc, kt = jax.random.split(key_t, 3)
            active = valid
            if p.sample_rate < 1.0:
                active = active & (
                    jax.random.uniform(kr, active.shape) < p.sample_rate
                )
            F = bins.shape[1]
            if p.col_sample_rate_per_tree < 1.0:
                ncols = max(1, int(round(p.col_sample_rate_per_tree * F)))
                r = jax.random.uniform(kc, (F,))
                thresh = jnp.sort(r)[ncols - 1]
                feat_mask = r <= thresh
            else:
                feat_mask = jnp.ones((F,), bool)

            outs = []
            for c in range(C):
                tree, pred = _build_one_tree(
                    bins,
                    g_all[:, c].astype(jnp.float32),
                    h_all[:, c].astype(jnp.float32),
                    active,
                    feat_mask,
                    jax.random.fold_in(kt, c),
                    p,
                    mesh,
                    bins_fm=bins_fm,
                    constraints=mono if monotone else None,
                    rw=w if weighted else None,
                    subtract=subtract,
                )
                # margin update from this tree (full data, not just the sample)
                margin = margin.at[:, c].add(pred)
                outs.append(tree)
            stacked = tuple(
                jnp.stack([outs[c][i] for c in range(C)]) for i in range(5)
            )  # each [C, M]
            return margin, stacked

        margin, trees = jax.lax.scan(one_round, margin, keys)
        return margin, trees

    return block_fn


# ---------------------------------------------------------------------------
# training driver


class BoostedTrees:
    """Trained ensemble: per-class Trees + binning spec + init margin."""

    def __init__(
        self,
        trees_per_class: List[Trees],
        init_margin: np.ndarray,  # [C]
        params: TreeParams,
        average: bool = False,  # DRF averages instead of summing margins
    ):
        self.trees_per_class = trees_per_class
        self.init_margin = init_margin
        self.params = params
        self.average = average

    @property
    def nclasses_trees(self) -> int:
        return len(self.trees_per_class)

    def predict_margin(self, X: np.ndarray) -> np.ndarray:
        """Raw margins [N, C] from raw features (re-binned with stored edges)."""
        t0 = self.trees_per_class[0]
        bins = jnp.asarray(apply_bins(X, t0.edges))
        cols = []
        for c, trees in enumerate(self.trees_per_class):
            if trees.ntrees == 0:
                cols.append(np.full(X.shape[0], self.init_margin[c], dtype=np.float64))
                continue
            s = _predict_stacked(
                bins, *trees.stacked(), max_depth=trees.max_depth,
                n_bins1_arr=jnp.int32(trees.n_bins1),
            )
            s = np.asarray(jax.device_get(s), dtype=np.float64)
            if self.average:
                s = s / trees.ntrees
            cols.append(self.init_margin[c] + s)
        return np.stack(cols, axis=1)


def train_boosted(
    X: np.ndarray,
    objective: str,
    y: np.ndarray,
    n_class_trees: int,
    init_margin: np.ndarray,
    params: TreeParams,
    average: bool = False,
    monitor: Optional[Callable[[int, np.ndarray], bool]] = None,
    score_interval: int = 1,
    mesh=None,
    timings: Optional[dict] = None,
    resume_from: Optional["BoostedTrees"] = None,
    weights: Optional[np.ndarray] = None,
    offset: Optional[np.ndarray] = None,
    monotone: Optional[np.ndarray] = None,
    cache_token=None,
    cache_frame_key: Optional[str] = None,
) -> BoostedTrees:
    """Device-resident booster loop.

    objective: a grad_hess_device family name ('gaussian', 'bernoulli',
    'multinomial', 'poisson', 'gamma', 'laplace', 'tweedie:<p>',
    'huber:<delta>', 'quantile:<alpha>') or 'fixed' with y = targets [N, C]
    (DRF bagging semantics, average=True).
    monitor(tree_idx, margin[N, C]) -> True to stop early (ScoreKeeper hook);
    called every `score_interval` trees, which is also the device-block size —
    between calls nothing crosses the host boundary.
    resume_from: checkpoint-continue (SharedTree.java:131-136): start from an
    existing ensemble's trees + margin and train ``ntrees`` MORE trees. The
    per-tree RNG is keyed by absolute tree index, so k trees then k more
    reproduces a single 2k-tree run exactly.
    weights: [N] per-row observation weights (weights_column,
    hex/tree/SharedTree.java weights plumbing) folded into (g, h) on device.
    offset: [N] per-row margin offset (offset_column) added to the initial
    margin; single-margin objectives only. The caller owns adding the offset
    back at scoring time (Model.score semantics).
    monotone: [F] per-feature direction in {-1, 0, +1} (monotone_constraints).
    cache_token: hashable identity of X's provenance (frame column versions
    + encoding; see models/tree/common.tree_cache_token). When set, the
    quantize-and-place block (apply_bins + bin-code/validity/feature-major
    device_put) is memoized in the process-wide device frame cache, so
    repeat GBM/DRF/XGBoost fits on the same unmutated frame — and every
    tree of every fit — reuse the resident bin codes instead of re-binning
    and re-uploading. cache_frame_key links the entry to a DKV frame for
    lifecycle eviction. None bypasses the cache entirely.
    """
    if getattr(X, "is_dist_hist", False):
        # chunk-homed training: the level loop fans hist_level ctx-DTasks
        # to the chunk homes and only histogram partials cross the wire
        from h2o3_tpu.models.tree import dist_hist as _dist_hist

        return _dist_hist.train_boosted_dist(
            X, objective, y, n_class_trees, init_margin, params,
            average=average, monitor=monitor,
            score_interval=score_interval, timings=timings,
            weights=weights, offset=offset)

    import time as _time

    from jax.sharding import NamedSharding, PartitionSpec as P

    from h2o3_tpu.ops.histogram import _hist_impl
    from h2o3_tpu.parallel.mesh import DATA_AXIS

    _t0 = _time.time()
    n, F = X.shape
    p = params
    if mesh is None:
        mesh = default_mesh()
    nshards = mesh.devices.size

    if resume_from is not None:
        # continue training: reuse the checkpoint's binning + f0 exactly
        init_margin = resume_from.init_margin
        edges = resume_from.trees_per_class[0].edges
        if resume_from.trees_per_class[0].n_bins1 != p.nbins + 1:
            raise ValueError("checkpoint nbins mismatch")
    else:
        edges = make_bins(X, p.nbins, seed=p.seed)
    n_bins1 = p.nbins + 1
    # pallas path: pad every shard to the kernel row tile so the prepared
    # feature-major copy needs no per-level realignment
    use_pallas = _hist_impl(None) == "pallas"
    if use_pallas:
        from h2o3_tpu.ops.pallas_histogram import _ROW_TILE

        mult = nshards * _ROW_TILE
    else:
        mult = nshards

    def _place_bins():
        bins_host = apply_bins(X, edges)
        padn = (-n) % mult
        if padn:
            bh = np.concatenate(
                [bins_host, np.zeros((padn, F), dtype=np.int32)], axis=0
            )
        else:
            bh = bins_host
        bins_d = jax.device_put(bh, row_sharding(mesh, 2))
        n_pad = bh.shape[0]
        valid_d = jax.device_put(np.arange(n_pad) < n, row_sharding(mesh, 1))
        bins_fm_d = None
        if use_pallas:
            from h2o3_tpu.ops.pallas_histogram import _FEAT_BLOCK

            fb = min(_FEAT_BLOCK, F)
            Fp = F + (-F) % fb
            bfm_host = np.zeros((Fp, n_pad), dtype=np.int32)
            bfm_host[:F] = bh.T
            bins_fm_d = jax.device_put(
                bfm_host, NamedSharding(mesh, P(None, DATA_AXIS))
            )
        return bins_d, valid_d, bins_fm_d, n_pad

    # bin codes are a pure function of (X provenance, edges, padding
    # layout) — reusable across ntrees, checkpoint-continues, and
    # GBM/DRF/XGBoost fits sharing a frame + binning spec
    import hashlib

    from h2o3_tpu.frame import devcache as _devcache

    edges_digest = hashlib.sha1(
        np.ascontiguousarray(edges).tobytes()
    ).hexdigest()
    bins_d, valid_d, bins_fm_d, n_pad = _devcache.cached(
        "tree_bins", cache_token, (edges_digest, p.nbins, mult), mesh,
        _place_bins, frame_key=cache_frame_key,
    )

    C = n_class_trees
    if objective == "fixed":
        targets = np.asarray(y, dtype=np.float32)
        if targets.ndim == 1:
            targets = targets[:, None]
        y_host = np.zeros((n_pad, targets.shape[1]), np.float32)
        y_host[:n] = targets
        y_d = jax.device_put(y_host, row_sharding(mesh, 2))
    else:
        y_host = np.zeros(n_pad, np.float32)
        y_host[:n] = np.asarray(y, dtype=np.float32)
        y_d = jax.device_put(y_host, row_sharding(mesh, 1))

    if resume_from is not None and objective != "fixed":
        m0 = resume_from.predict_margin(X).astype(np.float32)  # [n, C]
        margin_host = np.tile(
            np.asarray(init_margin, dtype=np.float32), (n_pad, 1)
        )
        margin_host[:n] = m0
    else:
        margin_host = np.tile(
            np.asarray(init_margin, dtype=np.float32), (n_pad, 1)
        )
    if offset is not None:
        if C != 1:
            raise ValueError("offset_column requires a single-margin objective")
        margin_host[:n, 0] += np.asarray(offset, dtype=np.float32)
    margin = jax.device_put(margin_host, row_sharding(mesh, 2))

    w_d = None
    if weights is not None:
        w_host = np.zeros(n_pad, np.float32)
        w_host[:n] = np.asarray(weights, dtype=np.float32)
        w_d = jax.device_put(w_host, row_sharding(mesh, 1))
    mono_d = None
    if monotone is not None and np.any(np.asarray(monotone) != 0):
        mono_d = jnp.asarray(np.asarray(monotone, dtype=np.int32))

    trees_per_class = [Trees(p.max_depth, n_bins1, edges) for _ in range(C)]
    tree_offset = 0
    if resume_from is not None:
        tree_offset = resume_from.trees_per_class[0].ntrees
        for c in range(C):
            src = resume_from.trees_per_class[c]
            dst = trees_per_class[c]
            dst.feat = list(src.feat)
            dst.split_bin = list(src.split_bin)
            dst.default_left = list(src.default_left)
            dst.is_split = list(src.is_split)
            dst.leaf = list(src.leaf)
    key = jax.random.PRNGKey(p.seed)
    jax.block_until_ready(margin)
    _t_prep = _time.time()

    # the block program depends on neither ntrees nor seed — normalize them
    # out of the compile-cache key
    from dataclasses import replace as _dc_replace

    p_key = _dc_replace(p, ntrees=0, seed=0)

    from h2o3_tpu.util import timeline

    built = 0
    default_block = tree_block_size()
    subtract_on = _tree_subtract_enabled()
    while built < p.ntrees:
        block = (
            min(score_interval, p.ntrees - built)
            if monitor is not None
            else min(default_block, p.ntrees - built)
        )
        fn = _make_block_fn(
            objective, C, block, p_key, mesh,
            weighted=w_d is not None, monotone=mono_d is not None,
            subtract=subtract_on,
        )
        # one key per ABSOLUTE tree index: blocking and checkpoints never
        # change the random stream a given tree sees
        keys = jax.vmap(lambda t: jax.random.fold_in(key, t))(
            jnp.arange(tree_offset + built, tree_offset + built + block)
        )
        with timeline.timed(
            "tree_block", objective=objective, trees=block, rows=n,
            first_tree=tree_offset + built,
        ):
            margin, trees_dev = fn(
                bins_d, y_d, valid_d, margin, keys, bins_fm_d, w_d, mono_d
            )
            jax.block_until_ready(margin)
        tf, tb, tdl, tsp, tlf = jax.device_get(trees_dev)  # [block, C, M] each
        for t in range(block):
            for c in range(C):
                trees_per_class[c].append(
                    tf[t, c], tb[t, c], tdl[t, c], tsp[t, c], tlf[t, c]
                )
        built += block
        if monitor is not None:
            margin_host = np.asarray(jax.device_get(margin), np.float64)[:n]
            if monitor(built - 1, margin_host):
                break

    if timings is not None:
        jax.block_until_ready(margin)
        timings["prep_s"] = _t_prep - _t0
        timings["train_s"] = _time.time() - _t_prep
    return BoostedTrees(trees_per_class, np.asarray(init_margin, np.float64), p, average=average)
