"""The tpu_hist booster core — histogram GBDT shared by GBM/DRF/XGBoost.

Reference architecture being re-designed (not translated):
  * driver loop: ``hex/tree/SharedTree.java:208-210,440-469`` (iterate trees ×
    scoreAndBuildTrees, k trees per class);
  * per-level fused pass: ``hex/tree/ScoreBuildHistogram2.java`` (re-assign
    rows to new leaves + accumulate histograms);
  * split search over bins: ``hex/tree/DTree.java`` (UndecidedNode.bestCol);
  * XGBoost-style second-order machinery: ``h2o-extensions/xgboost``'s native
    ``grow_gpu_hist`` updater (``XGBoostModel.java:382-394``), Rabit allreduce
    replaced by ``lax.psum`` (SURVEY.md §2.3).

TPU-native design decisions:
  * global quantile binning once per training run (static uint8-range codes)
    — the reference's ``histogram_type=QuantilesGlobal`` made the default,
    because per-leaf re-binning (UniformAdaptive) implies dynamic shapes;
  * level-wise growth with a fixed node capacity of 2^depth per level: every
    level is one jitted program of static shape, compiled once per depth and
    reused across all trees and all boosting rounds;
  * rows carry a level-local node id (-1 = out of tree); the histogram is a
    shard-private scatter-add + psum (h2o3_tpu/ops/histogram.py);
  * split search, leaf values, and node routing are replicated O(K·F·B) jnp
    ops — tiny next to the histogram pass;
  * NA routing learns a per-split default direction by evaluating the NA
    bucket on both sides (DHistogram's trailing NA bin, XGBoost default-dir).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Callable, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from h2o3_tpu.ops.histogram import apply_bins, build_histogram_sharded, make_bins
from h2o3_tpu.parallel.mesh import default_mesh, row_sharding


@dataclass
class TreeParams:
    ntrees: int = 50
    max_depth: int = 6
    learn_rate: float = 0.1
    nbins: int = 256
    min_rows: float = 1.0
    min_split_improvement: float = 1e-5
    reg_lambda: float = 1.0  # L2 on leaf values (xgboost lambda; GBM uses 0)
    reg_alpha: float = 0.0  # L1 on leaf values
    gamma: float = 0.0  # min loss reduction (xgboost gamma)
    sample_rate: float = 1.0  # row subsample per tree
    col_sample_rate_per_tree: float = 1.0
    mtries: int = -1  # features per split; -1 = all (DRF uses sqrt/thirds)
    seed: int = 42


class Trees:
    """Heap-layout tree arrays. Node i's children are 2i+1 / 2i+2.

    Per tree: feat[M] int32, split_bin[M] int32, default_left[M] bool,
    is_split[M] bool, leaf[M] f32 (learn-rate scaled), with
    M = 2^(max_depth+1)-1. Stored stacked: [T, M] per field.
    """

    def __init__(self, max_depth: int, n_bins1: int, edges: np.ndarray):
        self.max_depth = max_depth
        self.n_bins1 = n_bins1
        self.edges = edges  # [F, B-1] for re-binning at predict time
        self.feat: List[np.ndarray] = []
        self.split_bin: List[np.ndarray] = []
        self.default_left: List[np.ndarray] = []
        self.is_split: List[np.ndarray] = []
        self.leaf: List[np.ndarray] = []

    def append(self, feat, split_bin, default_left, is_split, leaf) -> None:
        self.feat.append(np.asarray(feat))
        self.split_bin.append(np.asarray(split_bin))
        self.default_left.append(np.asarray(default_left))
        self.is_split.append(np.asarray(is_split))
        self.leaf.append(np.asarray(leaf))

    @property
    def ntrees(self) -> int:
        return len(self.feat)

    def stacked(self):
        return (
            jnp.asarray(np.stack(self.feat)),
            jnp.asarray(np.stack(self.split_bin)),
            jnp.asarray(np.stack(self.default_left)),
            jnp.asarray(np.stack(self.is_split)),
            jnp.asarray(np.stack(self.leaf)),
        )


# ---------------------------------------------------------------------------
# jitted level-step pieces


@partial(jax.jit, static_argnames=("n_bins1", "min_rows"))
def _split_search(hist, lam, alpha, gamma, lr, feat_mask, min_rows: float, n_bins1: int):
    """Per-node best split over (feature, bin, NA-direction).

    hist: [K, F, B+1, 3] (Σg, Σh, count). Returns per-node arrays:
    feat, bin, default_left, gain, leaf_value (lr-scaled), plus can_split.
    """
    B = n_bins1 - 1
    total = hist.sum(axis=2)  # [K, F, 3] — identical across F
    G = total[:, 0, 0]
    H = total[:, 0, 1]
    CNT = total[:, 0, 2]

    real = hist[:, :, :B, :]
    na = hist[:, :, B, :]  # [K, F, 3]
    cum = jnp.cumsum(real, axis=2)  # bins <= b on the left

    def side_score(g, h):
        # optimal leaf objective with L1/L2: 0.5 * T(g)^2 / (h + lam)
        t = jnp.sign(g) * jnp.maximum(jnp.abs(g) - alpha, 0.0)
        return t * t / jnp.maximum(h + lam, 1e-12)

    parent = side_score(G, H)  # [K]

    def dir_gain(gl, hl, cl):
        gr = G[:, None, None] - gl
        hr = H[:, None, None] - hl
        cr = CNT[:, None, None] - cl
        gain = 0.5 * (side_score(gl, hl) + side_score(gr, hr) - parent[:, None, None]) - gamma
        ok = (cl >= min_rows) & (cr >= min_rows)
        return jnp.where(ok, gain, -jnp.inf)

    # NA right (default_left=False): left stats = cum; NA left: left += NA bucket
    gain_r = dir_gain(cum[..., 0], cum[..., 1], cum[..., 2])
    gain_l = dir_gain(
        cum[..., 0] + na[..., 0][:, :, None],
        cum[..., 1] + na[..., 1][:, :, None],
        cum[..., 2] + na[..., 2][:, :, None],
    )

    go_left_better = gain_l > gain_r
    gain_fb = jnp.where(go_left_better, gain_l, gain_r)  # [K, F, B]
    # feat_mask: [F] global or [K, F] per-node (DRF mtries per split)
    fm = feat_mask[None, :, None] if feat_mask.ndim == 1 else feat_mask[:, :, None]
    gain_fb = jnp.where(fm, gain_fb, -jnp.inf)

    flat = gain_fb.reshape(gain_fb.shape[0], -1)
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], axis=1)[:, 0]
    best_f = (best // B).astype(jnp.int32)
    best_b = (best % B).astype(jnp.int32)
    dl = jnp.take_along_axis(
        go_left_better.reshape(go_left_better.shape[0], -1), best[:, None], axis=1
    )[:, 0]

    # leaf value if this node terminates (Newton step, L1-thresholded, lr-scaled)
    t = jnp.sign(G) * jnp.maximum(jnp.abs(G) - alpha, 0.0)
    leaf = -lr * t / jnp.maximum(H + lam, 1e-12)
    return best_f, best_b, dl, best_gain, leaf


@jax.jit
def _route_rows(bins, nodes, feat, split_bin, default_left, is_split, n_bins1_arr):
    """Advance rows one level: node k -> 2k (left) / 2k+1 (right); rows whose
    node became a leaf leave the tree (-1)."""
    k = jnp.where(nodes >= 0, nodes, 0)
    f = feat[k]
    b = jnp.take_along_axis(bins, f[:, None], axis=1)[:, 0]
    is_na = b >= n_bins1_arr - 1
    go_left = jnp.where(is_na, default_left[k], b <= split_bin[k])
    child = 2 * k + jnp.where(go_left, 0, 1)
    new_nodes = jnp.where((nodes >= 0) & is_split[k], child, -1)
    return new_nodes.astype(jnp.int32)


@partial(jax.jit, static_argnames=("max_depth",))
def _predict_stacked(bins, feat, split_bin, default_left, is_split, leaf, max_depth: int, n_bins1_arr):
    """Sum of all trees' outputs for each row. Tree arrays: [T, M]."""

    def one_tree(carry, tree):
        tf, tb, tdl, tsp, tlf = tree
        idx = jnp.zeros(bins.shape[0], dtype=jnp.int32)

        def body(_, idx):
            f = tf[idx]
            b = jnp.take_along_axis(bins, f[:, None], axis=1)[:, 0]
            is_na = b >= n_bins1_arr - 1
            go_left = jnp.where(is_na, tdl[idx], b <= tb[idx])
            nxt = 2 * idx + jnp.where(go_left, 1, 2)
            return jnp.where(tsp[idx], nxt, idx)

        idx = jax.lax.fori_loop(0, max_depth, body, idx)
        return carry + tlf[idx], None

    out, _ = jax.lax.scan(one_tree, jnp.zeros(bins.shape[0], jnp.float32), (feat, split_bin, default_left, is_split, leaf))
    return out


# ---------------------------------------------------------------------------
# training driver


class BoostedTrees:
    """Trained ensemble: per-class Trees + binning spec + init margin."""

    def __init__(
        self,
        trees_per_class: List[Trees],
        init_margin: np.ndarray,  # [C]
        params: TreeParams,
        average: bool = False,  # DRF averages instead of summing margins
    ):
        self.trees_per_class = trees_per_class
        self.init_margin = init_margin
        self.params = params
        self.average = average

    @property
    def nclasses_trees(self) -> int:
        return len(self.trees_per_class)

    def predict_margin(self, X: np.ndarray) -> np.ndarray:
        """Raw margins [N, C] from raw features (re-binned with stored edges)."""
        t0 = self.trees_per_class[0]
        bins = jnp.asarray(apply_bins(X, t0.edges))
        cols = []
        for c, trees in enumerate(self.trees_per_class):
            if trees.ntrees == 0:
                cols.append(np.full(X.shape[0], self.init_margin[c], dtype=np.float64))
                continue
            s = _predict_stacked(
                bins, *trees.stacked(), max_depth=trees.max_depth,
                n_bins1_arr=jnp.int32(trees.n_bins1),
            )
            s = np.asarray(jax.device_get(s), dtype=np.float64)
            if self.average:
                s = s / trees.ntrees
            cols.append(self.init_margin[c] + s)
        return np.stack(cols, axis=1)


def train_boosted(
    X: np.ndarray,
    grad_hess_fn: Callable[[np.ndarray], Tuple[jnp.ndarray, jnp.ndarray]],
    n_class_trees: int,
    init_margin: np.ndarray,
    params: TreeParams,
    average: bool = False,
    monitor: Optional[Callable[[int, np.ndarray], bool]] = None,
    mesh=None,
) -> BoostedTrees:
    """Generic booster loop.

    grad_hess_fn(margin[N, C]) -> (g[N, C], h[N, C]) on host or device.
    monitor(tree_idx, margin) -> True to stop early (ScoreKeeper hook).
    ``average=True`` gives DRF semantics (bagged trees, mean aggregation):
    each tree then fits the raw targets (grad_hess_fn ignores the margin).
    """
    n, F = X.shape
    p = params
    if mesh is None:
        mesh = default_mesh()
    nshards = mesh.devices.size

    edges = make_bins(X, p.nbins, seed=p.seed)
    bins_host = apply_bins(X, edges)
    n_bins1 = p.nbins + 1
    padn = (-n) % nshards
    if padn:
        bins_host = np.concatenate(
            [bins_host, np.zeros((padn, F), dtype=np.int32)], axis=0
        )
    bins_d = jax.device_put(bins_host, row_sharding(mesh, 2))
    n_pad = bins_host.shape[0]
    valid_row = np.arange(n_pad) < n

    margin = np.tile(np.asarray(init_margin, dtype=np.float32), (n, 1))  # [N, C]
    rng = np.random.default_rng(p.seed)
    trees_per_class = [Trees(p.max_depth, n_bins1, edges) for _ in range(n_class_trees)]

    key = jax.random.PRNGKey(p.seed)
    for t in range(p.ntrees):
        g_all, h_all = grad_hess_fn(margin)
        g_all = np.asarray(g_all, dtype=np.float32)
        h_all = np.asarray(h_all, dtype=np.float32)
        # row subsample for this boosting round
        if p.sample_rate < 1.0:
            row_mask = rng.random(n) < p.sample_rate
        else:
            row_mask = np.ones(n, dtype=bool)
        # per-tree column subsample
        if p.col_sample_rate_per_tree < 1.0:
            ncols = max(1, int(round(p.col_sample_rate_per_tree * F)))
            chosen = rng.choice(F, ncols, replace=False)
            feat_mask = np.zeros(F, dtype=bool)
            feat_mask[chosen] = True
        else:
            feat_mask = np.ones(F, dtype=bool)
        feat_mask_d = jnp.asarray(feat_mask)

        for c in range(n_class_trees):
            g = np.zeros(n_pad, dtype=np.float32)
            h = np.zeros(n_pad, dtype=np.float32)
            g[:n], h[:n] = g_all[:, c], h_all[:, c]
            g_d = jax.device_put(g, row_sharding(mesh, 1))
            h_d = jax.device_put(h, row_sharding(mesh, 1))
            active = row_mask
            if padn:
                active = np.concatenate([row_mask, np.zeros(padn, dtype=bool)])
            nodes0 = np.where(valid_row & active, 0, -1).astype(np.int32)
            nodes = jax.device_put(nodes0, row_sharding(mesh, 1))

            M = 2 ** (p.max_depth + 1) - 1
            t_feat = np.zeros(M, np.int32)
            t_bin = np.zeros(M, np.int32)
            t_dl = np.zeros(M, bool)
            t_sp = np.zeros(M, bool)
            t_lf = np.zeros(M, np.float32)

            for d in range(p.max_depth + 1):
                K = 2**d
                hist = build_histogram_sharded(
                    bins_d, nodes, g_d, h_d, n_nodes=K, n_bins1=n_bins1, mesh=mesh
                )
                if p.mtries > 0:
                    key, sub = jax.random.split(key)
                    r = jax.random.uniform(sub, (K, F))
                    thresh = jnp.sort(r, axis=1)[:, p.mtries - 1][:, None]
                    node_feat_mask = (r <= thresh) & feat_mask_d[None, :]
                else:
                    node_feat_mask = None
                bf, bb, dl, gain, leaf = _split_search(
                    hist,
                    jnp.float32(p.reg_lambda),
                    jnp.float32(p.reg_alpha),
                    jnp.float32(p.gamma),
                    jnp.float32(p.learn_rate),
                    feat_mask_d if node_feat_mask is None else node_feat_mask,
                    min_rows=float(p.min_rows),
                    n_bins1=n_bins1,
                )
                bf, bb, dl, gain, leaf = jax.device_get((bf, bb, dl, gain, leaf))
                lo = 2**d - 1
                can = (gain > max(p.min_split_improvement, 0.0)) & np.isfinite(gain) & (d < p.max_depth)
                t_feat[lo : lo + K] = bf
                t_bin[lo : lo + K] = bb
                t_dl[lo : lo + K] = dl
                t_sp[lo : lo + K] = can
                t_lf[lo : lo + K] = leaf
                if not can.any():
                    break
                nodes = _route_rows(
                    bins_d,
                    nodes,
                    jnp.asarray(bf),
                    jnp.asarray(bb),
                    jnp.asarray(dl),
                    jnp.asarray(can),
                    jnp.int32(n_bins1),
                )
            trees_per_class[c].append(t_feat, t_bin, t_dl, t_sp, t_lf)

            # margin update from this tree (full data, not just the sample)
            pred = _tree_predict_single(
                bins_d, jnp.asarray(t_feat), jnp.asarray(t_bin), jnp.asarray(t_dl),
                jnp.asarray(t_sp), jnp.asarray(t_lf), p.max_depth, jnp.int32(n_bins1),
            )
            margin[:, c] += np.asarray(jax.device_get(pred))[:n]

        if monitor is not None and monitor(t, margin):
            break

    if average:
        # DRF: margins were accumulated as sums; convert to means lazily at
        # predict; training margin conversion is the caller's concern.
        pass
    return BoostedTrees(trees_per_class, np.asarray(init_margin, np.float64), p, average=average)


@partial(jax.jit, static_argnames=("max_depth",))
def _tree_predict_single(bins, feat, split_bin, default_left, is_split, leaf, max_depth: int, n_bins1_arr):
    idx = jnp.zeros(bins.shape[0], dtype=jnp.int32)

    def body(_, idx):
        f = feat[idx]
        b = jnp.take_along_axis(bins, f[:, None], axis=1)[:, 0]
        is_na = b >= n_bins1_arr - 1
        go_left = jnp.where(is_na, default_left[idx], b <= split_bin[idx])
        nxt = 2 * idx + jnp.where(go_left, 1, 2)
        return jnp.where(is_split[idx], nxt, idx)

    idx = jax.lax.fori_loop(0, max_depth, body, idx)
    return leaf[idx]
