"""Shared plumbing for tree models: matrices, distributions, monitors.

Reference: trees consume raw (non-standardized) predictors with categorical
codes; ``hex/tree/SharedTree.java`` + ``hex/DataInfo`` handle the layout and
``hex/Distribution.java`` the gradient families. Categorical handling:
``categorical_encoding="label_encoder"`` (the default here) treats
categorical codes as ordinal bins (the reference's sorted enum mode);
``"one_hot_explicit"`` expands each level to an indicator feature
(``hex/DataInfo`` OneHotExplicit) — the tree can then isolate any level
subset via successive indicator splits, the dense stand-in for the
reference's set-valued splits (``hex/tree/DTree.java``).
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import numpy as np

from h2o3_tpu.frame.frame import ColType, Frame
from h2o3_tpu.models.data_info import DataInfo, _align_codes, build_data_info
from h2o3_tpu.models.framework import Model
from h2o3_tpu.models import metrics as M


def tree_data_info(frame: Frame, y: str, ignored=()) -> DataInfo:
    """Layout for tree models: raw numerics, label-encoded categoricals."""
    return build_data_info(
        frame, y=y, ignored=ignored, standardize=False, use_all_factor_levels=True
    )


TREE_ENCODINGS = ("auto", "enum", "label_encoder", "one_hot_explicit")


def resolve_tree_encoding(categorical_encoding: str) -> str:
    """Map the categorical_encoding param to a tree matrix layout."""
    if categorical_encoding in ("auto", "enum", "label_encoder"):
        return "label_encoder"
    if categorical_encoding == "one_hot_explicit":
        return "one_hot_explicit"
    raise ValueError(
        f"categorical_encoding {categorical_encoding!r} not supported for "
        f"tree models; choose from {TREE_ENCODINGS}"
    )


def tree_feature_names(info: DataInfo, encoding: str = "label_encoder") -> List[str]:
    """Feature names in tree_matrix column order (one-hot expands levels)."""
    names: List[str] = []
    for name in info.predictor_names:
        if encoding == "one_hot_explicit" and name in info.cat_domains:
            names += [f"{name}.{lv}" for lv in info.cat_domains[name]]
        else:
            names.append(name)
    return names


def tree_matrix(
    info: DataInfo, frame: Frame, encoding: str = "label_encoder"
) -> np.ndarray:
    """[N, F] float32 raw-feature matrix; NaN for NA.

    label_encoder: cat codes as ordinals (one column per predictor).
    one_hot_explicit: one 0/1 column per level; an NA row is NaN across the
    whole block so NA routing still learns a default direction per split.
    """
    cols = []
    for name in info.predictor_names:
        col = frame.col(name)
        if name in info.cat_domains:
            codes = _align_codes(col, info.cat_domains[name])
            if encoding == "one_hot_explicit":
                dom = info.cat_domains[name]
                block = (codes[:, None] == np.arange(len(dom))[None, :]).astype(
                    np.float32
                )
                block[codes < 0] = np.nan
                cols.append(block)
            else:
                cols.append(
                    np.where(codes >= 0, codes.astype(np.float32), np.nan)[:, None]
                )
        else:
            cols.append(col.numeric_view().astype(np.float32)[:, None])
    return np.concatenate(cols, axis=1)


# -- distributions (hex/Distribution.java gradient/hessian families) ---------


def sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def softmax(m):
    z = m - m.max(axis=1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=1, keepdims=True)


def grad_hess(distribution: str, y: np.ndarray, margin: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-row (g, h) of the loss wrt the margin. y: [N] (codes for classif),
    margin: [N, C]. Returns [N, C] arrays. Host oracle mirroring
    booster.grad_hess_device (parameterized families use 'name:arg')."""
    name, _, arg = distribution.partition(":")
    if name == "custom":
        from h2o3_tpu.udf import get_distribution

        g, h = get_distribution(arg)["grad_hess"](y, margin[:, 0])
        return (np.asarray(g, np.float64)[:, None],
                np.maximum(np.asarray(h, np.float64), 1e-16)[:, None])
    if name == "gaussian":
        g = margin[:, 0] - y
        return g[:, None], np.ones_like(g)[:, None]
    if name == "bernoulli":
        p = sigmoid(margin[:, 0])
        return (p - y)[:, None], np.maximum(p * (1 - p), 1e-16)[:, None]
    if name == "multinomial":
        p = softmax(margin)
        onehot = np.zeros_like(p)
        onehot[np.arange(len(y)), y.astype(np.int64)] = 1.0
        return p - onehot, np.maximum(p * (1 - p), 1e-16)
    if name == "poisson":
        mu = np.exp(margin[:, 0])
        return (mu - y)[:, None], np.maximum(mu, 1e-16)[:, None]
    if name == "gamma":
        ymf = y * np.exp(-margin[:, 0])
        return (1.0 - ymf)[:, None], np.maximum(ymf, 1e-16)[:, None]
    if name == "tweedie":
        pw = float(arg)
        a = y * np.exp((1.0 - pw) * margin[:, 0])
        b = np.exp((2.0 - pw) * margin[:, 0])
        return (b - a)[:, None], np.maximum((pw - 1) * a + (2 - pw) * b, 1e-16)[:, None]
    if name == "huber":
        delta = float(arg)
        r = margin[:, 0] - y
        return np.clip(r, -delta, delta)[:, None], np.ones_like(r)[:, None]
    if name == "laplace":
        g = np.sign(margin[:, 0] - y)
        return g[:, None], np.ones_like(g)[:, None]
    if name == "quantile" or distribution == "quantile_0.5":
        alpha = float(arg) if arg else 0.5
        g = np.where(margin[:, 0] < y, -alpha, 1.0 - alpha)
        return g[:, None], np.ones_like(g)[:, None]
    raise ValueError(f"unknown distribution {distribution!r}")


def _wmean(y: np.ndarray, w: Optional[np.ndarray]) -> float:
    if w is None:
        return float(np.nanmean(y))
    m = ~np.isnan(y)
    return float(np.average(y[m], weights=w[m]))


def _family_param(params, field: str, distribution: str) -> float:
    """A family parameter must exist on the builder's Parameters dataclass —
    a builder that lists a distribution but lacks its parameter would
    otherwise silently train with a hardcoded default (the
    accepted-and-ignored failure mode the param guard exists to prevent)."""
    val = getattr(params, field, None)
    if val is None:
        raise ValueError(
            f"distribution {distribution!r} needs parameter {field!r}, which "
            f"{type(params).__name__} does not declare"
        )
    return float(val)


def resolve_objective(distribution: str, params, y: np.ndarray) -> str:
    """Builder distribution name -> booster objective string, folding the
    family parameter in (``hex/Distribution.java``'s per-family params).
    huber: delta is the huber_alpha quantile of |y - median(y)| residuals
    (the reference re-estimates it per iteration; fixed-at-init here)."""
    if distribution.partition(":")[0] == "custom":
        from h2o3_tpu.udf import get_distribution

        name = distribution.partition(":")[2]
        if not name:
            raise ValueError(
                "custom distribution needs a name: 'custom:<registered>'")
        get_distribution(name)  # unregistered name fails HERE, not mid-train
        return distribution
    if distribution == "gamma":
        # gamma deviance needs strictly positive y (zero rows give ~0
        # hessians and exploding leaves; the reference validates this too)
        if np.nanmin(y) <= 0:
            raise ValueError("gamma requires a strictly positive response")
    elif distribution in ("poisson", "tweedie"):
        if np.nanmin(y) < 0:
            raise ValueError(f"{distribution} requires a non-negative response")
    if distribution == "tweedie":
        pw = _family_param(params, "tweedie_power", distribution)
        if not 1.0 < pw < 2.0:
            raise ValueError(f"tweedie_power must be in (1, 2), got {pw}")
        return f"tweedie:{pw}"
    if distribution == "quantile":
        alpha = _family_param(params, "quantile_alpha", distribution)
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"quantile_alpha must be in (0, 1), got {alpha}")
        return f"quantile:{alpha}"
    if distribution == "huber":
        ha = _family_param(params, "huber_alpha", distribution)
        r = np.abs(y - np.nanmedian(y))
        delta = max(float(np.nanquantile(r, ha)), 1e-10)
        return f"huber:{delta:.8g}"
    return distribution


def init_margin(
    distribution: str, y: np.ndarray, nclasses: int,
    weights: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Initial margin f0 (SharedTree init: response moments / priors),
    weighted when an observation-weights column is in play."""
    name, _, arg = distribution.partition(":")
    if name == "custom":
        from h2o3_tpu.udf import get_distribution

        init = get_distribution(arg)["init"]
        return np.array([float(init(y, weights)) if init is not None
                         else _wmean(y, weights)])
    if name in ("gaussian", "huber"):
        return np.array([_wmean(y, weights)])
    if name == "bernoulli":
        p = _wmean(y, weights)
        p = min(max(p, 1e-10), 1 - 1e-10)
        return np.array([np.log(p / (1 - p))])
    if name == "multinomial":
        m = ~np.isnan(y)
        w = weights[m] if weights is not None else None
        pri = np.bincount(
            y[m].astype(np.int64), weights=w, minlength=nclasses
        ).astype(np.float64)
        pri = np.maximum(pri / pri.sum(), 1e-10)
        return np.log(pri)
    if name in ("poisson", "gamma", "tweedie"):
        return np.array([np.log(max(_wmean(y, weights), 1e-10))])
    if name == "laplace" or distribution == "quantile_0.5":
        return np.array([float(np.nanmedian(y))])
    if name == "quantile":
        return np.array([float(np.nanquantile(y, float(arg)))])
    raise ValueError(f"unknown distribution {distribution!r}")


def margin_to_probs(distribution: str, margin: np.ndarray) -> np.ndarray:
    if distribution == "bernoulli":
        p = sigmoid(margin[:, 0])
        return np.stack([1 - p, p], axis=1)
    if distribution == "multinomial":
        return softmax(margin)
    return margin  # regression: identity


def link_inverse(distribution: str, margin: np.ndarray) -> np.ndarray:
    """Regression margin -> response scale (Distribution.linkInv): the
    log-link families train on log(mu), predictions report mu."""
    name, _, arg = distribution.partition(":")
    if name == "custom":
        from h2o3_tpu.udf import get_distribution

        inv = get_distribution(arg)["link_inv"]
        return np.asarray(inv(margin), np.float64) if inv is not None \
            else margin
    if name in ("poisson", "gamma", "tweedie"):
        return np.exp(margin)
    return margin


def auto_distribution(nclasses: int) -> str:
    if nclasses == 2:
        return "bernoulli"
    if nclasses > 2:
        return "multinomial"
    return "gaussian"


def training_score(
    distribution: str, y: np.ndarray, margin: np.ndarray,
    weights: Optional[np.ndarray] = None,
) -> float:
    """Scalar stopping metric from the current margin (deviance-flavored,
    weighted mean when observation weights are in play)."""

    def wavg(v):
        return float(np.average(v, weights=weights))

    name, _, arg = distribution.partition(":")
    if name == "bernoulli":
        p = np.clip(sigmoid(margin[:, 0]), 1e-15, 1 - 1e-15)
        return wavg(-(y * np.log(p) + (1 - y) * np.log(1 - p)))
    if name == "multinomial":
        p = softmax(margin)
        return wavg(-np.log(np.clip(p[np.arange(len(y)), y.astype(np.int64)], 1e-15, 1)))
    if name == "poisson":
        mu = np.exp(margin[:, 0])
        return wavg(2 * (np.where(y > 0, y * np.log(np.where(y > 0, y, 1) / mu), 0) - (y - mu)))
    if name == "gamma":
        mu = np.maximum(np.exp(margin[:, 0]), 1e-15)
        ys = np.maximum(y, 1e-15)
        return wavg(2 * (ys / mu - np.log(ys / mu) - 1))
    if name == "tweedie":
        pw = float(arg)
        mu = np.maximum(np.exp(margin[:, 0]), 1e-15)
        return wavg(
            2 * (
                np.power(np.maximum(y, 0), 2 - pw) / ((1 - pw) * (2 - pw))
                - y * np.power(mu, 1 - pw) / (1 - pw)
                + np.power(mu, 2 - pw) / (2 - pw)
            )
        )
    if name == "huber":
        delta = float(arg)
        r = np.abs(margin[:, 0] - y)
        return wavg(np.where(r <= delta, 0.5 * r * r, delta * (r - 0.5 * delta)))
    if name == "laplace":
        return wavg(np.abs(margin[:, 0] - y))
    if name == "quantile" or distribution == "quantile_0.5":
        alpha = float(arg) if arg else 0.5
        r = y - margin[:, 0]
        return wavg(np.where(r >= 0, alpha * r, (alpha - 1) * r))
    return wavg((margin[:, 0] - y) ** 2)


def tree_cache_token(frame: Frame, p, encoding: str):
    """Devcache identity of a booster's bin-code placement.

    The binned matrix is a pure function of (frame column versions, the
    categorical encoding, and the params that shape X / the keep mask:
    ignored + response + weights + offset columns) — algo-independent, so
    GBM/DRF/XGBoost fits on the same frame + binning spec share one entry.
    Returns None (cache bypass) for frames without version stamps."""
    from h2o3_tpu.frame import devcache

    if (getattr(frame, "chunk_layout", None) is not None
            and getattr(frame, "_materialized", None) is None):
        # chunk-homed frame, rows still on their homes: the layout stamp
        # identifies the distributed data state (chunks are immutable DKV
        # puts under (frame_key, stamp) keys; remove/rekey evicts via the
        # frame-key link) — the same identity the per-home bind cache
        # keys on, without materializing chunks just to stamp versions.
        # Once materialized, resident columns carry versions; use those
        # so caller-side mutations invalidate as usual.
        lay = frame.chunk_layout
        tok = ("dist", lay["frame_key"], lay["stamp"],
               int(lay["espc"][-1]))
    else:
        tok = devcache.frame_token(frame)
    if tok is None:
        return None
    return (
        tok, encoding, tuple(p.ignored_columns), p.response_column,
        getattr(p, "weights_column", None),
        getattr(p, "offset_column", None),
    )


def extract_weights(frame: Frame, p, keep: np.ndarray):
    """Load + validate weights_column, folding zero/NA-weight rows into the
    keep mask (dropping them is equivalent to the reference's zero
    contribution). Returns the [N] weights or None; index with keep after."""
    if not p.weights_column:
        return None
    weights = frame.col(p.weights_column).numeric_view().astype(np.float64)
    if np.nanmin(weights) < 0:
        raise ValueError("weights_column must be non-negative")
    keep &= ~np.isnan(weights) & (weights > 0)
    return weights


def tree_fit_setup(frame: Frame, p, model_cls, use_offset: bool):
    """Shared GBM/XGBoost front half of _fit: layout, matrices, aux columns,
    objective resolution, init margin, monotone validation.

    Returns (model, X, y, weights, offset, objective, f0, n_class_trees,
    mono) with the keep mask (NA response / zero-weight / NA-offset rows)
    already applied to X/y/weights/offset."""
    from h2o3_tpu.models.data_info import response_vector

    if getattr(frame, "chunk_layout", None) is not None:
        from h2o3_tpu.models.tree import dist_hist

        enc = resolve_tree_encoding(
            getattr(p, "categorical_encoding", "auto"))
        if dist_hist.use_dist(frame, p, enc):
            # chunk-homed frame + map-side engine eligible: rows stay on
            # their homes, only sketches/aux vectors gather once
            return dist_hist.dist_fit_setup(frame, p, model_cls, use_offset)
        # ineligible combination (knob off, checkpoint, monotone, custom
        # objective, explicit one-hot): materialize and run the legacy path

    ignored = list(p.ignored_columns)
    aux_cols = [p.weights_column] + ([p.offset_column] if use_offset else [])
    for aux in aux_cols:
        if aux and aux not in ignored:
            ignored.append(aux)
    info = tree_data_info(frame, p.response_column, ignored)
    y = response_vector(info, frame)
    nclasses = len(info.response_domain) if info.response_domain else 1
    dist = auto_distribution(nclasses) if p.distribution == "auto" else p.distribution

    model = model_cls(p, info, dist)
    enc = model.tree_encoding
    X = tree_matrix(info, frame, encoding=enc)
    keep = ~np.isnan(y)
    weights = extract_weights(frame, p, keep)
    offset = None
    if use_offset and p.offset_column:
        offset = frame.col(p.offset_column).numeric_view().astype(np.float64)
        keep &= ~np.isnan(offset)
    X, y = X[keep], y[keep]
    if weights is not None:
        weights = weights[keep]
    if offset is not None:
        offset = offset[keep]

    objective = resolve_objective(dist, p, y)
    f0 = init_margin(objective, y, nclasses, weights=weights)
    n_class_trees = nclasses if dist == "multinomial" else 1
    mono = monotone_array(getattr(p, "monotone_constraints", None), info, enc)
    if mono is not None and dist == "multinomial":
        # softmax normalization voids per-margin monotonicity; the
        # reference rejects this combination too (GBM.java validation)
        raise ValueError("monotone_constraints not supported for multinomial")
    return model, X, y, weights, offset, objective, f0, n_class_trees, mono


def make_tree_monitor(model, p, objective, y, weights, history):
    """ScoreKeeper monitor closure shared by GBM/XGBoost: wall-clock budget
    (max_runtime_secs) + stopping_rounds early stopping. Returns
    (monitor_or_None, score_interval): when only the deadline is active the
    interval stays at the device block size so the budget check does not
    force a host sync every tree."""
    import time as _time

    from h2o3_tpu.models.tree.booster import tree_block_size

    deadline = (_time.time() + p.max_runtime_secs) if p.max_runtime_secs > 0 else None

    def monitor(t: int, margin: np.ndarray) -> bool:
        model.ntrees_built = t + 1
        if deadline is not None and _time.time() >= deadline:
            return True
        if p.stopping_rounds <= 0 or (t + 1) % p.score_tree_interval:
            return False
        history.append(training_score(objective, y, margin, weights=weights))
        model.scoring_history.append({"tree": t + 1, "score": history[-1]})
        return M.stop_early(
            history, p.stopping_rounds, more_is_better=False,
            stopping_tolerance=p.stopping_tolerance,
        )

    if p.stopping_rounds > 0:
        return monitor, p.score_tree_interval
    if deadline is not None:
        return monitor, max(p.score_tree_interval, tree_block_size())
    return None, p.score_tree_interval


def checkpoint_booster(
    p, n_class_trees: int, algo_name: str = None,
    n_features: int = None, encoding: str = None,
):
    """Resolve the ``checkpoint`` param to the prior model's booster
    (checkpoint-continue, ``hex/tree/SharedTree.java:131-136``). The
    reference validates that non-modifiable params match the checkpoint
    (CheckpointUtils); here: same algo, class count, depth, binning, and
    feature layout (count + categorical encoding) — trees from two
    different layouts index features incompatibly."""
    if not p.checkpoint:
        return None
    from h2o3_tpu.keyed import DKV

    prior = DKV.get(p.checkpoint)
    if prior is None:
        raise ValueError(f"checkpoint model {p.checkpoint!r} not found")
    b = getattr(prior, "booster", None)
    if b is None:
        raise ValueError(f"checkpoint model {p.checkpoint!r} is not a tree model")
    if algo_name is not None and getattr(prior, "algo_name", None) != algo_name:
        raise ValueError(
            f"checkpoint model is {getattr(prior, 'algo_name', '?')!r}, "
            f"cannot continue it as {algo_name!r}"
        )
    if b.nclasses_trees != n_class_trees:
        raise ValueError("checkpoint class count differs from this training frame")
    t0 = b.trees_per_class[0]
    if t0.max_depth != p.max_depth:
        raise ValueError(
            f"checkpoint max_depth={t0.max_depth} differs from requested {p.max_depth}"
        )
    if t0.n_bins1 != p.nbins + 1:
        raise ValueError(
            f"checkpoint nbins={t0.n_bins1 - 1} differs from requested {p.nbins}"
        )
    if n_features is not None and t0.edges.shape[0] != n_features:
        raise ValueError(
            f"checkpoint was trained on {t0.edges.shape[0]} tree features, "
            f"this frame/encoding produces {n_features}"
        )
    prior_enc = getattr(prior, "tree_encoding", None)
    if encoding is not None and prior_enc is not None and prior_enc != encoding:
        raise ValueError(
            f"checkpoint categorical_encoding={prior_enc!r} differs from "
            f"requested {encoding!r}"
        )
    return b


def extra_trees(p, n_class_trees: int) -> int:
    """Trees still to build on top of the checkpoint; ``ntrees`` is the TOTAL
    (reference: restart validation requires ntrees > checkpoint's)."""
    b = checkpoint_booster(p, n_class_trees)
    if b is None:
        return p.ntrees
    built = b.trees_per_class[0].ntrees
    if p.ntrees <= built:
        raise ValueError(
            f"checkpoint already has {built} trees; ntrees={p.ntrees} must exceed it"
        )
    return p.ntrees - built


def monotone_array(
    constraints: Optional[dict], info: DataInfo, encoding: str
) -> Optional[np.ndarray]:
    """monotone_constraints dict {col: ±1} -> per-tree-feature int array.

    Reference semantics (hex/tree/gbm/GBM.java monotone validation):
    constraints apply to numeric predictors only; unknown columns and
    categorical columns are errors, not silently dropped."""
    if not constraints:
        return None
    names = tree_feature_names(info, encoding)
    arr = np.zeros(len(names), dtype=np.int32)
    for col, direction in constraints.items():
        if direction not in (-1, 0, 1):
            raise ValueError(
                f"monotone_constraints[{col!r}] must be -1, 0 or 1, got {direction!r}"
            )
        if col in info.cat_domains:
            raise ValueError(
                f"monotone_constraints not supported on categorical column {col!r}"
            )
        if col not in names:
            raise ValueError(f"monotone_constraints column {col!r} not in predictors")
        arr[names.index(col)] = direction
    return arr


class TreeModelBase(Model):
    """Common prediction path for GBM/DRF/XGBoost models."""

    def __init__(self, params, data_info, distribution: str):
        super().__init__(params, data_info)
        self.distribution = distribution
        self.booster = None  # BoostedTrees
        self.ntrees_built = 0
        self.tree_encoding = resolve_tree_encoding(
            getattr(params, "categorical_encoding", "auto")
        )

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        X = tree_matrix(self.data_info, frame, encoding=self.tree_encoding)
        margin = self.booster.predict_margin(X)
        off = getattr(self.params, "offset_column", None)
        if off:
            # Model.score: the offset column of the SCORING frame shifts the
            # margin (hex/Model.java adaptTestForTrain offset handling)
            if off not in frame.names:
                raise ValueError(
                    f"offset_column {off!r} must be present in the scoring frame"
                )
            off_vals = frame.col(off).numeric_view()
            if np.isnan(off_vals).any():
                # match the MOJO scorer: loud, not silently-NaN predictions
                raise ValueError(
                    f"offset_column {off!r} has NA values in the scoring frame"
                )
            margin = margin + off_vals[:, None]
        return self._raw_from_margin(margin)

    def _raw_from_margin(self, margin: np.ndarray) -> np.ndarray:
        """Raw scores (probabilities / inverse-linked response) from the
        ensemble margin — shared by the materializing predict path and the
        distributed fit's margin-resident scoring."""
        return (
            margin_to_probs(self.distribution, margin)
            if self.is_classifier
            else link_inverse(self.distribution, margin[:, 0])
        )

    def model_performance(self, frame: Frame) -> Any:
        ev = getattr(self.booster, "dist_eval", None)
        if ev is not None and frame is ev["frame"]:
            # the distributed fit already holds this frame's final margins
            # (over its kept rows) — score them without materializing rows
            return self._metrics_from_dist(ev)
        return super().model_performance(frame)

    def _metrics_from_dist(self, ev: dict) -> Any:
        raw = self._raw_from_margin(np.asarray(ev["margin"], np.float64))
        y = np.asarray(ev["y"], np.float64)
        w = ev.get("w")
        if not self.is_classifier:
            return M.regression_metrics(y, raw, weights=w)
        if self.nclasses == 2:
            return M.binomial_metrics(y, raw[:, 1], weights=w)
        return M.multinomial_metrics(
            y.astype(np.int64), raw, self.data_info.response_domain,
            weights=w)

    def predict_contributions(self, frame: Frame, background_frame=None) -> Frame:
        """Exact per-feature SHAP contributions on the margin scale
        (Model.scoreContributions / TreeSHAPPredictor): one column per tree
        feature plus BiasTerm; rows sum to the raw margin exactly."""
        from h2o3_tpu.frame.frame import Column
        from h2o3_tpu.models.tree.shap import predict_contributions as _pc

        contribs = _pc(self, frame, background_frame=background_frame)
        names = tree_feature_names(self.data_info, self.tree_encoding)
        cols = [
            Column(names[j], contribs[:, j], ColType.NUM)
            for j in range(len(names))
        ]
        cols.append(Column("BiasTerm", contribs[:, -1], ColType.NUM))
        return Frame(cols)

    def variable_importances(self) -> dict:
        """Split-count/gain-weighted importances (SharedTree varimp analogue:
        squared-error reduction summed per feature)."""
        names = tree_feature_names(self.data_info, self.tree_encoding)
        imp = np.zeros(len(names))
        for trees in self.booster.trees_per_class:
            for t in range(trees.ntrees):
                sp = trees.is_split[t]
                feats = trees.feat[t][sp]
                np.add.at(imp, feats, 1.0)
        total = imp.sum()
        rel = imp / total if total > 0 else imp
        return dict(zip(names, rel.tolist()))
