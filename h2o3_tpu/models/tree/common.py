"""Shared plumbing for tree models: matrices, distributions, monitors.

Reference: trees consume raw (non-standardized) predictors with categorical
codes; ``hex/tree/SharedTree.java`` + ``hex/DataInfo`` handle the layout and
``hex/Distribution.java`` the gradient families. Categorical handling note:
the reference can split categorical sets directly; this build currently
treats categorical codes as ordinal bins (equivalent to the reference's
``categorical_encoding=label_encoder`` / sorted enum mode) — set-valued
splits are a planned refinement.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.data_info import DataInfo, _align_codes, build_data_info
from h2o3_tpu.models.framework import Model
from h2o3_tpu.models import metrics as M


def tree_data_info(frame: Frame, y: str, ignored=()) -> DataInfo:
    """Layout for tree models: raw numerics, label-encoded categoricals."""
    return build_data_info(
        frame, y=y, ignored=ignored, standardize=False, use_all_factor_levels=True
    )


def tree_matrix(info: DataInfo, frame: Frame) -> np.ndarray:
    """[N, F] float32 raw-feature matrix; cat codes as ordinals, NaN for NA."""
    cols = []
    for name in info.predictor_names:
        col = frame.col(name)
        if name in info.cat_domains:
            codes = _align_codes(col, info.cat_domains[name])
            cols.append(np.where(codes >= 0, codes.astype(np.float32), np.nan))
        else:
            cols.append(col.numeric_view().astype(np.float32))
    return np.stack(cols, axis=1)


# -- distributions (hex/Distribution.java gradient/hessian families) ---------


def sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def softmax(m):
    z = m - m.max(axis=1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=1, keepdims=True)


def grad_hess(distribution: str, y: np.ndarray, margin: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Per-row (g, h) of the loss wrt the margin. y: [N] (codes for classif),
    margin: [N, C]. Returns [N, C] arrays."""
    if distribution == "gaussian":
        g = margin[:, 0] - y
        return g[:, None], np.ones_like(g)[:, None]
    if distribution == "bernoulli":
        p = sigmoid(margin[:, 0])
        return (p - y)[:, None], np.maximum(p * (1 - p), 1e-16)[:, None]
    if distribution == "multinomial":
        p = softmax(margin)
        onehot = np.zeros_like(p)
        onehot[np.arange(len(y)), y.astype(np.int64)] = 1.0
        return p - onehot, np.maximum(p * (1 - p), 1e-16)
    if distribution == "poisson":
        mu = np.exp(margin[:, 0])
        return (mu - y)[:, None], np.maximum(mu, 1e-16)[:, None]
    if distribution == "laplace":
        g = np.sign(margin[:, 0] - y)
        return g[:, None], np.ones_like(g)[:, None]
    if distribution == "quantile_0.5":
        g = np.where(margin[:, 0] > y, 0.5, -0.5)
        return g[:, None], np.ones_like(g)[:, None]
    raise ValueError(f"unknown distribution {distribution!r}")


def init_margin(distribution: str, y: np.ndarray, nclasses: int) -> np.ndarray:
    """Initial margin f0 (SharedTree init: response moments / priors)."""
    if distribution == "gaussian":
        return np.array([float(np.nanmean(y))])
    if distribution == "bernoulli":
        p = float(np.nanmean(y))
        p = min(max(p, 1e-10), 1 - 1e-10)
        return np.array([np.log(p / (1 - p))])
    if distribution == "multinomial":
        pri = np.bincount(y[~np.isnan(y)].astype(np.int64), minlength=nclasses).astype(np.float64)
        pri = np.maximum(pri / pri.sum(), 1e-10)
        return np.log(pri)
    if distribution == "poisson":
        return np.array([np.log(max(float(np.nanmean(y)), 1e-10))])
    if distribution in ("laplace", "quantile_0.5"):
        return np.array([float(np.nanmedian(y))])
    raise ValueError(f"unknown distribution {distribution!r}")


def margin_to_probs(distribution: str, margin: np.ndarray) -> np.ndarray:
    if distribution == "bernoulli":
        p = sigmoid(margin[:, 0])
        return np.stack([1 - p, p], axis=1)
    if distribution == "multinomial":
        return softmax(margin)
    return margin  # regression: identity


def auto_distribution(nclasses: int) -> str:
    if nclasses == 2:
        return "bernoulli"
    if nclasses > 2:
        return "multinomial"
    return "gaussian"


def training_score(distribution: str, y: np.ndarray, margin: np.ndarray) -> float:
    """Scalar stopping metric from the current margin (deviance-flavored)."""
    if distribution == "bernoulli":
        p = np.clip(sigmoid(margin[:, 0]), 1e-15, 1 - 1e-15)
        return float(np.mean(-(y * np.log(p) + (1 - y) * np.log(1 - p))))
    if distribution == "multinomial":
        p = softmax(margin)
        return float(np.mean(-np.log(np.clip(p[np.arange(len(y)), y.astype(np.int64)], 1e-15, 1))))
    if distribution == "poisson":
        mu = np.exp(margin[:, 0])
        return float(np.mean(2 * (np.where(y > 0, y * np.log(np.where(y > 0, y, 1) / mu), 0) - (y - mu))))
    return float(np.mean((margin[:, 0] - y) ** 2))


def checkpoint_booster(p, n_class_trees: int, algo_name: str = None):
    """Resolve the ``checkpoint`` param to the prior model's booster
    (checkpoint-continue, ``hex/tree/SharedTree.java:131-136``). The
    reference validates that non-modifiable params match the checkpoint
    (CheckpointUtils); here: same algo, class count, depth, and binning."""
    if not p.checkpoint:
        return None
    from h2o3_tpu.keyed import DKV

    prior = DKV.get(p.checkpoint)
    if prior is None:
        raise ValueError(f"checkpoint model {p.checkpoint!r} not found")
    b = getattr(prior, "booster", None)
    if b is None:
        raise ValueError(f"checkpoint model {p.checkpoint!r} is not a tree model")
    if algo_name is not None and getattr(prior, "algo_name", None) != algo_name:
        raise ValueError(
            f"checkpoint model is {getattr(prior, 'algo_name', '?')!r}, "
            f"cannot continue it as {algo_name!r}"
        )
    if b.nclasses_trees != n_class_trees:
        raise ValueError("checkpoint class count differs from this training frame")
    t0 = b.trees_per_class[0]
    if t0.max_depth != p.max_depth:
        raise ValueError(
            f"checkpoint max_depth={t0.max_depth} differs from requested {p.max_depth}"
        )
    if t0.n_bins1 != p.nbins + 1:
        raise ValueError(
            f"checkpoint nbins={t0.n_bins1 - 1} differs from requested {p.nbins}"
        )
    return b


def extra_trees(p, n_class_trees: int) -> int:
    """Trees still to build on top of the checkpoint; ``ntrees`` is the TOTAL
    (reference: restart validation requires ntrees > checkpoint's)."""
    b = checkpoint_booster(p, n_class_trees)
    if b is None:
        return p.ntrees
    built = b.trees_per_class[0].ntrees
    if p.ntrees <= built:
        raise ValueError(
            f"checkpoint already has {built} trees; ntrees={p.ntrees} must exceed it"
        )
    return p.ntrees - built


class TreeModelBase(Model):
    """Common prediction path for GBM/DRF/XGBoost models."""

    def __init__(self, params, data_info, distribution: str):
        super().__init__(params, data_info)
        self.distribution = distribution
        self.booster = None  # BoostedTrees
        self.ntrees_built = 0

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        X = tree_matrix(self.data_info, frame)
        margin = self.booster.predict_margin(X)
        return (
            margin_to_probs(self.distribution, margin)
            if self.is_classifier
            else margin[:, 0]
        )

    def variable_importances(self) -> dict:
        """Split-count/gain-weighted importances (SharedTree varimp analogue:
        squared-error reduction summed per feature)."""
        imp = np.zeros(len(self.data_info.predictor_names))
        for trees in self.booster.trees_per_class:
            for t in range(trees.ntrees):
                sp = trees.is_split[t]
                feats = trees.feat[t][sp]
                np.add.at(imp, feats, 1.0)
        total = imp.sum()
        rel = imp / total if total > 0 else imp
        return dict(zip(self.data_info.predictor_names, rel.tolist()))
