"""GBM — H2O-style gradient boosting on the tpu_hist booster core.

Reference: ``hex/tree/gbm/GBM.java:452,493,571`` (buildNextKTrees / growTrees),
distributions from ``hex/Distribution.java``, defaults from GBMParametersV3.
One tree per class per iteration (SharedTree k-trees), Newton leaf values,
row/column sampling, ScoreKeeper early stopping.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import metrics as M
from h2o3_tpu.models.data_info import response_vector
from h2o3_tpu.models.framework import ModelBuilder, ModelParameters
from h2o3_tpu.models.tree.booster import TreeParams, train_boosted
from h2o3_tpu.models.tree.common import (
    TreeModelBase,
    auto_distribution,
    checkpoint_booster as _checkpoint_booster,
    extra_trees as _extra_trees,
    init_margin,
    training_score,
    tree_data_info,
    tree_matrix,
)


@dataclass
class GBMParameters(ModelParameters):
    ntrees: int = 50
    max_depth: int = 5
    learn_rate: float = 0.1
    nbins: int = 20  # reference GBM default nbins=20 (GBMParametersV3)
    min_rows: float = 10.0
    min_split_improvement: float = 1e-5
    sample_rate: float = 1.0
    col_sample_rate_per_tree: float = 1.0
    distribution: str = "auto"
    score_tree_interval: int = 1


class GBMModel(TreeModelBase):
    algo_name = "gbm"


class GBM(ModelBuilder):

    SUPPORTED_COMMON = frozenset({"checkpoint", "stopping_rounds"})
    algo_name = "gbm"

    def __init__(self, params: Optional[GBMParameters] = None, **kw) -> None:
        super().__init__(params or GBMParameters(**kw))

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> GBMModel:
        p: GBMParameters = self.params
        info = tree_data_info(frame, p.response_column, p.ignored_columns)
        y = response_vector(info, frame)
        nclasses = len(info.response_domain) if info.response_domain else 1
        dist = auto_distribution(nclasses) if p.distribution == "auto" else p.distribution

        model = GBMModel(p, info, dist)
        X = tree_matrix(info, frame)
        keep = ~np.isnan(y)
        X, y = X[keep], y[keep]

        f0 = init_margin(dist, y, nclasses)
        n_class_trees = nclasses if dist == "multinomial" else 1

        tp = TreeParams(
            ntrees=_extra_trees(p, n_class_trees),
            max_depth=p.max_depth,
            learn_rate=p.learn_rate,
            nbins=p.nbins,
            min_rows=p.min_rows,
            min_split_improvement=p.min_split_improvement,
            reg_lambda=0.0,  # the reference GBM has no leaf L2
            reg_alpha=0.0,
            sample_rate=p.sample_rate,
            col_sample_rate_per_tree=p.col_sample_rate_per_tree,
            seed=p.actual_seed(),
        )

        history = []

        def monitor(t: int, margin: np.ndarray) -> bool:
            model.ntrees_built = t + 1
            if p.stopping_rounds <= 0 or (t + 1) % p.score_tree_interval:
                return False
            history.append(training_score(dist, y, margin))
            model.scoring_history.append({"tree": t + 1, "score": history[-1]})
            return M.stop_early(
                history, p.stopping_rounds, more_is_better=False,
                stopping_tolerance=p.stopping_tolerance,
            )

        model.booster = train_boosted(
            X,
            objective=dist,
            y=y,
            n_class_trees=n_class_trees,
            init_margin=f0,
            params=tp,
            monitor=monitor if p.stopping_rounds > 0 else None,
            score_interval=p.score_tree_interval,
            resume_from=_checkpoint_booster(p, n_class_trees, self.algo_name),
        )
        model.ntrees_built = model.booster.trees_per_class[0].ntrees
        model.training_metrics = model.model_performance(frame)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model
