"""GBM — H2O-style gradient boosting on the tpu_hist booster core.

Reference: ``hex/tree/gbm/GBM.java:452,493,571`` (buildNextKTrees / growTrees),
distributions from ``hex/Distribution.java``, defaults from GBMParametersV3.
One tree per class per iteration (SharedTree k-trees), Newton leaf values,
row/column sampling, ScoreKeeper early stopping.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.framework import ModelBuilder, ModelParameters
from h2o3_tpu.models.tree.booster import TreeParams, train_boosted
from h2o3_tpu.models.tree.common import (
    TreeModelBase,
    checkpoint_booster as _checkpoint_booster,
    extra_trees as _extra_trees,
    make_tree_monitor,
    tree_cache_token,
    tree_fit_setup,
)


@dataclass
class GBMParameters(ModelParameters):
    ntrees: int = 50
    max_depth: int = 5
    learn_rate: float = 0.1
    nbins: int = 20  # reference GBM default nbins=20 (GBMParametersV3)
    min_rows: float = 10.0
    min_split_improvement: float = 1e-5
    sample_rate: float = 1.0
    col_sample_rate_per_tree: float = 1.0
    distribution: str = "auto"
    score_tree_interval: int = 1
    tweedie_power: float = 1.5  # hex/Distribution.java tweedie variance power
    quantile_alpha: float = 0.5
    huber_alpha: float = 0.9
    monotone_constraints: Optional[dict] = None  # {col: -1|+1}


class GBMModel(TreeModelBase):
    algo_name = "gbm"


class GBM(ModelBuilder):

    SUPPORTED_COMMON = frozenset(
        {
            "checkpoint",
            "stopping_rounds",
            "weights_column",
            "offset_column",
            "categorical_encoding",
            "max_runtime_secs",
        }
    )
    algo_name = "gbm"

    def __init__(self, params: Optional[GBMParameters] = None, **kw) -> None:
        super().__init__(params or GBMParameters(**kw))

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> GBMModel:
        p: GBMParameters = self.params
        model, X, y, weights, offset, objective, f0, n_class_trees, mono = (
            tree_fit_setup(frame, p, GBMModel, use_offset=True)
        )

        tp = TreeParams(
            ntrees=_extra_trees(p, n_class_trees),
            max_depth=p.max_depth,
            learn_rate=p.learn_rate,
            nbins=p.nbins,
            min_rows=p.min_rows,
            min_split_improvement=p.min_split_improvement,
            reg_lambda=0.0,  # the reference GBM has no leaf L2
            reg_alpha=0.0,
            sample_rate=p.sample_rate,
            col_sample_rate_per_tree=p.col_sample_rate_per_tree,
            seed=p.actual_seed(),
        )

        history = []
        monitor, score_interval = make_tree_monitor(
            model, p, objective, y, weights, history
        )
        model.booster = train_boosted(
            X,
            objective=objective,
            y=y,
            n_class_trees=n_class_trees,
            init_margin=f0,
            params=tp,
            monitor=monitor,
            score_interval=score_interval,
            resume_from=_checkpoint_booster(
                p, n_class_trees, self.algo_name,
                n_features=X.shape[1], encoding=model.tree_encoding,
            ),
            weights=weights,
            offset=offset,
            monotone=mono,
            cache_token=tree_cache_token(frame, p, model.tree_encoding),
            cache_frame_key=getattr(frame, "key", None),
        )
        model.ntrees_built = model.booster.trees_per_class[0].ntrees
        model.training_metrics = model.model_performance(frame)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model
