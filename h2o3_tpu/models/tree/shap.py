"""Exact per-feature prediction contributions for tree ensembles (TreeSHAP).

Reference: ``h2o-genmodel/.../algos/tree/TreeSHAP.java`` /
``TreeSHAPPredictor.java`` — H2O's ``predict_contributions`` computes exact
SHAP values per feature with the polynomial-time TreeSHAP recursion
(Lundberg's Algorithm 2: the EXTEND/UNWIND path bookkeeping), satisfying
the local-accuracy property: contributions + bias == the raw margin.

Design notes: the reference walks its CompressedTree with node weights
recorded at training time. Our heap-layout trees carry no covers, so they
are computed here by routing a background frame (default: the scoring
frame) through each tree — which also makes the background distribution an
explicit, user-controllable choice. Cover computation is vectorized numpy;
the per-row recursion is host-side Python over depth <= ~7 paths (tiny).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np


def node_covers(feat, split_bin, default_left, is_split, bins, n_bins1: int,
                max_depth: int) -> np.ndarray:
    """Per-heap-node row counts from routing `bins` [N, F] down one tree."""
    M = len(feat)
    idx = np.zeros(bins.shape[0], dtype=np.int64)
    covers = np.zeros(M, dtype=np.float64)
    np.add.at(covers, idx, 1.0)
    for _ in range(max_depth):
        f = feat[idx]
        b = bins[np.arange(bins.shape[0]), f]
        is_na = b >= n_bins1 - 1
        go_left = np.where(is_na, default_left[idx], b <= split_bin[idx])
        nxt = 2 * idx + np.where(go_left, 1, 2)
        moved = is_split[idx]
        idx = np.where(moved, nxt, idx)
        np.add.at(covers, idx[moved], 1.0)
    return covers


class _Path:
    """The unique-path state of the TreeSHAP recursion."""

    __slots__ = ("d", "z", "o", "w")

    def __init__(self) -> None:
        self.d: List[int] = []   # feature index (-1 at the root slot)
        self.z: List[float] = []  # fraction of zero (background) paths
        self.o: List[float] = []  # fraction of one (this row's) paths
        self.w: List[float] = []  # permutation weights

    def copy(self) -> "_Path":
        p = _Path()
        p.d = self.d[:]
        p.z = self.z[:]
        p.o = self.o[:]
        p.w = self.w[:]
        return p

    def extend(self, pz: float, po: float, pi: int) -> None:
        l = len(self.d)
        self.d.append(pi)
        self.z.append(pz)
        self.o.append(po)
        self.w.append(1.0 if l == 0 else 0.0)
        for i in range(l - 1, -1, -1):
            self.w[i + 1] += po * self.w[i] * (i + 1) / (l + 1)
            self.w[i] = pz * self.w[i] * (l - i) / (l + 1)

    def unwind(self, i: int) -> None:
        l = len(self.d) - 1
        po, pz = self.o[i], self.z[i]
        n = self.w[l]
        for j in range(l - 1, -1, -1):
            if po != 0:
                t = self.w[j]
                self.w[j] = n * (l + 1) / ((j + 1) * po)
                n = t - self.w[j] * pz * (l - j) / (l + 1)
            else:
                self.w[j] = self.w[j] * (l + 1) / (pz * (l - j))
        for j in range(i, l):
            self.d[j] = self.d[j + 1]
            self.z[j] = self.z[j + 1]
            self.o[j] = self.o[j + 1]
        del self.d[l], self.z[l], self.o[l], self.w[l]

    def unwound_sum(self, i: int) -> float:
        l = len(self.d) - 1
        po, pz = self.o[i], self.z[i]
        total = 0.0
        n = self.w[l]
        for j in range(l - 1, -1, -1):
            if po != 0:
                t = n * (l + 1) / ((j + 1) * po)
                total += t
                n = self.w[j] - t * pz * (l - j) / (l + 1)
            else:
                total += self.w[j] * (l + 1) / (pz * (l - j))
        return total


def tree_shap_row(
    feat, split_bin, default_left, is_split, leaf, covers,
    x_bins: np.ndarray, n_bins1: int, phi: np.ndarray,
) -> None:
    """Accumulate one tree's exact SHAP contributions for one row into phi
    (length F + 1; last slot is the bias). Lundberg Algorithm 2."""
    phi[-1] += leaf[0] if not is_split[0] else 0.0

    def hot_child(node: int) -> Tuple[int, int]:
        f, sb = int(feat[node]), int(split_bin[node])
        b = int(x_bins[f])
        go_left = default_left[node] if b >= n_bins1 - 1 else b <= sb
        l, r = 2 * node + 1, 2 * node + 2
        return (l, r) if go_left else (r, l)

    def recurse(node: int, path: _Path, pz: float, po: float, pi: int) -> None:
        path = path.copy()
        path.extend(pz, po, pi)
        if not is_split[node]:
            v = float(leaf[node])
            for i in range(1, len(path.d)):
                w = path.unwound_sum(i)
                phi[path.d[i]] += w * (path.o[i] - path.z[i]) * v
            return
        f = int(feat[node])
        hot, cold = hot_child(node)
        iz, io = 1.0, 1.0
        k = next((i for i in range(1, len(path.d)) if path.d[i] == f), None)
        if k is not None:
            iz, io = path.z[k], path.o[k]
            path.unwind(k)
        cov = covers[node] if covers[node] > 0 else 1.0
        recurse(hot, path, iz * covers[hot] / cov, io, f)
        recurse(cold, path, iz * covers[cold] / cov, 0.0, f)

    if is_split[0]:
        recurse(0, _Path(), 1.0, 1.0, -1)
        # the bias is E[f(x)] over the background: cover-weighted leaf mean
        total = 0.0
        stack = [0]
        while stack:
            node = stack.pop()
            if not is_split[node]:
                total += covers[node] * float(leaf[node])
            else:
                stack.append(2 * node + 1)
                stack.append(2 * node + 2)
        phi[-1] += total / max(covers[0], 1e-300)


def predict_contributions(
    model,
    frame,
    background_frame=None,
) -> "np.ndarray":
    """[N, F+1] exact SHAP contributions (+ bias last) on the margin scale
    (Model.predict_contributions / /3/Predictions ``predict_contributions``).

    Local accuracy: rows sum (plus init margin) to predict_margin exactly.
    """
    from h2o3_tpu.models.tree.common import tree_matrix
    from h2o3_tpu.ops.histogram import apply_bins

    b = model.booster
    if len(b.trees_per_class) != 1:
        raise ValueError(
            "predict_contributions supports regression/binomial models"
        )
    trees = b.trees_per_class[0]
    X = tree_matrix(model.data_info, frame, encoding=model.tree_encoding)
    bins = apply_bins(X, trees.edges)
    if background_frame is None:
        bg_bins = bins
    else:
        bg = tree_matrix(model.data_info, background_frame,
                         encoding=model.tree_encoding)
        bg_bins = apply_bins(bg, trees.edges)

    n, F = bins.shape
    out = np.zeros((n, F + 1), dtype=np.float64)
    n_bins1 = trees.n_bins1
    for t in range(trees.ntrees):
        feat = trees.feat[t]
        sb = trees.split_bin[t]
        dl = trees.default_left[t]
        sp = trees.is_split[t]
        lf = trees.leaf[t].astype(np.float64)
        covers = node_covers(feat, sb, dl, sp, bg_bins, n_bins1, trees.max_depth)
        for i in range(n):
            tree_shap_row(feat, sb, dl, sp, lf, covers, bins[i], n_bins1, out[i])
    if b.average and trees.ntrees:
        out /= trees.ntrees
    out[:, -1] += float(b.init_margin[0])
    return out
