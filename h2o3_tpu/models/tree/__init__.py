from h2o3_tpu.models.tree.booster import BoostedTrees, TreeParams, train_boosted
from h2o3_tpu.models.tree.gbm import GBM, GBMModel, GBMParameters
from h2o3_tpu.models.tree.drf import DRF, DRFModel, DRFParameters
from h2o3_tpu.models.tree.xgboost import XGBoost, XGBoostModel, XGBoostParameters

__all__ = [
    "BoostedTrees",
    "TreeParams",
    "train_boosted",
    "GBM",
    "GBMModel",
    "GBMParameters",
    "DRF",
    "DRFModel",
    "DRFParameters",
    "XGBoost",
    "XGBoostModel",
    "XGBoostParameters",
]
