"""Map-side distributed histogram tree building over chunk homes.

The booster's private-then-merge core (``ScoreBuildHistogram2``) moved to
the cluster: when the training frame is a chunk-homed :class:`DistFrame`,
each home keeps a per-fit context (bin codes, margins, node positions)
and per tree level only ``(feature, bin, {Sum g, Sum h, Sum w})`` histogram
partials and the chosen splits cross the wire — never rows.

Protocol (six ctx-DTasks, one global monotonic ``seq`` per fit):

``hist_open``
    seq 0 — assemble the group's local columns from the ring, filter rows
    the single-node path would drop (NaN response/weight/offset, weight
    <= 0), sketch every feature for global binning, and ship the one-time
    auxiliary vectors (y, w, offset) the caller needs for grad/hess-free
    bookkeeping.  Creates the context (``last_seq = 0``).
``hist_bind``
    seq 1 — receive the merged global edges, bin locally
    (``apply_bins`` never ships bin codes), drop the raw feature matrix,
    and install the fit parameters (f0, objective, seed, sample rate).
    The binned-code matrix is served resident from the device frame cache
    (keyed on layout stamp + bin-edges digest), so a repeat fit on an
    unmutated frame decodes and uploads nothing.
``hist_level``
    one op per level: ``level`` (apply parent routes, build this level's
    histogram partial — small side only under subtraction), ``totals``
    (terminal node G/H/W totals), ``fin`` (apply terminal routes, add the
    finished tree's leaf values into the local margins), and the seq-free
    ``margins`` read-back.
``hist_levels``
    several ``hist_level`` rounds in one RPC: output-free ``fin`` ops are
    deferred caller-side (``H2O3_TPU_DIST_HIST_BATCH``) and ride with the
    next output-bearing op — each item fences its own seq in issue order,
    so state and recovery are exactly the sequential rounds'.
``hist_replay``
    recovery: rebuild a lost context from the caller's op log (open +
    bind + every routing-relevant op replayed without building output),
    then fence at the caller's seq.
``hist_fin``
    drop the context.

Every context mutation is fenced: an op whose ``seq`` is not exactly
``last_seq + 1`` raises 409 and the caller replays, so a home that missed
a level (or a survivor adopting a dead home's group) converges to the
exact same state — no double-counted rows.  The caller merges partials in
canonical group order with float64 accumulation, so the fit is
bit-identical across topologies for a fixed seed.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

import numpy as np

import jax.numpy as jnp

from h2o3_tpu.cluster import rpc as _rpc
from h2o3_tpu.cluster.dkv import MAX_REPLICAS
from h2o3_tpu.compute.quantile import merge_edges, sketch_column
from h2o3_tpu.frame import devcache as _devcache
from h2o3_tpu.frame.frame import ColType
from h2o3_tpu.models.data_info import DataInfo
from h2o3_tpu.ops.histogram import apply_bins, guard_hist_payload
from h2o3_tpu.util import flight as _flight
from h2o3_tpu.util import ledger as _ledger
from h2o3_tpu.util import telemetry

_FITS = telemetry.counter(
    "dist_hist_fits_total",
    "distributed histogram tree fits started, by execution mode",
    labels=("mode",))
_LEVELS = telemetry.counter(
    "dist_hist_levels_total",
    "tree-level histogram fan-outs issued by distributed fits")
_PARTIAL_BYTES = telemetry.counter(
    "dist_hist_partial_bytes_total",
    "bytes of histogram partials produced by chunk homes")
_BIND_CACHE = telemetry.counter(
    "dist_hist_bind_cache_total",
    "hist_bind binned-code lookups against the device frame cache",
    labels=("result",))
_CTX_ENTRIES = telemetry.gauge(
    "cluster_hist_context_entries",
    "live per-fit histogram contexts held by this member")


def dist_mode() -> str:
    """``H2O3_TPU_DIST_HIST``: ``1`` (fan to chunk homes when a cloud is
    up), ``local`` (same engine, every op runs caller-side) or ``0``
    (legacy path via lazy materialization)."""
    v = os.environ.get("H2O3_TPU_DIST_HIST", "1").strip().lower()
    return v if v in ("0", "1", "local") else "1"


def _timeout() -> float:
    try:
        return float(os.environ.get("H2O3_TPU_DIST_HIST_TIMEOUT", "120"))
    except ValueError:
        return 120.0


def _ctx_cap() -> int:
    try:
        return max(1, int(os.environ.get("H2O3_TPU_DIST_HIST_CTX", "4")))
    except ValueError:
        return 4


def _batch_enabled() -> bool:
    """``H2O3_TPU_DIST_HIST_BATCH``: coalesce output-free ``fin`` ops with
    the next output-bearing op into one ``hist_levels`` round (default on;
    ``0`` sends every op as its own ``hist_level`` RPC)."""
    return os.environ.get("H2O3_TPU_DIST_HIST_BATCH", "1").strip() != "0"


# ---------------------------------------------------------------------------
# home-side context store

_CTX_LOCK = threading.Lock()
#: ctx_id -> {group index -> _GroupState}; LRU-bounded so leaked fits
#: (caller died before hist_fin) cannot pin host memory forever
_CTXS: "OrderedDict[str, Dict[int, _GroupState]]" = OrderedDict()
_CTX_COUNTER = [0]


class _GroupState:
    """One group's training-local state on its executor."""

    def __init__(self, g: int) -> None:
        self.g = g
        self.X: Optional[np.ndarray] = None   # [n, F] f32, dropped at bind
        self.y: Optional[np.ndarray] = None   # [n] f64 (kept rows)
        self.w: Optional[np.ndarray] = None
        self.off: Optional[np.ndarray] = None
        self.last_seq = 0
        self.bins: Optional[np.ndarray] = None  # [n, F] int bin codes
        self.F = 0
        self.n_bins1 = 0
        self.base = 0          # this group's offset in the global row order
        self.n_total = 0
        self.C = 1
        self.objective = ""
        self.seed = 0
        self.sample_rate = 1.0
        self.margin: Optional[np.ndarray] = None   # [n, C] f64
        self.targets: Optional[np.ndarray] = None  # fixed-objective targets
        self.pos: Optional[np.ndarray] = None      # [C, n] int32 heap index
        self.gh_round = -1
        self.g_all: Optional[np.ndarray] = None
        self.h_all: Optional[np.ndarray] = None
        self.sample: Optional[np.ndarray] = None


def _ctx_store(ctx_id: str, g: int, st: _GroupState) -> None:
    with _CTX_LOCK:
        groups = _CTXS.setdefault(ctx_id, {})
        groups[g] = st
        _CTXS.move_to_end(ctx_id)
        cap = _ctx_cap()
        while len(_CTXS) > cap:
            _CTXS.popitem(last=False)
        _CTX_ENTRIES.set(float(len(_CTXS)))


def _ctx_group(payload: Dict[str, Any]) -> _GroupState:
    with _CTX_LOCK:
        groups = _CTXS.get(payload["ctx_id"])
        st = groups.get(int(payload["g"])) if groups else None
    if st is None:
        raise _rpc.RpcFault(
            f"no histogram context {payload['ctx_id']!r} for group "
            f"{payload['g']} on this member", code=404)
    return st


def _ctx_drop(ctx_id: str) -> None:
    with _CTX_LOCK:
        _CTXS.pop(ctx_id, None)
        _CTX_ENTRIES.set(float(len(_CTXS)))


def _check_seq(st: _GroupState, seq: int) -> None:
    if seq != st.last_seq + 1:
        raise _rpc.RpcFault(
            f"stale context: got seq {seq}, expected {st.last_seq + 1}",
            code=409)
    st.last_seq = seq


# ---------------------------------------------------------------------------
# home-side op execution


def _round_start(st: _GroupState, r: int) -> None:
    """Grad/hess for round ``r`` from the pre-round margins (computed once
    per round — block 2 of a multinomial round reuses the cache, matching
    the single-node engine computing g_all before its class trees)."""
    if st.gh_round == r:
        return
    n = st.y.size
    if st.objective == "fixed":
        g = -st.targets
        h = np.ones_like(st.targets)
    else:
        from h2o3_tpu.models.tree import common as _common
        g, h = _common.grad_hess(st.objective, st.y, st.margin)
        g = np.asarray(g, np.float64)
        h = np.asarray(h, np.float64)
    if st.w is not None:
        g = g * st.w[:, None]
        h = h * st.w[:, None]
    if st.sample_rate < 1.0:
        u = np.random.default_rng((st.seed, 1, r)).random(st.n_total)
        st.sample = u[st.base:st.base + n] < st.sample_rate
    else:
        st.sample = None
    st.g_all, st.h_all, st.gh_round = g, h, r


def _apply_routes(st: _GroupState, routes: Dict[str, Any],
                  c0: int, c1: int, n_bins1: int) -> None:
    """Advance node positions one level using the caller's split
    decisions — the same routing arithmetic as the single-node heap."""
    n = st.y.size
    if n == 0:
        return
    bf = np.asarray(routes["bf"], np.int32)
    bb = np.asarray(routes["bb"], np.int32)
    dl = np.asarray(routes["dl"], bool)
    can = np.asarray(routes["can"], bool)
    kp = bf.shape[1]
    lo_p = kp - 1
    rows = np.arange(n)
    for ci in range(c1 - c0):
        pos = st.pos[c0 + ci]
        local = pos - lo_p
        in_lvl = (local >= 0) & (local < kp)
        k = np.clip(local, 0, kp - 1)
        f = bf[ci][k]
        b = st.bins[rows, f]
        go_left = np.where(b >= n_bins1 - 1, dl[ci][k], b <= bb[ci][k])
        child = 2 * (lo_p + k) + np.where(go_left, 1, 2)
        st.pos[c0 + ci] = np.where(
            in_lvl & can[ci][k], child, pos).astype(np.int32)


def _build_partial(st: _GroupState, op: Dict[str, Any]) -> np.ndarray:
    """This group's ``[classes, nodes, F, n_bins1, 3]`` float64 histogram
    partial for one level — small-side nodes only under subtraction."""
    d, c0, c1 = int(op["d"]), int(op["c0"]), int(op["c1"])
    subtract = bool(op.get("subtract")) and d > 0
    k_lvl = 1 << d
    lo = k_lvl - 1
    kb = k_lvl // 2 if subtract else k_lvl
    n = st.y.size
    cb = c1 - c0
    out = np.zeros((cb, kb, st.F, st.n_bins1, 3), np.float64)
    if n == 0 or st.F == 0:
        return out
    sp = np.asarray(op["routes"]["sp"], np.int32) if subtract else None
    for ci in range(cb):
        local = st.pos[c0 + ci] - lo
        in_lvl = (local >= 0) & (local < k_lvl)
        if subtract:
            par = np.clip(local // 2, 0, kb - 1)
            parity = local % 2
            m = in_lvl & (parity == sp[ci][par])
            nodes = par
        else:
            m = in_lvl
            nodes = np.clip(local, 0, k_lvl - 1)
        if st.sample is not None:
            m = m & st.sample
        nm = int(np.count_nonzero(m))
        if nm == 0:
            continue
        flat = ((nodes[m].astype(np.int64)[:, None] * st.F
                 + np.arange(st.F)[None, :]) * st.n_bins1
                + st.bins[m]).ravel()
        rw = st.w[m] if st.w is not None else np.ones(nm, np.float64)
        size = kb * st.F * st.n_bins1
        for ch, v in enumerate((st.g_all[m, c0 + ci] if st.g_all.shape[1] > 1
                                else st.g_all[m, 0],
                                st.h_all[m, c0 + ci] if st.h_all.shape[1] > 1
                                else st.h_all[m, 0],
                                rw)):
            out[ci, :, :, :, ch] = np.bincount(
                flat,
                weights=np.broadcast_to(
                    np.asarray(v, np.float64)[:, None], (nm, st.F)).ravel(),
                minlength=size).reshape(kb, st.F, st.n_bins1)
    return out


def _node_totals(st: _GroupState, op: Dict[str, Any]) -> np.ndarray:
    """Terminal-level ``[classes, nodes, 3]`` G/H/W totals."""
    d, c0, c1 = int(op["d"]), int(op["c0"]), int(op["c1"])
    k_lvl = 1 << d
    lo = k_lvl - 1
    n = st.y.size
    cb = c1 - c0
    out = np.zeros((cb, k_lvl, 3), np.float64)
    if n == 0:
        return out
    for ci in range(cb):
        local = st.pos[c0 + ci] - lo
        m = (local >= 0) & (local < k_lvl)
        if st.sample is not None:
            m = m & st.sample
        nm = int(np.count_nonzero(m))
        if nm == 0:
            continue
        nodes = np.clip(local, 0, k_lvl - 1)[m]
        rw = st.w[m] if st.w is not None else np.ones(nm, np.float64)
        for ch, v in enumerate((st.g_all[m, c0 + ci] if st.g_all.shape[1] > 1
                                else st.g_all[m, 0],
                                st.h_all[m, c0 + ci] if st.h_all.shape[1] > 1
                                else st.h_all[m, 0],
                                rw)):
            out[ci, :, ch] = np.bincount(
                nodes, weights=np.asarray(v, np.float64),
                minlength=k_lvl)[:k_lvl]
    return out


def _apply_op(st: _GroupState, op: Dict[str, Any],
              build: bool = True) -> Optional[np.ndarray]:
    """Execute one protocol op against a group's state.  ``build=False``
    (the replay path) applies routing/margin effects without producing
    any output arrays."""
    kind = op["kind"]
    if kind == "margins":
        return st.margin.copy()
    if kind in ("level", "totals"):
        c0, c1 = int(op["c0"]), int(op["c1"])
        _round_start(st, int(op["r"]))
        routes = op.get("routes")
        if routes is None:
            st.pos[c0:c1] = 0
        else:
            _apply_routes(st, routes, c0, c1, st.n_bins1)
        if not build:
            return None
        return (_build_partial(st, op) if kind == "level"
                else _node_totals(st, op))
    if kind == "fin":
        c0, c1 = int(op["c0"]), int(op["c1"])
        routes = op.get("routes")
        if routes is not None:
            _apply_routes(st, routes, c0, c1, st.n_bins1)
        leaf = np.asarray(op["leaf"], np.float64)
        for ci in range(c1 - c0):
            st.margin[:, c0 + ci] += leaf[ci][st.pos[c0 + ci]]
        if build and op.get("want_margin"):
            return st.margin.copy()
        return None
    raise _rpc.RpcFault(f"unknown hist op kind {kind!r}", code=400)


# ---------------------------------------------------------------------------
# the five handlers (tasks.py wraps these as ctx-DTasks)


def hist_open(payload: Dict[str, Any], cloud, store) -> Dict[str, Any]:
    from h2o3_tpu.cluster import frames as _frames
    if store is None:
        raise _rpc.RpcFault("no chunk store on this member", code=503)
    layout = _frames._layout_for(
        store, payload["frame_key"], payload["stamp"])
    g = int(payload["g"])
    y_name = payload["y_name"]
    w_name = payload.get("w_name")
    off_name = payload.get("off_name")
    preds = list(payload["pred_names"])
    names = [y_name] + preds
    if w_name:
        names.append(w_name)
    if off_name:
        names.append(off_name)
    cols = _frames.columns_from_group(store, layout, g, names)
    y = np.asarray(cols[y_name], np.float64)
    if preds:
        X = np.column_stack(
            [cols[c] for c in preds]).astype(np.float32)
    else:
        X = np.zeros((y.size, 0), np.float32)
    keep = ~np.isnan(y)
    w = off = None
    neg = False
    if w_name:
        w = np.asarray(cols[w_name], np.float64)
        neg = bool(np.any(w < 0))
        keep &= ~np.isnan(w) & (w > 0)
    if off_name:
        off = np.asarray(cols[off_name], np.float64)
        keep &= ~np.isnan(off)
    X, y = X[keep], y[keep]
    if w is not None:
        w = w[keep]
    if off is not None:
        off = off[keep]
    nbins = int(payload["nbins"])
    # per-fit quantile sketches are a pure function of the group's kept
    # rows — identified by (layout stamp, column roles), the same identity
    # the bind cache keys on — so a repeat fit serves them resident too
    sk_token = ("hist_sketch_home", payload["frame_key"], payload["stamp"],
                g, y_name, w_name or "", off_name or "", tuple(preds))

    def _sketch():
        return [sketch_column(X[:, f].astype(np.float64), nbins)
                for f in range(X.shape[1])]

    sketches = _devcache.cached_host(
        "hist_sketch_home", sk_token, nbins, _sketch,
        frame_key=str(payload["frame_key"]))
    st = _GroupState(g)
    st.X, st.y, st.w, st.off = X, y, w, off
    st.last_seq = 0
    _ctx_store(payload["ctx_id"], g, st)
    return {"n": int(y.size), "y": y, "w": w, "off": off,
            "sketches": sketches, "neg_weights": neg}


def _edges_digest(edges: np.ndarray) -> str:
    return hashlib.sha1(
        np.ascontiguousarray(edges, np.float64).tobytes()).hexdigest()


def _bind_codes(st: _GroupState, payload: Dict[str, Any],
                edges: np.ndarray) -> np.ndarray:
    """This group's binned-code matrix, served device-cache-resident.

    Keyed on (frame_key, layout stamp, column roles, group, bin-edges
    digest): the stamp identifies the distributed data state and the edges
    are a pure function of (data, nbins), so a repeat fit on an unmutated
    DistFrame hits — zero ``apply_bins`` decodes, zero upload bytes (the
    miss path's ledger charge never happens). The entry is linked to the
    frame key so a DKV remove/rekey evicts it. Entries are read-only by
    protocol: routing/partials only ever index ``st.bins``."""
    bk = payload.get("bins_key")
    if bk is None:  # replayed pre-cache caller: decode uncached
        _BIND_CACHE.inc(result="miss")
        return np.asarray(apply_bins(st.X, edges))
    token = tuple(tuple(x) if isinstance(x, list) else x for x in bk)
    decoded = []

    def _decode() -> np.ndarray:
        decoded.append(True)
        return np.asarray(apply_bins(st.X, edges))

    bins = _devcache.cached_host(
        "hist_bins_home", token, (st.g, _edges_digest(edges)), _decode,
        frame_key=str(bk[0]))
    _BIND_CACHE.inc(result="miss" if decoded else "hit")
    return bins


def hist_bind(payload: Dict[str, Any], cloud, store) -> Dict[str, Any]:
    st = _ctx_group(payload)
    _check_seq(st, int(payload["seq"]))
    edges = np.asarray(payload["edges"], np.float64)
    st.bins = _bind_codes(st, payload, edges)
    st.X = None
    st.F = int(edges.shape[0])
    st.n_bins1 = int(edges.shape[1]) + 2
    st.base = int(payload["bases"][st.g])
    st.n_total = int(payload["n_total"])
    st.C = int(payload["C"])
    st.objective = str(payload["objective"])
    st.seed = int(payload["seed"])
    st.sample_rate = float(payload["sample_rate"])
    n = st.y.size
    f0 = np.asarray(payload["f0"], np.float64).reshape(-1)
    st.margin = np.tile(f0[None, :], (n, 1))
    if payload.get("use_offset") and st.off is not None:
        st.margin[:, 0] += st.off
    if st.objective == "fixed":
        if st.C > 1:
            t = np.zeros((n, st.C), np.float64)
            if n:
                t[np.arange(n), st.y.astype(np.int64)] = 1.0
        else:
            t = st.y[:, None].astype(np.float64)
        st.targets = t
    st.pos = np.zeros((st.C, n), np.int32)
    st.gh_round = -1
    return {"n": int(n)}


def _meter_level_out(st: _GroupState, op: Dict[str, Any], out) -> None:
    if op["kind"] == "level" and out is not None:
        guard_hist_payload("histogram partial", out.shape[0], out.shape[1],
                           st.F, st.n_bins1)
        _PARTIAL_BYTES.inc(float(out.nbytes))


def hist_level(payload: Dict[str, Any], cloud, store) -> Any:
    st = _ctx_group(payload)
    op = payload["op"]
    seq_fenced = op["kind"] != "margins"
    if seq_fenced:
        _check_seq(st, int(payload["seq"]))
    t0 = time.perf_counter()
    out = _apply_op(st, op, build=True)
    if seq_fenced:
        _ledger.charge(_ledger.HIST_LEVEL_WALL, time.perf_counter() - t0)
    _meter_level_out(st, op, out)
    return out


def hist_levels(payload: Dict[str, Any], cloud, store) -> List[Any]:
    """Batched protocol rounds: apply ``payload["ops"]`` — a list of
    ``{"seq", "op"}`` items in issue order — against one group and return
    the per-op outputs. Each fenced op checks/advances the seq exactly as
    its own ``hist_level`` round would, so the batch converges to the same
    state and the 404/409 -> replay ladder is unchanged (the payload's
    top-level ``seq`` is the first fenced op's, the replay fence point)."""
    st = _ctx_group(payload)
    t0 = time.perf_counter()
    outs: List[Any] = []
    fenced = False
    for item in payload["ops"]:
        op = item["op"]
        if op["kind"] != "margins":
            _check_seq(st, int(item["seq"]))
            fenced = True
        out = _apply_op(st, op, build=True)
        _meter_level_out(st, op, out)
        outs.append(out)
    if fenced:
        _ledger.charge(_ledger.HIST_LEVEL_WALL, time.perf_counter() - t0)
    return outs


def hist_replay(payload: Dict[str, Any], cloud, store) -> Dict[str, Any]:
    if store is None:
        raise _rpc.RpcFault("no chunk store on this member", code=503)
    hist_open(payload["open"], cloud, store)
    st = _ctx_group({"ctx_id": payload["ctx_id"], "g": payload["g"]})
    bind = payload.get("bind")
    if bind is not None:
        st.last_seq = int(bind["seq"]) - 1
        hist_bind(bind, cloud, store)
        for op in payload.get("ops") or []:
            _apply_op(st, op, build=False)
    st.last_seq = int(payload["last_seq"])
    return {"ok": True}


def hist_fin(payload: Dict[str, Any], cloud, store) -> Dict[str, Any]:
    _ctx_drop(payload["ctx_id"])
    return {"ok": True}


_HANDLERS = {
    "hist_open": hist_open,
    "hist_bind": hist_bind,
    "hist_level": hist_level,
    "hist_levels": hist_levels,
    "hist_replay": hist_replay,
    "hist_fin": hist_fin,
}


# ---------------------------------------------------------------------------
# caller-side driver


def use_dist(frame, p, encoding: str) -> bool:
    """Whether a fit over ``frame`` should run the distributed engine:
    chunk-homed frame, knob not ``0``, and no feature the map-side path
    does not implement (those fall back to lazy materialization)."""
    if getattr(frame, "chunk_layout", None) is None:
        return False
    if dist_mode() == "0":
        return False
    if getattr(p, "checkpoint", None):
        return False
    if getattr(p, "monotone_constraints", None):
        return False
    if str(getattr(p, "distribution", "auto")).startswith("custom"):
        return False
    if encoding == "one_hot_explicit":
        return False
    return True


def _data_info_from_layout(layout: Dict[str, Any], y: str,
                           ignored=()) -> DataInfo:
    """A :class:`DataInfo` straight from a chunk layout — the same
    predictor filter as ``build_data_info`` without materializing rows."""
    skip = set(ignored) | {y}
    names = layout["column_names"]
    types = layout["column_types"]
    preds = [n for n, t in zip(names, types)
             if n not in skip and t in (ColType.NUM, ColType.TIME,
                                        ColType.CAT)]
    info = DataInfo(
        predictor_names=preds,
        response_name=y,
        use_all_factor_levels=True,
        standardize=False,
        missing_values_handling="mean_imputation")
    for n in preds:
        t = types[names.index(n)]
        if t is ColType.CAT:
            dom = list(layout["domains"].get(n) or [])
            info.cat_domains[n] = dom
            info.cat_mode[n] = 0
            info.coef_names.extend(f"{n}.{lv}" for lv in dom)
        else:
            info.num_means[n] = 0.0
            info.num_sds[n] = 1.0
            info.coef_names.append(n)
    yt = types[names.index(y)]
    if yt is ColType.CAT:
        info.response_domain = list(layout["domains"].get(y) or [])
    return info


class DistTreeMatrix:
    """The distributed fit's stand-in for the dense feature matrix: owns
    the per-home contexts, fans protocol ops, merges results in canonical
    group order, and walks the replica -> survivor -> caller-local ladder
    when a home dies mid-level."""

    is_dist_hist = True

    def __init__(self, frame, pred_names: List[str], y_name: str,
                 w_name: Optional[str] = None,
                 off_name: Optional[str] = None, nbins: int = 20) -> None:
        from h2o3_tpu.cluster import active_cloud
        from h2o3_tpu.cluster import frames as _frames
        from h2o3_tpu.cluster import tasks as _tasks
        self.frame = frame
        self.layout = frame.chunk_layout
        self.groups = self.layout["groups"]
        self.pred_names = list(pred_names)
        self.y_name = y_name
        self.w_name = w_name
        self.off_name = off_name
        self.nbins = int(nbins)
        store = getattr(frame, "_store", None)
        router = getattr(store, "router", None) if store is not None else None
        # the frame's OWN store/router names the cloud this fit belongs to
        # — with several in-process Clouds the process-global would lie
        cloud = getattr(router, "cloud", None)
        if cloud is None:
            try:
                cloud = active_cloud()
            except Exception:
                cloud = None
        self.cloud = cloud
        if store is None:
            store = _frames._resolve_store(cloud)
        self.store = store
        router = getattr(store, "router", None)
        workers = (_tasks._healthy_workers(cloud)
                   if cloud is not None else [])
        if (dist_mode() == "local" or cloud is None or router is None
                or not router.active() or len(workers) < 2):
            self.mode = "local"
        else:
            self.mode = "dist"
        self.router = router
        with _CTX_LOCK:
            _CTX_COUNTER[0] += 1
            n_fit = _CTX_COUNTER[0]
        self.ctx_id = (f"{self.layout['frame_key']}#{self.layout['stamp']}"
                       f"#{self.mode}#{n_fit}")
        self._seq = 0
        self._oplog: List[Dict[str, Any]] = []
        #: output-free ops (seq already assigned, oplog already appended)
        #: waiting to ride the next output-bearing hist_levels round
        self._pending: List[Dict[str, Any]] = []
        self._batch = _batch_enabled()
        self._bind_common: Optional[Dict[str, Any]] = None
        self._exec_map: Dict[int, str] = {}
        self._timeout = _timeout()
        self._finished = False
        self._ex = (ThreadPoolExecutor(
            max_workers=max(2, len(self.groups)),
            thread_name_prefix="dist-hist")
            if self.mode == "dist" else None)
        self._open()

    # -- protocol -----------------------------------------------------

    def _open(self) -> None:
        self._open_tmpl = [
            {"ctx_id": self.ctx_id,
             "frame_key": self.layout["frame_key"],
             "stamp": self.layout["stamp"],
             "g": gi,
             "y_name": self.y_name,
             "w_name": self.w_name,
             "off_name": self.off_name,
             "pred_names": self.pred_names,
             "nbins": self.nbins,
             "seq": 0}
            for gi in range(len(self.groups))]
        outs = self._fan("hist_open", self._open_tmpl)
        if any(o.get("neg_weights") for o in outs):
            self._finish()
            raise ValueError("weights_column must be non-negative")
        group_n = [int(o["n"]) for o in outs]
        self.bases = np.concatenate(
            [[0], np.cumsum(group_n)]).astype(int)
        self.n_total = int(self.bases[-1])
        self.y_all = np.concatenate(
            [np.asarray(o["y"], np.float64) for o in outs]) \
            if outs else np.empty(0)
        self.w_all = (np.concatenate(
            [np.asarray(o["w"], np.float64) for o in outs])
            if self.w_name else None)
        self.off_all = (np.concatenate(
            [np.asarray(o["off"], np.float64) for o in outs])
            if self.off_name else None)
        F = len(self.pred_names)
        edges = np.empty((F, max(self.nbins - 1, 0)), np.float64)
        for f in range(F):
            edges[f] = merge_edges(
                [o["sketches"][f] for o in outs], self.nbins)
        self.edges = edges
        self.shape = (self.n_total, F)

    def _bind(self, f0: np.ndarray, C: int, objective: str, seed: int,
              sample_rate: float, use_offset: bool) -> None:
        self._seq = 1
        self._bind_common = {
            "ctx_id": self.ctx_id,
            "seq": 1,
            # data identity of the binned codes: homes key their decoded
            # matrix on this + the edges digest so a repeat fit on an
            # unmutated frame re-decodes nothing (see _bind_codes)
            "bins_key": [self.layout["frame_key"], self.layout["stamp"],
                         self.y_name, self.w_name or "", self.off_name or "",
                         list(self.pred_names), int(self.nbins)],
            "edges": self.edges,
            "bases": [int(b) for b in self.bases[:-1]],
            "n_total": self.n_total,
            "f0": np.asarray(f0, np.float64),
            "C": int(C),
            "objective": objective,
            "seed": int(seed),
            "sample_rate": float(sample_rate),
            "use_offset": bool(use_offset)}
        self._fan("hist_bind",
                  [dict(self._bind_common, g=gi)
                   for gi in range(len(self.groups))])

    def _op(self, op: Dict[str, Any]) -> List[Any]:
        seq = self._seq + 1
        self._seq = seq
        self._oplog.append(op)
        if (self._batch and op["kind"] == "fin"
                and not op.get("want_margin")):
            # output-free fin: defer it — the next output-bearing op (the
            # following block's level 0, or the final margins read) ships
            # it in the same hist_levels round, one dispatch + wire trip
            # instead of two. Seq/oplog state is already advanced, so the
            # replay ladder sees exactly the sequential history.
            self._pending.append({"seq": seq, "op": op})
            return []
        return self._flush({"seq": seq, "op": op})

    def _flush(self, item: Dict[str, Any]) -> List[Any]:
        """One protocol round carrying ``item`` (plus any deferred ops):
        a plain ``hist_level`` when nothing is pending, else a batched
        ``hist_levels`` whose outputs list ends with ``item``'s."""
        if not self._pending:
            payloads = [{"ctx_id": self.ctx_id, "g": gi,
                         "seq": item.get("seq", self._seq + 1),
                         "op": item["op"]}
                        for gi in range(len(self.groups))]
            return self._fan("hist_level", payloads)
        items = self._pending + [item]
        self._pending = []
        first_seq = int(items[0]["seq"])
        outs = self._fan("hist_levels",
                         [{"ctx_id": self.ctx_id, "g": gi,
                           "seq": first_seq, "ops": items}
                          for gi in range(len(self.groups))])
        return [o[-1] for o in outs]

    def _margins(self) -> np.ndarray:
        outs = self._flush({"op": {"kind": "margins"}})
        return np.concatenate([np.asarray(o, np.float64) for o in outs],
                              axis=0)

    # -- fan-out / recovery -------------------------------------------

    def _replay_payload(self, gi: int, upto_seq: int) -> Dict[str, Any]:
        bind = (dict(self._bind_common, g=gi)
                if upto_seq >= 2 and self._bind_common is not None
                else None)
        ops = self._oplog[:max(0, upto_seq - 2)]
        return {"ctx_id": self.ctx_id, "g": gi,
                "open": self._open_tmpl[gi],
                "bind": bind, "ops": ops,
                "last_seq": upto_seq - 1}

    def _fan(self, task: str, payloads: List[Dict[str, Any]]) -> List[Any]:
        if self.mode == "local":
            return [self._attempt(gi, "<caller>", task, p)
                    for gi, p in enumerate(payloads)]
        ctx = telemetry.current_trace_context()
        fo = _flight.FANOUTS.begin(task, len(payloads))
        _flight.record(_flight.FANOUT, "info", "schedule", kind=task,
                       groups=len(payloads))

        def _run(gi: int, p: Dict[str, Any]):
            kw: Dict[str, Any] = {"group": gi, "task": task}
            if ctx is not None:
                kw["trace_id"] = ctx["trace_id"]
                kw["parent_id"] = ctx["span_id"]
            with telemetry.Span("hist_group", **kw):
                try:
                    return self._run_group(gi, task, p)
                finally:
                    fo.progress()

        try:
            futs = [self._ex.submit(_run, gi, p)
                    for gi, p in enumerate(payloads)]
            return [f.result() for f in futs]
        finally:
            fo.end()

    def _run_group(self, gi: int, task: str, payload: Dict[str, Any]):
        from h2o3_tpu.cluster import tasks as _tasks
        tried = set()
        sticky = self._exec_map.get(gi)
        if sticky == "<caller>":
            return self._attempt(gi, "<caller>", task, payload)
        if sticky is not None:
            m = next((m for m in _tasks._healthy_workers(self.cloud)
                      if m.info.name == sticky), None)
            if m is not None:
                try:
                    return self._attempt(gi, m, task, payload)
                except (_rpc.RPCError, _rpc.RpcFault):
                    tried.add(sticky)
        anchor = self.groups[gi]["anchor"]
        cands = (self.router.home_members(anchor, MAX_REPLICAS)
                 if self.router is not None else [])
        rungs = []
        if cands:
            rungs.append(("home", cands[0]))
            rungs.extend(("replica", m) for m in cands[1:])
        cand_names = {m.info.name for m in cands}
        my_name = self.cloud.info.name
        rungs.extend(
            ("survivor", m)
            for m in _tasks._healthy_workers(self.cloud)
            if m.info.name not in cand_names and m.info.name != my_name)
        for path, m in rungs:
            name = m.info.name
            if name in tried:
                continue
            tried.add(name)
            try:
                out = self._attempt(gi, m, task, payload)
            except (_rpc.RPCError, _rpc.RpcFault):
                continue
            if path != "home":
                _tasks._RECOVERED.inc(path=path)
                _flight.record(_flight.RECOVERY, "warn", "hist_group",
                               path=path, group=gi, task=task,
                               member=name)
            self._exec_map[gi] = name
            return out
        out = self._attempt(gi, "<caller>", task, payload)
        _tasks._RECOVERED.inc(path="local")
        _flight.record(_flight.RECOVERY, "warn", "hist_group",
                       path="local", group=gi, task=task)
        self._exec_map[gi] = "<caller>"
        return out

    def _attempt(self, gi: int, member, task: str, payload: Dict[str, Any]):
        from h2o3_tpu.cluster import tasks as _tasks

        def _send(t: str, p: Dict[str, Any]):
            if member == "<caller>" or (
                    self.cloud is not None
                    and member.info.name == self.cloud.info.name):
                return _HANDLERS[t](p, self.cloud, self.store)
            return _tasks.submit(self.cloud, member, t, p,
                                 timeout=self._timeout)

        try:
            return _send(task, payload)
        except (_rpc.RpcFault, _rpc.RemoteError) as e:
            code = getattr(e, "code", None)
            if task == "hist_open" or code not in (404, 409):
                raise
            _send("hist_replay",
                  self._replay_payload(gi, int(payload["seq"])))
            return _send(task, payload)

    def _finish(self) -> None:
        if self._finished:
            return
        self._finished = True
        from h2o3_tpu.cluster import tasks as _tasks
        payload = {"ctx_id": self.ctx_id}
        if self.mode == "dist" and self.cloud is not None:
            seen = set()
            workers = {m.info.name: m
                       for m in _tasks._healthy_workers(self.cloud)}
            for gi in range(len(self.groups)):
                name = self._exec_map.get(gi)
                if name is None or name in seen:
                    continue
                seen.add(name)
                try:
                    if name == "<caller>":
                        hist_fin(payload, self.cloud, self.store)
                    elif name in workers:
                        self._attempt(gi, workers[name],
                                      "hist_fin", payload)
                except Exception:
                    pass
        _ctx_drop(self.ctx_id)
        if self._ex is not None:
            self._ex.shutdown(wait=False)


# ---------------------------------------------------------------------------
# fit-setup fronts (the distributed analogues of tree_fit_setup)


def dist_fit_setup(frame, p, model_cls, use_offset: bool):
    """The distributed analogue of ``tree_fit_setup``: model + DataInfo
    straight from the chunk layout, aux vectors from the one-time open
    gather — rows never materialize on the caller."""
    from h2o3_tpu.models.tree import common as _common
    ignored = list(getattr(p, "ignored_columns", ()) or ())
    if p.weights_column:
        ignored.append(p.weights_column)
    if use_offset and getattr(p, "offset_column", None):
        ignored.append(p.offset_column)
    info = _data_info_from_layout(
        frame.chunk_layout, p.response_column, ignored)
    nclasses = (len(info.response_domain)
                if info.response_domain else 1)
    dist = p.distribution
    if dist == "auto":
        dist = _common.auto_distribution(nclasses)
    model = model_cls(p, info, dist)
    Xd = DistTreeMatrix(
        frame, info.predictor_names, p.response_column,
        w_name=p.weights_column or None,
        off_name=(getattr(p, "offset_column", None) or None)
        if use_offset else None,
        nbins=p.nbins)
    try:
        objective = _common.resolve_objective(dist, p, Xd.y_all)
        f0 = _common.init_margin(objective, Xd.y_all, nclasses,
                                 weights=Xd.w_all)
    except Exception:
        Xd._finish()
        raise
    n_class_trees = nclasses if dist == "multinomial" else 1
    return (model, Xd, Xd.y_all, Xd.w_all, Xd.off_all,
            objective, f0, n_class_trees, None)


def dist_drf_front(frame, p, model_cls):
    """DRF's front half over a chunk-homed frame: model + DataInfo +
    aux vectors, targets built caller-side from ``y_all``."""
    ignored = list(getattr(p, "ignored_columns", ()) or ())
    if p.weights_column:
        ignored.append(p.weights_column)
    info = _data_info_from_layout(
        frame.chunk_layout, p.response_column, ignored)
    nclasses = (len(info.response_domain)
                if info.response_domain else 1)
    model = model_cls(p, info, "gaussian")
    Xd = DistTreeMatrix(
        frame, info.predictor_names, p.response_column,
        w_name=p.weights_column or None, nbins=p.nbins)
    return model, Xd, Xd.y_all, Xd.w_all, nclasses


# ---------------------------------------------------------------------------
# the distributed training driver


def train_boosted_dist(Xd: DistTreeMatrix, objective: str, y, n_class_trees,
                       init_margin, params, average: bool = False,
                       monitor=None, score_interval: int = 1,
                       timings: Optional[dict] = None, weights=None,
                       offset=None):
    """``train_boosted`` over a :class:`DistTreeMatrix`: the level loop
    fans ``hist_level`` ops, merges float64 partials in canonical group
    order, and runs the existing ``_split_search`` caller-side — the
    result is a plain :class:`BoostedTrees` plus a ``dist_eval`` handle
    for materialization-free scoring."""
    from h2o3_tpu.models.tree import booster as _booster
    _t0 = time.time()
    p = params
    n_bins1 = p.nbins + 1
    C = int(n_class_trees)
    F = Xd.shape[1]
    try:
        if Xd.off_all is not None and C != 1:
            raise ValueError(
                "offset_column requires a single-margin objective")
        subtract = _booster._tree_subtract_enabled() and p.max_depth > 0
        D = p.max_depth
        cb = min(C, max(1, _booster.tree_block_size()))
        if D > 0:
            worst = (max(1, 1 << max(D - 2, 0)) if subtract
                     else 1 << (D - 1))
            guard_hist_payload("histogram partial", cb, worst, F, n_bins1)
        f0 = np.broadcast_to(
            np.asarray(init_margin, np.float64).reshape(-1), (C,)).copy()
        _FITS.inc(mode=Xd.mode)
        with telemetry.Span("dist_tree_fit", mode=Xd.mode,
                            groups=len(Xd.groups), trees=int(p.ntrees),
                            classes=C, rows=int(Xd.n_total)):
            Xd._bind(f0, C, objective, p.seed, p.sample_rate,
                     use_offset=Xd.off_all is not None)
            _t_prep = time.time()
            trees_per_class = [
                _booster.Trees(D, n_bins1, Xd.edges) for _ in range(C)]
            level_walls: List[float] = []
            levels_n = 0
            built = 0

            def _timed_op(op):
                nonlocal levels_n
                t0 = time.perf_counter()
                outs = Xd._op(op)
                level_walls.append(time.perf_counter() - t0)
                levels_n += 1
                _LEVELS.inc()
                return outs

            def one_block(r, c0, c1, feat_mask, want_margin):
                cb_n = c1 - c0
                heaps = [([], [], [], [], []) for _ in range(cb_n)]
                routes = None
                prev = [None] * cb_n
                for d in range(D):
                    k_lvl = 1 << d
                    op = {"kind": "level", "r": r, "d": d,
                          "c0": c0, "c1": c1,
                          "subtract": bool(subtract), "routes": routes}
                    parts = _timed_op(op)
                    merged = np.zeros_like(np.asarray(parts[0], np.float64))
                    for part in parts:
                        merged = merged + np.asarray(part, np.float64)
                    bf_l, bb_l, dl_l, can_l, ls_l = [], [], [], [], []
                    prev_new = [None] * cb_n
                    for ci in range(cb_n):
                        if subtract and d > 0:
                            small = merged[ci]
                            pv = prev[ci]
                            can_m = pv["can"][:, None, None, None]
                            big = np.where(can_m, pv["hist"] - small, 0.0)
                            ls_m = pv["ls"][:, None, None, None]
                            left = np.where(ls_m, small, big)
                            right = np.where(ls_m, big, small)
                            hist_ci = np.stack(
                                [left, right], axis=1).reshape(
                                    k_lvl, F, n_bins1, 3)
                        else:
                            hist_ci = merged[ci]
                        if p.mtries > 0:
                            u = np.random.default_rng(
                                (p.seed, 3, r, c0 + ci, d)).random(
                                    (k_lvl, F))
                            th = np.sort(u, axis=1)[:, p.mtries - 1][:, None]
                            fm = (u <= th) & feat_mask[None, :]
                        else:
                            fm = feat_mask
                        out = _booster._split_search(
                            jnp.asarray(hist_ci.astype(np.float32)),
                            jnp.float32(p.reg_lambda),
                            jnp.float32(p.reg_alpha),
                            jnp.float32(p.gamma),
                            jnp.float32(p.learn_rate),
                            jnp.asarray(fm),
                            min_rows=float(p.min_rows),
                            n_bins1=n_bins1,
                            child_stats=True)
                        bf, bb, dl, gain, leaf, bwl, bwr, ls = (
                            np.asarray(v) for v in out)
                        can = ((gain > max(p.min_split_improvement, 0.0))
                               & np.isfinite(gain))
                        hf, hb, hdl, hsp, hlf = heaps[ci]
                        hf.append(bf.astype(np.int32))
                        hb.append(bb.astype(np.int32))
                        hdl.append(dl.astype(bool))
                        hsp.append(can.astype(bool))
                        hlf.append(leaf.astype(np.float32))
                        bf_l.append(bf.astype(np.int32))
                        bb_l.append(bb.astype(np.int32))
                        dl_l.append(dl.astype(bool))
                        can_l.append(can.astype(bool))
                        ls_l.append(ls.astype(bool))
                        prev_new[ci] = {
                            "hist": hist_ci, "can": can, "ls": ls,
                            "wl": np.asarray(bwl, np.float64),
                            "wr": np.asarray(bwr, np.float64)}
                    prev = prev_new
                    routes = {"bf": np.stack(bf_l), "bb": np.stack(bb_l),
                              "dl": np.stack(dl_l), "can": np.stack(can_l)}
                    if subtract:
                        routes["sp"] = np.where(
                            np.stack(ls_l), 0, 1).astype(np.int32)
                # terminal level
                k_term = 1 << D
                leaves = []
                if subtract and D > 0:
                    term_routes = routes
                    for ci in range(cb_n):
                        raw = np.stack(
                            [prev[ci]["wl"], prev[ci]["wr"]],
                            axis=1).reshape(k_term)
                        leaves.append(
                            np.float32(p.learn_rate)
                            * raw.astype(np.float32))
                else:
                    op = {"kind": "totals", "r": r, "d": D,
                          "c0": c0, "c1": c1, "routes": routes}
                    parts = _timed_op(op)
                    tot = np.zeros_like(np.asarray(parts[0], np.float64))
                    for part in parts:
                        tot = tot + np.asarray(part, np.float64)
                    for ci in range(cb_n):
                        G = tot[ci, :, 0]
                        H = tot[ci, :, 1]
                        t = np.sign(G) * np.maximum(
                            np.abs(G) - p.reg_alpha, 0.0)
                        raw = -t / np.maximum(H + p.reg_lambda, 1e-12)
                        leaves.append(
                            np.float32(p.learn_rate)
                            * raw.astype(np.float32))
                    term_routes = None
                leaf_heap = []
                for ci in range(cb_n):
                    hf, hb, hdl, hsp, hlf = heaps[ci]
                    hf.append(np.zeros(k_term, np.int32))
                    hb.append(np.zeros(k_term, np.int32))
                    hdl.append(np.zeros(k_term, bool))
                    hsp.append(np.zeros(k_term, bool))
                    hlf.append(leaves[ci])
                    leaf_heap.append(np.concatenate(hlf))
                fin = {"kind": "fin", "r": r, "c0": c0, "c1": c1,
                       "routes": term_routes,
                       "leaf": np.stack(leaf_heap).astype(np.float64),
                       "want_margin": bool(want_margin)}
                outs = Xd._op(fin)
                for ci in range(cb_n):
                    hf, hb, hdl, hsp, hlf = heaps[ci]
                    trees_per_class[c0 + ci].append(
                        np.concatenate(hf), np.concatenate(hb),
                        np.concatenate(hdl), np.concatenate(hsp),
                        np.concatenate(hlf))
                if want_margin:
                    return np.concatenate(
                        [np.asarray(o, np.float64) for o in outs], axis=0)
                return None

            stop = False
            margin_host = None
            for r in range(p.ntrees):
                if p.col_sample_rate_per_tree < 1.0:
                    u = np.random.default_rng((p.seed, 2, r)).random(F)
                    ncols = max(
                        1, int(round(p.col_sample_rate_per_tree * F)))
                    th = np.sort(u)[ncols - 1]
                    feat_mask = u <= th
                else:
                    feat_mask = np.ones(F, bool)
                want = monitor is not None and (
                    (built + 1) % score_interval == 0
                    or built + 1 == p.ntrees)
                blocks = [(c0, min(c0 + cb, C))
                          for c0 in range(0, C, cb)]
                margin_host = None
                for bi, (c0, c1) in enumerate(blocks):
                    out = one_block(r, c0, c1, feat_mask,
                                    want and bi == len(blocks) - 1)
                    if out is not None:
                        margin_host = out
                built += 1
                if monitor is not None and margin_host is not None:
                    if monitor(built - 1, margin_host):
                        stop = True
                if stop:
                    break

            margin_final = Xd._margins()
            if average and built > 0:
                margin_score = (f0[None, :]
                                + (margin_final - f0[None, :]) / built)
            else:
                margin_score = margin_final
        bt = _booster.BoostedTrees(
            trees_per_class, np.asarray(init_margin, np.float64), p,
            average=average)
        bt.dist_eval = {"frame": Xd.frame, "y": Xd.y_all, "w": Xd.w_all,
                        "margin": margin_score}
        if timings is not None:
            timings["prep_s"] = _t_prep - _t0
            timings["train_s"] = time.time() - _t_prep
            timings["level_walls"] = level_walls
            timings["levels"] = levels_n
        return bt
    finally:
        Xd._finish()
