"""DRF — distributed random forest on the shared tree machinery.

Reference: ``hex/tree/drf/DRF.java`` — same SharedTree driver as GBM, but
bagged trees fit the raw response (no boosting), per-split feature sampling
(mtries), sample_rate 0.632 default, and predictions aggregate by averaging.
Classification leaves hold class frequencies; this build realizes that as a
per-class indicator-regression tree (leaf = class fraction in the leaf),
averaged over trees and normalized — same estimator, SPMD-friendly shapes.
OOB scoring is a planned refinement (reference scores OOB by default).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.data_info import response_vector
from h2o3_tpu.models.framework import ModelBuilder, ModelParameters
from h2o3_tpu.models.tree.booster import TreeParams, train_boosted
from h2o3_tpu.models.tree.common import (
    TreeModelBase,
    checkpoint_booster as _checkpoint_booster,
    extra_trees as _extra_trees,
    extract_weights,
    tree_cache_token,
    tree_data_info,
    tree_matrix,
)


@dataclass
class DRFParameters(ModelParameters):
    ntrees: int = 50
    max_depth: int = 12  # reference default 20; dense level-wise capacity caps this build
    nbins: int = 20
    min_rows: float = 1.0
    min_split_improvement: float = 1e-5
    sample_rate: float = 0.632  # reference DRF default (DRFParametersV3)
    mtries: int = -1  # -1: sqrt(F) classif, F/3 regression (DRF.java)


class DRFModel(TreeModelBase):
    algo_name = "drf"

    def _raw_from_margin(self, margin: np.ndarray) -> np.ndarray:
        # margin: averaged leaf values per class
        if not self.is_classifier:
            return margin[:, 0]
        p = np.clip(margin, 1e-9, None)
        if p.shape[1] == 1:  # binomial: single tree-set predicts P(class 1)
            p1 = np.clip(margin[:, 0], 0.0, 1.0)
            return np.stack([1 - p1, p1], axis=1)
        return p / p.sum(axis=1, keepdims=True)


class DRF(ModelBuilder):

    SUPPORTED_COMMON = frozenset(
        {"checkpoint", "weights_column", "categorical_encoding"}
    )
    algo_name = "drf"

    def __init__(self, params: Optional[DRFParameters] = None, **kw) -> None:
        super().__init__(params or DRFParameters(**kw))

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> DRFModel:
        from h2o3_tpu.models.tree import dist_hist
        from h2o3_tpu.models.tree.common import resolve_tree_encoding

        p: DRFParameters = self.params
        if dist_hist.use_dist(frame, p, resolve_tree_encoding(
                getattr(p, "categorical_encoding", "auto"))):
            # chunk-homed frame: rows stay on their homes; the targets
            # (and grad/hess) are rebuilt map-side at bind time
            model, X, y, weights, nclasses = dist_hist.dist_drf_front(
                frame, p, DRFModel)
        else:
            ignored = list(p.ignored_columns)
            if p.weights_column and p.weights_column not in ignored:
                ignored.append(p.weights_column)
            info = tree_data_info(frame, p.response_column, ignored)
            y = response_vector(info, frame)
            nclasses = (len(info.response_domain)
                        if info.response_domain else 1)
            model = DRFModel(p, info, "gaussian")
            X = tree_matrix(info, frame, encoding=model.tree_encoding)
            keep = ~np.isnan(y)
            weights = extract_weights(frame, p, keep)
            X, y = X[keep], y[keep]
            if weights is not None:
                weights = weights[keep]
        F = X.shape[1]

        mtries = p.mtries
        if mtries <= 0:
            mtries = max(1, int(np.sqrt(F)) if nclasses > 1 else max(1, F // 3))

        # targets: raw y (regression) or per-class indicators (classification)
        if nclasses > 1 and nclasses != 2:
            targets = np.zeros((len(y), nclasses), dtype=np.float64)
            targets[np.arange(len(y)), y.astype(np.int64)] = 1.0
            n_class_trees = nclasses
        elif nclasses == 2:
            targets = y[:, None]
            n_class_trees = 1
        else:
            targets = y[:, None]
            n_class_trees = 1

        tp = TreeParams(
            ntrees=_extra_trees(p, n_class_trees),
            max_depth=p.max_depth,
            learn_rate=1.0,  # no shrinkage: each tree predicts the target itself
            nbins=p.nbins,
            min_rows=p.min_rows,
            min_split_improvement=p.min_split_improvement,
            reg_lambda=0.0,
            reg_alpha=0.0,
            sample_rate=p.sample_rate,
            mtries=mtries,
            seed=p.actual_seed(),
        )

        # objective='fixed': each tree independently fits the raw targets
        # (g = -target, h = 1 gives Newton leaf = mean(target in leaf);
        # with weights g = -w*t, h = w gives the weighted in-leaf mean)
        model.booster = train_boosted(
            X,
            objective="fixed",
            y=targets,
            n_class_trees=n_class_trees,
            init_margin=np.zeros(n_class_trees),
            params=tp,
            average=True,
            resume_from=_checkpoint_booster(
                p, n_class_trees, self.algo_name,
                n_features=F, encoding=model.tree_encoding,
            ),
            weights=weights,
            cache_token=tree_cache_token(frame, p, model.tree_encoding),
            cache_frame_key=getattr(frame, "key", None),
        )
        model.ntrees_built = model.booster.trees_per_class[0].ntrees
        model.training_metrics = model.model_performance(frame)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model
