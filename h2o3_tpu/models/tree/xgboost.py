"""XGBoost-style booster — tree_method="tpu_hist", the north-star config.

Reference: ``h2o-extensions/xgboost`` — Java glue around native libxgboost
(``XGBoostModel.java:240-292,382-394`` resolves backend/tree_method to
``grow_gpu_hist``; ``task/XGBoostUpdater.java:124,155`` steps the native
booster; Rabit allreduce merges histograms across nodes, SURVEY.md §2.3).

TPU-native: no JNI, no Rabit, no DMatrix conversion — the booster IS the
tpu_hist core (h2o3_tpu/models/tree/booster.py): quantized features, Pallas/
XLA scatter-add histograms, psum merge over ICI, second-order split gains
with lambda/alpha/gamma regularization exactly as libxgboost defines them.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.framework import ModelBuilder, ModelParameters
from h2o3_tpu.models.tree.booster import TreeParams, train_boosted
from h2o3_tpu.models.tree.common import (
    TreeModelBase,
    checkpoint_booster as _checkpoint_booster,
    extra_trees as _extra_trees,
    make_tree_monitor,
    tree_cache_token,
    tree_fit_setup,
)


@dataclass
class XGBoostParameters(ModelParameters):
    ntrees: int = 50
    max_depth: int = 6
    learn_rate: float = 0.3  # eta
    nbins: int = 256  # max_bins (hist/gpu_hist default)
    min_rows: float = 1.0  # min_child_weight analogue on row counts
    min_split_improvement: float = 0.0
    reg_lambda: float = 1.0
    reg_alpha: float = 0.0
    gamma: float = 0.0
    sample_rate: float = 1.0  # subsample
    col_sample_rate_per_tree: float = 1.0  # colsample_bytree
    tree_method: str = "tpu_hist"
    distribution: str = "auto"
    score_tree_interval: int = 1
    tweedie_power: float = 1.5  # reg:tweedie variance power
    monotone_constraints: Optional[dict] = None  # {col: -1|+1}


class XGBoostModel(TreeModelBase):
    algo_name = "xgboost"


class XGBoost(ModelBuilder):

    SUPPORTED_COMMON = frozenset(
        {
            "checkpoint",
            "stopping_rounds",
            "weights_column",
            "categorical_encoding",
            "max_runtime_secs",
        }
    )
    algo_name = "xgboost"

    def __init__(self, params: Optional[XGBoostParameters] = None, **kw) -> None:
        super().__init__(params or XGBoostParameters(**kw))

    #: distributions the XGBoost objective surface supports (libxgboost's
    #: reg:squarederror / binary:logistic / multi:softprob / count:poisson /
    #: reg:gamma / reg:tweedie — no huber/quantile/laplace objectives there)
    DISTRIBUTIONS = frozenset(
        {"auto", "gaussian", "bernoulli", "multinomial", "poisson", "gamma", "tweedie"}
    )

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> XGBoostModel:
        p: XGBoostParameters = self.params
        if p.distribution not in self.DISTRIBUTIONS:
            raise ValueError(
                f"xgboost does not support distribution {p.distribution!r}; "
                f"choose from {sorted(self.DISTRIBUTIONS)}"
            )
        # (libxgboost starts from base_score — 0.5 prob -> 0 margin; we use
        # the data-driven init like the reference's H2O-side initial pred)
        model, X, y, weights, _, objective, f0, n_class_trees, mono = (
            tree_fit_setup(frame, p, XGBoostModel, use_offset=False)
        )

        tp = TreeParams(
            ntrees=_extra_trees(p, n_class_trees),
            max_depth=p.max_depth,
            learn_rate=p.learn_rate,
            nbins=p.nbins,
            min_rows=p.min_rows,
            min_split_improvement=p.min_split_improvement,
            reg_lambda=p.reg_lambda,
            reg_alpha=p.reg_alpha,
            gamma=p.gamma,
            sample_rate=p.sample_rate,
            col_sample_rate_per_tree=p.col_sample_rate_per_tree,
            seed=p.actual_seed(),
        )

        history = []
        monitor, score_interval = make_tree_monitor(
            model, p, objective, y, weights, history
        )
        model.booster = train_boosted(
            X,
            objective=objective,
            y=y,
            n_class_trees=n_class_trees,
            init_margin=f0,
            params=tp,
            monitor=monitor,
            score_interval=score_interval,
            resume_from=_checkpoint_booster(
                p, n_class_trees, self.algo_name,
                n_features=X.shape[1], encoding=model.tree_encoding,
            ),
            weights=weights,
            monotone=mono,
            cache_token=tree_cache_token(frame, p, model.tree_encoding),
            cache_frame_key=getattr(frame, "key", None),
        )
        model.ntrees_built = model.booster.trees_per_class[0].ntrees
        model.training_metrics = model.model_performance(frame)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model
