"""XGBoost-style booster — tree_method="tpu_hist", the north-star config.

Reference: ``h2o-extensions/xgboost`` — Java glue around native libxgboost
(``XGBoostModel.java:240-292,382-394`` resolves backend/tree_method to
``grow_gpu_hist``; ``task/XGBoostUpdater.java:124,155`` steps the native
booster; Rabit allreduce merges histograms across nodes, SURVEY.md §2.3).

TPU-native: no JNI, no Rabit, no DMatrix conversion — the booster IS the
tpu_hist core (h2o3_tpu/models/tree/booster.py): quantized features, Pallas/
XLA scatter-add histograms, psum merge over ICI, second-order split gains
with lambda/alpha/gamma regularization exactly as libxgboost defines them.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import metrics as M
from h2o3_tpu.models.data_info import response_vector
from h2o3_tpu.models.framework import ModelBuilder, ModelParameters
from h2o3_tpu.models.tree.booster import TreeParams, train_boosted
from h2o3_tpu.models.tree.common import (
    TreeModelBase,
    auto_distribution,
    checkpoint_booster as _checkpoint_booster,
    extra_trees as _extra_trees,
    init_margin,
    training_score,
    tree_data_info,
    tree_matrix,
)


@dataclass
class XGBoostParameters(ModelParameters):
    ntrees: int = 50
    max_depth: int = 6
    learn_rate: float = 0.3  # eta
    nbins: int = 256  # max_bins (hist/gpu_hist default)
    min_rows: float = 1.0  # min_child_weight analogue on row counts
    min_split_improvement: float = 0.0
    reg_lambda: float = 1.0
    reg_alpha: float = 0.0
    gamma: float = 0.0
    sample_rate: float = 1.0  # subsample
    col_sample_rate_per_tree: float = 1.0  # colsample_bytree
    tree_method: str = "tpu_hist"
    distribution: str = "auto"
    score_tree_interval: int = 1


class XGBoostModel(TreeModelBase):
    algo_name = "xgboost"


class XGBoost(ModelBuilder):

    SUPPORTED_COMMON = frozenset({"checkpoint", "stopping_rounds"})
    algo_name = "xgboost"

    def __init__(self, params: Optional[XGBoostParameters] = None, **kw) -> None:
        super().__init__(params or XGBoostParameters(**kw))

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> XGBoostModel:
        p: XGBoostParameters = self.params
        info = tree_data_info(frame, p.response_column, p.ignored_columns)
        y = response_vector(info, frame)
        nclasses = len(info.response_domain) if info.response_domain else 1
        dist = auto_distribution(nclasses) if p.distribution == "auto" else p.distribution

        model = XGBoostModel(p, info, dist)
        X = tree_matrix(info, frame)
        keep = ~np.isnan(y)
        X, y = X[keep], y[keep]

        # libxgboost starts from base_score (0.5 prob -> 0 margin); we use the
        # data-driven init like the reference's H2O-side initial prediction
        f0 = init_margin(dist, y, nclasses)
        n_class_trees = nclasses if dist == "multinomial" else 1

        tp = TreeParams(
            ntrees=_extra_trees(p, n_class_trees),
            max_depth=p.max_depth,
            learn_rate=p.learn_rate,
            nbins=p.nbins,
            min_rows=p.min_rows,
            min_split_improvement=p.min_split_improvement,
            reg_lambda=p.reg_lambda,
            reg_alpha=p.reg_alpha,
            gamma=p.gamma,
            sample_rate=p.sample_rate,
            col_sample_rate_per_tree=p.col_sample_rate_per_tree,
            seed=p.actual_seed(),
        )

        history = []

        def monitor(t: int, margin: np.ndarray) -> bool:
            model.ntrees_built = t + 1
            if p.stopping_rounds <= 0 or (t + 1) % p.score_tree_interval:
                return False
            history.append(training_score(dist, y, margin))
            model.scoring_history.append({"tree": t + 1, "score": history[-1]})
            return M.stop_early(
                history, p.stopping_rounds, more_is_better=False,
                stopping_tolerance=p.stopping_tolerance,
            )

        model.booster = train_boosted(
            X,
            objective=dist,
            y=y,
            n_class_trees=n_class_trees,
            init_margin=f0,
            params=tp,
            monitor=monitor if p.stopping_rounds > 0 else None,
            score_interval=p.score_tree_interval,
            resume_from=_checkpoint_booster(p, n_class_trees, self.algo_name),
        )
        model.ntrees_built = model.booster.trees_per_class[0].ntrees
        model.training_metrics = model.model_performance(frame)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model
