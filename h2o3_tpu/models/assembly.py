"""Assembly — a fitted munging pipeline, exportable as standalone code.

Reference: ``water/api/AssemblyHandler.java`` + h2o-py's ``H2OAssembly``
(steps: H2OColSelect / H2OColOp / H2OBinaryOp) — a named pipeline of
frame transforms fit once and exportable via ``toJava`` as a
dependency-free munger that replays the steps outside the cluster.

TPU-native: steps are tiny host-side column ops (the heavy path stays
rapids/mesh); the Java emitter writes a ``double[] fit(double[] row)``
over the numeric row, the same contract genmodel's GenMunger has.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.keyed import DKV

#: unary functions shared by apply + codegen (name -> (numpy, java expr))
_UNI = {
    "log": (np.log, "Math.log(v)"),
    "log1p": (np.log1p, "Math.log1p(v)"),
    "exp": (np.exp, "Math.exp(v)"),
    "sqrt": (np.sqrt, "Math.sqrt(v)"),
    "abs": (np.abs, "Math.abs(v)"),
    "floor": (np.floor, "Math.floor(v)"),
    "ceil": (np.ceil, "Math.ceil(v)"),
    "sin": (np.sin, "Math.sin(v)"),
    "cos": (np.cos, "Math.cos(v)"),
    "sign": (np.sign, "Math.signum(v)"),
    "negate": (np.negative, "-v"),
}

_BIN = {
    "+": "+", "-": "-", "*": "*", "/": "/",
}


@dataclass
class Assembly:
    """An ordered list of steps; ``fit`` applies them to a frame."""

    steps: List[Dict[str, Any]]
    key: str = ""
    #: column order of the fitted OUTPUT frame (codegen contract)
    out_names: List[str] = field(default_factory=list)
    in_names: List[str] = field(default_factory=list)

    def fit(self, frame: Frame) -> Frame:
        self.in_names = list(frame.names)
        fr = frame
        for step in self.steps:
            fr = self._apply(fr, step)
        self.out_names = list(fr.names)
        return fr

    def _apply(self, fr: Frame, step: Dict[str, Any]) -> Frame:
        op = step.get("op")
        if op == "ColSelect":
            cols = step.get("cols") or []
            missing = [c for c in cols if c not in fr.names]
            if missing:
                raise ValueError(f"ColSelect: no such columns {missing}")
            return fr.cols(list(cols))
        if op == "ColOp":
            fun = step.get("fun")
            if fun not in _UNI:
                raise ValueError(
                    f"ColOp: unknown fun {fun!r} (have {sorted(_UNI)})")
            col = step.get("col")
            c = fr.col(col)
            inplace = bool(step.get("inplace"))
            new = col if inplace else (
                step.get("new_col_name") or f"{fun}_{col}")
            with np.errstate(all="ignore"):
                data = _UNI[fun][0](c.numeric_view().astype(np.float64))
            # add_column replaces an existing same-named column IN PLACE,
            # so the inplace path keeps column order
            return fr.add_column(Column(new, data, ColType.NUM))
        if op == "BinaryOp":
            fun = step.get("fun")
            if fun not in _BIN:
                raise ValueError(
                    f"BinaryOp: unknown fun {fun!r} (have {sorted(_BIN)})")
            left = fr.col(step.get("left")).numeric_view().astype(np.float64)
            rhs = step.get("right")
            if isinstance(rhs, str):
                right = fr.col(rhs).numeric_view().astype(np.float64)
            else:
                right = float(rhs)
            with np.errstate(all="ignore"):
                data = {"+": np.add, "-": np.subtract,
                        "*": np.multiply, "/": np.divide}[fun](left, right)
            new = step.get("new_col_name") or f"{step.get('left')}_{fun}"
            return fr.add_column(Column(new, data, ColType.NUM))
        raise ValueError(f"unknown assembly op {op!r} "
                         f"(ColSelect | ColOp | BinaryOp)")

    # -- codegen (AssemblyHandler.toJava / GenMunger contract) ---------------
    def to_java(self, pojo_name: str) -> str:
        """Standalone Java munger: double[] fit(double[] row) replays the
        steps over the numeric input row (input order = in_names;
        categorical columns travel as their level codes)."""
        if not self.out_names:
            raise ValueError("assembly must be fit before toJava")
        idx = {n: i for i, n in enumerate(self.in_names)}
        lines = [
            f"// GENERATED assembly munger — do not edit.",
            f"// input columns: {', '.join(self.in_names)}",
            f"// output columns: {', '.join(self.out_names)}",
            f"public class {pojo_name} {{",
            f"  public static double[] fit(double[] row) {{",
            f"    java.util.HashMap<String, Double> v = new java.util.HashMap<>();",
        ]
        for n, i in idx.items():
            lines.append(f'    v.put("{n}", row[{i}]);')
        # the output projection comes from out_names (recorded at fit);
        # ColSelect steps only affect which names fit() kept
        for step in self.steps:
            op = step.get("op")
            if op == "ColOp":
                fun, col = step["fun"], step["col"]
                new = (col if step.get("inplace")
                       else (step.get("new_col_name") or f"{fun}_{col}"))
                expr = _UNI[fun][1].replace("v", f'v.get("{col}")')
                lines.append(f'    v.put("{new}", {expr});')
            elif op == "BinaryOp":
                fun = _BIN[step["fun"]]
                left = f'v.get("{step["left"]}")'
                rhs = step.get("right")
                right = (f'v.get("{rhs}")' if isinstance(rhs, str)
                         else repr(float(rhs)))
                new = step.get("new_col_name") or f"{step['left']}_{step['fun']}"
                lines.append(f'    v.put("{new}", {left} {fun} {right});')
        lines.append(f"    double[] out = new double[{len(self.out_names)}];")
        for j, n in enumerate(self.out_names):
            lines.append(f'    out[{j}] = v.get("{n}");')
        lines += ["    return out;", "  }", "}"]
        return "\n".join(lines) + "\n"


def fit_assembly(steps: List[Dict[str, Any]], frame: Frame) -> tuple:
    asm = Assembly(steps=list(steps))
    out = asm.fit(frame)
    asm.key = DKV.make_key("assembly")
    DKV.put(asm.key, asm)
    return asm, out
