"""PSVM — kernel SVM via incomplete Cholesky factorization (ICF).

Reference: ``hex/psvm/PSVM.java:24`` — binary soft-margin SVM with a Gaussian
kernel; the kernel matrix is approximated by a low-rank ICF factor H
(``hex/psvm/icf/``, rank ≈ rank_ratio·√n) distributed over nodes, the dual QP
is solved by an interior-point method over the factorized system, and the
model stores the support vectors + alphas + rho for exact-kernel scoring
(``hex/psvm/ScorerTask``).

TPU-native: ICF pivots on the host (rank·N kernel-column evaluations — each
column is one row-sharded matmul-shaped pass), and the dual QP is solved by
*projected gradient ascent on the box* with the bias folded in as a constant
feature (removes the yᵀα=0 equality constraint) — every iteration is two
[N,r] matmuls, jitted; no IPM linear algebra.  Scoring keeps the reference's
exact-kernel form over the support vectors.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.models.data_info import build_data_info, expand_matrix, response_vector
from h2o3_tpu.models.framework import Model, ModelBuilder, ModelParameters


@dataclass
class PSVMParameters(ModelParameters):
    hyper_param: float = 1.0  # C
    kernel_type: str = "gaussian"
    gamma: float = -1.0  # -1: 1/#features
    rank_ratio: float = -1.0  # -1: sqrt(n)/n
    positive_weight: float = 1.0
    negative_weight: float = 1.0
    sv_threshold: float = 1e-4
    max_iterations: int = 300
    fact_threshold: float = 1e-5


def _rbf_columns(X: np.ndarray, idx: np.ndarray, gamma: float) -> np.ndarray:
    """K[:, idx] for the gaussian kernel — one sharded-matmul-shaped pass."""
    sq = (X * X).sum(axis=1)
    P = X[idx]
    d2 = sq[:, None] - 2.0 * X @ P.T + (P * P).sum(axis=1)[None, :]
    return np.exp(-gamma * np.maximum(d2, 0.0))


def _icf(X: np.ndarray, gamma: float, rank: int, tol: float) -> np.ndarray:
    """Incomplete Cholesky of the RBF kernel with greedy pivoting
    (hex/psvm/icf/ IncompleteCholeskyFactorization): K ≈ H Hᵀ, H [n, r]."""
    n = X.shape[0]
    H = np.zeros((n, rank))
    d = np.ones(n)  # diag(K) - Σ H², RBF diag = 1
    pivots = []
    for j in range(rank):
        i = int(np.argmax(d))
        if d[i] < tol:
            H = H[:, :j]
            break
        pivots.append(i)
        kcol = _rbf_columns(X, np.array([i]), gamma)[:, 0]
        h = (kcol - H[:, :j] @ H[i, :j]) / np.sqrt(d[i])
        H[:, j] = h
        d = np.maximum(d - h * h, 0.0)
    return H


@partial(jax.jit, static_argnames=("iters",))
def _solve_box_qp(Z, Cvec, iters: int):
    """max Σα - ½αᵀQα, 0 ≤ α ≤ C, with Q = Z Zᵀ (Z = diag(y)·[H, 1]).
    Projected gradient ascent with a spectral-norm step estimate."""
    n = Z.shape[0]
    # power iteration for L = λmax(Q) (few steps suffice for a step size)
    v0 = jnp.ones(n) / jnp.sqrt(n)

    def power(_, v):
        w = Z @ (Z.T @ v)
        return w / jnp.maximum(jnp.linalg.norm(w), 1e-12)

    v = jax.lax.fori_loop(0, 20, power, v0)
    L = jnp.maximum(v @ (Z @ (Z.T @ v)), 1e-6)
    step = 1.0 / L

    def body(_, alpha):
        grad = 1.0 - Z @ (Z.T @ alpha)
        return jnp.clip(alpha + step * grad, 0.0, Cvec)

    return jax.lax.fori_loop(0, iters, body, jnp.zeros(n))


class PSVMModel(Model):
    algo_name = "psvm"

    def __init__(self, params, data_info):
        super().__init__(params, data_info)
        self.support_vectors: Optional[np.ndarray] = None  # [S, D]
        self.alpha_y: Optional[np.ndarray] = None  # αᵢyᵢ at support vectors
        self.rho: float = 0.0
        self.gamma_: float = 0.0
        self.svs_count: int = 0
        self.bounded_svs_count: int = 0
        self.rank_: int = 0

    def decision_function(self, frame: Frame) -> np.ndarray:
        X, _ = expand_matrix(self.data_info, frame, dtype=np.float64)
        sq = (X * X).sum(axis=1)
        S = self.support_vectors
        d2 = sq[:, None] - 2.0 * X @ S.T + (S * S).sum(axis=1)[None, :]
        K = np.exp(-self.gamma_ * np.maximum(d2, 0.0))
        return K @ self.alpha_y - self.rho

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        f = self.decision_function(frame)
        # calibrated-ish probabilities via the logistic of the margin
        pr = 1.0 / (1.0 + np.exp(-f))
        return np.stack([1 - pr, pr], axis=1)


class PSVM(ModelBuilder):
    algo_name = "psvm"

    def __init__(self, params: Optional[PSVMParameters] = None, **kw) -> None:
        super().__init__(params or PSVMParameters(**kw))

    def _validate(self, frame: Frame) -> None:
        super()._validate(frame)
        if self.params.kernel_type != "gaussian":
            raise ValueError("only the gaussian kernel is supported (like the reference)")

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> PSVMModel:
        p: PSVMParameters = self.params
        ycol = frame.col(p.response_column)
        if not ycol.is_categorical():
            frame = frame.add_column(ycol.as_factor())
        info = build_data_info(frame, p.response_column, ignored=p.ignored_columns,
                               standardize=True)
        if info.response_domain is None or len(info.response_domain) != 2:
            raise ValueError("PSVM requires a binary response")
        model = PSVMModel(p, info)
        X, skip = expand_matrix(info, frame, dtype=np.float64)
        yc = response_vector(info, frame)
        keep = ~(skip | np.isnan(yc))
        X, yc = X[keep], yc[keep]
        y = np.where(yc > 0, 1.0, -1.0)
        n, d = X.shape

        gamma = p.gamma if p.gamma > 0 else 1.0 / max(d, 1)
        model.gamma_ = gamma
        rank = int(p.rank_ratio * n) if p.rank_ratio > 0 else int(np.sqrt(n))
        rank = max(min(rank, n), 1)
        H = _icf(X, gamma, rank, p.fact_threshold)
        model.rank_ = H.shape[1]

        # bias as a constant pseudo-feature removes the equality constraint
        Haug = np.concatenate([H, np.ones((n, 1))], axis=1)
        Z = y[:, None] * Haug
        Cvec = np.where(y > 0, p.hyper_param * p.positive_weight,
                        p.hyper_param * p.negative_weight)
        alpha = np.asarray(
            _solve_box_qp(jnp.asarray(Z), jnp.asarray(Cvec), p.max_iterations)
        )

        sv = alpha > p.sv_threshold
        model.svs_count = int(sv.sum())
        model.bounded_svs_count = int((alpha >= Cvec - 1e-8).sum())
        model.support_vectors = X[sv]
        model.alpha_y = (alpha * y)[sv]
        # rho from the bias pseudo-feature's weight: f(x) = Σ αyK + b, b = wᵣ
        w = Z.T @ alpha
        model.rho = -float(w[-1])

        model.training_metrics = model.model_performance(frame)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model
