"""Aggregator — exemplar-based data aggregation.

Reference: ``hex/aggregator/Aggregator.java:16`` — single pass over rows:
a row within ``radius`` of an existing exemplar is counted into it, otherwise
it becomes a new exemplar; the radius is grown (and exemplars re-aggregated)
whenever the exemplar count overshoots ``target_num_exemplars`` beyond
``rel_tol_num_exemplars``.  Output is the exemplar frame + per-exemplar
``counts`` column.

TPU-native: the sequential scan becomes a *batched* scan — each batch computes
its full [B, E] distance matrix to the current exemplars as one MXU matmul,
absorbs covered rows with a segment-sum, and only the uncovered remainder is
processed greedily (tiny).  Radius escalation re-aggregates exemplars against
themselves with the same kernel.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.models.data_info import build_data_info, expand_matrix
from h2o3_tpu.models.framework import Model, ModelBuilder, ModelParameters


@dataclass
class AggregatorParameters(ModelParameters):
    target_num_exemplars: int = 5000
    rel_tol_num_exemplars: float = 0.5
    transform: str = "normalize"  # none | standardize | normalize
    batch_size: int = 65536


def _dist2(B: np.ndarray, E: np.ndarray) -> np.ndarray:
    """Squared euclidean distances [nb, ne] via the matmul expansion.

    Plain numpy on purpose: the exemplar count changes every batch, so a
    jitted version would recompile per batch and compile time would dominate.
    """
    return (
        (B * B).sum(axis=1, keepdims=True)
        - 2.0 * B @ E.T
        + (E * E).sum(axis=1)[None, :]
    )


class _ExemplarBuffer:
    """Capacity-doubling [cap, d] float32 buffer (amortized O(1) append)."""

    def __init__(self, d: int, cap: int = 1024) -> None:
        self._buf = np.zeros((cap, d), dtype=np.float32)
        self.n = 0

    def append(self, x: np.ndarray) -> None:
        if self.n == len(self._buf):
            self._buf = np.concatenate([self._buf, np.zeros_like(self._buf)])
        self._buf[self.n] = x
        self.n += 1

    @property
    def view(self) -> np.ndarray:
        return self._buf[: self.n]


class AggregatorModel(Model):
    algo_name = "aggregator"

    def __init__(self, params, data_info):
        super().__init__(params, data_info)
        self.exemplar_rows: Optional[np.ndarray] = None  # row indices into training frame
        self.counts: Optional[np.ndarray] = None
        self.output_frame: Optional[Frame] = None
        self.radius: float = 0.0

    @property
    def is_classifier(self) -> bool:
        return False

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        raise NotImplementedError("Aggregator produces an output frame, not predictions")


class Aggregator(ModelBuilder):
    algo_name = "aggregator"

    def __init__(self, params: Optional[AggregatorParameters] = None, **kw) -> None:
        super().__init__(params or AggregatorParameters(**kw))

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> AggregatorModel:
        p: AggregatorParameters = self.params
        info = build_data_info(
            frame, None, ignored=p.ignored_columns,
            standardize=p.transform in ("standardize", "normalize"),
        )
        X, _ = expand_matrix(info, frame, dtype=np.float32)
        n, d = X.shape
        if p.transform == "normalize" and d:
            # scale standardized features into [-.5,.5]-ish per-dim range
            span = X.max(axis=0) - X.min(axis=0)
            X = X / np.where(span > 0, span, 1.0)

        target = min(p.target_num_exemplars, n)
        hi_cap = target * (1.0 + p.rel_tol_num_exemplars)
        radius2 = 0.0  # start exact: every distinct row is an exemplar until overshoot
        ex_idx: List[int] = []
        counts: List[float] = []

        buf = _ExemplarBuffer(d)
        for start in range(0, n, p.batch_size):
            B = X[start : start + p.batch_size]
            covered = np.zeros(len(B), dtype=bool)
            assign = np.zeros(len(B), dtype=np.int64)
            if buf.n:
                d2 = _dist2(B, buf.view)
                j = d2.argmin(axis=1)
                m = d2[np.arange(len(B)), j] <= radius2
                covered, assign = m, j
            for k, c in zip(*np.unique(assign[covered], return_counts=True)):
                counts[k] += float(c)
            for bi in np.nonzero(~covered)[0]:
                x = B[bi]
                if buf.n:
                    d2x = ((buf.view - x) ** 2).sum(axis=1)
                    k = int(d2x.argmin())
                    if d2x[k] <= radius2:
                        counts[k] += 1.0
                        continue
                ex_idx.append(start + int(bi))
                counts.append(1.0)
                buf.append(x)
                if buf.n > hi_cap:
                    radius2 = _grow_radius(radius2, X)
                    ex_idx, counts, buf = _reaggregate(
                        ex_idx, buf, counts, radius2
                    )
            if self.job:
                self.job.update(min(1.0, (start + len(B)) / n))

        model = AggregatorModel(p, info)
        model.exemplar_rows = np.asarray(ex_idx, dtype=np.int64)
        model.counts = np.asarray(counts)
        model.radius = float(np.sqrt(radius2))
        out = frame.rows(model.exemplar_rows)
        model.output_frame = out.add_column(Column("counts", model.counts, ColType.NUM))
        return model


def _grow_radius(radius2: float, X: np.ndarray) -> float:
    """Escalate the merge radius (Aggregator.java's iterative radius growth)."""
    if radius2 <= 0.0:
        d = X.shape[1]
        return 1e-4 * max(d, 1)
    return radius2 * 2.0


def _reaggregate(ex_idx, buf: "_ExemplarBuffer", counts, radius2):
    """Merge exemplars that now fall within the grown radius of an earlier one."""
    keep_idx: List[int] = []
    keep_counts: List[float] = []
    kept = _ExemplarBuffer(buf.view.shape[1])
    for i in range(len(ex_idx)):
        x = buf.view[i]
        if kept.n:
            d2 = ((kept.view - x) ** 2).sum(axis=1)
            k = int(d2.argmin())
            if d2[k] <= radius2:
                keep_counts[k] += counts[i]
                continue
        keep_idx.append(ex_idx[i])
        keep_counts.append(counts[i])
        kept.append(x)
    return keep_idx, keep_counts, kept
