"""Generic model — import a scoring artifact (MOJO) back as a first-class
servable model.

Reference: ``h2o-algos/src/main/java/hex/generic/`` — ``Generic`` is a
ModelBuilder whose "training" is reading a MOJO; the resulting
``GenericModel`` scores through the embedded MojoModel and is otherwise a
normal in-cluster model (predict routes, metrics on demand, DKV key).

TPU-native: the embedded scorer is the numpy-only ``h2o3_tpu.genmodel``
MojoModel; batch scoring feeds it whole columns, so imported models score
vectorized like native ones (the reference's row-wise EasyPredict wrapper
is for streaming use).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from h2o3_tpu.frame.frame import ColType, Frame
from h2o3_tpu.keyed import DKV
from h2o3_tpu.models.data_info import DataInfo
from h2o3_tpu.models.framework import Job, Model, ModelBuilder, ModelParameters


@dataclass
class GenericParameters(ModelParameters):
    #: server-side path of the MOJO archive to import (hex/generic's
    #: GenericModelParameters._path / model_key upload)
    path: Optional[str] = None


class GenericModel(Model):
    algo_name = "generic"

    def __init__(self, params: GenericParameters, data_info: DataInfo, mojo) -> None:
        super().__init__(params, data_info)
        self.mojo = mojo

    @property
    def source_algo(self) -> str:
        return self.mojo.meta.get("algo", "?")

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        # feed the MojoModel whole columns (it reads only what it needs:
        # predictors + an optional offset column)
        data = {}
        for col in frame.columns:
            if col.type is ColType.CAT:
                data[col.name] = [
                    col.domain[v] if v >= 0 else None for v in col.data
                ]
            elif col.type is ColType.STR:
                data[col.name] = list(col.data)
            else:
                data[col.name] = col.numeric_view()
        return self.mojo.score(data)

    def variable_importances(self) -> dict:
        raise NotImplementedError("imported MOJOs carry no variable importances")


class Generic(ModelBuilder):
    """hex/generic/Generic.java — "training" = loading the artifact."""

    algo_name = "generic"

    def __init__(self, params: Optional[GenericParameters] = None, **kw) -> None:
        super().__init__(params or GenericParameters(**kw))

    def train(self, frame: Optional[Frame] = None, valid: Optional[Frame] = None) -> GenericModel:
        # no training frame: the artifact defines the layout — but the
        # no-silent-param guard still applies (frameless half of _validate)
        self._validate_params()
        p: GenericParameters = self.params
        if p.nfolds or p.fold_column:
            raise ValueError("generic import does not support cross-validation")
        self.job = Job("generic import").start()
        try:
            model = self._fit(frame, valid)
            self.job.done()
            return model
        except BaseException as e:
            self.job.fail(e)
            raise

    def _fit(self, frame: Optional[Frame] = None, valid: Optional[Frame] = None) -> GenericModel:
        p: GenericParameters = self.params
        if not p.path:
            raise ValueError("generic import requires `path` to a MOJO archive")
        from h2o3_tpu.genmodel import load_mojo

        mojo = load_mojo(p.path)
        lay = mojo.layout
        info = DataInfo(
            predictor_names=list(lay.predictor_names),
            response_name=lay.response_name,
            use_all_factor_levels=lay.use_all_factor_levels,
            standardize=lay.standardize,
            missing_values_handling=lay.missing_values_handling,
            num_means=dict(lay.num_means),
            num_sds=dict(lay.num_sds),
            cat_domains={k: list(v) for k, v in lay.cat_domains.items()},
            cat_mode=dict(lay.cat_mode),
            coef_names=list(lay.coef_names),
            response_domain=list(lay.response_domain) if lay.response_domain else None,
        )
        return GenericModel(p, info, mojo)


def import_mojo(path: str, model_id: Optional[str] = None) -> GenericModel:
    """h2o.import_mojo analogue: MOJO file -> servable Generic model."""
    model = Generic(path=path).train()
    if model_id:
        DKV.rekey(model, model_id)
    return model
