"""Word2Vec — skip-gram word embeddings with synchronous minibatch SGD.

Reference: ``hex/word2vec/Word2Vec.java:15-17`` (SkipGram word model,
hierarchical-softmax norm model, window/sent-sample/learning-rate-decay
params) and ``hex/word2vec/WordVectorTrainer.java:17,126`` (racy shared-memory
"Hogwild" updates + per-iteration cross-node model averaging).

TPU-native redesign: Hogwild is a CPU-cache idiom; on TPU the same estimator
is synchronous minibatch SGD with *negative sampling* (the standard modern
replacement for hierarchical softmax — no per-word binary-tree walk, just
batched gathers + matmuls that XLA fuses). Training pairs are generated
host-side per epoch (dynamic windows, frequency subsampling like the
reference's sent_sample_rate); every step is one jitted scatter-update over
the row-sharded pair batch.

Input convention follows the reference: a single string column of words in
order; NA rows separate sentences (``h2o-py h2o.H2OFrame`` tokenized layout).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.models.framework import Model, ModelBuilder, ModelParameters


@dataclass
class Word2VecParameters(ModelParameters):
    vec_size: int = 100
    window_size: int = 5
    epochs: int = 5
    min_word_freq: int = 5
    init_learning_rate: float = 0.025
    sent_sample_rate: float = 1e-3
    negative_samples: int = 5
    batch_size: int = 8192
    word_model: str = "skip_gram"  # skip_gram (CBOW not in reference either)


@partial(jax.jit, static_argnames=(), donate_argnums=(0, 1))
def _sgd_step(W, C, center, context, negs, lr):
    """One negative-sampling step. W/C: [V,D] in/out embeddings;
    center/context: [B]; negs: [B,K]."""
    w = W[center]  # [B, D]
    cpos = C[context]  # [B, D]
    cneg = C[negs]  # [B, K, D]

    pos_score = jnp.einsum("bd,bd->b", w, cpos)
    neg_score = jnp.einsum("bd,bkd->bk", w, cneg)
    gpos = jax.nn.sigmoid(pos_score) - 1.0  # dL/dscore
    gneg = jax.nn.sigmoid(neg_score)  # [B, K]

    grad_w = gpos[:, None] * cpos + jnp.einsum("bk,bkd->bd", gneg, cneg)
    grad_cpos = gpos[:, None] * w
    grad_cneg = gneg[:, :, None] * w[:, None, :]

    # per-index gradient *averaging*: a batch holds many pairs per word, and
    # summing their updates (sequential-SGD × batch duplicates) diverges
    D = W.shape[1]
    gW = jnp.zeros_like(W).at[center].add(grad_w)
    nW = jnp.zeros(W.shape[0], W.dtype).at[center].add(1.0)
    flat_negs = negs.reshape(-1)
    gC = (
        jnp.zeros_like(C)
        .at[context].add(grad_cpos)
        .at[flat_negs].add(grad_cneg.reshape(-1, D))
    )
    nC = (
        jnp.zeros(C.shape[0], C.dtype)
        .at[context].add(1.0)
        .at[flat_negs].add(1.0)
    )
    W = W - lr * gW / jnp.maximum(nW, 1.0)[:, None]
    C = C - lr * gC / jnp.maximum(nC, 1.0)[:, None]
    loss = -jnp.mean(
        jax.nn.log_sigmoid(pos_score) + jax.nn.log_sigmoid(-neg_score).sum(axis=1)
    )
    return W, C, loss


class Word2VecModel(Model):
    algo_name = "word2vec"

    def __init__(self, params, data_info=None):
        from h2o3_tpu.models.data_info import DataInfo

        super().__init__(params, data_info or DataInfo([], None, False, False, "skip"))
        self.vocab: Dict[str, int] = {}
        self.words: List[str] = []
        self.vectors: Optional[np.ndarray] = None  # [V, D]
        self.epochs_run: int = 0
        self.losses: List[float] = []

    @property
    def is_classifier(self) -> bool:
        return False

    def word_vector(self, word: str) -> Optional[np.ndarray]:
        i = self.vocab.get(word)
        return None if i is None else self.vectors[i]

    def find_synonyms(self, word: str, count: int = 10) -> Dict[str, float]:
        """Cosine-nearest words (reference Word2VecModel.findSynonyms)."""
        v = self.word_vector(word)
        if v is None:
            return {}
        V = self.vectors
        sims = (V @ v) / (np.linalg.norm(V, axis=1) * np.linalg.norm(v) + 1e-12)
        order = np.argsort(-sims)
        out: Dict[str, float] = {}
        for i in order:
            if self.words[i] == word:
                continue
            out[self.words[i]] = float(sims[i])
            if len(out) >= count:
                break
        return out

    def transform(self, frame: Frame, aggregate_method: str = "none") -> Frame:
        """Words -> vectors; ``aggregate_method='average'`` pools each
        NA-separated sentence (reference Word2VecModel.transform)."""
        col = frame.col(0)
        words = _string_values(col)
        D = self.vectors.shape[1]
        vecs = np.zeros((len(words), D))
        known = np.zeros(len(words), dtype=bool)
        for i, w in enumerate(words):
            j = self.vocab.get(w) if w is not None else None
            if j is not None:
                vecs[i] = self.vectors[j]
                known[i] = True
        if aggregate_method == "none":
            cols = [
                Column(f"V{d + 1}", np.where(known, vecs[:, d], np.nan), ColType.NUM)
                for d in range(D)
            ]
            return Frame(cols)
        # average per sentence (NA row = separator)
        sent_vecs: List[np.ndarray] = []
        acc, cnt = np.zeros(D), 0
        for i, w in enumerate(words):
            if w is None:
                sent_vecs.append(acc / cnt if cnt else np.full(D, np.nan))
                acc, cnt = np.zeros(D), 0
            elif known[i]:
                acc, cnt = acc + vecs[i], cnt + 1
        if cnt or not sent_vecs:
            sent_vecs.append(acc / cnt if cnt else np.full(D, np.nan))
        S = np.stack(sent_vecs)
        return Frame([Column(f"V{d + 1}", S[:, d], ColType.NUM) for d in range(D)])

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        raise NotImplementedError("Word2Vec transforms frames; use .transform()")


class Word2Vec(ModelBuilder):
    algo_name = "word2vec"

    def __init__(self, params: Optional[Word2VecParameters] = None, **kw) -> None:
        super().__init__(params or Word2VecParameters(**kw))

    def _validate(self, frame: Frame) -> None:
        super()._validate(frame)
        if frame.ncols != 1:
            raise ValueError("Word2Vec expects a single (string) column of words")

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> Word2VecModel:
        p: Word2VecParameters = self.params
        words = _string_values(frame.col(0))
        # vocab with min frequency (reference min_word_freq)
        freq: Dict[str, int] = {}
        for w in words:
            if w is not None:
                freq[w] = freq.get(w, 0) + 1
        vocab_words = sorted([w for w, c in freq.items() if c >= p.min_word_freq])
        vocab = {w: i for i, w in enumerate(vocab_words)}
        V = len(vocab)
        if V == 0:
            raise ValueError("no words meet min_word_freq")

        # sentences of word ids
        sentences: List[List[int]] = [[]]
        for w in words:
            if w is None:
                if sentences[-1]:
                    sentences.append([])
            else:
                i = vocab.get(w)
                if i is not None:
                    sentences[-1].append(i)
        if not sentences[-1]:
            sentences.pop()

        counts = np.array([freq[w] for w in vocab_words], dtype=np.float64)
        total = counts.sum()
        # subsampling keep-probability (word2vec sent_sample_rate formula)
        keep_p = np.minimum(
            (np.sqrt(counts / (p.sent_sample_rate * total)) + 1)
            * (p.sent_sample_rate * total) / np.maximum(counts, 1),
            1.0,
        ) if p.sent_sample_rate > 0 else np.ones(V)
        # unigram^0.75 negative-sampling table
        neg_p = counts**0.75
        neg_p /= neg_p.sum()

        rng = np.random.default_rng(p.actual_seed())
        D = p.vec_size
        W = jnp.asarray(((rng.random((V, D)) - 0.5) / D).astype(np.float32))
        C = jnp.asarray(np.zeros((V, D), dtype=np.float32))

        model = Word2VecModel(p)
        model.vocab = vocab
        model.words = vocab_words

        step = 0
        total_steps = max(p.epochs, 1)
        for epoch in range(p.epochs):
            centers, contexts = _make_pairs(sentences, p.window_size, keep_p, rng)
            if len(centers) == 0:
                break
            lr = p.init_learning_rate * max(1.0 - epoch / max(p.epochs, 1), 1e-4)
            order = rng.permutation(len(centers))
            bs = min(p.batch_size, len(centers))
            # whole batches only: a ragged tail would trigger a recompile, and
            # the shuffle re-covers dropped pairs across epochs
            n_batches = max(len(centers) // bs, 1)
            order = order[: n_batches * bs]
            centers_e, contexts_e = centers[order], contexts[order]
            # all negatives for the epoch in one draw (alias-free unigram^0.75)
            negs_e = rng.choice(
                V, size=(len(centers_e), p.negative_samples), p=neg_p
            ).astype(np.int32)
            ep_loss, nb = 0.0, 0
            for s in range(0, len(centers_e), bs):
                W, C, loss = _sgd_step(
                    W, C,
                    jnp.asarray(centers_e[s : s + bs]),
                    jnp.asarray(contexts_e[s : s + bs]),
                    jnp.asarray(negs_e[s : s + bs]),
                    jnp.float32(lr),
                )
                ep_loss += float(loss)
                nb += 1
            model.losses.append(ep_loss / max(nb, 1))
            model.epochs_run = epoch + 1
            if self.job:
                self.job.update((epoch + 1) / total_steps)
        model.vectors = np.asarray(W, dtype=np.float64)
        return model


def _make_pairs(
    sentences: List[List[int]], window: int, keep_p: np.ndarray, rng
) -> Tuple[np.ndarray, np.ndarray]:
    centers: List[int] = []
    contexts: List[int] = []
    for sent in sentences:
        ids = [i for i in sent if rng.random() < keep_p[i]]
        n = len(ids)
        for pos, c in enumerate(ids):
            b = rng.integers(1, window + 1)  # dynamic window like word2vec.c
            for off in range(-b, b + 1):
                j = pos + off
                if off != 0 and 0 <= j < n:
                    centers.append(c)
                    contexts.append(ids[j])
    return np.asarray(centers, dtype=np.int32), np.asarray(contexts, dtype=np.int32)


def _string_values(col: Column) -> List[Optional[str]]:
    """Column -> python words; NA -> None (sentence separator)."""
    if col.is_string():
        return [None if v is None else str(v) for v in col.data]
    if col.is_categorical():
        return [None if c < 0 else col.domain[c] for c in col.data]
    raise ValueError("Word2Vec needs a string or categorical column")
