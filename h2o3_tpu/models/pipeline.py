"""Scoring pipeline — munging steps + a model in ONE portable artifact.

Reference: the mojo-pipeline extension
(``h2o-extensions/mojo-pipeline/.../MojoPipeline.java:34-77`` —
``transform(Frame)`` over a pipeline artifact with strict input-column
adaptation — and ``rapids/AstPipelineTransform.java`` — the
``mojo.pipeline.transform`` rapids verb).  The reference scores
DriverlessAI MOJO2 archives through a licensed closed runtime it loads
reflectively; that runtime cannot and should not be reproduced.

TPU-native redesign: the pipeline artifact is self-describing — a zip of

* ``pipeline.json``: the fitted Assembly steps (models/assembly.py) plus
  the input/output column contract, and
* ``model.mojo``: this framework's MOJO (models/mojo_export.py),

scored by the numpy-only genmodel reader (genmodel/mojo_model.py), so a
saved pipeline runs anywhere the genmodel does — no cluster, no license.
``transform`` = adapt columns (missing input -> error, same contract as
``MojoPipeline.adaptFrame``) -> replay munging steps -> score.
"""

from __future__ import annotations

import io
import json
import os
import tempfile
import zipfile
from typing import Any, Dict, List, Optional

import numpy as np

from h2o3_tpu.frame.frame import ColType, Frame
from h2o3_tpu.keyed import DKV

#: artifact member names
_META = "pipeline.json"
_MOJO = "model.mojo"


class ScoringPipeline:
    """A fitted munging pipeline + embedded MOJO, servable and portable.

    steps: Assembly step dicts (may be empty — model-only pipeline);
    mojo_bytes: the embedded model artifact (None = transform-only);
    in_names: required input columns (adaptFrame contract).
    """

    def __init__(
        self,
        steps: List[Dict[str, Any]],
        mojo_bytes: Optional[bytes],
        in_names: List[str],
        key: str = "",
    ) -> None:
        self.steps = list(steps)
        self.mojo_bytes = mojo_bytes
        self.in_names = list(in_names)
        self.key = key
        self._mojo = None  # lazily loaded genmodel MojoModel

    # -- construction --------------------------------------------------------

    @classmethod
    def from_parts(cls, model=None, assembly=None) -> "ScoringPipeline":
        """Build from live objects: a trained Model and/or a fitted
        Assembly (either may be None, not both)."""
        if model is None and assembly is None:
            raise ValueError("pipeline needs a model, an assembly, or both")
        steps = list(assembly.steps) if assembly is not None else []
        # scoring-time inputs = columns the steps read + the model's
        # predictors that the steps don't themselves produce.  The frame
        # the assembly was FIT on may carry more (the response, id
        # columns); requiring those at transform time would make the
        # deployed pipeline unusable on unlabeled data.
        produced = set()
        referenced = set()
        for s in steps:
            op = s.get("op")
            if op == "ColSelect":
                referenced.update(s.get("cols") or [])
            elif op == "ColOp":
                referenced.add(s.get("col"))
                produced.add(
                    s["col"] if s.get("inplace")
                    else (s.get("new_col_name") or f"{s.get('fun')}_{s.get('col')}"))
            elif op == "BinaryOp":
                referenced.add(s.get("left"))
                if isinstance(s.get("right"), str):
                    referenced.add(s["right"])
                produced.add(
                    s.get("new_col_name") or f"{s.get('left')}_{s.get('fun')}")
        needed = set(referenced)
        if model is not None:
            needed.update(
                n for n in model.data_info.predictor_names
                if n not in produced
            )
        if assembly is not None and assembly.in_names:
            in_names = [n for n in assembly.in_names if n in needed]
            # a model predictor absent from the fit frame cannot happen in
            # a fit assembly; keep any stragglers anyway (fail loud later)
            in_names += sorted(needed - set(assembly.in_names) - produced)
        else:
            in_names = sorted(needed)
        mojo_bytes = None
        if model is not None:
            fd, path = tempfile.mkstemp(suffix=".mojo")
            os.close(fd)
            try:
                model.download_mojo(path)
                with open(path, "rb") as f:
                    mojo_bytes = f.read()
            finally:
                os.unlink(path)
        return cls(steps, mojo_bytes, in_names)

    # -- the artifact --------------------------------------------------------

    def to_bytes(self) -> bytes:
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr(_META, json.dumps({
                "version": 1,
                "steps": self.steps,
                "in_names": self.in_names,
            }))
            if self.mojo_bytes is not None:
                z.writestr(_MOJO, self.mojo_bytes)
        return buf.getvalue()

    def save(self, path: str) -> str:
        with open(path, "wb") as f:
            f.write(self.to_bytes())
        return path

    @classmethod
    def from_bytes(cls, data: bytes) -> "ScoringPipeline":
        with zipfile.ZipFile(io.BytesIO(data)) as z:
            names = set(z.namelist())
            if _META not in names:
                raise ValueError(
                    f"not a pipeline artifact (no {_META} member)")
            meta = json.loads(z.read(_META).decode())
            mojo = z.read(_MOJO) if _MOJO in names else None
        return cls(meta.get("steps") or [], mojo,
                   meta.get("in_names") or [])

    @classmethod
    def load(cls, path: str) -> "ScoringPipeline":
        with open(path, "rb") as f:
            return cls.from_bytes(f.read())

    # -- scoring -------------------------------------------------------------

    def _genmodel(self):
        if self._mojo is None:
            if self.mojo_bytes is None:
                raise ValueError("transform-only pipeline has no model")
            from h2o3_tpu.genmodel.mojo_model import load_mojo

            fd, path = tempfile.mkstemp(suffix=".mojo")
            os.close(fd)
            try:
                with open(path, "wb") as f:
                    f.write(self.mojo_bytes)
                self._mojo = load_mojo(path)
            finally:
                os.unlink(path)
        return self._mojo

    def _adapt(self, frame: Frame) -> Frame:
        """MojoPipeline.adaptFrame: every declared input column must be
        present; extra columns pass through untouched (munging steps may
        reference them only if they were recorded as inputs)."""
        for name in self.in_names:
            if name not in frame.names:
                raise ValueError(
                    f"Input frame is missing a column: {name}")
        return frame

    def transform(self, frame: Frame) -> Frame:
        """Munging steps then (if a model is embedded) scoring; returns the
        output frame (predictions, or the munged frame for transform-only
        pipelines)."""
        fr = self._adapt(frame)
        if self.steps:
            from h2o3_tpu.models.assembly import Assembly

            fr = Assembly(steps=self.steps).fit(fr)
        if self.mojo_bytes is None:
            return fr
        mojo = self._genmodel()
        data: Dict[str, Any] = {}
        for col in fr.columns:
            if col.type is ColType.CAT:
                data[col.name] = [
                    col.domain[v] if v >= 0 else None for v in col.data
                ]
            elif col.type is ColType.STR:
                data[col.name] = list(col.data)
            else:
                data[col.name] = col.numeric_view()
        raw = mojo.score(data)
        from h2o3_tpu.models.framework import prediction_frame

        # dispatch on the MOJO's declared response domain, NOT the score
        # shape: an unsupervised model's [N, k] output (PCA projections)
        # must come back as k numeric columns, not argmax "labels"
        if not mojo.is_classifier:
            return prediction_frame(raw, None)
        return prediction_frame(
            raw, mojo.domain_values,
            float(mojo.meta.get("default_threshold", 0.5)))


def build_pipeline(model=None, assembly=None) -> ScoringPipeline:
    """Construct, register in the DKV, and return a ScoringPipeline."""
    pipe = ScoringPipeline.from_parts(model=model, assembly=assembly)
    pipe.key = DKV.make_key("pipeline")
    DKV.put(pipe.key, pipe)
    return pipe
