"""Reference-format MOJO export: the actual H2O-3 MOJO zip layout.

Reference (format spec, mirrored byte-for-byte):
  * container: ``hex/ModelMojoWriter.java`` — a zip of ``model.ini``
    ([info] key=value, [columns], [domains] sections), ``domains/d*.txt``
    and binary blobs;
  * compressed trees: ``hex/tree/DTree.java:727-815`` (``size``/
    ``compress``) — per decided node: 1B nodeType (equal bits 8/12,
    left-leaf |=48, else skip-size bits; right-leaf |=0xC0), 2B colId,
    1B naSplitDir, 4B float split value, skip offset in 1..4 bytes,
    then the left and right subtrees inline; leaves are a bare 4B
    float; a root-leaf is ``00 FF FF`` + float
    (``DTree.java:855``);
  * reader contract: ``hex/genmodel/ModelMojoReader.readAll`` (required
    [info] keys), ``SharedTreeMojoReader`` (n_trees/n_trees_per_class/
    tree blob names), ``GbmMojoModel.score0/unifyPreds`` (init_f +
    link inverse; multinomial softmax over per-class tree sums).

The writer emits GBM models in this exact layout; ``read_mojo`` is an
INDEPENDENT decoder implementing the ``SharedTreeMojoModel.scoreTree``
byte-walk, used by the parity tests (write -> decode -> score must
equal in-framework predict). It handles float splits only; bitset
categorical splits are rejected loudly — this framework's boosters
label-encode categoricals, so the writer never emits them.
"""

from __future__ import annotations

import io
import struct
import uuid as _uuid
import zipfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

_NA_LEFT = 2
_NA_RIGHT = 3

_LINK_BY_DIST = {
    "bernoulli": "logit",
    "multinomial": "identity",  # softmax applied in unifyPreds
    "poisson": "log",
    "gamma": "log",
    "tweedie": "log",
}


# ---------------------------------------------------------------------------
# tree encoder (DTree.size + DTree.compress)


def _encode_subtree(trees, t: int, i: int, edges, raw_thresh=None) -> bytes:
    """Compress the heap subtree rooted at node i of tree t.

    raw_thresh: optional [M] float thresholds for trees that split on raw
    values rather than bin codes (isolation forest) — bypasses the
    edges[feature][bin] lookup."""
    is_split = trees.is_split[t]
    if not is_split[i]:
        return struct.pack("<f", float(trees.leaf[t][i]))
    f = int(trees.feat[t][i])
    if raw_thresh is not None:
        thr = float(raw_thresh[i])
    else:
        sb = int(trees.split_bin[t][i])
        thr = (np.inf if sb >= edges.shape[1]
               else float(edges[f][sb]))
    # a split node's children always exist in the heap (splits stop one
    # level above the leaf frontier)
    left = _encode_subtree(trees, t, 2 * i + 1, edges, raw_thresh)
    right = _encode_subtree(trees, t, 2 * i + 2, edges, raw_thresh)
    left_leaf = not is_split[2 * i + 1]
    right_leaf = not is_split[2 * i + 2]

    node_type = 0  # equal == 0: float compare
    if left_leaf:
        node_type |= 48
        offset = b""
    else:
        lsz = len(left)
        slen = 0 if lsz < 256 else (1 if lsz < 65535 else
                                    (2 if lsz < (1 << 24) else 3))
        node_type |= slen
        offset = lsz.to_bytes(slen + 1, "little")
    if right_leaf:
        node_type |= (48 << 2) & 0xFF

    na_dir = _NA_LEFT if trees.default_left[t][i] else _NA_RIGHT
    out = bytearray()
    out.append(node_type)
    out += struct.pack("<H", f)
    out.append(na_dir)
    out += struct.pack("<f", thr)
    out += offset
    out += left
    out += right
    return bytes(out)


def _encode_tree(trees, t: int, leaf_shift: float = 0.0,
                 leaf_flip: bool = False) -> bytes:
    if leaf_flip or leaf_shift:
        # copy-on-write of THIS tree's leaves only (a shallow list copy;
        # deep-copying every tree here would make export O(ntrees²)).
        # leaf_shift bakes the class's WHOLE init margin into this tree
        # (the caller picks tree 0): the MOJO carries one scalar init_f,
        # and margins are additive, so one tree carrying +init_c on
        # every root-to-leaf path reproduces the class offset exactly.
        # leaf_flip turns per-tree p1 leaves into the class-0
        # probabilities DrfMojoModel expects.
        import copy

        trees = copy.copy(trees)
        trees.leaf = list(trees.leaf)
        lf = trees.leaf[t].astype(np.float64)
        if leaf_flip:
            lf = 1.0 - lf
        trees.leaf[t] = (lf + leaf_shift).astype(np.float32)
    if not trees.is_split[t][0]:
        return b"\x00\xff\xff" + struct.pack(
            "<f", float(trees.leaf[t][0]))
    return _encode_subtree(trees, t, 0, trees.edges)


def _encode_raw_tree(is_split, feat, thresh, leaf) -> bytes:
    """Encode one raw-threshold heap tree (isolation forest): NaN routes
    left at every split, leaves carry float path lengths."""
    import types

    shim = types.SimpleNamespace(
        is_split=[np.asarray(is_split)],
        feat=[np.asarray(feat)],
        leaf=[np.asarray(leaf)],
        default_left=[np.ones(len(feat), bool)],
        split_bin=[np.zeros(len(feat), np.int32)],
        edges=np.zeros((0, 0)),
    )
    if not is_split[0]:
        return b"\x00\xff\xff" + struct.pack("<f", float(leaf[0]))
    return _encode_subtree(shim, 0, 0, shim.edges, raw_thresh=thresh)


# ---------------------------------------------------------------------------
# writer


def _zip_write(path: str, ini_lines: List[str],
               domain_texts: Dict[str, str],
               blobs: Dict[str, bytes]) -> str:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("model.ini", "\n".join(ini_lines) + "\n")
        for name, text in domain_texts.items():
            z.writestr(name, text)
        for name, blob in blobs.items():
            z.writestr(name, blob)
    with open(path, "wb") as f:
        f.write(buf.getvalue())
    return path


def _jdouble(v: float) -> str:
    """One double in Java Double.toString spelling: non-finite values are
    'Infinity'/'-Infinity'/'NaN' (Python repr's 'inf'/'nan' would misparse
    in a genuine h2o-genmodel reader's parseDouble)."""
    v = float(v)
    if v != v:
        return "NaN"
    if v == float("inf"):
        return "Infinity"
    if v == float("-inf"):
        return "-Infinity"
    return repr(v)


def _jarr(vals) -> str:
    """Java Arrays.toString formatting for a double[] ini value."""
    return "[" + ", ".join(_jdouble(v) for v in vals) + "]"


def _parse_jarr(s: str, cast=float):
    """Inverse of _jarr: parse a bracketed comma-joined kv array.
    float() natively accepts both the Java ('Infinity'/'NaN') and the
    Python ('inf'/'nan') spellings, so no special casing is needed."""
    body = s.strip()[1:-1].strip()
    return [cast(x) for x in body.split(",")] if body else []


def _glm_class_beta(info_d, cats, nums, coef: Dict[str, float]):
    """One class's flat beta in the reference layout: cats-first
    (catOffsets, skipping level 0 unless use_all_factor_levels), nums,
    intercept last. Returns (beta, cat_offsets)."""
    skip = 0 if info_d.use_all_factor_levels else 1
    cat_offsets = [0]
    beta: List[float] = []
    for c in cats:
        dom = info_d.cat_domains[c]
        for lv in dom[skip:]:
            beta.append(float(coef.get(f"{c}.{lv}", 0.0)))
        cat_offsets.append(len(beta))
    for n in nums:
        beta.append(float(coef.get(n, 0.0)))
    beta.append(float(coef.get("Intercept", 0.0)))
    return beta, cat_offsets


def _write_glm_mojo(model, path: str) -> str:
    """GLM in the reference layout (GLMMojoWriter.writeModelData /
    GlmMojoModel.glmScore0, GlmMultinomialMojoModel for multinomial):
    cats-first row layout, catOffsets into a flat raw-scale beta, num
    block, intercept last; multinomial concatenates the per-class betas
    class-major (beta[i + c*P])."""
    p = model.params
    if p.family == "ordinal":
        raise ValueError("reference-format GLM MOJO does not cover the "
                         "ordinal family (thresholded cumulative etas "
                         "have no GlmMojoModel analogue)")
    info_d = model.data_info
    cats = [n for n in info_d.predictor_names if n in info_d.cat_domains]
    nums = [n for n in info_d.predictor_names
            if n not in info_d.cat_domains]
    if p.family == "multinomial":
        beta = []
        cat_offsets = None
        for lv in info_d.response_domain:
            cb, cat_offsets = _glm_class_beta(
                info_d, cats, nums, model.coefficients_multinomial[lv])
            beta.extend(cb)
    else:
        beta, cat_offsets = _glm_class_beta(
            info_d, cats, nums, model.coefficients)

    columns = cats + nums + [p.response_column]
    dom_texts: Dict[str, str] = {}
    dom_lines = []
    di = 0
    for ci, c in enumerate(cats):
        dom = info_d.cat_domains[c]
        dom_lines.append(f"{ci}: {len(dom)} d{di:03d}.txt")
        dom_texts[f"domains/d{di:03d}.txt"] = "\n".join(dom) + "\n"
        di += 1
    rdom = info_d.response_domain
    if rdom:
        dom_lines.append(f"{len(columns) - 1}: {len(rdom)} d{di:03d}.txt")
        dom_texts[f"domains/d{di:03d}.txt"] = "\n".join(rdom) + "\n"

    nclasses = model.nclasses
    if p.family == "multinomial":
        category = "Multinomial"
    else:
        category = "Binomial" if nclasses == 2 else "Regression"
    kv = [
        ("algorithm", "Generalized Linear Model"),
        ("algo", "glm"),
        ("category", category),
        ("uuid", str(_uuid.uuid4())),
        ("supervised", "true"),
        ("n_features", len(cats) + len(nums)),
        ("n_classes", nclasses if nclasses > 1 else 1),
        ("n_columns", len(columns)),
        ("n_domains", len(dom_lines)),
        ("balance_classes", "false"),
        ("default_threshold", 0.5),
        ("prior_class_distrib", "null"),
        ("model_class_distrib", "null"),
        ("offset_column", "null"),
        ("mojo_version", "1.00"),
        ("h2o_version", "h2o3-tpu"),
        ("use_all_factor_levels",
         "true" if info_d.use_all_factor_levels else "false"),
        ("cats", len(cats)),
        ("cat_modes", "[" + ", ".join(
            str(info_d.cat_mode[c]) for c in cats) + "]"),
        ("cat_offsets", "[" + ", ".join(map(str, cat_offsets)) + "]"),
        ("nums", len(nums)),
        ("num_means", "[" + ", ".join(
            _jdouble(info_d.num_means[n]) for n in nums) + "]"),
        ("mean_imputation",
         "true" if info_d.missing_values_handling == "mean_imputation"
         else "false"),
        ("beta", "[" + ", ".join(_jdouble(b) for b in beta) + "]"),
        ("family", p.family),
        ("link", p.actual_link()),
        ("tweedie_link_power", p.tweedie_link_power),
    ]
    lines = ["[info]"]
    lines += [f"{k} = {v}" for k, v in kv]
    lines += ["", "[columns]"] + columns + ["", "[domains]"] + dom_lines
    return _zip_write(path, lines, dom_texts, {})


def _write_gam_mojo(model, path: str) -> str:
    """GAM (cubic-regression smoothers) in the reference layout
    (``hex/gam/GAMMojoWriter.java`` / ``GamMojoReader.java``): the
    artifact carries knots, ``_binvD`` (= B⁻¹D) and ``zTranspose`` per
    smoother as big-endian double blobs, the gam column-name text files,
    and both the centered and de-centered GLM betas; the scorer
    re-gamifies each row with ``GamUtilsCubicRegression`` and evaluates
    ``beta_center``. The training-side basis construction
    (``models/gam.py cr_basis``) is the same a/c-function algebra, so
    in-range rows score identically; outside the boundary knots the
    reference extrapolates the boundary-bin cubic while training used
    linear extrapolation — only such rows can differ.

    Covered: every-smoother-CR (bs=0), non-multinomial families,
    standardize=False. Thin-plate needs the polynomial-basis machinery
    (``GamUtilsThinPlateRegression``) and I-/M-splines have no genmodel
    scorer at all — all three refuse rather than export an artifact
    that scores differently."""
    p = model.params
    if any(s.kind != 0 for s in model.specs):
        raise ValueError(
            "reference-format GAM MOJO covers cubic-regression smoothers "
            "(bs=0) only; thin-plate needs GamUtilsThinPlateRegression's "
            "polynomial machinery and I-/M-splines have no genmodel "
            "scorer")
    if p.family in ("multinomial", "ordinal"):
        raise ValueError("reference-format GAM MOJO covers non-"
                         "multinomial families only")
    if p.standardize:
        raise ValueError("reference-format GAM MOJO export requires "
                         "standardize=False (the reference stores raw-"
                         "scale betas)")
    info_d = model.data_info
    cats = [n for n in info_d.predictor_names if n in info_d.cat_domains]
    nums = [n for n in info_d.predictor_names
            if n not in info_d.cat_domains]
    # linear betas permuted cats-first (same layout as the GLM writer)
    lin_beta, cat_offsets = _glm_class_beta(
        info_d, cats, nums, model.coefficients)
    lin_beta = lin_beta[:-1]  # intercept re-appended after the gam block
    intercept = float(model.coefficients["Intercept"])

    specs = model.specs
    n_gam = len(specs)
    n_lin = info_d.n_coefs
    # centered gam coefficients straight from the solved beta blocks
    gam_center: List[np.ndarray] = []
    off = n_lin
    for s in specs:
        kz = len(s.knots) - 1
        gam_center.append(np.asarray(model.beta[off:off + kz], np.float64))
        off += kz
    gam_no_center = [s.Z @ g for s, g in zip(specs, gam_center)]

    beta_center = lin_beta + [float(v) for g in gam_center for v in g] \
        + [intercept]
    beta_no_center = lin_beta + [float(v) for g in gam_no_center
                                 for v in g] + [intercept]

    gam_col_names = [[f"{s.column}_cr_{i}" for i in range(len(s.knots))]
                     for s in specs]
    gam_col_names_center = [
        [f"{s.column}_cr_{i}" for i in range(len(s.knots) - 1)]
        for s in specs]
    names_no_centering = (cats + nums
                          + [n for blk in gam_col_names for n in blk])
    columns = (cats + nums
               + [n for blk in gam_col_names_center for n in blk]
               + [p.response_column])

    dom_texts: Dict[str, str] = {}
    dom_lines = []
    di = 0
    for ci, c in enumerate(cats):
        dom = info_d.cat_domains[c]
        dom_lines.append(f"{ci}: {len(dom)} d{di:03d}.txt")
        dom_texts[f"domains/d{di:03d}.txt"] = "\n".join(dom) + "\n"
        di += 1
    rdom = info_d.response_domain
    if rdom:
        dom_lines.append(f"{len(columns) - 1}: {len(rdom)} d{di:03d}.txt")
        dom_texts[f"domains/d{di:03d}.txt"] = "\n".join(rdom) + "\n"

    # blobs: knots / zTranspose / _binvD, big-endian f64 (ByteBuffer)
    from h2o3_tpu.models.gam import cr_matrices

    knots_blob = b"".join(
        np.asarray(s.knots, ">f8").tobytes() for s in specs)
    zt_blob = b"".join(
        np.ascontiguousarray(s.Z.T, ">f8").tobytes() for s in specs)
    binvd_blob = b""
    for s in specs:
        D, B = cr_matrices(np.asarray(s.knots))
        binvd_blob += np.ascontiguousarray(
            np.linalg.solve(B, D), ">f8").tobytes()

    n_expanded = sum(len(s.knots) for s in specs)
    n_expanded_center = sum(len(s.knots) - 1 for s in specs)
    nclasses = model.nclasses
    category = "Binomial" if nclasses == 2 else "Regression"
    kv: List[Tuple[str, Any]] = [
        ("algorithm", "Generalized Additive Model"),
        ("algo", "gam"),
        ("category", category),
        ("uuid", str(_uuid.uuid4())),
        ("supervised", "true"),
        ("n_features", len(columns) - 1),
        ("n_classes", nclasses if nclasses > 1 else 1),
        ("n_columns", len(columns)),
        ("n_domains", len(dom_lines)),
        ("balance_classes", "false"),
        ("default_threshold",
         _jdouble(model.default_threshold()) if nclasses == 2 else 0.5),
        ("prior_class_distrib", "null"),
        ("model_class_distrib", "null"),
        ("mojo_version", "1.00"),
        ("h2o_version", "h2o3-tpu"),
        ("use_all_factor_levels",
         "true" if info_d.use_all_factor_levels else "false"),
        ("family", p.family),
        ("link", p.actual_link()),
        ("tweedie_link_power", p.tweedie_link_power),
        ("cats", len(cats)),
        ("cat_offsets", "[" + ", ".join(map(str, cat_offsets)) + "]"),
        ("catNAFills", "[" + ", ".join(
            str(info_d.cat_mode[c]) for c in cats) + "]"),
        ("num", len(nums) + n_expanded),
        ("numsCenter", len(nums) + n_expanded_center),
        ("numNAFillsCenter", _jarr(
            [info_d.num_means[n] for n in nums]
            + [0.0] * n_expanded_center)),
        ("mean_imputation",
         "true" if info_d.missing_values_handling == "mean_imputation"
         else "false"),
        ("beta length per class", len(beta_no_center)),
        ("beta center length per class", len(beta_center)),
        ("beta", _jarr(beta_no_center)),
        ("beta_center", _jarr(beta_center)),
        ("num_expanded_gam_columns", n_expanded),
        ("num_expanded_gam_columns_center", n_expanded_center),
        ("num_knots", "[" + ", ".join(
            str(len(s.knots)) for s in specs) + "]"),
        ("num_knots_sorted", "[" + ", ".join(
            str(len(s.knots)) for s in specs) + "]"),
        ("gam_column_dim", "[" + ", ".join(["1"] * n_gam) + "]"),
        ("gam_column_dim_sorted", "[" + ", ".join(["1"] * n_gam) + "]"),
        ("num_TP_col", 0),
        ("total feature size", len(names_no_centering)),
        ("bs", "[" + ", ".join(["0"] * n_gam) + "]"),
        ("bs_sorted", "[" + ", ".join(["0"] * n_gam) + "]"),
        ("gamColName_dim", "[" + ", ".join(
            str(len(s.knots)) for s in specs) + "]"),
        ("_d", "[" + ", ".join(["1"] * n_gam) + "]"),
    ]
    dom_texts["gam_columns"] = "\n".join(s.column for s in specs) + "\n"
    dom_texts["gam_columns_sorted"] = dom_texts["gam_columns"]
    dom_texts["_names_no_centering"] = "\n".join(names_no_centering) + "\n"
    dom_texts["gamColNames"] = "\n".join(
        n for blk in gam_col_names for n in blk) + "\n"
    dom_texts["gamColNamesCenter"] = "\n".join(
        n for blk in gam_col_names_center for n in blk) + "\n"
    lines = ["[info]"]
    lines += [f"{k} = {v}" for k, v in kv]
    lines += ["", "[columns]"] + columns + ["", "[domains]"] + dom_lines
    return _zip_write(path, lines, dom_texts, {
        "knots": knots_blob,
        "zTranspose": zt_blob,
        "_binvD": binvd_blob,
    })


def _write_kmeans_mojo(model, path: str) -> str:
    """KMeans in the reference layout (KMeansMojoWriter.writeModelData /
    KMeansMojoModel.score0): standardize means/mults/modes kv arrays plus
    one ``center_<i>`` kv per centroid, distance in standardized space.

    Numeric predictors only: the reference scorer keeps categorical
    columns as single indicator-distance columns while this framework
    one-hot expands them into the design matrix — the two center layouts
    are not interconvertible, so categorical models raise."""
    info = model.data_info
    if info.cat_domains:
        raise ValueError("reference-format KMeans MOJO covers numeric "
                         "predictors only (the reference scorer's "
                         "categorical distance is not one-hot)")
    nums = list(info.predictor_names)
    standardize = bool(getattr(info, "standardize", False))
    centers = model.centers_std if standardize else model.centers
    centers = np.asarray(centers, np.float64)

    kv = [
        ("algorithm", "K-means"),
        ("algo", "kmeans"),
        ("category", "Clustering"),
        ("uuid", str(_uuid.uuid4())),
        ("supervised", "false"),
        ("n_features", len(nums)),
        ("n_classes", 1),
        ("n_columns", len(nums)),
        ("n_domains", 0),
        ("balance_classes", "false"),
        ("default_threshold", 0.5),
        ("prior_class_distrib", "null"),
        ("model_class_distrib", "null"),
        ("mojo_version", "1.00"),
        ("h2o_version", "h2o3-tpu"),
        ("standardize", "true" if standardize else "false"),
    ]
    # means are written even when standardize is off: the in-framework
    # scorer always mean-imputes NAs, and this extra kv lets the decoder
    # match it (a reference reader only consults these when standardize
    # is true — for NA rows on unstandardized models the reference
    # runtime itself cannot impute)
    kv.append(("standardize_means", _jarr(info.num_means[n] for n in nums)))
    if standardize:
        kv += [
            ("standardize_mults",
             _jarr(1.0 / max(info.num_sds[n], 1e-300) for n in nums)),
            ("standardize_modes",
             "[" + ", ".join(["-1"] * len(nums)) + "]"),
        ]
    kv.append(("center_num", centers.shape[0]))
    for i, c in enumerate(centers):
        kv.append((f"center_{i}", _jarr(c)))
    lines = ["[info]"]
    lines += [f"{k} = {v}" for k, v in kv]
    lines += ["", "[columns]"] + nums + ["", "[domains]"]
    return _zip_write(path, lines, {}, {})


def _write_isofor_mojo(model, path: str) -> str:
    """Isolation forest in the reference layout
    (IsolationForestMojoWriter / IsolationForestMojoModel.unifyPreds):
    SharedTree-format trees whose leaves carry path lengths, plus
    min/max_path_length for the (max - sum)/(max - min) score."""
    feats, threshs, splits, plens = model.trees
    ntrees = feats.shape[0]
    names = list(model.data_info.predictor_names)
    info = [
        ("algorithm", "Isolation Forest"),
        ("algo", "isolation_forest"),
        ("category", "AnomalyDetection"),
        ("uuid", str(_uuid.uuid4())),
        ("supervised", "false"),
        ("n_features", len(names)),
        ("n_classes", 1),
        ("n_columns", len(names)),
        ("n_domains", 0),
        ("balance_classes", "false"),
        ("default_threshold", 0.5),
        ("prior_class_distrib", "null"),
        ("model_class_distrib", "null"),
        ("mojo_version", "1.40"),
        ("h2o_version", "h2o3-tpu"),
        ("n_trees", ntrees),
        ("n_trees_per_class", 1),
        # int fields on the reference model (IsolationForestMojoReader):
        # conservative rounding keeps every training score inside [0, 1]
        ("max_path_length", int(np.ceil(model.max_path_total))),
        ("min_path_length", int(np.floor(model.min_path_total))),
        ("output_anomaly_flag", "false"),
        ("_genmodel_encoding", "LabelEncoder"),
    ]
    lines = ["[info]"]
    lines += [f"{k} = {v}" for k, v in info]
    lines += ["", "[columns]"] + names + ["", "[domains]"]
    # training routes left on v <= cut; the MOJO runtime routes left on
    # v < thr (strict) — thr = nextafter(cut) makes the two identical for
    # every float32 input
    thr_adj = np.nextafter(
        np.asarray(threshs, np.float32), np.float32(np.inf))
    blobs = {
        f"trees/t00_{t:03d}.bin": _encode_raw_tree(
            splits[t], feats[t], thr_adj[t], plens[t])
        for t in range(ntrees)
    }
    return _zip_write(path, lines, {}, blobs)


def _write_word2vec_mojo(model, path: str) -> str:
    """Word2Vec in the reference layout (Word2VecMojoWriter): vec_size /
    vocab_size kv, a ``vocabulary`` text file (one escaped word per
    line), and a ``vectors`` blob of BIG-endian float32s — Java
    ByteBuffer's default order, unlike the little-endian tree bytes."""
    vecs = np.asarray(model.vectors, np.float32)
    V, D = vecs.shape
    kv = [
        ("algorithm", "Word2Vec"),
        ("algo", "word2vec"),
        ("category", "WordEmbedding"),
        ("uuid", str(_uuid.uuid4())),
        ("supervised", "false"),
        ("n_features", 0),
        ("n_classes", 1),
        ("n_columns", 0),
        ("n_domains", 0),
        ("balance_classes", "false"),
        ("default_threshold", 0.5),
        ("prior_class_distrib", "null"),
        ("model_class_distrib", "null"),
        ("mojo_version", "1.00"),
        ("h2o_version", "h2o3-tpu"),
        ("vec_size", D),
        ("vocab_size", V),
    ]
    lines = ["[info]"]
    lines += [f"{k} = {v}" for k, v in kv]
    lines += ["", "[columns]", "", "[domains]"]
    vocab_text = "\n".join(
        _escape_vocab_word(w) for w in model.words
    ) + "\n"
    blobs = {"vectors": vecs.astype(">f4").tobytes()}
    return _zip_write(path, lines, {"vocabulary": vocab_text}, blobs)


def _escape_vocab_word(w: str) -> str:
    """One word per line: every character splitlines() treats as a line
    boundary must be escaped, or the vocab/vector zip misaligns."""
    out = []
    for ch in w:
        if ch == "\\":
            out.append("\\\\")
        elif ch == "\n":
            out.append("\\n")
        elif ch == "\r":
            out.append("\\r")
        elif ch in "\v\f\x1c\x1d\x1e\x85\u2028\u2029":
            out.append(f"\\u{ord(ch):04x}")
        else:
            out.append(ch)
    return "".join(out)


def _unescape_vocab_word(s: str) -> str:
    """Single left-to-right scan — sequential str.replace calls corrupt
    words containing a literal backslash followed by 'n'."""
    out = []
    i = 0
    while i < len(s):
        ch = s[i]
        if ch == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            if nxt == "\\":
                out.append("\\")
                i += 2
                continue
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt == "r":
                out.append("\r")
                i += 2
                continue
            if nxt == "u" and i + 6 <= len(s):
                out.append(chr(int(s[i + 2:i + 6], 16)))
                i += 6
                continue
        out.append(ch)
        i += 1
    return "".join(out)


def _write_dl_mojo(model, path: str) -> str:
    """DeepLearning in the reference layout (DeepLearningMojoWriter /
    DeeplearningMojoModel.score0): neural_network_sizes + per-layer
    weight/bias kv arrays, weights flattened ROW-major [out, in]
    (gemv_row_optimized order; this framework stores [in, out]).

    Numeric predictors only (the reference scorer's cats-first
    setInput layout differs from this framework's interleaved design
    matrix) and non-autoencoder. Hidden dropout ratios are written as 0:
    training uses inverted dropout, so inference-time scaling is already
    baked into the weights. The maxout family degrades to Rectifier in
    this build, so it exports as Rectifier — the artifact reproduces
    this model's predictions, not the reference's maxout."""
    info = model.data_info
    if info.cat_domains:
        raise ValueError("reference-format DeepLearning MOJO covers "
                         "numeric predictors only")
    if model.params.autoencoder:
        raise ValueError("reference-format DeepLearning MOJO does not "
                         "cover autoencoder models")
    nums = list(info.predictor_names)
    F = len(nums)
    net = [(np.asarray(W, np.float64), np.asarray(b, np.float64))
           for W, b in model.net_params]
    units = [F] + [w.shape[1] for w, _ in net]
    nclasses = model.nclasses
    is_clf = model.is_classifier
    act = {"rectifier": "Rectifier", "relu": "Rectifier", "tanh": "Tanh",
           "maxout": "Rectifier"}[model.params.activation]
    if is_clf:
        family = "bernoulli" if nclasses == 2 else "multinomial"
        category = "Binomial" if nclasses == 2 else "Multinomial"
    else:
        family = "gaussian"
        category = "Regression"

    columns = nums + [model.params.response_column]
    rdom = info.response_domain
    kv = [
        ("algorithm", "Deep Learning"),
        ("algo", "deeplearning"),
        ("category", category),
        ("uuid", str(_uuid.uuid4())),
        ("supervised", "true"),
        ("n_features", F),
        ("n_classes", nclasses if nclasses > 1 else 1),
        ("n_columns", len(columns)),
        ("n_domains", 1 if rdom else 0),
        ("balance_classes", "false"),
        ("default_threshold", 0.5),
        ("prior_class_distrib", "null"),
        ("model_class_distrib", "null"),
        ("mojo_version", "1.10"),
        ("h2o_version", "h2o3-tpu"),
        ("mini_batch_size", 1),
        ("nums", F),
        ("cats", 0),
        ("cat_offsets", "[0]"),
        ("use_all_factor_levels",
         "true" if info.use_all_factor_levels else "false"),
        ("activation", act),
        ("distribution", family),
        ("mean_imputation", "true"),
        ("norm_resp_mul", "null"),
        ("norm_resp_sub", "null"),
        ("neural_network_sizes", "[" + ", ".join(map(str, units)) + "]"),
        ("hidden_dropout_ratios", _jarr([0.0] * len(net))),
        ("_genmodel_encoding", "AUTO"),
    ]
    means = np.asarray([info.num_means[n] for n in nums], np.float64)
    if getattr(info, "standardize", False):
        kv.append(("norm_sub", _jarr(means)))
        kv.append(("norm_mul",
                   _jarr(1.0 / max(info.num_sds[n], 1e-300)
                        for n in nums)))
    else:
        # the scorer's NaN handling is ZERO-after-normalization; this
        # model mean-imputes. Writing norm_sub=means/norm_mul=1 makes the
        # scorer's NaN -> 0 equal mean-imputation, and the mean shift on
        # non-NaN values is folded into the first-layer bias exactly:
        # (x - m)·W0 + (b0 + m·W0) == x·W0 + b0
        kv.append(("norm_sub", _jarr(means)))
        kv.append(("norm_mul", _jarr(np.ones(F))))
        W0, b0 = net[0]
        net[0] = (W0, b0 + means @ W0)
    for i, (W, b) in enumerate(net):
        kv.append((f"weight_layer{i}", _jarr(W.T.reshape(-1))))
        kv.append((f"bias_layer{i}", _jarr(b)))
    lines = ["[info]"]
    lines += [f"{k} = {v}" for k, v in kv]
    lines += ["", "[columns]"] + columns + ["", "[domains]"]
    dom_texts: Dict[str, str] = {}
    if rdom:
        lines.append(f"{len(columns) - 1}: {len(rdom)} d000.txt")
        dom_texts["domains/d000.txt"] = "\n".join(rdom) + "\n"
    return _zip_write(path, lines, dom_texts, {})


def _write_pca_mojo(model, path: str) -> str:
    """PCA in the reference layout (PCAMojoWriter / PCAMojoModel.score0):
    eigenvectors_raw blob of big-endian doubles [ncoefs, k] in CATS-FIRST
    coefficient order, normSub/normMul over the num block, catOffsets,
    and a permutation mapping the cats-first positions back to this
    model's column order. NA semantics differ from in-framework predict
    (the reference skips NA cats and propagates NaN nums; this framework
    mean/mode-imputes), so parity holds on NA-free rows."""
    info = model.data_info
    k = model.eigenvectors.shape[1]
    # our expanded design matrix is interleaved in predictor order;
    # reorder its rows into the cats-first layout the scorer expects
    order, cat_offsets, cats, nums = _coefs_cats_first(info)
    ev = np.asarray(model.eigenvectors, np.float64)[order]  # [ncoefs, k]

    # permutation: raw-row position (predictor order) of each cats-first
    # column index
    pos = {name: i for i, name in enumerate(info.predictor_names)}
    permutation = [pos[c] for c in cats] + [pos[n] for n in nums]

    # normSub/normMul carry the training-time transform. This model's
    # demean/descale statistics cover the EXPANDED matrix (one-hot cat
    # columns included), but the reference scorer only normalizes the
    # num block — those modes are not representable in the format
    transform = getattr(model.params, "transform",
                        "standardize" if getattr(info, "standardize", False)
                        else "none")
    if transform in ("demean", "descale"):
        raise ValueError(
            "reference-format PCA MOJO covers transform='standardize' or "
            "'none'; demean/descale statistics span the expanded one-hot "
            "columns, which PCAMojoModel's num-only normalization cannot "
            "express")
    if transform == "standardize":
        sub = [info.num_means[n] for n in nums]
        mul = [1.0 / max(info.num_sds[n], 1e-300) for n in nums]
    else:
        sub = [0.0] * len(nums)
        mul = [1.0] * len(nums)

    columns = cats + nums
    dom_texts: Dict[str, str] = {}
    dom_lines = []
    for ci, c in enumerate(cats):
        dom = info.cat_domains[c]
        dom_lines.append(f"{ci}: {len(dom)} d{ci:03d}.txt")
        dom_texts[f"domains/d{ci:03d}.txt"] = "\n".join(dom) + "\n"
    kv = [
        ("algorithm", "Principal Components Analysis"),
        ("algo", "pca"),
        ("category", "DimReduction"),
        ("uuid", str(_uuid.uuid4())),
        ("supervised", "false"),
        ("n_features", len(columns)),
        ("n_classes", 1),
        ("n_columns", len(columns)),
        ("n_domains", len(dom_lines)),
        ("balance_classes", "false"),
        ("default_threshold", 0.5),
        ("prior_class_distrib", "null"),
        ("model_class_distrib", "null"),
        ("mojo_version", "1.00"),
        ("h2o_version", "h2o3-tpu"),
        ("pcaMethod", "GramSVD"),
        ("pca_impl", "MTJ_EVD_SYMMMATRIX"),
        ("k", k),
        ("use_all_factor_levels",
         "true" if info.use_all_factor_levels else "false"),
        ("permutation", "[" + ", ".join(map(str, permutation)) + "]"),
        ("ncats", len(cats)),
        ("nnums", len(nums)),
        ("normSub", _jarr(sub)),
        ("normMul", _jarr(mul)),
        ("catOffsets", "[" + ", ".join(map(str, cat_offsets)) + "]"),
        ("eigenvector_size", ev.shape[0]),
    ]
    lines = ["[info]"]
    lines += [f"{k_} = {v}" for k_, v in kv]
    lines += ["", "[columns]"] + columns + ["", "[domains]"] + dom_lines
    blobs = {"eigenvectors_raw": ev.astype(">f8").tobytes()}
    return _zip_write(path, lines, dom_texts, blobs)


def _coefs_cats_first(info):
    """(order, cat_offsets, cats, nums): indices reordering this
    framework's interleaved expanded coefficient space into the
    reference's cats-first layout."""
    cats = [n for n in info.predictor_names if n in info.cat_domains]
    nums = [n for n in info.predictor_names if n not in info.cat_domains]
    skip = 0 if info.use_all_factor_levels else 1
    offsets = {}
    off = 0
    for name in info.predictor_names:
        offsets[name] = off
        off += (len(info.cat_domains[name]) - skip
                if name in info.cat_domains else 1)
    order: List[int] = []
    cat_offsets = [0]
    for c in cats:
        width = len(info.cat_domains[c]) - skip
        order.extend(range(offsets[c], offsets[c] + width))
        cat_offsets.append(cat_offsets[-1] + width)
    for n in nums:
        order.append(offsets[n])
    return order, cat_offsets, cats, nums


def _write_coxph_mojo(model, path: str) -> str:
    """CoxPH in the reference layout (CoxPHMojoWriter /
    CoxPHMojoModel.score0): cats-first coef kv, x_mean_cat/x_mean_num
    rectangular blobs (big-endian doubles + _size1/_size2 kv) whose
    coef-weighted sum forms lpBase, so the scored linear predictor is
    coef·(x − x̄) exactly like this framework's predict. No strata
    (strata_count = 0; the reference scorer then always uses row 0)."""
    info = model.data_info
    order, cat_offsets, cats, nums = _coefs_cats_first(info)
    beta = np.asarray(model.beta, np.float64)[order]
    means = np.asarray(model.feature_means, np.float64).reshape(-1)[order]
    ncatc = cat_offsets[-1]
    columns = cats + nums
    dom_texts: Dict[str, str] = {}
    dom_lines = []
    for ci, c in enumerate(cats):
        dom = info.cat_domains[c]
        dom_lines.append(f"{ci}: {len(dom)} d{ci:03d}.txt")
        dom_texts[f"domains/d{ci:03d}.txt"] = "\n".join(dom) + "\n"
    kv = [
        ("algorithm", "CoxPH"),
        ("algo", "coxph"),
        ("category", "CoxPH"),
        ("uuid", str(_uuid.uuid4())),
        ("supervised", "true"),
        ("n_features", len(columns)),
        ("n_classes", 1),
        ("n_columns", len(columns)),
        ("n_domains", len(dom_lines)),
        ("balance_classes", "false"),
        ("default_threshold", 0.5),
        ("prior_class_distrib", "null"),
        ("model_class_distrib", "null"),
        ("mojo_version", "1.00"),
        ("h2o_version", "h2o3-tpu"),
        ("coef", _jarr(beta)),
        ("cats", len(cats)),
        ("cat_offsets", "[" + ", ".join(map(str, cat_offsets)) + "]"),
        ("use_all_factor_levels",
         "true" if info.use_all_factor_levels else "false"),
        ("x_mean_cat_size1", 1),
        ("x_mean_cat_size2", ncatc),
        ("x_mean_num_size1", 1),
        ("x_mean_num_size2", len(nums)),
        ("strata_count", 0),
    ]
    lines = ["[info]"]
    lines += [f"{k} = {v}" for k, v in kv]
    lines += ["", "[columns]"] + columns + ["", "[domains]"] + dom_lines
    blobs = {
        "x_mean_cat": means[:ncatc].astype(">f8").tobytes(),
        "x_mean_num": means[ncatc:].astype(">f8").tobytes(),
    }
    return _zip_write(path, lines, dom_texts, blobs)


def _write_te_mojo(model, path: str) -> str:
    """TargetEncoder in the reference layout (TargetEncoderMojoWriter):
    an ``encoding_map.ini`` of ``[column]`` sections with
    ``code = numerator denominator`` lines, NA-presence and column
    mapping files under ``feature_engineering/target_encoding/``, and
    blending kv. This framework's NA handling maps unseen/missing
    levels to the prior, which is the reference scorer's path when the
    column's NA-presence flag is 0 — so every flag is written 0."""
    p = model.params
    cols = list(model.encodings)
    columns = cols + [p.response_column]
    dom_texts: Dict[str, str] = {}
    dom_lines = []
    for ci, c in enumerate(cols):
        dom = model.encodings[c][0]
        dom_lines.append(f"{ci}: {len(dom)} d{ci:03d}.txt")
        dom_texts[f"domains/d{ci:03d}.txt"] = "\n".join(dom) + "\n"
    rdom = model.data_info.response_domain
    if rdom:
        dom_lines.append(
            f"{len(columns) - 1}: {len(rdom)} d{len(cols):03d}.txt")
        dom_texts[f"domains/d{len(cols):03d}.txt"] = "\n".join(rdom) + "\n"

    kv = [
        ("algorithm", "TargetEncoder"),
        ("algo", "targetencoder"),
        ("category", "TargetEncoder"),
        ("uuid", str(_uuid.uuid4())),
        ("supervised", "true"),
        ("n_features", len(cols)),
        ("n_classes", 2 if rdom else 1),
        ("n_columns", len(columns)),
        ("n_domains", len(dom_lines)),
        ("balance_classes", "false"),
        ("default_threshold", 0.5),
        ("prior_class_distrib", "null"),
        ("model_class_distrib", "null"),
        ("mojo_version", "1.00"),
        ("h2o_version", "h2o3-tpu"),
        ("keep_original_categorical_columns",
         "true" if p.keep_original_categorical_columns else "false"),
        ("with_blending", "true" if p.blending else "false"),
    ]
    if p.blending:
        kv.append(("inflection_point", p.inflection_point))
        kv.append(("smoothing", p.smoothing))
    kv.append(("non_predictors", p.response_column))

    base = "feature_engineering/target_encoding"
    enc_lines = []
    for c in cols:
        dom, num, den = model.encodings[c]
        enc_lines.append(f"[{c}]")
        for code in range(len(dom)):
            enc_lines.append(
                f"{code} = {float(num[code])!r} {float(den[code])!r}")
        # the reference scorer derives each column's prior as
        # Σnum/Σden over its map; rows whose code was NA are absent from
        # the per-level sums, so without correction the map prior would
        # drift from this model's global prior_mean. One synthetic
        # category (an unused code — levels only go to len(dom)-1, and
        # the NA-presence flag is 0 so it is never looked up) restores
        # Σnum/Σden == prior_mean exactly.
        resid_den = 1.0
        resid_num = model.prior_mean * (float(den.sum()) + resid_den) \
            - float(num.sum())
        enc_lines.append(f"{len(dom)} = {resid_num!r} {resid_den!r}")
    dom_texts[f"{base}/encoding_map.ini"] = "\n".join(enc_lines) + "\n"
    dom_texts[f"{base}/te_column_name_to_missing_values_presence.ini"] = (
        "\n".join(f"{c} = 0" for c in cols) + "\n")
    dom_texts[f"{base}/input_encoding_columns_map.ini"] = "\n".join(
        f"[from]\n{c}\n[to]\n{c}" for c in cols) + "\n"
    dom_texts[f"{base}/input_output_columns_map.ini"] = "\n".join(
        f"[from]\n{c}\n[to]\n{c}_te" for c in cols) + "\n"

    lines = ["[info]"]
    lines += [f"{k} = {v}" for k, v in kv]
    lines += ["", "[columns]"] + columns + ["", "[domains]"] + dom_lines
    return _zip_write(path, lines, dom_texts, {})


def _write_ensemble_mojo(model, path: str) -> str:
    """StackedEnsemble in the reference layout (StackedEnsembleMojoWriter
    / MultiModelMojoWriter): the metalearner and every base model are
    full MOJOs embedded under ``models/<algo>/<key>/``, with parent kv
    naming the metalearner and ``base_model<i>`` keys. Every sub-model
    must itself be reference-exportable."""
    import tempfile

    sub_entries: Dict[str, bytes] = {}

    def embed(sub) -> str:
        key = str(sub.key)
        with tempfile.NamedTemporaryFile(suffix=".zip") as tf:
            write_mojo(sub, tf.name)
            with zipfile.ZipFile(tf.name) as sz:
                for nm in sz.namelist():
                    sub_entries[f"models/{sub.algo_name}/{key}/{nm}"] = \
                        sz.read(nm)
        return key

    meta_key = embed(model.metalearner)
    base_keys = [embed(bm) for bm in model.base_models]

    info = model.data_info
    cats = [n for n in info.predictor_names if n in info.cat_domains]
    nums = [n for n in info.predictor_names if n not in info.cat_domains]
    columns = cats + nums + [info.response_name]
    dom_texts: Dict[str, str] = {}
    dom_lines = []
    for ci, c in enumerate(cats):
        dom = info.cat_domains[c]
        dom_lines.append(f"{ci}: {len(dom)} d{ci:03d}.txt")
        dom_texts[f"domains/d{ci:03d}.txt"] = "\n".join(dom) + "\n"
    rdom = info.response_domain
    if rdom:
        dom_lines.append(
            f"{len(columns) - 1}: {len(rdom)} d{len(cats):03d}.txt")
        dom_texts[f"domains/d{len(cats):03d}.txt"] = "\n".join(rdom) + "\n"
    nclasses = model.nclasses
    category = ("Binomial" if nclasses == 2
                else "Multinomial" if nclasses > 2 else "Regression")
    kv = [
        ("algorithm", "StackedEnsemble"),
        ("algo", "stackedensemble"),
        ("category", category),
        ("uuid", str(_uuid.uuid4())),
        ("supervised", "true"),
        ("n_features", len(cats) + len(nums)),
        ("n_classes", nclasses if nclasses > 1 else 1),
        ("n_columns", len(columns)),
        ("n_domains", len(dom_lines)),
        ("balance_classes", "false"),
        ("default_threshold", 0.5),
        ("prior_class_distrib", "null"),
        ("model_class_distrib", "null"),
        ("mojo_version", "1.01"),
        ("h2o_version", "h2o3-tpu"),
        ("submodel_count", 1 + len(base_keys)),
        ("base_models_num", len(base_keys)),
        ("metalearner", meta_key),
        ("metalearner_transform", "NONE"),
    ]
    for i, key in enumerate(base_keys):
        kv.append((f"base_model{i}", key))
    lines = ["[info]"]
    lines += [f"{k} = {v}" for k, v in kv]
    lines += ["", "[columns]"] + columns + ["", "[domains]"] + dom_lines
    return _zip_write(path, lines, dom_texts, sub_entries)


def _model_feature_schema(model) -> List[Tuple[str, Optional[List[str]]]]:
    """(name, domain) of a model's feature columns in MOJO order
    (cats first, then nums — the DataInfo permutation every writer
    here uses)."""
    info = model.data_info
    cats = [n for n in info.predictor_names if n in info.cat_domains]
    nums = [n for n in info.predictor_names if n not in info.cat_domains]
    return ([(c, list(info.cat_domains[c])) for c in cats]
            + [(n, None) for n in nums])


def write_pipeline_mojo(models: Dict[str, Any],
                        input_mapping: Dict[str, str],
                        main_alias: str, path: str) -> str:
    """Compose reference-exportable models into ONE pipeline MOJO in the
    reference layout (``hex/genmodel/MojoPipelineWriter.java``): every
    model embeds as a full MOJO under ``models/<alias>/`` with
    ``submodel_key_i``/``submodel_dir_i`` kvs; ``input_mapping`` maps a
    generated column name consumed by the main model to
    ``"<alias>:<prediction index>"`` of the sub-model producing it; the
    pipeline's input schema is derived exactly like
    ``deriveInputSchema`` (union of sub-model features + the main
    model's non-generated columns, response included)."""
    import tempfile

    if main_alias not in models:
        raise ValueError(f"Main model is missing. There is no model with "
                         f"alias '{main_alias}'.")
    main = models[main_alias]

    sub_entries: Dict[str, bytes] = {}
    for alias, m in models.items():
        with tempfile.NamedTemporaryFile(suffix=".zip") as tf:
            write_mojo(m, tf.name)
            with zipfile.ZipFile(tf.name) as sz:
                for nm in sz.namelist():
                    sub_entries[f"models/{alias}/{nm}"] = sz.read(nm)

    # deriveInputSchema: sub-model features first (domain conflicts are
    # an error), then the main model's columns not generated by a sub
    schema: List[Tuple[str, Optional[List[str]]]] = []
    seen: Dict[str, Optional[List[str]]] = {}
    for alias, m in models.items():
        if alias == main_alias:
            continue
        for name, dom in _model_feature_schema(m):
            if name in seen:
                if seen[name] != dom:
                    raise ValueError(
                        f"Domains of column '{name}' differ.")
                continue
            seen[name] = dom
            schema.append((name, dom))
    minfo = main.data_info
    main_cols = (_model_feature_schema(main)
                 + [(minfo.response_name,
                     list(minfo.response_domain)
                     if minfo.response_domain else None)])
    for name, dom in main_cols:
        if name in input_mapping or name in seen:
            continue
        seen[name] = dom
        schema.append((name, dom))

    columns = [n for n, _ in schema]
    dom_texts: Dict[str, str] = {}
    dom_lines = []
    di = 0
    for ci, (_n, dom) in enumerate(schema):
        if dom is None:
            continue
        dom_lines.append(f"{ci}: {len(dom)} d{di:03d}.txt")
        dom_texts[f"domains/d{di:03d}.txt"] = "\n".join(dom) + "\n"
        di += 1

    nclasses = main.nclasses
    category = ("Binomial" if nclasses == 2
                else "Multinomial" if nclasses > 2 else "Regression")
    kv: List[Tuple[str, Any]] = [
        ("algorithm", "MOJO Pipeline"),
        ("algo", "pipeline"),
        ("category", category),
        ("uuid", str(_uuid.uuid4())),
        ("supervised", "true"),
        ("n_features", len(columns) - 1),
        ("n_classes", nclasses if nclasses > 1 else 1),
        ("n_columns", len(columns)),
        ("n_domains", len(dom_lines)),
        ("balance_classes", "false"),
        ("default_threshold", 0.5),
        ("prior_class_distrib", "null"),
        ("model_class_distrib", "null"),
        ("mojo_version", "1.00"),
        ("h2o_version", "h2o3-tpu"),
        ("submodel_count", len(models)),
    ]
    for i, alias in enumerate(models):
        kv.append((f"submodel_key_{i}", alias))
        kv.append((f"submodel_dir_{i}", f"models/{alias}/"))
    kv.append(("generated_column_count", len(input_mapping)))
    for i, (gname, spec) in enumerate(input_mapping.items()):
        alias, _, idx = spec.partition(":")
        kv.append((f"generated_column_name_{i}", gname))
        kv.append((f"generated_column_model_{i}", alias))
        kv.append((f"generated_column_index_{i}", int(idx)))
    kv.append(("main_model", main_alias))

    lines = ["[info]"]
    lines += [f"{k} = {v}" for k, v in kv]
    lines += ["", "[columns]"] + columns + ["", "[domains]"] + dom_lines
    return _zip_write(path, lines, dom_texts, sub_entries)


def write_mojo(model, path: str) -> str:
    """Serialize a GBM, DRF, GLM, GAM (CR smoothers), KMeans,
    IsolationForest, Word2Vec, DeepLearning, TargetEncoder, PCA, CoxPH,
    StackedEnsemble or pipeline model into the reference MOJO layout."""
    from h2o3_tpu.models.tree.common import tree_feature_names

    algo = model.algo_name
    if getattr(model.params, "offset_column", None):
        # the format has no offset term; exporting would silently drop it
        raise ValueError("reference-format MOJO export does not support "
                         "offset_column models")
    writers = {
        "glm": _write_glm_mojo,
        "gam": _write_gam_mojo,
        "kmeans": _write_kmeans_mojo,
        "isolationforest": _write_isofor_mojo,
        "word2vec": _write_word2vec_mojo,
        "deeplearning": _write_dl_mojo,
        "targetencoder": _write_te_mojo,
        "pca": _write_pca_mojo,
        "coxph": _write_coxph_mojo,
        "stackedensemble": _write_ensemble_mojo,
    }
    if algo in writers:
        return writers[algo](model, path)
    if algo not in ("gbm", "drf"):
        covered = ", ".join(sorted(["gbm", "drf", *writers]))
        raise ValueError(
            f"reference-format MOJO export currently covers {covered}; "
            "use the native .mojo (models/mojo_export.py) or POJO "
            f"codegen for {algo}")
    b = model.booster
    names = tree_feature_names(model.data_info, model.tree_encoding)
    dom = model.data_info.response_domain
    nclasses = model.nclasses
    dist = model.distribution
    K = len(b.trees_per_class)
    ntrees = b.trees_per_class[0].ntrees
    supervised = True
    columns = list(names) + [model.params.response_column]
    cat_domains: Dict[int, List[str]] = {}
    # label-encoded tree features are numeric to the MOJO; only the
    # response carries a domain
    if dom:
        cat_domains[len(columns) - 1] = list(dom)

    if nclasses == 2:
        init_f = float(b.init_margin[0])
        category = "Binomial"
    elif nclasses > 2:
        init_f = 0.0  # per-class inits are baked into tree 0's leaves
        category = "Multinomial"
    else:
        init_f = float(b.init_margin[0])
        category = "Regression"
    if algo == "drf":
        init_f = 0.0  # DRF trains from zero margin; DrfMojoModel has no init

    info = [
        ("algorithm", "Gradient Boosting Machine" if algo == "gbm"
         else "Distributed Random Forest"),
        ("algo", algo),
        ("category", category),
        ("uuid", str(_uuid.uuid4())),
        ("supervised", "true" if supervised else "false"),
        ("n_features", len(names)),
        ("n_classes", nclasses if nclasses > 1 else 1),
        ("n_columns", len(columns)),
        ("n_domains", len(cat_domains)),
        ("balance_classes", "false"),
        ("default_threshold", 0.5),
        ("prior_class_distrib", "null"),
        ("model_class_distrib", "null"),
        ("offset_column", "null"),
        ("mojo_version", "1.40"),
        ("h2o_version", "h2o3-tpu"),
        ("n_trees", ntrees),
        ("n_trees_per_class", K),
        ("distribution", dist),
        ("link_function", _LINK_BY_DIST.get(dist, "identity")),
        ("init_f", _jdouble(init_f)),
    ]
    if algo == "drf":
        info.append(("binomial_double_trees", "false"))
    # mojo_version >= 1.40 readers call readkv("_genmodel_encoding")
    # .toString() unconditionally (SharedTreeMojoReader.java:25-28)
    enc = getattr(model, "tree_encoding", "label_encoder")
    info.append(("_genmodel_encoding",
                 "OneHotExplicit" if enc == "one_hot_explicit"
                 else "LabelEncoder"))
    lines = ["[info]"]
    lines += [f"{k} = {v}" for k, v in info]
    lines.append("")
    lines.append("[columns]")
    lines += columns
    lines.append("")
    lines.append("[domains]")
    for ci, (col, d) in enumerate(sorted(cat_domains.items())):
        # reference parseModelDomains expects '<col>: <n_elements> <file>'
        # (ModelMojoReader.java splits on space and parses the count)
        lines.append(f"{col}: {len(d)} d{ci:03d}.txt")

    dom_texts = {
        f"domains/d{ci:03d}.txt": "\n".join(d) + "\n"
        for ci, (col, d) in enumerate(sorted(cat_domains.items()))
    }
    blobs: Dict[str, bytes] = {}
    for c, trees in enumerate(b.trees_per_class):
        for t in range(trees.ntrees):
            shift = (float(b.init_margin[c])
                     if (algo == "gbm" and nclasses > 2 and t == 0)
                     else 0.0)
            # DrfMojoModel's binomial preds[1] is the CLASS-0
            # probability (preds[2] = 1 - preds[1]); our DRF trees
            # predict p1 per tree, so leaves flip to 1 - p
            flip = (algo == "drf" and nclasses == 2)
            blobs[f"trees/t{c:02d}_{t:03d}.bin"] = _encode_tree(
                trees, t, leaf_shift=shift, leaf_flip=flip)
    return _zip_write(path, lines, dom_texts, blobs)


# ---------------------------------------------------------------------------
# independent reader (SharedTreeMojoModel.scoreTree byte-walk)


class RefMojo:
    def __init__(self) -> None:
        self.info: Dict[str, str] = {}
        self.columns: List[str] = []
        self.domains: Dict[int, List[str]] = {}
        self.trees: List[List[bytes]] = []  # [class][tree]

    @property
    def nclasses(self) -> int:
        return int(self.info.get("n_classes", 1))

    def score_tree(self, tree: bytes, row: np.ndarray) -> float:
        """Exact scoreTree walk (SharedTreeMojoModel.java:130-215),
        float-split subset."""
        pos = 0
        while True:
            node_type = tree[pos]; pos += 1
            col_id = struct.unpack_from("<H", tree, pos)[0]; pos += 2
            if col_id == 65535:
                return struct.unpack_from("<f", tree, pos)[0]
            na_dir = tree[pos]; pos += 1
            na_vs_rest = na_dir == 1
            leftward = na_dir in (2, 4)
            lmask = node_type & 51
            equal = node_type & 12
            if equal != 0:
                raise ValueError(
                    "bitset categorical splits are not supported by this "
                    "reader (label-encoded models use float splits)")
            split_val = None
            if not na_vs_rest:
                split_val = struct.unpack_from("<f", tree, pos)[0]; pos += 4
            d = row[col_id]
            if np.isnan(d):
                go_right = not leftward
            elif na_vs_rest:
                go_right = False
            else:
                go_right = d >= split_val
            if go_right:
                if lmask <= 3:
                    n = int.from_bytes(tree[pos:pos + lmask + 1], "little")
                    pos += lmask + 1
                    pos += n
                elif lmask == 48:
                    pos += 4
                else:
                    raise ValueError(f"illegal lmask {lmask}")
                lmask = (node_type & 0xC0) >> 2
            else:
                if lmask <= 3:
                    pos += lmask + 1
            if lmask & 16:
                return struct.unpack_from("<f", tree, pos)[0]

    def _glm_arrays(self):
        """Parse the GLM kv arrays ONCE and cache (score0 is per-row)."""
        cached = getattr(self, "_glm_cache", None)
        if cached is not None:
            return cached

        def arr(key, cast=float):
            return _parse_jarr(self.info[key], cast)

        cached = {
            "cats": int(self.info["cats"]),
            "nums": int(self.info["nums"]),
            "cat_offsets": arr("cat_offsets", int),
            "beta": np.asarray(arr("beta"), np.float64),
            "cat_modes": (arr("cat_modes", int)
                          if "cat_modes" in self.info else []),
            "num_means": (arr("num_means")
                          if "num_means" in self.info else []),
        }
        self._glm_cache = cached
        return cached

    def _glm_score0(self, row: np.ndarray) -> np.ndarray:
        """GlmMojoModelBase.score0 + GlmMojoModel.glmScore0: cats-first
        row, mean imputation, catOffsets beta lookup, link inverse."""
        g = self._glm_arrays()
        cats, nums = g["cats"], g["nums"]
        cat_offsets, beta = g["cat_offsets"], g["beta"]
        data = np.asarray(row, np.float64).copy()
        if self.info.get("mean_imputation") == "true":
            for i in range(cats):
                if np.isnan(data[i]):
                    data[i] = g["cat_modes"][i]
            for i in range(nums):
                if np.isnan(data[cats + i]):
                    data[cats + i] = g["num_means"][i]
        use_all = self.info.get("use_all_factor_levels") == "true"

        def class_eta(cbeta):
            eta = 0.0
            for i in range(cats):
                # Java's (int) NaN is 0 — an unimputed NaN categorical
                # maps to level 0 exactly like the reference runtime
                iv = data[i]
                ival = (0 if np.isnan(iv) else int(iv)) - (
                    0 if use_all else 1)
                if ival < 0:
                    continue
                ival += cat_offsets[i]
                if ival < cat_offsets[i + 1]:
                    eta += cbeta[ival]
            noff = cat_offsets[cats] - cats
            for i in range(cats, len(cbeta) - 1 - noff):
                eta += cbeta[noff + i] * data[i]
            return eta + cbeta[-1]

        if self.info.get("family") == "multinomial":
            # GlmMultinomialMojoModel.glmScore0 — including its quirk of
            # seeding the max with 0, not -inf
            C = self.nclasses
            P = len(beta) // C
            etas = np.array([class_eta(beta[c * P:(c + 1) * P])
                             for c in range(C)])
            max_row = max(0.0, float(etas.max()))
            e = np.exp(etas - max_row)
            return e / e.sum()

        eta = class_eta(beta)
        link = self.info.get("link", "identity")
        if link == "logit":
            mu = 1.0 / (1.0 + np.exp(-eta))
        elif link == "log":
            mu = np.exp(eta)
        elif link == "inverse":
            d = eta if abs(eta) >= 1e-10 else (
                1e-10 if eta + 1e-30 >= 0 else -1e-10)
            mu = 1.0 / d
        elif link == "tweedie":
            lp = float(self.info.get("tweedie_link_power", 0.0))
            mu = np.exp(eta) if lp == 0 else max(eta, 1e-10) ** (1.0 / lp)
        else:
            mu = eta
        if self.info.get("family") in ("binomial", "quasibinomial"):
            return np.array([1.0 - mu, mu])
        return np.array([mu])

    def _kmeans_arrays(self):
        """Parse the KMeans kv arrays ONCE and cache (score0 is per-row)."""
        cached = getattr(self, "_kmeans_cache", None)
        if cached is not None:
            return cached

        def arr(key):
            return np.asarray(_parse_jarr(self.info[key]), np.float64)

        cached = {
            "centers": np.stack([
                arr(f"center_{i}")
                for i in range(int(self.info["center_num"]))
            ]),
            "means": (arr("standardize_means")
                      if "standardize_means" in self.info else None),
            "mults": (arr("standardize_mults")
                      if "standardize_mults" in self.info else None),
        }
        self._kmeans_cache = cached
        return cached

    def _kmeans_score0(self, row: np.ndarray) -> np.ndarray:
        """KMeansMojoModel.score0: Kmeans_preprocessData (NaN -> mean,
        subtract-mean times mult) then KMeans_closest in standardized
        space (numeric columns only in this exporter).

        NaN imputation uses standardize_means whenever the writer
        recorded them — this framework's writer emits them even for
        standardize=False models so the artifact can reproduce
        in-framework predictions on NA rows (the reference runtime only
        imputes when standardize is on; a reference reader ignores the
        extra key)."""
        km = self._kmeans_arrays()
        data = np.asarray(row, np.float64).copy()
        if km["means"] is not None:
            nan = np.isnan(data)
            data[nan] = km["means"][nan]
        if self.info.get("standardize") == "true":
            data = (data - km["means"]) * km["mults"]
        d2 = ((km["centers"] - data[None, :]) ** 2).sum(axis=1)
        return np.array([float(np.argmin(d2))])

    def _dl_arrays(self):
        cached = getattr(self, "_dl_cache", None)
        if cached is not None:
            return cached

        def arr(key):
            return np.asarray(_parse_jarr(self.info[key]), np.float64)

        units = [int(u) for u in arr("neural_network_sizes")]
        layers = []
        for i in range(len(units) - 1):
            W = arr(f"weight_layer{i}").reshape(units[i + 1], units[i])
            b = arr(f"bias_layer{i}")
            layers.append((W, b))
        cached = {
            "units": units,
            "layers": layers,
            "norm_sub": arr("norm_sub") if "norm_sub" in self.info else None,
            "norm_mul": arr("norm_mul") if "norm_mul" in self.info else None,
        }
        self._dl_cache = cached
        return cached

    def _dl_score0(self, row: np.ndarray) -> np.ndarray:
        """DeeplearningMojoModel.score0, numeric-only subset: setInput
        ((d - norm_sub) * norm_mul, NaN -> 0 after normalization), then
        fprop with the stored activation per hidden layer and
        Softmax/Linear on the output layer."""
        dl = self._dl_arrays()
        x = np.asarray(row, np.float64).copy()
        if dl["norm_sub"] is not None:
            x = (x - dl["norm_sub"]) * dl["norm_mul"]
        x[np.isnan(x)] = 0.0  # replaceMissingWithZero (post-normalization)
        act = self.info.get("activation", "Rectifier")
        n_layers = len(dl["layers"])
        for i, (W, b) in enumerate(dl["layers"]):
            x = W @ x + b
            if i < n_layers - 1:
                if act == "Tanh":
                    x = np.tanh(x)
                else:  # Rectifier
                    x = np.maximum(x, 0.0)
        if self.info.get("category") in ("Binomial", "Multinomial"):
            z = x - x.max()
            e = np.exp(z)
            return e / e.sum()
        return np.array([x[0]])

    def _pca_arrays(self):
        """Parse the PCA kv arrays ONCE and cache (score0 is per-row)."""
        cached = getattr(self, "_pca_cache", None)
        if cached is not None:
            return cached
        cached = {
            "ncats": int(self.info["ncats"]),
            "nnums": int(self.info["nnums"]),
            "k": int(self.info["k"]),
            "perm": _parse_jarr(self.info["permutation"], int),
            "cat_offsets": _parse_jarr(self.info["catOffsets"], int),
            "sub": np.asarray(_parse_jarr(self.info["normSub"])),
            "mul": np.asarray(_parse_jarr(self.info["normMul"])),
        }
        self._pca_cache = cached
        return cached

    def _pca_score0(self, row: np.ndarray) -> np.ndarray:
        """PCAMojoModel.score0: per component, sum the one-hot cat
        eigenvector entries (NA cats skipped) plus normalized nums times
        the num-block entries."""
        p = self._pca_arrays()
        ncats, nnums, kcomp = p["ncats"], p["nnums"], p["k"]
        perm, cat_offsets = p["perm"], p["cat_offsets"]
        sub, mul = p["sub"], p["mul"]
        use_all = self.info.get("use_all_factor_levels") == "true"
        ev = self.eigenvectors
        num_start = cat_offsets[ncats]
        out = np.zeros(kcomp)
        for j in range(ncats):
            v = row[perm[j]]
            if np.isnan(v):
                continue  # missing categoricals are skipped
            last = cat_offsets[j + 1] - cat_offsets[j] - 1
            level = int(v) - (0 if use_all else 1)
            if level < 0 or level > last:
                continue  # unseen test level
            out += ev[cat_offsets[j] + level]
        for j in range(nnums):
            out += (row[perm[ncats + j]] - sub[j]) * mul[j] * \
                ev[num_start + j]
        return out

    def _ensemble_score0(self, row: np.ndarray) -> np.ndarray:
        """StackedEnsembleMojoModel.score0: score every base model on the
        (re-mapped) row, stack the level-one vector in base order
        (binomial p1 / regression pred / multinomial all classes), then
        score the metalearner on it."""
        nclasses = self.nclasses
        # parent row layout = parent columns minus the response; each
        # sub-model expects ITS column order — remap by name, computed
        # once (score0 is per-row)
        remaps = getattr(self, "_ensemble_remaps", None)
        if remaps is None:
            pos = {c: i for i, c in enumerate(self.columns[:-1])}
            remaps = [
                None if bm is None
                else np.asarray([pos[c] for c in bm.columns[:-1]], np.intp)
                for bm in self.base_models
            ]
            self._ensemble_remaps = remaps
        base_preds: List[float] = []
        for bm, idx in zip(self.base_models, remaps):
            if bm is None:
                continue
            sub_row = row[idx]
            out = bm.score0(sub_row)
            if nclasses > 2:
                base_preds.extend(out)
            elif nclasses == 2:
                base_preds.append(out[-1])  # p1 (preds[2] in the runtime)
            else:
                base_preds.append(out[0])
        if self.info.get("metalearner_transform") == "Logit":
            base_preds = [
                float(np.log(max(min(p, 1 - 1e-9), 1e-9)
                             / (1 - max(min(p, 1 - 1e-9), 1e-9))))
                for p in base_preds
            ]
        return self.metalearner.score0(np.asarray(base_preds, np.float64))

    def _coxph_score0(self, row: np.ndarray) -> np.ndarray:
        """CoxPHMojoModel.score0 (no strata): lp = forCategories +
        forOtherColumns − lpBase, with lpBase = x̄·coef from the
        x_mean_cat/x_mean_num blobs — i.e. coef·(x − x̄)."""
        cached = getattr(self, "_coxph_cache", None)
        if cached is None:
            coef = np.asarray(_parse_jarr(self.info["coef"]))
            cat_offsets = _parse_jarr(self.info["cat_offsets"], int)
            ncatc = cat_offsets[-1]
            means = np.concatenate([self.x_mean_cat, self.x_mean_num])
            cached = {
                "coef": coef,
                "cat_offsets": cat_offsets,
                "cats": int(self.info["cats"]),
                "lp_base": float(means @ coef),
                "use_all": self.info.get(
                    "use_all_factor_levels") == "true",
                "ncatc": ncatc,
            }
            self._coxph_cache = cached
        coef = cached["coef"]
        cat_offsets = cached["cat_offsets"]
        cats = cached["cats"]
        lp = 0.0
        for j in range(cats):
            v = row[j]
            if np.isnan(v):
                continue
            level = int(v) - (0 if cached["use_all"] else 1)
            if level < 0 or level >= cat_offsets[j + 1] - cat_offsets[j]:
                continue
            lp += coef[cat_offsets[j] + level]
        for j in range(len(coef) - cached["ncatc"]):
            lp += coef[cached["ncatc"] + j] * row[cats + j]
        return np.array([lp - cached["lp_base"]])

    def te_transform(self, levels: Dict[str, float]) -> Dict[str, float]:
        """TargetEncoderMojoModel.score0 semantics: per encoded column,
        numerator/denominator lookup by level code with optional blending
        against the column map's prior (Σnum/Σden); NaN/unseen levels
        take the prior (every NA-presence flag is written 0)."""
        blending = self.info.get("with_blending") == "true"
        k = float(self.info.get("inflection_point", 10.0))
        f = float(self.info.get("smoothing", 20.0))
        priors = getattr(self, "_te_priors", None)
        if priors is None:  # per-column Σnum/Σden, computed once
            priors = {
                col: (sum(v[0] for v in emap.values())
                      / max(sum(v[1] for v in emap.values()), 1e-300))
                for col, emap in self.te_encodings.items()
            }
            self._te_priors = priors
        bounds = getattr(self, "_te_bounds", None)
        if bounds is None:
            # valid level codes come from the column's DOMAIN, not the
            # map length: this writer appends one synthetic
            # prior-correction entry past the domain (never a real
            # level), while a foreign reference writer emits exactly the
            # domain — either way the domain bound is right
            bounds = {}
            for col in self.te_columns:
                try:
                    ci = self.columns.index(col)
                    bounds[col] = len(self.domains[ci])
                except (ValueError, KeyError):
                    bounds[col] = len(self.te_encodings[col]) - 1
            self._te_bounds = bounds
        out: Dict[str, float] = {}
        for col in self.te_columns:
            emap = self.te_encodings[col]
            prior = priors[col]
            cat = levels.get(col, float("nan"))
            # a level inside the domain can still be absent from a
            # foreign writer's map (unseen in training): prior fallback
            if cat is None or (isinstance(cat, float) and np.isnan(cat)) \
                    or not (0 <= int(cat) < bounds[col]) \
                    or int(cat) not in emap:
                out[f"{col}_te"] = prior
                continue
            num, den = emap[int(cat)]
            post = num / den if den else prior
            if blending:
                lam = 1.0 / (1.0 + np.exp((k - den) / max(f, 1e-12)))
                post = lam * post + (1.0 - lam) * prior
            out[f"{col}_te"] = post
        return out

    @property
    def nfeatures(self) -> int:
        return int(self.info.get("n_features", len(self.columns)))

    # -- GAM (GamMojoModel + GamUtilsCubicRegression, ported) --------------
    @staticmethod
    def _gam_locate_bin(x: float, knots: np.ndarray) -> int:
        """GamUtilsCubicRegression.locateBin — boundary values clamp to
        the first/last bin (the cubic then EXTRAPOLATES with raw x)."""
        if x <= knots[0]:
            return 0
        if x >= knots[-1]:
            return len(knots) - 2
        return int(np.searchsorted(knots, x, side="right") - 1)

    def _gam_expand_one(self, x: float, ci: int) -> np.ndarray:
        """expandOneGamCol: the K basis values of smoother ci at x."""
        knots = self.gam_knots[ci]
        binvd = self.gam_binvd[ci]
        K = len(knots)
        vals = np.zeros(K)
        if np.isnan(x):
            return np.full(K, np.nan)
        j = self._gam_locate_bin(x, knots)
        hj = knots[j + 1] - knots[j]
        tm, tp = knots[j + 1] - x, x - knots[j]
        cmj = (tm ** 3 / hj - tm * hj) / 6.0
        cpj = (tp ** 3 / hj - tp * hj) / 6.0
        if j == 0:
            vals[:] = binvd[0] * cpj
        elif j >= binvd.shape[0]:
            vals[:] = binvd[j - 1] * cmj
        else:
            vals[:] = binvd[j - 1] * cmj + binvd[j] * cpj
        vals[j] += tm / hj
        vals[j + 1] += tp / hj
        return vals

    def gam_score0(self, row: Dict[str, float]) -> np.ndarray:
        """GamMojoModel.gamScore0 over a {column: value} row (cats as
        level codes, gam predictors as raw values): gamify each smoother
        column, center through zTranspose, evaluate beta_center."""
        cats = int(self.info.get("cats", 0))
        cat_offsets = _parse_jarr(self.info.get("cat_offsets", "[0]"), int)
        use_all = self.info.get("use_all_factor_levels") == "true"
        beta = np.asarray(_parse_jarr(self.info["beta_center"]))
        feats = self.columns[:-1]
        eta = 0.0
        for i in range(cats):
            ival = int(row[feats[i]])
            if not use_all:
                ival -= 1
            if ival >= 0:
                ival += cat_offsets[i]
                if ival < cat_offsets[i + 1]:
                    eta += beta[ival]
        noff = cat_offsets[cats] - cats
        # plain numeric features come before the gamified block
        n_center = sum(len(k) - 1 for k in self.gam_knots)
        for i in range(cats, len(feats) - n_center):
            eta += beta[noff + i] * row[feats[i]]
        pos = noff + len(feats) - n_center
        for ci, col in enumerate(self.gam_columns):
            basis = self._gam_expand_one(float(row[col]), ci)
            centered = self.gam_zt[ci] @ basis
            for v in centered:
                eta += beta[pos] * v
                pos += 1
        eta += beta[-1]
        fam = self.info.get("family", "gaussian")
        link = self.info.get("link", "identity")
        if link == "logit":
            mu = 1.0 / (1.0 + np.exp(-eta))
        elif link == "log":
            mu = np.exp(eta)
        else:
            mu = eta
        if fam in ("binomial", "quasibinomial", "fractionalbinomial"):
            return np.array([1.0 - mu, mu])
        return np.array([mu])

    def _pipeline_score0(self, row: np.ndarray) -> np.ndarray:
        """MojoPipeline.score0: copy passthrough inputs into the main
        model's row layout, score every sub-model to fill the generated
        columns, then score the main model."""
        main = self.pipeline_models[self.pipeline_main]
        gen_names = {g[0] for g in self.pipeline_gen}
        main_feats = main.columns[:main.nfeatures]
        main_row = np.full(main.nfeatures, np.nan)
        for ti, name in enumerate(main_feats):
            if name not in gen_names:
                main_row[ti] = row[self.columns.index(name)]
        for alias, sub in self.pipeline_models.items():
            if alias == self.pipeline_main:
                continue
            sub_row = np.array([
                row[self.columns.index(nm)]
                for nm in sub.columns[:sub.nfeatures]
            ])
            preds = sub.score0(sub_row)
            for gname, galias, gidx in self.pipeline_gen:
                if galias == alias:
                    main_row[main_feats.index(gname)] = preds[gidx]
        return main.score0(main_row)

    def score0(self, row: np.ndarray) -> np.ndarray:
        """Gbm/Drf/Glm/KMeansMojoModel semantics over the decoded payload."""
        algo = self.info.get("algo", "gbm")
        if algo == "targetencoder":
            raise ValueError(
                "TargetEncoder MOJOs transform rows rather than score "
                "them — use te_transform({column: level_code, ...})")
        if algo == "glm":  # no trees to walk
            return self._glm_score0(row)
        if algo == "deeplearning":
            return self._dl_score0(row)
        if algo == "pca":
            return self._pca_score0(row)
        if algo == "coxph":
            return self._coxph_score0(row)
        if algo == "stackedensemble":
            return self._ensemble_score0(row)
        if algo == "pipeline":
            return self._pipeline_score0(row)
        if algo == "kmeans":
            return self._kmeans_score0(row)
        if algo == "isolation_forest":
            # IsolationForestMojoModel.unifyPreds: sum of per-tree path
            # lengths -> normalized score + mean path length
            total = float(np.sum([
                self.score_tree(t, row) for t in self.trees[0]
            ], dtype=np.float64))
            ntrees = int(self.info.get("n_trees", 1))
            mx = float(self.info["max_path_length"])
            mn = float(self.info["min_path_length"])
            score = (mx - total) / (mx - mn) if mx > mn else 1.0
            return np.array([score, total / max(ntrees, 1)])
        init_f = float(self.info.get("init_f", 0.0))
        dist = self.info.get("distribution", "gaussian")
        link = self.info.get("link_function", "identity")
        sums = np.array([
            np.sum([self.score_tree(t, row) for t in cls], dtype=np.float32)
            for cls in self.trees
        ], dtype=np.float64)
        if algo == "drf":  # DrfMojoModel.unifyPreds
            ntrees = int(self.info.get("n_trees", 1))
            if self.nclasses == 1:
                return np.array([sums[0] / ntrees])
            if self.nclasses == 2:
                p0 = sums[0] / ntrees  # trees carry CLASS-0 probability
                return np.array([p0, 1.0 - p0])
            total = sums.sum()
            return sums / total if total > 0 else sums
        if dist == "bernoulli":
            f = sums[0] + init_f
            p1 = 1.0 / (1.0 + np.exp(-f))
            return np.array([1.0 - p1, p1])
        if self.nclasses > 2:
            e = np.exp(sums - sums.max())
            return e / e.sum()
        f = sums[0] + init_f
        return np.array([np.exp(f) if link == "log" else f])


def read_mojo(path: str) -> RefMojo:
    with zipfile.ZipFile(path) as z:
        return _read_entry(z, "")


def _read_entry(z: "zipfile.ZipFile", prefix: str) -> RefMojo:
    """Parse one model rooted at `prefix` inside the archive ("" for the
    top level; "models/<algo>/<key>/" for MultiModelMojoWriter
    sub-models)."""
    m = RefMojo()
    section = 0
    columns: List[str] = []
    domain_files: Dict[int, str] = {}
    for raw in z.read(prefix + "model.ini").decode().splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[info]":
            section = 1
        elif line == "[columns]":
            section = 2
        elif line == "[domains]":
            section = 3
        elif section == 1:
            k, _, v = line.partition("=")
            m.info[k.strip()] = v.strip()
        elif section == 2:
            columns.append(line)
        elif section == 3:
            ci, _, rest = line.partition(":")
            # '<col>: <n_elements> <file>' (count optional for
            # tolerance with older writers)
            toks = rest.split()
            domain_files[int(ci)] = toks[-1]
    m.columns = columns
    for ci, fname in domain_files.items():
        m.domains[ci] = z.read(
            f"{prefix}domains/{fname}").decode().splitlines()
    K = int(m.info.get("n_trees_per_class", 1))
    ntrees = int(m.info.get("n_trees", 0))
    for c in range(K):
        m.trees.append([
            z.read(f"{prefix}trees/t{c:02d}_{t:03d}.bin")
            for t in range(ntrees)
        ])
    if m.info.get("algo") == "coxph":
        m.x_mean_cat = np.frombuffer(z.read(prefix + "x_mean_cat"), ">f8")
        m.x_mean_num = np.frombuffer(z.read(prefix + "x_mean_num"), ">f8")
    if m.info.get("algo") == "pca":
        ncoefs = int(m.info["eigenvector_size"])
        kcomp = int(m.info["k"])
        m.eigenvectors = np.frombuffer(
            z.read(prefix + "eigenvectors_raw"), ">f8"
        ).reshape(ncoefs, kcomp)
    if m.info.get("algo") == "targetencoder":
        base = prefix + "feature_engineering/target_encoding"
        enc: Dict[str, Dict[int, tuple]] = {}
        cur = None
        for line in z.read(f"{base}/encoding_map.ini").decode() \
                .splitlines():
            line = line.strip()
            if line.startswith("[") and line.endswith("]"):
                cur = line[1:-1]
                enc[cur] = {}
            elif line and cur is not None:
                k, _, v = line.partition("=")
                parts = v.split()
                enc[cur][int(k)] = (float(parts[0]), float(parts[1]))
        m.te_encodings = enc
        order = []
        in_from = False
        for line in z.read(f"{base}/input_encoding_columns_map.ini") \
                .decode().splitlines():
            line = line.strip()
            if line == "[from]":
                in_from = True
            elif line.startswith("["):
                in_from = False
            elif line and in_from:
                order.append(line)
        m.te_columns = order or list(enc)
    if m.info.get("algo") == "word2vec":
        words = [
            _unescape_vocab_word(w)
            for w in z.read(prefix + "vocabulary").decode().split("\n")
            if w != ""
        ]
        vocab_size = int(m.info["vocab_size"])
        if len(words) != vocab_size:
            raise ValueError(
                f"corrupted vocabulary: {len(words)} words != "
                f"vocab_size {vocab_size}")
        vecs = np.frombuffer(
            z.read(prefix + "vectors"), dtype=">f4").reshape(
            vocab_size, int(m.info["vec_size"])
        )
        m.word_vectors = dict(zip(words, np.asarray(vecs, np.float32)))
    if m.info.get("algo") == "gam":
        # GamMojoReader: per-smoother knots / zTranspose / _binvD blobs
        # (big-endian f64) + the gam column-name text files
        nks = _parse_jarr(m.info["num_knots_sorted"], int)
        m.gam_columns = z.read(
            prefix + "gam_columns_sorted").decode().split()
        kb = z.read(prefix + "knots")
        zb = z.read(prefix + "zTranspose")
        bb = z.read(prefix + "_binvD")
        m.gam_knots, m.gam_zt, m.gam_binvd = [], [], []
        ko = zo = bo = 0
        for k in nks:
            m.gam_knots.append(np.frombuffer(
                kb, ">f8", count=k, offset=ko).copy())
            ko += 8 * k
            m.gam_zt.append(np.frombuffer(
                zb, ">f8", count=(k - 1) * k, offset=zo
            ).reshape(k - 1, k).copy())
            zo += 8 * (k - 1) * k
            m.gam_binvd.append(np.frombuffer(
                bb, ">f8", count=(k - 2) * k, offset=bo
            ).reshape(k - 2, k).copy())
            bo += 8 * (k - 2) * k
    if m.info.get("algo") == "pipeline":
        # MojoPipelineReader: sub-models by submodel_dir_i, generated
        # columns bound to (model alias, prediction index)
        m.pipeline_models = {}
        for i in range(int(m.info["submodel_count"])):
            key = m.info[f"submodel_key_{i}"]
            subdir = m.info[f"submodel_dir_{i}"]
            m.pipeline_models[key] = _read_entry(z, prefix + subdir)
        m.pipeline_gen = []
        for i in range(int(m.info.get("generated_column_count", 0))):
            m.pipeline_gen.append((
                m.info[f"generated_column_name_{i}"],
                m.info[f"generated_column_model_{i}"],
                int(m.info[f"generated_column_index_{i}"]),
            ))
        m.pipeline_main = m.info["main_model"]
    if m.info.get("algo") == "stackedensemble":
        # sub-models live under models/<algo>/<key>/ (MultiModelMojoWriter)
        def find_prefix(key: str) -> str:
            suffix = f"/{key}/model.ini"
            for nm in z.namelist():
                if nm.startswith(prefix + "models/") and nm.endswith(suffix):
                    return nm[: -len("model.ini")]
            raise ValueError(f"sub-model {key!r} missing from archive")

        m.metalearner = _read_entry(z, find_prefix(m.info["metalearner"]))
        m.base_models = []
        for i in range(int(m.info["base_models_num"])):
            key = m.info.get(f"base_model{i}")
            m.base_models.append(
                _read_entry(z, find_prefix(key)) if key else None)
    return m
