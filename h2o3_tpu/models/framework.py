"""Model framework: Parameters / Model / ModelBuilder / Job lifecycle.

Reference: ``hex/Model.java`` (scoring + test-frame adaptation + metrics
hookup, Model.java:1764 score, 2077 BigScore), ``hex/ModelBuilder.java``
(lifecycle + validation + cross-validation, ModelBuilder.java:228,368-377,597),
``water/Job.java`` (cancellable progress handle in the DKV).

TPU-native: the lifecycle is the same shape — validate params, build, score,
metrics — but scoring is a jitted batch computation over sharded arrays
instead of a per-row MRTask, and CV fold models are independent jit programs
(the reference's parallel fold building, hex/CVModelBuilder.java:10).
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field as dataclass_field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.keyed import DKV
from h2o3_tpu.models import metrics as M
from h2o3_tpu.models.data_info import DataInfo
from h2o3_tpu.util import telemetry

#: fit accounting: wall seconds per algo (histogram — AutoML fans dozens of
#: fits through here) + outcome counter; the fit Span makes every timeline
#: event of a build (train blocks, mapreduce dispatches) share one trace_id
_FIT_SECONDS = telemetry.histogram(
    "model_fit_seconds", "model build wall seconds", labels=("algo",)
)
_FITS = telemetry.counter(
    "model_fit_total", "model builds", labels=("algo", "outcome")
)


@dataclass
class ModelParameters:
    """Common hyperparameters (reference: hex/Model.Parameters)."""

    response_column: Optional[str] = None
    ignored_columns: List[str] = dataclass_field(default_factory=list)
    weights_column: Optional[str] = None
    offset_column: Optional[str] = None
    fold_column: Optional[str] = None
    nfolds: int = 0
    fold_assignment: str = "auto"  # auto|random|modulo|stratified
    keep_cross_validation_predictions: bool = False
    seed: int = -1
    max_runtime_secs: float = 0.0
    stopping_rounds: int = 0
    stopping_metric: str = "auto"
    stopping_tolerance: float = 1e-3
    categorical_encoding: str = "auto"
    checkpoint: Optional[str] = None  # model key to continue training from

    def actual_seed(self) -> int:
        if self.seed is None or self.seed == -1:
            return int(time.time_ns() % (2**31))
        return int(self.seed)


class Job:
    """Cancellable, progress-reporting handle (water/Job.java)."""

    def __init__(self, description: str = "") -> None:
        self.key = DKV.make_key("job")
        self.description = description
        self.progress = 0.0
        #: live human-readable detail (e.g. distributed search streaming
        #: "3/12 models across 4 member(s)" via the search_progress RPC)
        self.progress_msg: Optional[str] = None
        self.status = "CREATED"
        self.start_time: Optional[float] = None
        self.end_time: Optional[float] = None
        self.exception: Optional[BaseException] = None
        self._cancel_requested = False
        DKV.put(self.key, self)

    def start(self) -> "Job":
        self.start_time = time.time()
        self.status = "RUNNING"
        return self

    def update(self, progress: float) -> None:
        self.progress = min(max(progress, 0.0), 1.0)

    def cancel(self) -> None:
        self._cancel_requested = True

    @property
    def stop_requested(self) -> bool:
        return self._cancel_requested

    def done(self) -> None:
        self.end_time = time.time()
        self.progress = 1.0
        self.status = "DONE" if not self._cancel_requested else "CANCELLED"

    def fail(self, e: BaseException) -> None:
        self.end_time = time.time()
        self.exception = e
        self.status = "FAILED"

    @property
    def run_time(self) -> float:
        end = self.end_time if self.end_time is not None else time.time()
        return (end - self.start_time) if self.start_time else 0.0


def prediction_frame(raw: np.ndarray, domain, threshold: float = 0.5) -> Frame:
    """Raw scores -> the canonical predictions frame (Model.score layout).

    domain None => not a classifier: 1-D raw becomes a 'predict' numeric
    column; 2-D raw (PCA projections, autoencoder reconstructions) becomes
    one numeric column per output. With a domain, binomial labels threshold
    ``p[:, 1]`` at ``threshold`` (training max-F1 by default), multinomial
    labels argmax; per-class columns are named p<level>.
    """
    if domain is None:
        if raw.ndim == 1:
            return Frame(
                [Column("predict", raw.astype(np.float64), ColType.NUM)])
        return Frame([
            Column(f"C{k + 1}", raw[:, k].astype(np.float64), ColType.NUM)
            for k in range(raw.shape[1])
        ])
    if raw.shape[1] == 2:
        labels = (raw[:, 1] >= threshold).astype(np.int32)
    else:
        labels = raw.argmax(axis=1).astype(np.int32)
    cols = [Column("predict", labels, ColType.CAT, list(domain))]
    for k, lv in enumerate(domain):
        cols.append(
            Column(f"p{lv}", raw[:, k].astype(np.float64), ColType.NUM))
    return Frame(cols)


class Model:
    """Trained model: predict + metrics (hex/Model.java).

    Subclasses implement ``_predict_raw(frame) -> np.ndarray``:
      regression      -> [N] predictions
      binomial        -> [N, 2] class probabilities
      multinomial     -> [N, K] class probabilities
    """

    algo_name: str = "model"

    def __init__(self, params: ModelParameters, data_info: DataInfo) -> None:
        self.key = DKV.make_key(self.algo_name)
        self.params = params
        self.data_info = data_info
        self.training_metrics: Optional[Any] = None
        self.validation_metrics: Optional[Any] = None
        self.cross_validation_metrics: Optional[Any] = None
        self.scoring_history: List[Dict[str, Any]] = []
        self.run_time: float = 0.0
        DKV.put(self.key, self)

    # -- category of the learning problem -----------------------------------
    @property
    def nclasses(self) -> int:
        dom = self.data_info.response_domain
        return len(dom) if dom else 1

    @property
    def is_classifier(self) -> bool:
        return self.nclasses > 1

    # -- scoring (Model.score, Model.java:1764) ------------------------------
    def _predict_raw(self, frame: Frame) -> np.ndarray:
        raise NotImplementedError

    def _apply_preprocessors(self, frame: Frame) -> Frame:
        """Models trained on a preprocessed frame (e.g. AutoML target
        encoding) carry their transformers in ``self.preprocessors`` so a
        RAW frame scores correctly (the reference embeds TE in the model
        pipeline; here the transform re-applies at score time). A frame
        already carrying the derived columns passes through untouched."""
        for pre in getattr(self, "preprocessors", None) or []:
            outs = [f"{name}_te" for name in getattr(pre, "encodings", {})]
            if outs and all(o in frame.names for o in outs):
                continue  # already transformed (e.g. the training frame)
            frame = pre.transform(frame)
        return frame

    def default_threshold(self) -> float:
        """Binomial label threshold: an explicit reset wins, else the
        training max-F1 (Model._output.defaultThreshold())."""
        override = getattr(self, "_threshold_override", None)
        if override is not None:
            return override
        return getattr(self.training_metrics, "max_f1_threshold", 0.5) or 0.5

    def reset_threshold(self, threshold: float) -> float:
        """Set the classification threshold used by predict; returns the
        previous effective threshold (Model.resetThreshold,
        rapids ``model.reset.threshold``)."""
        old = self.default_threshold()
        self._threshold_override = float(threshold)
        return old

    def predict(self, frame: Frame) -> Frame:
        """Predictions frame: 'predict' (+ per-class probability columns)."""
        frame = self._apply_preprocessors(frame)
        return self.prediction_from_raw(self._predict_raw(frame))

    def prediction_from_raw(self, raw: np.ndarray) -> Frame:
        """Raw scores -> the predictions frame (the second half of
        ``predict``; the serving coalescer computes raw once per batch and
        fans it out per caller through here)."""
        if not self.is_classifier:
            return prediction_frame(raw, None)
        return prediction_frame(raw, self.data_info.response_domain,
                                self.default_threshold())

    def predict_raw_batched(
        self, frames: Sequence[Frame]
    ) -> List[Tuple[np.ndarray, Frame]]:
        """One raw-score pass over several frames (the coalesced REST
        scoring entry).  Returns ``(raw, preprocessed_frame)`` per input,
        aligned.  Identical frames — same object, or equal (names, types,
        version) stamps, the devcache identity — score ONCE and share the
        result; distinct frames with one schema row-stack into a single
        ``_predict_raw`` dispatch and split back per caller.  Every
        ``_predict_raw`` scores row-wise (no cross-row coupling), so both
        paths are bit-identical to per-frame calls; anything unstackable
        falls back to one dispatch per distinct frame."""
        pres = [self._apply_preprocessors(f) for f in frames]
        uniq: List[Frame] = []
        which: List[int] = []
        seen: Dict[Any, int] = {}
        for f in pres:
            try:
                sig: Any = (tuple(f.names),
                            tuple(c.type for c in f.columns), f.version)
            except Exception:
                sig = id(f)
            i = seen.get(sig)
            if i is None:
                i = seen[sig] = len(uniq)
                uniq.append(f)
            which.append(i)
        if len(uniq) == 1:
            raws = [self._predict_raw(uniq[0])]
        else:
            head = uniq[0]
            same_schema = all(
                u.names == head.names
                and [c.type for c in u.columns]
                == [c.type for c in head.columns]
                for u in uniq[1:]
            )
            if same_schema:
                stacked = head
                for u in uniq[1:]:
                    stacked = stacked.rbind(u)
                raw_all = self._predict_raw(stacked)
                raws, off = [], 0
                for u in uniq:
                    raws.append(raw_all[off:off + u.nrows])
                    off += u.nrows
            else:
                raws = [self._predict_raw(u) for u in uniq]
        return [(raws[i], pres[k]) for k, i in enumerate(which)]

    def model_performance(self, frame: Frame) -> Any:
        """Score a frame and build the right ModelMetrics (Model.score + MM builders)."""
        frame = self._apply_preprocessors(frame)
        return self._metrics_from_raw(frame, self._predict_raw(frame))

    def _metrics_from_raw(self, frame: Frame, raw: np.ndarray) -> Any:
        """ModelMetrics from an already-computed raw score over an already-
        preprocessed frame — ``model_performance`` minus the scoring pass,
        so the batched REST path never scores the same frame twice."""
        from h2o3_tpu.models.data_info import response_vector

        y = response_vector(self.data_info, frame)
        w = (
            frame.col(self.params.weights_column).numeric_view()
            if self.params.weights_column
            else None
        )
        if not self.is_classifier:
            return M.regression_metrics(y, raw, weights=w)
        if self.nclasses == 2:
            return M.binomial_metrics(y, raw[:, 1], weights=w)
        return M.multinomial_metrics(
            y.astype(np.int64), raw, self.data_info.response_domain, weights=w
        )

    def pojo(self, lang: str = "c") -> str:
        """Standalone scoring source (hex/tree/TreeJCodeGen / water/codegen
        POJO export, /3/Models.java): C (compiles with any C99 toolchain)
        or Java (genmodel score0 shape). Tree models + GLM."""
        from h2o3_tpu.models.pojo import pojo_source

        return pojo_source(self, lang)

    def download_mojo(self, path: str) -> str:
        """Export as a portable MOJO zip (Model.getMojo, /3/Models .../mojo);
        scored offline by the numpy-only ``h2o3_tpu.genmodel`` package."""
        from h2o3_tpu.models.mojo_export import write_mojo

        return write_mojo(self, path)

    def __repr__(self) -> str:
        return f"<{type(self).__name__} {self.key} metrics={self.training_metrics!r}>"


class ModelBuilder:
    """Train lifecycle (hex/ModelBuilder.java:368-377 trainModel).

    Subclasses set ``model_class`` and implement ``_fit(frame) -> Model``.
    ``train`` adds: parameter validation, the Job, cross-validation
    (ModelBuilder.java:597 computeCrossValidation), and main-model CV metrics
    from the aggregated holdout predictions.
    """

    algo_name: str = "builder"

    #: Common ModelParameters fields this builder honors beyond the
    #: framework-provided ones (CV, seed, response/ignored columns). Setting
    #: any other guarded field to a non-default value raises instead of being
    #: silently ignored — the reference validates every param in
    #: hex/ModelBuilder.init (VERDICT r2: accepted-and-ignored params were the
    #: worst user-facing behavior; this guard makes them structurally
    #: impossible).
    SUPPORTED_COMMON: frozenset = frozenset()

    #: guarded field -> its dataclass default
    _GUARDED_DEFAULTS = {
        "weights_column": None,
        "offset_column": None,
        "checkpoint": None,
        "stopping_rounds": 0,
        "max_runtime_secs": 0.0,
        "categorical_encoding": "auto",
    }

    def __init__(self, params: ModelParameters) -> None:
        self.params = params
        self.job: Optional[Job] = None

    # -- validation (ModelBuilder.init) --------------------------------------
    def _validate_params(self) -> None:
        """Frame-independent checks: the no-silent-param guard + CV combos.
        Frame-free builders (generic) run this directly."""
        p = self.params
        for name, default in self._GUARDED_DEFAULTS.items():
            val = getattr(p, name, default)
            if val != default and name not in self.SUPPORTED_COMMON:
                raise ValueError(
                    f"{self.algo_name} does not support {name!r} "
                    f"(got {val!r}); supported common params: "
                    f"{sorted(self.SUPPORTED_COMMON) or 'none'}"
                )
        if p.nfolds == 1:
            raise ValueError("nfolds must be 0 or >= 2")
        if p.nfolds and p.fold_column:
            raise ValueError("cannot use both nfolds and fold_column")

    def _validate(self, frame: Frame) -> None:
        self._validate_params()
        p = self.params
        if p.response_column and p.response_column not in frame.names:
            raise ValueError(f"response_column {p.response_column!r} not in frame")
        if p.weights_column and p.weights_column not in frame.names:
            raise ValueError(f"weights_column {p.weights_column!r} not in frame")

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> Model:
        raise NotImplementedError

    def train(self, frame: Frame, valid: Optional[Frame] = None) -> Model:
        from h2o3_tpu.util.log import get_logger

        log = get_logger("train")
        self._validate(frame)
        self.job = Job(f"{self.algo_name} train").start()
        t0 = time.time()
        log.info(
            "%s train start: %d rows x %d cols, response=%r",
            self.algo_name, frame.nrows, frame.ncols,
            self.params.response_column,
        )
        # Lockable: the training frame(s) must not be deleted mid-build
        locked = [
            fr.key for fr in (frame, valid)
            if fr is not None and getattr(fr, "key", None)
        ]
        for k in locked:
            DKV.read_lock(k, self.job.key)
        # a failed build must not strand a half-constructed model in the
        # DKV (Lockable.delete on builder failure); keys registered
        # during _fit are scope-tracked and swept unless the build wins
        DKV.scope_enter()
        keep = [self.job.key]
        try:
            with telemetry.Span(
                "train", algo=self.algo_name, rows=frame.nrows
            ) as span:
                model = self._fit(frame, valid)
                if self.params.nfolds >= 2 or self.params.fold_column:
                    self._cross_validate(model, frame)
                model.run_time = time.time() - t0
                span.set(train_s=round(model.run_time, 3))
                iters = getattr(model, "iterations", None)
                if isinstance(iters, (int, float)):
                    span.set(iterations=int(iters))
            _FIT_SECONDS.observe(model.run_time, algo=self.algo_name)
            _FITS.inc(algo=self.algo_name, outcome="ok")
            self.job.done()
            keep = None  # success: everything the build registered lives
            # on a live multi-node cloud the finished model is homed onto
            # the serving ring (blob + replicas) so ANY member can score
            # it; best-effort — a failed homing leaves builder-local
            # serving intact (cluster/serving.py)
            from h2o3_tpu.cluster import active_cloud as _active_cloud

            if _active_cloud() is not None:
                from h2o3_tpu.cluster import serving as _serving

                _serving.home_model(model)
            log.info(
                "%s train done in %.2fs -> %s", self.algo_name,
                model.run_time, model.key,
            )
            return model
        except BaseException as e:
            _FITS.inc(algo=self.algo_name, outcome="error")
            self.job.fail(e)
            log.error("%s train failed: %s: %s", self.algo_name, type(e).__name__, e)
            raise
        finally:
            if keep is None:
                DKV.scope_exit(keep=DKV.keys())  # keep all
            else:
                DKV.scope_exit(keep=keep)
            for k in locked:
                DKV.read_unlock(k, self.job.key)

    # -- cross-validation (ModelBuilder.computeCrossValidation) --------------
    def _cross_validate(self, main_model: Model, frame: Frame) -> None:
        from h2o3_tpu.models.data_info import response_vector

        p = self.params
        fold = fold_assignment(
            n=frame.nrows,
            nfolds=p.nfolds,
            scheme=p.fold_assignment,
            seed=p.actual_seed(),
            y=response_vector(main_model.data_info, frame) if p.fold_assignment == "stratified" else None,
            fold_column=frame.col(p.fold_column).numeric_view().astype(np.int64)
            if p.fold_column
            else None,
        )
        nfolds = int(fold.max()) + 1
        nclasses = main_model.nclasses
        holdout = (
            np.full(frame.nrows, np.nan)
            if nclasses == 1
            else np.full((frame.nrows, nclasses), np.nan)
        )
        cv_models = []
        for f in range(nfolds):
            tr = frame.rows(fold != f)
            te = frame.rows(fold == f)
            sub = type(self)(_clone_params_no_cv(p))
            m = sub._fit(tr)
            cv_models.append(m)
            holdout[fold == f] = m._predict_raw(te)
            self.job.update(0.5 + 0.5 * (f + 1) / nfolds)
        y = response_vector(main_model.data_info, frame)
        w = (
            frame.col(p.weights_column).numeric_view() if p.weights_column else None
        )
        if nclasses == 1:
            main_model.cross_validation_metrics = M.regression_metrics(y, holdout, weights=w)
        elif nclasses == 2:
            main_model.cross_validation_metrics = M.binomial_metrics(y, holdout[:, 1], weights=w)
        else:
            main_model.cross_validation_metrics = M.multinomial_metrics(
                y.astype(np.int64), holdout, main_model.data_info.response_domain, weights=w
            )
        main_model.cv_models = cv_models
        if p.keep_cross_validation_predictions:
            main_model.cv_holdout_predictions = holdout


def _clone_params_no_cv(p: ModelParameters) -> ModelParameters:
    import copy

    q = copy.deepcopy(p)
    q.nfolds = 0
    q.fold_column = None
    return q


def fold_assignment(
    n: int,
    nfolds: int,
    scheme: str = "auto",
    seed: int = 42,
    y: Optional[np.ndarray] = None,
    fold_column: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Row -> fold id (hex/FoldAssignment.java). auto==random; modulo is
    deterministic row%nfolds; stratified balances class frequencies per fold."""
    if fold_column is not None:
        vals = fold_column
        uniq = np.unique(vals)
        remap = {v: i for i, v in enumerate(uniq)}
        return np.array([remap[v] for v in vals], dtype=np.int64)
    if scheme in ("auto", "random"):
        rng = np.random.default_rng(seed)
        return rng.integers(0, nfolds, size=n)
    if scheme == "modulo":
        return np.arange(n) % nfolds
    if scheme == "stratified":
        assert y is not None, "stratified fold assignment needs the response"
        rng = np.random.default_rng(seed)
        fold = np.zeros(n, dtype=np.int64)
        for cls in np.unique(y[~np.isnan(y)]):
            idx = np.nonzero(y == cls)[0]
            perm = rng.permutation(len(idx))
            fold[idx[perm]] = np.arange(len(idx)) % nfolds
        return fold
    raise ValueError(f"unknown fold_assignment {scheme!r}")
