"""POJO-style standalone scoring codegen.

Reference: ``hex/tree/TreeJCodeGen.java`` + ``water/codegen/`` — export a
trained model as dependency-free scoring SOURCE that runs without the
cluster. Two emitters:

  * C (primary, TPU-era equivalent): compiles with any C99 compiler,
    no runtime dependency; the test tier actually compiles it with the
    image's gcc/g++ and pins bit-level parity against in-framework
    ``predict``.
  * Java (reference-parity surface): the same trees/coefficients as a
    single class with a ``score0(double[] row, double[] preds)`` in the
    genmodel shape; compiled in CI only where a JDK exists.

Tree scorers take the model's TREE-FEATURE vector (the
``tree_feature_names`` order — label-encoded category codes, or the
one-hot block under one_hot_explicit), as ``float`` values: training
binned float32 features, so scoring in float keeps threshold comparisons
bit-identical to the in-framework path. GLM scorers take the expanded
design vector matching ``coefficient_names``.
"""

from __future__ import annotations

from typing import List

import numpy as np


def _c_float(v: float) -> str:
    if np.isnan(v):
        return "NAN"
    if np.isinf(v):
        return "INFINITY" if v > 0 else "-INFINITY"
    return repr(float(v))


def _c_arr(name: str, vals, ctype: str, fmt=str) -> str:
    body = ", ".join(fmt(v) for v in vals)
    return f"static const {ctype} {name}[] = {{{body}}};\n"


# ---------------------------------------------------------------------------
# tree models (GBM / DRF / XGBoost-style)


def _tree_tables(model):
    """Flatten the booster into per-class per-tree node tables with raw
    float thresholds (bin edge at the split bin; +inf when the split only
    separates NA from non-NA)."""
    b = model.booster
    out = []
    for trees in b.trees_per_class:
        edges = trees.edges  # [F, B-1]
        cls_trees = []
        for t in range(trees.ntrees):
            feat = trees.feat[t].astype(np.int32)
            sb = trees.split_bin[t].astype(np.int64)
            # thresholds stay float64: the framework compares float32
            # features against float64 edges, and rounding the edge to
            # f32 would flip rows landing exactly on the rounded value
            thr = np.where(
                sb >= edges.shape[1],
                np.inf,
                edges[feat, np.clip(sb, 0, edges.shape[1] - 1)],
            ).astype(np.float64)
            cls_trees.append({
                "feat": feat,
                "thr": thr,
                "default_left": trees.default_left[t].astype(np.int32),
                "is_split": trees.is_split[t].astype(np.int32),
                "leaf": trees.leaf[t].astype(np.float64),
            })
        out.append(cls_trees)
    return out


def tree_pojo_c(model) -> str:
    from h2o3_tpu.models.tree.common import tree_feature_names

    b = model.booster
    names = tree_feature_names(model.data_info, model.tree_encoding)
    tables = _tree_tables(model)
    K = len(tables)
    T = len(tables[0])
    M = tables[0][0]["feat"].shape[0]
    depth = int(np.log2(M + 1)) - 1
    dist = model.distribution
    nclasses = model.nclasses

    chunks: List[str] = []
    chunks.append(
        f"""/* GENERATED standalone scorer — do not edit.
 * Model: {model.key} ({model.algo_name}, distribution={dist})
 * Emitted by h2o3_tpu.models.pojo (TreeJCodeGen/water-codegen analogue).
 *
 * double out[{max(nclasses, 1) + (1 if nclasses > 1 else 0)}];
 * score(x, out);
 *   x: float[{len(names)}] tree features, order: {", ".join(names)}
 *      (categorical columns: label-encoded level index; NAN = missing)
 *   classifier out: [predicted_class, p0, p1, ...]; regression out: [mu]
 */
#include <math.h>

#define N_FEAT {len(names)}
#define N_CLASS_SETS {K}
#define N_TREES {T}
#define N_NODES {M}
#define MAX_DEPTH {depth}

""")
    for c, cls_trees in enumerate(tables):
        for t, tb in enumerate(cls_trees):
            p = f"c{c}_t{t}"
            chunks.append(_c_arr(f"feat_{p}", tb["feat"], "int"))
            chunks.append(_c_arr(f"thr_{p}", tb["thr"], "double", _c_float))
            chunks.append(_c_arr(f"dl_{p}", tb["default_left"], "int"))
            chunks.append(_c_arr(f"sp_{p}", tb["is_split"], "int"))
            chunks.append(_c_arr(f"leaf_{p}", tb["leaf"], "double", _c_float))
    chunks.append(_c_arr("init_margin", np.asarray(b.init_margin, np.float64),
                         "double", _c_float))
    chunks.append("""
static double walk(const float *x, const int *feat, const double *thr,
                   const int *dl, const int *sp, const double *leaf) {
  int idx = 0;
  for (int d = 0; d < MAX_DEPTH; d++) {
    if (!sp[idx]) break;
    double v = (double)x[feat[idx]];  /* f32 feature vs f64 edge, as trained */
    int left = isnan(v) ? dl[idx] : (v < thr[idx]);
    idx = 2 * idx + (left ? 1 : 2);
  }
  return leaf[idx];
}

""")
    # per-class margin accumulators
    chunks.append("static double margin_class(const float *x, int c) {\n"
                  "  double s = 0.0;\n  switch (c) {\n")
    for c, cls_trees in enumerate(tables):
        chunks.append(f"  case {c}:\n")
        for t in range(len(cls_trees)):
            p = f"c{c}_t{t}"
            chunks.append(
                f"    s += walk(x, feat_{p}, thr_{p}, dl_{p}, sp_{p}, "
                f"leaf_{p});\n")
        chunks.append("    break;\n")
    chunks.append("  }\n")
    if getattr(b, "average", False):
        chunks.append("  s /= (double)N_TREES;\n")
    chunks.append("  return init_margin[c] + s;\n}\n\n")

    averaged = bool(getattr(b, "average", False))
    if averaged and nclasses == 2:
        # DRF: the single tree set predicts P(class 1) directly
        chunks.append("""void score(const float *x, double *out) {
  double p1 = margin_class(x, 0);
  if (p1 < 0.0) p1 = 0.0;
  if (p1 > 1.0) p1 = 1.0;
  out[1] = 1.0 - p1; out[2] = p1;
  out[0] = (p1 >= 0.5) ? 1.0 : 0.0;  /* threshold tuned server-side */
}
""")
    elif averaged and nclasses > 2:
        chunks.append("""void score(const float *x, double *out) {
  double s = 0.0;
  int best = 0;
  for (int c = 0; c < N_CLASS_SETS; c++) {
    double p = margin_class(x, c);
    if (p < 1e-9) p = 1e-9;
    out[1 + c] = p; s += p;
  }
  for (int c = 0; c < N_CLASS_SETS; c++) {
    out[1 + c] /= s;
    if (out[1 + c] > out[1 + best]) best = c;
  }
  out[0] = (double)best;
}
""")
    elif nclasses == 2 and dist == "bernoulli":
        chunks.append("""void score(const float *x, double *out) {
  double m = margin_class(x, 0);
  double p1 = 1.0 / (1.0 + exp(-m));
  out[1] = 1.0 - p1; out[2] = p1;
  out[0] = (p1 >= 0.5) ? 1.0 : 0.0;  /* threshold tuned server-side */
}
""")
    elif nclasses > 2:
        chunks.append("""void score(const float *x, double *out) {
  double m[N_CLASS_SETS], mx = -INFINITY, s = 0.0;
  for (int c = 0; c < N_CLASS_SETS; c++) {
    m[c] = margin_class(x, c);
    if (m[c] > mx) mx = m[c];
  }
  for (int c = 0; c < N_CLASS_SETS; c++) { m[c] = exp(m[c] - mx); s += m[c]; }
  int best = 0;
  for (int c = 0; c < N_CLASS_SETS; c++) {
    out[1 + c] = m[c] / s;
    if (out[1 + c] > out[1 + best]) best = c;
  }
  out[0] = (double)best;
}
""")
    else:
        link = ("exp(m)" if dist.partition(":")[0] in
                ("poisson", "gamma", "tweedie") else "m")
        chunks.append(f"""void score(const float *x, double *out) {{
  double m = margin_class(x, 0);
  out[0] = {link};
}}
""")
    return "".join(chunks)


def tree_pojo_java(model) -> str:
    """Reference-shaped Java source: one class, score0(double[], double[])."""
    from h2o3_tpu.models.tree.common import tree_feature_names

    b = model.booster
    names = tree_feature_names(model.data_info, model.tree_encoding)
    tables = _tree_tables(model)
    dist = model.distribution
    nclasses = model.nclasses
    cls_name = f"POJO_{model.key}".replace("-", "_").replace(".", "_")

    def jarr(vals, jt, fmt):
        return "{" + ", ".join(fmt(v) for v in vals) + "}"

    def jdouble(v):
        if np.isnan(v):
            return "Double.NaN"
        if np.isinf(v):
            return ("Double.POSITIVE_INFINITY" if v > 0
                    else "Double.NEGATIVE_INFINITY")
        return repr(float(v))

    out = [f"""// GENERATED standalone scorer — do not edit.
// Model: {model.key} ({model.algo_name}); features: {", ".join(names)}
public class {cls_name} {{
"""]
    for c, cls_trees in enumerate(tables):
        for t, tb in enumerate(cls_trees):
            p = f"c{c}_t{t}"
            out.append(f"  static final int[] FEAT_{p} = "
                       f"{jarr(tb['feat'], 'int', str)};\n")
            out.append(f"  static final double[] THR_{p} = "
                       f"{jarr(tb['thr'], 'double', jdouble)};\n")
            out.append(f"  static final boolean[] DL_{p} = "
                       f"{jarr(tb['default_left'], 'boolean', lambda v: 'true' if v else 'false')};\n")
            out.append(f"  static final boolean[] SP_{p} = "
                       f"{jarr(tb['is_split'], 'boolean', lambda v: 'true' if v else 'false')};\n")
            out.append(f"  static final double[] LEAF_{p} = "
                       f"{jarr(tb['leaf'], 'double', jdouble)};\n")
    out.append(f"  static final double[] INIT = "
               f"{jarr(np.asarray(b.init_margin, np.float64), 'double', jdouble)};\n")
    M = tables[0][0]["feat"].shape[0]
    depth = int(np.log2(M + 1)) - 1
    out.append(f"""
  static double walk(float[] x, int[] feat, double[] thr, boolean[] dl,
                     boolean[] sp, double[] leaf) {{
    int idx = 0;
    for (int d = 0; d < {depth}; d++) {{
      if (!sp[idx]) break;
      double v = (double) x[feat[idx]];  // f32 feature vs f64 edge
      boolean left = Double.isNaN(v) ? dl[idx] : (v < thr[idx]);
      idx = 2 * idx + (left ? 1 : 2);
    }}
    return leaf[idx];
  }}

  static double marginClass(float[] x, int c) {{
    double s = 0.0;
    switch (c) {{
""")
    for c, cls_trees in enumerate(tables):
        out.append(f"      case {c}:\n")
        for t in range(len(cls_trees)):
            p = f"c{c}_t{t}"
            out.append(f"        s += walk(x, FEAT_{p}, THR_{p}, DL_{p}, "
                       f"SP_{p}, LEAF_{p});\n")
        out.append("        break;\n")
    out.append("    }\n")
    if getattr(b, "average", False):
        out.append(f"    s /= {len(tables[0])}.0;\n")
    out.append("    return INIT[c] + s;\n  }\n")
    averaged = bool(getattr(b, "average", False))
    if averaged and nclasses == 2:
        out.append("""
  public static double[] score0(double[] row, double[] preds) {
    float[] x = new float[row.length];
    for (int i = 0; i < row.length; i++) x[i] = (float) row[i];
    double p1 = marginClass(x, 0);
    p1 = Math.min(1.0, Math.max(0.0, p1));
    preds[1] = 1.0 - p1; preds[2] = p1; preds[0] = p1 >= 0.5 ? 1 : 0;
    return preds;
  }
}
""")
    elif averaged and nclasses > 2:
        K = len(tables)
        out.append(f"""
  public static double[] score0(double[] row, double[] preds) {{
    float[] x = new float[row.length];
    for (int i = 0; i < row.length; i++) x[i] = (float) row[i];
    double s = 0.0;
    int best = 0;
    for (int c = 0; c < {K}; c++) {{
      double p = Math.max(1e-9, marginClass(x, c));
      preds[1 + c] = p; s += p;
    }}
    for (int c = 0; c < {K}; c++) {{
      preds[1 + c] /= s;
      if (preds[1 + c] > preds[1 + best]) best = c;
    }}
    preds[0] = best;
    return preds;
  }}
}}
""")
    elif nclasses == 2 and dist == "bernoulli":
        out.append("""
  public static double[] score0(double[] row, double[] preds) {
    float[] x = new float[row.length];
    for (int i = 0; i < row.length; i++) x[i] = (float) row[i];
    double p1 = 1.0 / (1.0 + Math.exp(-marginClass(x, 0)));
    preds[1] = 1.0 - p1; preds[2] = p1; preds[0] = p1 >= 0.5 ? 1 : 0;
    return preds;
  }
}
""")
    elif nclasses > 2:
        K = len(tables)
        out.append(f"""
  public static double[] score0(double[] row, double[] preds) {{
    float[] x = new float[row.length];
    for (int i = 0; i < row.length; i++) x[i] = (float) row[i];
    double[] m = new double[{K}];
    double mx = Double.NEGATIVE_INFINITY, s = 0.0;
    for (int c = 0; c < {K}; c++) {{ m[c] = marginClass(x, c); if (m[c] > mx) mx = m[c]; }}
    for (int c = 0; c < {K}; c++) {{ m[c] = Math.exp(m[c] - mx); s += m[c]; }}
    int best = 0;
    for (int c = 0; c < {K}; c++) {{
      preds[1 + c] = m[c] / s;
      if (preds[1 + c] > preds[1 + best]) best = c;
    }}
    preds[0] = best;
    return preds;
  }}
}}
""")
    else:
        expo = dist.partition(":")[0] in ("poisson", "gamma", "tweedie")
        expr = "Math.exp(m)" if expo else "m"
        out.append(f"""
  public static double[] score0(double[] row, double[] preds) {{
    float[] x = new float[row.length];
    for (int i = 0; i < row.length; i++) x[i] = (float) row[i];
    double m = marginClass(x, 0);
    preds[0] = {expr};
    return preds;
  }}
}}
""")
    return "".join(out)


# ---------------------------------------------------------------------------
# GLM


def glm_pojo_c(model) -> str:
    """Linear scorer over the model's design vector.

    The design vector is exactly what ``expand_matrix`` produces at
    predict time (NA-imputed, one-hot expanded, standardized numerics),
    scored with the standardized betas — so the emitted source computes
    the same eta bit-for-bit as the in-framework ``_eta``."""
    names = list(model.data_info.coef_names)
    beta_full = np.asarray(model.beta_std, dtype=np.float64)
    beta, icpt = beta_full[:-1], float(beta_full[-1])
    family = model.params.family
    nclasses = model.nclasses
    chunks = [f"""/* GENERATED standalone GLM scorer — do not edit.
 * Model: {model.key} (family={family})
 * x: double[{len(names)}] standardized design vector (expand_matrix
 * order: numerics (v - train_mean) / train_sd, NA mean-imputed,
 * categoricals one-hot): {", ".join(names)}
 */
#include <math.h>

"""]
    chunks.append(_c_arr("beta", beta, "double", _c_float))
    chunks.append(f"static const double intercept = {_c_float(icpt)};\n\n")
    # exact _linkinv replication per resolved link (glm.py:87-98) — used
    # for BOTH branches: a binomial model with a non-canonical link must
    # score through its actual link, not a hardcoded sigmoid
    link = model.params.actual_link()
    if link == "identity":
        inv = "mu = eta;"
    elif link == "log":
        inv = "mu = exp(eta);"
    elif link == "inverse":
        inv = ("{ double d = eta; if (fabs(d) < 1e-10) "
               "d = (d + 1e-30 >= 0.0 ? 1e-10 : -1e-10); mu = 1.0 / d; }")
    elif link == "tweedie":
        lp = float(model.params.tweedie_link_power)
        inv = ("mu = exp(eta);" if lp == 0 else
               f"mu = pow(eta > 1e-10 ? eta : 1e-10, {1.0 / lp!r});")
    elif link == "logit":
        inv = "mu = 1.0 / (1.0 + exp(-eta));"
    else:
        raise ValueError(f"unsupported link {link!r} for POJO export")
    if nclasses == 2:
        chunks.append(f"""void score(const double *x, double *out) {{
  double eta = intercept;
  for (int i = 0; i < {len(beta)}; i++) eta += beta[i] * x[i];
  double mu;
  {inv}
  out[1] = 1.0 - mu; out[2] = mu; out[0] = (mu >= 0.5) ? 1.0 : 0.0;
}}
""")
    else:
        chunks.append(f"""void score(const double *x, double *out) {{
  double eta = intercept;
  for (int i = 0; i < {len(beta)}; i++) eta += beta[i] * x[i];
  double mu;
  {inv}
  out[0] = mu;
}}
""")
    return "".join(chunks)


def glm_multinomial_pojo_c(model) -> str:
    """Multinomial GLM scorer: K etas over the standardized design
    vector (class-major beta_multi layout, intercept row last) +
    numerically-stable softmax — matching ``_predict_raw``'s
    ``_softmax(X @ B[:-1] + B[-1])`` exactly."""
    names = list(model.data_info.coef_names)
    B = np.asarray(model.beta_multi, dtype=np.float64)  # [P+1, K]
    P, K = B.shape[0] - 1, B.shape[1]
    chunks = [f"""/* GENERATED standalone multinomial GLM scorer — do not edit.
 * Model: {model.key} (K={K} classes)
 * x: double[{P}] standardized design vector (expand_matrix order):
 * {", ".join(names)}
 * out: [label, p_0..p_{K - 1}]
 */
#include <math.h>

"""]
    chunks.append(_c_arr("beta", B[:-1].ravel(), "double", _c_float))
    chunks.append(_c_arr("icpt", B[-1], "double", _c_float))
    chunks.append(f"""
void score(const double *x, double *out) {{
  double eta[{K}];
  double mx = -1e308;
  for (int k = 0; k < {K}; k++) {{
    double e = icpt[k];
    for (int i = 0; i < {P}; i++) e += beta[i * {K} + k] * x[i];
    eta[k] = e;
    if (e > mx) mx = e;
  }}
  double tot = 0.0;
  for (int k = 0; k < {K}; k++) {{ eta[k] = exp(eta[k] - mx); tot += eta[k]; }}
  int best = 0;
  for (int k = 0; k < {K}; k++) {{
    out[k + 1] = eta[k] / tot;
    if (out[k + 1] > out[best + 1]) best = k;
  }}
  out[0] = (double) best;
}}
""")
    return "".join(chunks)


def gam_pojo_c(model) -> str:
    """Standalone GAM scorer: the emitted source re-computes each
    cubic-regression smoother's basis (cr_basis algebra: locateBin +
    a/c functions + the B⁻¹D rows), centers it through Z, and adds the
    linear eta — matching in-framework ``_predict_raw`` exactly for
    rows inside the knot range (outside, the C clamps to the boundary
    knot while training-side scoring extrapolates linearly; NA gam
    values mean-impute with the training median like ``GamSpec.expand``).

    Input contract: ``x = [linear design vector (expand_matrix order,
    len n_lin)] + [raw gam column values, one per smoother]``."""
    from h2o3_tpu.models.gam import cr_matrices

    if any(s.kind != 0 for s in model.specs):
        raise ValueError("GAM POJO export covers cubic-regression "
                         "smoothers (bs=0) only")
    p = model.params
    if p.family in ("multinomial", "ordinal"):
        raise ValueError("GAM POJO export supports single-eta families "
                         "only")
    info = model.data_info
    n_lin = info.n_coefs
    beta_full = np.asarray(model.beta, dtype=np.float64)
    beta, icpt = beta_full[:-1], float(beta_full[-1])
    link = p.actual_link()
    if link == "identity":
        inv = "mu = eta;"
    elif link == "log":
        inv = "mu = exp(eta);"
    elif link == "logit":
        inv = "mu = 1.0 / (1.0 + exp(-eta));"
    else:
        raise ValueError(f"unsupported link {link!r} for GAM POJO export")

    chunks = [f"""/* GENERATED standalone GAM scorer — do not edit.
 * Model: {model.key} (family={p.family})
 * x: double[{n_lin + len(model.specs)}] = linear design vector
 * ({", ".join(info.coef_names)}) then raw gam values
 * ({", ".join(s.column for s in model.specs)})
 */
#include <math.h>

"""]
    chunks.append(_c_arr("beta", beta, "double", _c_float))
    chunks.append(f"static const double intercept = {_c_float(icpt)};\n")
    for ci, s in enumerate(model.specs):
        K = len(s.knots)
        D, B = cr_matrices(np.asarray(s.knots))
        binvd = np.linalg.solve(B, D)
        chunks.append(_c_arr(f"knots_{ci}", s.knots, "double", _c_float))
        chunks.append(_c_arr(f"binvd_{ci}", binvd.ravel(), "double",
                             _c_float))
        chunks.append(_c_arr(f"zt_{ci}", np.ascontiguousarray(
            s.Z.T).ravel(), "double", _c_float))
        chunks.append(
            f"static const double nafill_{ci} = "
            f"{_c_float(s.na_fill)};\n")
        chunks.append(f"""
static void gamify_{ci}(double xv, double *out) {{
  const int K = {K};
  double basis[{K}];
  if (isnan(xv)) xv = nafill_{ci};
  if (xv < knots_{ci}[0]) xv = knots_{ci}[0];
  if (xv > knots_{ci}[K-1]) xv = knots_{ci}[K-1];
  int j = 0;
  while (j < K - 2 && xv >= knots_{ci}[j+1]) j++;
  double hj = knots_{ci}[j+1] - knots_{ci}[j];
  double tm = knots_{ci}[j+1] - xv, tp = xv - knots_{ci}[j];
  double cmj = (tm*tm*tm/hj - tm*hj) / 6.0;
  double cpj = (tp*tp*tp/hj - tp*hj) / 6.0;
  for (int i = 0; i < K; i++) {{
    double v = 0.0;
    if (j > 0) v += binvd_{ci}[(j-1)*K + i] * cmj;
    if (j < K - 2) v += binvd_{ci}[j*K + i] * cpj;
    basis[i] = v;
  }}
  basis[j] += tm / hj;
  basis[j+1] += tp / hj;
  for (int r = 0; r < K - 1; r++) {{
    double acc = 0.0;
    for (int i = 0; i < K; i++) acc += zt_{ci}[r*K + i] * basis[i];
    out[r] = acc;
  }}
}}
""")
    body = [f"""
void score(const double *x, double *out) {{
  double eta = intercept;
  for (int i = 0; i < {n_lin}; i++) eta += beta[i] * x[i];
"""]
    off = n_lin
    for ci, s in enumerate(model.specs):
        kz = len(s.knots) - 1
        body.append(f"""  {{
    double g[{kz}];
    gamify_{ci}(x[{n_lin + ci}], g);
    for (int r = 0; r < {kz}; r++) eta += beta[{off} + r] * g[r];
  }}
""")
        off += kz
    body.append(f"""  double mu;
  {inv}
""")
    if model.nclasses == 2:
        body.append("  out[1] = 1.0 - mu; out[2] = mu; "
                    "out[0] = (mu >= 0.5) ? 1.0 : 0.0;\n}\n")
    else:
        body.append("  out[0] = mu;\n}\n")
    chunks.extend(body)
    return "".join(chunks)


def pojo_source(model, lang: str = "c") -> str:
    from h2o3_tpu.models.tree.common import TreeModelBase

    if getattr(model.params, "offset_column", None):
        # the in-framework predict adds the scoring frame's offset to the
        # margin/eta; an exported scorer has no offset input — refusing
        # beats silently dropping the term
        raise ValueError(
            "POJO export does not support offset_column models")
    if isinstance(model, TreeModelBase):
        if model.booster is None:
            raise ValueError("model has no trained trees")
        return tree_pojo_c(model) if lang == "c" else tree_pojo_java(model)
    if getattr(model, "algo_name", "") == "gam":
        if lang != "c":
            raise ValueError("GAM POJO is emitted as C only")
        return gam_pojo_c(model)
    if hasattr(model, "coefficients") and isinstance(
            getattr(model, "coefficients", None), dict):
        if lang != "c":
            raise ValueError("GLM POJO is emitted as C only")
        if getattr(model.params, "family", "") == "multinomial":
            if getattr(model, "beta_multi", None) is None:
                raise ValueError("multinomial GLM has no trained betas")
            return glm_multinomial_pojo_c(model)
        if getattr(model.params, "family", "") == "ordinal" \
                or getattr(model, "beta_std", None) is None:
            raise ValueError(
                "GLM POJO export does not cover the ordinal family "
                "(thresholded cumulative etas)")
        return glm_pojo_c(model)
    raise ValueError(
        f"POJO export supports tree models and GLM, not {model.algo_name}")
