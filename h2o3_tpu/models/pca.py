"""PCA / SVD — Gram-based decomposition with a sharded Gram pass.

Reference: ``hex/pca/PCA.java`` (pca_method=GramSVD default: distributed Gram
then local SVD) and ``hex/svd/SVD.java`` (distributed power iteration).

TPU-native: the [D,D] Gram is one sharded ``XᵀX`` matmul (psum implicit);
the small host-side eigendecomposition mirrors the reference's driver-side
SVD of the Gram. Scores/u are one more sharded matmul. Power iteration is
pointless below D≈10⁴, which covers the reference's use cases.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.models.data_info import build_data_info, expand_matrix
from h2o3_tpu.models.framework import Model, ModelBuilder, ModelParameters
from h2o3_tpu.parallel.mesh import default_mesh, row_mask, shard_rows


@dataclass
class PCAParameters(ModelParameters):
    k: int = 2
    transform: str = "standardize"  # none|standardize|demean|descale
    pca_method: str = "gram_svd"
    use_all_factor_levels: bool = False


@jax.jit
def _gram_xx(X, mask):
    Xm = X * mask[:, None]
    return Xm.T @ Xm, jnp.sum(mask)


class PCAModel(Model):
    algo_name = "pca"

    def __init__(self, params, data_info):
        super().__init__(params, data_info)
        self.eigenvectors: Optional[np.ndarray] = None  # [D, k]
        #: expanded-space demean/descale statistics from training (None
        #: for standardize/none, which expand_matrix handles itself)
        self.transform_sub: Optional[np.ndarray] = None
        self.transform_mul: Optional[np.ndarray] = None
        self.std_deviation: Optional[np.ndarray] = None  # [k]
        self.pve: Optional[np.ndarray] = None  # proportion of variance explained
        self.cum_pve: Optional[np.ndarray] = None

    @property
    def is_classifier(self) -> bool:
        return False

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        X, _ = expand_matrix(self.data_info, frame, dtype=np.float32)
        # demean/descale are applied OUTSIDE expand_matrix at fit time;
        # scoring must re-apply the TRAINING statistics or the projection
        # is computed in a different space than the eigenvectors
        if self.transform_sub is not None:
            X = X - self.transform_sub
        if self.transform_mul is not None:
            X = X * self.transform_mul
        return X @ self.eigenvectors

    def predict(self, frame: Frame) -> Frame:
        scores = self._predict_raw(frame)
        return Frame(
            [Column(f"PC{i + 1}", scores[:, i].astype(np.float64), ColType.NUM)
             for i in range(scores.shape[1])]
        )

    def model_performance(self, frame: Frame):
        return {"std_deviation": self.std_deviation, "pve": self.pve, "cum_pve": self.cum_pve}


class PCA(ModelBuilder):
    algo_name = "pca"

    def __init__(self, params: Optional[PCAParameters] = None, **kw) -> None:
        super().__init__(params or PCAParameters(**kw))

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> PCAModel:
        p: PCAParameters = self.params
        standardize = p.transform == "standardize"
        info = build_data_info(
            frame, y=None, ignored=p.ignored_columns,
            standardize=standardize, use_all_factor_levels=p.use_all_factor_levels,
        )
        X, _ = expand_matrix(info, frame, dtype=np.float32)
        # transform semantics (hex/DataInfo TransformType): STANDARDIZE is done
        # inside expand_matrix; DEMEAN centers only; DESCALE scales only
        tsub = tmul = None
        if p.transform == "demean":
            tsub = X.mean(axis=0, keepdims=True)
            X = X - tsub
        elif p.transform == "descale":
            sd = X.std(axis=0, ddof=1, keepdims=True)
            tmul = 1.0 / np.where(sd > 0, sd, 1.0)
            X = X * tmul
        n, D = X.shape
        k = min(p.k, D)
        model = PCAModel(p, info)
        model.transform_sub = tsub
        model.transform_mul = tmul

        mesh = default_mesh()
        from h2o3_tpu.frame import devcache as _devcache

        Xd = _devcache.cached(
            "pca_x", _devcache.frame_token(frame),
            (p.transform, p.use_all_factor_levels, tuple(p.ignored_columns)),
            mesh,
            lambda: shard_rows(X, mesh)[0],
            frame_key=getattr(frame, "key", None),
        )
        maskd = row_mask(n, Xd.shape[0], mesh).astype(jnp.float32)
        G, cnt = jax.device_get(_gram_xx(Xd, maskd))
        G = np.asarray(G, dtype=np.float64) / max(n - 1, 1)

        evals, evecs = np.linalg.eigh(G)
        order = np.argsort(evals)[::-1]
        evals = np.maximum(evals[order][:k], 0.0)
        evecs = evecs[:, order][:, :k]
        # deterministic sign: largest-|loading| component positive
        for i in range(k):
            j = np.argmax(np.abs(evecs[:, i]))
            if evecs[j, i] < 0:
                evecs[:, i] = -evecs[:, i]
        total_var = np.trace(G)
        model.eigenvectors = evecs.astype(np.float32)
        model.std_deviation = np.sqrt(evals)
        model.pve = evals / max(total_var, 1e-300)
        model.cum_pve = np.cumsum(model.pve)
        model.training_metrics = model.model_performance(frame)
        return model


@dataclass
class SVDParameters(PCAParameters):
    nv: int = 2  # number of right singular vectors


class SVDModel(PCAModel):
    algo_name = "svd"

    def __init__(self, params, data_info):
        super().__init__(params, data_info)
        self.d: Optional[np.ndarray] = None  # singular values
        self.v: Optional[np.ndarray] = None  # [D, nv]


class SVD(ModelBuilder):
    """Distributed SVD via the Gram eigendecomposition (hex/svd/SVD.java)."""

    algo_name = "svd"

    def __init__(self, params: Optional[SVDParameters] = None, **kw) -> None:
        super().__init__(params or SVDParameters(**kw))

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> SVDModel:
        p: SVDParameters = self.params
        inner = PCA(PCAParameters(
            k=max(p.nv, p.k), transform=p.transform,
            ignored_columns=p.ignored_columns,
            use_all_factor_levels=p.use_all_factor_levels,
        ))
        pca_model = inner._fit(frame)
        model = SVDModel(p, pca_model.data_info)
        X, _ = expand_matrix(pca_model.data_info, frame, dtype=np.float32)
        n = X.shape[0]
        model.v = pca_model.eigenvectors
        model.d = pca_model.std_deviation * np.sqrt(max(n - 1, 1))
        model.eigenvectors = pca_model.eigenvectors
        model.transform_sub = pca_model.transform_sub
        model.transform_mul = pca_model.transform_mul
        model.std_deviation = pca_model.std_deviation
        model.pve = pca_model.pve
        model.cum_pve = pca_model.cum_pve
        model.training_metrics = {"d": model.d}
        return model
