"""RuleFit — rules from a tree ensemble + sparse linear model.

Reference: ``hex/rulefit/RuleFit.java:34`` — (1) train tree ensembles (GBM or
DRF) over a ladder of depths (min_rule_length..max_rule_length); (2) extract
every root→node path as a binary rule (``hex/rulefit/RuleExtractor.java``);
(3) deduplicate rules; (4) fit a LASSO GLM on the rule indicator matrix
(+ optionally the winsorized linear terms, model_type rules_and_linear);
(5) report rule importance = |coef| (reference sorts by absolute coefficient).

TPU-native: rule evaluation is a batched comparison against the booster's
quantile-bin codes — every rule is (feature, bin-threshold, direction)
conjunctions, so the [N, R] indicator matrix is dense elementwise ops on the
already-quantized int codes; the LASSO runs on the GLM core's sharded-Gram
ADMM path.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.models.data_info import build_data_info, response_vector
from h2o3_tpu.models.framework import Model, ModelBuilder, ModelParameters
from h2o3_tpu.models.glm import GLM, GLMModel


@dataclass
class RuleCondition:
    feature: int  # design-matrix column index
    feature_name: str
    threshold: float  # raw-space threshold from the bin edges
    go_left: bool  # True: x < threshold (NA follows na_left)
    na_left: bool

    def describe(self) -> str:
        op = "<" if self.go_left else ">="
        return f"({self.feature_name} {op} {self.threshold:.6g})"


@dataclass
class Rule:
    conditions: List[RuleCondition]
    support: float = 0.0
    coefficient: float = 0.0

    def key(self) -> Tuple:
        return tuple(
            (c.feature, round(c.threshold, 10), c.go_left, c.na_left)
            for c in sorted(self.conditions, key=lambda c: (c.feature, c.threshold))
        )

    def describe(self) -> str:
        return " & ".join(c.describe() for c in self.conditions)

    def evaluate(self, X: np.ndarray) -> np.ndarray:
        m = np.ones(X.shape[0], dtype=bool)
        for c in self.conditions:
            x = X[:, c.feature]
            na = np.isnan(x)
            left = np.where(na, c.na_left, x < c.threshold)
            m &= left if c.go_left else ~left
        return m


@dataclass
class RuleFitParameters(ModelParameters):
    algorithm: str = "gbm"  # gbm | drf
    min_rule_length: int = 3
    max_rule_length: int = 3
    max_num_rules: int = -1  # -1: keep what LASSO selects
    model_type: str = "rules_and_linear"  # rules_and_linear | rules | linear
    rule_generation_ntrees: int = 50
    distribution: str = "auto"
    lambda_: Optional[float] = None  # None: auto from lambda search


class RuleFitModel(Model):
    algo_name = "rulefit"

    def __init__(self, params, data_info):
        super().__init__(params, data_info)
        self.rules: List[Rule] = []
        self.linear_names: List[str] = []
        self.glm: Optional[GLMModel] = None
        self.rule_importance: List[Dict] = []
        self.winsor: Optional[Tuple[np.ndarray, np.ndarray]] = None

    def _rule_frame(self, frame: Frame) -> Frame:
        from h2o3_tpu.models.tree.common import tree_matrix

        X = tree_matrix(self.data_info, frame)
        cols = []
        for ri, r in enumerate(self.rules):
            cols.append(Column(f"rule_{ri}", r.evaluate(X).astype(np.float64), ColType.NUM))
        if self.params.model_type in ("rules_and_linear", "linear"):
            lo, hi = self.winsor
            for j, nm in enumerate(self.linear_names):
                x = np.clip(X[:, j], lo[j], hi[j])
                x = np.where(np.isnan(X[:, j]), np.nan, x)
                cols.append(Column(f"linear_{nm}", x, ColType.NUM))
        return Frame(cols)

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        return self.glm._predict_raw(self._rule_frame(frame))


class RuleFit(ModelBuilder):
    algo_name = "rulefit"

    def __init__(self, params: Optional[RuleFitParameters] = None, **kw) -> None:
        super().__init__(params or RuleFitParameters(**kw))

    def _validate(self, frame: Frame) -> None:
        super()._validate(frame)
        p: RuleFitParameters = self.params
        if p.min_rule_length > p.max_rule_length:
            raise ValueError("min_rule_length must be <= max_rule_length")
        if p.model_type not in ("rules_and_linear", "rules", "linear"):
            raise ValueError(f"bad model_type {p.model_type!r}")

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> RuleFitModel:
        from h2o3_tpu.models.tree.common import tree_data_info, tree_matrix

        p: RuleFitParameters = self.params
        info = tree_data_info(frame, p.response_column, ignored=p.ignored_columns)
        model = RuleFitModel(p, info)
        X = tree_matrix(info, frame)
        nclasses = len(info.response_domain) if info.response_domain else 1

        rules: List[Rule] = []
        if p.model_type != "linear":
            ntrees_per_depth = max(p.rule_generation_ntrees // max(
                p.max_rule_length - p.min_rule_length + 1, 1), 1)
            for depth in range(p.min_rule_length, p.max_rule_length + 1):
                ens = self._tree_ensemble(frame, depth, ntrees_per_depth)
                rules += _extract_rules(ens, info)
            # dedupe + drop degenerate support
            seen = {}
            for r in rules:
                sup = r.evaluate(X).mean()
                if 0.005 < sup < 0.995:
                    r.support = float(sup)
                    seen.setdefault(r.key(), r)
            rules = list(seen.values())
        model.rules = rules
        model.linear_names = list(info.coef_names)
        lo = np.nanquantile(X, 0.025, axis=0)
        hi = np.nanquantile(X, 0.975, axis=0)
        model.winsor = (lo, hi)

        rf = model._rule_frame(frame)
        rf = rf.add_column(frame.col(p.response_column).copy())
        family = (
            "gaussian" if nclasses == 1 else ("binomial" if nclasses == 2 else "multinomial")
        )
        lam = p.lambda_ if p.lambda_ is not None else _auto_lambda(rf, p)
        model.glm = GLM(
            response_column=p.response_column, family=family, alpha=1.0,
            lambda_=lam, seed=p.actual_seed(),
        ).train(rf)

        # importance table (reference: sorted |coef|, with rule language)
        imp = []
        coefs = model.glm.coefficients
        for ri, r in enumerate(model.rules):
            c = coefs.get(f"rule_{ri}", 0.0)
            r.coefficient = c
            if c != 0.0:
                imp.append({"variable": f"rule_{ri}", "coefficient": c,
                            "rule": r.describe(), "support": r.support})
        for nm in model.linear_names:
            c = coefs.get(f"linear_{nm}", 0.0)
            if c != 0.0:
                imp.append({"variable": f"linear_{nm}", "coefficient": c,
                            "rule": f"linear({nm})", "support": 1.0})
        imp.sort(key=lambda d: -abs(d["coefficient"]))
        if p.max_num_rules > 0:
            imp = imp[: p.max_num_rules]
        model.rule_importance = imp

        model.training_metrics = model.model_performance(frame)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model

    def _tree_ensemble(self, frame: Frame, depth: int, ntrees: int):
        p: RuleFitParameters = self.params
        kw = dict(
            response_column=p.response_column, ntrees=ntrees, max_depth=depth,
            seed=p.actual_seed() + depth, ignored_columns=list(p.ignored_columns),
        )
        if p.algorithm == "gbm":
            from h2o3_tpu.models.tree.gbm import GBM

            return GBM(**kw).train(frame)
        from h2o3_tpu.models.tree.drf import DRF

        return DRF(**kw).train(frame)


def _extract_rules(tree_model, info) -> List[Rule]:
    """Every root→node path of every tree becomes a rule
    (hex/rulefit/RuleExtractor.java walks all nodes, not just leaves)."""
    out: List[Rule] = []
    booster = tree_model.booster
    edges = booster.trees_per_class[0].edges
    names = info.coef_names
    for trees in booster.trees_per_class:
        for t in range(trees.ntrees):
            feat, sb = trees.feat[t], trees.split_bin[t]
            dl, sp = trees.default_left[t], trees.is_split[t]

            def walk(node: int, conds: List[RuleCondition]):
                if conds:
                    out.append(Rule(list(conds)))
                if node >= len(sp) or not sp[node]:
                    return
                f = int(feat[node])
                b = int(sb[node])
                # split sends bin<=b left, i.e. x <= edges[b]; b == nbins-1 is
                # the all-non-NA-left (NA-only right) split -> threshold +inf
                thr = float(edges[f][b]) if b < edges.shape[1] else float("inf")
                na_l = bool(dl[node])
                left = RuleCondition(f, names[f] if f < len(names) else f"C{f}", thr, True, na_l)
                right = RuleCondition(f, names[f] if f < len(names) else f"C{f}", thr, False, na_l)
                walk(2 * node + 1, conds + [left])
                walk(2 * node + 2, conds + [right])

            walk(0, [])
    return out


def _auto_lambda(rf: Frame, p: RuleFitParameters) -> float:
    """Small fixed fraction of lambda_max (the reference runs a lambda
    search; a single conservative point keeps the fit sparse + fast)."""
    n = rf.nrows
    return 1.0 / max(np.sqrt(n), 1.0) * 0.5
