"""IsolationForest — anomaly detection by random isolation trees.

Reference: ``hex/tree/isofor/IsolationForest.java`` — trees grown on small
row samples with uniformly random (feature, threshold) splits; anomaly score
normalizes the mean path length by c(sample_size)
(score = 2^(-E[path]/c(n)), Liu et al.).

TPU-native split of labor: tree BUILDING runs on the host — each tree sees
only ``sample_size`` (default 256) rows, so building is microseconds and
data-independent of N. SCORING is the N-scale work and runs as the same
jitted heap-walk used by the boosting trees (leaf value = path length), over
row-sharded data. This mirrors the reference's economics where build cost is
bounded by the sample, not the frame.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from functools import partial

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.models.framework import Model, ModelBuilder, ModelParameters
from h2o3_tpu.models.tree.common import tree_data_info, tree_matrix


@partial(jax.jit, static_argnames=("max_depth",))
def _path_lengths(X, feat, thresh, is_split, path_len, max_depth: int):
    """Mean isolation path length per row over all trees (scan over [T, M])."""

    def one_tree(carry, tree):
        tf, tt, tsp, tpl = tree
        idx = jnp.zeros(X.shape[0], dtype=jnp.int32)

        def body(_, idx):
            f = tf[idx]
            v = jnp.take_along_axis(X, f[:, None], axis=1)[:, 0]
            go_left = ~(v > tt[idx])  # NaN compares False -> routes left
            nxt = 2 * idx + jnp.where(go_left, 1, 2)
            return jnp.where(tsp[idx], nxt, idx)

        idx = jax.lax.fori_loop(0, max_depth, body, idx)
        return carry + tpl[idx], None

    total, _ = jax.lax.scan(
        one_tree, jnp.zeros(X.shape[0], jnp.float32), (feat, thresh, is_split, path_len)
    )
    return total / feat.shape[0]


@dataclass
class IsolationForestParameters(ModelParameters):
    ntrees: int = 50
    sample_size: int = 256
    max_depth: int = 8  # reference default: ceil(log2(sample_size))
    mtries: int = -1


def _c_factor(n: float) -> float:
    """Average unsuccessful BST search length c(n) (Liu et al.; reference scoring)."""
    if n <= 1:
        return 0.0
    return 2.0 * (np.log(n - 1.0) + 0.5772156649) - 2.0 * (n - 1.0) / n


class IsolationForestModel(Model):
    algo_name = "isolationforest"

    def __init__(self, params, data_info):
        super().__init__(params, data_info)
        self.trees = None  # stacked arrays [T, M] like the booster
        self.max_depth = params.max_depth
        self._cn = 1.0

    @property
    def is_classifier(self) -> bool:
        return False

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        """Anomaly score in [0,1]; higher = more anomalous."""
        X = tree_matrix(self.data_info, frame)
        feat, thresh, is_split, path_len = self.trees
        mean_path = np.asarray(jax.device_get(_path_lengths(
            jnp.asarray(X), jnp.asarray(feat), jnp.asarray(thresh),
            jnp.asarray(is_split), jnp.asarray(path_len), self.max_depth,
        )), dtype=np.float64)
        return np.power(2.0, -mean_path / max(self._cn, 1e-9))

    def model_performance(self, frame: Frame):
        s = self._predict_raw(frame)
        return {"mean_score": float(s.mean()), "max_score": float(s.max())}

    def predict(self, frame: Frame) -> Frame:
        s = self._predict_raw(frame)
        return Frame([Column("anomaly_score", s, ColType.NUM)])


class IsolationForest(ModelBuilder):
    algo_name = "isolationforest"

    def __init__(self, params: Optional[IsolationForestParameters] = None, **kw) -> None:
        super().__init__(params or IsolationForestParameters(**kw))

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> IsolationForestModel:
        p: IsolationForestParameters = self.params
        info = tree_data_info(frame, y=None, ignored=p.ignored_columns)
        X = tree_matrix(info, frame)
        n, F = X.shape
        model = IsolationForestModel(p, info)
        rng = np.random.default_rng(p.actual_seed())
        sample = min(p.sample_size, n)
        model._cn = _c_factor(sample)
        M = 2 ** (p.max_depth + 1) - 1

        feats = np.zeros((p.ntrees, M), np.int32)
        threshs = np.zeros((p.ntrees, M), np.float32)
        splits = np.zeros((p.ntrees, M), bool)
        plens = np.zeros((p.ntrees, M), np.float32)

        for t in range(p.ntrees):
            rows = rng.choice(n, sample, replace=False)
            self._grow(X[rows], 0, 0, rng, feats[t], threshs[t], splits[t], plens[t], p.max_depth)
        model.trees = (feats, threshs, splits, plens)
        # ONE full-data scoring pass serves both the training metrics and
        # the summed-path-length extremes the reference stores on the
        # output for MOJO scoring ((max - sum) / (max - min),
        # IsolationForestMojoModel.unifyPreds)
        mean_path = np.asarray(jax.device_get(_path_lengths(
            jnp.asarray(X), jnp.asarray(feats), jnp.asarray(threshs),
            jnp.asarray(splits), jnp.asarray(plens), p.max_depth)),
            dtype=np.float64)
        total = mean_path * p.ntrees
        model.min_path_total = float(total.min())
        model.max_path_total = float(total.max())
        score = np.power(2.0, -mean_path / max(model._cn, 1e-9))
        model.training_metrics = {
            "mean_score": float(score.mean()), "max_score": float(score.max())
        }
        return model

    def _grow(self, Xn, node, depth, rng, feat, thresh, is_split, path_len, max_depth) -> None:
        m = len(Xn)
        if depth >= max_depth or m <= 1:
            path_len[node] = depth + _c_factor(m)
            return
        # random feature with spread (from an mtries subset when set),
        # random threshold in (min, max)
        F = Xn.shape[1]
        mtries = self.params.mtries
        cand = rng.choice(F, min(mtries, F), replace=False) if mtries > 0 else None
        for _ in range(F):
            f = rng.choice(cand) if cand is not None else rng.integers(F)
            col = Xn[:, f]
            ok = ~np.isnan(col)
            if ok.any() and np.nanmin(col) < np.nanmax(col):
                break
        else:
            path_len[node] = depth + _c_factor(m)
            return
        lo, hi = np.nanmin(col), np.nanmax(col)
        if not (hi > lo):
            path_len[node] = depth + _c_factor(m)
            return
        cut = rng.uniform(lo, hi)
        go_left = ~(col > cut)  # NaN routes left
        feat[node] = f
        thresh[node] = cut
        is_split[node] = True
        self._grow(Xn[go_left], 2 * node + 1, depth + 1, rng, feat, thresh, is_split, path_len, max_depth)
        self._grow(Xn[~go_left], 2 * node + 2, depth + 1, rng, feat, thresh, is_split, path_len, max_depth)
