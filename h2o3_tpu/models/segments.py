"""Segment models: train one model per data segment.

Reference: ``hex/segments/SegmentModelsBuilder.java`` /
``SegmentModels.java`` — split the training frame by the distinct value
combinations of the segment columns (or an explicit segments frame), train
an independent model per segment, collect results (model key / status /
errors / warnings) into a frame (``SegmentModelsUtils``; exposed over REST
as ``segment_models_as_frame``).

TPU-native: segments are independent jitted programs; failures are
captured per-segment like the reference (a failed segment records its
exception, the rest proceed).
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import replace
from typing import Any, Dict, List, Optional, Sequence, Type

import numpy as np

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.keyed import DKV
from h2o3_tpu.models.framework import Model, ModelBuilder


class SegmentModels:
    """Result container (hex/segments/SegmentModels.java)."""

    def __init__(self, key: Optional[str] = None) -> None:
        self.key = key or DKV.make_key("segment_models")
        self.segments: List[Dict[str, Any]] = []  # segment col -> value
        self.models: List[Optional[Model]] = []
        self.errors: List[Optional[str]] = []
        self.run_times: List[float] = []
        DKV.put(self.key, self)

    def as_frame(self) -> Frame:
        """segment columns + model_id/status/errors/warnings
        (SegmentModelsUtils.toFrame / AstSegmentModelsAsFrame)."""
        if not self.segments:
            return Frame([])
        cols: List[Column] = []
        for name in self.segments[0]:
            vals = [str(s[name]) for s in self.segments]
            dom = sorted(set(vals))
            codes = np.array([dom.index(v) for v in vals], dtype=np.int32)
            cols.append(Column(name, codes, ColType.CAT, dom))
        status = ["succeeded" if e is None else "failed" for e in self.errors]
        sdom = sorted(set(status))
        cols.append(
            Column(
                "status",
                np.array([sdom.index(s) for s in status], dtype=np.int32),
                ColType.CAT,
                sdom,
            )
        )
        mids = [m.key if m is not None else "" for m in self.models]
        mdom = list(dict.fromkeys(mids))
        cols.append(
            Column(
                "model",
                np.array([mdom.index(v) for v in mids], dtype=np.int32),
                ColType.CAT,
                mdom,
            )
        )
        errs = [e or "" for e in self.errors]
        edom = list(dict.fromkeys(errs))
        cols.append(
            Column(
                "errors",
                np.array([edom.index(v) for v in errs], dtype=np.int32),
                ColType.CAT,
                edom,
            )
        )
        return Frame(cols)

    def model_for(self, **segment_values: Any) -> Optional[Model]:
        for seg, m in zip(self.segments, self.models):
            if all(str(seg.get(k)) == str(v) for k, v in segment_values.items()):
                return m
        return None

    def __repr__(self) -> str:
        ok = sum(e is None for e in self.errors)
        return f"<SegmentModels {self.key}: {ok}/{len(self.segments)} succeeded>"


class SegmentModelsBuilder:
    """hex/segments/SegmentModelsBuilder.java: enumerate segments, train each."""

    def __init__(
        self,
        builder_cls: Type[ModelBuilder],
        params: Any,
        segment_columns: Sequence[str],
        parallelism: int = 1,
    ) -> None:
        if not segment_columns:
            raise ValueError("segment_columns must be non-empty")
        self.builder_cls = builder_cls
        self.params = params
        self.segment_columns = list(segment_columns)
        self.parallelism = max(1, int(parallelism))

    def _enumerate_segments(self, frame: Frame) -> List[Dict[str, Any]]:
        cols = []
        for name in self.segment_columns:
            c = frame.col(name)
            if c.type is ColType.CAT:
                cols.append([c.domain[v] if v >= 0 else None for v in c.data])
            else:
                # canonicalize NaN -> None: float('nan') != float('nan'), so
                # raw NaNs would each become their own bogus segment
                cols.append(
                    [None if np.isnan(v) else float(v) for v in c.numeric_view()]
                )
        seen: Dict[tuple, None] = {}
        for row in zip(*cols):
            seen.setdefault(row, None)
        return [dict(zip(self.segment_columns, k)) for k in seen]

    def _segment_mask(self, frame: Frame, seg: Dict[str, Any]) -> np.ndarray:
        mask = np.ones(frame.nrows, dtype=bool)
        for name, val in seg.items():
            c = frame.col(name)
            if c.type is ColType.CAT:
                if val is None:
                    mask &= c.data < 0
                else:
                    mask &= c.data == c.domain.index(val)
            else:
                x = c.numeric_view()
                mask &= np.isnan(x) if val is None else (x == val)
        return mask

    def train(self, frame: Frame) -> SegmentModels:
        segments = self._enumerate_segments(frame)
        result = SegmentModels()

        def build(seg: Dict[str, Any]):
            sub = frame.rows(self._segment_mask(frame, seg))
            p = replace(
                self.params,
                ignored_columns=list(
                    set(self.params.ignored_columns) | set(self.segment_columns)
                ),
            )
            return self.builder_cls(p).train(sub)

        def run_one(seg):
            t0 = time.time()
            try:
                m = build(seg)
                return seg, m, None, time.time() - t0
            except Exception as e:
                return seg, None, f"{type(e).__name__}: {e}", time.time() - t0

        if self.parallelism == 1:
            outs = [run_one(s) for s in segments]
        else:
            with ThreadPoolExecutor(max_workers=self.parallelism) as pool:
                outs = list(pool.map(run_one, segments))
        for seg, m, err, dt in outs:
            result.segments.append(seg)
            result.models.append(m)
            result.errors.append(err)
            result.run_times.append(dt)
        return result
