"""Target (mean) encoding as a first-class Model + preprocessor.

Reference: ``h2o-extensions/target-encoder`` —
``ai/h2o/targetencoding/TargetEncoder.java`` (builder),
``TargetEncoderModel.java`` (transform with data-leakage handling), and
``TargetEncoderHelper.java:237-247`` (blended value
``P = λ(n)·posterior + (1-λ(n))·prior`` with
``λ(n) = 1 / (1 + exp((k - n) / f))``, k = inflection point, f = smoothing).

TPU-native: encoding tables are tiny (per-level numerator/denominator pairs
computed by one segment-sum over the sharded codes); the transform is a pure
gather + elementwise blend, which XLA fuses.  KFold / LOO leakage handling
subtracts the held-out contribution from the gathered aggregates instead of
re-aggregating per fold.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.models.data_info import build_data_info
from h2o3_tpu.models.framework import (
    Model,
    ModelBuilder,
    ModelParameters,
    fold_assignment,
)


@dataclass
class TargetEncoderParameters(ModelParameters):
    columns_to_encode: Optional[List[str]] = None  # default: all categoricals
    keep_original_categorical_columns: bool = True
    data_leakage_handling: str = "none"  # none | leave_one_out | k_fold
    blending: bool = False
    inflection_point: float = 10.0  # k in λ(n)
    smoothing: float = 20.0  # f in λ(n)
    noise: float = 0.01  # magnitude of uniform noise added on transform


class TargetEncoderModel(Model):
    algo_name = "targetencoder"

    def __init__(self, params: TargetEncoderParameters, data_info) -> None:
        super().__init__(params, data_info)
        # per encoded column: (domain, numerator[L], denominator[L])
        self.encodings: Dict[str, Tuple[List[str], np.ndarray, np.ndarray]] = {}
        self.prior_mean: float = np.nan
        self.fold: Optional[np.ndarray] = None  # training fold ids (k_fold)
        self.train_key: Optional[str] = None

    @property
    def is_classifier(self) -> bool:
        return False

    def _blend(self, num: np.ndarray, den: np.ndarray) -> np.ndarray:
        """Posterior/prior shrinkage (TargetEncoderHelper.java:246-247)."""
        p = self.params
        post = np.where(den > 0, num / np.maximum(den, 1e-300), self.prior_mean)
        if not p.blending:
            return np.where(den > 0, post, self.prior_mean)
        lam = 1.0 / (1.0 + np.exp((p.inflection_point - den) / max(p.smoothing, 1e-12)))
        return lam * post + (1.0 - lam) * self.prior_mean

    def transform(
        self,
        frame: Frame,
        as_training: bool = False,
        noise: Optional[float] = None,
    ) -> Frame:
        """Append ``<col>_te`` columns.  ``as_training=True`` applies the
        configured leakage handling (LOO subtracts the row's own target;
        k-fold uses out-of-fold aggregates) — reference
        ``TargetEncoderModel.transformTraining``."""
        p = self.params
        rng = np.random.default_rng(p.actual_seed())
        # noise is a training-time regularizer only; inference transforms must
        # be deterministic (reference applies noise in transformTraining)
        if noise is None:
            noise = p.noise if as_training else 0.0
        y = None
        if as_training and p.data_leakage_handling != "none":
            from h2o3_tpu.models.data_info import response_vector

            y = response_vector(self.data_info, frame)
        out = frame
        for name, (dom, num, den) in self.encodings.items():
            if name not in frame.names:
                continue
            col = frame.col(name)
            codes = _codes_on_domain(col, dom)
            g_num, g_den = num[np.clip(codes, 0, None)], den[np.clip(codes, 0, None)]
            if as_training and y is not None:
                ok = ~np.isnan(y)
                if p.data_leakage_handling == "leave_one_out":
                    g_num = g_num - np.where(ok, y, 0.0)
                    g_den = g_den - ok.astype(np.float64)
                elif p.data_leakage_handling == "k_fold" and self.fold is not None:
                    # subtract this fold's per-level aggregates
                    for f in np.unique(self.fold):
                        in_f = self.fold == f
                        fn, fd = _aggregate(codes[in_f], y[in_f], len(dom))
                        g_num[in_f] -= fn[np.clip(codes[in_f], 0, None)]
                        g_den[in_f] -= fd[np.clip(codes[in_f], 0, None)]
            enc = self._blend(g_num, g_den)
            enc = np.where(codes >= 0, enc, self.prior_mean)
            if noise:
                enc = enc + rng.uniform(-noise, noise, size=enc.shape)
            out = out.add_column(Column(f"{name}_te", enc, ColType.NUM))
        if not p.keep_original_categorical_columns:
            out = out.drop([n for n in self.encodings if n in out.names])
        return out

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        raise NotImplementedError("TargetEncoderModel transforms frames; use .transform()")


class TargetEncoder(ModelBuilder):
    algo_name = "targetencoder"

    def __init__(self, params: Optional[TargetEncoderParameters] = None, **kw) -> None:
        super().__init__(params or TargetEncoderParameters(**kw))

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> TargetEncoderModel:
        from h2o3_tpu.models.data_info import response_vector

        p: TargetEncoderParameters = self.params
        if not p.response_column:
            raise ValueError("target encoding needs a response_column")
        info = build_data_info(frame, p.response_column, ignored=p.ignored_columns,
                               standardize=False)
        model = TargetEncoderModel(p, info)
        y = response_vector(info, frame)
        if info.response_domain is not None:
            if len(info.response_domain) != 2:
                raise ValueError("target encoding supports binary or numeric targets")
            # binomial: encode P(y == positive class), positive = last level
            y = (y == len(info.response_domain) - 1).astype(np.float64)
        ok = ~np.isnan(y)
        model.prior_mean = float(y[ok].mean()) if ok.any() else 0.0
        cols = p.columns_to_encode or [
            c.name for c in frame.columns
            if c.type is ColType.CAT and c.name != p.response_column
        ]
        for name in cols:
            col = frame.col(name)
            if col.type is not ColType.CAT:
                col = col.as_factor()
            dom = list(col.domain)
            num, den = _aggregate(col.data, np.where(ok, y, np.nan), len(dom))
            model.encodings[name] = (dom, num, den)
        if p.data_leakage_handling == "k_fold":
            model.fold = fold_assignment(
                n=frame.nrows,
                nfolds=max(p.nfolds, 2) if p.nfolds else 5,
                scheme="auto" if p.fold_assignment == "auto" else p.fold_assignment,
                seed=p.actual_seed(),
                fold_column=frame.col(p.fold_column).numeric_view().astype(np.int64)
                if p.fold_column else None,
            )
        return model


def _aggregate(codes: np.ndarray, y: np.ndarray, n_levels: int) -> Tuple[np.ndarray, np.ndarray]:
    """Per-level (Σy, count) ignoring NA codes/targets."""
    ok = (codes >= 0) & ~np.isnan(y)
    num = np.bincount(codes[ok], weights=y[ok], minlength=n_levels).astype(np.float64)
    den = np.bincount(codes[ok], minlength=n_levels).astype(np.float64)
    return num, den


def _codes_on_domain(col: Column, domain: List[str]) -> np.ndarray:
    from h2o3_tpu.models.data_info import _align_codes

    return _align_codes(col, domain)
