"""DataInfo — the shared design-matrix adapter.

Reference: ``hex/DataInfo.java:23`` — one class every algorithm shares for
turning a Frame into a modeling matrix: categorical encodings (one-hot /
enum-limited), numeric standardization, NA imputation, and the bookkeeping to
map coefficients back to column names and to adapt a test frame to the
training layout (``hex/Model.java`` adaptTestForTrain).

TPU-native: the product is a dense [N, P] device-shardable matrix — dense
one-hot blocks are MXU-friendly; sparse row extraction (the reference's CSR
path) is deliberately absent because TPUs want dense tiles. Standardization
coefficients and categorical domains are recorded so predict-time frames are
adapted identically (unseen levels -> NA treatment, missing columns -> mean).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from h2o3_tpu.frame.frame import ColType, Column, Frame


@dataclass
class DataInfo:
    predictor_names: List[str]
    response_name: Optional[str]
    use_all_factor_levels: bool
    standardize: bool
    missing_values_handling: str  # "mean_imputation" | "skip"
    # per input column metadata, in predictor order: ("num", mean, sd) or ("cat", domain)
    num_means: Dict[str, float] = field(default_factory=dict)
    num_sds: Dict[str, float] = field(default_factory=dict)
    cat_domains: Dict[str, List[str]] = field(default_factory=dict)
    cat_mode: Dict[str, int] = field(default_factory=dict)  # most frequent level for NA imputation
    coef_names: List[str] = field(default_factory=list)
    response_domain: Optional[List[str]] = None

    @property
    def n_coefs(self) -> int:
        return len(self.coef_names)


def build_data_info(
    frame: Frame,
    y: Optional[str],
    ignored: Sequence[str] = (),
    use_all_factor_levels: bool = False,
    standardize: bool = True,
    missing_values_handling: str = "mean_imputation",
) -> DataInfo:
    """Learn the design-matrix layout from the training frame."""
    skip = set(ignored) | ({y} if y else set())
    preds = [
        c.name
        for c in frame.columns
        if c.name not in skip and c.type in (ColType.NUM, ColType.TIME, ColType.CAT)
    ]
    info = DataInfo(
        predictor_names=preds,
        response_name=y,
        use_all_factor_levels=use_all_factor_levels,
        standardize=standardize,
        missing_values_handling=missing_values_handling,
    )
    coef_names: List[str] = []
    for name in preds:
        col = frame.col(name)
        if col.type is ColType.CAT:
            dom = list(col.domain)
            info.cat_domains[name] = dom
            counts = np.bincount(col.data[col.data >= 0], minlength=len(dom))
            info.cat_mode[name] = int(counts.argmax()) if counts.size else 0
            start = 0 if use_all_factor_levels else 1
            coef_names += [f"{name}.{lv}" for lv in dom[start:]]
        else:
            r = col.rollups
            info.num_means[name] = float(r.mean) if r.mean == r.mean else 0.0
            sd = float(r.sigma)
            info.num_sds[name] = sd if sd > 0 else 1.0
            coef_names.append(name)
    info.coef_names = coef_names
    if y is not None:
        ycol = frame.col(y)
        info.response_domain = list(ycol.domain) if ycol.type is ColType.CAT else None
    return info


def expand_matrix(
    info: DataInfo,
    frame: Frame,
    dtype=np.float32,
) -> Tuple[np.ndarray, np.ndarray]:
    """Frame -> dense [N, P] design matrix per the learned layout.

    Returns (X, skip_mask) where skip_mask marks rows dropped under
    missing_values_handling="skip". Unseen test-time categorical levels map to
    NA (the reference's adaptTestForTrain) and then follow
    missing_values_handling like any other NA: mode-imputed under
    mean_imputation, row-dropped under skip. Numerics are NA-imputed with the
    training mean and standardized with the training mean/sd.
    """
    n = frame.nrows
    blocks: List[np.ndarray] = []
    any_na = np.zeros(n, dtype=bool)
    for name in info.predictor_names:
        if name in info.cat_domains:
            dom = info.cat_domains[name]
            codes = _align_codes(frame.col(name), dom)
            na = codes < 0
            any_na |= na
            if info.missing_values_handling == "mean_imputation":
                codes = np.where(na, info.cat_mode[name], codes)
            start = 0 if info.use_all_factor_levels else 1
            width = len(dom) - start
            block = np.zeros((n, width), dtype=dtype)
            sel = codes - start
            rows = np.nonzero(sel >= 0)[0]
            block[rows, sel[rows]] = 1.0
            blocks.append(block)
        else:
            x = frame.col(name).numeric_view().astype(np.float64)
            na = np.isnan(x)
            any_na |= na
            x = np.where(na, info.num_means[name], x)
            if info.standardize:
                x = (x - info.num_means[name]) / info.num_sds[name]
            blocks.append(x.astype(dtype)[:, None])
    X = np.concatenate(blocks, axis=1) if blocks else np.zeros((n, 0), dtype=dtype)
    skip = any_na if info.missing_values_handling == "skip" else np.zeros(n, dtype=bool)
    return X, skip


def response_vector(info: DataInfo, frame: Frame) -> np.ndarray:
    """Response as float64: class codes for CAT (aligned to training domain)."""
    assert info.response_name is not None
    col = frame.col(info.response_name)
    if info.response_domain is not None:
        codes = _align_codes(col, info.response_domain)
        return np.where(codes >= 0, codes.astype(np.float64), np.nan)
    return col.numeric_view().astype(np.float64)


def destandardize_coefs(
    info: DataInfo, beta_std: np.ndarray, intercept_std: float
) -> Tuple[np.ndarray, float]:
    """Map standardized-space coefficients back to the input scale
    (reference: GLMModel beta vs beta_std, hex/glm/GLMModel.java)."""
    beta = beta_std.copy().astype(np.float64)
    intercept = float(intercept_std)
    i = 0
    for name in info.predictor_names:
        if name in info.cat_domains:
            start = 0 if info.use_all_factor_levels else 1
            i += len(info.cat_domains[name]) - start
        else:
            if info.standardize:
                beta[i] = beta_std[i] / info.num_sds[name]
                intercept -= beta[i] * info.num_means[name]
            i += 1
    return beta, intercept


def _align_codes(col: Column, domain: List[str]) -> np.ndarray:
    """Remap a column's codes onto a target domain; unseen levels -> -1
    (reference: Model.adaptTestForTrain domain mapping)."""
    if col.type is not ColType.CAT:
        col = col.as_factor()
    if col.domain == domain:
        return col.data
    index = {lv: i for i, lv in enumerate(domain)}
    remap = np.array([index.get(lv, -1) for lv in col.domain], dtype=np.int32)
    return np.where(col.data >= 0, remap[np.clip(col.data, 0, None)], -1).astype(np.int32)
