"""Hyperparameter grid search.

Reference: ``hex/grid/GridSearch.java`` (875 LoC driver), walkers in
``hex/grid/HyperSpaceWalker.java:187-190,381`` — CartesianWalker (full
product) and RandomDiscreteValueWalker (seeded sampling without replacement
under ``RandomDiscreteValueSearchCriteria``: max_models, max_runtime_secs,
and ScoreKeeper-style early stopping over the sequence of finished models),
grid persistence (``hex/grid/Grid.java``, export_grid/import_grid REST).

TPU-native: each hyperparameter combo is an independent jitted training
program; optional thread parallelism overlaps host-side work while XLA
serializes device programs (the reference's ``parallelism`` arg /
``ParallelModelBuilder``). Model failures are recorded per-combo, not
fatal (GridSearch.java's failed-params tracking).
"""

from __future__ import annotations

import hashlib
import itertools
import json
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Sequence, Tuple, Type

import numpy as np

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.keyed import DKV
from h2o3_tpu.models.framework import Model, ModelBuilder


def cell_key(hp: Dict[str, Any]) -> str:
    """Canonical identity of one grid cell: the sorted-JSON hyperparameter
    combo.  The recovery snapshot's consumed-multiset, the distributed
    search plane, and per-cell seeding all agree on this encoding."""
    return json.dumps(hp, sort_keys=True, default=str)


def cell_seed(search_seed: Optional[int], key: str) -> Optional[int]:
    """Per-cell builder seed derived from ``(search_seed, canonical cell
    key)``.  Position-independent by construction: reordering the walk,
    fanning cells across cluster members, or resuming a snapshot can
    never re-seed a cell — the prerequisite for the bit-identical
    leaderboard contract (cluster/search.py)."""
    if search_seed is None or search_seed == -1:
        return None
    digest = hashlib.md5(f"{int(search_seed)}|{key}".encode()).digest()
    return int.from_bytes(digest[:8], "big") & 0x7FFFFFFF


@dataclass
class SearchCriteria:
    """hex/grid/HyperSpaceSearchCriteria.java."""

    strategy: str = "Cartesian"  # Cartesian | RandomDiscrete
    max_models: int = 0  # 0 = unlimited
    max_runtime_secs: float = 0.0  # 0 = unlimited
    seed: int = -1
    stopping_rounds: int = 0
    stopping_metric: str = "auto"
    stopping_tolerance: float = 1e-3


def _default_metric(model: Model) -> Tuple[str, bool]:
    """(metric name, larger_is_better) like ScoreKeeper.StoppingMetric auto."""
    if not model.is_classifier:
        return "rmse", False
    if model.nclasses == 2:
        return "auc", True
    return "logloss", False


def metric_value(model: Model, name: str = "auto") -> Tuple[float, bool]:
    """Pull a metric from CV metrics if present, else validation, else training."""
    mm = (
        model.cross_validation_metrics
        or model.validation_metrics
        or model.training_metrics
    )
    auto_name, larger = _default_metric(model)
    if name in (None, "", "auto"):
        name = auto_name
    else:
        larger = name.lower() in ("auc", "pr_auc", "gini", "r2", "accuracy", "f1")
    v = getattr(mm, name.lower(), np.nan)
    return float(v), larger


class Grid:
    """Search result container (hex/grid/Grid.java)."""

    def __init__(self, grid_id: Optional[str] = None) -> None:
        self.grid_id = grid_id or DKV.make_key("grid")
        self.models: List[Model] = []
        self.hyper_params: List[Dict[str, Any]] = []
        self.failures: List[Tuple[Dict[str, Any], str]] = []
        self.runtime_secs: float = 0.0
        DKV.put(self.grid_id, self)

    def get_grid(
        self, sort_by: str = "auto", decreasing: Optional[bool] = None
    ) -> "Grid":
        """Return a new Grid view with models sorted by a metric."""
        if not self.models:
            return self
        vals = []
        for m in self.models:
            v, larger = metric_value(m, sort_by)
            vals.append(v)
        if decreasing is None:
            decreasing = larger
        order = np.argsort(vals)
        if decreasing:
            order = order[::-1]
        # NaNs always last
        order = sorted(order, key=lambda i: (np.isnan(vals[i]),))
        g = Grid.__new__(Grid)
        g.grid_id = self.grid_id
        g.models = [self.models[i] for i in order]
        g.hyper_params = [self.hyper_params[i] for i in order]
        g.failures = self.failures
        g.runtime_secs = self.runtime_secs
        return g

    @property
    def model_ids(self) -> List[str]:
        return [m.key for m in self.models]

    def summary_table(self, sort_by: str = "auto") -> List[Dict[str, Any]]:
        g = self.get_grid(sort_by)
        out = []
        for hp, m in zip(g.hyper_params, g.models):
            v, _ = metric_value(m, sort_by)
            out.append({**hp, "model_id": m.key, "metric": v})
        return out

    # -- persistence (export_grid / import_grid REST routes) ----------------
    def save(self, path: str) -> str:
        """Pickle-free export on the allowlisted object-tree format
        (models/persist.py) — same container the binary model routes use
        (hex/grid/Grid.java importBinary/exportBinary)."""
        from h2o3_tpu.models.persist import save_model

        return save_model(self, path)

    @staticmethod
    def load(path: str) -> "Grid":
        from h2o3_tpu.models.persist import load_model

        # decode first, mutate the DKV only after the type check passes
        g = load_model(path, register=False)
        if not isinstance(g, Grid):
            raise ValueError(f"{path!r} is not a grid export")
        DKV.put(g.grid_id, g)
        for m in g.models:  # member models become addressable again too
            DKV.put(m.key, m)
        return g

    def __repr__(self) -> str:
        return (
            f"<Grid {self.grid_id}: {len(self.models)} models, "
            f"{len(self.failures)} failures>"
        )


def _cartesian(hyper: Dict[str, Sequence[Any]]):
    keys = sorted(hyper.keys())
    for combo in itertools.product(*(hyper[k] for k in keys)):
        yield dict(zip(keys, combo))


def _random_discrete(hyper: Dict[str, Sequence[Any]], seed: int):
    """Seeded sampling without replacement over the full product space
    (HyperSpaceWalker.java:381 RandomDiscreteValueWalker).

    Lazy rejection sampling — never materializes the product space, which
    can be astronomically large (10 params x 10 values = 1e10 combos)."""
    keys = sorted(hyper.keys())
    sizes = [len(hyper[k]) for k in keys]
    total = int(np.prod(sizes)) if sizes else 0
    rng = np.random.default_rng(None if seed in (-1, None) else seed)
    seen = set()
    while len(seen) < total:
        flat = int(rng.integers(total))
        if flat in seen:
            continue
        seen.add(flat)
        combo = {}
        for k, sz in zip(keys, sizes):
            combo[k] = hyper[k][int(flat % sz)]
            flat //= sz
        yield combo


class GridSearch:
    """Driver (hex/grid/GridSearch.java).

    ``builder_cls`` is a ModelBuilder subclass; ``params`` its base
    parameters object; ``hyper_params`` maps parameter names to candidate
    value lists.
    """

    def __init__(
        self,
        builder_cls: Type[ModelBuilder],
        params: Any,
        hyper_params: Dict[str, Sequence[Any]],
        search_criteria: Optional[SearchCriteria] = None,
        parallelism: int = 1,
        recovery_dir: Optional[str] = None,
    ) -> None:
        self.builder_cls = builder_cls
        self.params = params
        self.hyper_params = dict(hyper_params)
        self.criteria = search_criteria or SearchCriteria()
        self.parallelism = max(1, int(parallelism))
        #: auto-recovery snapshots (hex/faulttolerance/Recovery.java):
        #: frames + params at start, every finished model as it completes
        self.recovery_dir = recovery_dir
        if recovery_dir and self.parallelism > 1:
            raise ValueError("recovery_dir requires parallelism=1")
        if (
            recovery_dir
            and self.criteria.strategy.lower() in ("randomdiscrete", "random_discrete")
            and self.criteria.seed in (-1, None)
        ):
            # resume replays the walker; an unseeded random walk would skip
            # DIFFERENT combos than the ones already trained
            raise ValueError(
                "recovery_dir with RandomDiscrete requires an explicit "
                "search_criteria.seed (resume must replay the same walk)"
            )
        for k in self.hyper_params:
            if not hasattr(params, k):
                raise ValueError(f"unknown hyperparameter {k!r} for {builder_cls.__name__}")

    # -- determinism: canonical per-cell seeds -------------------------------
    def _search_seed(self) -> Optional[int]:
        """The seed the whole search derives per-cell seeds from: the
        search criteria's seed, else the base params' seed, else None."""
        if self.criteria.seed not in (-1, None):
            return int(self.criteria.seed)
        base = getattr(self.params, "seed", -1)
        if base not in (-1, None):
            return int(base)
        return None

    def _cell_params(self, hp: Dict[str, Any]):
        """Final builder params for one cell.  When a seed is in play it
        derives from ``(search_seed, canonical cell key)`` — NOT from the
        walk position — so dispatch and completion order can never
        re-seed a cell.  A seed the user put in the hyper grid itself is
        an explicit per-cell choice and is honored as-is."""
        p = replace(self.params, **hp)
        if "seed" in hp or not hasattr(p, "seed"):
            return p
        derived = cell_seed(self._search_seed(), cell_key(hp))
        if derived is None:
            return p
        return replace(p, seed=derived)

    def train(
        self,
        frame: Frame,
        valid: Optional[Frame] = None,
        job=None,
    ) -> Grid:
        rec = None
        if self.recovery_dir:
            from h2o3_tpu.recovery import Recovery

            rec = Recovery(self.recovery_dir)
            frames = {"train": frame}
            if valid is not None:
                frames["valid"] = valid
            rec.on_start(
                "grid",
                {
                    "algo": self.builder_cls.algo_name,
                    "params": self.params,
                    "hyper_params": self.hyper_params,
                    "criteria": self.criteria,
                },
                frames,
            )
        grid = self._execute(Grid(), frame, valid, rec, scores=[], job=job)
        if rec is not None and not (job is not None and job.stop_requested):
            # a cancelled recoverable search keeps its snapshot so
            # auto_recover can finish it without retraining done cells
            rec.on_done()
        return grid

    def _execute(
        self,
        grid: Grid,
        frame: Frame,
        valid: Optional[Frame],
        rec,
        scores: List[float],
        init_larger: bool = True,
        consumed: Optional[List[Dict[str, Any]]] = None,
        job=None,
    ) -> Grid:
        """Run the walk locally, or fan cells across the cloud when a
        multi-member cloud is live (cluster/search.py) — same recorded
        model sequence either way."""
        cloud = None
        try:
            from h2o3_tpu.cluster import search as _search

            cloud = _search.search_cloud()
        except Exception:
            cloud = None
        if cloud is not None:
            from h2o3_tpu.cluster.search import distributed_grid_search

            return distributed_grid_search(
                self, grid, frame, valid, cloud, rec=rec, job=job,
                scores=scores, init_larger=init_larger, consumed=consumed)
        return self._run(
            grid, frame, valid, rec, scores=scores,
            init_larger=init_larger, consumed=consumed, job=job)

    def n_cells_hint(self) -> int:
        """Planned cell count (for progress fractions): the hyper product
        capped by max_models.  Early stopping can finish under it."""
        sizes = [len(v) for v in self.hyper_params.values()]
        total = int(np.prod(sizes)) if sizes else 0
        if self.criteria.max_models:
            total = min(total, self.criteria.max_models)
        return total

    @staticmethod
    def _resume(rec, state, frames, models) -> Grid:
        """Continue an interrupted search: finished models are NOT
        re-trained; the walker replays deterministically and skips them
        (Recovery.autoRecover best-effort continuation)."""
        from h2o3_tpu.api.registry import algo_map

        bcls, _ = algo_map()[state["algo"]]
        gs = GridSearch(
            bcls, state["params"], state["hyper_params"],
            search_criteria=state["criteria"],
        )
        grid = Grid()
        meta = rec._read_meta()
        scores: List[float] = []
        larger = True
        # models is aligned 1:1 with meta["models"] (None = snapshot file
        # went missing): survivors pair with THEIR OWN hp entry, and a
        # missing combo stays unconsumed so the walker retrains exactly it
        consumed: List[Dict[str, Any]] = []
        for entry, m in zip(meta["models"], models):
            if m is None:
                continue
            DKV.put(m.key, m)
            grid.models.append(m)
            grid.hyper_params.append(entry.get("hp", {}))
            v, larger = metric_value(m, gs.criteria.stopping_metric)
            scores.append(v)
            consumed.append(entry.get("hp", {}))
        failures = meta.get("failures", [])
        for f_ in failures:
            grid.failures.append((f_.get("hp", {}), f_.get("error", "?")))
            # failed combos consumed walker positions too
            consumed.append(f_.get("hp", {}))
        grid = gs._execute(
            grid, frames["train"], frames.get("valid"), rec,
            consumed=consumed, scores=scores,
            init_larger=larger,
        )
        rec.on_done()
        return grid

    def _walk(self, consumed: Optional[List[Dict[str, Any]]] = None):
        """The canonical cell walk: strategy order, minus each combo a
        resume snapshot already consumed (multiset semantics, by value —
        positional skipping misaligns when a snapshot file vanished)."""
        c = self.criteria
        if c.strategy.lower() == "cartesian":
            walker = _cartesian(self.hyper_params)
        elif c.strategy.lower() in ("randomdiscrete", "random_discrete"):
            walker = _random_discrete(self.hyper_params, c.seed)
        else:
            raise ValueError(f"unknown strategy {c.strategy!r}")
        if consumed:
            from collections import Counter

            budget = Counter(cell_key(hp) for hp in consumed)

            def _filtered(inner):
                for hp in inner:
                    k = cell_key(hp)
                    if budget.get(k):
                        budget[k] -= 1
                        continue
                    yield hp

            walker = _filtered(walker)
        return walker

    def _stopped_early(self, scores: List[float], direction) -> bool:
        """ScoreKeeper.stopEarly over the finished-model metric sequence:
        stop when the best of the last `stopping_rounds` models does not
        improve on the best before them by stopping_tolerance (relative).
        Shared verbatim by the local loop and the distributed recorder so
        both cut the walk at exactly the same cell."""
        c = self.criteria
        k = c.stopping_rounds
        if not k or len(scores) < 2 * k:
            return False
        arr = np.array(scores, dtype=np.float64)
        if not direction["larger"]:
            arr = -arr
        recent = np.max(arr[-k:])
        before = np.max(arr[:-k])
        improvement = (recent - before) / max(abs(before), 1e-12)
        return improvement < c.stopping_tolerance

    def _run(
        self,
        grid: Grid,
        frame: Frame,
        valid: Optional[Frame],
        rec,
        skip: int = 0,
        scores: List[float] = None,
        init_larger: bool = True,
        consumed: Optional[List[Dict[str, Any]]] = None,
        job=None,
    ) -> Grid:
        scores = [] if scores is None else scores
        c = self.criteria
        t0 = time.time()
        walker = self._walk(consumed)
        if skip:
            walker = itertools.islice(walker, skip, None)
        # metric direction comes from the first finished model (set in
        # _record); on resume the preloaded scores arrive with their
        # recovered direction so early stopping never compares inverted
        direction = {"larger": init_larger}
        n_hint = self.n_cells_hint()

        def build_one(hp: Dict[str, Any]):
            return self.builder_cls(self._cell_params(hp)).train(frame, valid)

        def out_of_budget() -> bool:
            if c.max_models and len(grid.models) >= c.max_models:
                return True
            if c.max_runtime_secs and time.time() - t0 >= c.max_runtime_secs:
                return True
            return False

        def stopped_early() -> bool:
            return self._stopped_early(scores, direction)

        if self.parallelism == 1:
            for hp in walker:
                if out_of_budget() or stopped_early():
                    break
                if job is not None and job.stop_requested:
                    break
                self._build_into(grid, hp, build_one, scores, c, direction, rec=rec)
                if job is not None and n_hint:
                    job.update(
                        (len(grid.models) + len(grid.failures)) / n_hint)
        else:
            with ThreadPoolExecutor(max_workers=self.parallelism) as pool:
                pending = []
                for hp in walker:
                    if out_of_budget() or stopped_early():
                        break
                    pending.append((hp, pool.submit(build_one, hp)))
                    if len(pending) >= self.parallelism:
                        self._drain(grid, pending, scores, c, direction)
                        pending = []
                self._drain(grid, pending, scores, c, direction)

        grid.runtime_secs = time.time() - t0
        return grid

    def _record(self, grid, hp, m, scores, c, direction) -> None:
        grid.models.append(m)
        grid.hyper_params.append(hp)
        v, larger = metric_value(m, c.stopping_metric)
        scores.append(v)
        direction["larger"] = larger

    def _build_into(self, grid, hp, build_one, scores, c, direction, rec=None) -> None:
        try:
            m = build_one(hp)
            self._record(grid, hp, m, scores, c, direction)
            if rec is not None:  # durable progress: finished work survives a crash
                rec.on_model(m, info={"hp": hp})
        except Exception as e:  # failed combos are recorded, not fatal
            msg = f"{type(e).__name__}: {e}"
            grid.failures.append((hp, msg))
            if rec is not None:  # failures consume walker positions too
                rec.on_failure({"hp": hp, "error": msg})

    def _drain(self, grid, pending, scores, c, direction) -> None:
        for hp, fut in pending:
            try:
                m = fut.result()
                self._record(grid, hp, m, scores, c, direction)
            except Exception as e:
                grid.failures.append((hp, f"{type(e).__name__}: {e}"))
