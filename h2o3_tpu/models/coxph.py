"""CoxPH — proportional hazards with Efron/Breslow tie handling.

Reference: ``hex/coxph/CoxPH.java:29`` — Newton-Raphson on the partial
log-likelihood; per-iteration statistics (risk-set sums S0 = Σ w·exp(η),
S1 = Σ w·x·exp(η), S2 = Σ w·xxᵀ·exp(η), accumulated over distinct event
times) are an MRTask in the reference (``CoxPHTask``); ties via Efron
(default) or Breslow approximation; outputs coef/exp(coef)/se(coef)/z,
log-likelihood, concordance.

TPU-native: rows are sorted by stop time once; the risk-set sums become
reverse cumulative sums over the sorted, row-sharded arrays (S2 as a
[N, P, P] einsum contracted per event time), one jitted pass per Newton
iteration.  The P×P Newton solve runs on the host like the reference's
driver-side solve.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.data_info import build_data_info, expand_matrix, response_vector
from h2o3_tpu.models.framework import Model, ModelBuilder, ModelParameters


@dataclass
class CoxPHParameters(ModelParameters):
    start_column: Optional[str] = None
    stop_column: Optional[str] = None  # event time (required)
    ties: str = "efron"  # efron | breslow
    max_iterations: int = 20
    lre_min: float = 9.0  # log-relative-error convergence (reference default)


@partial(jax.jit, static_argnames=("efron", "truncated"))
def _partial_stats(
    Xs, ws, ds, group_start, group_size, efron: bool, beta,
    Xe=None, we=None, m=None, truncated: bool = False,
):
    """Gradient / Hessian / loglik of the partial likelihood.

    Inputs are sorted by descending stop time so the risk set at event time t
    is a *prefix*; rows of one tied event time form a contiguous group.
    Xs [N,P], ws [N] weights, ds [N] event indicator, group_start/size [G]
    aligned to event-time groups (G = distinct event times with >=1 event).

    Left truncation (counting-process (start, stop] data, reference
    ``hex/coxph/CoxPH.java`` start_column): a row is at risk at event time t
    iff start < t <= stop.  The prefix over descending-stop rows counts
    {stop >= t}; ``Xe/we`` are the same rows sorted by DESCENDING start and
    ``m[g]`` = #rows with start >= t_g, whose aggregates are subtracted.
    """
    eta = Xs @ beta
    r = ws * jnp.exp(eta)  # risk contributions
    rx = r[:, None] * Xs  # [N,P]
    # prefix sums -> risk-set aggregates at each group boundary
    c0 = jnp.cumsum(r)
    c1 = jnp.cumsum(rx, axis=0)
    cxx = jnp.cumsum(rx[:, :, None] * Xs[:, None, :], axis=0)  # [N,P,P]

    end = group_start + group_size - 1  # inclusive last index of the tie group
    S0 = c0[end]
    S1 = c1[end]
    S2 = cxx[end]

    if truncated:
        re = we * jnp.exp(Xe @ beta)
        rex = re[:, None] * Xe
        a0 = jnp.cumsum(re)
        a1 = jnp.cumsum(rex, axis=0)
        a2 = jnp.cumsum(rex[:, :, None] * Xe[:, None, :], axis=0)
        has = m > 0
        idx = jnp.maximum(m - 1, 0)
        S0 = S0 - jnp.where(has, a0[idx], 0.0)
        S1 = S1 - jnp.where(has[:, None], a1[idx], 0.0)
        S2 = S2 - jnp.where(has[:, None, None], a2[idx], 0.0)

    # per-group sums over *events* (tied deaths) in the group
    ev_w = ws * ds
    e0g = jnp.cumsum(ev_w)
    e1g = jnp.cumsum(ev_w[:, None] * Xs, axis=0)
    # tied-event risk sums (for Efron): Σ_{events in group} w exp(η), x-weighted
    er = r * ds
    er0 = jnp.cumsum(er)
    er1 = jnp.cumsum(er[:, None] * Xs, axis=0)
    er2 = jnp.cumsum((er[:, None] * Xs)[:, :, None] * Xs[:, None, :], axis=0)

    def group_range(c, s, e):
        cond = (s > 0).reshape(s.shape + (1,) * (c.ndim - 1))
        first = jnp.where(cond, c[jnp.maximum(s - 1, 0)], jnp.zeros_like(c[e]))
        return c[e] - first

    d_cnt = group_range(jnp.cumsum(ds), group_start, end)  # events per group
    wd = group_range(e0g, group_start, end)  # Σ w over events
    xd = group_range(e1g, group_start, end)  # Σ w·x over events
    R0 = group_range(er0, group_start, end)
    R1 = group_range(er1, group_start, end)
    R2 = group_range(er2, group_start, end)

    P = Xs.shape[1]

    def one_group(carry, g):
        ll, grad, hess = carry
        s0, s1, s2, r0, r1, r2, dc, w_d, x_d = g
        if efron:
            # Efron: average out the tied events' own contribution
            dmax = dc.astype(jnp.int32)

            def body(l, acc):
                ll_a, g_a, h_a = acc
                frac = l.astype(s0.dtype) / jnp.maximum(dc, 1.0)
                s0l = s0 - frac * r0
                s1l = s1 - frac * r1
                s2l = s2 - frac * r2
                avg_w = w_d / jnp.maximum(dc, 1.0)
                ll_a = ll_a - avg_w * jnp.log(jnp.maximum(s0l, 1e-300))
                g_a = g_a - avg_w * s1l / jnp.maximum(s0l, 1e-300)
                h_a = h_a - avg_w * (
                    s2l / jnp.maximum(s0l, 1e-300)
                    - (s1l[:, None] * s1l[None, :]) / jnp.maximum(s0l * s0l, 1e-300)
                )
                return ll_a, g_a, h_a

            ll_g, g_g, h_g = jax.lax.fori_loop(
                0, dmax, body,
                (jnp.zeros(()), jnp.zeros(P), jnp.zeros((P, P))),
            )
        else:
            ll_g = -w_d * jnp.log(jnp.maximum(s0, 1e-300))
            g_g = -w_d * s1 / jnp.maximum(s0, 1e-300)
            h_g = -w_d * (
                s2 / jnp.maximum(s0, 1e-300)
                - (s1[:, None] * s1[None, :]) / jnp.maximum(s0 * s0, 1e-300)
            )
        # events' own linear term
        ll = ll + x_d @ beta + ll_g
        grad = grad + x_d + g_g
        hess = hess + h_g
        return (ll, grad, hess), None

    init = (jnp.zeros(()), jnp.zeros(P), jnp.zeros((P, P)))
    (ll, grad, hess), _ = jax.lax.scan(
        one_group, init, (S0, S1, S2, R0, R1, R2, d_cnt, wd, xd)
    )
    return ll, grad, hess


class CoxPHModel(Model):
    algo_name = "coxph"

    def __init__(self, params, data_info):
        super().__init__(params, data_info)
        self.coefficients: Dict[str, float] = {}
        self.exp_coef: Dict[str, float] = {}
        self.std_errors: Dict[str, float] = {}
        self.z_values: Dict[str, float] = {}
        self.beta: Optional[np.ndarray] = None
        self.loglik: float = np.nan
        self.loglik_null: float = np.nan
        self.concordance: float = np.nan
        self.n_events: int = 0
        self.iterations: int = 0
        self.feature_means: Optional[np.ndarray] = None

    @property
    def is_classifier(self) -> bool:
        return False

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        """Linear predictor (log relative hazard), centered like the reference."""
        X, _ = expand_matrix(self.data_info, frame, dtype=np.float64)
        return (X - self.feature_means) @ self.beta


class CoxPH(ModelBuilder):

    SUPPORTED_COMMON = frozenset({"weights_column"})
    algo_name = "coxph"

    def __init__(self, params: Optional[CoxPHParameters] = None, **kw) -> None:
        super().__init__(params or CoxPHParameters(**kw))

    def _validate(self, frame: Frame) -> None:
        super()._validate(frame)
        p: CoxPHParameters = self.params
        if not p.stop_column:
            raise ValueError("CoxPH requires stop_column (event time)")
        if not p.response_column:
            raise ValueError("CoxPH requires response_column (event indicator)")
        if p.ties not in ("efron", "breslow"):
            raise ValueError("ties must be 'efron' or 'breslow'")

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> CoxPHModel:
        p: CoxPHParameters = self.params
        info = build_data_info(
            frame, y=p.response_column,
            ignored=list(p.ignored_columns) + [p.stop_column]
            + ([p.start_column] if p.start_column else []),
            standardize=False,
        )
        model = CoxPHModel(p, info)
        X, skip = expand_matrix(info, frame, dtype=np.float64)
        y = response_vector(info, frame)  # event indicator 0/1
        t = frame.col(p.stop_column).numeric_view().astype(np.float64)
        w = (
            frame.col(p.weights_column).numeric_view().astype(np.float64)
            if p.weights_column else np.ones(frame.nrows)
        )
        s = (
            frame.col(p.start_column).numeric_view().astype(np.float64)
            if p.start_column else None
        )
        keep = ~(skip | np.isnan(y) | np.isnan(t))
        if s is not None:
            keep &= ~np.isnan(s) & (s < t)  # (start, stop] intervals only
        X, y, t, w = X[keep], y[keep], t[keep], w[keep]
        if s is not None:
            s = s[keep]
        n, P = X.shape
        model.n_events = int((y > 0).sum())

        # center covariates (reference centers at the weighted mean)
        mean = (w[:, None] * X).sum(0) / w.sum()
        model.feature_means = mean
        Xc = X - mean

        # sort by descending time; within a time, events first (risk set is a prefix)
        order = np.lexsort((1 - y, -t))
        Xs, ws, ds, ts = Xc[order], w[order], y[order], t[order]

        # event-time groups: contiguous runs of equal time containing >= 1 event
        starts, sizes = [], []
        i = 0
        while i < n:
            j = i
            while j < n and ts[j] == ts[i]:
                j += 1
            # group = the event rows at this time (they sort first within the run)
            n_ev = int(ds[i:j].sum())
            if n_ev > 0:
                starts.append(i)
                sizes.append(j - i)
            i = j
        gs = jnp.asarray(np.array(starts, dtype=np.int32))
        gz = jnp.asarray(np.array(sizes, dtype=np.int32))
        Xj, wj, dj = jnp.asarray(Xs), jnp.asarray(ws), jnp.asarray(ds)
        efron = p.ties == "efron"

        # left truncation: rows sorted by descending start; m[g] = #rows whose
        # start >= the group's event time (they have not yet entered the study)
        trunc_kw = dict(truncated=False)
        if s is not None:
            e_order = np.argsort(-s, kind="stable")
            s_desc = s[e_order]
            group_times = ts[np.array(starts, dtype=np.int64)] if starts else np.array([])
            # count of start >= t_g in the descending start array
            m = np.searchsorted(-s_desc, -group_times, side="right").astype(np.int32)
            trunc_kw = dict(
                Xe=jnp.asarray(Xc[e_order]),
                we=jnp.asarray(w[e_order]),
                m=jnp.asarray(m),
                truncated=True,
            )

        beta = np.zeros(P)
        ll0 = None
        prev_ll = -np.inf
        for it in range(p.max_iterations):
            ll, grad, hess = _partial_stats(Xj, wj, dj, gs, gz, efron, jnp.asarray(beta), **trunc_kw)
            ll = float(ll)
            g = np.asarray(grad)
            H = np.asarray(hess)  # negative definite (d²ll/dβ²)
            if ll0 is None:
                ll0 = ll
            model.iterations = it + 1
            try:
                delta = np.linalg.solve(H - 1e-10 * np.eye(P), g)
            except np.linalg.LinAlgError:
                delta = np.linalg.lstsq(H, g, rcond=None)[0]
            beta = beta - delta
            lre = -np.log10(max(abs(ll - prev_ll) / max(abs(ll), 1e-300), 1e-300))
            prev_ll = ll
            if lre >= p.lre_min:
                break

        ll, grad, hess = _partial_stats(Xj, wj, dj, gs, gz, efron, jnp.asarray(beta), **trunc_kw)
        model.loglik = float(ll)
        model.loglik_null = float(ll0) if ll0 is not None else np.nan
        H = np.asarray(hess)
        cov = np.linalg.pinv(-H)
        se = np.sqrt(np.maximum(np.diag(cov), 0.0))
        model.beta = beta
        names = info.coef_names
        model.coefficients = dict(zip(names, beta.tolist()))
        model.exp_coef = {k: float(np.exp(v)) for k, v in model.coefficients.items()}
        model.std_errors = dict(zip(names, se.tolist()))
        model.z_values = {
            k: (model.coefficients[k] / s if s > 0 else np.nan)
            for k, s in zip(names, se.tolist())
        }
        model.concordance = _concordance(t, y, Xc @ beta, start=s)
        return model


def _concordance(
    t: np.ndarray, d: np.ndarray, risk: np.ndarray,
    start: Optional[np.ndarray] = None,
) -> float:
    """Harrell's C: P(higher risk → earlier event) over comparable pairs
    (subsampled for large n — metric only, not part of the fit).

    With left truncation, a pair (i event, j) is comparable only if j was
    at risk at t_i, i.e. start_j < t_i."""
    n = len(t)
    if n > 4000:
        rng = np.random.default_rng(0)
        idx = rng.choice(n, 4000, replace=False)
        t, d, risk = t[idx], d[idx], risk[idx]
        if start is not None:
            start = start[idx]
        n = 4000
    conc = ties = comp = 0.0
    ev = np.nonzero(d > 0)[0]
    for i in ev:
        later = (t > t[i]) | ((t == t[i]) & (d == 0))
        if start is not None:
            later &= start < t[i]
        comp += later.sum()
        conc += (risk[i] > risk[later]).sum()
        ties += (risk[i] == risk[later]).sum()
    return float((conc + 0.5 * ties) / comp) if comp > 0 else np.nan
