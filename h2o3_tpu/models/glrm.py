"""GLRM — generalized low-rank models via alternating proximal gradient.

Reference: ``hex/glrm/GLRM.java:52`` — factorize A ≈ X·Y (X: [N,k] row
factors, Y: [k,P] archetypes) under per-entry losses (quadratic, absolute,
huber, poisson, logistic; categorical one-hot for factors) and regularizers
(none/l1/l2/non-negative) on X and Y, minimized by alternating updates with
step-halving line search (``GLRM.java`` updateX/updateY), NAs skipped in the
loss.

TPU-native: both half-steps are jitted dense matmul gradients on the
row-sharded A and X (grad_X = M ⊙ (XY - A) Yᵀ — MXU work, psum implicit for
the replicated Y gradient), followed by elementwise prox maps; no per-entry
loops.  The line search keeps the reference's monotone-objective guarantee.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.models.data_info import build_data_info, expand_matrix
from h2o3_tpu.models.framework import Model, ModelBuilder, ModelParameters

LOSSES = ("quadratic", "absolute", "huber", "poisson", "logistic")
REGS = ("none", "l1", "l2", "non_negative")


@dataclass
class GLRMParameters(ModelParameters):
    k: int = 1
    loss: str = "quadratic"
    regularization_x: str = "none"
    regularization_y: str = "none"
    gamma_x: float = 0.0
    gamma_y: float = 0.0
    max_iterations: int = 100
    init_step_size: float = 1.0
    min_step_size: float = 1e-4
    init: str = "svd"  # svd | random
    transform: str = "none"  # none | standardize
    recover_svd: bool = False


def _loss_and_grad(loss: str):
    """Per-entry loss l(xy, a) and dl/d(xy); NAs are masked by the caller."""
    if loss == "quadratic":
        return (lambda u, a: (u - a) ** 2), (lambda u, a: 2.0 * (u - a))
    if loss == "absolute":
        return (lambda u, a: jnp.abs(u - a)), (lambda u, a: jnp.sign(u - a))
    if loss == "huber":
        def l(u, a):
            r = u - a
            return jnp.where(jnp.abs(r) <= 1.0, 0.5 * r * r, jnp.abs(r) - 0.5)

        def g(u, a):
            r = u - a
            return jnp.where(jnp.abs(r) <= 1.0, r, jnp.sign(r))

        return l, g
    if loss == "poisson":
        return (
            lambda u, a: jnp.exp(u) - a * u,
            lambda u, a: jnp.exp(u) - a,
        )
    if loss == "logistic":
        # a ∈ {0,1}: logistic loss on the margin
        return (
            lambda u, a: jnp.log1p(jnp.exp(-(2 * a - 1) * u)),
            lambda u, a: -(2 * a - 1) / (1.0 + jnp.exp((2 * a - 1) * u)),
        )
    raise ValueError(f"unknown loss {loss!r}")


def _prox(reg: str, gamma: float):
    if reg == "none" or gamma == 0.0 and reg != "non_negative":
        return lambda v, step: v
    if reg == "l1":
        return lambda v, step: jnp.sign(v) * jnp.maximum(jnp.abs(v) - step * gamma, 0.0)
    if reg == "l2":
        return lambda v, step: v / (1.0 + 2.0 * step * gamma)
    if reg == "non_negative":
        return lambda v, step: jnp.maximum(v, 0.0)
    raise ValueError(f"unknown regularization {reg!r}")


def _reg_value(reg: str, gamma: float, v) -> float:
    if reg == "l1":
        return float(gamma * jnp.abs(v).sum())
    if reg == "l2":
        return float(gamma * (v * v).sum())
    return 0.0


class GLRMModel(Model):
    algo_name = "glrm"

    def __init__(self, params, data_info):
        super().__init__(params, data_info)
        self.archetypes: Optional[np.ndarray] = None  # Y [k, P]
        self.x_factors: Optional[np.ndarray] = None  # X [N, k] (training rows)
        self.objective: float = np.nan
        self.step_size: float = np.nan
        self.iterations: int = 0
        self.singular_vals: Optional[np.ndarray] = None

    @property
    def is_classifier(self) -> bool:
        return False

    def transform_frame(self, frame: Frame, iterations: int = 50) -> Frame:
        """Project new rows onto the archetypes (solve for X with Y fixed)."""
        A, mask = _design(self.data_info, frame)
        X = _solve_x(
            jnp.asarray(A), jnp.asarray(mask), jnp.asarray(self.archetypes),
            self.params, iterations,
        )
        X = np.asarray(X)
        return Frame([
            Column(f"Arch{j + 1}", X[:, j].astype(np.float64), ColType.NUM)
            for j in range(X.shape[1])
        ])

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        """Reconstruction Â = XY for the frame's rows."""
        A, mask = _design(self.data_info, frame)
        X = np.asarray(
            _solve_x(jnp.asarray(A), jnp.asarray(mask), jnp.asarray(self.archetypes),
                     self.params, 50)
        )
        return X @ self.archetypes

    def reconstruct(self, frame: Frame) -> Frame:
        R = self._predict_raw(frame)
        names = self.data_info.coef_names
        return Frame([
            Column(f"reconstr_{names[j]}", R[:, j].astype(np.float64), ColType.NUM)
            for j in range(R.shape[1])
        ])


def _design(info, frame):
    X, _ = expand_matrix(info, frame, dtype=np.float32)
    # NA mask must reflect the *original* NAs (expand_matrix imputes them)
    mask = np.ones_like(X, dtype=bool)
    col_off = 0
    for name in info.predictor_names:
        if name in info.cat_domains:
            w = len(info.cat_domains[name]) - (0 if info.use_all_factor_levels else 1)
            na = frame.col(name).isna()
            mask[na, col_off : col_off + w] = False
            col_off += w
        else:
            na = frame.col(name).isna()
            mask[na, col_off] = False
            col_off += 1
    return X, mask


@partial(jax.jit, static_argnames=("loss", "reg", "steps"))
def _solve_x_impl(A, M, Y, gamma, loss: str, reg: str, steps: int):
    _, gfn = _loss_and_grad(loss)
    n, k = A.shape[0], Y.shape[0]
    L = jnp.maximum((Y * Y).sum() * 2.0, 1e-6)
    step = 1.0 / L

    def body(_, X):
        U = X @ Y
        G = (M * gfn(U, A)) @ Y.T
        V = X - step * G
        if reg == "l1":
            V = jnp.sign(V) * jnp.maximum(jnp.abs(V) - step * gamma, 0.0)
        elif reg == "l2":
            V = V / (1.0 + 2.0 * step * gamma)
        elif reg == "non_negative":
            V = jnp.maximum(V, 0.0)
        return V

    X0 = jnp.zeros((n, k), dtype=A.dtype)
    return jax.lax.fori_loop(0, steps, body, X0)


def _solve_x(A, M, Y, p: GLRMParameters, steps: int):
    Mf = M.astype(A.dtype)
    if p.loss == "quadratic" and p.regularization_x in ("none", "l2"):
        return _als_x(A, Mf, Y, p.gamma_x if p.regularization_x == "l2" else 0.0)
    return _solve_x_impl(A, Mf, Y, p.gamma_x, p.loss, p.regularization_x, steps)


@partial(jax.jit, static_argnames=("loss",))
def _objective(A, M, X, Y, loss: str):
    lfn, _ = _loss_and_grad(loss)
    return (M * lfn(X @ Y, A)).sum()


@partial(jax.jit, static_argnames=("loss",))
def _grads(A, M, X, Y, loss: str):
    _, gfn = _loss_and_grad(loss)
    R = M * gfn(X @ Y, A)
    return R @ Y.T, X.T @ R  # grad_X [N,k], grad_Y [k,P]


@jax.jit
def _als_x(A, M, Y, ridge):
    """Exact masked least-squares row solves: Xᵢ = (Y Mᵢ Yᵀ + γI)⁻¹ Y Mᵢ Aᵢ."""
    k = Y.shape[0]
    G = jnp.einsum("np,kp,lp->nkl", M, Y, Y) + ridge * jnp.eye(k) + 1e-8 * jnp.eye(k)
    b = jnp.einsum("np,kp->nk", M * A, Y)
    return jax.vmap(jnp.linalg.solve)(G, b)


@jax.jit
def _als_y(A, M, X, ridge):
    """Exact masked least-squares column solves for the archetypes."""
    k = X.shape[1]
    G = jnp.einsum("np,nk,nl->pkl", M, X, X) + ridge * jnp.eye(k) + 1e-8 * jnp.eye(k)
    b = jnp.einsum("np,nk->pk", M * A, X)
    return jax.vmap(jnp.linalg.solve)(G, b).T  # [k, P]


class GLRM(ModelBuilder):
    algo_name = "glrm"

    def __init__(self, params: Optional[GLRMParameters] = None, **kw) -> None:
        super().__init__(params or GLRMParameters(**kw))

    def _validate(self, frame: Frame) -> None:
        super()._validate(frame)
        p: GLRMParameters = self.params
        if p.loss not in LOSSES:
            raise ValueError(f"loss must be one of {LOSSES}")
        if p.regularization_x not in REGS or p.regularization_y not in REGS:
            raise ValueError(f"regularization must be one of {REGS}")

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> GLRMModel:
        p: GLRMParameters = self.params
        info = build_data_info(
            frame, None, ignored=p.ignored_columns,
            use_all_factor_levels=True,
            standardize=p.transform == "standardize",
        )
        model = GLRMModel(p, info)
        A_np, M_np = _design(info, frame)
        n, pc = A_np.shape
        k = min(p.k, min(n, pc))
        rng = np.random.default_rng(p.actual_seed())

        if p.init == "svd":
            A0 = np.where(M_np, A_np, 0.0)
            U, s, Vt = np.linalg.svd(A0, full_matrices=False)
            X0 = (U[:, :k] * s[:k]).astype(np.float32)
            Y0 = Vt[:k].astype(np.float32)
        else:
            X0 = rng.normal(scale=0.1, size=(n, k)).astype(np.float32)
            Y0 = rng.normal(scale=0.1, size=(k, pc)).astype(np.float32)

        A, M = jnp.asarray(A_np), jnp.asarray(M_np.astype(A_np.dtype))
        X, Y = jnp.asarray(X0), jnp.asarray(Y0)
        prox_x = _prox(p.regularization_x, p.gamma_x)
        prox_y = _prox(p.regularization_y, p.gamma_y)

        def full_obj(X, Y):
            return (
                float(_objective(A, M, X, Y, p.loss))
                + _reg_value(p.regularization_x, p.gamma_x, X)
                + _reg_value(p.regularization_y, p.gamma_y, Y)
            )

        obj = full_obj(X, Y)
        step = p.init_step_size
        exact_als = p.loss == "quadratic" and {p.regularization_x, p.regularization_y} <= {"none", "l2"}
        for it in range(p.max_iterations):
            if exact_als:
                # quadratic + (none|l2): exact alternating masked least squares
                X = _als_x(A, M, Y, p.gamma_x if p.regularization_x == "l2" else 0.0)
                Y = _als_y(A, M, X, p.gamma_y if p.regularization_y == "l2" else 0.0)
                new_obj = full_obj(X, Y)
                improved = new_obj < obj - 1e-10 * max(abs(obj), 1.0)
                obj = new_obj
            else:
                # proximal gradient with per-side Lipschitz steps + backtracking
                # (GLRM.java's step-halving line search)
                improved = False
                lx = 1.0 / max(2.0 * float((Y * Y).sum()), 1e-6)
                while step > p.min_step_size:
                    gX = _grads(A, M, X, Y, p.loss)[0]
                    Xn = prox_x(X - step * lx * gX, step * lx)
                    ly = 1.0 / max(2.0 * float((Xn * Xn).sum()), 1e-6)
                    gYn = _grads(A, M, Xn, Y, p.loss)[1]
                    Yn = prox_y(Y - step * ly * gYn, step * ly)
                    new_obj = full_obj(Xn, Yn)
                    if new_obj < obj:
                        X, Y, obj = Xn, Yn, new_obj
                        step *= 1.05
                        improved = True
                        break
                    step *= 0.5
            model.iterations = it + 1
            if self.job:
                self.job.update((it + 1) / p.max_iterations)
            if not improved:
                break

        model.x_factors = np.asarray(X, dtype=np.float64)
        model.archetypes = np.asarray(Y, dtype=np.float64)
        model.objective = obj
        model.step_size = step
        if p.recover_svd:
            # SVD of the fitted XY product (GLRM.java recover_svd)
            U, s, Vt = np.linalg.svd(model.x_factors @ model.archetypes, full_matrices=False)
            model.singular_vals = s[:k]
        return model
