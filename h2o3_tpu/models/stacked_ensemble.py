"""Stacked Ensemble — metalearner over base-model CV holdout predictions.

Reference: ``hex/ensemble/StackedEnsemble.java:28`` — the level-one frame is
the column-bind of every base model's cross-validation holdout predictions
(class probabilities for classifiers, predictions for regression) plus the
response; the metalearner (default GLM with an appropriate family,
``hex/ensemble/Metalearners.java``) trains on it; prediction stacks base-model
predictions into the same layout and scores the metalearner.

TPU-native: level-one assembly is pure array plumbing; base models and the
metalearner are the framework's jitted models.  Base models must be trained
with ``nfolds >= 2`` and ``keep_cross_validation_predictions=True`` on the
same training frame (same constraint as the reference).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional

import numpy as np

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.models.data_info import build_data_info, response_vector
from h2o3_tpu.models.framework import Model, ModelBuilder, ModelParameters


@dataclass
class StackedEnsembleParameters(ModelParameters):
    base_models: List[Any] = field(default_factory=list)  # trained Models
    metalearner_algorithm: str = "auto"  # auto|glm|gbm|drf|deeplearning
    metalearner_params: dict = field(default_factory=dict)
    metalearner_nfolds: int = 0


class StackedEnsembleModel(Model):
    algo_name = "stackedensemble"

    def __init__(self, params, data_info):
        super().__init__(params, data_info)
        self.metalearner: Optional[Model] = None
        self.base_models: List[Any] = []
        self.levelone_names: List[str] = []

    def _levelone_matrix(self, frame: Frame) -> np.ndarray:
        cols = []
        for bm in self.base_models:
            raw = bm._predict_raw(frame)
            cols.append(_pred_columns(raw, bm.nclasses))
        return np.concatenate(cols, axis=1)

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        L1 = self._levelone_matrix(frame)
        lf = _levelone_frame(L1, self.levelone_names)
        return self.metalearner._predict_raw(lf)


class StackedEnsemble(ModelBuilder):
    algo_name = "stackedensemble"

    def __init__(self, params: Optional[StackedEnsembleParameters] = None, **kw) -> None:
        super().__init__(params or StackedEnsembleParameters(**kw))

    def _validate(self, frame: Frame) -> None:
        super()._validate(frame)
        p: StackedEnsembleParameters = self.params
        if not p.base_models:
            raise ValueError("StackedEnsemble needs at least one base model")
        for bm in p.base_models:
            if getattr(bm, "cv_holdout_predictions", None) is None:
                raise ValueError(
                    f"base model {bm.key} lacks CV holdout predictions — train with "
                    "nfolds >= 2 and keep_cross_validation_predictions=True"
                )

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> StackedEnsembleModel:
        p: StackedEnsembleParameters = self.params
        y_name = p.response_column or p.base_models[0].params.response_column
        info = build_data_info(frame, y_name, standardize=False)
        model = StackedEnsembleModel(p, info)
        model.base_models = list(p.base_models)

        # level-one frame: per-base-model holdout prediction columns + response
        blocks, names = [], []
        for mi, bm in enumerate(p.base_models):
            hp = np.asarray(bm.cv_holdout_predictions)
            block = _pred_columns(hp, bm.nclasses)
            blocks.append(block)
            names += [f"m{mi}_{bm.algo_name}_c{j}" for j in range(block.shape[1])]
        L1 = np.concatenate(blocks, axis=1)
        model.levelone_names = names

        lf = _levelone_frame(L1, names)
        ycol = frame.col(y_name)
        lf = lf.add_column(ycol.copy())

        model.metalearner = _build_metalearner(p, y_name, info).train(lf)
        model.training_metrics = model.model_performance(frame)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model


def _pred_columns(raw: np.ndarray, nclasses: int) -> np.ndarray:
    """Base-model output -> level-one block (drop one redundant prob column
    for binomial, like the reference's levelone keeps p1 only)."""
    if nclasses == 1:
        return raw.reshape(-1, 1).astype(np.float64)
    if nclasses == 2:
        return raw[:, 1:2].astype(np.float64)
    return raw.astype(np.float64)


def _levelone_frame(L1: np.ndarray, names: List[str]) -> Frame:
    return Frame([
        Column(nm, L1[:, j].astype(np.float64), ColType.NUM) for j, nm in enumerate(names)
    ])


def _build_metalearner(p: StackedEnsembleParameters, y_name: str, info) -> ModelBuilder:
    algo = p.metalearner_algorithm
    kw = dict(p.metalearner_params)
    kw.setdefault("response_column", y_name)
    kw.setdefault("nfolds", p.metalearner_nfolds)
    kw.setdefault("seed", p.seed)
    if algo in ("auto", "glm"):
        from h2o3_tpu.models.glm import GLM

        if "family" not in kw:
            dom = info.response_domain
            kw["family"] = (
                "gaussian" if dom is None else ("binomial" if len(dom) == 2 else "multinomial")
            )
        if algo == "auto":
            kw.setdefault("alpha", 0.0)
            kw.setdefault("lambda_", 0.0)
        return GLM(**kw)
    if algo == "gbm":
        from h2o3_tpu.models.tree.gbm import GBM

        return GBM(**kw)
    if algo == "drf":
        from h2o3_tpu.models.tree.drf import DRF

        return DRF(**kw)
    if algo == "deeplearning":
        from h2o3_tpu.models.deeplearning import DeepLearning

        return DeepLearning(**kw)
    raise ValueError(f"unknown metalearner_algorithm {algo!r}")
