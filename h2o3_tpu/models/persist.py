"""Binary model save/load — full-fidelity, pickle-free.

Reference: ``hex/Model.java`` ``exportBinaryModel`` / ``importBinaryModel``
(the ``/3/Models/.../save`` + ``/99/Models.bin`` routes) built on the Iced
auto-serialization (``water/Iced.java:5-33``, javassist-woven ``$Icer``
delegates).

TPU-native replacement for Iced: a typed, allowlisted object-tree format.
Structure goes to JSON, numeric payloads to one npz, and object classes are
restricted to the ``h2o3_tpu`` package — loading reconstructs instances via
``__new__`` + field assignment and never executes arbitrary code (pickle's
``__reduce__`` hole is the reason the reference's own Grid import warns about
trusted files; this format has no such hole).

Handles every model class generically: dataclasses (params, DataInfo,
metrics), plain objects (BoostedTrees/Trees, models themselves), numpy
arrays, containers, enums, and shared references (memoized by object id so
aliased sub-objects stay aliased after load).
"""

from __future__ import annotations

import dataclasses
import importlib
import io
import json
import math
import os
import zipfile
from enum import Enum
from typing import Any, Dict, List, Optional, Union

import numpy as np

FORMAT_VERSION = 1

#: only classes inside these packages may be instantiated at load time
_ALLOWED_PREFIXES = ("h2o3_tpu.",)


# ---------------------------------------------------------------------------
# encode


class _Encoder:
    def __init__(self) -> None:
        self.arrays: Dict[str, np.ndarray] = {}
        self.memo: Dict[int, int] = {}  # id(obj) -> object table index
        self.next_ref = 0

    def enc(self, o: Any) -> Any:
        if o is None or isinstance(o, (bool, str)):
            return o
        if isinstance(o, (int, np.integer)):
            return int(o)
        if isinstance(o, (float, np.floating)):
            f = float(o)
            if math.isfinite(f):
                return f
            return {"__k": "f", "v": repr(f)}
        if isinstance(o, np.ndarray):
            aid = f"a{len(self.arrays)}"
            self.arrays[aid] = o
            return {"__k": "nd", "id": aid}
        if isinstance(o, (list, tuple)):
            return {
                "__k": "list" if isinstance(o, list) else "tuple",
                "items": [self.enc(x) for x in o],
            }
        if isinstance(o, dict):
            return {
                "__k": "dict",
                "items": [[self.enc(k), self.enc(v)] for k, v in o.items()],
            }
        if isinstance(o, Enum):
            return {
                "__k": "enum",
                "cls": f"{type(o).__module__}:{type(o).__qualname__}",
                "name": o.name,
            }
        if hasattr(o, "__dict__") or hasattr(o, "__slots__"):
            oid = id(o)
            if oid in self.memo:
                return {"__k": "ref", "ref": self.memo[oid]}
            self.memo[oid] = ref = self.next_ref
            self.next_ref += 1
            cls = type(o)
            mod = cls.__module__
            if not any(mod.startswith(p) or mod == p.rstrip(".") for p in _ALLOWED_PREFIXES):
                raise TypeError(
                    f"cannot serialize {cls.__module__}.{cls.__qualname__}: "
                    "outside the h2o3_tpu allowlist"
                )
            if hasattr(o, "__dict__"):
                fields = dict(vars(o))
            else:
                fields = {
                    s: getattr(o, s)
                    for s in cls.__slots__
                    if hasattr(o, s)
                }
            # device arrays / callables cannot ride a checkpoint
            clean = {}
            for k, v in fields.items():
                if callable(v) and not isinstance(v, type):
                    continue  # drop bound callables (monitors, caches)
                if k == "dist_eval":
                    # scoring shim pinned to a live DistFrame/store —
                    # process-local by construction, never persisted
                    continue
                clean[k] = v
            return {
                "__k": "obj",
                "id": ref,
                "cls": f"{mod}:{cls.__qualname__}",
                "fields": {k: self.enc(v) for k, v in clean.items()},
            }
        raise TypeError(f"cannot serialize {type(o)!r}")


# ---------------------------------------------------------------------------
# decode


class _Decoder:
    def __init__(self, arrays) -> None:
        self.arrays = arrays
        self.table: Dict[int, Any] = {}

    @staticmethod
    def _resolve(spec: str) -> type:
        mod, _, qual = spec.partition(":")
        if not any(mod.startswith(p) or mod == p.rstrip(".") for p in _ALLOWED_PREFIXES):
            raise ValueError(f"class {spec!r} outside the h2o3_tpu allowlist")
        m = importlib.import_module(mod)
        o: Any = m
        for part in qual.split("."):
            o = getattr(o, part)
        if not isinstance(o, type):
            raise ValueError(f"{spec!r} is not a class")
        return o

    def dec(self, e: Any) -> Any:
        if e is None or isinstance(e, (bool, int, float, str)):
            return e
        k = e["__k"]
        if k == "f":
            return float(e["v"])
        if k == "nd":
            return np.asarray(self.arrays[e["id"]])
        if k == "list":
            return [self.dec(x) for x in e["items"]]
        if k == "tuple":
            return tuple(self.dec(x) for x in e["items"])
        if k == "dict":
            return {self.dec(kk): self.dec(v) for kk, v in e["items"]}
        if k == "enum":
            return getattr(self._resolve(e["cls"]), e["name"])
        if k == "ref":
            return self.table[e["ref"]]
        if k == "obj":
            cls = self._resolve(e["cls"])
            obj = cls.__new__(cls)
            self.table[e["id"]] = obj
            for name, fe in e["fields"].items():
                object.__setattr__(obj, name, self.dec(fe))
            return obj
        raise ValueError(f"unknown node kind {k!r}")


# ---------------------------------------------------------------------------
# public API


def _write_archive(dest, model) -> None:
    """Write the zip(JSON tree + npz) container to a path or file object."""
    enc = _Encoder()
    tree = enc.enc(model)
    meta = {
        "version": FORMAT_VERSION,
        "algo": getattr(model, "algo_name", type(model).__name__),
        "class": f"{type(model).__module__}:{type(model).__qualname__}",
    }
    buf = io.BytesIO()
    np.savez_compressed(buf, **enc.arrays)
    with zipfile.ZipFile(dest, "w", zipfile.ZIP_DEFLATED) as z:
        # fixed entry timestamps: dumps_model of the same model is
        # byte-identical across calls and nodes, so the serving plane can
        # compare home/replica blob copies by digest
        for name, data in (("meta.json", json.dumps(meta)),
                           ("model.json", json.dumps(tree)),
                           ("arrays.npz", buf.getvalue())):
            info = zipfile.ZipInfo(name, date_time=(1980, 1, 1, 0, 0, 0))
            info.compress_type = zipfile.ZIP_DEFLATED
            info.external_attr = 0o600 << 16
            z.writestr(info, data)


def _read_archive(src):
    """Decode a container written by :func:`_write_archive`."""
    with zipfile.ZipFile(src, "r") as z:
        meta = json.loads(z.read("meta.json"))
        if meta.get("version", 0) > FORMAT_VERSION:
            raise ValueError(f"model file version {meta['version']} too new")
        tree = json.loads(z.read("model.json"))
        arrays = np.load(io.BytesIO(z.read("arrays.npz")), allow_pickle=False)
        return _Decoder(arrays).dec(tree)


def save_model(model, path: Union[str, os.PathLike]) -> str:
    """Serialize a trained model (any algo) to ``path``. Returns the path."""
    path = os.fspath(path)
    _write_archive(path, model)
    return path


def dumps_model(model) -> bytes:
    """The :func:`save_model` container as bytes — the wire form a
    distributed-search member ships a finished cell's model back in."""
    buf = io.BytesIO()
    _write_archive(buf, model)
    return buf.getvalue()


def loads_model(data: bytes, key: Optional[str] = None, register: bool = False):
    """Decode a :func:`dumps_model` blob.  ``register=False`` by default:
    the receiving side (cluster/search.py) must collision-check keys
    minted in another node's process before the model joins the DKV."""
    model = _read_archive(io.BytesIO(data))
    if not register:
        return model
    from h2o3_tpu.keyed import DKV

    if key:
        model.key = key
        DKV.put(key, model)
    elif getattr(model, "key", None):
        DKV.put(model.key, model)
    return model


def load_model(
    path: Union[str, os.PathLike], key: Optional[str] = None, register: bool = True
):
    """Load a model written by ``save_model`` and register it in the DKV.

    key: register under this key instead of the file's saved key — the saved
    key is then left untouched, so restoring a snapshot under a new id never
    clobbers a live model that happens to share the original key.
    register=False: decode only, touch nothing — callers that must
    type-check the payload first (a grid route handed a model file, or vice
    versa) register explicitly after checking."""
    from h2o3_tpu.keyed import DKV

    path = os.fspath(path)
    model = _read_archive(path)
    if not register:
        return model
    if key:
        model.key = key
        DKV.put(key, model)
    elif getattr(model, "key", None):
        DKV.put(model.key, model)
    return model
