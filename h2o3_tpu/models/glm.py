"""GLM — generalized linear models via IRLSM with a device-side Gram.

Reference: ``hex/glm/GLM.java`` (3.8k LoC; IRLSM driver GLM.java:1160,
1184-1222), ``hex/glm/GLMTask.java:1502`` (GLMIterationTask: one MRTask pass
computes the weighted Gram X'WX + X'Wz), ``hex/gram/Gram.java:452`` (Cholesky),
``hex/optimization/ADMM.java`` (L1 via ADMM soft-thresholding),
``hex/glm/GLMModel.java:268-334`` (families/links).

TPU-native: the per-iteration distributed pass is ONE jitted matmul —
``X.T @ (W[:,None] * X)`` on the row-sharded design matrix; XLA inserts the
psum over the data axis (sharded-in, replicated-out), which IS the MRTask
reduce. The tiny (P+1)^2 solve (Cholesky or ADMM inner loop) runs on the
host in float64, mirroring the reference where the Gram solve happens on the
driver node. The design matrix comes from DataInfo (one-hot cats,
standardization) exactly as in the reference.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.data_info import (
    DataInfo,
    build_data_info,
    destandardize_coefs,
    expand_matrix,
    response_vector,
)
from h2o3_tpu.models.framework import Model, ModelBuilder, ModelParameters
from h2o3_tpu.parallel.mesh import default_mesh, pad_rows, shard_rows

FAMILIES = (
    "gaussian", "binomial", "quasibinomial", "poisson", "gamma", "tweedie",
    "multinomial", "ordinal",
)

_DEFAULT_LINK = {
    "gaussian": "identity",
    "binomial": "logit",
    "quasibinomial": "logit",
    "poisson": "log",
    "gamma": "log",
    "tweedie": "tweedie",
    "multinomial": "multinomial",  # softmax
    "ordinal": "ologit",  # cumulative logit (proportional odds)
}

SOLVERS = ("auto", "irlsm", "lbfgs")


@dataclass
class GLMParameters(ModelParameters):
    family: str = "gaussian"
    link: str = "family_default"
    alpha: float = 0.5
    lambda_: float = 0.0
    lambda_search: bool = False
    nlambdas: int = 30
    standardize: bool = True
    intercept: bool = True
    max_iterations: int = 50
    beta_epsilon: float = 1e-4
    objective_epsilon: float = 1e-6
    tweedie_variance_power: float = 1.5
    tweedie_link_power: float = 0.0
    compute_p_values: bool = False
    missing_values_handling: str = "mean_imputation"
    solver: str = "auto"  # auto|irlsm|lbfgs (GLMModel.java:268-334 solver enum)
    lambda_min_ratio: float = 0.0  # 0 = auto: 1e-4 if n > p else 1e-2

    def actual_link(self) -> str:
        return _DEFAULT_LINK[self.family] if self.link == "family_default" else self.link


# ---------------------------------------------------------------------------
# family math (hex/glm/GLMModel.GLMParameters link/variance/deviance defs)


def _linkinv(link: str, eta: np.ndarray, p: GLMParameters) -> np.ndarray:
    if link == "identity":
        return eta
    if link == "logit":
        return 1.0 / (1.0 + np.exp(-eta))
    if link == "log":
        return np.exp(eta)
    if link == "inverse":
        return 1.0 / np.where(np.abs(eta) < 1e-10, np.sign(eta + 1e-30) * 1e-10, eta)
    if link == "tweedie":
        lp = p.tweedie_link_power
        return np.exp(eta) if lp == 0 else np.power(np.maximum(eta, 1e-10), 1.0 / lp)
    raise ValueError(f"unknown link {link}")


def _link_deriv(link: str, mu: np.ndarray, p: GLMParameters) -> np.ndarray:
    """d eta / d mu."""
    if link == "identity":
        return np.ones_like(mu)
    if link == "logit":
        return 1.0 / np.maximum(mu * (1 - mu), 1e-10)
    if link == "log":
        return 1.0 / np.maximum(mu, 1e-10)
    if link == "inverse":
        return -1.0 / np.maximum(mu**2, 1e-10)
    if link == "tweedie":
        lp = p.tweedie_link_power
        if lp == 0:
            return 1.0 / np.maximum(mu, 1e-10)
        return lp * np.power(np.maximum(mu, 1e-10), lp - 1)
    raise ValueError(f"unknown link {link}")


def _variance(family: str, mu: np.ndarray, p: GLMParameters) -> np.ndarray:
    if family == "gaussian":
        return np.ones_like(mu)
    if family in ("binomial", "quasibinomial"):
        return np.maximum(mu * (1 - mu), 1e-10)
    if family == "poisson":
        return np.maximum(mu, 1e-10)
    if family == "gamma":
        return np.maximum(mu**2, 1e-10)
    if family == "tweedie":
        return np.power(np.maximum(mu, 1e-10), p.tweedie_variance_power)
    raise ValueError(f"unknown family {family}")


def deviance(family: str, y: np.ndarray, mu: np.ndarray, p: GLMParameters) -> np.ndarray:
    """Per-row unit deviance (hex/Distribution.java / GLMModel deviance defs)."""
    eps = 1e-10
    if family == "gaussian":
        return (y - mu) ** 2
    if family in ("binomial", "quasibinomial"):
        mu = np.clip(mu, eps, 1 - eps)
        return -2 * (y * np.log(mu) + (1 - y) * np.log(1 - mu))
    if family == "poisson":
        mu = np.maximum(mu, eps)
        t = np.where(y > 0, y * np.log(np.where(y > 0, y, 1.0) / mu), 0.0)
        return 2 * (t - (y - mu))
    if family == "gamma":
        mu = np.maximum(mu, eps)
        ys = np.maximum(y, eps)
        return -2 * (np.log(ys / mu) - (ys - mu) / mu)
    if family == "tweedie":
        vp = p.tweedie_variance_power
        mu = np.maximum(mu, eps)
        ys = np.maximum(y, 0.0)
        a = np.where(ys > 0, np.power(np.maximum(ys, eps), 2 - vp) / ((1 - vp) * (2 - vp)), 0.0)
        b = ys * np.power(mu, 1 - vp) / (1 - vp)
        c = np.power(mu, 2 - vp) / (2 - vp)
        return 2 * (a - b + c)
    raise ValueError(f"unknown family {family}")


# ---------------------------------------------------------------------------
# the distributed pass: weighted Gram via one sharded matmul


@jax.jit
def _gram_kernel(Xw, wz, w):
    """X'WX and X'Wz in one pass. Xw:[N,P+1] (with intercept col), w:[N]."""
    WX = Xw * w[:, None]
    g = Xw.T @ WX  # psum over the sharded data axis is implicit
    q = Xw.T @ (w * wz)
    return g, q


def _gram(Xd, wz, w):
    g, q = _gram_kernel(Xd, jnp.asarray(wz, dtype=Xd.dtype), jnp.asarray(w, dtype=Xd.dtype))
    return np.asarray(jax.device_get(g), dtype=np.float64), np.asarray(
        jax.device_get(q), dtype=np.float64
    )


# ---------------------------------------------------------------------------
# host-side solvers (the reference solves the Gram on the driver node too)


def _solve_ridge(G: np.ndarray, q: np.ndarray, l2: float, free: int) -> np.ndarray:
    """(G + l2*I) b = q, no penalty on the last ``free`` coefs (intercept)."""
    A = G.copy()
    n = A.shape[0]
    pen = n - free
    A[np.arange(pen), np.arange(pen)] += l2
    A[np.arange(n), np.arange(n)] += 1e-10  # jitter for singular one-hot blocks
    try:
        from scipy.linalg import cho_factor, cho_solve

        return cho_solve(cho_factor(A, lower=True), q)
    except Exception:
        return np.linalg.lstsq(A, q, rcond=None)[0]


def _solve_admm(
    G: np.ndarray, q: np.ndarray, l1: float, l2: float, free: int, iters: int = 500, tol: float = 1e-7
) -> np.ndarray:
    """Elastic-net quadratic subproblem via ADMM (hex/optimization/ADMM.java):
    min 1/2 b'Gb - q'b + l1*|b|_1 + l2/2*|b|^2, intercept unpenalized."""
    n = G.shape[0]
    pen = n - free
    rho = float(np.mean(np.diag(G))) + l2 + 1e-6
    A = G.copy()
    A[np.arange(pen), np.arange(pen)] += l2 + rho
    A[np.arange(pen, n), np.arange(pen, n)] += rho
    A[np.arange(n), np.arange(n)] += 1e-10
    from scipy.linalg import cho_factor, cho_solve

    cf = cho_factor(A, lower=True)
    z = np.zeros(n)
    u = np.zeros(n)
    for _ in range(iters):
        x = cho_solve(cf, q + rho * (z - u))
        z_old = z
        xu = x + u
        z = np.concatenate(
            [np.sign(xu[:pen]) * np.maximum(np.abs(xu[:pen]) - l1 / rho, 0.0), xu[pen:]]
        )
        u = xu - z
        if np.max(np.abs(z - z_old)) < tol and np.max(np.abs(x - z)) < tol:
            break
    return z


# ---------------------------------------------------------------------------
# model


class GLMModel(Model):
    algo_name = "glm"

    def __init__(self, params: GLMParameters, data_info: DataInfo) -> None:
        super().__init__(params, data_info)
        self.coefficients: Dict[str, float] = {}
        self.coefficients_std: Dict[str, float] = {}
        self.beta_std: Optional[np.ndarray] = None  # [P+1] incl intercept, std space
        # multinomial: [P+1, K] per-class betas (std space); ordinal: [P] beta
        # + [K-1] increasing thresholds (std space), mirroring
        # GLMModel.GLMOutput._global_beta_multinomial / ordinal intercepts
        self.beta_multi: Optional[np.ndarray] = None
        self.ordinal_thresholds: Optional[np.ndarray] = None
        self.coefficients_multinomial: Optional[Dict[str, Dict[str, float]]] = None
        self.null_deviance: float = np.nan
        self.residual_deviance: float = np.nan
        self.aic: float = np.nan
        self.dispersion: float = 1.0
        self.std_errors: Optional[Dict[str, float]] = None
        self.p_values: Optional[Dict[str, float]] = None
        self.iterations: int = 0
        # lambda_search artifacts (GLMModel.RegularizationPath)
        self.lambda_path: Optional[List[Dict[str, float]]] = None
        self.lambda_best: Optional[float] = None

    def _eta(self, frame: Frame) -> np.ndarray:
        X, _ = expand_matrix(self.data_info, frame, dtype=np.float64)
        b = self.beta_std
        eta = X @ b[:-1] + b[-1]
        if self.params.offset_column:
            eta = eta + frame.col(self.params.offset_column).numeric_view()
        return eta

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        p: GLMParameters = self.params
        if p.family == "multinomial":
            X, _ = expand_matrix(self.data_info, frame, dtype=np.float64)
            eta = X @ self.beta_multi[:-1] + self.beta_multi[-1]
            if p.offset_column:
                eta = eta + frame.col(p.offset_column).numeric_view()[:, None]
            return _softmax(eta)
        if p.family == "ordinal":
            X, _ = expand_matrix(self.data_info, frame, dtype=np.float64)
            eta = X @ self.beta_std
            if p.offset_column:
                eta = eta + frame.col(p.offset_column).numeric_view()
            return _ordinal_probs(eta, self.ordinal_thresholds)
        mu = _linkinv(p.actual_link(), self._eta(frame), p)
        if p.family in ("binomial", "quasibinomial"):
            return np.stack([1 - mu, mu], axis=1)
        return mu


def _softmax(eta: np.ndarray) -> np.ndarray:
    z = eta - eta.max(axis=1, keepdims=True)
    e = np.exp(z)
    return e / e.sum(axis=1, keepdims=True)


def _ordinal_probs(eta: np.ndarray, thresholds: np.ndarray) -> np.ndarray:
    """Proportional-odds class probabilities: P(y<=k) = sigmoid(t_k - eta)."""
    cum = 1.0 / (1.0 + np.exp(-(thresholds[None, :] - eta[:, None])))  # [N, K-1]
    full = np.concatenate([cum, np.ones((len(eta), 1))], axis=1)
    lower = np.concatenate([np.zeros((len(eta), 1)), cum], axis=1)
    return np.maximum(full - lower, 1e-15)


class GLM(ModelBuilder):
    """Builder (reference driver loop: hex/glm/GLM.java:1160 fitIRLSM)."""

    SUPPORTED_COMMON = frozenset({"weights_column", "offset_column"})

    algo_name = "glm"

    def __init__(self, params: Optional[GLMParameters] = None, **kw) -> None:
        super().__init__(params or GLMParameters(**kw))

    def _validate(self, frame: Frame) -> None:
        super()._validate(frame)
        p: GLMParameters = self.params
        if p.family not in FAMILIES:
            raise ValueError(f"family must be one of {FAMILIES}, got {p.family!r}")
        if p.solver not in SOLVERS:
            raise ValueError(f"solver must be one of {SOLVERS}, got {p.solver!r}")
        if not (0 <= p.alpha <= 1):
            raise ValueError("alpha must be in [0, 1]")
        if p.lambda_ < 0:
            raise ValueError("lambda must be >= 0")
        if p.compute_p_values and (p.lambda_ > 0 or p.lambda_search):
            raise ValueError("p-values require lambda = 0 (no regularization)")
        if p.compute_p_values and p.family in ("multinomial", "ordinal"):
            raise ValueError(f"compute_p_values is not supported for family={p.family!r}")
        if p.solver == "lbfgs" and p.alpha > 0 and (p.lambda_ > 0 or p.lambda_search):
            raise ValueError(
                "solver='lbfgs' does not support L1 (alpha > 0 with lambda > 0); "
                "use solver='irlsm' (ADMM) or alpha=0"
            )
        if p.family == "ordinal":
            if p.alpha > 0 and p.lambda_ > 0:
                raise ValueError("family='ordinal' supports L2 regularization only (alpha=0)")
            if p.lambda_search:
                raise ValueError("lambda_search is not supported for family='ordinal'")
            if p.solver == "irlsm":
                raise ValueError(
                    "family='ordinal' uses a gradient solver; set solver='auto' or 'lbfgs'"
                )
        if p.family == "multinomial" and p.offset_column:
            # a shared offset shifts every class eta equally and cancels in the
            # softmax — accepting it would be a silent no-op
            raise ValueError("offset_column is not supported for family='multinomial'")
        if p.lambda_search and p.nlambdas < 1:
            raise ValueError("nlambdas must be >= 1")

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> GLMModel:
        p: GLMParameters = self.params
        link = p.actual_link()
        # device-design cache identity, captured BEFORE any response
        # conversion below rebinds `frame`: the expanded+filtered design is
        # a pure function of the original column versions and these params,
        # so lambda-path refits and AutoML retrains on the same unmutated
        # frame reuse the resident device matrix (devcache tentpole)
        from h2o3_tpu.frame import devcache as _devcache

        self._design_token = _devcache.frame_token(frame)
        self._design_sig = (
            p.standardize, p.missing_values_handling,
            tuple(p.ignored_columns), p.response_column, p.weights_column,
            p.offset_column, p.intercept,
        )
        self._train_frame_key = getattr(frame, "key", None)
        if p.family in ("binomial", "quasibinomial", "multinomial", "ordinal"):
            # the reference requires a categorical response for these
            # families; a numeric column is auto-converted (as_factor)
            ycol = frame.col(p.response_column)
            if not ycol.is_categorical():
                frame = frame.add_column(ycol.as_factor())
                if valid is not None:
                    valid = valid.add_column(valid.col(p.response_column).as_factor())
        info = build_data_info(
            frame,
            y=p.response_column,
            ignored=p.ignored_columns,
            standardize=p.standardize,
            missing_values_handling=p.missing_values_handling,
        )
        model = GLMModel(p, info)

        X, skip = expand_matrix(info, frame, dtype=np.float32)
        y = response_vector(info, frame)
        obs_w = (
            frame.col(p.weights_column).numeric_view().astype(np.float64)
            if p.weights_column
            else np.ones(frame.nrows)
        )
        offset = (
            frame.col(p.offset_column).numeric_view().astype(np.float64)
            if p.offset_column
            else np.zeros(frame.nrows)
        )
        keep = ~(skip | np.isnan(y) | np.isnan(obs_w))
        X, y, obs_w, offset = X[keep], y[keep], obs_w[keep], offset[keep]
        n, pcols = X.shape
        if n == 0:
            raise ValueError("no rows left after NA handling")
        X64 = X.astype(np.float64)  # host copy for eta/deviance (made once)
        wsum = float(obs_w.sum())

        # held-out data for lambda_search submodel selection
        valid_data = None
        if valid is not None and p.lambda_search:
            Xv, skipv = expand_matrix(info, valid, dtype=np.float64)
            yv = response_vector(info, valid)
            wv = (
                valid.col(p.weights_column).numeric_view().astype(np.float64)
                if p.weights_column
                else np.ones(valid.nrows)
            )
            ov = (
                valid.col(p.offset_column).numeric_view().astype(np.float64)
                if p.offset_column
                else np.zeros(valid.nrows)
            )
            keepv = ~(skipv | np.isnan(yv) | np.isnan(wv))
            valid_data = (Xv[keepv], yv[keepv], wv[keepv], ov[keepv])

        if p.family == "multinomial":
            self._fit_multinomial(model, info, X, X64, y, obs_w, offset, wsum, valid_data)
        elif p.family == "ordinal":
            self._fit_ordinal(model, info, X, X64, y, obs_w, offset, wsum)
        else:
            self._fit_gaussian_like(
                model, info, X, X64, y, obs_w, offset, link, wsum, valid_data
            )

        model.training_metrics = model.model_performance(frame)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model

    # -- exponential-family path (IRLSM / L-BFGS + lambda search) ------------

    def _fit_gaussian_like(
        self, model, info, X, X64, y, obs_w, offset, link, wsum, valid_data
    ) -> None:
        p: GLMParameters = self.params
        n, pcols = X.shape
        ybar = float((obs_w * y).sum() / wsum)
        beta0 = np.zeros(pcols + 1)
        # intercept warm start at the link of the response mean (GLM.java init)
        if p.intercept:
            beta0[-1] = _link_of_mean(link, ybar, p)
        solver = "irlsm" if p.solver == "auto" else p.solver
        if solver == "lbfgs":
            solve = self._make_lbfgs_solver(X64, y, obs_w, offset, link, wsum)
        else:
            Xd, pad = self._device_design(X)
            solve = lambda lam, b0: self._irlsm(
                X64, Xd, pad, y, obs_w, offset, link, lam, b0, wsum
            )

        if p.lambda_search:
            lambdas = self._lambda_grid(X64, y, obs_w, offset, link, wsum, pcols, n)
            null_dev = float(
                (obs_w * deviance(p.family, y, np.full_like(y, ybar), p)).sum()
            )

            def dev_train(b):
                mu = _linkinv(link, X64 @ b[:-1] + b[-1] + offset, p)
                return float((obs_w * deviance(p.family, y, mu, p)).sum())

            dev_valid = None
            if valid_data is not None:
                Xv, yv, wv, ov = valid_data

                def dev_valid(b):
                    muv = _linkinv(link, Xv @ b[:-1] + b[-1] + ov, p)
                    return float((wv * deviance(p.family, yv, muv, p)).sum())

            beta = self._run_lambda_path(
                model, lambdas, solve, dev_train, dev_valid,
                nonzeros=lambda b: int(np.sum(np.abs(b[:-1]) > 1e-12)),
                null_dev=null_dev, state0=beta0,
            )
        else:
            beta, model.iterations = solve(p.lambda_, beta0)

        model.beta_std = beta
        b_raw, icpt = destandardize_coefs(info, beta[:-1], beta[-1])
        model.coefficients = dict(zip(info.coef_names, b_raw.tolist()))
        model.coefficients["Intercept"] = icpt
        model.coefficients_std = dict(zip(info.coef_names, beta[:-1].tolist()))
        model.coefficients_std["Intercept"] = float(beta[-1])

        # deviances + AIC (GLMModel.GLMOutput)
        mu = _linkinv(link, X64 @ beta[:-1] + beta[-1] + offset, p)
        model.residual_deviance = float((obs_w * deviance(p.family, y, mu, p)).sum())
        mu0 = np.full_like(y, ybar)
        model.null_deviance = float((obs_w * deviance(p.family, y, mu0, p)).sum())
        rank = int(np.sum(np.abs(beta[:-1]) > 0)) + (1 if p.intercept else 0)
        model.aic = _aic(p.family, y, mu, obs_w, model.residual_deviance, rank)

        if p.compute_p_values and p.lambda_ == 0 and not p.lambda_search:
            self._p_values(model, X, y, mu, obs_w, offset, link, p, info)

    def _cached_upload(self, kind: str, mesh, build):
        """Memoize a device placement through the process-wide devcache,
        keyed on (placement kind, frame token, design params, mesh). Falls
        through to a plain upload when the frame has no version stamps."""
        from h2o3_tpu.frame import devcache as _devcache

        return _devcache.cached(
            kind, getattr(self, "_design_token", None),
            getattr(self, "_design_sig", None), mesh, build,
            frame_key=getattr(self, "_train_frame_key", None),
        )

    def _device_design(self, X: np.ndarray):
        """Row-sharded design matrix [N, P(+1 intercept col)] + row padder."""
        p: GLMParameters = self.params
        mesh = default_mesh()
        nshards = mesh.devices.size

        def build():
            Xi = (
                np.concatenate(
                    [X, np.ones((len(X), 1), dtype=np.float32)], axis=1
                )
                if p.intercept
                else X
            )
            Xd, _ = shard_rows(Xi, mesh)
            return Xd

        return self._cached_upload("glm_design", mesh, build), (
            lambda a: pad_rows(a, nshards)[0]
        )

    def _run_lambda_path(
        self, model, lambdas, solve, dev_train, dev_valid, nonzeros, null_dev, state0
    ):
        """Warm-started fit along the lambda path + submodel selection
        (GLM.java:1632 lambda search; selection by validation deviance when a
        validation frame exists, else training deviance)."""
        path: List[Dict[str, float]] = []
        states: List[np.ndarray] = []
        state = state0
        total_iters = 0
        for lam in lambdas:
            state, iters = solve(float(lam), state)
            total_iters += iters
            dev = dev_train(state)
            entry = {
                "lambda": float(lam),
                "deviance_train": dev,
                "explained_deviance_train": 1.0 - dev / max(null_dev, 1e-300),
                "nonzeros": nonzeros(state),
            }
            if dev_valid is not None:
                entry["deviance_valid"] = dev_valid(state)
            path.append(entry)
            states.append(np.array(state, copy=True))
        crit = "deviance_valid" if dev_valid is not None else "deviance_train"
        best = int(np.argmin([e[crit] for e in path]))
        model.lambda_path = path
        model.lambda_best = path[best]["lambda"]
        model.iterations = total_iters
        return states[best]

    def _grid_from_gradient(self, g: np.ndarray, wsum: float, n: int, pcols: int) -> np.ndarray:
        """Lambda grid given the null-model gradient: lambda_max is the
        smallest lambda that zeroes every penalized coefficient."""
        p: GLMParameters = self.params
        lambda_max = max(float(np.max(np.abs(g))) / (wsum * max(p.alpha, 1e-3)), 1e-10)
        lmin_ratio = p.lambda_min_ratio or (1e-4 if n > pcols else 1e-2)
        if p.nlambdas == 1:
            return np.array([lambda_max])
        return np.geomspace(lambda_max, lambda_max * lmin_ratio, p.nlambdas)

    def _irlsm(
        self, X64, Xd, pad, y, obs_w, offset, link, lam, beta0, wsum
    ) -> Tuple[np.ndarray, int]:
        """One IRLSM solve at a fixed lambda (GLM.java:1160 fitIRLSM)."""
        p: GLMParameters = self.params
        l1 = lam * p.alpha
        l2 = lam * (1 - p.alpha)
        beta = beta0.copy()
        prev_obj = np.inf
        iters = 0
        for it in range(p.max_iterations):
            eta = X64 @ beta[:-1] + beta[-1] + offset
            mu = _linkinv(link, eta, p)
            d = _link_deriv(link, mu, p)
            v = _variance(p.family, mu, p)
            w = obs_w / np.maximum(v * d * d, 1e-12)
            wz = (eta - offset) + (y - mu) * d

            G, q = _gram(Xd, pad(wz), pad(w))
            free = 1 if p.intercept else 0
            if l1 > 0:
                solved = _solve_admm(G / wsum, q / wsum, l1, l2, free=free)
            else:
                solved = _solve_ridge(G / wsum, q / wsum, l2, free=free)
            # without an intercept the ones column is excluded from the solve
            # entirely (clamping after solving would converge to wrong coefs)
            beta_new = solved if p.intercept else np.append(solved, 0.0)

            dev = float((obs_w * deviance(p.family, y, _linkinv(link, X64 @ beta_new[:-1] + beta_new[-1] + offset, p), p)).sum())
            obj = dev / (2 * wsum) + lam * (
                p.alpha * np.abs(beta_new[:-1]).sum() + (1 - p.alpha) / 2 * (beta_new[:-1] ** 2).sum()
            )
            delta = np.max(np.abs(beta_new - beta))
            beta = beta_new
            iters = it + 1
            if delta < p.beta_epsilon or abs(prev_obj - obj) < p.objective_epsilon * max(abs(prev_obj), 1.0):
                break
            prev_obj = obj
        return beta, iters

    def _lambda_grid(self, X64, y, obs_w, offset, link, wsum, pcols, n) -> np.ndarray:
        """Log-spaced lambda path from lambda_max down (GLM.java:1632
        makeLambdaSearch; lambda_max = smallest lambda that zeroes every
        penalized coefficient, from the null-model gradient)."""
        p: GLMParameters = self.params
        ybar = float((obs_w * y).sum() / wsum)
        eta0 = np.full_like(y, _link_of_mean(link, ybar, p)) + offset
        mu0 = _linkinv(link, eta0, p)
        d = _link_deriv(link, mu0, p)
        v = _variance(p.family, mu0, p)
        w = obs_w / np.maximum(v * d * d, 1e-12)
        g = X64.T @ (w * (y - mu0) * d)
        return self._grid_from_gradient(g, wsum, n, pcols)

    _CANONICAL_LINK = {
        "gaussian": "identity", "binomial": "logit", "quasibinomial": "logit",
        "poisson": "log", "gamma": "log", "tweedie": "tweedie",
    }

    def _make_lbfgs_solver(self, X64, y, obs_w, offset, link, wsum):
        """L-BFGS solver factory (hex/optimization/L_BFGS.java): device
        arrays are placed and the value-and-grad program compiled ONCE; the
        returned solve(lam, beta0) is reused across a lambda path. The NLL
        below is written in eta for the canonical link of each family, so any
        other link must be rejected (it would silently fit a different
        model)."""
        p: GLMParameters = self.params
        canonical = self._CANONICAL_LINK.get(p.family)
        if link != canonical or (p.family == "tweedie" and p.tweedie_link_power != 0):
            raise ValueError(
                f"solver='lbfgs' supports only the canonical link for "
                f"family={p.family!r} ({canonical!r}"
                + (", tweedie_link_power=0" if p.family == "tweedie" else "")
                + f"); got link={link!r}. Use solver='irlsm'."
            )
        mesh = default_mesh()
        nshards = mesh.devices.size
        Xf = self._cached_upload(
            "glm_lbfgs_x", mesh,
            lambda: shard_rows(X64.astype(np.float32), mesh)[0],
        )
        wd = jnp.asarray(pad_rows(obs_w, nshards)[0], dtype=jnp.float32)
        yd = jnp.asarray(pad_rows(y, nshards)[0], dtype=jnp.float32)
        od = jnp.asarray(pad_rows(offset, nshards)[0], dtype=jnp.float32)
        family = p.family
        vpow = p.tweedie_variance_power
        intercept = p.intercept

        @jax.jit
        def vg(params, l2):
            def nll(params):
                beta, icpt = params[:-1], params[-1]
                eta = Xf @ beta + (icpt if intercept else 0.0) + od
                if family == "gaussian":
                    per = 0.5 * (yd - eta) ** 2
                elif family in ("binomial", "quasibinomial"):
                    per = jax.nn.softplus(eta) - yd * eta
                elif family == "poisson":
                    per = jnp.exp(eta) - yd * eta
                elif family == "gamma":
                    per = yd * jnp.exp(-eta) + eta
                else:  # tweedie, log link
                    mu = jnp.exp(eta)
                    a = jnp.where(
                        yd > 0,
                        jnp.power(jnp.maximum(yd, 1e-10), 2 - vpow) / ((1 - vpow) * (2 - vpow)),
                        0.0,
                    )
                    per = a - yd * jnp.power(mu, 1 - vpow) / (1 - vpow) + jnp.power(mu, 2 - vpow) / (2 - vpow)
                return (wd * per).sum() / wsum + 0.5 * l2 * (beta ** 2).sum()

            return jax.value_and_grad(nll)(params)

        from scipy.optimize import minimize

        def solve(lam: float, beta0: np.ndarray) -> Tuple[np.ndarray, int]:
            l2 = jnp.float32(lam * (1 - p.alpha))

            def fun(x):
                v, g = vg(jnp.asarray(x, dtype=jnp.float32), l2)
                g = np.asarray(g, dtype=np.float64)
                if not intercept:
                    g[-1] = 0.0
                return float(v), g

            res = minimize(
                fun, beta0, jac=True, method="L-BFGS-B",
                options={"maxiter": max(p.max_iterations * 10, 100), "ftol": 1e-12},
            )
            return np.asarray(res.x, dtype=np.float64), int(res.nit)

        return solve

    # -- multinomial (GLM.java:1160 fitIRLSM multinomial: cyclic per-class) --

    def _fit_multinomial(
        self, model, info, X, X64, y, obs_w, offset, wsum, valid_data
    ) -> None:
        p: GLMParameters = self.params
        K = len(info.response_domain)
        n, pcols = X.shape
        yi = y.astype(np.int64)
        Y = np.zeros((n, K))
        Y[np.arange(n), yi] = 1.0
        priors = np.maximum(obs_w @ Y / wsum, 1e-10)
        B0 = np.zeros((pcols + 1, K))
        if p.intercept:
            B0[-1] = np.log(priors)

        null_mu = np.tile(priors, (n, 1))
        model.null_deviance = float(
            -2.0 * (obs_w * np.log(null_mu[np.arange(n), yi])).sum()
        )

        solver = "irlsm" if p.solver == "auto" else p.solver
        if solver == "lbfgs":
            mn_solve = self._make_multinomial_lbfgs(X64, Y, obs_w, wsum, pcols, K)
        else:
            Xd, pad = self._device_design(X)
            mn_solve = lambda lam, B0_: self._multinomial_irlsm(
                X64, Xd, pad, Y, yi, obs_w, offset, lam, B0_, wsum
            )

        if p.lambda_search:
            # lambda_max from the per-class null-model gradients
            g = X64.T @ (obs_w[:, None] * (Y - null_mu))
            lambdas = self._grid_from_gradient(g, wsum, n, pcols)
            dev_valid = None
            if valid_data is not None:
                Xv, yv, wv, ov = valid_data
                dev_valid = lambda B: self._multinomial_deviance(
                    Xv, B, ov, yv.astype(np.int64), wv
                )
            B = self._run_lambda_path(
                model, lambdas, mn_solve,
                dev_train=lambda B: self._multinomial_deviance(X64, B, offset, yi, obs_w),
                dev_valid=dev_valid,
                nonzeros=lambda B: int(np.sum(np.abs(B[:-1]) > 1e-12)),
                null_dev=model.null_deviance, state0=B0,
            )
        else:
            B, model.iterations = mn_solve(p.lambda_, B0)

        model.beta_multi = B
        model.residual_deviance = self._multinomial_deviance(X64, B, offset, yi, obs_w)
        coefs: Dict[str, Dict[str, float]] = {}
        for k, lv in enumerate(info.response_domain):
            b_raw, icpt = destandardize_coefs(info, B[:-1, k], B[-1, k])
            d = dict(zip(info.coef_names, b_raw.tolist()))
            d["Intercept"] = icpt
            coefs[lv] = d
        model.coefficients_multinomial = coefs
        # flat view for generic consumers: class-suffixed names
        model.coefficients = {
            f"{name}_{lv}": val
            for lv, d in coefs.items()
            for name, val in d.items()
        }

    def _multinomial_irlsm(
        self, X64, Xd, pad, Y, yi, obs_w, offset, lam, B0, wsum
    ) -> Tuple[np.ndarray, int]:
        """Cyclic per-class IRLS: for class c, a weighted least-squares solve
        with softmax weights mu_c(1-mu_c), recomputing the softmax after each
        class update (the reference's multinomial IRLSM sweep)."""
        p: GLMParameters = self.params
        l1 = lam * p.alpha
        l2 = lam * (1 - p.alpha)
        K = Y.shape[1]
        n = len(yi)
        B = B0.copy()
        eta = X64 @ B[:-1] + B[-1] + offset[:, None]
        prev_obj = np.inf
        iters = 0
        free = 1 if p.intercept else 0
        for it in range(p.max_iterations):
            max_delta = 0.0
            for c in range(K):
                mu = _softmax(eta)
                muc = np.clip(mu[:, c], 1e-10, 1 - 1e-10)
                vc = muc * (1 - muc)
                w = obs_w * vc
                wz = (eta[:, c] - offset) + (Y[:, c] - muc) / vc
                G, q = _gram(Xd, pad(wz), pad(w))
                if l1 > 0:
                    solved = _solve_admm(G / wsum, q / wsum, l1, l2, free=free)
                else:
                    solved = _solve_ridge(G / wsum, q / wsum, l2, free=free)
                bc = solved if p.intercept else np.append(solved, 0.0)
                max_delta = max(max_delta, float(np.max(np.abs(bc - B[:, c]))))
                B[:, c] = bc
                eta[:, c] = X64 @ bc[:-1] + bc[-1] + offset
            dev = self._multinomial_deviance(X64, B, offset, yi, obs_w)
            obj = dev / (2 * wsum) + lam * (
                p.alpha * np.abs(B[:-1]).sum() + (1 - p.alpha) / 2 * (B[:-1] ** 2).sum()
            )
            iters = it + 1
            if max_delta < p.beta_epsilon or abs(prev_obj - obj) < p.objective_epsilon * max(abs(prev_obj), 1.0):
                break
            prev_obj = obj
        return B, iters

    @staticmethod
    def _multinomial_deviance(X64, B, offset, yi, obs_w) -> float:
        eta = X64 @ B[:-1] + B[-1]
        if np.ndim(offset) == 1 and len(np.atleast_1d(offset)) == eta.shape[0]:
            eta = eta + np.asarray(offset)[:, None]
        mu = _softmax(eta)
        pi = np.clip(mu[np.arange(len(yi)), yi], 1e-15, 1.0)
        return float(-2.0 * (obs_w * np.log(pi)).sum())

    def _make_multinomial_lbfgs(self, X64, Y, obs_w, wsum, pcols, K):
        """Softmax cross-entropy L-BFGS over the full [P+1, K] coefficient
        block (the reference's multinomial L_BFGS solver path); one jitted
        value-and-grad program reused across a lambda path."""
        p: GLMParameters = self.params
        mesh = default_mesh()
        nshards = mesh.devices.size
        Xf = self._cached_upload(
            "glm_multinomial_x", mesh,
            lambda: shard_rows(X64.astype(np.float32), mesh)[0],
        )
        wd = jnp.asarray(pad_rows(obs_w, nshards)[0], dtype=jnp.float32)
        Yd = jnp.asarray(pad_rows(Y, nshards)[0], dtype=jnp.float32)
        intercept = p.intercept

        @jax.jit
        def vg(flat, l2):
            def nll(flat):
                B = flat.reshape(pcols + 1, K)
                eta = Xf @ B[:-1] + (B[-1] if intercept else 0.0)
                logp = jax.nn.log_softmax(eta, axis=1)
                ce = -(wd * (Yd * logp).sum(axis=1)).sum() / wsum
                return ce + 0.5 * l2 * (B[:-1] ** 2).sum()

            return jax.value_and_grad(nll)(flat)

        from scipy.optimize import minimize

        def solve(lam: float, B0: np.ndarray) -> Tuple[np.ndarray, int]:
            l2 = jnp.float32(lam * (1 - p.alpha))

            def fun(x):
                v, g = vg(jnp.asarray(x, dtype=jnp.float32), l2)
                g = np.asarray(g, dtype=np.float64).reshape(pcols + 1, K)
                if not intercept:
                    g[-1] = 0.0
                return float(v), g.ravel()

            res = minimize(
                fun, np.asarray(B0, dtype=np.float64).ravel(), jac=True,
                method="L-BFGS-B",
                options={"maxiter": max(p.max_iterations * 10, 200), "ftol": 1e-12},
            )
            return np.asarray(res.x, dtype=np.float64).reshape(pcols + 1, K), int(res.nit)

        return solve

    # -- ordinal (proportional odds / ologit; GLM.java ordinal solver) -------

    def _fit_ordinal(self, model, info, X, X64, y, obs_w, offset, wsum) -> None:
        """Cumulative-logit fit: shared beta + K-1 increasing thresholds,
        maximized by L-BFGS with a jitted device value-and-grad (the
        reference's ordinal gradient solver, GLMModel ordinal family)."""
        p: GLMParameters = self.params
        K = len(info.response_domain)
        if K < 2:
            raise ValueError("ordinal family needs a categorical response with >= 2 levels")
        n, pcols = X.shape
        l2 = p.lambda_ * (1 - p.alpha)
        mesh = default_mesh()
        nshards = mesh.devices.size
        Xf = self._cached_upload(
            "glm_ordinal_x", mesh, lambda: shard_rows(X, mesh)[0]
        )
        wd = jnp.asarray(pad_rows(obs_w, nshards)[0], dtype=jnp.float32)
        yk = jnp.asarray(pad_rows(y.astype(np.int32), nshards)[0], dtype=jnp.int32)
        od = jnp.asarray(pad_rows(offset, nshards)[0], dtype=jnp.float32)
        nth = K - 1

        @jax.jit
        def nll(params):
            beta = params[:pcols]
            a = params[pcols:]
            if nth > 1:
                t = jnp.concatenate([a[:1], a[:1] + jnp.cumsum(jax.nn.softplus(a[1:]))])
            else:
                t = a
            eta = Xf @ beta + od
            cum = jax.nn.sigmoid(t[None, :] - eta[:, None])  # [N, K-1]
            full = jnp.concatenate([cum, jnp.ones((cum.shape[0], 1))], axis=1)
            lower = jnp.concatenate([jnp.zeros((cum.shape[0], 1)), cum], axis=1)
            pk = jnp.clip(full - lower, 1e-12, 1.0)
            pi = jnp.take_along_axis(pk, yk[:, None], axis=1)[:, 0]
            return -(wd * jnp.log(pi)).sum() / wsum + 0.5 * l2 * (beta ** 2).sum()

        vg = jax.jit(jax.value_and_grad(nll))

        def fun(x):
            v, g = vg(jnp.asarray(x, dtype=jnp.float32))
            return float(v), np.asarray(g, dtype=np.float64)

        # threshold init from cumulative class priors (logit scale)
        yi = y.astype(np.int64)
        counts = np.bincount(yi, weights=obs_w, minlength=K)
        cp = np.clip(np.cumsum(counts)[:-1] / wsum, 1e-6, 1 - 1e-6)
        t0 = np.log(cp / (1 - cp))
        a0 = np.empty(nth)
        a0[0] = t0[0]
        if nth > 1:
            d = np.maximum(np.diff(t0), 1e-3)
            a0[1:] = np.log(np.expm1(d))  # softplus inverse
        x0 = np.concatenate([np.zeros(pcols), a0])

        from scipy.optimize import minimize

        res = minimize(
            fun, x0, jac=True, method="L-BFGS-B",
            options={"maxiter": max(p.max_iterations * 10, 200), "ftol": 1e-12},
        )
        sol = np.asarray(res.x, dtype=np.float64)
        model.iterations = int(res.nit)
        beta = sol[:pcols]
        a = sol[pcols:]
        t = (
            np.concatenate([a[:1], a[0] + np.cumsum(np.log1p(np.exp(a[1:])))])
            if nth > 1
            else a
        )
        model.beta_std = beta
        model.ordinal_thresholds = t

        b_raw, icpt_shift = destandardize_coefs(info, beta, 0.0)
        model.coefficients = dict(zip(info.coef_names, b_raw.tolist()))
        for k in range(nth):
            # raw-space threshold: P(y<=k) = sigmoid(t_k_raw - x.b_raw)
            model.coefficients[f"Threshold.{info.response_domain[k]}"] = float(t[k] - icpt_shift)
        model.coefficients_std = dict(zip(info.coef_names, beta.tolist()))

        probs = _ordinal_probs(X64 @ beta + offset, t)
        pi = probs[np.arange(n), yi]
        model.residual_deviance = float(-2.0 * (obs_w * np.log(pi)).sum())
        priors = np.maximum(counts / wsum, 1e-15)
        model.null_deviance = float(-2.0 * (obs_w * np.log(priors[yi])).sum())

    def _p_values(self, model, X, y, mu, obs_w, offset, link, p, info) -> None:
        d = _link_deriv(link, mu, p)
        v = _variance(p.family, mu, p)
        w = obs_w / np.maximum(v * d * d, 1e-12)
        Xi = np.concatenate([X.astype(np.float64), np.ones((len(y), 1))], axis=1)
        G = Xi.T @ (w[:, None] * Xi)
        cov = np.linalg.pinv(G)
        if p.family in ("gaussian", "gamma", "tweedie", "quasibinomial"):
            dof = max(len(y) - G.shape[0], 1)
            disp = float((obs_w * (y - mu) ** 2 / _variance(p.family, mu, p)).sum() / dof)
        else:
            disp = 1.0
        model.dispersion = disp
        se = np.sqrt(np.maximum(np.diag(cov) * disp, 0))
        zvals = model.beta_std / np.maximum(se, 1e-300)
        from scipy import stats as sps

        if p.family in ("gaussian",):
            pv = 2 * sps.t.sf(np.abs(zvals), df=max(len(y) - G.shape[0], 1))
        else:
            pv = 2 * sps.norm.sf(np.abs(zvals))
        names = info.coef_names + ["Intercept"]
        model.std_errors = dict(zip(names, se.tolist()))
        model.p_values = dict(zip(names, pv.tolist()))


def _link_of_mean(link: str, ybar: float, p: GLMParameters) -> float:
    eps = 1e-10
    if link == "identity":
        return ybar
    if link == "logit":
        yb = min(max(ybar, eps), 1 - eps)
        return float(np.log(yb / (1 - yb)))
    if link == "log":
        return float(np.log(max(ybar, eps)))
    if link == "inverse":
        return 1.0 / max(abs(ybar), eps) * (1 if ybar >= 0 else -1)
    if link == "tweedie":
        lp = p.tweedie_link_power
        return float(np.log(max(ybar, eps))) if lp == 0 else float(np.power(max(ybar, eps), lp))
    raise ValueError(link)


def _aic(family, y, mu, w, resid_dev, rank) -> float:
    n = len(y)
    eps = 1e-15
    if family == "gaussian":
        return float(n * np.log(2 * np.pi * resid_dev / n) + n + 2 * (rank + 1))
    if family == "binomial":
        mu = np.clip(mu, eps, 1 - eps)
        ll = float((w * (y * np.log(mu) + (1 - y) * np.log(1 - mu))).sum())
        return -2 * ll + 2 * rank
    if family == "poisson":
        from scipy.special import gammaln

        ll = float((w * (y * np.log(np.maximum(mu, eps)) - mu - gammaln(y + 1))).sum())
        return -2 * ll + 2 * rank
    return float("nan")  # gamma/tweedie AIC needs dispersion MLE (as in reference: NaN unless computed)
