"""GLM — generalized linear models via IRLSM with a device-side Gram.

Reference: ``hex/glm/GLM.java`` (3.8k LoC; IRLSM driver GLM.java:1160,
1184-1222), ``hex/glm/GLMTask.java:1502`` (GLMIterationTask: one MRTask pass
computes the weighted Gram X'WX + X'Wz), ``hex/gram/Gram.java:452`` (Cholesky),
``hex/optimization/ADMM.java`` (L1 via ADMM soft-thresholding),
``hex/glm/GLMModel.java:268-334`` (families/links).

TPU-native: the per-iteration distributed pass is ONE jitted matmul —
``X.T @ (W[:,None] * X)`` on the row-sharded design matrix; XLA inserts the
psum over the data axis (sharded-in, replicated-out), which IS the MRTask
reduce. The tiny (P+1)^2 solve (Cholesky or ADMM inner loop) runs on the
host in float64, mirroring the reference where the Gram solve happens on the
driver node. The design matrix comes from DataInfo (one-hot cats,
standardization) exactly as in the reference.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.data_info import (
    DataInfo,
    build_data_info,
    destandardize_coefs,
    expand_matrix,
    response_vector,
)
from h2o3_tpu.models.framework import Model, ModelBuilder, ModelParameters
from h2o3_tpu.parallel.mesh import default_mesh, pad_rows, shard_rows

FAMILIES = (
    "gaussian", "binomial", "quasibinomial", "poisson", "gamma", "tweedie",
    "multinomial", "ordinal",
)

_DEFAULT_LINK = {
    "gaussian": "identity",
    "binomial": "logit",
    "quasibinomial": "logit",
    "poisson": "log",
    "gamma": "log",
    "tweedie": "tweedie",
    "multinomial": "multinomial",  # softmax
    "ordinal": "ologit",  # cumulative logit (proportional odds)
}

SOLVERS = ("auto", "irlsm", "lbfgs")


@dataclass
class GLMParameters(ModelParameters):
    family: str = "gaussian"
    link: str = "family_default"
    alpha: float = 0.5
    lambda_: float = 0.0
    lambda_search: bool = False
    nlambdas: int = 30
    standardize: bool = True
    intercept: bool = True
    max_iterations: int = 50
    beta_epsilon: float = 1e-4
    objective_epsilon: float = 1e-6
    tweedie_variance_power: float = 1.5
    tweedie_link_power: float = 0.0
    compute_p_values: bool = False
    missing_values_handling: str = "mean_imputation"
    solver: str = "auto"  # auto|irlsm|lbfgs (GLMModel.java:268-334 solver enum)
    lambda_min_ratio: float = 0.0  # 0 = auto: 1e-4 if n > p else 1e-2

    def actual_link(self) -> str:
        return _DEFAULT_LINK[self.family] if self.link == "family_default" else self.link


# ---------------------------------------------------------------------------
# family math (hex/glm/GLMModel.GLMParameters link/variance/deviance defs)


def _linkinv(link: str, eta: np.ndarray, p: GLMParameters) -> np.ndarray:
    if link == "identity":
        return eta
    if link == "logit":
        return 1.0 / (1.0 + np.exp(-eta))
    if link == "log":
        return np.exp(eta)
    if link == "inverse":
        return 1.0 / np.where(np.abs(eta) < 1e-10, np.sign(eta + 1e-30) * 1e-10, eta)
    if link == "tweedie":
        lp = p.tweedie_link_power
        return np.exp(eta) if lp == 0 else np.power(np.maximum(eta, 1e-10), 1.0 / lp)
    raise ValueError(f"unknown link {link}")


def _link_deriv(link: str, mu: np.ndarray, p: GLMParameters) -> np.ndarray:
    """d eta / d mu."""
    if link == "identity":
        return np.ones_like(mu)
    if link == "logit":
        return 1.0 / np.maximum(mu * (1 - mu), 1e-10)
    if link == "log":
        return 1.0 / np.maximum(mu, 1e-10)
    if link == "inverse":
        return -1.0 / np.maximum(mu**2, 1e-10)
    if link == "tweedie":
        lp = p.tweedie_link_power
        if lp == 0:
            return 1.0 / np.maximum(mu, 1e-10)
        return lp * np.power(np.maximum(mu, 1e-10), lp - 1)
    raise ValueError(f"unknown link {link}")


def _variance(family: str, mu: np.ndarray, p: GLMParameters) -> np.ndarray:
    if family == "gaussian":
        return np.ones_like(mu)
    if family in ("binomial", "quasibinomial"):
        return np.maximum(mu * (1 - mu), 1e-10)
    if family == "poisson":
        return np.maximum(mu, 1e-10)
    if family == "gamma":
        return np.maximum(mu**2, 1e-10)
    if family == "tweedie":
        return np.power(np.maximum(mu, 1e-10), p.tweedie_variance_power)
    raise ValueError(f"unknown family {family}")


def deviance(family: str, y: np.ndarray, mu: np.ndarray, p: GLMParameters) -> np.ndarray:
    """Per-row unit deviance (hex/Distribution.java / GLMModel deviance defs)."""
    eps = 1e-10
    if family == "gaussian":
        return (y - mu) ** 2
    if family in ("binomial", "quasibinomial"):
        mu = np.clip(mu, eps, 1 - eps)
        return -2 * (y * np.log(mu) + (1 - y) * np.log(1 - mu))
    if family == "poisson":
        mu = np.maximum(mu, eps)
        t = np.where(y > 0, y * np.log(np.where(y > 0, y, 1.0) / mu), 0.0)
        return 2 * (t - (y - mu))
    if family == "gamma":
        mu = np.maximum(mu, eps)
        ys = np.maximum(y, eps)
        return -2 * (np.log(ys / mu) - (ys - mu) / mu)
    if family == "tweedie":
        vp = p.tweedie_variance_power
        mu = np.maximum(mu, eps)
        ys = np.maximum(y, 0.0)
        a = np.where(ys > 0, np.power(np.maximum(ys, eps), 2 - vp) / ((1 - vp) * (2 - vp)), 0.0)
        b = ys * np.power(mu, 1 - vp) / (1 - vp)
        c = np.power(mu, 2 - vp) / (2 - vp)
        return 2 * (a - b + c)
    raise ValueError(f"unknown family {family}")


# ---------------------------------------------------------------------------
# the distributed pass: weighted Gram via one sharded matmul


@jax.jit
def _gram_kernel(Xw, wz, w):
    """X'WX and X'Wz in one pass. Xw:[N,P+1] (with intercept col), w:[N]."""
    WX = Xw * w[:, None]
    g = Xw.T @ WX  # psum over the sharded data axis is implicit
    q = Xw.T @ (w * wz)
    return g, q


def _gram(Xd, wz, w):
    g, q = _gram_kernel(Xd, jnp.asarray(wz, dtype=Xd.dtype), jnp.asarray(w, dtype=Xd.dtype))
    return np.asarray(jax.device_get(g), dtype=np.float64), np.asarray(
        jax.device_get(q), dtype=np.float64
    )


# ---------------------------------------------------------------------------
# host-side solvers (the reference solves the Gram on the driver node too)


def _solve_ridge(G: np.ndarray, q: np.ndarray, l2: float, free: int) -> np.ndarray:
    """(G + l2*I) b = q, no penalty on the last ``free`` coefs (intercept)."""
    A = G.copy()
    n = A.shape[0]
    pen = n - free
    A[np.arange(pen), np.arange(pen)] += l2
    A[np.arange(n), np.arange(n)] += 1e-10  # jitter for singular one-hot blocks
    try:
        from scipy.linalg import cho_factor, cho_solve

        return cho_solve(cho_factor(A, lower=True), q)
    except Exception:
        return np.linalg.lstsq(A, q, rcond=None)[0]


def _solve_admm(
    G: np.ndarray, q: np.ndarray, l1: float, l2: float, free: int, iters: int = 500, tol: float = 1e-7
) -> np.ndarray:
    """Elastic-net quadratic subproblem via ADMM (hex/optimization/ADMM.java):
    min 1/2 b'Gb - q'b + l1*|b|_1 + l2/2*|b|^2, intercept unpenalized."""
    n = G.shape[0]
    pen = n - free
    rho = float(np.mean(np.diag(G))) + l2 + 1e-6
    A = G.copy()
    A[np.arange(pen), np.arange(pen)] += l2 + rho
    A[np.arange(pen, n), np.arange(pen, n)] += rho
    A[np.arange(n), np.arange(n)] += 1e-10
    from scipy.linalg import cho_factor, cho_solve

    cf = cho_factor(A, lower=True)
    z = np.zeros(n)
    u = np.zeros(n)
    for _ in range(iters):
        x = cho_solve(cf, q + rho * (z - u))
        z_old = z
        xu = x + u
        z = np.concatenate(
            [np.sign(xu[:pen]) * np.maximum(np.abs(xu[:pen]) - l1 / rho, 0.0), xu[pen:]]
        )
        u = xu - z
        if np.max(np.abs(z - z_old)) < tol and np.max(np.abs(x - z)) < tol:
            break
    return z


# ---------------------------------------------------------------------------
# model


class GLMModel(Model):
    algo_name = "glm"

    def __init__(self, params: GLMParameters, data_info: DataInfo) -> None:
        super().__init__(params, data_info)
        self.coefficients: Dict[str, float] = {}
        self.coefficients_std: Dict[str, float] = {}
        self.beta_std: Optional[np.ndarray] = None  # [P+1] incl intercept, std space
        self.null_deviance: float = np.nan
        self.residual_deviance: float = np.nan
        self.aic: float = np.nan
        self.dispersion: float = 1.0
        self.std_errors: Optional[Dict[str, float]] = None
        self.p_values: Optional[Dict[str, float]] = None
        self.iterations: int = 0

    def _eta(self, frame: Frame) -> np.ndarray:
        X, _ = expand_matrix(self.data_info, frame, dtype=np.float64)
        b = self.beta_std
        eta = X @ b[:-1] + b[-1]
        if self.params.offset_column:
            eta = eta + frame.col(self.params.offset_column).numeric_view()
        return eta

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        p: GLMParameters = self.params
        mu = _linkinv(p.actual_link(), self._eta(frame), p)
        if p.family in ("binomial", "quasibinomial"):
            return np.stack([1 - mu, mu], axis=1)
        return mu


class GLM(ModelBuilder):
    """Builder (reference driver loop: hex/glm/GLM.java:1160 fitIRLSM)."""

    algo_name = "glm"

    def __init__(self, params: Optional[GLMParameters] = None, **kw) -> None:
        super().__init__(params or GLMParameters(**kw))

    def _validate(self, frame: Frame) -> None:
        super()._validate(frame)
        p: GLMParameters = self.params
        if p.family not in FAMILIES:
            raise ValueError(f"family must be one of {FAMILIES}, got {p.family!r}")
        if not (0 <= p.alpha <= 1):
            raise ValueError("alpha must be in [0, 1]")
        if p.lambda_ < 0:
            raise ValueError("lambda must be >= 0")
        if p.compute_p_values and p.lambda_ > 0:
            raise ValueError("p-values require lambda = 0 (no regularization)")

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> GLMModel:
        p: GLMParameters = self.params
        link = p.actual_link()
        if p.family in ("binomial", "quasibinomial"):
            # the reference requires a categorical response for binomial
            # families; a numeric 0/1 column is auto-converted (as_factor)
            ycol = frame.col(p.response_column)
            if not ycol.is_categorical():
                frame = frame.add_column(ycol.as_factor())
                if valid is not None:
                    valid = valid.add_column(valid.col(p.response_column).as_factor())
        info = build_data_info(
            frame,
            y=p.response_column,
            ignored=p.ignored_columns,
            standardize=p.standardize,
            missing_values_handling=p.missing_values_handling,
        )
        model = GLMModel(p, info)

        X, skip = expand_matrix(info, frame, dtype=np.float32)
        y = response_vector(info, frame)
        obs_w = (
            frame.col(p.weights_column).numeric_view().astype(np.float64)
            if p.weights_column
            else np.ones(frame.nrows)
        )
        offset = (
            frame.col(p.offset_column).numeric_view().astype(np.float64)
            if p.offset_column
            else np.zeros(frame.nrows)
        )
        keep = ~(skip | np.isnan(y) | np.isnan(obs_w))
        X, y, obs_w, offset = X[keep], y[keep], obs_w[keep], offset[keep]
        n, pcols = X.shape
        if n == 0:
            raise ValueError("no rows left after NA handling")

        # device placement: row-sharded [N, P(+1 intercept col when enabled)]
        mesh = default_mesh()
        nshards = mesh.devices.size
        Xi = (
            np.concatenate([X, np.ones((n, 1), dtype=np.float32)], axis=1)
            if p.intercept
            else X
        )
        Xd, _ = shard_rows(Xi, mesh)
        pad = lambda a: pad_rows(a, nshards)[0]

        X64 = X.astype(np.float64)  # host copy for eta/deviance (made once)
        wsum = float(obs_w.sum())
        ybar = float((obs_w * y).sum() / wsum)
        beta = np.zeros(pcols + 1)
        # intercept warm start at the link of the response mean (GLM.java init)
        if p.intercept:
            beta[-1] = _link_of_mean(link, ybar, p)
        l1 = p.lambda_ * p.alpha * wsum
        l2 = p.lambda_ * (1 - p.alpha) * wsum

        prev_obj = np.inf
        for it in range(p.max_iterations):
            eta = X64 @ beta[:-1] + beta[-1] + offset
            mu = _linkinv(link, eta, p)
            d = _link_deriv(link, mu, p)
            v = _variance(p.family, mu, p)
            w = obs_w / np.maximum(v * d * d, 1e-12)
            wz = (eta - offset) + (y - mu) * d

            G, q = _gram(Xd, pad(wz), pad(w))
            free = 1 if p.intercept else 0
            if l1 > 0:
                solved = _solve_admm(G / wsum, q / wsum, l1 / wsum, l2 / wsum, free=free)
            else:
                solved = _solve_ridge(G / wsum, q / wsum, l2 / wsum, free=free)
            # without an intercept the ones column is excluded from the solve
            # entirely (clamping after solving would converge to wrong coefs)
            beta_new = solved if p.intercept else np.append(solved, 0.0)

            dev = float((obs_w * deviance(p.family, y, _linkinv(link, X64 @ beta_new[:-1] + beta_new[-1] + offset, p), p)).sum())
            obj = dev / (2 * wsum) + p.lambda_ * (
                p.alpha * np.abs(beta_new[:-1]).sum() + (1 - p.alpha) / 2 * (beta_new[:-1] ** 2).sum()
            )
            delta = np.max(np.abs(beta_new - beta))
            beta = beta_new
            model.iterations = it + 1
            if delta < p.beta_epsilon or abs(prev_obj - obj) < p.objective_epsilon * max(abs(prev_obj), 1.0):
                prev_obj = obj
                break
            prev_obj = obj

        model.beta_std = beta
        b_raw, icpt = destandardize_coefs(info, beta[:-1], beta[-1])
        model.coefficients = dict(zip(info.coef_names, b_raw.tolist()))
        model.coefficients["Intercept"] = icpt
        model.coefficients_std = dict(zip(info.coef_names, beta[:-1].tolist()))
        model.coefficients_std["Intercept"] = float(beta[-1])

        # deviances + AIC (GLMModel.GLMOutput)
        mu = _linkinv(link, X64 @ beta[:-1] + beta[-1] + offset, p)
        model.residual_deviance = float((obs_w * deviance(p.family, y, mu, p)).sum())
        mu0 = np.full_like(y, ybar)
        model.null_deviance = float((obs_w * deviance(p.family, y, mu0, p)).sum())
        rank = int(np.sum(np.abs(beta[:-1]) > 0)) + (1 if p.intercept else 0)
        model.aic = _aic(p.family, y, mu, obs_w, model.residual_deviance, rank)

        if p.compute_p_values and p.lambda_ == 0:
            self._p_values(model, X, y, mu, obs_w, offset, link, p, info)

        model.training_metrics = model.model_performance(frame)
        if valid is not None:
            model.validation_metrics = model.model_performance(valid)
        return model

    def _p_values(self, model, X, y, mu, obs_w, offset, link, p, info) -> None:
        d = _link_deriv(link, mu, p)
        v = _variance(p.family, mu, p)
        w = obs_w / np.maximum(v * d * d, 1e-12)
        Xi = np.concatenate([X.astype(np.float64), np.ones((len(y), 1))], axis=1)
        G = Xi.T @ (w[:, None] * Xi)
        cov = np.linalg.pinv(G)
        if p.family in ("gaussian", "gamma", "tweedie", "quasibinomial"):
            dof = max(len(y) - G.shape[0], 1)
            disp = float((obs_w * (y - mu) ** 2 / _variance(p.family, mu, p)).sum() / dof)
        else:
            disp = 1.0
        model.dispersion = disp
        se = np.sqrt(np.maximum(np.diag(cov) * disp, 0))
        zvals = model.beta_std / np.maximum(se, 1e-300)
        from scipy import stats as sps

        if p.family in ("gaussian",):
            pv = 2 * sps.t.sf(np.abs(zvals), df=max(len(y) - G.shape[0], 1))
        else:
            pv = 2 * sps.norm.sf(np.abs(zvals))
        names = info.coef_names + ["Intercept"]
        model.std_errors = dict(zip(names, se.tolist()))
        model.p_values = dict(zip(names, pv.tolist()))


def _link_of_mean(link: str, ybar: float, p: GLMParameters) -> float:
    eps = 1e-10
    if link == "identity":
        return ybar
    if link == "logit":
        yb = min(max(ybar, eps), 1 - eps)
        return float(np.log(yb / (1 - yb)))
    if link == "log":
        return float(np.log(max(ybar, eps)))
    if link == "inverse":
        return 1.0 / max(abs(ybar), eps) * (1 if ybar >= 0 else -1)
    if link == "tweedie":
        lp = p.tweedie_link_power
        return float(np.log(max(ybar, eps))) if lp == 0 else float(np.power(max(ybar, eps), lp))
    raise ValueError(link)


def _aic(family, y, mu, w, resid_dev, rank) -> float:
    n = len(y)
    eps = 1e-15
    if family == "gaussian":
        return float(n * np.log(2 * np.pi * resid_dev / n) + n + 2 * (rank + 1))
    if family == "binomial":
        mu = np.clip(mu, eps, 1 - eps)
        ll = float((w * (y * np.log(mu) + (1 - y) * np.log(1 - mu))).sum())
        return -2 * ll + 2 * rank
    if family == "poisson":
        from scipy.special import gammaln

        ll = float((w * (y * np.log(np.maximum(mu, eps)) - mu - gammaln(y + 1))).sum())
        return -2 * ll + 2 * rank
    return float("nan")  # gamma/tweedie AIC needs dispersion MLE (as in reference: NaN unless computed)
