"""Model metrics — the ModelMetrics* hierarchy, TPU-native.

Reference: ``hex/ModelMetrics*.java`` (~30 classes), ``hex/AUC2.java`` (AUC via
a 400-bin threshold histogram, ``AUC2.java:36`` NBINS=400), ``hex/ConfusionMatrix``,
GainsLift. Metric definitions below match the reference's semantics:

  * AUC: trapezoidal over the threshold-histogram ROC. ``nbins=400`` gives the
    reference's approximation; ``nbins=0`` computes the exact (perfect) AUC,
    equivalent to ``AUC2.perfectAUC`` (``AUC2.java:589``).
  * Max-F1 threshold is the default classification threshold, as in
    ``AUC2.defaultThreshold`` / ``ThresholdCriterion.f1``.
  * Deviances per family follow ``hex/Distribution.java`` definitions.

Inputs are host numpy arrays (predictions already gathered); each metric is a
cheap O(N) or O(N log N) pass. Device-side streaming computation plugs in at
the compute layer when metrics are fused into scoring loops.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# shared helpers


def _weighted(x: np.ndarray, w: Optional[np.ndarray]) -> Tuple[np.ndarray, float]:
    if w is None:
        w = np.ones_like(x, dtype=np.float64)
    return w.astype(np.float64), float(w.sum())


# ---------------------------------------------------------------------------
# regression


@dataclass
class RegressionMetrics:
    mse: float
    rmse: float
    mae: float
    rmsle: float
    mean_residual_deviance: float
    r2: float
    nobs: int

    def __repr__(self) -> str:
        return (
            f"RegressionMetrics(rmse={self.rmse:.6g}, mse={self.mse:.6g}, "
            f"mae={self.mae:.6g}, r2={self.r2:.4f}, "
            f"mean_residual_deviance={self.mean_residual_deviance:.6g})"
        )


def regression_metrics(
    actual: np.ndarray,
    predicted: np.ndarray,
    weights: Optional[np.ndarray] = None,
    deviance: Optional[np.ndarray] = None,
) -> RegressionMetrics:
    y = np.asarray(actual, dtype=np.float64)
    p = np.asarray(predicted, dtype=np.float64)
    ok = ~(np.isnan(y) | np.isnan(p))
    y, p = y[ok], p[ok]
    w, wsum = _weighted(y, None if weights is None else np.asarray(weights)[ok])
    err = y - p
    mse = float(np.sum(w * err**2) / wsum)
    mae = float(np.sum(w * np.abs(err)) / wsum)
    if np.all(y >= 0) and np.all(p >= 0):
        rmsle = float(np.sqrt(np.sum(w * (np.log1p(p) - np.log1p(y)) ** 2) / wsum))
    else:
        rmsle = float("nan")
    ybar = float(np.sum(w * y) / wsum)
    ss_tot = float(np.sum(w * (y - ybar) ** 2))
    r2 = 1.0 - np.sum(w * err**2) / ss_tot if ss_tot > 0 else float("nan")
    mrd = (
        float(np.sum(w * deviance[ok]) / wsum)
        if deviance is not None
        else mse  # gaussian deviance == squared error (hex/Distribution.java)
    )
    return RegressionMetrics(
        mse=mse,
        rmse=float(np.sqrt(mse)),
        mae=mae,
        rmsle=rmsle,
        mean_residual_deviance=mrd,
        r2=float(r2),
        nobs=int(len(y)),
    )


# ---------------------------------------------------------------------------
# binomial


@dataclass
class ConfusionMatrix:
    """2x2 at a threshold: [[tn, fp], [fn, tp]] (hex/ConfusionMatrix.java layout
    is domain x domain with actual rows, predicted columns)."""

    tn: float
    fp: float
    fn: float
    tp: float
    threshold: float

    @property
    def table(self) -> np.ndarray:
        return np.array([[self.tn, self.fp], [self.fn, self.tp]])

    @property
    def accuracy(self) -> float:
        t = self.tn + self.fp + self.fn + self.tp
        return (self.tn + self.tp) / t if t else float("nan")

    @property
    def precision(self) -> float:
        d = self.tp + self.fp
        return self.tp / d if d else float("nan")

    @property
    def recall(self) -> float:
        d = self.tp + self.fn
        return self.tp / d if d else float("nan")

    @property
    def specificity(self) -> float:
        d = self.tn + self.fp
        return self.tn / d if d else float("nan")

    @property
    def f1(self) -> float:
        p, r = self.precision, self.recall
        return 2 * p * r / (p + r) if (p + r) else float("nan")

    @property
    def mcc(self) -> float:
        d = np.sqrt(
            (self.tp + self.fp) * (self.tp + self.fn) * (self.tn + self.fp) * (self.tn + self.fn)
        )
        return ((self.tp * self.tn - self.fp * self.fn) / d) if d else float("nan")


@dataclass
class BinomialMetrics:
    auc: float
    pr_auc: float
    gini: float
    logloss: float
    mse: float
    rmse: float
    mean_per_class_error: float
    max_f1_threshold: float
    cm: ConfusionMatrix
    nobs: int
    thresholds: np.ndarray = field(default_factory=lambda: np.empty(0), repr=False)
    tps: np.ndarray = field(default_factory=lambda: np.empty(0), repr=False)
    fps: np.ndarray = field(default_factory=lambda: np.empty(0), repr=False)

    def confusion_matrix(self, threshold: Optional[float] = None) -> ConfusionMatrix:
        return self.cm if threshold is None else _cm_at(self.thresholds, self.tps, self.fps, self._p, self._n, threshold)

    _p: float = 0.0
    _n: float = 0.0

    def __repr__(self) -> str:
        return (
            f"BinomialMetrics(auc={self.auc:.6f}, logloss={self.logloss:.6f}, "
            f"pr_auc={self.pr_auc:.6f}, rmse={self.rmse:.6g}, "
            f"max_f1_threshold={self.max_f1_threshold:.4f})"
        )


def _roc_points(
    actual: np.ndarray, prob: np.ndarray, weights: Optional[np.ndarray], nbins: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, float, float]:
    """Sorted-descending unique thresholds with cumulative tp/fp counts.

    nbins=0 → exact (one threshold per distinct score, AUC2.perfectAUC);
    nbins=400 → the reference's histogram approximation (AUC2.java:36).
    """
    y = np.asarray(actual, dtype=np.float64)
    p = np.asarray(prob, dtype=np.float64)
    ok = ~(np.isnan(y) | np.isnan(p))
    y, p = y[ok], p[ok]
    w, _ = _weighted(y, None if weights is None else np.asarray(weights)[ok])
    if nbins and len(np.unique(p)) > nbins:
        # histogram thresholds: uniform quantile-ish bin centers over score range
        edges = np.quantile(p, np.linspace(0, 1, nbins + 1))
        centers = np.unique(edges)
        idx = np.clip(np.searchsorted(centers, p, side="right") - 1, 0, len(centers) - 1)
        p = centers[idx]
    order = np.argsort(-p, kind="stable")
    ps, ys, ws = p[order], y[order], w[order]
    pos_w = np.where(ys > 0.5, ws, 0.0)
    neg_w = np.where(ys > 0.5, 0.0, ws)
    cum_tp = np.cumsum(pos_w)
    cum_fp = np.cumsum(neg_w)
    # keep last occurrence of each distinct threshold
    last = np.ones(len(ps), dtype=bool)
    last[:-1] = ps[:-1] != ps[1:]
    return ps[last], cum_tp[last], cum_fp[last], float(pos_w.sum()), float(neg_w.sum())


def _cm_at(ths, tps, fps, P, N, threshold) -> ConfusionMatrix:
    i = np.searchsorted(-ths, -threshold, side="right") - 1
    tp = tps[i] if i >= 0 else 0.0
    fp = fps[i] if i >= 0 else 0.0
    return ConfusionMatrix(tn=N - fp, fp=fp, fn=P - tp, tp=tp, threshold=float(threshold))


def binomial_metrics(
    actual: np.ndarray,
    prob: np.ndarray,
    weights: Optional[np.ndarray] = None,
    nbins: int = 0,
) -> BinomialMetrics:
    """Binomial metrics from actual labels {0,1} and P(class=1)."""
    y = np.asarray(actual, dtype=np.float64)
    p = np.asarray(prob, dtype=np.float64)
    ok = ~(np.isnan(y) | np.isnan(p))
    y, p = y[ok], p[ok]
    w, wsum = _weighted(y, None if weights is None else np.asarray(weights)[ok])

    ths, tps, fps, P, N = _roc_points(y, p, w, nbins)
    if P == 0 or N == 0:
        auc = pr = float("nan")
    else:
        tpr = np.concatenate([[0.0], tps / P])
        fpr = np.concatenate([[0.0], fps / N])
        auc = float(np.trapezoid(tpr, fpr))
        prec = tps / np.maximum(tps + fps, 1e-300)
        rec = tps / P
        # PR-AUC by trapezoid over recall (reference pr_auc, AUC2.java:288)
        pr = float(np.trapezoid(np.concatenate([[prec[0]], prec]), np.concatenate([[0.0], rec])))

    eps = 1e-15
    pc = np.clip(p, eps, 1 - eps)
    logloss = float(np.sum(w * -(y * np.log(pc) + (1 - y) * np.log(1 - pc))) / wsum)
    mse = float(np.sum(w * (y - p) ** 2) / wsum)

    # max-F1 threshold scan (default threshold, AUC2 ThresholdCriterion.f1)
    if P > 0 and N > 0 and len(ths):
        precs = tps / np.maximum(tps + fps, 1e-300)
        recs = tps / P
        f1s = np.where(precs + recs > 0, 2 * precs * recs / np.maximum(precs + recs, 1e-300), 0.0)
        best = int(np.argmax(f1s))
        thr = float(ths[best])
    else:
        thr = 0.5
    cm = _cm_at(ths, tps, fps, P, N, thr) if len(ths) else ConfusionMatrix(N, 0, P, 0, thr)
    tpr_ = cm.tp / P if P else float("nan")
    tnr_ = cm.tn / N if N else float("nan")
    mpce = float(1 - (tpr_ + tnr_) / 2)

    m = BinomialMetrics(
        auc=auc,
        pr_auc=pr,
        gini=2 * auc - 1 if auc == auc else float("nan"),
        logloss=logloss,
        mse=mse,
        rmse=float(np.sqrt(mse)),
        mean_per_class_error=mpce,
        max_f1_threshold=thr,
        cm=cm,
        nobs=int(len(y)),
        thresholds=ths,
        tps=tps,
        fps=fps,
    )
    m._p, m._n = P, N
    return m


# ---------------------------------------------------------------------------
# multinomial


@dataclass
class MultinomialMetrics:
    logloss: float
    mse: float
    rmse: float
    mean_per_class_error: float
    confusion_matrix: np.ndarray
    hit_ratios: np.ndarray  # top-k hit ratio, k=1..K (hex/HitRatio semantics)
    domain: List[str]
    nobs: int

    def __repr__(self) -> str:
        return (
            f"MultinomialMetrics(logloss={self.logloss:.6f}, "
            f"mean_per_class_error={self.mean_per_class_error:.4f}, "
            f"top1={self.hit_ratios[0]:.4f})"
        )


def multinomial_metrics(
    actual: np.ndarray,
    probs: np.ndarray,
    domain: List[str],
    weights: Optional[np.ndarray] = None,
    max_hit_ratio_k: int = 10,
) -> MultinomialMetrics:
    """actual: int class ids [N]; probs: [N, K] class probabilities."""
    y = np.asarray(actual)
    P = np.asarray(probs, dtype=np.float64)
    ok = y >= 0
    y, P = y[ok].astype(np.int64), P[ok]
    w, wsum = _weighted(y.astype(np.float64), None if weights is None else np.asarray(weights)[ok])
    K = P.shape[1]
    eps = 1e-15
    py = np.clip(P[np.arange(len(y)), y], eps, 1.0)
    logloss = float(np.sum(w * -np.log(py)) / wsum)
    # MSE over the 1-of-K residual (reference ModelMetricsMultinomial)
    onehot = np.zeros_like(P)
    onehot[np.arange(len(y)), y] = 1.0
    mse = float(np.sum(w[:, None] * (onehot - P) ** 2) / wsum)
    pred = P.argmax(axis=1)
    cm = np.zeros((K, K), dtype=np.float64)
    np.add.at(cm, (y, pred), w)
    row = cm.sum(axis=1)
    per_class_err = np.where(row > 0, 1 - np.diag(cm) / np.maximum(row, 1e-300), np.nan)
    mpce = float(np.nanmean(per_class_err))
    # top-k hit ratios
    kk = min(max_hit_ratio_k, K)
    ranks = np.argsort(-P, axis=1)[:, :kk]
    hits = ranks == y[:, None]
    hr = (hits.astype(np.float64) * w[:, None]).sum(axis=0) if len(y) else np.zeros(kk)
    hit_ratios = np.cumsum(hr) / wsum
    return MultinomialMetrics(
        logloss=logloss,
        mse=mse,
        rmse=float(np.sqrt(mse)),
        mean_per_class_error=mpce,
        confusion_matrix=cm,
        hit_ratios=hit_ratios,
        domain=list(domain),
        nobs=int(len(y)),
    )


# ---------------------------------------------------------------------------
# early stopping — exact ScoreKeeper.stopEarly semantics


#: metrics where larger is better (ScoreKeeper.StoppingMetric convergence strategies)
MORE_IS_BETTER = {"auc", "pr_auc", "r2", "accuracy", "f1", "lift_top_group"}
#: metrics bounded below by 0 (ScoreKeeper IStoppingMetric.isLowerBoundBy0)
LOWER_BOUND_0 = {"deviance", "logloss", "mse", "rmse", "mae", "rmsle", "misclassification", "anomaly_score"}


def stop_early(
    history: List[float],
    stopping_rounds: int,
    more_is_better: bool,
    stopping_tolerance: float,
) -> bool:
    """Replicates hex/ScoreKeeper.stopEarly (ScoreKeeper.java:261-337):
    k+1 simple moving averages of window k over the last 2k scoring events
    (skipping the first event); converged when the best of the k new averages
    fails to improve on the reference average by rel tolerance."""
    k = stopping_rounds
    if k == 0:
        return False
    if len(history) - 1 < 2 * k:
        return False
    vals = np.asarray(history, dtype=np.float64)
    mov = np.empty(k + 1)
    for i in range(k + 1):
        start = len(vals) - 2 * k + i
        mov[i] = vals[start : start + k].mean()
        if np.isnan(mov[i]):
            return False
    last_before = mov[0]
    min_in, max_in = mov[1:].min(), mov[1:].max()
    if not more_is_better and last_before == 0.0:
        return True  # converged to lower bound
    if np.sign(mov.max()) != np.sign(mov.min()):
        return False  # zero crossing — don't divide
    if more_is_better:
        ratio = max_in / last_before
        return bool(not np.isnan(ratio) and ratio <= 1 + stopping_tolerance)
    ratio = min_in / last_before
    return bool(not np.isnan(ratio) and ratio >= 1 - stopping_tolerance)


# ---------------------------------------------------------------------------
# DKV-resident scoring records + makeMetrics


@dataclass
class ScoringRecord:
    """A cached scoring result, queryable over REST.

    Reference: ``hex/ModelMetrics.java`` ``buildKey``/``getFromDKV`` —
    scoring a frame with a model leaves a ModelMetrics object in the DKV
    keyed by (model, frame), which the 10 /3/ModelMetrics routes fetch,
    filter and delete."""

    model_id: str
    frame_id: str
    metrics: object
    model_category: str
    scoring_time: float

    @staticmethod
    def key_for(model_id: str, frame_id: str) -> str:
        return f"modelmetrics_{model_id}@{frame_id}"


def make_metrics(
    predictions: np.ndarray,
    actuals: np.ndarray,
    domain: Optional[List[str]] = None,
    distribution: str = "gaussian",
    weights: Optional[np.ndarray] = None,
):
    """Build metrics from raw predictions + actuals with no model.

    Reference: ``ModelMetricsHandler.make`` (the ``h2o.make_metrics``
    client call): a domain means classification (binomial for 2 levels,
    multinomial above), otherwise regression under ``distribution``.

    ``predictions`` column conventions match the reference's: regression
    takes one column; binomial takes p1 directly, [p0 p1], or
    [predict p0 p1] (the extra leading column is the label and is
    dropped); multinomial likewise K or 1+K columns.
    """
    P = np.asarray(predictions, dtype=np.float64)
    if P.ndim == 1:
        P = P[:, None]
    if domain is None:
        if P.shape[1] != 1:
            raise ValueError(
                f"regression expects 1 prediction column, got {P.shape[1]}")
        y = np.asarray(actuals, dtype=np.float64)
        dev = None
        if distribution and distribution != "gaussian":
            from h2o3_tpu.models.glm import GLMParameters, deviance

            dev = deviance(distribution, y, P[:, 0],
                           GLMParameters(response_column=""))
        return regression_metrics(y, P[:, 0], weights=weights, deviance=dev)
    K = len(domain)
    if K == 2:
        if P.shape[1] == 1:
            p1 = P[:, 0]
        elif P.shape[1] == 2:
            p1 = P[:, 1]
        elif P.shape[1] == 3:
            p1 = P[:, 2]
        else:
            raise ValueError(
                f"binomial expects 1, 2 or 3 prediction columns, got {P.shape[1]}")
        return binomial_metrics(np.asarray(actuals, dtype=np.float64), p1,
                                weights=weights)
    if P.shape[1] == K + 1:
        P = P[:, 1:]
    if P.shape[1] != K:
        raise ValueError(
            f"multinomial expects {K} or {K + 1} prediction columns, "
            f"got {P.shape[1]}")
    return multinomial_metrics(np.asarray(actuals).astype(np.int64), P,
                               domain, weights=weights)
