"""DeepLearning — multilayer perceptron, TPU-native.

Reference: ``hex/deeplearning`` (5.7k LoC) — MLP with SGD+momentum or
ADADELTA, dropout, L1/L2, autoencoder mode; per-layer fprop/bprop hand-coded
(``Neurons.java:184-229``); parallelism is per-node Hogwild racy updates plus
cross-node model averaging each iteration (``DeepLearningModelInfo.java:70``,
``DeepLearningTask.java:50-62,125``).

TPU-native redesign (SURVEY.md §2.4): Hogwild and model averaging are replaced
by SYNCHRONOUS minibatch data-parallel SGD — the batch is row-sharded over the
mesh, parameters are replicated, and XLA inserts the gradient all-reduce; this
is both deterministic and faster on TPU (racy updates don't exist in SPMD).
Forward/backward come from ``jax.grad`` instead of hand-coded bprop; the MXU
sees one [B, in]x[in, out] matmul per layer. Optimizers via optax
(ADADELTA to match the reference's adaptive_rate default, SGD+momentum with
rate annealing otherwise). Dropout/L1/L2/autoencoder semantics preserved.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
import optax

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models import metrics as M
from h2o3_tpu.models.data_info import build_data_info, expand_matrix, response_vector
from h2o3_tpu.models.framework import Model, ModelBuilder, ModelParameters
from h2o3_tpu.parallel.mesh import default_mesh, row_sharding


@dataclass
class DeepLearningParameters(ModelParameters):
    hidden: List[int] = field(default_factory=lambda: [200, 200])
    activation: str = "rectifier"  # rectifier|tanh|maxout(≈rectifier here)
    epochs: float = 10.0
    mini_batch_size: int = 256  # reference default is 1 (Hogwild); sync DP wants real batches
    adaptive_rate: bool = True  # ADADELTA (rho/epsilon), as in the reference
    rho: float = 0.99
    epsilon: float = 1e-8
    rate: float = 0.005
    rate_annealing: float = 1e-6
    momentum_start: float = 0.0
    momentum_ramp: float = 1e6  # samples over which momentum ramps (reference default)
    momentum_stable: float = 0.0
    input_dropout_ratio: float = 0.0
    hidden_dropout_ratios: Optional[List[float]] = None
    l1: float = 0.0
    l2: float = 0.0
    loss: str = "auto"  # auto|cross_entropy|quadratic|absolute
    distribution: str = "auto"
    standardize: bool = True
    autoencoder: bool = False
    score_interval: int = 1  # epochs between scoring events


def _activation(name: str):
    return {
        "rectifier": jax.nn.relu,
        "relu": jax.nn.relu,
        "tanh": jnp.tanh,
        "maxout": jax.nn.relu,  # maxout pieces degrade to relu in this build
    }[name]


def _init_params(key, sizes: List[int]) -> List[Tuple[jnp.ndarray, jnp.ndarray]]:
    """He-uniform init (reference: UniformAdaptive initial_weight_distribution)."""
    params = []
    for i in range(len(sizes) - 1):
        key, sub = jax.random.split(key)
        fan_in, fan_out = sizes[i], sizes[i + 1]
        bound = jnp.sqrt(6.0 / (fan_in + fan_out))
        W = jax.random.uniform(sub, (fan_in, fan_out), jnp.float32, -bound, bound)
        params.append((W, jnp.zeros(fan_out, jnp.float32)))
    return params


def _forward(params, x, act, dropout_key=None, input_dropout=0.0, hidden_dropout=None):
    h = x
    if dropout_key is not None and input_dropout > 0:
        dropout_key, sub = jax.random.split(dropout_key)
        keep = jax.random.bernoulli(sub, 1 - input_dropout, h.shape)
        h = jnp.where(keep, h / (1 - input_dropout), 0.0)
    n_layers = len(params)
    for i, (W, b) in enumerate(params):
        h = h @ W + b
        if i < n_layers - 1:
            h = act(h)
            if dropout_key is not None and hidden_dropout is not None and hidden_dropout[i] > 0:
                dropout_key, sub = jax.random.split(dropout_key)
                keep = jax.random.bernoulli(sub, 1 - hidden_dropout[i], h.shape)
                h = jnp.where(keep, h / (1 - hidden_dropout[i]), 0.0)
    return h


class DeepLearningModel(Model):
    algo_name = "deeplearning"

    def __init__(self, params, data_info, loss_kind: str):
        super().__init__(params, data_info)
        self.net_params = None
        self.loss_kind = loss_kind
        self.epochs_trained = 0.0
        #: flattened optimizer-state leaves, kept so checkpoint-continue
        #: resumes ADADELTA accumulators / momentum / step counters exactly
        self.opt_leaves = None

    def _forward_np(self, frame: Frame) -> np.ndarray:
        X, _ = expand_matrix(self.data_info, frame, dtype=np.float32)
        out = _forward(self.net_params, jnp.asarray(X), _activation(self.params.activation))
        return np.asarray(jax.device_get(out))

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        out = self._forward_np(frame)
        if self.params.autoencoder:
            return out
        if self.is_classifier:
            z = out - out.max(axis=1, keepdims=True)
            e = np.exp(z)
            return e / e.sum(axis=1, keepdims=True)
        return out[:, 0]

    def predict(self, frame: Frame) -> Frame:
        if not self.params.autoencoder:
            return super().predict(frame)
        # reconstruction frame, one column per design-matrix coefficient
        # (reference: DeepLearningModel scoreAutoEncoder reconstruction output)
        from h2o3_tpu.frame.frame import ColType, Column

        rec = self._forward_np(frame)
        names = self.data_info.coef_names
        return Frame(
            [Column(f"reconstr_{names[i]}", rec[:, i].astype(np.float64), ColType.NUM)
             for i in range(rec.shape[1])]
        )

    def anomaly(self, frame: Frame) -> np.ndarray:
        """Autoencoder per-row reconstruction MSE (reference: DeepLearningModel
        scoreAutoEncoder)."""
        assert self.params.autoencoder, "anomaly() requires autoencoder=True"
        X, _ = expand_matrix(self.data_info, frame, dtype=np.float32)
        rec = np.asarray(jax.device_get(
            _forward(self.net_params, jnp.asarray(X), _activation(self.params.activation))
        ))
        return ((rec - X) ** 2).mean(axis=1)


class DeepLearning(ModelBuilder):

    SUPPORTED_COMMON = frozenset(
        {"stopping_rounds", "checkpoint", "max_runtime_secs"}
    )
    algo_name = "deeplearning"

    def _resolve_checkpoint(self, info, loss_kind: str):
        """checkpoint-continue (SharedTree.java:131-136 covers DL via
        CheckpointUtils): validate the non-modifiable params match, return
        the prior model. ``epochs`` is the TOTAL target, like trees' ntrees."""
        p = self.params
        if not p.checkpoint:
            return None
        from h2o3_tpu.keyed import DKV

        prior = DKV.get(p.checkpoint)
        if prior is None:
            raise ValueError(f"checkpoint model {p.checkpoint!r} not found")
        if getattr(prior, "algo_name", None) != self.algo_name:
            raise ValueError("checkpoint model is not a deeplearning model")
        pp = prior.params
        for f in ("hidden", "activation", "adaptive_rate", "standardize",
                  "autoencoder", "mini_batch_size"):
            if getattr(pp, f) != getattr(p, f):
                raise ValueError(
                    f"checkpoint {f}={getattr(pp, f)!r} differs from "
                    f"requested {getattr(p, f)!r}"
                )
        if prior.data_info.coef_names != info.coef_names:
            raise ValueError("checkpoint design-matrix layout differs from this frame")
        if prior.data_info.response_domain != info.response_domain:
            # different classes (or order) would gather out-of-range labels
            # against the prior output layer — silently, under jit
            raise ValueError("checkpoint response domain differs from this frame")
        if prior.loss_kind != loss_kind:
            raise ValueError("checkpoint loss differs from this training setup")
        if p.epochs <= prior.epochs_trained:
            raise ValueError(
                f"checkpoint already has {prior.epochs_trained} epochs; "
                f"epochs={p.epochs} must exceed it"
            )
        return prior

    def __init__(self, params: Optional[DeepLearningParameters] = None, **kw) -> None:
        super().__init__(params or DeepLearningParameters(**kw))

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> DeepLearningModel:
        p: DeepLearningParameters = self.params
        info = build_data_info(
            frame,
            y=None if p.autoencoder else p.response_column,
            ignored=p.ignored_columns,
            standardize=p.standardize,
            use_all_factor_levels=True,
        )
        X, _ = expand_matrix(info, frame, dtype=np.float32)
        n, d_in = X.shape

        if p.autoencoder:
            nclasses, y_codes = 1, None
            d_out, loss_kind = d_in, "quadratic"
            Y = X
        else:
            y = response_vector(info, frame)
            keep = ~np.isnan(y)
            X, y = X[keep], y[keep]
            n = len(y)
            nclasses = len(info.response_domain) if info.response_domain else 1
            if nclasses > 1:
                d_out, loss_kind = nclasses, "cross_entropy"
                Y = y.astype(np.int32)
            else:
                d_out, loss_kind = 1, "quadratic" if p.loss in ("auto", "quadratic") else p.loss
                Y = y.astype(np.float32)

        # resolve (and validate) the checkpoint BEFORE constructing the
        # model: Model.__init__ registers in the DKV, and a failed
        # validation must not leak a phantom untrained model
        prior = self._resolve_checkpoint(info, loss_kind)
        model = DeepLearningModel(p, info, loss_kind)
        act = _activation(p.activation)
        sizes = [d_in] + list(p.hidden) + [d_out]
        base_seed = p.actual_seed()
        base_key = jax.random.PRNGKey(base_seed)
        if prior is not None:
            net = [
                (jnp.asarray(W), jnp.asarray(b)) for W, b in prior.net_params
            ]
        else:
            _, init_key = jax.random.split(base_key)
            net = _init_params(init_key, sizes)

        use_momentum = (p.momentum_start > 0) or (p.momentum_stable > 0)
        if p.adaptive_rate:
            opt = optax.adadelta(learning_rate=1.0, rho=p.rho, eps=p.epsilon)
        else:
            sched = (
                optax.schedules.exponential_decay(p.rate, 1, 1.0 / (1.0 + p.rate_annealing))
                if p.rate_annealing > 0
                else p.rate
            )
            if use_momentum:
                # momentum ramps linearly from start to stable over momentum_ramp
                # samples (reference: Neurons momentum(), momentum_ramp param)
                def mom_sched(step):
                    samples = step * float(p.mini_batch_size)
                    frac = jnp.clip(samples / max(p.momentum_ramp, 1.0), 0.0, 1.0)
                    return p.momentum_start + (p.momentum_stable - p.momentum_start) * frac

                opt = optax.inject_hyperparams(
                    lambda momentum: optax.sgd(sched, momentum=momentum)
                )(momentum=mom_sched)
            else:
                opt = optax.sgd(sched)
        opt_state = opt.init(net)
        # getattr: models saved before opt_leaves existed decode without it
        if prior is not None and getattr(prior, "opt_leaves", None) is not None:
            # resume the optimizer exactly (accumulators + step counters)
            treedef = jax.tree_util.tree_structure(opt_state)
            leaves = [jnp.asarray(l) for l in prior.opt_leaves]
            if len(leaves) != treedef.num_leaves:
                raise ValueError("checkpoint optimizer state is incompatible")
            opt_state = jax.tree_util.tree_unflatten(treedef, leaves)

        hidden_do = tuple(p.hidden_dropout_ratios) if p.hidden_dropout_ratios else None

        def loss_fn(net, xb, yb, dk):
            out = _forward(net, xb, act, dk, p.input_dropout_ratio, hidden_do)
            if loss_kind == "cross_entropy":
                ll = optax.softmax_cross_entropy_with_integer_labels(out, yb)
                data_loss = ll.mean()
            elif loss_kind == "absolute":
                data_loss = jnp.abs(out[:, 0] - yb).mean()
            elif p.autoencoder:
                data_loss = ((out - yb) ** 2).mean()
            else:
                data_loss = ((out[:, 0] - yb) ** 2).mean()
            reg = sum(p.l1 * jnp.abs(W).sum() + p.l2 * (W**2).sum() for W, _ in net)
            return data_loss + reg

        @jax.jit
        def train_step(net, opt_state, xb, yb, dk):
            loss, grads = jax.value_and_grad(loss_fn)(net, xb, yb, dk)
            updates, opt_state = opt.update(grads, opt_state, net)
            net = optax.apply_updates(net, updates)
            return net, opt_state, loss

        mesh = default_mesh()
        nshards = mesh.devices.size
        bs = max(p.mini_batch_size, nshards)
        bs -= bs % nshards  # static sharded batch shape
        steps_per_epoch = max(n // bs, 1)
        total_epochs = int(np.ceil(p.epochs))
        start_epoch = int(prior.epochs_trained) if prior is not None else 0
        history: List[float] = []
        import time as _time

        deadline = (
            _time.time() + p.max_runtime_secs if p.max_runtime_secs > 0 else None
        )

        # RNG keyed by ABSOLUTE epoch/step index: k epochs then k more
        # reproduces a straight 2k-epoch run exactly (same design as the
        # tree booster's absolute-tree-index keys)
        for epoch in range(start_epoch, total_epochs):
            perm = np.random.default_rng(
                base_seed + 1_000_003 * (epoch + 1)
            ).permutation(n)
            ekey = jax.random.fold_in(base_key, epoch + 1)
            for s in range(steps_per_epoch):
                idx = perm[s * bs : (s + 1) * bs]
                if len(idx) < bs:  # static shapes: cycle the permutation
                    idx = np.resize(perm, bs)
                xb = jax.device_put(X[idx], row_sharding(mesh, 2))
                yb = jax.device_put(Y[idx], row_sharding(mesh, Y.ndim))
                dk = jax.random.fold_in(ekey, s)
                net, opt_state, loss = train_step(net, opt_state, xb, yb, dk)
            model.epochs_trained = epoch + 1
            if deadline is not None and _time.time() >= deadline:
                break
            if p.stopping_rounds > 0 and (epoch + 1) % p.score_interval == 0:
                history.append(float(jax.device_get(loss)))
                if M.stop_early(
                    history, p.stopping_rounds, more_is_better=False,
                    stopping_tolerance=p.stopping_tolerance,
                ):
                    break
            if self.job is not None:
                self.job.update((epoch + 1) / total_epochs)

        model.net_params = jax.device_get(net)
        model.opt_leaves = [
            np.asarray(l) for l in jax.tree_util.tree_leaves(jax.device_get(opt_state))
        ]
        if not p.autoencoder:
            model.training_metrics = model.model_performance(frame)
            if valid is not None:
                model.validation_metrics = model.model_performance(valid)
        return model
