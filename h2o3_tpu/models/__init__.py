from h2o3_tpu.models.framework import Job, Model, ModelBuilder, ModelParameters
from h2o3_tpu.models import metrics

__all__ = ["Job", "Model", "ModelBuilder", "ModelParameters", "metrics"]
