"""MOJO writer: trained models -> portable scoring artifacts.

Reference: ``hex/ModelMojoWriter.java:65-77`` (zip of ``model.ini`` + binary
blobs per algo; per-algo writers in ``h2o-algos/.../hex/*/...MojoWriter``).
The archive layout here: ``model.ini`` (human-readable summary),
``meta.json`` (algo scalars), ``data_info.json`` (design-matrix layout),
``arrays.npz`` (weights/trees/centers).  Read back by the numpy-only
``h2o3_tpu.genmodel`` package.
"""

from __future__ import annotations

import dataclasses
import io
import json
import zipfile
from typing import Any, Dict, Tuple

import numpy as np

from h2o3_tpu.models.framework import Model

Payload = Tuple[Dict[str, Any], Dict[str, np.ndarray]]


def _info_dict(model: Model) -> Dict[str, Any]:
    d = dataclasses.asdict(model.data_info)
    return d


def _payload(model: Model) -> Payload:
    """Dispatch to the per-algo payload builder (the *MojoWriter analogue)."""
    from h2o3_tpu.models.deeplearning import DeepLearningModel
    from h2o3_tpu.models.glm import GLMModel
    from h2o3_tpu.models.isolation_forest import IsolationForestModel
    from h2o3_tpu.models.kmeans import KMeansModel
    from h2o3_tpu.models.naive_bayes import NaiveBayesModel
    from h2o3_tpu.models.pca import PCAModel
    from h2o3_tpu.models.tree.common import TreeModelBase

    if isinstance(model, GLMModel):
        p = model.params
        meta = {
            "algo": "glm",
            "family": p.family,
            "link": p.actual_link(),
            "tweedie_link_power": p.tweedie_link_power,
            "offset_column": p.offset_column,
        }
        if p.family == "multinomial":
            return meta, {"beta_multi": np.asarray(model.beta_multi, dtype=np.float64)}
        if p.family == "ordinal":
            return meta, {
                # ordinal beta_std is [P] (no intercept slot; the thresholds
                # play that role) — see GLMModel._predict_raw ordinal branch
                "beta_std": np.asarray(model.beta_std, dtype=np.float64),
                "thresholds": np.asarray(model.ordinal_thresholds, dtype=np.float64),
            }
        return meta, {"beta_std": np.asarray(model.beta_std, dtype=np.float64)}

    if isinstance(model, TreeModelBase):
        from h2o3_tpu.models.tree.drf import DRFModel

        b = model.booster
        t0 = b.trees_per_class[0]
        if isinstance(model, DRFModel):
            # DRF classification = averaged votes, clipped + normalized
            # (not a link function; DRFModel._predict_raw)
            transform = "drf_votes" if model.is_classifier else "identity"
        elif model.distribution in ("bernoulli", "multinomial"):
            transform = model.distribution
        elif model.distribution in ("poisson", "gamma", "tweedie"):
            transform = "exp"  # log-link: margin -> response scale
        else:
            transform = "identity"
        meta = {
            "algo": model.algo_name,
            "distribution": model.distribution,
            "transform": transform,
            "n_bins1": int(t0.n_bins1),
            "max_depth": int(t0.max_depth),
            "average": bool(b.average),
            "tree_encoding": getattr(model, "tree_encoding", "label_encoder"),
            # offset models shift the margin by the scoring frame's offset
            # column (Model.java offset handling) — the MOJO must too
            "offset_column": getattr(model.params, "offset_column", None),
        }
        arrays: Dict[str, np.ndarray] = {
            "edges": np.asarray(t0.edges, dtype=np.float64),
            "init_margin": np.asarray(b.init_margin, dtype=np.float64),
        }
        for c, trees in enumerate(b.trees_per_class):
            arrays[f"feat_{c}"] = np.stack(trees.feat).astype(np.int32)
            arrays[f"split_bin_{c}"] = np.stack(trees.split_bin).astype(np.int32)
            arrays[f"default_left_{c}"] = np.stack(trees.default_left).astype(bool)
            arrays[f"is_split_{c}"] = np.stack(trees.is_split).astype(bool)
            arrays[f"leaf_{c}"] = np.stack(trees.leaf).astype(np.float32)
        return meta, arrays

    if isinstance(model, KMeansModel):
        return {"algo": "kmeans"}, {
            "centers_std": np.asarray(model.centers_std, dtype=np.float64),
            "centers": np.asarray(model.centers, dtype=np.float64),
        }

    if isinstance(model, DeepLearningModel):
        p = model.params
        arrays = {}
        for i, (W, bia) in enumerate(model.net_params):
            arrays[f"W_{i}"] = np.asarray(W, dtype=np.float32)
            arrays[f"b_{i}"] = np.asarray(bia, dtype=np.float32)
        meta = {
            "algo": "deeplearning",
            "activation": p.activation.lower(),
            "n_layers": len(model.net_params),
            "autoencoder": bool(p.autoencoder),
        }
        return meta, arrays

    if isinstance(model, NaiveBayesModel):
        arrays = {"priors": np.asarray(model.priors, dtype=np.float64)}
        for name, v in model.num_mean.items():
            arrays[f"mean_{name}"] = np.asarray(v, dtype=np.float64)
        for name, v in model.num_sd.items():
            arrays[f"sd_{name}"] = np.asarray(v, dtype=np.float64)
        for name, v in model.cat_probs.items():
            arrays[f"cat_{name}"] = np.asarray(v, dtype=np.float64)
        return {"algo": "naivebayes"}, arrays

    if isinstance(model, IsolationForestModel):
        feat, thresh, is_split, path_len = model.trees
        return (
            {
                "algo": "isolation_forest",
                "max_depth": int(model.max_depth),
                "c_norm": float(model._cn),
            },
            {
                "feat": np.asarray(feat, dtype=np.int32),
                "thresh": np.asarray(thresh, dtype=np.float64),
                "is_split": np.asarray(is_split, dtype=bool),
                "path_len": np.asarray(path_len, dtype=np.float64),
            },
        )

    if isinstance(model, PCAModel):
        arrays = {
            "eigenvectors": np.asarray(model.eigenvectors, dtype=np.float64)
        }
        # demean/descale statistics live OUTSIDE the design-matrix layout;
        # without them the offline scorer would project un-transformed rows
        # onto transformed-space eigenvectors
        if model.transform_sub is not None:
            arrays["transform_sub"] = np.asarray(
                model.transform_sub, dtype=np.float64)
        if model.transform_mul is not None:
            arrays["transform_mul"] = np.asarray(
                model.transform_mul, dtype=np.float64)
        return {"algo": "pca"}, arrays

    raise ValueError(f"MOJO export not supported for {type(model).__name__}")


def write_mojo(model: Model, path: str) -> str:
    """Model.getMojo / ModelMojoWriter.writeTo: serialize to a .mojo zip."""
    meta, arrays = _payload(model)
    info = _info_dict(model)
    # binomial label threshold: offline labels must match in-cluster
    # Model.predict — an explicit reset_threshold wins over the
    # training max-F1 point
    thr = getattr(model, "_threshold_override", None)
    if thr is None:
        thr = getattr(model.training_metrics, "max_f1_threshold", None)
    if thr is not None and np.isfinite(thr):
        meta["default_threshold"] = float(thr)
    buf = io.BytesIO()
    np.savez_compressed(buf, **arrays)
    ini_lines = [
        "[info]",
        f"algo = {meta['algo']}",
        f"mojo_version = 1.0",
        f"model_key = {model.key}",
        f"nclasses = {model.nclasses}",
        f"n_predictors = {len(model.data_info.predictor_names)}",
        "",
        "[columns]",
        *model.data_info.predictor_names,
    ]
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("model.ini", "\n".join(ini_lines) + "\n")
        z.writestr("meta.json", json.dumps(meta, indent=1))
        z.writestr("data_info.json", json.dumps(info, indent=1))
        z.writestr("arrays.npz", buf.getvalue())
    return path
