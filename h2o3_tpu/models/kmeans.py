"""KMeans — Lloyd's iterations as sharded device passes.

Reference: ``hex/kmeans/KMeans.java:688,725`` — kmeans++ ("PlusPlus") /
Furthest / Random init, standardized features, Lloyd's assign+recompute as an
MRTask per iteration, within-cluster SS metrics.

TPU-native: one jitted iteration computes [N,k] distances via the
|x|²-2x·C+|C|² matmul expansion (MXU), argmin assignment, and new centers via
a one-hot-matmul segment-mean (``onehot(assign)ᵀ @ X``) — all on row-sharded
arrays with implicit psum; no per-chunk loops.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.data_info import build_data_info, expand_matrix
from h2o3_tpu.models.framework import Model, ModelBuilder, ModelParameters
from h2o3_tpu.parallel.mesh import default_mesh, row_mask, shard_rows


@dataclass
class KMeansParameters(ModelParameters):
    k: int = 3
    max_iterations: int = 10
    init: str = "plus_plus"  # plus_plus|random|furthest
    standardize: bool = True
    estimate_k: bool = False


@partial(jax.jit, static_argnames=("k",))
def _lloyd_step(X, mask, C, k: int):
    """One Lloyd iteration. X:[N,D] sharded, C:[k,D] replicated."""
    d2 = (
        jnp.sum(X * X, axis=1, keepdims=True)
        - 2.0 * X @ C.T
        + jnp.sum(C * C, axis=1)[None, :]
    )  # [N, k]
    assign = jnp.argmin(d2, axis=1)
    # pad rows are zeroed (not inf-ed) everywhere they aggregate: 0*inf = NaN
    d2z = jnp.where(mask[:, None], d2, 0.0)
    onehot = jax.nn.one_hot(assign, k, dtype=X.dtype) * mask[:, None].astype(X.dtype)
    sums = onehot.T @ X  # [k, D] — psum implicit over the sharded axis
    counts = onehot.sum(axis=0)  # [k]
    newC = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), C)
    per_cluster_wss = (onehot * d2z).sum(axis=0)
    wss = per_cluster_wss.sum()
    return assign, newC, counts, wss, per_cluster_wss


class KMeansModel(Model):
    algo_name = "kmeans"

    def __init__(self, params, data_info):
        super().__init__(params, data_info)
        self.centers_std: Optional[np.ndarray] = None  # standardized space
        self.centers: Optional[np.ndarray] = None  # original space (numeric cols)
        self.size: Optional[np.ndarray] = None
        self.withinss: Optional[np.ndarray] = None
        self.tot_withinss: float = np.nan
        self.totss: float = np.nan
        self.betweenss: float = np.nan
        self.iterations: int = 0

    @property
    def is_classifier(self) -> bool:
        return False

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        X, _ = expand_matrix(self.data_info, frame, dtype=np.float32)
        C = self.centers_std
        d2 = (X * X).sum(1, keepdims=True) - 2 * X @ C.T + (C * C).sum(1)[None, :]
        return d2.argmin(axis=1).astype(np.float64)

    def model_performance(self, frame: Frame):
        return {
            "tot_withinss": self.tot_withinss,
            "totss": self.totss,
            "betweenss": self.betweenss,
            "size": self.size,
        }


class KMeans(ModelBuilder):
    algo_name = "kmeans"

    def __init__(self, params: Optional[KMeansParameters] = None, **kw) -> None:
        super().__init__(params or KMeansParameters(**kw))

    def _validate(self, frame: Frame) -> None:
        super()._validate(frame)
        if self.params.k < 1:
            raise ValueError("k must be >= 1")

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> KMeansModel:
        p: KMeansParameters = self.params
        info = build_data_info(
            frame, y=None, ignored=p.ignored_columns,
            standardize=p.standardize, use_all_factor_levels=True,
        )
        X, _ = expand_matrix(info, frame, dtype=np.float32)
        n, D = X.shape
        model = KMeansModel(p, info)
        rng = np.random.default_rng(p.actual_seed())

        mesh = default_mesh()
        from h2o3_tpu.frame import devcache as _devcache

        Xd = _devcache.cached(
            "kmeans_x", _devcache.frame_token(frame),
            (p.standardize, tuple(p.ignored_columns)), mesh,
            lambda: shard_rows(X, mesh)[0],
            frame_key=getattr(frame, "key", None),
        )
        maskd = row_mask(n, Xd.shape[0], mesh)

        def run_lloyd(C0: np.ndarray):
            """Lloyd to convergence from C0; returns the fitted state."""
            k = C0.shape[0]
            Cd = jnp.asarray(C0)
            prev_wss = np.inf
            iters = 0
            assign = counts = wss_k = None
            wss = np.inf
            for it in range(p.max_iterations):
                assign, Cd, counts, wss, wss_k = _lloyd_step(
                    Xd, maskd, Cd, k)
                iters = it + 1
                wss = float(jax.device_get(wss))
                if abs(prev_wss - wss) < 1e-6 * max(abs(prev_wss), 1.0):
                    break
                prev_wss = wss
            return (np.asarray(jax.device_get(Cd), np.float64),
                    np.asarray(jax.device_get(counts), np.int64),
                    np.asarray(jax.device_get(wss_k), np.float64),
                    wss, iters, np.asarray(jax.device_get(assign)))

        if p.estimate_k:
            # KMeans.java estimate_k (:278,301,398-414): deterministic —
            # start at k=1, split the largest cluster each outer round,
            # stop when relative tot_withinss improvement drops under
            # min(0.02 + 10/n + 2.5/F², 0.8); k is the CAP
            cutoff = min(0.02 + 10.0 / max(n, 1) + 2.5 / max(D, 1) ** 2,
                         0.8)
            C = X.mean(axis=0, keepdims=True).astype(np.float32)
            best = run_lloyd(C)
            prev_wss = best[3]
            total_iters = best[4]
            for k in range(2, p.k + 1):
                C = _split_largest_cluster(X, best[0], best[5], maskd)
                cur = run_lloyd(C)
                total_iters += cur[4]
                rel = (1.0 if prev_wss == 0
                       else (prev_wss - cur[3]) / prev_wss)
                if k > 1 and rel < cutoff:
                    break  # keep the previous (best) model
                best = cur
                prev_wss = cur[3]
            centers_std, counts, wss_k, _wss, _it, _assign = best
            model.iterations = total_iters
        else:
            C = _init_centers(X, p.k, p.init, rng)
            centers_std, counts, wss_k, _wss, iters, _assign = run_lloyd(C)
            model.iterations = iters

        model.centers_std = centers_std
        model.size = counts
        model.withinss = wss_k
        model.tot_withinss = float(model.withinss.sum())
        gmean = X.mean(axis=0)
        model.totss = float(((X - gmean) ** 2).sum())
        model.betweenss = model.totss - model.tot_withinss
        model.centers = _destandardize_centers(info, model.centers_std)
        model.training_metrics = model.model_performance(frame)
        return model


def _split_largest_cluster(X: np.ndarray, C: np.ndarray,
                           assign_padded: np.ndarray, maskd) -> np.ndarray:
    """KMeans.splitLargestCluster analogue, deterministic: the cluster
    with the most rows donates a second center at its farthest member."""
    import jax as _jax

    mask = np.asarray(_jax.device_get(maskd))
    assign = np.asarray(assign_padded)[: len(X)]
    mask = mask[: len(X)]
    assign = np.where(mask, assign, -1)
    counts = np.bincount(assign[assign >= 0], minlength=C.shape[0])
    big = int(counts.argmax())
    rows = np.nonzero(assign == big)[0]
    if len(rows) <= 1:  # nothing to split: duplicate with a nudge
        new = C[big] + 1e-3
    else:
        d2 = ((X[rows] - C[big].astype(np.float32)) ** 2).sum(axis=1)
        new = X[rows[int(d2.argmax())]]
    return np.vstack([C, new[None, :]]).astype(np.float32)


def _init_centers(X: np.ndarray, k: int, init: str, rng) -> np.ndarray:
    n = len(X)
    if init == "random":
        return X[rng.choice(n, k, replace=False)].copy()
    # kmeans++ / furthest share the distance-seeded loop (KMeans.java init)
    centers = [X[rng.integers(n)]]
    d2 = ((X - centers[0]) ** 2).sum(axis=1)
    for _ in range(1, k):
        if init == "furthest":
            centers.append(X[int(d2.argmax())])
        else:  # plus_plus: sample proportional to d²
            probs = d2 / max(d2.sum(), 1e-30)
            centers.append(X[rng.choice(n, p=probs)])
        d2 = np.minimum(d2, ((X - centers[-1]) ** 2).sum(axis=1))
    return np.stack(centers)


def _destandardize_centers(info, C_std: np.ndarray) -> np.ndarray:
    C = C_std.copy()
    j = 0
    for name in info.predictor_names:
        if name in info.cat_domains:
            j += len(info.cat_domains[name])
        else:
            if info.standardize:
                C[:, j] = C_std[:, j] * info.num_sds[name] + info.num_means[name]
            j += 1
    return C
