"""KMeans — Lloyd's iterations as sharded device passes.

Reference: ``hex/kmeans/KMeans.java:688,725`` — kmeans++ ("PlusPlus") /
Furthest / Random init, standardized features, Lloyd's assign+recompute as an
MRTask per iteration, within-cluster SS metrics.

TPU-native: one jitted iteration computes [N,k] distances via the
|x|²-2x·C+|C|² matmul expansion (MXU), argmin assignment, and new centers via
a one-hot-matmul segment-mean (``onehot(assign)ᵀ @ X``) — all on row-sharded
arrays with implicit psum; no per-chunk loops.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.models.data_info import build_data_info, expand_matrix
from h2o3_tpu.models.framework import Model, ModelBuilder, ModelParameters
from h2o3_tpu.parallel.mesh import default_mesh, row_mask, shard_rows


@dataclass
class KMeansParameters(ModelParameters):
    k: int = 3
    max_iterations: int = 10
    init: str = "plus_plus"  # plus_plus|random|furthest
    standardize: bool = True
    estimate_k: bool = False


@partial(jax.jit, static_argnames=("k",))
def _lloyd_step(X, mask, C, k: int):
    """One Lloyd iteration. X:[N,D] sharded, C:[k,D] replicated."""
    d2 = (
        jnp.sum(X * X, axis=1, keepdims=True)
        - 2.0 * X @ C.T
        + jnp.sum(C * C, axis=1)[None, :]
    )  # [N, k]
    assign = jnp.argmin(d2, axis=1)
    # pad rows are zeroed (not inf-ed) everywhere they aggregate: 0*inf = NaN
    d2z = jnp.where(mask[:, None], d2, 0.0)
    onehot = jax.nn.one_hot(assign, k, dtype=X.dtype) * mask[:, None].astype(X.dtype)
    sums = onehot.T @ X  # [k, D] — psum implicit over the sharded axis
    counts = onehot.sum(axis=0)  # [k]
    newC = jnp.where(counts[:, None] > 0, sums / jnp.maximum(counts[:, None], 1.0), C)
    per_cluster_wss = (onehot * d2z).sum(axis=0)
    wss = per_cluster_wss.sum()
    return assign, newC, counts, wss, per_cluster_wss


class KMeansModel(Model):
    algo_name = "kmeans"

    def __init__(self, params, data_info):
        super().__init__(params, data_info)
        self.centers_std: Optional[np.ndarray] = None  # standardized space
        self.centers: Optional[np.ndarray] = None  # original space (numeric cols)
        self.size: Optional[np.ndarray] = None
        self.withinss: Optional[np.ndarray] = None
        self.tot_withinss: float = np.nan
        self.totss: float = np.nan
        self.betweenss: float = np.nan
        self.iterations: int = 0

    @property
    def is_classifier(self) -> bool:
        return False

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        X, _ = expand_matrix(self.data_info, frame, dtype=np.float32)
        C = self.centers_std
        d2 = (X * X).sum(1, keepdims=True) - 2 * X @ C.T + (C * C).sum(1)[None, :]
        return d2.argmin(axis=1).astype(np.float64)

    def model_performance(self, frame: Frame):
        return {
            "tot_withinss": self.tot_withinss,
            "totss": self.totss,
            "betweenss": self.betweenss,
            "size": self.size,
        }


class KMeans(ModelBuilder):
    algo_name = "kmeans"

    def __init__(self, params: Optional[KMeansParameters] = None, **kw) -> None:
        super().__init__(params or KMeansParameters(**kw))

    def _validate(self, frame: Frame) -> None:
        super()._validate(frame)
        if self.params.k < 1:
            raise ValueError("k must be >= 1")
        if self.params.estimate_k:
            raise NotImplementedError(
                "estimate_k is not implemented yet; pass an explicit k"
            )

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> KMeansModel:
        p: KMeansParameters = self.params
        info = build_data_info(
            frame, y=None, ignored=p.ignored_columns,
            standardize=p.standardize, use_all_factor_levels=True,
        )
        X, _ = expand_matrix(info, frame, dtype=np.float32)
        n, D = X.shape
        model = KMeansModel(p, info)
        rng = np.random.default_rng(p.actual_seed())

        C = _init_centers(X, p.k, p.init, rng)

        mesh = default_mesh()
        Xd, _ = shard_rows(X, mesh)
        maskd = row_mask(n, Xd.shape[0], mesh)
        Cd = jnp.asarray(C)

        prev_wss = np.inf
        assign = counts = wss_k = None
        for it in range(p.max_iterations):
            assign, Cd, counts, wss, wss_k = _lloyd_step(Xd, maskd, Cd, p.k)
            model.iterations = it + 1
            wss = float(jax.device_get(wss))
            if abs(prev_wss - wss) < 1e-6 * max(abs(prev_wss), 1.0):
                break
            prev_wss = wss

        model.centers_std = np.asarray(jax.device_get(Cd), dtype=np.float64)
        model.size = np.asarray(jax.device_get(counts), dtype=np.int64)
        model.withinss = np.asarray(jax.device_get(wss_k), dtype=np.float64)
        model.tot_withinss = float(model.withinss.sum())
        gmean = X.mean(axis=0)
        model.totss = float(((X - gmean) ** 2).sum())
        model.betweenss = model.totss - model.tot_withinss
        model.centers = _destandardize_centers(info, model.centers_std)
        model.training_metrics = model.model_performance(frame)
        return model


def _init_centers(X: np.ndarray, k: int, init: str, rng) -> np.ndarray:
    n = len(X)
    if init == "random":
        return X[rng.choice(n, k, replace=False)].copy()
    # kmeans++ / furthest share the distance-seeded loop (KMeans.java init)
    centers = [X[rng.integers(n)]]
    d2 = ((X - centers[0]) ** 2).sum(axis=1)
    for _ in range(1, k):
        if init == "furthest":
            centers.append(X[int(d2.argmax())])
        else:  # plus_plus: sample proportional to d²
            probs = d2 / max(d2.sum(), 1e-30)
            centers.append(X[rng.choice(n, p=probs)])
        d2 = np.minimum(d2, ((X - centers[-1]) ** 2).sum(axis=1))
    return np.stack(centers)


def _destandardize_centers(info, C_std: np.ndarray) -> np.ndarray:
    C = C_std.copy()
    j = 0
    for name in info.predictor_names:
        if name in info.cat_domains:
            j += len(info.cat_domains[name])
        else:
            if info.standardize:
                C[:, j] = C_std[:, j] * info.num_sds[name] + info.num_means[name]
            j += 1
    return C
