"""Extended Isolation Forest — random oblique (hyperplane) splits.

Reference: ``hex/tree/isoforextended/ExtendedIsolationForest.java`` (subsample
per tree, height limit ceil(log2(sample_size)), ``IsolationTree`` with random
slope n and intercept p drawn in the subsample bounding box; extension_level
controls how many coordinates of n are non-zero — level 0 degenerates to the
classic axis-aligned Isolation Forest) and ``ExtendedIsolationForestModel.java:55-68``
(outputs ``anomaly_score = 2^(-E[h]/c(ψ))`` and ``mean_length``).

TPU-native: every tree is a *perfect* binary tree of fixed height stored as
dense arrays (normals [M, D], thresholds [M], leaf path-length corrections),
so scoring all trees × all rows is one jitted ``lax.fori_loop`` over levels —
static shapes, no per-node recursion.  Building happens on the per-tree
subsample (ψ ≤ 256 rows) and is vectorized with numpy on host; the O(N·T·depth)
scoring pass is the device program.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from h2o3_tpu.frame.frame import ColType, Column, Frame
from h2o3_tpu.models.data_info import build_data_info, expand_matrix
from h2o3_tpu.models.framework import Model, ModelBuilder, ModelParameters
from h2o3_tpu.models.isolation_forest import _c_factor


@dataclass
class ExtendedIsolationForestParameters(ModelParameters):
    ntrees: int = 100
    sample_size: int = 256
    extension_level: int = 0  # 0 .. D-1; 0 == axis-aligned IF


@partial(jax.jit, static_argnames=("depth",))
def _path_lengths(X, normals, offsets, is_split, correction, depth: int):
    """Mean adjusted path length over trees.

    X [N,D]; normals [T,M,D]; offsets [T,M]; is_split [T,M] bool;
    correction [T,M] = c(node_size) termination credit per node.
    Node indexing: heap order, root 0, children 2i+1 / 2i+2.
    """
    n = X.shape[0]
    T = normals.shape[0]

    def one_tree(carry, tree):
        total = carry
        nrm, off, sp, corr = tree

        def body(level, state):
            idx, length, done = state
            proj = jnp.einsum("nd,nd->n", X, nrm[idx])  # gather per-row node normal
            go_right = proj > off[idx]
            splitting = sp[idx] & ~done
            # terminate where the node is a leaf: add its credit
            terminating = ~sp[idx] & ~done
            length = length + jnp.where(terminating, corr[idx], 0.0)
            length = length + jnp.where(splitting, 1.0, 0.0)
            idx = jnp.where(splitting, 2 * idx + 1 + go_right.astype(jnp.int32), idx)
            return idx, length, done | terminating

        idx0 = jnp.zeros(n, dtype=jnp.int32)
        len0 = jnp.zeros(n, dtype=X.dtype)
        done0 = jnp.zeros(n, dtype=bool)
        idx, length, done = jax.lax.fori_loop(0, depth + 1, body, (idx0, len0, done0))
        # anything still alive at max depth gets its node's credit
        length = length + jnp.where(done, 0.0, corr[idx])
        return total + length, None

    total, _ = jax.lax.scan(
        one_tree, jnp.zeros(n, dtype=X.dtype), (normals, offsets, is_split, correction)
    )
    return total / T


class ExtendedIsolationForestModel(Model):
    algo_name = "extendedisolationforest"

    def __init__(self, params, data_info):
        super().__init__(params, data_info)
        self.normals: Optional[np.ndarray] = None
        self.offsets: Optional[np.ndarray] = None
        self.is_split: Optional[np.ndarray] = None
        self.correction: Optional[np.ndarray] = None
        self.depth: int = 0
        self.sample_size: int = 0

    @property
    def is_classifier(self) -> bool:
        return False

    def _mean_path_lengths(self, frame: Frame) -> np.ndarray:
        X, _ = expand_matrix(self.data_info, frame, dtype=np.float32)
        return np.asarray(
            _path_lengths(
                jnp.asarray(X),
                jnp.asarray(self.normals),
                jnp.asarray(self.offsets),
                jnp.asarray(self.is_split),
                jnp.asarray(self.correction),
                self.depth,
            )
        ).astype(np.float64)

    def _predict_raw(self, frame: Frame) -> np.ndarray:
        mean_len = self._mean_path_lengths(frame)
        return np.power(2.0, -mean_len / _c_factor(float(self.sample_size)))

    def predict(self, frame: Frame) -> Frame:
        """['anomaly_score', 'mean_length'] (ExtendedIsolationForestModel.java:33)."""
        mean_len = self._mean_path_lengths(frame)
        score = np.power(2.0, -mean_len / _c_factor(float(self.sample_size)))
        return Frame([
            Column("anomaly_score", score, ColType.NUM),
            Column("mean_length", mean_len, ColType.NUM),
        ])


class ExtendedIsolationForest(ModelBuilder):
    algo_name = "extendedisolationforest"

    def __init__(self, params: Optional[ExtendedIsolationForestParameters] = None, **kw) -> None:
        super().__init__(params or ExtendedIsolationForestParameters(**kw))

    def _fit(self, frame: Frame, valid: Optional[Frame] = None) -> ExtendedIsolationForestModel:
        p: ExtendedIsolationForestParameters = self.params
        info = build_data_info(frame, None, ignored=p.ignored_columns, standardize=False)
        X, _ = expand_matrix(info, frame, dtype=np.float64)
        n, d = X.shape
        if d == 0:
            raise ValueError("no usable predictor columns")
        if not (0 <= p.extension_level <= max(d - 1, 0)):
            raise ValueError(f"extension_level must be in [0, {d - 1}]")
        psi = min(p.sample_size, n)
        depth = max(int(np.ceil(np.log2(max(psi, 2)))), 1)
        m = 2 ** (depth + 1) - 1
        rng = np.random.default_rng(p.actual_seed())

        normals = np.zeros((p.ntrees, m, d))
        offsets = np.zeros((p.ntrees, m))
        is_split = np.zeros((p.ntrees, m), dtype=bool)
        correction = np.zeros((p.ntrees, m))

        for t in range(p.ntrees):
            sub = X[rng.choice(n, size=psi, replace=False)]
            _build_tree(sub, 0, depth, p.extension_level, rng,
                        normals[t], offsets[t], is_split[t], correction[t])
            if self.job:
                self.job.update((t + 1) / p.ntrees)

        model = ExtendedIsolationForestModel(p, info)
        model.normals = normals.astype(np.float32)
        model.offsets = offsets.astype(np.float32)
        model.is_split = is_split
        model.correction = correction.astype(np.float32)
        model.depth = depth
        model.sample_size = psi
        model.training_metrics = None
        return model


def _build_tree(pts, node, depth_left, ext, rng, normals, offsets, is_split, correction):
    """Recursive subsample split: random slope with ext+1 active coords,
    intercept uniform in the node's bounding box (IsolationTree semantics)."""
    m = pts.shape[0]
    if m <= 1 or depth_left == 0:
        correction[node] = _c_factor(float(m)) if m > 1 else 0.0
        return
    lo, hi = pts.min(axis=0), pts.max(axis=0)
    if np.all(hi - lo <= 0):
        correction[node] = _c_factor(float(m))
        return
    d = pts.shape[1]
    nrm = rng.normal(size=d)
    varying = np.nonzero(hi - lo > 0)[0]
    keep = rng.choice(varying, size=min(ext + 1, varying.size), replace=False)
    mask = np.zeros(d, dtype=bool)
    mask[keep] = True
    nrm[~mask] = 0.0
    p_int = rng.uniform(lo, hi)
    proj = pts @ nrm
    thr = float(p_int @ nrm)
    right = proj > thr
    if right.all() or (~right).all():
        correction[node] = _c_factor(float(m))
        return
    normals[node] = nrm
    offsets[node] = thr
    is_split[node] = True
    _build_tree(pts[~right], 2 * node + 1, depth_left - 1, ext, rng,
                normals, offsets, is_split, correction)
    _build_tree(pts[right], 2 * node + 2, depth_left - 1, ext, rng,
                normals, offsets, is_split, correction)
