"""Keyed object catalog — the TPU-native replacement for H2O's DKV.

The reference keeps every Frame/Vec/Chunk/Model under a ``water.Key`` in a
distributed K/V store with home-node hashing, caching and invalidation
(``water/DKV.java:3-62``, ``water/Key.java:196``). On TPU there is a single
host control-plane per process (multi-host SPMD runs the same program
everywhere), so the catalog is a plain in-process keyed store: device
placement of the *data* is owned by JAX shardings, not by the store. What we
keep from the reference is the *lifecycle* surface: put/get/remove, type-keyed
lookups, and `Scope`-style temp tracking (``water/Scope.java``).
"""

from __future__ import annotations

import sys
import threading
import uuid
from typing import Any, Dict, Iterator, List, Optional

from h2o3_tpu.util import telemetry


def _devcache_invalidate(key: Optional[str]) -> None:
    """Drop device placements linked to a dropped/renamed frame key.

    Looked up via sys.modules so the store never forces the devcache (and
    transitively the compute stack) to import: if the module was never
    loaded, nothing was ever cached."""
    if not key:
        return
    mod = sys.modules.get("h2o3_tpu.frame.devcache")
    if mod is not None:
        mod.DEVCACHE.invalidate_frame(key)


def _devcache_clear() -> None:
    mod = sys.modules.get("h2o3_tpu.frame.devcache")
    if mod is not None:
        mod.DEVCACHE.clear()

#: store churn meters — the DKV analogue of the reference's WaterMeter
#: gauges: size, put/get traffic, and Cleaner spill activity
_DKV_KEYS = telemetry.gauge("dkv_keys", "objects resident in the keyed store")
_DKV_PUTS = telemetry.counter("dkv_puts_total", "KeyedStore.put calls")
_DKV_GETS = telemetry.counter("dkv_gets_total", "KeyedStore.get calls")
_DKV_REMOVES = telemetry.counter(
    "dkv_removes_total", "keys dropped from the store (remove/scope sweep)"
)
_DKV_SPILLS = telemetry.counter(
    "dkv_spills_total", "frames spilled to the ice dir by the memory budget"
)


class _SpilledFrame:
    """Disk-resident stand-in for a spilled Frame (the reference Cleaner's
    LRU-persisted Value, ``water/Cleaner.java:10-12,155-162``). Carries the
    listing metadata (nrows/ncols/names) so catalogs and /3/Frames never
    fault the frame back in just to display it."""

    def __init__(self, path: str, nbytes: int, nrows: int, ncols: int,
                 names: List[str], cls: type) -> None:
        self.path = path
        self.nbytes = nbytes
        self.nrows = nrows
        self.ncols = ncols
        self.names = names
        #: concrete class of the spilled object, so type-keyed listings
        #: (keys_of_type) answer for subclasses and renamed Frame types
        self.cls = cls


def _frame_nbytes(obj: Any) -> int:
    # a chunk-homed DistFrame reports its RESIDENT bytes explicitly: its
    # ``columns`` property would gather every remote chunk, so sizing it
    # through the generic path below would materialize it on every put
    resident = getattr(obj, "nbytes_resident", None)
    if resident is not None:
        return int(resident)
    cols = getattr(obj, "columns", None)
    if cols is None or not hasattr(obj, "nrows"):
        return 0
    total = 0
    try:
        for c in cols:
            data = getattr(c, "data", None)
            total += getattr(data, "nbytes", 0)
    except TypeError:
        return 0
    return total


class KeyedStore:
    """Process-local keyed object store with scoped temp-key tracking.

    Memory manager: an optional host-memory budget for Frames
    (``water/MemoryManager.java`` + the ``Cleaner`` "user-mode swap-to-disk",
    ``water/Cleaner.java:10-12,155-162``): when resident frame bytes exceed
    the budget, least-recently-used frames spill to the ice dir through
    FramePersist and reload transparently on next access."""

    def __init__(self) -> None:
        self._store: Dict[str, Any] = {}
        self._lock = threading.RLock()
        #: distributed key-home router (h2o3_tpu/cluster/dkv.py DkvRouter),
        #: installed when a multi-node cloud forms: put/get/remove for keys
        #: homed on another node forward over RPC. None (or a single-node
        #: cloud) short-circuits every call to the plain local path below,
        #: so nothing changes for existing callers.
        self.router = None
        # Scope stacks are PER-THREAD (water/Scope.java is thread-local
        # too): concurrent builds (parallel grid, REST train threads)
        # must never see — or pop — each other's scopes
        self._scopes_tl = threading.local()
        self._budget: Optional[int] = None
        self._ice_dir: Optional[str] = None
        self._access: Dict[str, int] = {}  # frame key -> access counter
        self._tick = 0
        #: Lockable (water/Lockable.java): key -> owners holding a read
        #: lock; a read-locked key cannot be removed (a frame in use by a
        #: running training job must not vanish under it)
        self._read_locks: Dict[str, set] = {}
        #: keys with a spill write in flight — concurrent _maybe_spill
        #: calls must never pick the same victim (two writers to one
        #: path + a lost-race unlink would delete the winner's file)
        self._spilling: set = set()

    @property
    def _scopes(self) -> List[List[str]]:
        stack = getattr(self._scopes_tl, "stack", None)
        if stack is None:
            stack = self._scopes_tl.stack = []
        return stack

    # -- Lockable (water/Lockable.java read/write locking) --------------------
    def read_lock(self, key: str, owner: str) -> None:
        with self._lock:
            self._read_locks.setdefault(key, set()).add(owner)

    def read_unlock(self, key: str, owner: str) -> None:
        with self._lock:
            owners = self._read_locks.get(key)
            if owners is not None:
                owners.discard(owner)
                if not owners:
                    del self._read_locks[key]

    def locked_by(self, key: str) -> List[str]:
        with self._lock:
            return sorted(self._read_locks.get(key, ()))

    def unlock_all(self) -> None:
        """Drop every read lock (UnlockTask / POST /3/UnlockKeys — the
        operator's escape hatch when a crashed job left locks behind)."""
        with self._lock:
            self._read_locks.clear()

    def _check_unlocked(self, key: str) -> None:
        # caller holds the lock
        owners = self._read_locks.get(key)
        if owners:
            raise ValueError(
                f"{key!r} is locked by {sorted(owners)} and cannot be "
                f"removed or replaced (Lockable)"
            )

    # -- memory manager / Cleaner --------------------------------------------
    def set_memory_budget(
        self, nbytes: Optional[int], ice_dir: Optional[str] = None
    ) -> None:
        """Enable (or disable with None) frame spilling above ``nbytes``."""
        import os
        import tempfile

        ice = None
        if nbytes is not None:
            ice = ice_dir or os.environ.get(
                "H2O3_TPU_ICE_ROOT"
            ) or os.path.join(tempfile.gettempdir(), "h2o3_tpu_ice")
            # directory creation is disk I/O; under the store RLock it
            # would freeze every concurrent DKV op (and re-entrancy would
            # run the spill's serialize with the lock held too)
            os.makedirs(ice, exist_ok=True)
        with self._lock:
            self._budget = nbytes
            if ice is not None:
                self._ice_dir = ice
        self._maybe_spill()

    def resident_frame_bytes(self) -> int:
        with self._lock:
            return sum(_frame_nbytes(v) for v in self._store.values())

    def spilled_keys(self) -> List[str]:
        with self._lock:
            return [
                k for k, v in self._store.items() if isinstance(v, _SpilledFrame)
            ]

    def _maybe_spill(self) -> None:
        """Spill LRU frames until under budget. Disk writes happen OUTSIDE
        the store lock (a multi-hundred-MB serialize must not freeze every
        concurrent DKV operation); the marker swap re-checks under the lock
        that the frame was not replaced meanwhile."""
        if self._budget is None:
            return
        import os

        from h2o3_tpu.util.log import get_logger

        while True:
            with self._lock:
                if self._budget is None:
                    return
                frames = {
                    k: _frame_nbytes(v)
                    for k, v in self._store.items()
                    if _frame_nbytes(v) > 0
                }
                used = sum(frames.values())
                if used <= self._budget or len(frames) <= 1:
                    return
                # oldest access first; never the most recently touched,
                # never one another thread is already spilling
                newest = max(frames, key=lambda k: self._access.get(k, 0))
                victims = sorted(frames, key=lambda k: self._access.get(k, 0))
                victim = next(
                    (k for k in victims
                     if k != newest and k not in self._spilling), None)
                if victim is None:
                    return
                self._spilling.add(victim)
                fr = self._store[victim]
                nbytes = frames[victim]
                ice = self._ice_dir
            # unique path per spill attempt: even a lost race against a
            # concurrent put() unlinks only this attempt's own file
            path = os.path.join(ice, f"{victim}.{uuid.uuid4().hex[:8]}.h2f")
            from h2o3_tpu.frame.persist import save_frame

            try:
                save_frame(fr, path)  # I/O with no lock held
                with self._lock:
                    if self._store.get(victim) is fr:  # unchanged meanwhile
                        self._store[victim] = _SpilledFrame(
                            path, nbytes, fr.nrows, fr.ncols, list(fr.names),
                            cls=type(fr),
                        )
                        _DKV_SPILLS.inc()
                        get_logger("cleaner").info(
                            "spilled frame %s (%.1f MB) to %s",
                            victim, nbytes / 1e6, path,
                        )
                        # memory pressure reclaims the device tier too: a
                        # frame cold enough to leave host RAM has no claim
                        # on resident device placements
                        _devcache_invalidate(victim)
                    else:
                        try:
                            os.unlink(path)
                        except OSError:
                            pass
            finally:
                with self._lock:
                    self._spilling.discard(victim)

    def _unspill(self, key: str, marker: _SpilledFrame) -> Any:
        """Reload a spilled frame; the disk read happens without the lock."""
        import os

        from h2o3_tpu.frame.persist import load_frame

        fr = load_frame(marker.path)  # I/O with no lock held
        fr.key = key
        with self._lock:
            cur = self._store.get(key)
            if cur is marker:
                self._store[key] = fr
                try:
                    os.unlink(marker.path)
                except OSError:
                    pass
            elif not isinstance(cur, _SpilledFrame) and cur is not None:
                fr = cur  # raced: someone else already restored/replaced it
            self._tick += 1
            self._access[key] = self._tick
        self._maybe_spill()  # reloading may push another frame out
        return fr

    def _drop_value(self, key: str, v: Any) -> None:
        # caller holds the lock; spill files die with their entries
        import os

        self._access.pop(key, None)
        if isinstance(v, _SpilledFrame):
            try:
                os.unlink(v.path)
            except OSError:
                pass

    # -- DKV.put/get/remove (water/DKV.java:30-62) ---------------------------
    def _route(self, key: str, _local: bool):
        """The router when this op must forward: a live multi-node router,
        a key homed elsewhere, and not an RPC-served local op."""
        r = self.router
        if r is None or _local or not r.active() or r.is_home(key):
            return None
        return r

    def put(self, key: str, value: Any, *, replicas: int = 1,
            _local: bool = False) -> str:
        r = self._route(key, _local)
        if r is not None and r.routes_value(value):
            # plain data rides the ring to its home; framework objects
            # (Frame/Model/Job...) fall through to the local store —
            # this node owns their in-place mutation, listing and locks
            return r.remote_put(key, value, replicas)
        spillable = _frame_nbytes(value) > 0
        with self._lock:
            # replacing a read-locked registration with a DIFFERENT object
            # is deletion in disguise (Lockable); re-putting the same
            # object is a harmless refresh
            if key in self._read_locks and self._store.get(key) is not value:
                self._check_unlocked(key)
            self._store[key] = value
            if self._scopes:
                self._scopes[-1].append(key)
            if spillable:
                self._tick += 1
                self._access[key] = self._tick
            _DKV_PUTS.inc()
            _DKV_KEYS.set(len(self._store))
        r2 = self.router
        if r2 is not None and r2.routes_value(value):
            # stamp a write epoch and clear any tombstone this write
            # supersedes (a legitimate re-put after remove resurrects;
            # a stale replica restore must not — see DkvRouter.note_put)
            r2.note_put(key)
        if spillable:
            self._maybe_spill()
        if replicas > 1 and not _local:
            # home-side replica fan-out (the replicas= knob for metadata
            # keys; plain data only — node-local framework objects never
            # ship); reached both by local puts on the home node and by
            # the RPC dkv_put handler forwarding a non-home caller's put
            r = self.router
            if r is not None and r.active() and r.routes_value(value):
                r.replicate(key, value, replicas)
        return key

    def get(self, key: str, default: Any = None, *,
            _local: bool = False) -> Any:
        r = self._route(key, _local)
        if r is not None:
            return r.remote_get(key, default)
        _DKV_GETS.inc()
        sentinel = object()
        marker = None
        with self._lock:
            v = self._store.get(key, sentinel)
            if isinstance(v, _SpilledFrame):
                marker = v
            elif v is not sentinel:
                if _frame_nbytes(v) > 0:
                    self._tick += 1
                    self._access[key] = self._tick
                return v
        if marker is not None:
            # reload outside the store lock: _unspill's disk read must not
            # run under it (RLock re-entrancy would silently keep it held)
            return self._unspill(key, marker)
        # local miss on a key THIS node homes: a replica successor may
        # hold the only surviving copy (this node restarted empty and
        # rejoined) — walk the ring before declaring it absent; the walk
        # read-repairs the value back onto this home
        if not _local:
            r = self.router
            if r is not None and r.active():
                return r.remote_get(key, default)
        return default

    def peek(self, key: str, default: Any = None) -> Any:
        """The stored value WITHOUT faulting a spilled frame back in —
        listings read nrows/ncols/names straight off the marker."""
        with self._lock:
            return self._store.get(key, default)

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._store

    def remove(self, key: str, *, _local: bool = False) -> None:
        with self._lock:
            self._check_unlocked(key)
            v = self._store.pop(key, None)
            self._drop_value(key, v)
            if v is not None:
                _DKV_REMOVES.inc()
            _DKV_KEYS.set(len(self._store))
        if v is not None:
            _devcache_invalidate(key)
        if not _local:
            # removal routes to the key's ring home, which reaps any
            # replica copies it tracked — at most one RPC here, zero
            # when this node is the home
            r = self.router
            if r is not None and r.active():
                r.remote_remove(key)

    def rekey(self, obj: Any, new_key: str) -> str:
        """Re-register ``obj`` (which carries a ``.key`` attribute) under
        ``new_key``. The old registration is dropped only if it still points
        at ``obj`` — renaming never destroys an unrelated live object that
        happens to share the old key."""
        with self._lock:
            old = getattr(obj, "key", None)
            if old and self._store.get(old) is obj:
                self._check_unlocked(old)
                self._store.pop(old, None)
            obj.key = new_key
            self._store[new_key] = obj
            if self._scopes:
                self._scopes[-1].append(new_key)
            _DKV_KEYS.set(len(self._store))
        if old and old != new_key:
            # placements registered under the old key re-upload on next
            # use; renaming must never leave stale device state reachable
            _devcache_invalidate(old)
        return new_key

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._store.keys())

    def keys_of_type(self, cls: type) -> List[str]:
        with self._lock:
            return [
                k for k, v in self._store.items()
                if isinstance(v, cls)
                # spilled frames are still frames (or Frame subclasses)
                # to every listing: match on the recorded concrete class
                or (isinstance(v, _SpilledFrame) and issubclass(v.cls, cls))
            ]

    def clear(self) -> None:
        """Nuke the world (tests / shutdown): locks clear with the store."""
        with self._lock:
            self._read_locks.clear()
            for k, v in list(self._store.items()):
                self._drop_value(k, v)
            _DKV_REMOVES.inc(len(self._store))
            self._store.clear()
            _DKV_KEYS.set(0)
        _devcache_clear()

    @staticmethod
    def make_key(prefix: str = "obj") -> str:
        """Fresh unique key (reference: ``Key.make()``, water/Key.java:44)."""
        return f"{prefix}_{uuid.uuid4().hex[:12]}"

    # -- Scope.enter/exit (water/Scope.java) ---------------------------------
    def scope_enter(self) -> None:
        with self._lock:
            self._scopes.append([])

    def scope_exit(self, keep: Optional[List[str]] = None) -> None:
        keep_set = set(keep or [])
        dropped: List[str] = []
        with self._lock:
            if not self._scopes:
                return
            for k in self._scopes.pop():
                if k in keep_set:
                    continue
                if self._read_locks.get(k):
                    continue  # in use by a running job: defer, never yank
                v = self._store.pop(k, None)
                self._drop_value(k, v)
                if v is not None:
                    _DKV_REMOVES.inc()
                    dropped.append(k)
            _DKV_KEYS.set(len(self._store))
        for k in dropped:
            _devcache_invalidate(k)

    def scope(self) -> "_ScopeCtx":
        return _ScopeCtx(self)

    def __iter__(self) -> Iterator[str]:
        return iter(self.keys())

    def __len__(self) -> int:
        with self._lock:
            return len(self._store)


class _ScopeCtx:
    def __init__(self, store: KeyedStore) -> None:
        self._store = store

    def __enter__(self) -> KeyedStore:
        self._store.scope_enter()
        return self._store

    def __exit__(self, *exc: Any) -> None:
        self._store.scope_exit()


#: Global catalog — the analogue of the cluster-wide DKV singleton.
DKV = KeyedStore()
