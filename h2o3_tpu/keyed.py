"""Keyed object catalog — the TPU-native replacement for H2O's DKV.

The reference keeps every Frame/Vec/Chunk/Model under a ``water.Key`` in a
distributed K/V store with home-node hashing, caching and invalidation
(``water/DKV.java:3-62``, ``water/Key.java:196``). On TPU there is a single
host control-plane per process (multi-host SPMD runs the same program
everywhere), so the catalog is a plain in-process keyed store: device
placement of the *data* is owned by JAX shardings, not by the store. What we
keep from the reference is the *lifecycle* surface: put/get/remove, type-keyed
lookups, and `Scope`-style temp tracking (``water/Scope.java``).
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, Iterator, List, Optional


class KeyedStore:
    """Process-local keyed object store with scoped temp-key tracking."""

    def __init__(self) -> None:
        self._store: Dict[str, Any] = {}
        self._lock = threading.RLock()
        self._scopes: List[List[str]] = []

    # -- DKV.put/get/remove (water/DKV.java:30-62) ---------------------------
    def put(self, key: str, value: Any) -> str:
        with self._lock:
            self._store[key] = value
            if self._scopes:
                self._scopes[-1].append(key)
        return key

    def get(self, key: str, default: Any = None) -> Any:
        with self._lock:
            return self._store.get(key, default)

    def __contains__(self, key: str) -> bool:
        with self._lock:
            return key in self._store

    def remove(self, key: str) -> None:
        with self._lock:
            self._store.pop(key, None)

    def rekey(self, obj: Any, new_key: str) -> str:
        """Re-register ``obj`` (which carries a ``.key`` attribute) under
        ``new_key``. The old registration is dropped only if it still points
        at ``obj`` — renaming never destroys an unrelated live object that
        happens to share the old key."""
        with self._lock:
            old = getattr(obj, "key", None)
            if old and self._store.get(old) is obj:
                self._store.pop(old, None)
            obj.key = new_key
            self._store[new_key] = obj
            if self._scopes:
                self._scopes[-1].append(new_key)
        return new_key

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._store.keys())

    def keys_of_type(self, cls: type) -> List[str]:
        with self._lock:
            return [k for k, v in self._store.items() if isinstance(v, cls)]

    def clear(self) -> None:
        with self._lock:
            self._store.clear()

    @staticmethod
    def make_key(prefix: str = "obj") -> str:
        """Fresh unique key (reference: ``Key.make()``, water/Key.java:44)."""
        return f"{prefix}_{uuid.uuid4().hex[:12]}"

    # -- Scope.enter/exit (water/Scope.java) ---------------------------------
    def scope_enter(self) -> None:
        with self._lock:
            self._scopes.append([])

    def scope_exit(self, keep: Optional[List[str]] = None) -> None:
        keep_set = set(keep or [])
        with self._lock:
            if not self._scopes:
                return
            for k in self._scopes.pop():
                if k not in keep_set:
                    self._store.pop(k, None)

    def scope(self) -> "_ScopeCtx":
        return _ScopeCtx(self)

    def __iter__(self) -> Iterator[str]:
        return iter(self.keys())

    def __len__(self) -> int:
        with self._lock:
            return len(self._store)


class _ScopeCtx:
    def __init__(self, store: KeyedStore) -> None:
        self._store = store

    def __enter__(self) -> KeyedStore:
        self._store.scope_enter()
        return self._store

    def __exit__(self, *exc: Any) -> None:
        self._store.scope_exit()


#: Global catalog — the analogue of the cluster-wide DKV singleton.
DKV = KeyedStore()
