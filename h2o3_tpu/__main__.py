"""CLI launcher: ``python -m h2o3_tpu`` starts a serving node.

Reference: ``water/H2O.java:352-616,2238`` — the ``OptArgs`` CLI surface of
``java -jar h2o.jar`` (-name -port -baseport -ice_root -nthreads -cleaner
-auto_recovery_dir -jks/-hash_login ...) and the launcher modules
(``h2o-app/H2OApp.java:3``, SURVEY.md L11).

TPU-native: one process is one cloud (the device mesh is the "cluster");
the launcher parses the OptArgs subset that still has meaning here, starts
the REST server, optionally resumes interrupted Recoverables, and serves
until interrupted.
"""

from __future__ import annotations

import argparse
import signal
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m h2o3_tpu",
        description="Start an h2o3-tpu serving node (REST API + Flow-lite).",
    )
    p.add_argument("--name", default="h2o3-tpu",
                   help="cloud name (-name)")
    p.add_argument("--port", type=int, default=54321,
                   help="REST port (-port); 0 picks a free port")
    p.add_argument("--ip", default="127.0.0.1",
                   help="bind address (-ip); use 0.0.0.0 in pods/containers")
    p.add_argument("--ice-root", default=None,
                   help="spill/log directory (-ice_root)")
    p.add_argument("--max-mem", default=None,
                   help="host-memory budget for frames before spilling, "
                        "e.g. 4g / 512m (the -Xmx + -cleaner pair)")
    p.add_argument("--auto-recovery-dir", default=None,
                   help="resume an interrupted grid/AutoML from this "
                        "directory at startup (-auto_recovery_dir)")
    p.add_argument("--ssl-cert", default=None, help="TLS certificate (PEM)")
    p.add_argument("--ssl-key", default=None, help="TLS private key (PEM)")
    p.add_argument("--hash-login-file", default=None,
                   help="hash-file Basic auth (-hash_login): lines of "
                        "user:sha256hex or the salted "
                        "user:pbkdf2:iters:salt:hash form emitted by "
                        "--hash-password")
    p.add_argument("--login-type", default=None,
                   choices=["hash", "ldap"],
                   help="auth SPI backend (LoginType); hash is implied "
                        "by --hash-login-file")
    p.add_argument("--ldap-url", default=None,
                   help="LDAP server URL for --login-type ldap "
                        "(-ldap_login)")
    p.add_argument("--ldap-bind-template", default=None,
                   help="bind-DN template with {} for the username, e.g. "
                        "'uid={},ou=people,dc=example,dc=org'")
    p.add_argument("--hash-password", nargs=2, metavar=("USER", "PASS"),
                   default=None,
                   help="print a salted PBKDF2 hash-file line for "
                        "USER/PASS and exit")
    p.add_argument("--log-dir", default=None,
                   help="write logs here in addition to the in-memory ring")
    # multi-host pod launch (the h2odriver / h2o-k8s analogue: instead of
    # flatfile/multicast cloud formation, hosts rendezvous at a JAX
    # coordinator and XLA owns the collective fabric)
    p.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                   help="JAX distributed coordinator address; process 0 "
                        "binds it, others connect (multi-host pods; "
                        "replaces -flatfile cloud formation)")
    p.add_argument("--num-processes", type=int, default=None,
                   help="total processes in the pod")
    p.add_argument("--process-id", type=int, default=None,
                   help="this process's index (0-based); on k8s, derive "
                        "from the StatefulSet ordinal (see deploy/)")
    # application-plane clustering (the -flatfile / -name cloud formation
    # of the reference, h2o3_tpu/cluster/): heartbeat membership, node
    # RPC, distributed DKV homes, multi-node task fan-out
    p.add_argument("--flatfile", default=None, metavar="PATH",
                   help="peer list (one host:port RPC address per line, "
                        "# comments ok); presence of this flag boots the "
                        "application-plane cluster node (-flatfile)")
    p.add_argument("--cluster-name", default=None,
                   help="application-plane cloud name; members of one "
                        "cloud must agree on it (default: --name)")
    p.add_argument("--node-name", default=None,
                   help="this node's unique name in the cloud (default: "
                        "<name>-<pid>); a duplicate is rejected at join "
                        "with a clear 409")
    p.add_argument("--cluster-port", type=int, default=0,
                   help="node RPC bind port (0 = OS-assigned)")
    p.add_argument("--cluster-address-file", default=None, metavar="PATH",
                   help="write this node's resolved RPC host:port here "
                        "after bind (harness rendezvous for --cluster-port 0)")
    return p


def _parse_mem(s: str) -> int:
    s = s.strip().lower()
    mult = 1
    if s.endswith("g"):
        mult, s = 1 << 30, s[:-1]
    elif s.endswith("m"):
        mult, s = 1 << 20, s[:-1]
    elif s.endswith("k"):
        mult, s = 1 << 10, s[:-1]
    return int(float(s) * mult)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    if args.hash_password:
        from h2o3_tpu.api.auth import hash_entry

        print(hash_entry(*args.hash_password))
        return 0

    from h2o3_tpu.util import log as L

    L.init(dir=args.log_dir or args.ice_root)
    logger = L.get_logger("launcher")

    if args.coordinator:
        # multi-host rendezvous BEFORE any backend use: after this, every
        # process sees the pod's full device set and default_mesh() spans
        # hosts (water/H2O.java cloud formation -> jax.distributed)
        from h2o3_tpu.parallel.mesh import distributed_initialize

        if args.num_processes is None or args.process_id is None:
            print("--coordinator requires --num-processes and --process-id",
                  file=sys.stderr)
            return 2
        if args.num_processes < 1 or not (
                0 <= args.process_id < args.num_processes):
            # catch the misconfiguration HERE with a clear message — fed
            # to the coordinator it becomes an opaque rendezvous stall
            print(f"--process-id must be in [0, --num-processes): got "
                  f"process-id={args.process_id} "
                  f"num-processes={args.num_processes}", file=sys.stderr)
            return 2
        distributed_initialize(
            coordinator_address=args.coordinator,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
        logger.info("joined pod: process %d/%d via %s",
                    args.process_id, args.num_processes, args.coordinator)

    if args.max_mem:
        from h2o3_tpu.keyed import DKV

        DKV.set_memory_budget(_parse_mem(args.max_mem), ice_dir=args.ice_root)
        logger.info("frame memory budget: %s (ice: %s)",
                    args.max_mem, args.ice_root or "<tmp>")

    cloud = None
    if args.flatfile is not None:
        # application-plane cloud BEFORE the REST server: /3/Cloud must
        # answer with real members from the first request; the server
        # advertises its resolved REST port into the cloud at bind time
        import os as _os

        from h2o3_tpu.cluster.membership import CloudJoinError, boot_node

        try:
            # a wildcard --ip binds the RPC server on all interfaces;
            # Cloud advertises a routable address in its place so
            # cross-host peers can actually dial back
            cloud = boot_node(
                args.cluster_name or args.name,
                args.node_name or f"{args.name}-{_os.getpid()}",
                host=args.ip,
                port=args.cluster_port,
                flatfile=args.flatfile,
                address_file=args.cluster_address_file,
            )
        except CloudJoinError as e:
            # the clear 4xx surface: a duplicate --node-name (409) or
            # wrong --cluster-name (400) fails fast and says so, instead
            # of stalling forever on a membership hash that never agrees
            print(f"cluster join rejected ({e.code}): {e}", file=sys.stderr)
            return 2
        logger.info("cluster node %s up in cloud '%s' (rpc %s:%d)",
                    cloud.info.name, cloud.cloud_name,
                    cloud.info.host, cloud.info.port)

    from h2o3_tpu.api import start_server

    auth_backend = None
    if args.login_type == "ldap":
        from h2o3_tpu.api.auth import make_backend

        auth_backend = make_backend(
            "ldap", ldap_url=args.ldap_url,
            ldap_bind_template=args.ldap_bind_template)

    server = start_server(
        port=args.port,
        name=args.name,
        ssl_cert=args.ssl_cert,
        ssl_key=args.ssl_key,
        auth_file=args.hash_login_file,
        auth_backend=auth_backend,
        ip=args.ip,
    )
    logger.info("%s listening on %s", args.name, server.url)
    print(f"h2o3-tpu node '{args.name}' up at {server.url}", flush=True)

    if args.auto_recovery_dir:
        from h2o3_tpu.recovery import Recovery, auto_recover

        if Recovery.present(args.auto_recovery_dir):
            logger.info("auto-recovering from %s", args.auto_recovery_dir)
            try:
                result = auto_recover(args.auto_recovery_dir)
                logger.info("auto-recovery finished: %r", result)
            except Exception as e:
                logger.error("auto-recovery failed: %s: %s",
                             type(e).__name__, e)

    stop = {"flag": False}

    def _sig(signum, frame):
        stop["flag"] = True

    signal.signal(signal.SIGINT, _sig)
    signal.signal(signal.SIGTERM, _sig)
    try:
        import time

        while not stop["flag"]:
            time.sleep(0.5)
    finally:
        server.stop()
        if cloud is not None:
            from h2o3_tpu.cluster.membership import set_local_cloud

            cloud.stop()
            set_local_cloud(None)
        logger.info("node stopped")
    return 0


if __name__ == "__main__":
    sys.exit(main())
