"""Device mesh + sharding utilities — the cluster layer, TPU-native.

The reference builds a cluster out of UDP heartbeats + Paxos quorum
(``water/Paxos.java:10-27``), a custom RPC (``water/RPC.java:101``) and a
distributed K/V store with home-node key hashing (``water/Key.java:196``).
None of that exists here by design: membership, rendezvous and collectives are
XLA's job. ``jax.distributed.initialize`` is the Paxos/heartbeat replacement
for multi-host pods (coordinator-based rendezvous over DCN), and a
``jax.sharding.Mesh`` over all addressable devices is "the cloud".

Row-sharded placement: a Frame column maps to a device array padded to a
multiple of the mesh's data-axis size and sharded along axis 0 with
``NamedSharding(P(DATA_AXIS))`` — one shard per device is the analogue of one
node's home chunks, and XLA inserts the psum/all-gather that MRTask's node
tree did by hand (``water/MRTask.java:96-127``).
"""

from __future__ import annotations

import os
from typing import Dict, Optional, Sequence, Tuple, Union

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from h2o3_tpu.util import telemetry

#: Name of the batch/data axis — every algo shards rows over this axis (pure DP;
#: the reference has no TP/PP/SP workloads, SURVEY.md §2.4: its models are
#: trees/linear/small MLPs and the long axis is *rows*).
DATA_AXIS = "data"

_default_mesh: Optional[Mesh] = None

#: placement accounting (the WaterMeter analogue for the device tier): how
#: many devices "the cloud" has, and how much padding the SPMD static-shape
#: contract costs on every host->mesh transfer
_MESH_DEVICES = telemetry.gauge(
    "mesh_devices", "devices in the default data mesh"
)
_SHARD_BYTES = telemetry.counter(
    "shard_bytes_total", "bytes placed row-sharded on the mesh (incl. pad)"
)
_SHARD_PAD_ROWS = telemetry.counter(
    "shard_pad_rows_total", "pad rows added to satisfy static SPMD shapes"
)
_SHARD_PAD_BYTES = telemetry.gauge(
    "shard_last_pad_bytes", "pad bytes of the most recent shard_rows call"
)


def distributed_initialize(**kwargs) -> None:
    """Multi-host bootstrap (replaces Paxos cloud formation; SURVEY.md §5).

    On a multi-host pod, call once per host before any computation:
    coordinator rendezvous + global device visibility via the JAX distributed
    runtime. Single-host (and CI) setups skip this silently.
    """
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        # exactly ONE RuntimeError is benign: repeat initialization (the
        # bootstrap is idempotent by contract). Every other RuntimeError
        # is a real rendezvous failure — surface it WITH the attempted
        # kwargs, or a multi-host job silently trains on one host and the
        # operator has nothing to debug with.
        if "already" in str(e).lower():
            return
        raise RuntimeError(
            f"jax.distributed.initialize({_fmt_kwargs(kwargs)}) failed: {e}"
        ) from e
    except ValueError as e:
        # exactly ONE ValueError is benign: a bare initialize() on a
        # single-process run where no coordinator was configured AT ALL
        # (jax raises "coordinator_address should be defined"). If the
        # caller supplied any bootstrap kwargs, a ValueError means they
        # are wrong (bad process id, missing num_processes, ...) — always
        # re-raise those, with the kwargs in the message.
        if not kwargs and "coordinator_address" in str(e):
            return
        raise ValueError(
            f"jax.distributed.initialize({_fmt_kwargs(kwargs)}) failed: {e}"
        ) from e


def _fmt_kwargs(kwargs: dict) -> str:
    return ", ".join(f"{k}={v!r}" for k, v in sorted(kwargs.items()))


def device_count() -> int:
    return jax.device_count()


def default_mesh(n_devices: Optional[int] = None) -> Mesh:
    """The 1-D data mesh over all (or the first n) addressable devices."""
    global _default_mesh
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
        return Mesh(np.array(devs), (DATA_AXIS,))
    if _default_mesh is None or len(_default_mesh.devices.flat) != len(devs):
        _default_mesh = Mesh(np.array(devs), (DATA_AXIS,))
    _MESH_DEVICES.set(len(devs))
    return _default_mesh


def row_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard axis 0 over DATA_AXIS, replicate the rest."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_rows(
    x: np.ndarray, multiple: int, fill: Union[int, float] = 0
) -> Tuple[np.ndarray, int]:
    """Pad axis 0 up to a multiple; returns (padded, original_n)."""
    n = x.shape[0]
    rem = (-n) % multiple
    if rem == 0:
        return x, n
    pad_widths = [(0, rem)] + [(0, 0)] * (x.ndim - 1)
    return np.pad(x, pad_widths, constant_values=fill), n


def shard_rows(
    x: np.ndarray,
    mesh: Optional[Mesh] = None,
    fill: Union[int, float] = 0,
) -> Tuple[jax.Array, int]:
    """Place a host array on the mesh row-sharded; returns (array, valid_rows).

    The pad rows are the price of SPMD static shapes; every consumer masks them
    via the ``valid_rows`` count (compare: the reference's ESPC chunk layout
    allows ragged chunks, ``water/fvec/Vec.java:264-280`` — ragged shards are
    hostile to XLA, so we pad instead).
    """
    mesh = mesh or default_mesh()
    nshards = mesh.devices.size
    padded, n = pad_rows(np.asarray(x), nshards, fill)
    pad_count = padded.shape[0] - n
    _SHARD_BYTES.inc(padded.nbytes)
    _SHARD_PAD_ROWS.inc(pad_count)
    _SHARD_PAD_BYTES.set(
        pad_count * (padded.nbytes / padded.shape[0]) if padded.shape[0] else 0
    )
    arr = jax.device_put(padded, row_sharding(mesh, padded.ndim))
    return arr, n


def row_mask(n_valid: int, n_padded: int, mesh: Optional[Mesh] = None) -> jax.Array:
    """Boolean validity mask for padded row-sharded arrays."""
    mesh = mesh or default_mesh()
    m = (np.arange(n_padded) < n_valid)
    arr = jax.device_put(m, row_sharding(mesh, 1))
    return arr


def shard_table(
    columns: Dict[str, np.ndarray],
    mesh: Optional[Mesh] = None,
) -> Tuple[Dict[str, jax.Array], jax.Array, int]:
    """Shard a dict of equal-length host columns; returns (device cols, mask, n)."""
    mesh = mesh or default_mesh()
    out: Dict[str, jax.Array] = {}
    n = None
    for name, arr in columns.items():
        sharded, n = shard_rows(arr, mesh)
        out[name] = sharded
    assert n is not None, "empty table"
    some = next(iter(out.values()))
    mask = row_mask(n, some.shape[0], mesh)
    return out, mask, n
