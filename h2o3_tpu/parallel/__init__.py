from h2o3_tpu.parallel.mesh import (
    DATA_AXIS,
    default_mesh,
    device_count,
    distributed_initialize,
    pad_rows,
    row_sharding,
    shard_rows,
)

__all__ = [
    "DATA_AXIS",
    "default_mesh",
    "device_count",
    "distributed_initialize",
    "pad_rows",
    "row_sharding",
    "shard_rows",
]
