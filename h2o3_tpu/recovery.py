"""Job-level fault tolerance: snapshot + resume (auto recovery).

Reference: ``hex/faulttolerance/Recovery.java:21-53`` / ``Recoverable.java``
— a Recoverable process (Grid search, AutoML) writes, under
``-auto_recovery_dir``: its parameters and references (``recovery.json``),
the referenced frames (FramePersist) and every finished model (binary
export) as it completes; ``autoRecover`` finds that state after a restart
and resumes the process so finished work is never re-trained. ``onDone``
cleans the directory.

TPU-native/single-process: the same split the reference chose — no
in-flight elasticity (a died process loses the partial device program) but
durable job state on disk, on the pickle-free persist formats. The
snapshot is self-describing: ``resume()`` needs only the directory.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional

from h2o3_tpu.frame.frame import Frame
from h2o3_tpu.keyed import DKV
from h2o3_tpu.util.log import get_logger

RECOVERY_META_FILE = "recovery.json"

log = get_logger("recovery")


class Recovery:
    """Manages one Recoverable process's on-disk state."""

    def __init__(self, dir: str) -> None:
        self.dir = os.path.expanduser(dir)

    # -- write side (Recovery.onStart / onModel / onDone) --------------------
    def on_start(self, kind: str, state: Dict[str, Any], frames: Dict[str, Frame]) -> None:
        """Persist everything needed to re-instantiate the process:
        ``state`` goes through the allowlisted object-tree format, frames
        through FramePersist."""
        from h2o3_tpu.frame.persist import save_frame
        from h2o3_tpu.models.persist import save_model

        os.makedirs(self.dir, exist_ok=True)
        frame_files = {}
        for name, fr in frames.items():
            frame_files[name] = os.path.basename(
                save_frame(fr, os.path.join(self.dir, f"frame_{name}.h2f"))
            )
        save_model(state, os.path.join(self.dir, "state.bin"))
        meta = {
            "kind": kind,
            "started": time.time(),
            "frames": frame_files,
            "models": [],
        }
        with open(os.path.join(self.dir, RECOVERY_META_FILE), "w") as f:
            json.dump(meta, f)
        log.info("recovery snapshot started in %s (%s)", self.dir, kind)

    def on_model(self, model, info: Optional[Dict[str, Any]] = None) -> None:
        """Persist one finished model and record it — after a crash, resume
        skips everything listed here (best-effort continuation)."""
        from h2o3_tpu.models.persist import save_model

        path = os.path.join(self.dir, f"model_{model.key}.bin")
        save_model(model, path)
        meta = self._read_meta()
        meta["models"].append(
            {"key": model.key, "file": os.path.basename(path), **(info or {})}
        )
        with open(os.path.join(self.dir, RECOVERY_META_FILE), "w") as f:
            json.dump(meta, f)

    def on_failure(self, info: Dict[str, Any]) -> None:
        """Record a combo that FAILED (not crashed): failures consume walker
        positions too, so resume must account for them or it would re-train
        duplicates and drop trailing combos."""
        meta = self._read_meta()
        meta.setdefault("failures", []).append(info)
        with open(os.path.join(self.dir, RECOVERY_META_FILE), "w") as f:
            json.dump(meta, f)

    def on_done(self) -> None:
        """Successful completion: recovery state is no longer needed."""
        if os.path.isdir(self.dir):
            shutil.rmtree(self.dir)
        log.info("recovery snapshot cleaned up: %s", self.dir)

    # -- read side (Recovery.autoRecover) ------------------------------------
    def _read_meta(self) -> Dict[str, Any]:
        with open(os.path.join(self.dir, RECOVERY_META_FILE)) as f:
            return json.load(f)

    @staticmethod
    def present(dir: str) -> bool:
        return os.path.exists(os.path.join(os.path.expanduser(dir), RECOVERY_META_FILE))

    def load(self):
        """Restore the snapshot: frames and finished models re-enter the
        DKV; returns (kind, state, frames_by_name, models_in_order).

        ``models_in_order`` is aligned 1:1 with the snapshot's model
        entries — a missing/corrupt model file yields ``None`` at its
        position rather than silently shortening the list, so a resume
        can pair each survivor with the RIGHT hyper-parameter entry and
        retrain exactly the missing combos (ADVICE r3)."""
        from h2o3_tpu.frame.persist import load_frame
        from h2o3_tpu.models.persist import load_model

        meta = self._read_meta()
        frames = {}
        for name, fname in meta["frames"].items():
            fr = load_frame(os.path.join(self.dir, fname))
            if fr.key:
                DKV.put(fr.key, fr)
            frames[name] = fr
        models = []
        for entry in meta["models"]:
            try:
                models.append(load_model(os.path.join(self.dir, entry["file"])))
            except Exception as e:  # missing OR corrupt (truncated write)
                log.warning("recovery: model file %s unreadable (%s: %s), "
                            "will retrain", entry["file"], type(e).__name__, e)
                models.append(None)
        state = load_model(os.path.join(self.dir, "state.bin"), register=False)
        log.info(
            "recovery: restored %s with %d frames, %d/%d finished models",
            meta["kind"], len(frames),
            sum(m is not None for m in models), len(models),
        )
        return meta["kind"], state, frames, models


def auto_recover(dir: Optional[str]):
    """Resume an interrupted Recoverable found in ``dir`` (Recovery
    .autoRecover). Currently Grid searches register themselves; returns the
    finished result or None when there is nothing to recover."""
    if not dir or not Recovery.present(dir):
        return None
    rec = Recovery(dir)
    kind, state, frames, models = rec.load()
    if kind == "grid":
        from h2o3_tpu.models.grid import GridSearch

        return GridSearch._resume(rec, state, frames, models)
    raise ValueError(f"unknown recoverable kind {kind!r}")
