"""L0 transport: length-prefixed TCP frames + a per-target connection pool.

Reference: ``water/AutoBuffer.java`` — H2O's one wire format is "a small
header, then bytes", written onto persistent node-to-node TCP channels
(``water/network/SocketChannelDriver``).  The analogue here is the
simplest correct thing: every message is ``!I`` length prefix + payload,
written to a pooled ``socket`` connection.  Everything above (request
ids, retries, method names) belongs to :mod:`h2o3_tpu.cluster.rpc`.

The ``dial`` entry point is deliberately a plain module function taken by
:class:`ConnectionPool` as a constructor argument: the RPC fault-injection
tests wrap it with a double that drops / delays / duplicates frames
without touching a real socket option.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

#: frame header: payload byte length, network order
_HEADER = struct.Struct("!I")

#: hard ceiling on one frame — a corrupt or hostile length prefix must
#: never make recv allocate unbounded memory (1 GiB covers any shipped
#: frame shard; bigger payloads should stream, not frame)
MAX_FRAME_BYTES = 1 << 30

Address = Tuple[str, int]


class FrameTooLarge(ConnectionError):
    """Peer announced a frame over MAX_FRAME_BYTES — protocol corruption."""


def send_frame(sock: socket.socket, payload: bytes) -> None:
    """One length-prefixed frame; a single sendall keeps it atomic enough
    that concurrent writers on DISTINCT sockets never interleave."""
    if len(payload) > MAX_FRAME_BYTES:
        raise FrameTooLarge(f"frame of {len(payload)} bytes exceeds "
                            f"{MAX_FRAME_BYTES}")
    sock.sendall(_HEADER.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf += chunk
    return bytes(buf)


def recv_frame(sock: socket.socket) -> bytes:
    (length,) = _HEADER.unpack(_recv_exact(sock, _HEADER.size))
    if length > MAX_FRAME_BYTES:
        raise FrameTooLarge(f"announced frame of {length} bytes exceeds "
                            f"{MAX_FRAME_BYTES}")
    return _recv_exact(sock, length)


class Connection:
    """One pooled TCP connection: send a request frame, read the response
    frame. NOT thread-safe — the pool hands a connection to exactly one
    caller at a time."""

    def __init__(self, sock: socket.socket, addr: Address) -> None:
        self.sock = sock
        self.addr = addr

    def request(self, payload: bytes, timeout: float) -> bytes:
        self.sock.settimeout(timeout)
        send_frame(self.sock, payload)
        return recv_frame(self.sock)

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


def dial(addr: Address, timeout: float = 5.0) -> Connection:
    """Open one connection (the pool's default dialer)."""
    sock = socket.create_connection(addr, timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return Connection(sock, addr)


class ConnectionPool:
    """Per-target idle-connection pool (RPC.java reuses its node channel;
    here a bounded stack of idle sockets per address).  A connection that
    errored mid-call is closed, never returned — the next call dials
    fresh rather than inheriting a poisoned stream."""

    def __init__(self, dialer: Callable[[Address, float], Connection] = dial,
                 max_idle: int = 4) -> None:
        self._dial = dialer
        self._max_idle = max_idle
        self._idle: Dict[Address, List[Connection]] = {}
        self._lock = threading.Lock()

    def get(self, addr: Address, timeout: float) -> Connection:
        conn = self.pop_idle(addr)
        return conn if conn is not None else self._dial(addr, timeout)

    def pop_idle(self, addr: Address) -> "Connection | None":
        """An idle pooled connection, or None — callers that must know
        whether a failure hit a possibly-stale pooled socket (the RPC
        retry ladder) pop explicitly and dial via :meth:`dial`."""
        with self._lock:
            stack = self._idle.get(addr)
            if stack:
                return stack.pop()
        return None

    def dial(self, addr: Address, timeout: float) -> Connection:
        return self._dial(addr, timeout)

    def put(self, conn: Connection) -> None:
        with self._lock:
            stack = self._idle.setdefault(conn.addr, [])
            if len(stack) < self._max_idle:
                stack.append(conn)
                return
        conn.close()

    def close_all(self) -> None:
        with self._lock:
            conns = [c for s in self._idle.values() for c in s]
            self._idle.clear()
        for c in conns:
            c.close()


class TransportServer:
    """Frame server: accept loop + one thread per connection, each frame
    handed to ``handler(payload) -> response`` and the response framed
    back (a ``None`` response drops the connection unreplied — the
    fault-injection hook for lost-response frames).  Binds port 0 by
    default — the resolved address is the node's identity, published via
    flatfile/address-file rendezvous."""

    def __init__(self, handler: Callable[[bytes], Optional[bytes]],
                 host: str = "127.0.0.1", port: int = 0) -> None:
        self._handler = handler
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.address: Address = self._sock.getsockname()[:2]
        self._stopping = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True,
            name=f"rpc-accept-{self.address[1]}",
        )
        self._accept_thread.start()

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _peer = self._sock.accept()
            except OSError:
                if self._stopping.is_set():
                    return  # listener closed by stop()
                # transient accept failure (EMFILE under thread fan-out,
                # ECONNABORTED): a dead accept loop would leave the node
                # heartbeating outbound — looking healthy — while every
                # inbound RPC fails; breathe and keep serving
                time.sleep(0.05)
                continue
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True,
                name="rpc-worker",
            ).start()

    def _serve_conn(self, sock: socket.socket) -> None:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        try:
            while not self._stopping.is_set():
                payload = recv_frame(sock)
                if self._stopping.is_set():
                    # a frame that raced stop() dies unreplied: a stopped
                    # server must look crashed to its callers' pooled
                    # sockets, not serve one parting frame each
                    break
                response = self._handler(payload)
                if response is None:
                    # the handler dropped the response (fault injection):
                    # close unreplied so the caller sees a dead socket
                    # immediately instead of hanging its full timeout
                    break
                send_frame(sock, response)
        except (ConnectionError, OSError):
            pass  # client went away: its pooled socket died with it
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stopping.set()
        try:
            self._sock.close()
        except OSError:
            pass
